"""Content-addressed inference result cache + single-flight coalescing.

At production scale the traffic the stack serves is heavily repetitive:
popular images recur across tenants, client retries resend identical
payloads, and streaming replays re-score chunks a previous run already
scored.  Re-dispatching those is pure waste — the engine computes a
deterministic function of (program, weights, input), so an identical
input is an identical output.  This module is the chip-free lever
ROADMAP item 5 names: a bounded (entries AND bytes) LRU result cache
keyed on content digests, with single-flight request coalescing so N
concurrent identical requests cost exactly ONE engine dispatch.

Key schema — every entry key is a tuple::

    (namespace..., input_digest)

where ``namespace`` identifies WHICH function would have computed the
result (the fleet uses ``(model_name, version, program_fingerprint)``;
a standalone :class:`~sparkdl_tpu.serving.server.Server` gets a
process-unique default so two servers sharing the process cache can
never serve each other's rows) and ``input_digest`` is the shared
:mod:`sparkdl_tpu.utils.digest` sha256 over the request payload's
dtype/shape/bytes — the same digest core ``streaming.source.
content_chunk_id`` has used since ISSUE 8, lifted into ``utils`` so
serving and streaming agree on what "same bytes" means.

Single-flight semantics (:meth:`InferenceCache.lookup`):

* **hit** — the stored value is returned as an independent copy, after
  an integrity re-check: the output digest recorded at insert time is
  recomputed over the copy, and a mismatch (bit rot, a buggy in-place
  mutation, the injected ``cache.hit`` corruption fault) invalidates
  the entry and demotes the call to a miss instead of serving a
  corrupt row.
* **leader** — the first requester of a missing key; it runs the real
  dispatch and MUST settle the flight: :meth:`InferenceCache.settle`
  inserts the value and resolves every parked follower with its own
  copy; :meth:`InferenceCache.fail` resolves the followers with the
  leader's error and caches NOTHING — a failed dispatch can never
  poison the cache.
* **follower** — a request for a key some leader is already computing;
  it parks on a future the leader's settle/fail resolves.  Followers
  cost zero engine dispatches — the coalescing contract the tier-1
  test pins (N concurrent identical requests -> exactly 1 dispatch).

Bounds: ``max_entries`` and ``max_bytes`` both cap the store (least
recently USED entries evicted first; an entry bigger than the whole
byte budget is served but never stored).  A cap of 0 on either axis
disables storage cleanly — lookups all become leaders, settle resolves
followers but inserts nothing.

Gate: ``SPARKDL_CACHE`` (the ``SPARKDL_FAULTS`` env pattern —
consulted once, on first use)::

    unset / "0" / "off"   -> no process-default cache (the default)
    "1" / "on"            -> process-default cache, default bounds
    "entries=N,mb=M"      -> process-default cache, custom bounds

The disabled path is one module-global read + identity check
(:func:`get_default` — same budget as ``faults.inject`` with no plan,
guarded by the run-tests.sh cache-overhead stage).

Fault sites: ``cache.hit`` fires inside the hit return path (an
injected error corrupts the copy handed back, which the digest
re-check must catch); ``cache.stampede`` fires on the leader's path in
``Server.submit`` (a sleep rule holds the leader's dispatch open so
follower pile-up is observable; an error rule is a leader failure the
followers must all see).  Flight events ``cache.hit`` / ``cache.miss``
/ ``cache.coalesced`` / ``cache.evict`` / ``cache.invalidate`` make
cache behavior visible on ``tools/blackbox.py`` incident timelines.
"""

from __future__ import annotations

import itertools
import os
from concurrent.futures import Future
from typing import Any, Dict, Hashable, List, Optional, Tuple

import numpy as np

from sparkdl_tpu.analysis.lockcheck import named_lock
from sparkdl_tpu.faults import inject
from sparkdl_tpu.faults.errors import InjectedFault
from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.utils.digest import content_digest
from sparkdl_tpu.utils.logging import get_logger
from sparkdl_tpu.utils.metrics import Metrics

logger = get_logger(__name__)

__all__ = [
    "InferenceCache",
    "CacheFlight",
    "lockfile_model_fingerprint",
    "get_default",
    "configure",
    "configure_from_env",
    "cache_from_env",
]

#: default bounds for an env-configured cache ("1"/"on", or omitted
#: keys in the "entries=N,mb=M" form)
DEFAULT_MAX_ENTRIES = 4096
DEFAULT_MAX_BYTES = 256 << 20

_OFF = ("", "0", "false", "off", "no")
_ON = ("1", "true", "on", "yes")


def _tree_copy(value: Any) -> Any:
    """Independent deep copy of an array pytree: a cached value handed
    to one caller must never alias the stored entry (or another
    caller's row) — a consumer mutating its result in place would
    otherwise corrupt every later hit."""
    import jax

    return jax.tree_util.tree_map(
        lambda a: np.array(a, copy=True), value)


def _tree_nbytes(value: Any) -> int:
    import jax

    return sum(int(getattr(leaf, "nbytes", 0) or 0)
               for leaf in jax.tree_util.tree_leaves(value))


class CacheFlight:
    """One in-flight single-flight computation: the leader's token.

    Followers park on :class:`~concurrent.futures.Future` s the
    leader's :meth:`InferenceCache.settle` / :meth:`InferenceCache.
    fail` resolves.  Plain data — all mutation happens under the
    cache lock."""

    __slots__ = ("key", "followers", "done")

    def __init__(self, key: Tuple[Hashable, ...]):
        self.key = key
        self.followers: List[Future] = []
        self.done = False


class _Entry:
    __slots__ = ("value", "nbytes", "digest", "hits")

    def __init__(self, value: Any, nbytes: int, digest: str):
        self.value = value
        self.nbytes = nbytes
        self.digest = digest
        self.hits = 0


class InferenceCache:
    """Bounded content-addressed LRU result store + single-flight table.

    Thread model: one lock ("serving.cache", an
    ``analysis.lockcheck``-named lock) guards the entry dict, the byte
    ledger, and the flight table; value copies are made OUTSIDE the
    lock (entries are immutable once inserted), so the lock hold is
    O(1) bookkeeping even for megabyte rows.  Metrics ride the cache's
    own registry unless one is shared in (``cache.*`` counters +
    entry/byte gauges — surfaced by ``Server.varz()``/``Fleet.varz()``
    and the bench cache config)."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 metrics: Optional[Metrics] = None):
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self.metrics = metrics if metrics is not None else Metrics()
        self._lock = named_lock("serving.cache")
        self._data: Dict[Tuple[Hashable, ...], _Entry] = {}
        self._bytes = 0
        self._flights: Dict[Tuple[Hashable, ...], CacheFlight] = {}

    # -- the request path --------------------------------------------------
    def lookup(self, key: Tuple[Hashable, ...]):
        """``("hit", value)`` | ``("follower", future)`` |
        ``("leader", flight)`` — see the module docstring.  A leader
        MUST later call :meth:`settle` or :meth:`fail` with its
        flight."""
        hit = self._probe(key)
        if hit is not None:
            return "hit", hit
        fut: Optional[Future] = None
        with self._lock:
            # re-probe under the lock: a leader may have settled between
            # the optimistic probe above and here
            entry = self._data.get(key)
            if entry is not None:
                self._data.pop(key)
                self._data[key] = entry  # MRU position
                entry.hits += 1
                stored, hits = entry.value, entry.hits
            else:
                flight = self._flights.get(key)
                if flight is not None:
                    fut = Future()
                    flight.followers.append(fut)
                    n_followers = len(flight.followers)
                else:
                    flight = CacheFlight(key)
                    self._flights[key] = flight
        if entry is not None:
            # settled-while-we-looked: serve it (skip the digest
            # re-check — the entry was inserted microseconds ago,
            # under the lock we just held)
            self.metrics.incr("cache.hits")
            flight_emit("cache.hit", hits=hits)
            return "hit", _tree_copy(stored)
        if fut is not None:
            self.metrics.incr("cache.coalesced")
            flight_emit("cache.coalesced", followers=n_followers)
            return "follower", fut
        self.metrics.incr("cache.misses")
        flight_emit("cache.miss")
        return "leader", flight

    def _probe(self, key: Tuple[Hashable, ...]) -> Optional[Any]:
        """Optimistic hit probe: an independent copy of the stored
        value after the integrity re-check, or None (absent OR the
        re-check demoted a corrupt entry to a miss)."""
        with self._lock:
            entry = self._data.get(key)
            if entry is None:
                return None
            self._data.pop(key)
            self._data[key] = entry  # MRU position
            entry.hits += 1
            stored, digest, hits, nbytes = (entry.value, entry.digest,
                                            entry.hits, entry.nbytes)
        value = _tree_copy(stored)
        corrupted = False
        try:
            # chaos hook: an error rule here stands in for bit rot / an
            # aliasing bug — the copy is corrupted and the digest
            # re-check below must catch it
            inject("cache.hit")
        except InjectedFault:
            corrupted = True
            self._corrupt_in_place(value)
        if content_digest(value) != digest:
            self.metrics.incr("cache.corruptions")
            logger.warning(
                "cache entry failed its output-digest re-check "
                "(injected=%s); invalidating and re-dispatching",
                corrupted)
            self.invalidate_key(key)
            return None  # demoted to a miss: the request re-computes
        self.metrics.incr("cache.hits")
        flight_emit("cache.hit", hits=hits, nbytes=nbytes)
        return value

    def settle(self, flight: CacheFlight, value: Any,
               store: bool = True) -> None:
        """Leader success: insert ``value`` (bounded; see class
        docstring) and resolve every follower with an independent
        copy.  ``store=False`` resolves the followers without
        inserting — how a leader that outlived its server's close()
        settles (its namespace was already reclaimed; inserting now
        would orphan the entry forever)."""
        stored = _tree_copy(value)
        nbytes = _tree_nbytes(stored)
        digest = content_digest(stored)
        evicted = []
        inserted = False
        with self._lock:
            followers = flight.followers
            flight.done = True
            if self._flights.get(flight.key) is flight:
                del self._flights[flight.key]
            if (store and self.max_entries > 0 and self.max_bytes > 0
                    and nbytes <= self.max_bytes):
                if flight.key in self._data:
                    old = self._data.pop(flight.key)
                    self._bytes -= old.nbytes
                while self._data and (
                        len(self._data) >= self.max_entries
                        or self._bytes + nbytes > self.max_bytes):
                    k = next(iter(self._data))  # LRU = oldest position
                    old = self._data.pop(k)
                    self._bytes -= old.nbytes
                    evicted.append((k, old.nbytes))
                self._data[flight.key] = _Entry(stored, nbytes, digest)
                self._bytes += nbytes
                inserted = True
            entries, total = len(self._data), self._bytes
        if inserted:
            self.metrics.incr("cache.inserts")
        self.metrics.gauge("cache.entries", entries)
        self.metrics.gauge("cache.bytes", total)
        for k, nb in evicted:
            self.metrics.incr("cache.evictions")
            flight_emit("cache.evict", nbytes=nb)
        for fut in followers:
            if not fut.done():
                fut.set_result(_tree_copy(value))

    def fail(self, flight: CacheFlight, exc: BaseException) -> None:
        """Leader failure: every follower sees the leader's error;
        NOTHING is cached — a failed dispatch must never poison the
        store for the retries that follow it."""
        with self._lock:
            followers = flight.followers
            flight.done = True
            if self._flights.get(flight.key) is flight:
                del self._flights[flight.key]
        self.metrics.incr("cache.leader_failures")
        for fut in followers:
            if not fut.done():
                fut.set_exception(exc)

    # -- direct get/put (the streaming replay path) ------------------------
    def get(self, key: Tuple[Hashable, ...]) -> Optional[Any]:
        """Plain probe without single-flight: the stored value as a
        copy (digest-re-checked like :meth:`lookup`), or None.  What
        ``StreamScorer`` uses at journal replay — replay is sequential,
        so there is no stampede to coalesce, and a probe must have NO
        side effects (no flight churn, no miss accounting for a path
        that was never going to dispatch through the cache)."""
        return self._probe(key)

    def put(self, key: Tuple[Hashable, ...], value: Any) -> None:
        """Direct insert (no flight): how the streaming runner records
        each scored chunk so a journal replay can skip the
        re-dispatch."""
        flight = CacheFlight(key)
        flight.done = True
        self.settle(flight, value)

    # -- invalidation ------------------------------------------------------
    def invalidate_key(self, key: Tuple[Hashable, ...]) -> int:
        with self._lock:
            entry = self._data.pop(key, None)
            if entry is not None:
                self._bytes -= entry.nbytes
            entries, total = len(self._data), self._bytes
        if entry is None:
            return 0
        self.metrics.incr("cache.invalidations")
        self.metrics.gauge("cache.entries", entries)
        self.metrics.gauge("cache.bytes", total)
        flight_emit("cache.invalidate", scope="key", entries=1)
        return 1

    def invalidate(self, namespace: Tuple[Hashable, ...]) -> int:
        """Drop every entry whose key starts with ``namespace`` — the
        hot-swap path: a promote whose program fingerprint (or weights)
        changed makes the old version's results unreachable AND wrong
        to keep charging the byte budget for."""
        ns = tuple(namespace)
        with self._lock:
            doomed = [k for k in self._data if k[:len(ns)] == ns]
            dropped = 0
            for k in doomed:
                entry = self._data.pop(k)
                self._bytes -= entry.nbytes
                dropped += 1
            entries, total = len(self._data), self._bytes
        if dropped:
            self.metrics.incr("cache.invalidations", dropped)
            self.metrics.gauge("cache.entries", entries)
            self.metrics.gauge("cache.bytes", total)
            flight_emit("cache.invalidate", scope="namespace",
                        entries=dropped)
        return dropped

    def adopt(self, old_namespace: Tuple[Hashable, ...],
              new_namespace: Tuple[Hashable, ...]) -> int:
        """Re-key every ``old_namespace`` entry under ``new_namespace``
        (LRU order preserved) — how entries SURVIVE a hot-swap when the
        promoted version provably computes the same function (unchanged
        ``PROGRAMS.lock.json`` fingerprint + identical weight bytes;
        see ``Fleet.promote``)."""
        old = tuple(old_namespace)
        new = tuple(new_namespace)
        if old == new:
            return 0
        moved = 0
        with self._lock:
            for k in [k for k in self._data if k[:len(old)] == old]:
                entry = self._data.pop(k)
                nk = new + k[len(old):]
                existing = self._data.pop(nk, None)
                if existing is not None:
                    # a post-flip request already settled this key under
                    # the new namespace (it raced the adopt): keep the
                    # fresher entry and release the old one's bytes —
                    # silently replacing would leak the byte ledger
                    self._bytes -= entry.nbytes
                    self._data[nk] = existing
                    continue
                self._data[nk] = entry
                moved += 1
        if moved:
            self.metrics.incr("cache.adopted", moved)
        return moved

    # -- introspection -----------------------------------------------------
    def __len__(self) -> int:
        return len(self._data)

    @property
    def total_bytes(self) -> int:
        return self._bytes

    def info(self) -> Dict[str, Any]:
        """JSON-serializable snapshot (the ``cache`` section of
        ``Server.varz()``/``Fleet.varz()`` and the bench line rider).

        ``counters`` always carries the feature-cut keys
        (``cache.feature_hits``/``cache.feature_requests``, zero when
        the deployment has no fan-out tier): ``HeadFanoutServer.varz()``
        merges its tier's counts over them, so BOTH server types expose
        the cache section under one schema and a dashboard query never
        branches on server type (ISSUE 18 satellite)."""
        with self._lock:
            entries = len(self._data)
            total = self._bytes
            inflight = len(self._flights)
        counters = {"cache.feature_hits": 0, "cache.feature_requests": 0}
        counters.update(
            {k: v for k, v in
             self.metrics.snapshot_raw()["counters"].items()
             if k.startswith("cache.")})
        return {
            "entries": entries,
            "bytes": total,
            "max_entries": self.max_entries,
            "max_bytes": self.max_bytes,
            "inflight_leaders": inflight,
            "counters": counters,
        }

    @staticmethod
    def _corrupt_in_place(value: Any) -> None:
        """Flip one byte of the first non-empty leaf — the injected
        ``cache.hit`` corruption the digest re-check must catch."""
        import jax

        for leaf in jax.tree_util.tree_leaves(value):
            a = np.asarray(leaf)
            if a.size:
                flat = a.view(np.uint8).reshape(-1)
                flat[0] ^= 0xFF
                return


# -- swap-survival fingerprints --------------------------------------------
def lockfile_model_fingerprint(model: str,
                               path: Optional[str] = None
                               ) -> Optional[str]:
    """The committed StableHLO identity of ``model``'s serving programs:
    sha256 over the sorted ``(program_name, fingerprint)`` pairs of
    every ``PROGRAMS.lock.json`` record whose ``model`` matches.  This
    is what makes "same computation" CHECKABLE chip-free at hot-swap
    time — the cache-survival analog of the fleet's no-recompile proof,
    pinned against the same committed lockfile.  None when the model
    has no audited programs (non-zoo fns): with no fingerprint there is
    no proof, so swaps conservatively invalidate."""
    import hashlib

    from sparkdl_tpu.analysis.program.lockfile import (DEFAULT_LOCKFILE,
                                                       read_lockfile)

    path = path or DEFAULT_LOCKFILE
    if not os.path.isfile(path):
        return None
    try:
        doc = read_lockfile(path)
    except (ValueError, OSError):
        return None
    pairs = sorted(
        (name, rec.get("fingerprint", ""))
        for name, rec in doc.get("programs", {}).items()
        if rec.get("model") == model and rec.get("kind") == "dispatch")
    if not pairs:
        return None
    h = hashlib.sha256()
    for name, fp in pairs:
        h.update(f"{name}={fp}\n".encode())
    return h.hexdigest()


def feature_namespace(model_desc: str,
                      fingerprint: Optional[str],
                      weights_digest: str) -> Tuple[str, str, str, str]:
    """The FEATURE-CUT cache namespace (head fan-out tier, ISSUE 17):
    ``("features", model_desc, backbone_program_fingerprint,
    backbone_weights_digest)``.

    Keyed on the backbone's identity and NOTHING about the heads — a
    head add/swap/evict changes neither component, so feature entries
    stay warm across head churn (a hot content digest keeps paying the
    backbone zero times); a backbone WEIGHT change rotates
    ``weights_digest`` and a backbone PROGRAM change rotates the
    lockfile fingerprint, either of which moves the namespace so stale
    features can never serve.  ``fingerprint=None`` (no audited
    programs for this backbone) pins ``"unpinned"`` — the namespace
    still rotates on weight changes, it just carries no committed
    StableHLO identity."""
    return ("features", str(model_desc),
            fingerprint if fingerprint else "unpinned",
            str(weights_digest))


# -- module default (the faults.inject / SPARKDL_TRACE pattern) ------------
_UNSET = object()   # before the first ask consults SPARKDL_CACHE
_default: Any = _UNSET
_default_lock = named_lock("serving.cache.configure")


def cache_from_env() -> Optional[InferenceCache]:
    """An :class:`InferenceCache` per the ``SPARKDL_CACHE`` grammar
    (module docstring), or None when the knob is off/unset.  Raises on
    a malformed spec — a typo'd cache config must fail loudly, never
    degrade into an uncached run."""
    raw = os.environ.get("SPARKDL_CACHE", "").strip()
    low = raw.lower()
    if low in _OFF:
        return None
    if low in _ON:
        return InferenceCache()
    entries, max_bytes = DEFAULT_MAX_ENTRIES, DEFAULT_MAX_BYTES
    for pair in raw.split(","):
        pair = pair.strip()
        if not pair:
            continue
        if "=" not in pair:
            raise ValueError(f"bad SPARKDL_CACHE clause {pair!r}; grammar: "
                             f"0|1|entries=N,mb=M")
        k, v = (s.strip() for s in pair.split("=", 1))
        try:
            if k == "entries":
                entries = int(v)
            elif k == "mb":
                max_bytes = int(float(v) * (1 << 20))
            else:
                raise ValueError(f"unknown SPARKDL_CACHE key {k!r} "
                                 f"(known: entries, mb)")
        except ValueError as e:
            if "SPARKDL_CACHE" in str(e):
                raise
            raise ValueError(f"bad SPARKDL_CACHE value {pair!r}") from None
    return InferenceCache(max_entries=entries, max_bytes=max_bytes)


def get_default() -> Optional[InferenceCache]:
    """The process-default cache (resolving ``SPARKDL_CACHE`` on first
    ask), or None.  Disabled path: one module-global read + identity
    check — the budget the run-tests.sh cache-overhead stage guards.
    First-ask resolution is serialized under the configure lock so two
    servers constructed concurrently at startup can never each build
    (and hold) their own byte budget."""
    global _default
    c = _default
    if c is not _UNSET:
        return c
    with _default_lock:
        if _default is _UNSET:
            _default = cache_from_env()
        return _default


def configure(cache: Optional[InferenceCache]) -> Optional[InferenceCache]:
    """Install ``cache`` as the process default (None disables, and
    stops consulting the env until :func:`configure_from_env`)."""
    global _default
    with _default_lock:
        _default = cache
    return cache


def configure_from_env() -> Optional[InferenceCache]:
    """(Re-)configure the process default from ``SPARKDL_CACHE``."""
    return configure(cache_from_env())


_namespace_seq = itertools.count(1)  # next() is atomic in CPython


def unique_namespace(prefix: str) -> Tuple[str, str]:
    """A process-unique default namespace for a standalone consumer
    sharing the process-default cache: two servers that never declared
    a shared identity must never serve each other's rows."""
    return (prefix, f"anon-{next(_namespace_seq)}")


def example_digest(example: Any) -> str:
    """The request-payload digest ``Server.submit`` keys on (one shared
    spelling so tests and adapters can precompute keys)."""
    return content_digest(example)


def resolve_cache(cache: Any, namespace: Optional[Any] = None,
                  prefix: str = "server"
                  ) -> Tuple[Optional[InferenceCache],
                             Tuple[Hashable, ...], bool]:
    """The ONE constructor-side resolution rule ``Server``,
    ``StreamScorer``, and ``Fleet`` share: ``(cache, namespace,
    owned)``.

    ``cache=None`` resolves the ``SPARKDL_CACHE`` process default;
    ``cache=False`` forces uncached; an :class:`InferenceCache` passes
    through.  An explicit ``namespace`` is NOT owned (its lifecycle
    belongs to whoever assigned it — the fleet's swap/rollback paths);
    with none given, a live cache gets a process-unique anon namespace
    the consumer OWNS and must reclaim on close."""
    if cache is None:
        cache = get_default()
    elif cache is False:
        cache = None
    if namespace is not None:
        return cache, tuple(namespace), False
    if cache is not None:
        return cache, unique_namespace(prefix), True
    return None, (prefix,), False


def zipfian_cache_benchmark(n_requests: int = 160,
                            universe: int = 16,
                            zipf_s: float = 1.1,
                            dispatch_ms: float = 10.0,
                            seed: int = 0,
                            feature_dim: int = 16,
                            max_batch_size: int = 8,
                            max_entries: int = DEFAULT_MAX_ENTRIES,
                            max_bytes: int = DEFAULT_MAX_BYTES
                            ) -> Dict[str, Any]:
    """Deterministic chip-free proof of the cache's throughput lever
    (the ``synthetic_overlap_benchmark`` pattern: a sleep stands in for
    the device, so the result is stable on any host and needs no
    relay).

    A seeded Zipfian request replay — ``p(rank r) ∝ 1/r^zipf_s`` over
    ``universe`` distinct payloads, the repetitive-traffic shape
    ROADMAP item 5 describes — is served twice through a real
    :class:`~sparkdl_tpu.serving.server.Server` whose bucket engines
    are wrapped with a blocking ``dispatch_ms`` sleep: once uncached
    (every request pays a dispatch) and once through an
    :class:`InferenceCache` (only single-flight leaders do).  Because
    the replay is sequential and the cache holds the whole universe,
    the analytic hit floor is EXACT: every repeat of an already-served
    payload must hit, so ``hits >= n_requests - distinct``.  Outputs
    are verified bit-identical (``np.array_equal``) between the two
    passes before timings are reported — the cached path must be a
    pure latency optimization, never an approximation."""
    import time as _time

    from sparkdl_tpu.serving.server import Server

    rng = np.random.default_rng(seed)
    variables = {"w": rng.normal(
        size=(feature_dim, feature_dim)).astype(np.float32)}

    def fn(v, x):
        import jax.numpy as jnp

        return jnp.tanh(x @ v["w"])

    payloads = [rng.normal(size=(feature_dim,)).astype(np.float32)
                for _ in range(universe)]
    ranks = np.arange(1, universe + 1, dtype=np.float64)
    probs = ranks ** -float(zipf_s)
    probs /= probs.sum()
    seq = [int(i) for i in rng.choice(universe, size=n_requests, p=probs)]
    distinct = len(set(seq))
    analytic_hit_rate = (n_requests - distinct) / n_requests

    def build(cache):
        srv = Server(fn, variables, max_batch_size=max_batch_size,
                     max_wait_ms=0.5, max_queue=n_requests + 16,
                     cache=cache)
        srv.warmup(payloads[0])  # compile BEFORE the sleep wrap below
        calls = [0]
        for b in srv.bucket_sizes:
            eng = srv._engine_for(b)
            real = eng.run_padded

            def slow(batch, _real=real):  # the synthetic slow device
                calls[0] += 1
                _time.sleep(dispatch_ms / 1e3)
                return _real(batch)

            eng.run_padded = slow
        return srv, calls

    srv, calls = build(cache=False)
    t0 = _time.perf_counter()
    uncached_out = [srv.predict(payloads[i]) for i in seq]
    uncached_s = _time.perf_counter() - t0
    uncached_dispatches = calls[0]
    srv.close()

    cache = InferenceCache(max_entries=max_entries, max_bytes=max_bytes)
    srv, calls = build(cache=cache)
    t0 = _time.perf_counter()
    cached_out = [srv.predict(payloads[i]) for i in seq]
    cached_s = _time.perf_counter() - t0
    cached_dispatches = calls[0]
    # snapshot occupancy BEFORE close(): the server owns its anon
    # namespace and close() reclaims it from the store
    cache_entries, cache_bytes = len(cache), cache.total_bytes
    srv.close()

    bit_identical = all(np.array_equal(a, b)
                        for a, b in zip(uncached_out, cached_out))
    counters = cache.metrics.snapshot_raw()["counters"]
    hits = counters.get("cache.hits", 0.0)
    return {
        "n_requests": n_requests,
        "universe": universe,
        "zipf_s": zipf_s,
        "distinct": distinct,
        "dispatch_ms": dispatch_ms,
        "uncached_s": round(uncached_s, 4),
        "cached_s": round(cached_s, 4),
        "speedup": round(uncached_s / cached_s, 4),
        "hit_rate": round(hits / n_requests, 4),
        "analytic_hit_rate": round(analytic_hit_rate, 4),
        "hits": int(hits),
        "misses": int(counters.get("cache.misses", 0.0)),
        "uncached_dispatches": uncached_dispatches,
        "cached_dispatches": cached_dispatches,
        "bit_identical": bit_identical,
        "cache_entries": cache_entries,
        "cache_bytes": cache_bytes,
    }


def head_fanout_benchmark(n_requests: int = 160,
                          universe: int = 16,
                          tenants: int = 64,
                          zipf_s: float = 1.1,
                          dispatch_ms: float = 10.0,
                          seed: int = 0,
                          max_batch_size: int = 8
                          ) -> Dict[str, Any]:
    """Deterministic chip-free proof of the shared-backbone fan-out
    tier (ISSUE 17) — the headline replay the tests assert and the
    ``headfanout`` bench config stamps.

    A seeded Zipf-content, ``tenants``-tenant replay is served through
    a :class:`~sparkdl_tpu.serving.server.HeadFanoutServer` whose
    backbone engines are wrapped with a blocking ``dispatch_ms`` sleep
    (the synthetic slow device — the same trick as
    :func:`zipfian_cache_benchmark`, so the result is stable on any
    host):

    * FULL-MODEL BASELINE: the same replay through an UNCACHED fan-out
      server — every request pays the backbone sleep, the per-request
      p50/p99 of a model-copy-per-tenant deployment;
    * COLD PASS (feature cache on, empty): the replay is sequential,
      so single-flight makes the floor exact — backbone dispatches MUST
      equal the number of distinct content digests (the "featurize
      once" claim, asserted here, not just reported);
    * WARM PASS: the replay again — ZERO further backbone dispatches,
      and the per-request p50/p99 is head-milliseconds only.

    Every output row (all three passes) is verified BIT-identical to
    an INDEPENDENT per-tenant full-model oracle
    (``parallel.engine.head_fanout_oracle_fn``, jitted on its own, one
    unbatched row at a time) before timings are reported: the fan-out
    tier must be a pure cost optimization, never an approximation."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from sparkdl_tpu.parallel.engine import (head_fanout_backbone_fn,
                                             head_fanout_oracle_fn)
    from sparkdl_tpu.serving.server import HeadFanoutServer

    d_in, d_feat, classes = 12, 16, 4
    rng = np.random.default_rng(seed)
    variables = {"backbone": rng.normal(
        size=(d_in, d_feat)).astype(np.float32)}
    heads = {f"t{i:03d}": {
        "kernel": rng.normal(size=(d_feat, classes)).astype(np.float32),
        "bias": rng.normal(size=(classes,)).astype(np.float32),
    } for i in range(tenants)}
    payloads = [rng.normal(size=(d_in,)).astype(np.float32)
                for _ in range(universe)]
    ranks = np.arange(1, universe + 1, dtype=np.float64)
    probs = ranks ** -float(zipf_s)
    probs /= probs.sum()
    seq = [(int(c), f"t{int(t):03d}") for c, t in zip(
        rng.choice(universe, size=n_requests, p=probs),
        rng.integers(0, tenants, size=n_requests))]
    distinct = len({c for c, _ in seq})

    # no donation: the oracle reuses its weights for every row
    oracle = jax.jit(head_fanout_oracle_fn, donate_argnums=())

    def oracle_row(content: int, tenant: str) -> np.ndarray:
        h = heads[tenant]
        return np.asarray(oracle(
            {"backbone": variables["backbone"], **h},
            jnp.asarray(payloads[content])))

    def build(cache):
        srv = HeadFanoutServer(
            head_fanout_backbone_fn, variables, model_desc="headfanout",
            cache=cache, max_batch_size=max_batch_size, max_wait_ms=0.5,
            max_queue=n_requests + 16)
        for t, h in heads.items():
            srv.add_head(t, h)
        srv.warmup(payloads[0])  # compile BEFORE the sleep wrap below
        srv.warm_head(np.zeros(d_feat, np.float32))
        calls = [0]
        for b in srv.bucket_sizes:
            eng = srv.backbone._engine_for(b)
            real = eng.run_padded

            def slow(batch, _real=real):  # the synthetic slow device
                calls[0] += 1
                _time.sleep(dispatch_ms / 1e3)
                return _real(batch)

            eng.run_padded = slow
        return srv, calls

    def replay(srv):
        lat, out = [], []
        for content, tenant in seq:
            t0 = _time.perf_counter()
            y = srv.predict(payloads[content], tenant)
            lat.append(_time.perf_counter() - t0)
            out.append(np.asarray(y))
        return lat, out

    def pcts(lat):
        return (round(float(np.percentile(lat, 50)) * 1e3, 3),
                round(float(np.percentile(lat, 99)) * 1e3, 3))

    # full-model baseline: no feature cache, every request pays the
    # backbone — the per-tenant-model-copy cost shape
    srv, calls = build(cache=False)
    base_lat, base_out = replay(srv)
    baseline_dispatches = calls[0]
    srv.close()

    cache = InferenceCache()
    srv, calls = build(cache=cache)
    _, cold_out = replay(srv)
    cold_dispatches = calls[0]
    # THE headline identity: sequential replay + single-flight means a
    # hot content digest pays the backbone exactly once EVER
    if cold_dispatches != distinct:
        raise AssertionError(
            f"backbone dispatched {cold_dispatches} times for "
            f"{distinct} distinct content digests")
    warm_lat, warm_out = replay(srv)
    if calls[0] != cold_dispatches:
        raise AssertionError(
            f"warm replay re-dispatched the backbone "
            f"({calls[0] - cold_dispatches} extra)")
    snap = srv.metrics.snapshot_raw()["counters"]
    feature_hits = int(snap.get("headfanout.feature_hits", 0))
    bank = srv.head_stats()
    srv.close()

    bit_identical = all(
        np.array_equal(y, oracle_row(c, t))
        for outs in (base_out, cold_out, warm_out)
        for (c, t), y in zip(seq, outs))
    base_p50, base_p99 = pcts(base_lat)
    warm_p50, warm_p99 = pcts(warm_lat)
    return {
        "n_requests": n_requests,
        "universe": universe,
        "tenants": tenants,
        "zipf_s": zipf_s,
        "distinct": distinct,
        "dispatch_ms": dispatch_ms,
        "backbone_dispatches": cold_dispatches,
        "baseline_dispatches": baseline_dispatches,
        "dispatch_ratio": round(cold_dispatches / distinct, 4),
        "baseline_p50_ms": base_p50,
        "baseline_p99_ms": base_p99,
        "warm_p50_ms": warm_p50,
        "warm_p99_ms": warm_p99,
        "p50_reduction": round(1.0 - warm_p50 / base_p50, 4),
        "feature_hits": feature_hits,
        "bank_param_bytes_per_chip": bank.get("param_bytes_per_chip"),
        "bank_capacity": bank.get("capacity"),
        "bank_mode": bank.get("mode"),
        "bit_identical": bit_identical,
    }
