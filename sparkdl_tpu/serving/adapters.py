"""Adapters: make existing pipeline stages servable.

``from_transformer`` lifts the batch-oriented stages (zoo transformers,
``TFImageTransformer``, ``ModelTransformer``/``KerasTransformer``) into a
running :class:`~sparkdl_tpu.serving.server.Server`: the stage supplies
the model (same weights, same fused preprocess, same cached zoo loads)
and its ``batchSize`` seeds ``max_batch_size``; the serving layer adds
the queue, dynamic batching, deadlines, and backpressure.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

from sparkdl_tpu.serving.server import Server


def _image_request_preprocess(height: int, width: int):
    """Host-side request prep for image servers: accepts an image-struct
    dict (the DataFrame wire format) or a ``[H, W, 3]`` uint8 RGB array,
    resizing to the model's input size when needed.  Runs on the
    SUBMITTER's thread (Server.host_preprocess), never the dispatcher."""
    from sparkdl_tpu.image.io import resizeImage, structToModelInput

    def pre(example: Any) -> np.ndarray:
        if isinstance(example, dict):  # image struct (origin/height/...)
            return structToModelInput(example, height, width).astype(
                np.uint8)
        arr = np.asarray(example)
        if arr.ndim != 3 or arr.shape[-1] != 3:
            raise ValueError(
                f"image request must be [H, W, 3] RGB (or an image "
                f"struct dict), got shape {arr.shape}")
        if arr.shape[:2] != (height, width):
            arr = resizeImage(arr.astype(np.uint8), height, width)
        return arr.astype(np.uint8)

    return pre


def _vector_request_preprocess(example: Any) -> np.ndarray:
    """Tensor-stage requests are 1-D float rows (the reference's
    KerasTransformer contract)."""
    return np.asarray(example, dtype=np.float32)


def from_transformer(transformer, **server_kwargs) -> Server:
    """Build a running :class:`Server` from a fitted/configured
    transformer stage, so any zoo transformer becomes servable::

        t = DeepImageFeaturizer(inputCol="image", outputCol="features",
                                modelName="InceptionV3")
        with serving.from_transformer(t, max_wait_ms=3) as srv:
            vec = srv.predict(rgb_array)      # same rows transform() emits

    Supported stages (each keeps its own engine semantics — weights,
    fused preprocess, compute dtype — and contributes ``batchSize`` as
    the default ``max_batch_size``):

    * ``DeepImageFeaturizer`` / ``DeepImagePredictor`` — requests are
      ``[H, W, 3]`` uint8 RGB arrays or image-struct dicts (resized
      host-side); results are the feature / probability rows.
    * ``TFImageTransformer`` — same request form, routed through the
      stage's ``ModelFunction`` (``inputSize`` must be set or inferable).
    * ``ModelTransformer`` / ``KerasTransformer`` — requests are 1-D
      float arrays.

    Extra ``server_kwargs`` pass through to :class:`Server` (deadlines,
    queue bound, buckets, ...).
    """
    from sparkdl_tpu.transformers.named_image import (TFImageTransformer,
                                                      _NamedImageTransformer)
    from sparkdl_tpu.transformers.tensor import ModelTransformer

    if isinstance(transformer, _NamedImageTransformer):
        from sparkdl_tpu.models import get_model_spec

        name = transformer.getModelName()
        h, w = get_model_spec(name).input_size
        server_kwargs.setdefault("max_batch_size",
                                 int(transformer.getBatchSize()))
        server_kwargs.setdefault("host_preprocess",
                                 _image_request_preprocess(h, w))
        return Server(name, featurize=transformer.featurize,
                      **server_kwargs)
    if isinstance(transformer, TFImageTransformer):
        size = _tf_image_input_size(transformer)
        server_kwargs.setdefault("max_batch_size",
                                 int(transformer.getBatchSize()))
        if size is not None:
            server_kwargs.setdefault("host_preprocess",
                                     _image_request_preprocess(*size))
        return Server(transformer.getModelFunction(), **server_kwargs)
    if isinstance(transformer, ModelTransformer):
        server_kwargs.setdefault("max_batch_size",
                                 int(transformer.getBatchSize()))
        server_kwargs.setdefault("host_preprocess",
                                 _vector_request_preprocess)
        return Server(transformer.getModelFunction(), **server_kwargs)
    raise TypeError(
        f"from_transformer supports the zoo/image/tensor inference stages, "
        f"not {type(transformer).__name__}")


def _tf_image_input_size(transformer) -> Optional[Tuple[int, int]]:
    if transformer.isDefined(transformer.inputSize):
        h, w = (int(v) for v in
                transformer.getOrDefault(transformer.inputSize))
        return h, w
    return None
