"""In-process async inference server over the TPU engine.

The online counterpart of the offline paths (transformers / UDFs /
``InferenceEngine.map_batches``): single-example requests are admitted
into a bounded queue, assembled into dynamic micro-batches
(:mod:`sparkdl_tpu.serving.batcher`), padded to a small set of BUCKET
sizes so the engine's jit executable cache stays warm (a handful of
compiled shapes, never one per request count), dispatched through the
existing :class:`~sparkdl_tpu.parallel.engine.InferenceEngine` (same
grouped-dispatch substrate and per-controller mesh policy), and
demultiplexed back to per-request futures.

Production envelope:
  * per-request deadlines — expired requests are shed BEFORE dispatch;
  * bounded admission queue — reject-with-``retry_after_s`` when full;
  * per-batch fault isolation — a model fn that raises (after the
    configured ``utils.retry`` budget) or stalls past
    ``dispatch_timeout_ms`` fails only its OWN batch's futures;
  * graceful drain on ``close()`` / context-manager exit;
  * ``utils.metrics``-integrated counters/gauges/latency histograms
    (queue depth, batch fill ratio, time-in-queue, p50/p99 latency).
"""

from __future__ import annotations

import inspect
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from sparkdl_tpu.analysis.lockcheck import named_condition, named_lock
from sparkdl_tpu.faults import inject
from sparkdl_tpu.obs.exemplar import ExemplarReservoir
from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.parallel.engine import CircuitOpenError
from sparkdl_tpu.obs.trace import get_tracer
from sparkdl_tpu.serving.batcher import (DynamicBatcher, Request,
                                         ragged_enabled_from_env)
from sparkdl_tpu.serving.errors import (DeadlineExceededError,
                                        DispatchTimeoutError,
                                        ServerClosedError,
                                        ServiceUnavailableError)
from sparkdl_tpu.utils.digest import content_digest
from sparkdl_tpu.utils.health import HealthTracker
from sparkdl_tpu.utils.logging import get_logger
from sparkdl_tpu.utils.metrics import Metrics
from sparkdl_tpu.utils.retry import NON_RETRYABLE, with_retries

logger = get_logger(__name__)


def _resolve_model(model, variables, featurize: bool):
    """(fn, host_variables, engine_overrides) from the three accepted
    model forms:

    * a zoo model NAME (str) — weights via the shared process cache, the
      model's ImageNet preprocess fused in front (``featurize`` picks the
      feature cut vs. probabilities), uint8 RGB ``[B, H, W, 3]`` input.
      Honors ``SPARKDL_ZOO_COMPUTE_DTYPE`` exactly like the zoo
      transformers (``named_image._zoo_engine``) — bf16 compute with f32
      host cast under the bench configuration — so served rows match
      ``transform()`` rows; the dtype choice rides ``engine_overrides``
      (applied unless the caller set the knobs explicitly);
    * a :class:`~sparkdl_tpu.graph.function.ModelFunction`;
    * a plain jit-traceable ``fn(variables, batch)`` plus ``variables``.
    """
    from sparkdl_tpu.graph.function import ModelFunction

    if isinstance(model, str):
        if variables is not None:
            raise ValueError("variables must be None when serving a named "
                             "zoo model")
        # the ONE zoo fn constructor — shared with _zoo_engine, the fleet
        # registry, and the program auditor, so served == transformed ==
        # audited
        from sparkdl_tpu.transformers.named_image import zoo_serving_bundle

        return zoo_serving_bundle(model, featurize)
    if isinstance(model, ModelFunction):
        if variables is not None:
            raise ValueError("variables must be None when serving a "
                             "ModelFunction (it carries its own)")
        return model.fn, model.variables, {}
    if callable(model):
        return model, ({} if variables is None else variables), {}
    raise TypeError(f"Cannot serve a {type(model).__name__}; expected a "
                    f"zoo model name, ModelFunction, or callable "
                    f"fn(variables, batch)")


def _default_buckets(max_batch_size: int) -> List[int]:
    """Quarter / half / full batch — three compiled shapes cover light,
    medium, and saturated traffic without per-count recompiles."""
    b = max(1, int(max_batch_size))
    return sorted({max(1, b // 4), max(1, b // 2), b})


def bucket_plan(max_batch_size: int,
                bucket_sizes: Optional[Sequence[int]] = None,
                mesh=None) -> List[int]:
    """The COMPILED bucket set a :class:`Server` would build: requested
    buckets (default quarter/half/full), validated, rounded up to the
    mesh's data-axis multiple (the engine does this per bucket anyway),
    and de-duplicated — two raw buckets that round to the same device
    batch were two engine objects compiling ONE shape.  This is the
    enumeration hook ``analysis.program`` walks to audit every serving
    program chip-free; the server itself builds its engines from the
    same plan so the audited set cannot drift from the served set."""
    from sparkdl_tpu.parallel.engine import (effective_device_batch,
                                             resolve_engine_mesh)

    max_batch_size = max(1, int(max_batch_size))
    buckets = (list(bucket_sizes) if bucket_sizes is not None
               else _default_buckets(max_batch_size))
    if not buckets or any(int(b) < 1 for b in buckets):
        raise ValueError(f"bucket_sizes must be positive, got {buckets}")
    buckets = sorted(int(b) for b in buckets)
    if buckets[-1] < max_batch_size:
        raise ValueError(
            f"largest bucket ({buckets[-1]}) must cover "
            f"max_batch_size ({max_batch_size})")
    mesh = resolve_engine_mesh(mesh)
    return sorted({effective_device_batch(b, mesh) for b in buckets})


class _Once:
    """Run a callback exactly once across racing threads (worker finish
    vs. stall watchdog)."""

    def __init__(self, fn: Callable[[], None]):
        self._fn = fn
        self._lock = named_lock("serving.once")
        self._done = False

    def __call__(self) -> None:
        with self._lock:
            if self._done:
                return
            self._done = True
        self._fn()


def _deadline_guard(inner: Future, timeout_s: float) -> Future:
    """Caller-facing view of ``inner`` that fails with
    ``DeadlineExceededError`` after ``timeout_s`` — how a coalesced
    follower keeps its own deadline while parked on a leader whose
    request may have none.

    One ``threading.Timer`` per deadline-carrying follower, cancelled
    the moment the leader settles — the same per-waiter budget as the
    dispatch watchdog's per-attempt timer, and it exists only for the
    flight's (typically milliseconds-long) lifetime.  A deadline wheel
    would amortize this if stampedes of deadline-carrying identical
    requests ever become a measured hot spot."""
    out: Future = Future()

    def _relay(f: Future) -> None:
        timer.cancel()
        try:
            if f.cancelled():
                out.cancel()
                return
            exc = f.exception()
            if exc is not None:
                out.set_exception(exc)
            else:
                out.set_result(f.result())
        except InvalidStateError:  # the deadline timer fired first
            pass

    def _expire() -> None:
        try:
            out.set_exception(DeadlineExceededError(
                f"coalesced request exceeded its "
                f"{timeout_s * 1e3:.0f}ms deadline while waiting on the "
                f"single-flight leader"))
        except InvalidStateError:  # the leader settled first
            pass

    timer = threading.Timer(timeout_s, _expire)
    timer.daemon = True
    timer.start()
    inner.add_done_callback(_relay)
    return out


def _settle_error(requests: Sequence[Request], exc: BaseException) -> None:
    for r in requests:
        if not r.future.done():
            try:
                r.future.set_exception(exc)
            except InvalidStateError:  # lost a race with the watchdog
                pass
        r.finish_span("error")
    if requests:
        bs = requests[0].batch_span
        if bs is not None:
            requests[0].batch_span = None
            bs.finish("error")


class Server:
    """Async dynamic-batching inference service over one model.

    ::

        with serving.Server(fn, variables, max_batch_size=64,
                            max_wait_ms=5) as srv:
            fut = srv.submit(example)           # concurrent.futures.Future
            y = fut.result()
            y = srv.predict(example)            # blocking sugar
            y = await srv.predict_async(example)  # asyncio integration

    Requests are single examples WITHOUT the batch axis (arrays or
    pytrees); results are the matching single-example output rows —
    bit-identical to batching the same inputs through
    ``InferenceEngine.map_batches`` at the same padded shape, regardless
    of arrival order or which micro-batch a request lands in (across
    DIFFERENT bucket shapes results agree to XLA-refusion tolerance, the
    same caveat as the engine's own grouped dispatch).

    Parameters beyond the batcher knobs:
      * ``bucket_sizes`` — padded dispatch sizes (default quarter/half/
        full ``max_batch_size``); each bucket is one compiled shape.
      * ``default_timeout_ms`` — deadline applied to requests that pass
        no ``timeout_ms`` of their own (None = no deadline).
      * ``dispatch_timeout_ms`` — stall watchdog: a model-call ATTEMPT
        exceeding this fails its batch with ``DispatchTimeoutError`` and
        later batches proceed (None = wait forever).  The window is
        re-armed per retry attempt and excludes both jit compile (each
        bucket's first batch triggers an untimed warm call) and the
        host-side demux.
      * ``max_retries`` — per-batch ``utils.retry.with_retries`` budget
        for transient model failures (default 0: fail fast; deterministic
        errors in ``retry.NON_RETRYABLE`` never retry).
      * ``max_inflight_batches`` — dispatch concurrency bound (device
        residency stays O(inflight x bucket), mirroring the engine's
        in-flight window).
      * ``host_preprocess`` — optional per-request host-side fn applied
        in ``submit`` on the CALLER's thread (e.g. image resize), so the
        dispatcher never blocks on host prep.
      * ``dispatch_retries`` / ``breaker_threshold`` /
        ``breaker_cooldown_s`` — the engines' failure-domain knobs
        (ISSUE 4): engine-level transient-dispatch retry budget
        (jittered, capped backoff) and the consecutive-device-error
        circuit breaker.  While a breaker is OPEN, :meth:`submit` sheds
        with ``ServiceUnavailableError`` + ``retry_after_s`` instead of
        letting every request queue, dispatch into a dead device, and
        time out; :meth:`health` reports live/ready/degraded with the
        per-bucket breaker state and last error.
      * ``slos`` — declarative :class:`~sparkdl_tpu.obs.slo.SLO`
        objectives (ISSUE 9) evaluated over this server's metrics on
        every :meth:`health`/:meth:`varz` poll; a burn-rate breach
        degrades health (naming the objective in ``last_error``) and
        the evaluation rides ``health()["slo"]``.
      * ``ragged`` — continuous ragged batching (ISSUE 13; default:
        the ``SPARKDL_RAGGED`` env knob, ON): flushes cut the queue at
        compiled-bucket boundaries (zero pad rows for the cut) and
        sub-bucket residuals top off with stack-compatible late
        arrivals right before dispatch, so the engine's pad path is
        only paid for the true residual.  ``False`` restores the
        flush-on-full baseline (everything waiting pads into the
        nearest covering bucket).
      * ``donate_batch`` — donate the per-dispatch device batch buffer
        to XLA (None = auto: donate iff an eval-shape probe proves the
        donation is CONSUMED — some output leaf aliases the batch;
        zoo models resolve to False by recorded GC001 exemption, their
        uint8 batch can never alias the float features).
      * ``partition_rules`` / ``param_shardings`` — tensor-parallel
        WEIGHT sharding (ISSUE 14): a ``(regex, PartitionSpec)`` rule
        list (or ``mesh -> rules`` factory) / an explicit per-leaf spec
        pytree splitting chosen params across the mesh's ``model``
        axis, so every bucket engine holds ``bytes / model_axis`` of a
        sharded leaf instead of one full weight copy per chip.  Zoo
        models default to ``mesh.default_partition_rules`` (resolves
        all-replicated — byte-identical programs — unless the mesh has
        a model axis > 1); ``varz()["sharding"]`` reports the resolved
        layout and per-chip HBM bytes.
    """

    def __init__(self, model, variables: Any = None, *,
                 featurize: bool = False,
                 max_batch_size: int = 64,
                 max_wait_ms: float = 5.0,
                 max_queue: int = 1024,
                 default_timeout_ms: Optional[float] = None,
                 dispatch_timeout_ms: Optional[float] = None,
                 bucket_sizes: Optional[Sequence[int]] = None,
                 max_inflight_batches: int = 2,
                 max_retries: int = 0,
                 retry_backoff_s: float = 0.0,
                 mesh=None,
                 compute_dtype: Optional[Any] = None,
                 output_host_dtype: Optional[Any] = None,
                 host_preprocess: Optional[Callable[[Any], Any]] = None,
                 dispatch_retries: int = 0,
                 breaker_threshold: int = 8,
                 breaker_cooldown_s: float = 30.0,
                 slos: Optional[Sequence[Any]] = None,
                 cache: Any = None,
                 cache_namespace: Optional[Sequence[Any]] = None,
                 ragged: Optional[bool] = None,
                 donate_batch: Optional[bool] = None,
                 partition_rules: Any = None,
                 param_shardings: Any = None,
                 metrics: Optional[Metrics] = None,
                 clock: Optional[Callable[[], float]] = None,
                 cost: Any = None,
                 model_desc: Optional[str] = None):
        self._fn, self._host_variables, _overrides = _resolve_model(
            model, variables, featurize)
        # what the cost ledger's lockfile lookup and showback report as
        # "model": zoo names match PROGRAMS.lock.json dispatch records;
        # anything else gets the fn's name (rows-only attribution)
        self.model_desc = (model_desc if model_desc is not None
                           else (model if isinstance(model, str)
                                 else getattr(model, "__name__",
                                              type(model).__name__)))
        if compute_dtype is None and output_host_dtype is None:
            compute_dtype = _overrides.get("compute_dtype")
            output_host_dtype = _overrides.get("output_host_dtype")
        if donate_batch is None:
            # zoo models override to False (uint8 batch can never alias
            # the float features — GC001's recorded exemption); anything
            # else stays None = probe per bucket at first dispatch
            donate_batch = _overrides.get("donate_batch")
        self._donate_batch = donate_batch
        # Tensor-parallel weight sharding (ISSUE 14): the policy every
        # bucket engine compiles/places weights under.  Zoo models
        # default to the per-family rules (mesh.default_partition_rules
        # via zoo_serving_bundle overrides) — a no-op replicate on
        # model-axis-1 meshes, weight splitting when the mesh has a
        # usable model axis; explicit partition_rules/param_shardings
        # always win.
        if partition_rules is None and param_shardings is None:
            partition_rules = _overrides.get("partition_rules")
        self._partition_rules = partition_rules
        self._param_shardings = param_shardings
        self.metrics = metrics if metrics is not None else Metrics()
        # Injected monotonic clock (ISSUE 16): deadlines, queue ages and
        # latency accounting all read THIS source, so a virtual-time
        # harness (the traffic twin) drives the whole request path
        # deterministically.  Real-time mechanics stay real: close()'s
        # drain wait, the dispatch watchdog and the follower deadline
        # guard are wall-clock liveness devices, not request semantics.
        self._clock = clock if clock is not None else time.monotonic
        self.max_batch_size = max(1, int(max_batch_size))
        from sparkdl_tpu.parallel import mesh as mesh_lib
        from sparkdl_tpu.parallel.engine import resolve_engine_mesh

        resolved_mesh = resolve_engine_mesh(mesh)
        self._data_parallel = int(resolved_mesh.shape[mesh_lib.DATA_AXIS])
        # mesh-rounded, de-duplicated compiled shapes; also what the
        # program auditor enumerates (bucket_plan docstring)
        self._buckets = bucket_plan(self.max_batch_size,
                                    bucket_sizes=bucket_sizes,
                                    mesh=resolved_mesh)
        self._default_timeout_s = (None if default_timeout_ms is None
                                   else max(0.0, default_timeout_ms) / 1e3)
        self._dispatch_timeout_s = (None if dispatch_timeout_ms is None
                                    else max(1e-3, dispatch_timeout_ms) / 1e3)
        self._max_retries = max(0, int(max_retries))
        self._retry_backoff_s = max(0.0, float(retry_backoff_s))
        self._mesh = mesh
        self._compute_dtype = compute_dtype
        self._output_host_dtype = output_host_dtype
        self._host_preprocess = host_preprocess
        self._dispatch_retries = max(0, int(dispatch_retries))
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_cooldown_s = float(breaker_cooldown_s)
        # Health state machine (ISSUE 4): "ready" <-> "degraded" driven
        # by dispatch/batch outcomes (every failed ATTEMPT notes
        # degraded — even one an engine retry later absorbs — and the
        # next success notes ready), with a bounded transition history
        # so tests/operators can see degraded->ready recoveries that a
        # point-in-time poll would race past.  Shared with the streaming
        # runner since ISSUE 8 (utils.health mirrors this contract).
        self._health = HealthTracker("serving.health")
        # Hardware cost attribution (ISSUE 18): ``cost=None`` resolves
        # the SPARKDL_COST process default (unset env = unmetered),
        # ``cost=False`` forces unmetered, a CostLedger is shared (the
        # fleet passes one across its servers).  First health binder
        # wins: a fleet binds its fleet-wide tracker before handing the
        # ledger here, so this bind is a no-op in that deployment.
        from sparkdl_tpu.obs.cost import resolve_cost

        self._cost = resolve_cost(cost)
        if self._cost is not None:
            self._cost.bind_health(self._health)
        self._cost_hbm: Dict[int, float] = {}
        # Declarative objectives (ISSUE 9): evaluated over THIS server's
        # metrics on every health()/varz() poll; a burn-rate breach
        # degrades the same tracker dispatch failures do, so "degraded"
        # finally answers "against what objective?".
        self._slo_engine = None
        if slos:
            from sparkdl_tpu.obs.slo import SLOEngine

            self._slo_engine = SLOEngine(self.metrics, slos,
                                         health=self._health,
                                         clock=self._clock)
        # Content-addressed result cache + single-flight coalescing
        # (ISSUE 11): probe BEFORE the admission-queue charge — a hit
        # costs zero queue slots and zero dispatches, a coalesced
        # follower parks on the identical in-flight leader.  ``cache=
        # None`` (the default) resolves the SPARKDL_CACHE process
        # default (unset env = uncached, the pre-ISSUE-11 behavior);
        # pass an InferenceCache to share one across servers (the
        # fleet does, with per-version namespaces) or ``cache=False``
        # to force uncached.
        from sparkdl_tpu.serving.cache import resolve_cache

        # owned (= auto-generated anon) namespaces are reclaimed from
        # the possibly-shared store by close() — nobody else can ever
        # reach those keys, so leaving them would charge the byte
        # budget until LRU pressure
        self._cache, self._cache_ns, self._cache_ns_owned = resolve_cache(
            cache, cache_namespace, "server")
        self._engines: Dict[int, Any] = {}
        self._warm: set = set()  # buckets whose program is compiled
        self._engine_lock = named_lock("serving.engines")
        # Continuous ragged batching (ISSUE 13): the batcher cuts
        # flushes at this server's compiled bucket boundaries, and
        # _execute tops a sub-bucket residual off with late arrivals
        # right before stacking.  ``SPARKDL_RAGGED=0`` (or
        # ``ragged=False``) restores the flush-on-full baseline.
        self._ragged = (ragged_enabled_from_env() if ragged is None
                        else bool(ragged))
        self._batcher = DynamicBatcher(
            max_batch_size=self.max_batch_size, max_wait_ms=max_wait_ms,
            max_queue=max_queue,
            bucket_plan=self._buckets if self._ragged else None,
            align=self._data_parallel,
            metrics=self.metrics, clock=self._clock)
        # Slow-request exemplars: top-K span trees, surfaced by varz();
        # inert (offer() returns False) unless SPARKDL_TRACE is on.
        self.exemplars = ExemplarReservoir(k=4)
        self._closed = False
        self._abandon = threading.Event()
        self._inflight = 0
        self._inflight_cond = named_condition("serving.inflight")
        self._inflight_sem = threading.Semaphore(
            max(1, int(max_inflight_batches)))
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="sparkdl-serving-dispatch")
        self._dispatcher.start()

    # -- engines (one per bucket, shared weights + shared jit program) ----
    def _bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    def _probe_donate(self, bucket: int, batch_example: Any) -> bool:
        """True iff XLA can actually CONSUME a donated batch buffer for
        this server's fn at ``bucket`` rows: every batch leaf must find
        a distinct output leaf with identical (shape, dtype) to alias
        (GC001's consumption criterion, probed abstractly — one
        ``eval_shape``, no compile).  Donating an unconsumable buffer
        is harmless but noisy (XLA drops it with a warning), so the
        auto path only declares what the audit would verify consumed.
        Zoo models never reach here (their uint8 batch can never alias
        the float features — the recorded GC001 exemption rides the
        ``zoo_serving_bundle`` engine overrides as
        ``donate_batch=False``)."""
        import jax

        from collections import Counter

        try:
            cdt = self._compute_dtype

            def var_aval(leaf):
                arr = leaf if hasattr(leaf, "dtype") else np.asarray(leaf)
                dt = arr.dtype
                if cdt is not None and np.issubdtype(dt, np.floating):
                    dt = cdt  # mirror the engine's _cast_floating
                return jax.ShapeDtypeStruct(tuple(arr.shape), dt)

            variables = jax.tree_util.tree_map(var_aval,
                                               self._host_variables)
            avals = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    (bucket,) + tuple(a.shape[1:]), a.dtype),
                batch_example)
            out = jax.eval_shape(self._fn, variables, avals)
            need = Counter((tuple(l.shape), np.dtype(l.dtype))
                           for l in jax.tree_util.tree_leaves(avals))
            have = Counter((tuple(l.shape), np.dtype(l.dtype))
                           for l in jax.tree_util.tree_leaves(out))
            return all(have[k] >= c for k, c in need.items())
        except Exception as e:  # noqa: BLE001 — probe must never break serving
            logger.info("donation probe failed (%s: %s); building bucket "
                        "%d without batch donation", type(e).__name__, e,
                        bucket)
            return False

    def _engine_for(self, bucket: int, batch_example: Any = None):
        with self._engine_lock:
            eng = self._engines.get(bucket)
            if eng is None:
                from sparkdl_tpu.parallel.engine import InferenceEngine

                first = next(iter(self._engines.values()), None)
                donate = self._donate_batch
                if donate is None:
                    # auto: donate the per-dispatch device batch iff the
                    # probe proves XLA will alias it into an output
                    # (ISSUE 13 satellite — the engine device_puts a
                    # fresh buffer per dispatch and never touches it
                    # again, so donation is always SAFE; the probe only
                    # decides whether it is CONSUMED)
                    donate = (self._probe_donate(bucket, batch_example)
                              if batch_example is not None else False)
                # Buckets share ONE device copy of the weights (device_put
                # of an already-replicated pytree is a no-op) and ONE jit
                # program (module-level engine cache keyed on fn/mesh) —
                # each bucket only adds one executable for its shape.
                eng = InferenceEngine(
                    self._fn,
                    first.variables if first is not None
                    else self._host_variables,
                    mesh=first.mesh if first is not None else self._mesh,
                    device_batch_size=bucket,
                    compute_dtype=(None if first is not None
                                   else self._compute_dtype),
                    output_host_dtype=self._output_host_dtype,
                    donate_batch=bool(donate),
                    # later buckets resolve the same policy against the
                    # first bucket's already-sharded device arrays —
                    # same specs, so device_put is a per-leaf no-op and
                    # every bucket shares one device copy of the weights
                    partition_rules=self._partition_rules,
                    param_shardings=self._param_shardings,
                    dispatch_retries=self._dispatch_retries,
                    breaker_threshold=self._breaker_threshold,
                    breaker_cooldown_s=self._breaker_cooldown_s,
                    on_dispatch_error=self._note_failure,
                    metrics=self.metrics)
                self._engines[bucket] = eng
            return eng

    def warmup(self, example: Any) -> None:
        """Compile every bucket's program ahead of traffic (one dummy
        dispatch per bucket shaped like ``example``, a single request
        payload) so first requests never pay compile time."""
        import jax

        if self._host_preprocess is not None:
            example = self._host_preprocess(example)
        example = jax.tree_util.tree_map(np.asarray, example)
        for b in self._buckets:
            # buckets are mesh-rounded already (bucket_plan), so the
            # bucket IS the engine's device batch; stacking first lets
            # _engine_for's donation probe see the real batch aval
            stacked = jax.tree_util.tree_map(
                lambda a: np.stack([a] * b), example)
            eng = self._engine_for(b, stacked)
            eng(stacked)
            self._warm.add(b)

    # -- health / failure domain -------------------------------------------
    def _note_failure(self, exc: BaseException) -> None:
        """Record a failed dispatch attempt / batch: state -> degraded.
        Wired as every engine's ``on_dispatch_error`` hook, so faults an
        engine-level retry absorbs still leave a health trace."""
        self._health.note_failure(exc)

    def _note_success(self) -> None:
        self._health.note_success()

    def _breaker_states(self) -> Dict[int, Dict[str, Any]]:
        with self._engine_lock:
            engines = dict(self._engines)
        return {b: eng.breaker_state() for b, eng in sorted(engines.items())}

    def _breaker_retry_after(self) -> Optional[float]:
        """Max remaining cool-down over OPEN bucket breakers, or None
        when none is open (the per-submit fast path: one cheap query per
        engine, no state snapshots).  Half-open breakers admit traffic —
        the trial dispatch that can close them has to come from
        somewhere."""
        with self._engine_lock:
            engines = list(self._engines.values())
        worst = None
        for eng in engines:
            remaining = eng.breaker.open_remaining_s()
            if remaining is not None:
                worst = max(worst or 0.0, remaining)
        return worst

    def breaker_retry_after(self) -> Optional[float]:
        """Public form of the per-submit breaker query: remaining
        cool-down of the worst OPEN bucket breaker, or None when
        admission is open.  The fleet front door consults this to shed
        lowest-priority traffic first while a model's device is
        failing."""
        return self._breaker_retry_after()

    def health(self) -> Dict[str, Any]:
        """Liveness/readiness snapshot (JSON-serializable; also embedded
        in :meth:`varz`), built through the ONE
        :meth:`~sparkdl_tpu.utils.health.HealthTracker.payload` schema
        every ``health()`` in the stack shares (ISSUE 9):

        * ``live`` — the serving loop exists (False once closed);
        * ``state`` — ``ready`` (serving normally), ``degraded``
          (breaker open/half-open, SLO breach, or a dispatch/batch
          failure with no success since), or ``closed``;
        * ``last_error`` — most recent failure (type/message/monotonic
          ts), surviving recovery for post-mortems;
        * ``transitions`` — bounded ready/degraded history, so a
          degraded->ready recovery is observable after the fact;
        * ``breaker`` — per-bucket engine circuit-breaker state (this
          surface's extra);
        * ``slo`` — the objective evaluation, when ``slos=`` were
          configured (each ``health()`` poll takes one burn-rate
          sample).
        """
        extra: Dict[str, Any] = {}
        if self._slo_engine is not None:
            # evaluate BEFORE the snapshot: a breach crossing on this
            # very poll must already show as degraded
            extra["slo"] = self._slo_engine.evaluate()
        breakers = self._breaker_states()
        state_override = None
        if any(st["state"] in ("open", "half_open")
               for st in breakers.values()):
            state_override = "degraded"
        if self._closed:
            state_override = "closed"
        return self._health.payload(live=not self._closed,
                                    state_override=state_override,
                                    breaker=breakers, **extra)

    # -- request path ------------------------------------------------------
    def submit(self, example: Any,
               timeout_ms: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        """Admit one example; returns its ``concurrent.futures.Future``.

        ``tenant`` is the cost-attribution identity (ISSUE 18) — it
        changes nothing about scheduling or admission here (quota lives
        in the Fleet); it only decides which ledger line the request's
        device/queue time lands on.  None charges ``"default"``.

        Raises ``ServerClosedError`` after close, ``QueueFullError``
        (with ``retry_after_s``) under backpressure, and
        ``ServiceUnavailableError`` (with ``retry_after_s``) while the
        dispatch circuit breaker is open — the device is failing every
        dispatch, so admitting more work would only convert each request
        into a slow timeout.  ``timeout_ms`` overrides the server's
        ``default_timeout_ms`` deadline.

        With a result cache configured (ISSUE 11) the probe runs FIRST
        — before the breaker shed and the admission-queue charge — so a
        hit serves even while the device is failing (the cached row
        needs no device), and N concurrent identical requests cost one
        dispatch: the first becomes the single-flight leader, the rest
        park on its future.  A leader failure settles its followers
        with the same error and caches nothing.
        """
        if self._closed:
            raise ServerClosedError("server is closed")
        if self._cache is not None:
            return self._submit_cached(example, timeout_ms, tenant)
        return self._submit_dispatch(example, timeout_ms, tenant=tenant)

    def _charge_hit(self, tenant: Optional[str], kind: str) -> None:
        """Near-zero ledger charge for a cache-absorbed request.
        Attribution is observability: any failure (the ``cost.attr``
        fault site included) degrades to an error counter — it must
        never fail the request it was accounting for."""
        if self._cost is None:
            return
        try:
            self._cost.record_hit(tenant=tenant or "default",
                                  model=self.model_desc, kind=kind)
        # graftlint: allow=SDL003 reason=cost.attr degrade contract: attribution failure is counted and logged, the request it accounted for already served
        except Exception as e:  # noqa: BLE001
            self.metrics.incr("serving.cost_attr_errors")
            self._cost.record_error()
            logger.warning("cost attribution (%s) failed: %s: %s", kind,
                           type(e).__name__, e)

    def _submit_cached(self, example: Any,
                       timeout_ms: Optional[float],
                       tenant: Optional[str] = None) -> Future:
        """The cache-fronted request path; see :meth:`submit`."""
        import jax

        t0 = self._clock()
        if self._host_preprocess is not None:
            example = self._host_preprocess(example)
        example = jax.tree_util.tree_map(np.asarray, example)
        key = self._cache_ns + (content_digest(example),)
        kind, res = self._cache.lookup(key)
        if kind == "hit":
            self.metrics.incr("serving.requests")
            self.metrics.incr("serving.completed")
            self.metrics.incr("serving.cache_hits")
            self.metrics.record_time("serving.request_latency",
                                     self._clock() - t0)
            self._charge_hit(tenant, "hit")
            fut: Future = Future()
            fut.set_result(res)
            return fut
        if kind == "follower":
            self.metrics.incr("serving.requests")
            self.metrics.incr("serving.cache_coalesced")
            self._charge_hit(tenant, "coalesced")

            def _follower_done(f: Future) -> None:
                if not f.cancelled() and f.exception() is None:
                    self.metrics.incr("serving.completed")
                    self.metrics.record_time("serving.request_latency",
                                             self._clock() - t0)

            # a coalesced follower keeps its OWN deadline: the leader
            # may have none, and "timeout_ms overrides the server
            # default" must hold whether or not the request coalesced
            timeout_s = (self._default_timeout_s if timeout_ms is None
                         else max(0.0, timeout_ms) / 1e3)
            caller_fut = (res if timeout_s is None
                          else _deadline_guard(res, timeout_s))
            # metrics ride the future the CALLER holds: a follower
            # whose deadline guard already failed it must not count as
            # completed (with the leader's latency) when the leader
            # eventually settles
            caller_fut.add_done_callback(_follower_done)
            return caller_fut
        flight = res
        try:
            # the leader's payload must be OURS: the digest above
            # described the ORIGINAL bytes, and a caller that refills
            # its input buffer after submit() returns would otherwise
            # have the dispatch compute the NEW bytes' output and
            # settle it under the OLD digest — a self-validating
            # poisoned entry the output re-check cannot catch.
            # O(input) copy, paid by leaders (misses) only; inside the
            # try so even a failed copy (MemoryError) fails the flight
            # instead of leaking it (which would park every later
            # identical request on a future nobody resolves).
            example = jax.tree_util.tree_map(
                lambda a: np.array(a, copy=True), example)
            # chaos hook: a sleep rule here holds the leader open so
            # follower pile-up is observable; an error rule is a leader
            # failure every follower must see (and caches nothing)
            inject("cache.stampede")
            fut = self._submit_dispatch(example, timeout_ms,
                                        preprocessed=True, tenant=tenant)
        except BaseException as e:  # noqa: BLE001 — settled to followers, re-raised
            self._cache.fail(flight, e)
            raise
        # the caller gets a SEPARATE future resolved only AFTER settle
        # has copied the row: returning the dispatch future directly
        # would let the caller mutate its row in place concurrently
        # with settle's copy — a torn copy would digest-validate
        # against itself and poison every later hit
        out: Future = Future()

        def _leader_done(f: Future) -> None:
            # settle/fail OFF the dispatch worker's completion: insert
            # + resolve followers on success, fail them (cache
            # untouched) on error — a poisoned result can never be
            # stored because only a SUCCESSFUL dispatch settles
            try:
                value = f.result()
            # graftlint: allow=SDL003 reason=the leader error is relayed to every follower via cache.fail and the caller future; re-raising in a done-callback would only hit the executor's swallow
            except BaseException as e:  # noqa: BLE001
                self._cache.fail(flight, e)
                if not out.done():
                    out.set_exception(e)
            else:
                # store=False once closed: close() already reclaimed an
                # owned namespace, and a late-settling leader (the
                # abandoned-wait close path) must not re-insert under
                # it — followers still get their copies either way
                self._cache.settle(
                    flight, value,
                    store=not (self._closed and self._cache_ns_owned))
                if not out.done():
                    out.set_result(value)

        fut.add_done_callback(_leader_done)
        return out

    def _submit_dispatch(self, example: Any,
                         timeout_ms: Optional[float],
                         preprocessed: bool = False,
                         tenant: Optional[str] = None) -> Future:
        """The direct dispatch path (the whole request path when no
        cache is configured; the single-flight leader's path when one
        is)."""
        retry_after = self._breaker_retry_after()
        if retry_after is not None:
            # count the request too: shed-rate consumers compute
            # rejected_*/requests, and queue-full rejects (raised after
            # the serving.requests incr below) are in the denominator —
            # breaker sheds must be as well or the ratio breaks 1.0
            self.metrics.incr("serving.requests")
            self.metrics.incr("serving.rejected_breaker_open")
            flight_emit("serving.shed", reason="breaker_open",
                        retry_after_s=round(retry_after, 4))
            raise ServiceUnavailableError(
                f"dispatch circuit breaker open (device failing); "
                f"retry in {retry_after:.2f}s", retry_after_s=retry_after)
        if not preprocessed:
            if self._host_preprocess is not None:
                example = self._host_preprocess(example)
            import jax

            example = jax.tree_util.tree_map(np.asarray, example)
        timeout_s = (self._default_timeout_s if timeout_ms is None
                     else max(0.0, timeout_ms) / 1e3)
        now_m = self._clock()
        deadline = None if timeout_s is None else now_m + timeout_s
        req = Request(example, deadline, now=now_m,
                      tenant=tenant or "default")
        tracer = get_tracer()
        if tracer.enabled:
            # root span of this request's trace: submit -> future settle
            req.span = tracer.start_span(
                "serving.request",
                timeout_ms=None if timeout_s is None else timeout_s * 1e3)
        self.metrics.incr("serving.requests")
        try:
            self._batcher.submit(req)
        except BaseException:
            req.finish_span("rejected")
            raise
        return req.future

    def predict(self, example: Any,
                timeout_ms: Optional[float] = None) -> Any:
        """Blocking single-request convenience: submit + wait."""
        return self.submit(example, timeout_ms=timeout_ms).result()

    async def predict_async(self, example: Any,
                            timeout_ms: Optional[float] = None) -> Any:
        """Awaitable form for asyncio handlers (wraps the submit future)."""
        import asyncio

        return await asyncio.wrap_future(
            self.submit(example, timeout_ms=timeout_ms))

    # -- dispatch ----------------------------------------------------------
    def _dispatch_loop(self) -> None:
        while True:
            batch = self._batcher.next_batch()
            if batch is None:
                return  # closed and drained
            if not batch:
                continue  # every request shed at flush
            # interruptible slot wait: if close() abandons a wedged server
            # (no watchdog configured), the batches the dispatcher holds
            # must still SETTLE — clients block in result() forever
            # otherwise
            acquired = False
            while not acquired and not self._abandon.is_set():
                acquired = self._inflight_sem.acquire(timeout=0.1)
            if not acquired:
                _settle_error(batch, ServerClosedError(
                    "server close abandoned a wedged dispatch; request "
                    "was never dispatched"))
                continue
            with self._inflight_cond:
                self._inflight += 1
            worker = threading.Thread(
                target=self._run_batch, args=(batch,), daemon=True,
                name="sparkdl-serving-batch")
            worker.start()

    def _finish_batch(self) -> None:
        self._inflight_sem.release()
        with self._inflight_cond:
            self._inflight -= 1
            self._inflight_cond.notify_all()

    def _run_batch(self, requests: List[Request]) -> None:
        finish = _Once(self._finish_batch)
        try:
            self._execute(requests, finish)
        except BaseException as e:  # noqa: BLE001 — isolate to this batch
            self.metrics.incr("serving.batch_failures")
            self._note_failure(e)
            _settle_error(requests, e)
            logger.warning("serving batch of %d failed: %s: %s",
                           len(requests), type(e).__name__, e)
        finally:
            finish()

    @staticmethod
    def _metered_kwargs(eng, on_metered) -> Dict[str, Any]:
        """``{"on_metered": ...}`` only when ``eng`` can take it.  Tests
        (and embedders) substitute plain ``fn(batch)`` callables for the
        engine; those still serve — they just don't feed the cost
        ledger's device-time meter (they don't tick the engine's
        ``engine.device_time_s`` counter either, so conservation holds).
        The signature probe is cached on the callable."""
        if on_metered is None:
            return {}
        cached = getattr(eng, "_sdl_accepts_on_metered", None)
        if cached is None:
            try:
                params = inspect.signature(eng).parameters
                cached = ("on_metered" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values()))
            except (TypeError, ValueError):
                cached = False
            try:
                eng._sdl_accepts_on_metered = cached
            except AttributeError:
                pass
        return {"on_metered": on_metered} if cached else {}

    def _guarded_call(self, eng, stacked, requests: List[Request],
                      finish: _Once, on_metered=None):
        """One model-call ATTEMPT under the stall watchdog.  The timer is
        armed per attempt (retry backoff and later attempts get their own
        window, so configuring retries never silently nullifies them) and
        covers ONLY the engine call — compile time is excluded by the
        untimed warm call in ``_execute``, and the host-side demux runs
        after the timer is disarmed.  The ``serving.model`` fault site
        sits INSIDE the watchdog window (a ``sleep`` rule is a wedged
        model the watchdog must catch; an ``error`` rule is a per-batch
        model failure)."""
        meter_kw = self._metered_kwargs(eng, on_metered)
        if self._dispatch_timeout_s is None:
            inject("serving.model")
            return eng(stacked, **meter_kw)
        attempt_done = threading.Event()

        def on_stall():
            if attempt_done.is_set():
                return
            self.metrics.incr("serving.dispatch_timeouts")
            self.metrics.incr("serving.batch_failures")
            _settle_error(requests, DispatchTimeoutError(
                f"model call exceeded "
                f"{self._dispatch_timeout_s * 1e3:.0f}ms; batch of "
                f"{len(requests)} abandoned"))
            # free the concurrency slot the wedged worker holds so later
            # batches keep flowing
            finish()

        timer = threading.Timer(self._dispatch_timeout_s, on_stall)
        timer.daemon = True
        timer.start()
        try:
            inject("serving.model")
            return eng(stacked, **meter_kw)
        finally:
            attempt_done.set()
            timer.cancel()

    def _top_off(self, gap: int, bucket: int, base: int,
                 like: Any) -> List[Request]:
        """The continuous half of ragged batching (ISSUE 13): right
        before a sub-bucket batch stacks, pull up to ``gap`` requests
        that arrived since the flush decision — they ride pad rows the
        dispatch was about to waste.  The ``batch.topoff`` fault site
        covers the pull: top-off is an OPTIMIZATION, so an injected
        failure degrades to the baseline padding (nobody is lost, the
        base batch still dispatches) instead of failing the batch."""
        try:
            inject("batch.topoff")
        # graftlint: allow=SDL003 reason=chaos contract: a failed top-off pull degrades to baseline padding (logged); the base batch must still dispatch
        except Exception as e:  # noqa: BLE001
            logger.warning("batch.topoff aborted: %s: %s; dispatching at "
                           "base fill %d/%d", type(e).__name__, e, base,
                           bucket)
            self.metrics.incr("serving.topoff_aborted")
            return []
        extras = self._batcher.top_off(gap, like=like)
        if extras:
            self.metrics.incr("serving.topoffs")
            self.metrics.incr("serving.topoff_rows", len(extras))
            flight_emit("batch.topoff", rows=len(extras), base=base,
                        bucket=bucket)
        return extras

    def _execute(self, requests: List[Request], finish: _Once) -> None:
        import jax

        n = len(requests)
        bucket = self._bucket_for(n)
        if self._ragged and n < bucket and len(
                {DynamicBatcher._payload_signature(r.payload)
                 for r in requests}) == 1:
            # top off only when the WHOLE base batch stacks: a flush can
            # legitimately pop mixed shapes (that batch is doomed to
            # fail its own stack — baseline behavior), and pulling a
            # healthy late arrival into it would widen the failure's
            # blast radius beyond what the flush policy dealt
            extras = self._top_off(bucket - n, bucket, n,
                                   requests[0].payload)
            if extras:
                # extend IN PLACE: _run_batch's error handler and the
                # stall watchdog hold this same list — a topped-off
                # request must be settled by every failure path too
                requests.extend(extras)
                n = len(requests)
        now = self._clock()
        queue_by: Dict[str, float] = {}
        for r in requests:
            waited = now - r.enqueued_at
            self.metrics.record_time("serving.time_in_queue", waited)
            queue_by[r.tenant] = queue_by.get(r.tenant, 0.0) + waited
        # Dispatch rides the same engine entrypoint as the offline stack
        # (parallel.pipeline): a micro-batch is a single device batch, so
        # the engine's single-piece fast path applies (no thread hop on
        # the latency path) and the online H2D/compute/gather overlap
        # comes from running up to max_inflight_batches of these worker
        # threads concurrently over jax's async dispatch.
        stacked = jax.tree_util.tree_map(
            lambda *rows: np.stack(rows, axis=0),
            *[r.payload for r in requests])
        eng = self._engine_for(bucket, stacked)
        if self._dispatch_timeout_s is not None and bucket not in self._warm:
            # compile OUTSIDE the watchdog window: the first call to a
            # bucket jits the program (seconds for real models), which
            # would otherwise eat any production-sized dispatch timeout
            eng(jax.tree_util.tree_map(np.zeros_like, stacked))
            self._warm.add(bucket)
        tracer = get_tracer()
        batch_span = requests[0].batch_span
        if batch_span is not None:
            batch_span.annotate(bucket=bucket)
        t0 = time.monotonic()  # real: batch_seconds_hint sizes real waits
        # re-root this worker thread onto the micro-batch span so the
        # engine's own spans (engine.call -> engine.dispatch) nest under
        # serving.request -> serving.microbatch
        # per-attempt metered engine seconds (the cost ledger's device-
        # time feed; retries append — the batch is charged what it
        # actually burned, not just the winning attempt)
        metered: List[float] = []
        with tracer.use(batch_span):
            # CircuitOpenError is exempt from the batch retry budget for
            # the same reason the engine's own _run_dispatch exempts it:
            # an open breaker fails fast BY DESIGN, and re-attempting it
            # max_retries times with backoff would turn every shed batch
            # into seconds of dead sleep against a device known to be
            # failing
            out = with_retries(
                lambda: self._guarded_call(eng, stacked, requests, finish,
                                           on_metered=metered.append),
                max_retries=self._max_retries,
                non_retryable=NON_RETRYABLE + (CircuitOpenError,),
                backoff_seconds=self._retry_backoff_s)
        batch_s = time.monotonic() - t0
        self._note_success()  # a served batch flips health back to ready
        self._batcher.batch_seconds_hint = batch_s
        self.metrics.incr("serving.batches")
        self.metrics.record_time("serving.batch_latency", batch_s)
        self.metrics.observe("serving.batch_fill_ratio",
                             n / eng.device_batch_size)
        # Attribute the settled batch BEFORE futures resolve, so any
        # completion-ordered observer (the fleet's settle barrier, the
        # twin's tick) sees the ledger already charged.  Degrade-not-
        # fail: the batch SERVED — an attribution failure (cost.attr
        # chaos included) is an error counter, never a failed request.
        if self._cost is not None:
            try:
                tenant_rows: Dict[str, int] = {}
                for r in requests:
                    tenant_rows[r.tenant] = tenant_rows.get(r.tenant,
                                                            0) + 1
                hbm = self._cost_hbm.get(bucket)
                if hbm is None:
                    sh = eng.sharding_info()
                    hbm = float(sh.get("param_bytes_per_chip") or 0.0)
                    self._cost_hbm[bucket] = hbm
                self._cost.record_batch(
                    model=self.model_desc, bucket=bucket,
                    tenant_rows=tenant_rows,
                    device_s=sum(metered),
                    queue_s_by_tenant=queue_by,
                    pad_rows=bucket - n,
                    hbm_bytes=hbm)
            # graftlint: allow=SDL003 reason=cost.attr degrade contract: attribution failure is counted and logged, the served batch still settles below
            except Exception as e:  # noqa: BLE001
                self.metrics.incr("serving.cost_attr_errors")
                self._cost.record_error()
                logger.warning("cost attribution failed for batch of %d "
                               "(bucket %d): %s: %s", n, bucket,
                               type(e).__name__, e)
        done = self._clock()
        slowest: Optional[Request] = None
        slowest_s = 0.0
        for i, r in enumerate(requests):
            if r.future.done():
                continue  # watchdog raced us; result discarded
            # copy, don't view: a retained row must pin O(row), not the
            # whole [bucket, ...] batch output it was sliced from
            row = jax.tree_util.tree_map(
                lambda a: np.array(a[i], copy=True), out)
            try:
                r.future.set_result(row)
                self.metrics.incr("serving.completed")
                latency_s = done - r.enqueued_at
                self.metrics.record_time("serving.request_latency",
                                         latency_s)
                if latency_s >= slowest_s:
                    slowest, slowest_s = r, latency_s
            except InvalidStateError:
                pass
        # close the micro-batch span BEFORE the request roots so every
        # child window sits inside its parent's, then capture exemplars
        # (offer is a float compare unless this batch holds a new top-K
        # outlier; a no-op with tracing off)
        if batch_span is not None:
            requests[0].batch_span = None
            batch_span.finish()
        slow_trace = (slowest.span.trace_id
                      if slowest is not None and slowest.span is not None
                      else None)
        for r in requests:
            r.finish_span()
        if slow_trace is not None:
            self.exemplars.offer(slowest_s, slow_trace, tracer)

    # -- lifecycle ---------------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._closed

    def queue_depth(self) -> int:
        return self._batcher.depth()

    @property
    def bucket_sizes(self) -> List[int]:
        """The compiled bucket plan (mesh-rounded, de-duplicated)."""
        return list(self._buckets)

    @property
    def max_queue(self) -> int:
        return self._batcher.max_queue

    def queue_pressure(self) -> float:
        """Queue occupancy in [0, 1] — the admission-pressure signal the
        fleet layer sheds lowest-priority traffic against."""
        return self._batcher.depth() / max(1, self._batcher.max_queue)

    def wake(self) -> None:
        """Re-evaluate the batcher's flush conditions — how a
        virtual-time driver tells the dispatcher the injected clock
        moved (see :meth:`DynamicBatcher.wake`)."""
        self._batcher.wake()

    @property
    def cache(self):
        """The result cache this server probes (None when uncached)."""
        return self._cache

    @property
    def cache_namespace(self) -> tuple:
        """The key prefix this server's entries live under."""
        return self._cache_ns

    def sharding_info(self) -> Optional[Dict[str, Any]]:
        """The bucket engines' weight-sharding layout (ISSUE 14):
        mesh shape, total vs per-chip param bytes, sharded leaf count,
        policy digest.  All buckets share one device weight copy and
        one policy, so the first engine's snapshot speaks for the
        server; ``None`` until a bucket engine exists (pre-warmup, no
        traffic yet)."""
        with self._engine_lock:
            first = next(iter(self._engines.values()), None)
        return None if first is None else first.sharding_info()

    def executable_state(self) -> Dict[int, Dict[str, Any]]:
        """Per-bucket compiled-program identity: the ``id()`` of the
        bucket engine's shared ``jax.jit`` object and that object's
        executable-cache size.  Two servers over the SAME fn (a fleet
        entry's v1 and v2) report equal ``jit_id`` per bucket, and a
        hot-swap that truly reuses the compiled executable leaves
        ``executables`` unchanged — the no-recompile proof
        ``serving.fleet.rollout`` asserts at promote time."""
        with self._engine_lock:
            engines = dict(self._engines)
        out: Dict[int, Dict[str, Any]] = {}
        for b, eng in sorted(engines.items()):
            compiled = eng._compiled
            try:
                n_exec = int(compiled._cache_size())
            except (AttributeError, TypeError):  # older jax: identity only
                n_exec = None
            out[b] = {"jit_id": id(compiled), "executables": n_exec}
        return out

    def stats(self) -> Dict[str, float]:
        """Snapshot of the serving metrics (counters, gauges, latency
        p50/p99 — see ``utils.metrics.Metrics.summary``), plus any
        ``pipeline.*`` stage metrics the shared engines recorded."""
        summary = self.metrics.summary()  # ONE aggregation pass
        return {k: v for k, v in summary.items()
                if k.startswith(("serving.", "engine_", "pipeline."))}

    def varz(self) -> Dict[str, Any]:
        """The ``/varz``-shaped structured form of :meth:`stats`: nested
        sections instead of flat dotted keys, plus server config/state,
        the full metrics snapshot (stable schema —
        ``obs.export.metrics_snapshot``), and the slow-request exemplars
        (full span trees of the slowest requests; populated only while
        ``SPARKDL_TRACE`` tracing is on).  JSON-serializable throughout:
        ``json.dumps(srv.varz())`` IS the monitoring endpoint body."""
        from sparkdl_tpu.obs.export import metrics_snapshot

        m = self.metrics

        def dist_ms(name: str) -> Dict[str, float]:
            out: Dict[str, float] = {}
            for q, key in ((50, "p50_ms"), (99, "p99_ms")):
                v = m.percentile(name, q, kind="timing")
                if v is not None:
                    out[key] = round(v * 1e3, 3)
            return out

        snap = metrics_snapshot(m)
        return {
            "server": {
                "closed": self._closed,
                "max_batch_size": self.max_batch_size,
                "bucket_sizes": list(self._buckets),
                "ragged": self._ragged,
                "queue_depth": self.queue_depth(),
                "inflight_batches": self._inflight,
            },
            "health": self.health(),
            "counters": {k: v for k, v in snap["counters"].items()
                         if k.startswith("serving.")},
            "latency_ms": {
                "request": dist_ms("serving.request_latency"),
                "batch": dist_ms("serving.batch_latency"),
                "queue": dist_ms("serving.time_in_queue"),
            },
            "metrics": snap,
            "cache": (self._cache.info() if self._cache is not None
                      else None),
            "cost": (self._cost.snapshot() if self._cost is not None
                     else None),
            "sharding": self.sharding_info(),
            "exemplars": self.exemplars.snapshot(),
        }

    def close(self, drain: bool = True,
              timeout_s: Optional[float] = 30.0) -> None:
        """Stop the server.  ``drain=True`` (graceful): stop admission,
        flush and serve everything already queued, wait for in-flight
        batches.  ``drain=False``: queued requests fail with
        ``ServerClosedError``; in-flight batches are still awaited.
        Idempotent.

        If the drain cannot complete within ``timeout_s`` (a wedged model
        call with no ``dispatch_timeout_ms`` configured), the wait is
        abandoned and every request NOT in the wedged batch itself is
        settled with ``ServerClosedError`` — only futures inside a batch
        whose model call never returns stay pending (configure
        ``dispatch_timeout_ms`` to bound that case too)."""
        if self._closed:
            self._batcher.close(drain=drain)
            return
        self._closed = True
        flight_emit("serving.drain", drain=drain,
                    queued=self._batcher.depth())
        try:
            self._batcher.close(drain=drain)
            self._dispatcher.join(timeout=timeout_s)
            if self._dispatcher.is_alive():
                logger.warning(
                    "close(): dispatcher still busy after %ss; abandoning "
                    "— undispatched requests fail with ServerClosedError",
                    timeout_s)
                self._abandon.set()
                self._dispatcher.join(timeout=5.0)
                self._batcher.close(drain=False)  # settle anything queued
            deadline = (None if timeout_s is None
                        else time.monotonic() + timeout_s)
            with self._inflight_cond:
                while self._inflight > 0:
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        logger.warning(
                            "close(): %d batch(es) still in flight "
                            "after %.1fs; abandoning wait",
                            self._inflight, timeout_s)
                        return
                    self._inflight_cond.wait(remaining)
        finally:
            if self._cache is not None and self._cache_ns_owned:
                # this server's anon namespace is unreachable once it
                # is closed — reclaim the bytes from the shared store
                self._cache.invalidate(self._cache_ns)

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(drain=exc_type is None)


class HeadFanoutServer:
    """Featurize ONCE, serve thousands of per-tenant heads (ISSUE 17).

    The production shape of the paper's core trick (a shared
    ``DeepImageFeaturizer`` backbone + a cheap per-use-case head): one
    backbone :class:`Server` at the FEATURE cut, fronted by the
    feature-cut cache namespace (``serving.cache.feature_namespace`` —
    keyed on the backbone's lockfile fingerprint + weight digest, so a
    hot content digest pays the backbone once EVER, and head churn
    keeps entries warm), fanned out through a
    :class:`~sparkdl_tpu.parallel.engine.HeadBank` whose single vmapped
    program serves every tenant's head by gather-by-tenant-index.

    Per-request cost once the feature cache is warm: zero backbone
    FLOPs, zero backbone queue slots (the probe short-circuits BEFORE
    the backbone server), head-milliseconds only.  Per-fleet HBM cost:
    one backbone copy + one stacked head bank (budgeted via
    ``hbm_budget_bytes`` against ``mesh.param_sharding_stats``) instead
    of a full model copy per tenant.

    The no-backbone-recompile contract: :meth:`add_head` /
    :meth:`swap_head` / :meth:`remove_head` return a
    ``serving.fleet.rollout.head_swap_report`` proving — via backbone
    jit-object identity, executable-cache non-growth, and the committed
    ``PROGRAMS.lock.json`` fingerprint — that head mutation never
    touched the backbone program.  In-flight requests are safe across a
    swap: the bank mutates atomically under its lock, so every future
    settles (with the old head's output or the new one, never a torn
    bank)."""

    def __init__(self, model, variables: Any = None, *,
                 head_fn: Optional[Callable] = None,
                 mesh=None,
                 hbm_budget_bytes: Optional[int] = None,
                 cache: Any = None,
                 cost: Any = None,
                 metrics: Optional[Metrics] = None,
                 model_desc: Optional[str] = None,
                 **server_kwargs):
        from sparkdl_tpu.parallel.engine import HeadBank
        from sparkdl_tpu.serving.cache import (feature_namespace,
                                               lockfile_model_fingerprint,
                                               resolve_cache)

        if isinstance(model, str):
            from sparkdl_tpu.transformers.named_image import \
                zoo_serving_bundle

            fn, host_vars, overrides, zoo_head = zoo_serving_bundle(
                model, featurize=True, feature_cut=True)
            if head_fn is None:
                head_fn = zoo_head
            desc = model
        else:
            fn, host_vars, overrides = _resolve_model(
                model, variables, featurize=True)
            desc = getattr(model, "__name__", type(model).__name__)
        self.model_desc = model_desc if model_desc is not None else desc
        self.metrics = metrics if metrics is not None else Metrics()
        self._backbone_fn = fn
        self._backbone_vars = host_vars
        # Backbone identity, pinned ONCE at construction: the committed
        # StableHLO fingerprint (None for unaudited fns) and the weight
        # digest together key the feature-cut namespace — head churn
        # can touch neither.
        self._fingerprint = lockfile_model_fingerprint(self.model_desc)
        self._weights_digest = content_digest(host_vars)
        self._feature_ns = feature_namespace(
            self.model_desc, self._fingerprint, self._weights_digest)
        # The backbone Server is built from the RESOLVED fn (one
        # resolution, like the fleet registry) so its jit identity is
        # this object's identity for the whole lifetime; zoo engine
        # overrides ride along fleet-style (caller kwargs win, and the
        # dtype pair travels together).
        dtype_keys = ("compute_dtype", "output_host_dtype")
        caller_set_dtype = any(k in server_kwargs for k in dtype_keys)
        for k, v in overrides.items():
            if k in dtype_keys and caller_set_dtype:
                continue
            server_kwargs.setdefault(k, v)
        resolved_cache, _, _ = resolve_cache(cache, self._feature_ns,
                                             "headfanout")
        # One ledger for the tier: feature-hit charges here and the
        # backbone's device-time attribution land on the SAME instance,
        # so the per-tenant showback covers both halves of a request
        from sparkdl_tpu.obs.cost import resolve_cost

        self._cost = resolve_cost(cost)
        self._backbone = Server(fn, host_vars, mesh=mesh,
                                cache=(resolved_cache if resolved_cache
                                       is not None else False),
                                cache_namespace=self._feature_ns,
                                metrics=self.metrics,
                                cost=(self._cost if self._cost is not None
                                      else False),
                                model_desc=self.model_desc,
                                **server_kwargs)
        self._bank = HeadBank(head_fn=head_fn, mesh=mesh,
                              hbm_budget_bytes=hbm_budget_bytes,
                              metrics=self.metrics)
        self.last_head_swap_report: Optional[Dict[str, Any]] = None
        self._swap_lock = named_lock("serving.headfanout.swap")

    # -- head management (the no-backbone-recompile surface) --------------

    @property
    def bank(self):
        """The :class:`HeadBank` serving this tier's head pass."""
        return self._bank

    @property
    def backbone(self) -> Server:
        """The feature-cut backbone server."""
        return self._backbone

    @property
    def feature_namespace(self) -> tuple:
        """The feature-cut cache namespace (backbone identity only)."""
        return self._feature_ns

    def tenants(self) -> List[str]:
        return self._bank.tenants()

    def _head_mutation(self, op: str, tenant: str, weights) -> Dict[str, Any]:
        from sparkdl_tpu.serving.fleet.rollout import head_swap_report

        with self._swap_lock:
            exec_before = self._backbone.executable_state()
            bank_before = self._bank.jit_info()
            fp_before = self._fingerprint
            if op == "add":
                self._bank.add_head(tenant, weights)
            elif op == "swap":
                self._bank.swap_head(tenant, weights)
            else:
                self._bank.remove_head(tenant)
            from sparkdl_tpu.serving.cache import \
                lockfile_model_fingerprint

            report = head_swap_report(
                self.model_desc, tenant, op,
                exec_before, self._backbone.executable_state(),
                bank_before, self._bank.jit_info(),
                fp_before, lockfile_model_fingerprint(self.model_desc))
            self.last_head_swap_report = report
            return report

    def add_head(self, tenant: str, weights) -> Dict[str, Any]:
        """Register a new tenant's head; returns the no-backbone-
        recompile report (``head_swap_report``)."""
        return self._head_mutation("add", tenant, weights)

    def swap_head(self, tenant: str, weights) -> Dict[str, Any]:
        """Hot-swap an existing tenant's head under load; returns the
        no-backbone-recompile report."""
        return self._head_mutation("swap", tenant, weights)

    def remove_head(self, tenant: str) -> Dict[str, Any]:
        """Evict a departed tenant's head; returns the report."""
        return self._head_mutation("remove", tenant, None)

    # -- request path ------------------------------------------------------

    def _feature_probe(self, example: Any):
        """(digest-keyed feature row or None) from the feature-cut
        cache — side-effect-free on a miss (``InferenceCache.get``), so
        miss accounting stays with the backbone's single-flight
        lookup."""
        cache = self._backbone.cache
        if cache is None:
            return None
        import jax

        probe = example
        if self._backbone._host_preprocess is not None:
            probe = self._backbone._host_preprocess(probe)
        probe = jax.tree_util.tree_map(np.asarray, probe)
        key = self._feature_ns + (content_digest(probe),)
        return cache.get(key)

    def submit(self, example: Any, tenant: str,
               timeout_ms: Optional[float] = None) -> Future:
        """Admit one (example, tenant) request; returns a Future of the
        tenant's head output row.

        A warm content digest short-circuits BEFORE the backbone server
        (``cache.feature_hit``): no backbone queue slot, no dispatch —
        the request pays the head pass only.  A cold digest rides the
        backbone's cached submit path (single-flight leaders, so N
        concurrent identical payloads cost ONE backbone dispatch), and
        the head pass runs when the features settle."""
        tenant = str(tenant)
        self.metrics.incr("headfanout.requests")
        feats_value = self._feature_probe(example)
        if feats_value is not None:
            self.metrics.incr("headfanout.feature_hits")
            flight_emit("cache.feature_hit", tenant=tenant)
            self._charge_feature_hit(tenant)
            out: Future = Future()
            try:
                row = self._bank.dispatch(
                    np.asarray(feats_value)[None], [tenant])[0]
            # graftlint: allow=SDL003 reason=the error is the future's result; the caller decides
            except BaseException as e:  # noqa: BLE001
                out.set_exception(e)
            else:
                out.set_result(row)
            return out
        feats_fut = self._backbone.submit(example, timeout_ms=timeout_ms,
                                          tenant=tenant)
        out = Future()

        def _features_done(f: Future) -> None:
            try:
                feats = f.result()
                row = self._bank.dispatch(
                    np.asarray(feats)[None], [tenant])[0]
            # graftlint: allow=SDL003 reason=relayed to the caller's future; raising in a done-callback would only hit the executor's swallow
            except BaseException as e:  # noqa: BLE001
                if not out.done():
                    out.set_exception(e)
            else:
                if not out.done():
                    out.set_result(row)

        feats_fut.add_done_callback(_features_done)
        return out

    def predict(self, example: Any, tenant: str,
                timeout_ms: Optional[float] = None):
        """Blocking single-request form of :meth:`submit`."""
        return self.submit(example, tenant, timeout_ms=timeout_ms).result()

    def predict_batch(self, examples: Sequence[Any],
                      tenants: Sequence[str],
                      timeout_ms: Optional[float] = None) -> List[Any]:
        """K tenants' rows, ONE head pass: resolve every row's features
        (warm digests from the cache, cold ones through the backbone —
        which batches/coalesces them), stack, and dispatch the whole
        mixed-tenant batch through the bank's single vmapped program."""
        tenants = [str(t) for t in tenants]
        if len(tenants) != len(examples):
            raise ValueError(f"{len(examples)} examples but "
                             f"{len(tenants)} tenants")
        self.metrics.incr("headfanout.requests", len(tenants))
        rows: List[Any] = [None] * len(tenants)
        pending: List[tuple] = []
        for i, ex in enumerate(examples):
            feats = self._feature_probe(ex)
            if feats is not None:
                self.metrics.incr("headfanout.feature_hits")
                flight_emit("cache.feature_hit", tenant=tenants[i])
                self._charge_feature_hit(tenants[i])
                rows[i] = np.asarray(feats)
            else:
                pending.append(
                    (i, self._backbone.submit(ex, timeout_ms=timeout_ms,
                                              tenant=tenants[i])))
        for i, fut in pending:
            rows[i] = np.asarray(fut.result())
        out = self._bank.dispatch(np.stack(rows), tenants)
        self.metrics.incr("headfanout.head_passes")
        return [out[i] for i in range(len(tenants))]

    # -- proof / observability surfaces -----------------------------------

    def executable_state(self) -> Dict[int, Dict[str, Any]]:
        """The BACKBONE's per-bucket compiled-program identity (the
        half the no-recompile proof pins; the head side is
        :meth:`head_state`)."""
        return self._backbone.executable_state()

    def head_state(self) -> Dict[str, Any]:
        """The head bank's jit identity + executable-cache size."""
        return self._bank.jit_info()

    def head_stats(self) -> Dict[str, Any]:
        """Stacked-bank HBM accounting (``param_sharding_stats``)."""
        return self._bank.stats()

    def warmup(self, example: Any) -> None:
        """Compile the backbone's bucket programs (no cache writes)."""
        self._backbone.warmup(example)

    def warm_head(self, features_row) -> None:
        """Compile the head program for the current bank capacity by
        dispatching one zeroed feature row — so latency measurements
        over a sleep-wrapped backbone never charge a head compile."""
        ts = self._bank.tenants()
        if not ts:
            return
        row = np.zeros_like(np.asarray(features_row))
        self._bank.dispatch(row[None], [ts[0]])

    def health(self) -> Dict[str, Any]:
        return self._backbone.health()

    def queue_depth(self) -> int:
        return self._backbone.queue_depth()

    def queue_pressure(self) -> float:
        return self._backbone.queue_pressure()

    def breaker_retry_after(self) -> Optional[float]:
        return self._backbone.breaker_retry_after()

    def wake(self) -> None:
        self._backbone.wake()

    @property
    def cache(self):
        return self._backbone.cache

    @property
    def bucket_sizes(self) -> List[int]:
        return self._backbone.bucket_sizes

    def stats(self) -> Dict[str, float]:
        summary = self.metrics.summary()
        return {k: v for k, v in summary.items()
                if k.startswith(("serving.", "engine_", "pipeline.",
                                 "headfanout.", "headbank."))}

    def _charge_feature_hit(self, tenant: str) -> None:
        """Near-zero ledger charge for a feature-cut short-circuit
        (same degrade-not-fail contract as ``Server._charge_hit``)."""
        if self._cost is None:
            return
        try:
            self._cost.record_hit(tenant=tenant, model=self.model_desc,
                                  kind="feature_hit")
        # graftlint: allow=SDL003 reason=cost.attr degrade contract: attribution failure is counted and logged, the hit already served
        except Exception as e:  # noqa: BLE001
            self.metrics.incr("serving.cost_attr_errors")
            self._cost.record_error()
            logger.warning("cost attribution (feature_hit) failed: "
                           "%s: %s", type(e).__name__, e)

    def varz(self) -> Dict[str, Any]:
        """The backbone's ``/varz`` body plus the fan-out tier's own
        section (bank mode/size/HBM, feature-hit counters, swap
        report).

        The ``cache`` section follows the SAME schema as
        ``Server.varz()`` — the fan-out tier's feature-cut hit and
        request counters are merged into ``cache["counters"]`` under
        ``cache.*`` keys, so one dashboard query shape covers both
        server types (ISSUE 18 satellite)."""
        doc = self._backbone.varz()
        snap = doc.get("metrics", {}).get("counters", {})
        doc["headfanout"] = {
            "tenants": len(self._bank),
            "bank": self._bank.stats(),
            "head_state": self._bank.jit_info(),
            "feature_namespace": list(self._feature_ns),
            "requests": snap.get("headfanout.requests", 0),
            "feature_hits": snap.get("headfanout.feature_hits", 0),
            "head_passes": snap.get("headfanout.head_passes", 0),
            "last_head_swap_report": self.last_head_swap_report,
        }
        if doc.get("cache") is not None:
            counters = doc["cache"].setdefault("counters", {})
            counters["cache.feature_hits"] = snap.get(
                "headfanout.feature_hits", 0)
            counters["cache.feature_requests"] = snap.get(
                "headfanout.requests", 0)
        if self._cost is not None:
            doc["cost"] = self._cost.snapshot()
        return doc

    def close(self, drain: bool = True,
              timeout_s: Optional[float] = 30.0) -> None:
        """Close the backbone server.  Feature entries are NOT
        reclaimed: the namespace is backbone identity, not this
        object's — a later server over the same backbone (same
        fingerprint + weights) legitimately serves them warm."""
        self._backbone.close(drain=drain, timeout_s=timeout_s)

    def __enter__(self) -> "HeadFanoutServer":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(drain=exc_type is None)
