"""sparkdl_tpu.serving — online inference over the TPU engine.

The L7 layer the offline stack was missing: where transformers and UDFs
score whole DataFrames, this package serves SINGLE requests under load —
an async dynamic-batching front-end (clipper-style adaptive batching)
over the same :class:`~sparkdl_tpu.parallel.engine.InferenceEngine`,
with deadlines, backpressure, fault isolation, graceful drain, and
latency/throughput metrics.

Public surface:

* :class:`Server` — ``Server(model_fn_or_named_model, ...)``; accepts a
  zoo model name, a ``ModelFunction``, or a raw ``fn(variables, batch)``.
* :func:`from_transformer` — lift a zoo/image/tensor transformer stage
  into a running server.
* ``register_serving_udf`` (``sparkdl_tpu.udf``) — expose a running
  server as a column UDF, so offline scoring shares the online queue.
* The error taxonomy: :class:`QueueFullError` (backpressure, carries
  ``retry_after_s``), :class:`DeadlineExceededError` (shed before
  dispatch), :class:`DispatchTimeoutError` (stalled model),
  :class:`ServiceUnavailableError` (shed at submit while the dispatch
  circuit breaker is open; carries ``retry_after_s``),
  :class:`ServerClosedError`.
* ``Server.health()`` — live/ready/degraded with last error, per-bucket
  circuit-breaker state, and a bounded transition history (also under
  ``varz()["health"]``); README "Failure model" documents the states.
* :class:`Fleet` (``sparkdl_tpu.serving.fleet``) — the multi-model,
  multi-tenant front door: named versioned registry entries,
  zero-downtime canary rollout with no-recompile hot-swap, per-tenant
  token-bucket quotas + priority classes (:class:`TenantQuota`,
  :class:`QuotaExceededError`), aggregated ``Fleet.varz()``/``health()``.
* :class:`InferenceCache` (``sparkdl_tpu.serving.cache``, ISSUE 11) —
  the content-addressed result cache + single-flight coalescing both
  front doors (and ``StreamScorer``) probe before any queue charge:
  bounded entries+bytes LRU keyed on ``utils.digest`` content digests,
  N concurrent identical requests -> one dispatch, hot-swap survival
  pinned against ``PROGRAMS.lock.json``, ``SPARKDL_CACHE`` env gate.
* :class:`HeadFanoutServer` (ISSUE 17) — the shared-backbone head
  fan-out tier: featurize each distinct input ONCE at the zoo's feature
  cut (cached under the backbone's lockfile fingerprint + weight
  digest), then serve per-tenant classifier heads from a stacked
  :class:`~sparkdl_tpu.parallel.engine.HeadBank` via one vmapped
  gather-by-tenant program; ``add_head``/``swap_head`` hot-swap can
  never recompile the backbone (witnessed per swap).
"""

from sparkdl_tpu.serving.adapters import from_transformer
from sparkdl_tpu.serving.batcher import DynamicBatcher, Request
from sparkdl_tpu.serving.cache import InferenceCache
from sparkdl_tpu.serving.errors import (DeadlineExceededError,
                                        DispatchTimeoutError, QueueFullError,
                                        QuotaExceededError, ServerClosedError,
                                        ServiceUnavailableError, ServingError)
from sparkdl_tpu.serving.server import (HeadFanoutServer, Server,
                                        bucket_plan)
# the fleet package imports serving.server/serving.errors, so it must
# come last here
from sparkdl_tpu.serving.fleet import (Fleet, ModelRegistry, ModelVersion,
                                       Rollout, TenantQuota)

__all__ = [
    "Server",
    "HeadFanoutServer",
    "bucket_plan",
    "InferenceCache",
    "from_transformer",
    "DynamicBatcher",
    "Request",
    "Fleet",
    "ModelRegistry",
    "ModelVersion",
    "Rollout",
    "TenantQuota",
    "ServingError",
    "QueueFullError",
    "QuotaExceededError",
    "DeadlineExceededError",
    "DispatchTimeoutError",
    "ServiceUnavailableError",
    "ServerClosedError",
]
