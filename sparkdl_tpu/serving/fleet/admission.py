"""Per-tenant admission control: token-bucket rate quotas, in-flight
caps, and priority classes.

Layered ON TOP of the existing serving backpressure, never instead of
it: the server's bounded queue still rejects with ``QueueFullError``
when genuinely full and sheds everyone with ``ServiceUnavailableError``
while a breaker is open — this controller decides, per TENANT, who is
turned away first as those pressure signals build (the clipper-style
admission tier).

Shed order under pressure (lowest priority first):

* **breaker open / device failing** — tenants below ``PRIORITY_HIGH``
  are shed at the fleet door with ``ServiceUnavailableError`` carrying
  the breaker's ``retry_after_s``; high-priority traffic still reaches
  the server (whose own gate decides — half-open trials have to come
  from somewhere).
* **queue pressure** — each priority class has a shed threshold as a
  fraction of the target server's queue (defaults: low 0.5, normal 0.8,
  high never): a 60%-full queue sheds low-priority tenants while normal
  and high still board.
* **rate quota** — a per-tenant token bucket (``rate_per_s`` refill,
  ``burst`` cap); an empty bucket raises :class:`QuotaExceededError`
  with the refill estimate.  ``rate_per_s=0`` is a ZERO-QUOTA tenant:
  never admitted (the deny-by-config form).
* **in-flight cap** — at most ``max_inflight`` unsettled requests per
  tenant; the fleet releases the slot when the request's future
  settles.

Determinism: the bucket runs on an injected monotonic ``clock``
(``time.monotonic`` by default) and holds no RNG, so a fixed submission
schedule admits/sheds identically run to run (the chaos test's
quota-tolerance assertion depends on this), and a virtual clock (the
traffic twin's) makes the refill schedule itself deterministic.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import time

from sparkdl_tpu.analysis.lockcheck import named_lock
from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.serving.errors import (QuotaExceededError,
                                        ServiceUnavailableError)

#: Priority classes, lowest shed first.
PRIORITY_LOW, PRIORITY_NORMAL, PRIORITY_HIGH = 0, 1, 2

#: Default shed thresholds: queue pressure (depth / max_queue) at which
#: a class is turned away.  > 1 means "never shed here" (the server's
#: own QueueFullError still applies at 1.0).
DEFAULT_SHED_PRESSURE: Dict[int, float] = {
    PRIORITY_LOW: 0.50,
    PRIORITY_NORMAL: 0.80,
    PRIORITY_HIGH: 1.01,
}


@dataclass(frozen=True)
class TenantQuota:
    """One tenant's admission contract.

    ``rate_per_s=None`` means unlimited rate; ``0.0`` means zero quota
    (never admitted).  ``burst`` defaults to ``max(1, rate_per_s)``
    rounded up — one second of quota.  ``max_inflight=None`` means no
    in-flight cap.
    """

    rate_per_s: Optional[float] = None
    burst: Optional[int] = None
    max_inflight: Optional[int] = None
    priority: int = PRIORITY_NORMAL

    def effective_burst(self) -> float:
        # zero-rate FIRST: rate_per_s=0.0 is the deny-by-config tenant
        # and stays denied even with a leftover explicit burst
        if not self.rate_per_s:  # unlimited (None) or zero quota (0.0)
            return 0.0
        if self.burst is not None:
            return max(0.0, float(self.burst))
        return max(1.0, float(int(self.rate_per_s + 0.999999)))

    def as_dict(self) -> Dict[str, Any]:
        return {"rate_per_s": self.rate_per_s, "burst": self.burst,
                "max_inflight": self.max_inflight,
                "priority": self.priority}


class AdmissionController:
    """Thread-safe tenant gate.  :meth:`admit` charges one token and one
    in-flight slot or raises; :meth:`release` frees the slot when the
    request settles (the fleet wires it to the future's done callback).
    """

    def __init__(self, quotas: Optional[Dict[str, TenantQuota]] = None,
                 default_quota: Optional[TenantQuota] = None,
                 shed_pressure: Optional[Dict[int, float]] = None,
                 retry_after_cap_s: float = 60.0,
                 clock: Optional[Callable[[], float]] = None):
        self._quotas: Dict[str, TenantQuota] = dict(quotas or {})
        self.default_quota = (default_quota if default_quota is not None
                              else TenantQuota())
        self.shed_pressure = dict(DEFAULT_SHED_PRESSURE)
        if shed_pressure:
            self.shed_pressure.update(shed_pressure)
        self.retry_after_cap_s = float(retry_after_cap_s)
        #: monotonic seconds source for bucket refills — injectable so a
        #: virtual-time harness can drive admission deterministically
        self._clock = clock if clock is not None else time.monotonic
        self._lock = named_lock("fleet.admission")
        #: tenant -> [tokens, last_refill_monotonic]
        self._buckets: Dict[str, list] = {}
        self._inflight: Dict[str, int] = {}
        self._admitted: Dict[str, int] = {}
        self._shed: Dict[str, int] = {}

    # -- configuration -----------------------------------------------------
    def set_quota(self, tenant: str, quota: TenantQuota) -> None:
        with self._lock:
            self._quotas[tenant] = quota
            self._buckets.pop(tenant, None)  # re-seed at the new burst

    def quota(self, tenant: str) -> TenantQuota:
        with self._lock:
            return self._quotas.get(tenant, self.default_quota)

    # -- the gate ----------------------------------------------------------
    def admit(self, tenant: str, pressure: float = 0.0,
              unavailable_retry_after: Optional[float] = None
              ) -> TenantQuota:
        """Gate one request for ``tenant`` against a target server whose
        queue pressure is ``pressure`` (and whose breaker, if OPEN,
        supplies ``unavailable_retry_after``).  Returns the tenant's
        quota on success; raises ``ServiceUnavailableError`` (priority
        shed) or :class:`QuotaExceededError` (rate / in-flight / zero
        quota).  Shed checks run BEFORE the token charge so a shed
        request costs no quota."""
        q = self.quota(tenant)
        if unavailable_retry_after is not None and q.priority < PRIORITY_HIGH:
            self._note_shed(tenant)
            flight_emit("fleet.shed", tenant=tenant, reason="breaker_open",
                        priority=q.priority,
                        retry_after_s=round(unavailable_retry_after, 4))
            raise ServiceUnavailableError(
                f"tenant {tenant!r} (priority {q.priority}) shed: model "
                f"circuit breaker open; retry in "
                f"{unavailable_retry_after:.2f}s",
                retry_after_s=unavailable_retry_after)
        threshold = self.shed_pressure.get(q.priority, 1.01)
        if pressure >= threshold:
            self._note_shed(tenant)
            flight_emit("fleet.shed", tenant=tenant, reason="pressure",
                        priority=q.priority, pressure=round(pressure, 4))
            raise ServiceUnavailableError(
                f"tenant {tenant!r} (priority {q.priority}) shed under "
                f"queue pressure {pressure:.2f} (threshold "
                f"{threshold:.2f}); higher-priority traffic boards first",
                retry_after_s=0.05)
        shed_exc: Optional[BaseException] = None
        reason = None
        with self._lock:
            # cap check BEFORE the token charge: a capped-out rejection
            # must not also burn rate quota ("a shed request costs no
            # quota" — retrying clients at their cap would otherwise
            # starve their own rate).  Shed exceptions are built here
            # but RAISED after the lock is released, so the fleet.shed
            # flight event never fires under the admission lock.
            cap = q.max_inflight
            cur = self._inflight.get(tenant, 0)
            if cap is not None and cur >= int(cap):
                self._shed[tenant] = self._shed.get(tenant, 0) + 1
                reason = "inflight_cap"
                shed_exc = QuotaExceededError(
                    f"tenant {tenant!r} at its in-flight cap ({cur}/"
                    f"{int(cap)}); retry when a request settles",
                    retry_after_s=0.05, tenant=tenant)
            elif q.rate_per_s is not None:
                rate = float(q.rate_per_s)
                burst = q.effective_burst()
                now = self._clock()
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = self._buckets[tenant] = [burst, now]
                tokens = min(burst, bucket[0] + (now - bucket[1]) * rate)
                bucket[1] = now
                if tokens < 1.0:
                    bucket[0] = tokens
                    self._shed[tenant] = self._shed.get(tenant, 0) + 1
                    if rate > 0:
                        hint = min(self.retry_after_cap_s,
                                   (1.0 - tokens) / rate)
                        msg = (f"tenant {tenant!r} rate quota exhausted "
                               f"({rate:g}/s, burst "
                               f"{burst:g}); retry in {hint:.3f}s")
                        reason = "rate_quota"
                    else:
                        hint = self.retry_after_cap_s
                        msg = f"tenant {tenant!r} has zero quota"
                        reason = "zero_quota"
                    shed_exc = QuotaExceededError(msg, retry_after_s=hint,
                                                  tenant=tenant)
                else:
                    bucket[0] = tokens - 1.0
            if shed_exc is None:
                self._inflight[tenant] = cur + 1
                self._admitted[tenant] = self._admitted.get(tenant, 0) + 1
        if shed_exc is not None:
            flight_emit("fleet.shed", tenant=tenant, reason=reason,
                        priority=q.priority)
            raise shed_exc
        return q

    def release(self, tenant: str) -> None:
        """Free one in-flight slot (future settled / submit failed)."""
        with self._lock:
            cur = self._inflight.get(tenant, 0)
            self._inflight[tenant] = max(0, cur - 1)

    def refund(self, tenant: str) -> None:
        """Undo one :meth:`admit` whose request never reached a server
        (the fleet's swap-window re-route): free the slot, return the
        rate token, and back out the admitted count — the retry will
        charge afresh, so one request never costs a tenant two tokens."""
        q = self.quota(tenant)
        with self._lock:
            cur = self._inflight.get(tenant, 0)
            self._inflight[tenant] = max(0, cur - 1)
            if q.rate_per_s is not None:
                bucket = self._buckets.get(tenant)
                if bucket is not None:
                    bucket[0] = min(q.effective_burst(), bucket[0] + 1.0)
            self._admitted[tenant] = max(
                0, self._admitted.get(tenant, 0) - 1)

    def _note_shed(self, tenant: str) -> None:
        with self._lock:
            self._shed[tenant] = self._shed.get(tenant, 0) + 1

    # -- introspection -----------------------------------------------------
    def inflight(self, tenant: str) -> int:
        with self._lock:
            return self._inflight.get(tenant, 0)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable admission state (``Fleet.varz`` embeds it)."""
        with self._lock:
            tenants = sorted(set(self._quotas) | set(self._inflight)
                             | set(self._admitted) | set(self._shed))
            out: Dict[str, Any] = {
                "default_quota": self.default_quota.as_dict(),
                "shed_pressure": {str(k): v
                                  for k, v in self.shed_pressure.items()},
                "tenants": {},
            }
            for t in tenants:
                q = self._quotas.get(t, self.default_quota)
                bucket = self._buckets.get(t)
                out["tenants"][t] = {
                    "quota": q.as_dict(),
                    "inflight": self._inflight.get(t, 0),
                    "admitted": self._admitted.get(t, 0),
                    "shed": self._shed.get(t, 0),
                    "tokens": (round(bucket[0], 3) if bucket is not None
                               else None),
                }
        return out
