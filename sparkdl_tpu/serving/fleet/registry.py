"""Named, versioned model registry — the fleet's catalog.

An ENTRY is a named model slot (``"image-featurizer"``): the model form
is resolved exactly once when the entry is created, through the same
``serving.server._resolve_model`` path every :class:`~sparkdl_tpu.
serving.server.Server` uses — a zoo model NAME routes through
``transformers.named_image.zoo_serving_bundle`` (→ ``zoo_model_fn``, so
served == transformed == audited stays true by construction), a
``ModelFunction`` or raw callable is taken as-is.  The resolved ``fn``
object is pinned on the entry and shared by every version.

A VERSION is that fn plus one concrete weight pytree, numbered
monotonically per entry (v1, v2, ...).  Because every version reuses the
entry's ONE fn object, the engine layer's module-level jit cache (keyed
on ``id(fn)``) hands v2's engines the very jit program v1 compiled:
identical shapes/dtypes mean identical executable cache keys, so a
hot-swap performs no recompilation — the property
``serving.fleet.rollout`` asserts at promote time and
``analysis.program``'s fleet enumeration hook pins against
``PROGRAMS.lock.json``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from sparkdl_tpu.analysis.lockcheck import named_lock
from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class ModelVersion:
    """One immutable (entry, version number, weights) triple."""

    __slots__ = ("name", "version", "variables", "label")

    def __init__(self, name: str, version: int, variables: Any,
                 label: Optional[str] = None):
        self.name = name
        self.version = int(version)
        self.variables = variables
        self.label = label

    def as_dict(self) -> Dict[str, Any]:
        """JSON-serializable summary (no weights)."""
        return {"name": self.name, "version": self.version,
                "label": self.label}

    def __repr__(self) -> str:  # pragma: no cover - debugging sugar
        return f"ModelVersion({self.name!r}, v{self.version})"


class HeadVersion:
    """One per-tenant HEAD version (head fan-out tier, ISSUE 17): the
    catalog record of a head add/swap.  Weights themselves live on the
    serving :class:`~sparkdl_tpu.parallel.engine.HeadBank` — the catalog
    keeps the content digest, so "which bytes is tenant t serving?" is
    answerable without holding a second copy of every head."""

    __slots__ = ("name", "tenant", "version", "weights_digest", "label")

    def __init__(self, name: str, tenant: str, version: int,
                 weights_digest: Optional[str],
                 label: Optional[str] = None):
        self.name = name
        self.tenant = tenant
        self.version = int(version)
        self.weights_digest = weights_digest
        self.label = label

    def as_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "tenant": self.tenant,
                "version": self.version,
                "weights_digest": self.weights_digest,
                "label": self.label}

    def __repr__(self) -> str:  # pragma: no cover - debugging sugar
        return (f"HeadVersion({self.name!r}, {self.tenant!r}, "
                f"v{self.version})")


class FleetEntry:
    """A named model slot: the ONE resolved fn + its versions (and, for
    head fan-out entries, per-tenant head versions — the backbone fn and
    its weights never version through those)."""

    __slots__ = ("name", "featurize", "fn", "default_variables",
                 "engine_overrides", "model_desc", "versions",
                 "_next_version", "heads")

    def __init__(self, name: str, fn, default_variables: Any,
                 engine_overrides: Dict[str, Any], featurize: bool,
                 model_desc: str):
        self.name = name
        self.featurize = bool(featurize)
        self.fn = fn
        self.default_variables = default_variables
        self.engine_overrides = dict(engine_overrides)
        self.model_desc = model_desc
        self.versions: Dict[int, ModelVersion] = {}
        self._next_version = 1
        #: tenant -> ordered head versions (head fan-out entries only)
        self.heads: Dict[str, List[HeadVersion]] = {}

    def as_dict(self) -> Dict[str, Any]:
        out = {
            "name": self.name,
            "model": self.model_desc,
            "featurize": self.featurize,
            "versions": sorted(self.versions),
        }
        if self.heads:
            out["heads"] = {t: hv[-1].version
                            for t, hv in sorted(self.heads.items()) if hv}
        return out


class ModelRegistry:
    """Thread-safe name → :class:`FleetEntry` catalog with monotonically
    numbered versions.

    ::

        reg = ModelRegistry()
        v1 = reg.register("clf", fn, variables_v1)    # entry + v1
        v2 = reg.register("clf", variables=variables_v2)  # same fn, v2

    Re-registering an existing entry with a NEW model form is refused:
    versions are weights-only by design — a different fn would silently
    fork the compiled-program identity and defeat the no-recompile
    hot-swap guarantee.
    """

    def __init__(self):
        self._entries: Dict[str, FleetEntry] = {}
        self._lock = named_lock("fleet.registry")

    def register(self, name: str, model: Any = None, variables: Any = None,
                 *, featurize: bool = False,
                 label: Optional[str] = None) -> ModelVersion:
        """Create entry ``name`` (first call: ``model`` required) and/or
        append its next :class:`ModelVersion` holding ``variables``
        (default: the entry's resolved weights — e.g. the zoo weights
        for a named zoo entry)."""
        if not name or not isinstance(name, str):
            raise ValueError(f"model name must be a non-empty string, "
                             f"got {name!r}")
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            if model is None:
                raise ValueError(
                    f"unknown model entry {name!r}: the first register() "
                    f"must pass the model (zoo name, ModelFunction, or "
                    f"fn(variables, batch))")
            from sparkdl_tpu.graph.function import ModelFunction
            from sparkdl_tpu.serving.server import _resolve_model

            # plain callables take their weights here; zoo names and
            # ModelFunctions carry their own (and _resolve_model refuses
            # explicit variables for them)
            resolve_vars = (variables if callable(model)
                            and not isinstance(model, ModelFunction)
                            else None)
            fn, default_vars, overrides = _resolve_model(
                model, resolve_vars, featurize)
            desc = (model if isinstance(model, str)
                    else type(model).__name__)
            entry = FleetEntry(name, fn, default_vars, overrides,
                               featurize, desc)
            with self._lock:
                if name in self._entries:  # lost a racing register
                    existing = self._entries[name]
                    if existing.fn is not entry.fn:
                        # adopting the winner would catalog OUR weights
                        # under THEIR fn — refuse, like re-register
                        raise ValueError(
                            f"entry {name!r} was concurrently registered "
                            f"with a different model fn; versions carry "
                            f"new WEIGHTS only")
                    entry = existing
                else:
                    self._entries[name] = entry
        elif model is not None:
            raise ValueError(
                f"entry {name!r} already exists; versions carry new "
                f"WEIGHTS only (pass variables=...) — a new model fn "
                f"would fork the compiled program and break the "
                f"no-recompile hot-swap contract")
        with self._lock:
            v = entry._next_version
            entry._next_version = v + 1
            mv = ModelVersion(
                name, v,
                entry.default_variables if variables is None else variables,
                label=label)
            entry.versions[v] = mv
        logger.info("registered %s v%d%s", name, v,
                    f" ({label})" if label else "")
        return mv

    def register_head(self, name: str, tenant: str, weights: Any = None,
                      *, label: Optional[str] = None) -> HeadVersion:
        """Append tenant ``tenant``'s next HEAD version under entry
        ``name`` (head fan-out tier).  Head versions are numbered
        monotonically PER TENANT and carry only the weight digest — the
        catalog half of ``Fleet.add_head``/``swap_head``.  The entry's
        backbone fn and ModelVersion chain are untouched by design:
        that is what makes head churn provably backbone-neutral."""
        entry = self.entry(name)
        tenant = str(tenant)
        digest = None
        if weights is not None:
            from sparkdl_tpu.utils.digest import content_digest

            digest = content_digest(weights)
        with self._lock:
            chain = entry.heads.setdefault(tenant, [])
            hv = HeadVersion(name, tenant, len(chain) + 1, digest,
                             label=label)
            chain.append(hv)
        logger.info("registered %s head %s v%d%s", name, tenant,
                    hv.version, f" ({label})" if label else "")
        return hv

    def head_versions(self, name: str, tenant: str) -> List[int]:
        """The registered head-version numbers for ``tenant`` (empty
        for a tenant with no head history)."""
        entry = self.entry(name)
        with self._lock:
            return [hv.version for hv in entry.heads.get(str(tenant), [])]

    def discard(self, name: str, version: int) -> None:
        """Back out a version that never deployed (the fleet's
        failed-deploy cleanup path); the entry goes with its last
        version, so the name is reusable after a failed first deploy."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                return
            entry.versions.pop(int(version), None)
            if not entry.versions:
                del self._entries[name]

    # -- lookup ------------------------------------------------------------
    def entry(self, name: str) -> FleetEntry:
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise KeyError(f"unknown model entry {name!r}; registered: "
                           f"{sorted(self._entries) or 'none'}")
        return entry

    def get(self, name: str, version: Optional[int] = None) -> ModelVersion:
        """Version ``version`` of entry ``name`` (default: latest)."""
        entry = self.entry(name)
        with self._lock:
            if version is None:
                version = max(entry.versions)
            mv = entry.versions.get(int(version))
        if mv is None:
            raise KeyError(f"{name!r} has no version {version}; known: "
                           f"{sorted(entry.versions)}")
        return mv

    def versions(self, name: str) -> List[int]:
        entry = self.entry(name)
        with self._lock:
            return sorted(entry.versions)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def as_dict(self) -> Dict[str, Any]:
        """JSON-serializable catalog summary (``Fleet.varz`` embeds it)."""
        with self._lock:
            entries = list(self._entries.values())
        return {e.name: e.as_dict() for e in entries}
