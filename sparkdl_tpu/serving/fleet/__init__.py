"""sparkdl_tpu.serving.fleet — multi-tenant, versioned model-fleet
serving with zero-downtime hot-swap.

The production front door ROADMAP item 2 asked for, assembled from the
PR 1–6 machinery: a :class:`~.registry.ModelRegistry` of named entries
with monotonically numbered :class:`~.registry.ModelVersion` s (each
resolving through ``named_image.zoo_model_fn`` so served == transformed
== audited), per-version :class:`~sparkdl_tpu.serving.server.Server` s
sharing compiled programs via the engine jit cache,
:class:`~.rollout.Rollout` canary → promote/rollback transitions that
never fail an in-flight request and never re-jit when shapes/dtypes are
unchanged, and an :class:`~.admission.AdmissionController` of per-tenant
token-bucket quotas, in-flight caps, and shed-lowest-priority-first
classes layered on the existing backpressure errors.

Fault sites: ``fleet.admit``, ``fleet.canary``, ``fleet.swap``
(``faults/sites.py``); spans: ``fleet.request`` tagged with model /
version / tenant; metrics: ``fleet.*`` counters plus per-model and
per-tenant ledgers in :meth:`~.fleet.Fleet.varz`.
"""

from sparkdl_tpu.serving.errors import QuotaExceededError
from sparkdl_tpu.serving.fleet.admission import (DEFAULT_SHED_PRESSURE,
                                                 PRIORITY_HIGH, PRIORITY_LOW,
                                                 PRIORITY_NORMAL,
                                                 AdmissionController,
                                                 TenantQuota)
from sparkdl_tpu.serving.fleet.fleet import Fleet
from sparkdl_tpu.serving.fleet.registry import (FleetEntry, ModelRegistry,
                                                ModelVersion)
from sparkdl_tpu.serving.fleet.rollout import Rollout

__all__ = [
    "Fleet",
    "ModelRegistry",
    "ModelVersion",
    "FleetEntry",
    "Rollout",
    "AdmissionController",
    "TenantQuota",
    "QuotaExceededError",
    "PRIORITY_LOW",
    "PRIORITY_NORMAL",
    "PRIORITY_HIGH",
    "DEFAULT_SHED_PRESSURE",
]
