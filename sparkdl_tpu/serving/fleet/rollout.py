"""Zero-downtime version rollout: canary → promote | rollback.

The TensorFlow-Serving shape (Olston et al. 2017) on this stack's
primitives: the NEW version's :class:`~sparkdl_tpu.serving.server.
Server` is built ALONGSIDE the stable one (both alive, both admitting),
a deterministic counter routes a configurable traffic fraction to the
canary, and the swap itself is a phase flip — after ``promote()`` every
new request routes to the canary server while the old server drains
gracefully (``close(drain=True)``), so a request ALWAYS completes on the
version that admitted it and no in-flight request is ever failed by a
swap.  ``rollback()`` is the mirror image: the canary drains, the stable
server never noticed.

No-recompile contract: both servers were built over the SAME entry fn
(``registry.FleetEntry`` resolves once), so the engine layer's jit cache
hands the canary the very compiled program the stable version runs.
:meth:`Rollout.report` proves it per bucket — the shared ``jax.jit``
object identity plus an executable-cache size that did NOT grow between
rollout start and promote (``Server.executable_state``); the program
fingerprints themselves are pinned against ``PROGRAMS.lock.json`` by
``analysis.program``'s fleet enumeration hook.

Fault sites: ``fleet.canary`` fires at each canary routing decision;
``fleet.swap`` fires at the promote/rollback attempt — an injected
swap-time fault aborts the phase flip with state UNCHANGED (both
servers keep serving; the operator retries), which is exactly what the
headline chaos test drives.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Tuple

from sparkdl_tpu.analysis.lockcheck import named_lock
from sparkdl_tpu.faults import inject
from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

PHASE_CANARY = "canary"
PHASE_PROMOTED = "promoted"
PHASE_ROLLED_BACK = "rolled_back"


class Rollout:
    """One in-progress version transition for one fleet entry.

    Built by :meth:`Fleet.start_rollout`; routing goes through
    :meth:`route` (deterministic fraction: request ``n`` rides the
    canary iff ``floor(n*f)`` advanced, so fraction 0.25 sends exactly
    every 4th request, 0.0 none, 1.0 all).  The phase flip methods only
    mutate THIS object's phase — the owning fleet swaps its own state
    and drains the losing server after the flip succeeds, so a fault
    injected at ``fleet.swap`` leaves the world exactly as it was.
    """

    def __init__(self, name: str, stable_version: int, stable_server,
                 canary_version: int, canary_server, fraction: float,
                 exec_before: Dict[int, Dict[str, Any]]):
        if not 0.0 <= float(fraction) <= 1.0:
            raise ValueError(f"canary fraction must be in [0, 1], got "
                             f"{fraction}")
        self.name = name
        self.stable_version = int(stable_version)
        self.stable_server = stable_server
        self.canary_version = int(canary_version)
        self.canary_server = canary_server
        self._fraction = float(fraction)
        self._exec_before = dict(exec_before)
        self._lock = named_lock("fleet.rollout")
        self._phase = PHASE_CANARY
        self._n = 0
        self._canary_n = 0

    # -- state -------------------------------------------------------------
    @property
    def phase(self) -> str:
        with self._lock:
            return self._phase

    @property
    def active(self) -> bool:
        return self.phase == PHASE_CANARY

    @property
    def fraction(self) -> float:
        with self._lock:
            return self._fraction

    def set_fraction(self, fraction: float) -> None:
        """Shift canary traffic mid-rollout (0.0 pauses it, 1.0 is a
        full dark-launch before the promote)."""
        if not 0.0 <= float(fraction) <= 1.0:
            raise ValueError(f"canary fraction must be in [0, 1], got "
                             f"{fraction}")
        with self._lock:
            self._fraction = float(fraction)

    # -- routing -----------------------------------------------------------
    def route(self) -> Tuple[int, Any, bool]:
        """(version, server, is_canary) for the next request.  After a
        phase flip, stale callers holding this object keep routing
        CORRECTLY: promoted → canary server, rolled back → stable."""
        with self._lock:
            phase = self._phase
            f = self._fraction
        if phase == PHASE_PROMOTED:
            return self.canary_version, self.canary_server, False
        if phase == PHASE_ROLLED_BACK:
            return self.stable_version, self.stable_server, False
        inject("fleet.canary")
        with self._lock:
            self._n += 1
            take = math.floor(self._n * f) > math.floor((self._n - 1) * f)
            if take:
                self._canary_n += 1
        if take:
            return self.canary_version, self.canary_server, True
        return self.stable_version, self.stable_server, False

    # -- phase flips -------------------------------------------------------
    def promote(self) -> Dict[str, Any]:
        """Make the canary the stable version.  The ``fleet.swap`` fault
        site fires BEFORE any state changes; on injected failure both
        versions keep serving and promote() can simply be retried.
        Returns :meth:`report`."""
        inject("fleet.swap")
        with self._lock:
            if self._phase != PHASE_CANARY:
                raise RuntimeError(
                    f"cannot promote {self.name!r}: rollout already "
                    f"{self._phase}")
            self._phase = PHASE_PROMOTED
        logger.info("%s: promoted v%d over v%d", self.name,
                    self.canary_version, self.stable_version)
        return self.report()

    def rollback(self) -> Dict[str, Any]:
        """Abandon the canary; the stable version keeps serving.  Same
        ``fleet.swap`` fault-site semantics as :meth:`promote`."""
        inject("fleet.swap")
        with self._lock:
            if self._phase != PHASE_CANARY:
                raise RuntimeError(
                    f"cannot roll back {self.name!r}: rollout already "
                    f"{self._phase}")
            self._phase = PHASE_ROLLED_BACK
        logger.info("%s: rolled back v%d, staying on v%d", self.name,
                    self.canary_version, self.stable_version)
        return self.report()

    # -- introspection -----------------------------------------------------
    def report(self) -> Dict[str, Any]:
        """JSON-serializable swap report, including the no-recompile
        proof: for every bucket both versions have touched, the canary's
        engine must hold the SAME ``jax.jit`` object the stable engine
        compiled (``shared_jit``), and that object's executable cache —
        one GLOBAL counter for the whole shared jit, every bucket
        reports the same number — may have grown only by buckets
        compiled for the FIRST time during the rollout.  Any growth
        beyond that is a same-shape re-jit, which the swap must never
        cause: identical shapes/dtypes reuse the compiled program."""
        now = self.canary_server.executable_state()
        buckets: Dict[int, Dict[str, Any]] = {}
        compared = False
        reused = True
        for b in sorted(set(self._exec_before) | set(now)):
            before = self._exec_before.get(b)
            cur = now.get(b)
            shared = (before is not None and cur is not None
                      and before["jit_id"] == cur["jit_id"])
            buckets[b] = {
                "shared_jit": shared,
                "executables_before": (before or {}).get("executables"),
                "executables_now": (cur or {}).get("executables"),
            }
            if before is not None and cur is not None:
                compared = True
                reused = reused and shared
        def _cache_size(state: Dict[int, Dict[str, Any]]):
            known = [v["executables"] for v in state.values()
                     if v.get("executables") is not None]
            return max(known) if known else None
        size_before = _cache_size(self._exec_before)
        size_now = _cache_size(now)
        new_buckets = len(set(now) - set(self._exec_before))
        if (size_before is not None and size_now is not None
                and size_now > size_before + new_buckets):
            reused = False
        with self._lock:
            status = {
                "name": self.name,
                "phase": self._phase,
                "stable_version": self.stable_version,
                "canary_version": self.canary_version,
                "fraction": self._fraction,
                "requests": self._n,
                "canary_requests": self._canary_n,
            }
        status["buckets"] = buckets
        status["no_recompile"] = bool(compared and reused)
        return status

    def status(self) -> Dict[str, Any]:
        """The light form ``Fleet.varz`` embeds per model."""
        with self._lock:
            return {
                "canary_version": self.canary_version,
                "stable_version": self.stable_version,
                "fraction": self._fraction,
                "phase": self._phase,
                "requests": self._n,
                "canary_requests": self._canary_n,
            }


def head_swap_report(name: str, tenant: str, op: str,
                     exec_before: Dict[int, Dict[str, Any]],
                     exec_now: Dict[int, Dict[str, Any]],
                     bank_before: Dict[str, Any],
                     bank_now: Dict[str, Any],
                     fingerprint_before: Any,
                     fingerprint_now: Any) -> Dict[str, Any]:
    """The head hot-swap analog of :meth:`Rollout.report` — THE proof
    that a per-tenant head mutation can never recompile the backbone.

    Three independent witnesses, all chip-free:

    * per backbone bucket, the jit object after the swap is the SAME
      object as before (``shared_jit`` — a head churn that re-jitted
      the backbone would mint a new one), and the shared executable
      cache did not grow (a same-shape backbone re-trace would);
    * the head bank's fan-out jit object is likewise the same (a head
      add may legitimately grow ITS executable cache — that is the
      HEAD program re-lowering at a doubled capacity, reported but not
      counted against the backbone);
    * the backbone's committed StableHLO identity
      (``serving.cache.lockfile_model_fingerprint``) is byte-equal
      before and after, pinning "same computation" against
      ``PROGRAMS.lock.json`` exactly like cache swap-survival does.

    ``no_backbone_recompile`` is the conjunction — the bit the tests
    and the fleet's swap reports assert."""
    buckets: Dict[int, Dict[str, Any]] = {}
    compared = False
    reused = True
    for b in sorted(set(exec_before) | set(exec_now)):
        before = exec_before.get(b)
        cur = exec_now.get(b)
        shared = (before is not None and cur is not None
                  and before["jit_id"] == cur["jit_id"])
        buckets[b] = {
            "shared_jit": shared,
            "executables_before": (before or {}).get("executables"),
            "executables_now": (cur or {}).get("executables"),
        }
        if before is not None and cur is not None:
            compared = True
            reused = reused and shared
            eb = before.get("executables")
            en = cur.get("executables")
            if eb is not None and en is not None and en > eb:
                reused = False  # backbone executable growth = recompile
    fp_pinned = (fingerprint_before is not None
                 and fingerprint_before == fingerprint_now)
    return {
        "name": name,
        "tenant": tenant,
        "op": op,
        "buckets": buckets,
        "head_jit_shared": bank_before.get("jit_id") == bank_now.get(
            "jit_id"),
        "head_executables_before": bank_before.get("executables"),
        "head_executables_now": bank_now.get("executables"),
        "bank_mode": bank_now.get("mode"),
        "fingerprint_before": fingerprint_before,
        "fingerprint_now": fingerprint_now,
        "fingerprint_pinned": fp_pinned,
        "no_backbone_recompile": bool(
            compared and reused
            and bank_before.get("jit_id") == bank_now.get("jit_id")
            and (fingerprint_before is None or fp_pinned)),
    }
