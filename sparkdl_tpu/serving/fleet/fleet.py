"""The fleet front door: many named, versioned models behind one API.

Where :class:`~sparkdl_tpu.serving.server.Server` fronts exactly ONE
model for one anonymous caller, a :class:`Fleet` multiplexes many
registry entries over shared TPU capacity with per-tenant admission
(:mod:`.admission`), zero-downtime version rollouts (:mod:`.rollout`),
and aggregated health/metrics:

::

    with Fleet(max_batch_size=32, max_wait_ms=3) as fleet:
        fleet.add_model("feats", "InceptionV3", featurize=True)
        fleet.add_model("clf", my_fn, variables_v1)
        y = fleet.predict("clf", row, tenant="team-a")

        fleet.add_version("clf", variables_v2)       # register v2
        ro = fleet.start_rollout("clf", canary_fraction=0.1)
        ...                                          # watch varz()
        fleet.promote("clf")                         # or rollback("clf")

Request path: route (stable vs canary, deterministic fraction) →
admission gate (tenant token bucket / in-flight cap / priority shed
against the TARGET server's queue pressure and breaker) → the version's
own ``Server`` (dynamic batching, buckets, deadlines, watchdog,
breaker).  The returned future carries ``fleet_model`` /
``fleet_version`` / ``fleet_tenant`` / ``fleet_canary`` attributes so
callers (and the chaos test) can hold results to the right oracle.
Request spans (``fleet.request``) tag model, version, and tenant, and
the per-version server spans nest under them.
"""

from __future__ import annotations

import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional

from sparkdl_tpu.analysis.lockcheck import named_lock
from sparkdl_tpu.faults import inject
from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.obs.trace import get_tracer
from sparkdl_tpu.serving.errors import ServerClosedError
from sparkdl_tpu.serving.fleet.admission import (AdmissionController,
                                                 TenantQuota)
from sparkdl_tpu.serving.fleet.registry import (HeadVersion, ModelRegistry,
                                                ModelVersion)
from sparkdl_tpu.serving.fleet.rollout import Rollout
from sparkdl_tpu.serving.server import HeadFanoutServer, Server
from sparkdl_tpu.utils.health import HealthTracker
from sparkdl_tpu.utils.logging import get_logger
from sparkdl_tpu.utils.metrics import Metrics

logger = get_logger(__name__)


class _ModelState:
    """One deployed entry: its live server, version, and rollout."""

    __slots__ = ("entry", "version", "server", "rollout",
                 "last_swap_report", "server_kwargs")

    def __init__(self, entry, version: int, server: Server,
                 server_kwargs: Dict[str, Any]):
        self.entry = entry
        self.version = version
        self.server = server
        self.rollout: Optional[Rollout] = None
        self.last_swap_report: Optional[Dict[str, Any]] = None
        self.server_kwargs = dict(server_kwargs)


class Fleet:
    """Multi-tenant, versioned model-fleet serving with zero-downtime
    hot-swap.  Constructor kwargs beyond the admission knobs are the
    DEFAULT per-version :class:`Server` configuration
    (``max_batch_size``, ``max_wait_ms``, ``max_queue``, buckets,
    breaker knobs, ...); ``add_model`` kwargs override them per entry.
    """

    def __init__(self, *,
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 default_quota: Optional[TenantQuota] = None,
                 shed_pressure: Optional[Dict[int, float]] = None,
                 slos: Optional[List[Any]] = None,
                 cache: Any = None,
                 cost: Any = None,
                 program_fingerprints: Any = None,
                 metrics: Optional[Metrics] = None,
                 clock: Optional[Callable[[], float]] = None,
                 **server_defaults):
        self.metrics = metrics if metrics is not None else Metrics()
        # Injected monotonic clock (ISSUE 16): one source drives the
        # admission buckets, the fleet SLO engine, latency accounting
        # AND (via server_defaults) every server this fleet builds — so
        # a virtual-time harness steps the entire serving stack on one
        # deterministic timeline.
        self._clock = clock if clock is not None else time.monotonic
        self.registry = ModelRegistry()
        # ONE result cache for the whole fleet (ISSUE 11), with
        # per-version key namespaces ``(model, version, fingerprint)``
        # so two versions can never serve each other's rows.  ``cache=
        # None`` resolves the SPARKDL_CACHE process default; an
        # explicit InferenceCache shares across fleets; ``cache=False``
        # forces uncached.  ``program_fingerprints`` overrides how a
        # version's StableHLO identity is resolved for the hot-swap
        # survival rule (a ``{name: fp}`` dict or ``fn(name, entry)``);
        # the default pins against the committed PROGRAMS.lock.json
        # (``serving.cache.lockfile_model_fingerprint`` over the
        # entry's zoo model), and entries with no audited programs get
        # None — no proof, so their swaps conservatively invalidate.
        from sparkdl_tpu.serving.cache import (resolve_cache,
                                               unique_namespace)

        self._cache = resolve_cache(cache)[0]
        # per-fleet namespace prefix: two fleets sharing the process
        # cache may deploy the same (name, version) with DIFFERENT
        # weights — their entries must never collide — and the prefix
        # makes close()'s whole-fleet reclaim safe (nobody else can
        # reach keys under it)
        self._cache_prefix = (unique_namespace("fleet")
                              if self._cache is not None else ("fleet",))
        self._program_fingerprints = program_fingerprints
        #: (name, version) -> (program_fingerprint, weights_digest) for
        #: deployed versions — the promote-time survival comparison
        self._version_meta: Dict[Any, Any] = {}
        self.admission = AdmissionController(
            quotas=quotas, default_quota=default_quota,
            shed_pressure=shed_pressure, clock=self._clock)
        # Fleet-level health (ISSUE 9): the per-model servers keep their
        # own trackers; this one carries fleet-wide objectives — an SLO
        # burn-rate breach over the fleet.* series degrades it, and its
        # snapshot is the last_error/transitions half of the unified
        # health() payload.
        self._health = HealthTracker("fleet.health")
        # ONE cost ledger for the whole fleet (ISSUE 18): every server
        # this fleet builds charges the same instance, so showback and
        # the regression sentinel see the fleet-wide picture.  Bound to
        # the FLEET tracker (first-binder-wins), so an open cost
        # regression degrades fleet health() like an SLO breach.
        from sparkdl_tpu.obs.cost import resolve_cost

        self._cost = resolve_cost(cost)
        if self._cost is not None:
            self._cost.bind_health(self._health)
        self._slo_engine = None
        if slos:
            from sparkdl_tpu.obs.slo import SLOEngine

            self._slo_engine = SLOEngine(self.metrics, slos,
                                         health=self._health,
                                         clock=self._clock)
        self._server_defaults = dict(server_defaults)
        if clock is not None:
            # explicit per-entry server_kwargs may still override
            self._server_defaults.setdefault("clock", clock)
        self._lock = named_lock("fleet.state")
        self._models: Dict[str, _ModelState] = {}
        self._closed = False
        #: per-model / per-tenant request ledgers (varz sections); plain
        #: dicts mutated only under self._lock
        self._per_model: Dict[str, Dict[str, int]] = {}
        self._per_tenant: Dict[str, Dict[str, int]] = {}

    # -- deployment --------------------------------------------------------
    def add_model(self, name: str, model: Any, variables: Any = None, *,
                  featurize: bool = False, label: Optional[str] = None,
                  warm_example: Any = None,
                  **server_kwargs) -> ModelVersion:
        """Register entry ``name`` (v1) and deploy it immediately.
        ``server_kwargs`` become this entry's Server configuration (on
        top of the fleet defaults) for v1 and every later version —
        including the tensor-parallel weight-sharding knob (ISSUE 14):
        ``partition_rules=``/``param_shardings=`` shard the entry's
        weights across the serving mesh's ``model`` axis on every
        version's server (zoo entries default to
        ``mesh.default_partition_rules`` via their serving bundle)."""
        with self._lock:
            if self._closed:
                raise ServerClosedError("fleet is closed")
            if name in self._models:
                raise ValueError(
                    f"model {name!r} already deployed; use add_version() "
                    f"+ start_rollout() to ship new weights")
        mv = self.registry.register(name, model, variables,
                                    featurize=featurize, label=label)
        entry = self.registry.entry(name)
        server = None
        try:
            server = self._build_server(entry, mv, server_kwargs)
            if warm_example is not None:
                server.warmup(warm_example)
            state = _ModelState(entry, mv.version, server, server_kwargs)
            with self._lock:
                # re-check BOTH refusals: a close() or a racing
                # add_model of the same name may have landed during the
                # (slow, outside-lock) server build — inserting now
                # would leak a live dispatcher thread no close() will
                # ever stop, or silently replace the racer's state
                closed = self._closed
                dup = name in self._models
                if not closed and not dup:
                    self._models[name] = state
            if dup:
                raise ValueError(
                    f"model {name!r} already deployed; use add_version() "
                    f"+ start_rollout() to ship new weights")
            if closed:
                raise ServerClosedError("fleet is closed")
        except BaseException:  # noqa: BLE001 — cleaned up, re-raised
            # a failed deploy must leave nothing behind: no live
            # dispatcher thread, and no catalog entry poisoning the
            # name for a retry
            if server is not None:
                server.close(drain=False)
            self.registry.discard(name, mv.version)
            raise
        logger.info("fleet: deployed %s v%d", name, mv.version)
        return mv

    def add_version(self, name: str, variables: Any = None, *,
                    label: Optional[str] = None) -> ModelVersion:
        """Register the next version's weights for entry ``name``.  The
        version is CATALOG-only until a rollout deploys it."""
        return self.registry.register(name, variables=variables,
                                      label=label)

    # -- head fan-out deployment (ISSUE 17) --------------------------------
    def add_fanout_model(self, name: str, model: Any, variables: Any = None,
                         *, head_fn: Optional[Callable] = None,
                         hbm_budget_bytes: Optional[int] = None,
                         label: Optional[str] = None,
                         warm_example: Any = None,
                         model_desc: Optional[str] = None,
                         **server_kwargs) -> ModelVersion:
        """Deploy ``name`` as a HEAD FAN-OUT entry: one shared backbone
        at the feature cut behind a
        :class:`~sparkdl_tpu.serving.server.HeadFanoutServer`, serving
        per-tenant heads from a stacked
        :class:`~sparkdl_tpu.parallel.engine.HeadBank` — thousands of
        tenant models for one backbone's HBM and FLOPs.

        Versioning for these entries is HEAD-ONLY (:meth:`add_head` /
        :meth:`swap_head`): the backbone's weights and program are
        pinned at deploy time, which is precisely what makes head churn
        provably recompile-free.  ``start_rollout`` refuses fan-out
        entries for the same reason.  The feature-cut cache namespace
        is backbone identity (``serving.cache.feature_namespace``), NOT
        the fleet's per-version prefix — a later deploy of the same
        backbone (any fleet) serves the warm entries."""
        with self._lock:
            if self._closed:
                raise ServerClosedError("fleet is closed")
            if name in self._models:
                raise ValueError(
                    f"model {name!r} already deployed; fan-out entries "
                    f"version by HEAD (add_head/swap_head)")
        mv = self.registry.register(name, model, variables,
                                    featurize=True, label=label)
        entry = self.registry.entry(name)
        # same precedence as _build_server, minus the per-version cache
        # namespace (HeadFanoutServer derives the feature-cut one)
        dtype_keys = ("compute_dtype", "output_host_dtype")
        caller_set_dtype = any(k in server_kwargs
                               or k in self._server_defaults
                               for k in dtype_keys)
        kw = dict(self._server_defaults)
        for k, v in entry.engine_overrides.items():
            if k in dtype_keys and caller_set_dtype:
                continue
            kw[k] = v
        kw.update(server_kwargs)
        kw.setdefault("cache",
                      self._cache if self._cache is not None else False)
        # fleet-shared ledger (False, not None: the fleet resolved the
        # SPARKDL_COST default once — per-entry servers must not
        # re-resolve it behind its back)
        kw.setdefault("cost",
                      self._cost if self._cost is not None else False)
        server = None
        try:
            server = HeadFanoutServer(
                entry.fn, mv.variables, head_fn=head_fn,
                hbm_budget_bytes=hbm_budget_bytes,
                # zoo entries keep the zoo name as the lockfile-facing
                # desc; callables let the server derive the fn name
                model_desc=(model_desc if model_desc is not None
                            else (model if isinstance(model, str)
                                  else None)),
                **kw)
            if warm_example is not None:
                server.warmup(warm_example)
            state = _ModelState(entry, mv.version, server, server_kwargs)
            with self._lock:
                closed = self._closed
                dup = name in self._models
                if not closed and not dup:
                    self._models[name] = state
            if dup:
                raise ValueError(
                    f"model {name!r} already deployed; fan-out entries "
                    f"version by HEAD (add_head/swap_head)")
            if closed:
                raise ServerClosedError("fleet is closed")
        except BaseException:  # noqa: BLE001 — cleaned up, re-raised
            if server is not None:
                server.close(drain=False)
            self.registry.discard(name, mv.version)
            raise
        logger.info("fleet: deployed fan-out entry %s v%d", name,
                    mv.version)
        return mv

    def _fanout_state(self, name: str) -> _ModelState:
        state = self._state(name)
        if not isinstance(state.server, HeadFanoutServer):
            raise TypeError(
                f"model {name!r} is not a head fan-out entry; deploy "
                f"with add_fanout_model() to use per-tenant heads")
        return state

    def add_head(self, name: str, tenant: str, weights, *,
                 label: Optional[str] = None) -> Dict[str, Any]:
        """Register + serve a NEW tenant head under fan-out entry
        ``name``.  Returns the ``head_swap_report`` no-backbone-
        recompile proof, extended with the catalog head version."""
        return self._head_op("add", name, tenant, weights, label)

    def swap_head(self, name: str, tenant: str, weights, *,
                  label: Optional[str] = None) -> Dict[str, Any]:
        """Hot-swap ``tenant``'s head under load.  The backbone cannot
        recompile (proven in the returned report) and the feature-cut
        cache stays warm — the namespace never saw the head."""
        return self._head_op("swap", name, tenant, weights, label)

    def remove_head(self, name: str, tenant: str) -> Dict[str, Any]:
        """Evict a departed tenant's head from the bank."""
        return self._head_op("remove", name, tenant, None, None)

    def _head_op(self, op: str, name: str, tenant: str, weights,
                 label: Optional[str]) -> Dict[str, Any]:
        state = self._fanout_state(name)
        server: HeadFanoutServer = state.server
        if op == "add":
            report = server.add_head(tenant, weights)
        elif op == "swap":
            report = server.swap_head(tenant, weights)
        else:
            report = server.remove_head(tenant)
        if op != "remove":
            hv: HeadVersion = self.registry.register_head(
                name, tenant, weights, label=label)
            report["head_version"] = hv.version
        with self._lock:
            state.last_swap_report = report
        self.metrics.incr("fleet.head_swaps")
        return report

    def _build_server(self, entry, mv: ModelVersion,
                      server_kwargs: Dict[str, Any]) -> Server:
        # precedence, most specific wins: explicit per-entry
        # server_kwargs > the entry's resolved bundle overrides > the
        # fleet-wide _server_defaults.  The bundle's DTYPE contract
        # (e.g. zoo bf16 compute + f32 host cast) additionally yields
        # whenever the caller set either dtype knob anywhere; its
        # OTHER overrides (partition_rules, donate_batch — the
        # recorded GC001 exemption) must beat fleet-wide defaults
        # regardless of the dtype choice.
        dtype_keys = ("compute_dtype", "output_host_dtype")
        caller_set_dtype = any(k in server_kwargs
                               or k in self._server_defaults
                               for k in dtype_keys)
        kw = dict(self._server_defaults)
        for k, v in entry.engine_overrides.items():
            if k in dtype_keys and caller_set_dtype:
                continue
            kw[k] = v
        kw.update(server_kwargs)
        if "cache" not in kw:
            if self._cache is not None:
                fp = self._resolve_fingerprint(entry)
                from sparkdl_tpu.utils.digest import content_digest

                self._version_meta[(entry.name, mv.version)] = (
                    fp, content_digest(mv.variables))
                kw["cache"] = self._cache
                kw["cache_namespace"] = self._cache_prefix + (
                    entry.name, mv.version, fp)
            else:
                # the fleet resolved the process default ONCE; the
                # per-version servers must not re-resolve it behind
                # its back
                kw["cache"] = False
        kw.setdefault("cost",
                      self._cost if self._cost is not None else False)
        # zoo entries keep the lockfile-facing model name so the cost
        # ledger's FLOPs lookup lands on the committed dispatch records
        # (tolerate registry doubles that carry no model_desc)
        md = getattr(entry, "model_desc", None)
        if md is not None:
            kw.setdefault("model_desc", md)
        return Server(entry.fn, variables=mv.variables, **kw)

    def _resolve_fingerprint(self, entry) -> Optional[str]:
        """The entry's committed program identity for cache survival
        (class docstring of the ``cache=`` knob in ``__init__``)."""
        pf = self._program_fingerprints
        if callable(pf):
            return pf(entry.name, entry)
        if isinstance(pf, dict):
            if entry.name in pf:
                return pf[entry.name]
        from sparkdl_tpu.serving.cache import lockfile_model_fingerprint

        return lockfile_model_fingerprint(entry.model_desc)

    def _swap_cache_entries(self, name: str, report: Dict[str, Any],
                            old_version: int, new_version: int) -> tuple:
        """The promote-time half of "cache-warm-across-swap": entries
        SURVIVE (re-keyed under the new version's namespace) iff the
        new version's ``PROGRAMS.lock.json`` StableHLO fingerprint is
        unchanged — the chip-free "same computation" proof ISSUE 11
        extends from the rollout's no-recompile contract — AND its
        weight bytes digest-equal the old version's (the fingerprint
        covers the program, not the weight VALUES; new weights mean
        new outputs, so a weights rollout always invalidates).  Any
        other promote invalidates the old namespace outright.  The
        verdict rides the swap report as ``report["cache"]``."""
        old_meta = self._version_meta.pop((name, old_version), None)
        new_meta = self._version_meta.get((name, new_version))
        old_fp, old_wd = old_meta if old_meta is not None else (None, None)
        new_fp, new_wd = new_meta if new_meta is not None else (None, None)
        fp_unchanged = old_fp is not None and old_fp == new_fp
        weights_unchanged = old_wd is not None and old_wd == new_wd
        survived = fp_unchanged and weights_unchanged
        old_ns = self._cache_prefix + (name, old_version, old_fp)
        if survived:
            entries = self._cache.adopt(
                old_ns, self._cache_prefix + (name, new_version, new_fp))
        else:
            entries = self._cache.invalidate(old_ns)
        report["cache"] = {
            "survived": survived,
            "entries": entries,
            "fingerprint_unchanged": fp_unchanged,
            "weights_unchanged": weights_unchanged,
        }
        # the caller sweeps this namespace AGAIN after the old server's
        # drain: in-flight old-version leaders settling during the
        # drain re-insert under it, and nothing can ever read those
        return old_ns

    # -- rollout lifecycle -------------------------------------------------
    def _state(self, name: str) -> _ModelState:
        with self._lock:
            state = self._models.get(name)
        if state is None:
            raise KeyError(f"model {name!r} is not deployed; deployed: "
                           f"{sorted(self._models) or 'none'}")
        return state

    def start_rollout(self, name: str, version: Optional[int] = None,
                      canary_fraction: float = 0.1,
                      warm_example: Any = None) -> Rollout:
        """Load ``version`` (default: latest registered) ALONGSIDE the
        live version and start routing ``canary_fraction`` of traffic to
        it.  Both versions serve until :meth:`promote` or
        :meth:`rollback`; in-flight requests always complete on the
        version that admitted them."""
        if not 0.0 <= float(canary_fraction) <= 1.0:
            # validate BEFORE building the canary server: a refused
            # rollout must not leak a live dispatcher thread
            raise ValueError(f"canary fraction must be in [0, 1], got "
                             f"{canary_fraction}")
        state = self._state(name)
        if isinstance(state.server, HeadFanoutServer):
            # the fan-out contract: the backbone is IMMUTABLE after
            # deploy (that immutability is the no-recompile proof) —
            # per-tenant versioning goes through swap_head instead
            raise RuntimeError(
                f"model {name!r} is a head fan-out entry; its backbone "
                f"never versions — hot-swap per-tenant heads with "
                f"swap_head() instead")
        with self._lock:
            if state.rollout is not None:
                raise RuntimeError(
                    f"a rollout for {name!r} is already in progress "
                    f"(v{state.rollout.canary_version}); promote or "
                    f"roll back first")
        mv = self.registry.get(name, version)
        if mv.version == state.version:
            raise ValueError(f"{name!r} is already serving v{mv.version}")
        canary = self._build_server(state.entry, mv, state.server_kwargs)
        if warm_example is not None:
            try:
                canary.warmup(warm_example)
            except BaseException:  # noqa: BLE001 — cleaned up, re-raised
                # a refused rollout must not leak a live dispatcher
                # thread; the version stays cataloged (it never deployed)
                canary.close(drain=False)
                raise
        ro = Rollout(name, state.version, state.server, mv.version, canary,
                     canary_fraction,
                     exec_before=state.server.executable_state())
        with self._lock:
            if state.rollout is not None or self._closed:
                already = state.rollout is not None
                state_err = ("rollout already in progress" if already
                             else "fleet is closed")
            else:
                state_err = None
                state.rollout = ro
        if state_err is not None:
            canary.close(drain=False)
            raise RuntimeError(f"cannot start rollout for {name!r}: "
                               f"{state_err}")
        self.metrics.incr("fleet.rollouts")
        flight_emit("rollout.start", model=name,
                    stable_version=ro.stable_version,
                    canary_version=mv.version,
                    fraction=float(canary_fraction))
        logger.info("fleet: rollout %s v%d -> v%d (canary %.0f%%)",
                    name, state.version, mv.version,
                    100 * canary_fraction)
        return ro

    def promote(self, name: str) -> Dict[str, Any]:
        """Flip ``name`` to its canary version and drain the old one.
        Returns the swap report (per-bucket no-recompile proof).  An
        injected ``fleet.swap`` fault aborts BEFORE any state changes —
        both versions keep serving and promote() can be retried."""
        state = self._state(name)
        ro = state.rollout
        if ro is None:
            raise RuntimeError(f"no rollout in progress for {name!r}")
        report = ro.promote()  # fleet.swap fires here; raises = no-op
        with self._lock:
            old_server = state.server
            state.server = ro.canary_server
            state.version = ro.canary_version
            state.rollout = None
            state.last_swap_report = report
            closed = self._closed
        old_ns = None
        if self._cache is not None:
            # between the phase flip above and this point v2 requests
            # simply miss (and lead their own flights) — survival only
            # decides whether the warm v1 entries carry over
            old_ns = self._swap_cache_entries(name, report,
                                              ro.stable_version,
                                              ro.canary_version)
        self.metrics.incr("fleet.swaps")
        flight_emit("rollout.promote", model=name,
                    version=ro.canary_version,
                    drained_version=ro.stable_version,
                    no_recompile=report.get("no_recompile"),
                    cache_survived=(report.get("cache") or {}).get(
                        "survived"))
        # the old version drains OUTSIDE the state lock: new requests
        # already route to the promoted server while every in-flight v1
        # request completes on v1
        old_server.close(drain=True)
        if self._cache is not None and old_ns is not None:
            # post-drain sweep: leaders that settled DURING the drain
            # re-inserted under the old namespace after the swap moved/
            # dropped it — unreachable forever, so reclaim the bytes
            self._cache.invalidate(old_ns)
        if closed:
            # a close() that raced the phase flip saw ro.active False,
            # skipped the canary, and closed only the old server — the
            # canary is the live server of a closed fleet now; stop it
            ro.canary_server.close(drain=True)
        return report

    def rollback(self, name: str) -> Dict[str, Any]:
        """Abandon ``name``'s canary: requests in flight on it complete
        on the canary version (graceful drain); the stable version never
        stopped serving."""
        state = self._state(name)
        ro = state.rollout
        if ro is None:
            raise RuntimeError(f"no rollout in progress for {name!r}")
        report = ro.rollback()  # fleet.swap fires here; raises = no-op
        with self._lock:
            state.rollout = None
            state.last_swap_report = report
        canary_ns = None
        if self._cache is not None:
            # the canary version will never serve again: its namespace
            # is unreachable — reclaim the bytes (the stable version's
            # entries never moved, so rollback keeps the cache warm)
            meta = self._version_meta.pop((name, ro.canary_version), None)
            fp = meta[0] if meta is not None else None
            canary_ns = self._cache_prefix + (name, ro.canary_version, fp)
            entries = self._cache.invalidate(canary_ns)
            report["cache"] = {"survived": False, "entries": entries,
                               "fingerprint_unchanged": None,
                               "weights_unchanged": None}
        self.metrics.incr("fleet.rollbacks")
        flight_emit("rollout.rollback", model=name,
                    drained_version=ro.canary_version,
                    version=ro.stable_version)
        ro.canary_server.close(drain=True)
        if self._cache is not None and canary_ns is not None:
            # post-drain sweep, same rationale as promote(): canary
            # leaders settling during the drain re-inserted under the
            # dead namespace
            self._cache.invalidate(canary_ns)
        return report

    def swap_report(self, name: str) -> Optional[Dict[str, Any]]:
        """The last promote/rollback report for ``name`` (None before
        the first swap)."""
        state = self._state(name)
        with self._lock:
            return state.last_swap_report

    # -- request path ------------------------------------------------------
    def submit(self, name: str, example: Any, *, tenant: str = "default",
               timeout_ms: Optional[float] = None) -> Future:
        """Admit one example for model ``name`` on behalf of ``tenant``.

        Raises ``KeyError`` (unknown model), ``ServerClosedError``
        (closed fleet), ``QuotaExceededError`` / ``QueueFullError`` /
        ``ServiceUnavailableError`` (admission — see :mod:`.admission`).
        The returned future settles exactly like ``Server.submit``'s and
        additionally carries ``fleet_model``/``fleet_version``/
        ``fleet_tenant``/``fleet_canary`` attributes."""
        with self._lock:
            if self._closed:
                raise ServerClosedError("fleet is closed")
        state = self._state(name)
        self.metrics.incr("fleet.requests")
        inject("fleet.admit")
        # a promote/rollback between route() and the server submit can
        # close the losing server under us; one re-route retries onto
        # the winner — the zero-downtime guarantee for the racing window
        for attempt in (0, 1):
            version, server, is_canary = self._route(state)
            quota = self.admission.admit(
                tenant, pressure=server.queue_pressure(),
                unavailable_retry_after=server.breaker_retry_after())
            t0 = self._clock()
            tracer = get_tracer()
            span = tracer.start_span("fleet.request", model=name,
                                     version=version, tenant=tenant,
                                     canary=is_canary,
                                     priority=quota.priority)
            try:
                with tracer.use(span):
                    if isinstance(server, HeadFanoutServer):
                        # fan-out entries dispatch the admission tenant's
                        # OWN head after the shared backbone featurizes
                        fut = server.submit(example, tenant,
                                            timeout_ms=timeout_ms)
                    else:
                        fut = server.submit(example, timeout_ms=timeout_ms,
                                            tenant=tenant)
                break
            except ServerClosedError:
                span.finish("rejected")
                # the request never reached a live server: refund the
                # charge (slot AND token, admitted ledger backed out) —
                # whether we retry or reject, it must not cost quota
                self.admission.refund(tenant)
                with self._lock:
                    fleet_closed = self._closed
                if attempt == 0 and not fleet_closed:
                    continue  # re-route: the swap already installed v2
                self.metrics.incr("fleet.rejected")
                self._count(name, tenant, "rejected")
                raise
            except BaseException:  # noqa: BLE001 — accounted, re-raised
                self.admission.release(tenant)
                span.finish("rejected")
                self.metrics.incr("fleet.rejected")
                self._count(name, tenant, "rejected")
                raise
        self._count(name, tenant, "requests")
        if is_canary:
            self.metrics.incr("fleet.canary_requests")
            self._count(name, tenant, "canary")
        fut.fleet_model = name
        fut.fleet_version = version
        fut.fleet_tenant = tenant
        fut.fleet_canary = is_canary

        def _settle(f: Future) -> None:
            self.admission.release(tenant)
            failed = f.cancelled() or f.exception() is not None
            self.metrics.record_time("fleet.request_latency",
                                     self._clock() - t0)
            if failed:
                self.metrics.incr("fleet.request_failures")
                self._count(name, tenant, "failed")
                span.finish("error")
            else:
                self.metrics.incr("fleet.completed")
                self._count(name, tenant, "completed")
                span.finish()

        fut.add_done_callback(_settle)
        return fut

    def predict(self, name: str, example: Any, *, tenant: str = "default",
                timeout_ms: Optional[float] = None) -> Any:
        """Blocking single-request convenience: submit + wait."""
        return self.submit(name, example, tenant=tenant,
                           timeout_ms=timeout_ms).result()

    def _route(self, state: _ModelState):
        with self._lock:
            ro = state.rollout
            version, server = state.version, state.server
        if ro is not None:
            return ro.route()
        return version, server, False

    def _count(self, model: str, tenant: str, key: str) -> None:
        with self._lock:
            m = self._per_model.setdefault(model, {})
            m[key] = m.get(key, 0) + 1
            t = self._per_tenant.setdefault(tenant, {})
            t[key] = t.get(key, 0) + 1

    # -- introspection -----------------------------------------------------
    @property
    def cache(self):
        """The fleet-wide result cache (None when uncached)."""
        return self._cache

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def deployed_version(self, name: str) -> int:
        state = self._state(name)
        with self._lock:
            return state.version

    def wake(self) -> None:
        """Nudge every deployed server's batcher (stable AND canary) to
        re-evaluate its flush windows — the fleet-wide form of
        :meth:`Server.wake`, called by a virtual-time driver after it
        advances the injected clock."""
        with self._lock:
            states = list(self._models.values())
        for state in states:
            state.server.wake()
            ro = state.rollout
            if ro is not None and ro.active:
                ro.canary_server.wake()

    def health(self) -> Dict[str, Any]:
        """Aggregated liveness/readiness, built through the ONE
        :meth:`~sparkdl_tpu.utils.health.HealthTracker.payload` schema
        every ``health()`` in the stack shares (ISSUE 9): fleet state is
        the WORST of its models' server states (plus canary servers
        mid-rollout) and the fleet tracker's own state (SLO breaches);
        per-model detail nests each server's own ``health()`` under the
        ``models`` extra, and ``slo`` carries the objective evaluation
        when ``slos=`` were configured."""
        extra: Dict[str, Any] = {}
        if self._slo_engine is not None:
            # evaluate BEFORE the aggregation: a breach crossing on this
            # very poll must already show as degraded
            extra["slo"] = self._slo_engine.evaluate()
        with self._lock:
            models = dict(self._models)
            closed = self._closed
        rank = {"ready": 0, "degraded": 1, "closed": 1}
        worst = "ready"
        per: Dict[str, Any] = {}
        for name, state in sorted(models.items()):
            h = state.server.health()
            entry: Dict[str, Any] = {"version": state.version,
                                     "stable": h}
            ro = state.rollout
            if ro is not None and ro.active:
                ch = ro.canary_server.health()
                entry["canary"] = {"version": ro.canary_version,
                                   "health": ch}
                if rank.get(ch["state"], 1) > rank[worst]:
                    worst = "degraded"
            per[name] = entry
            if rank.get(h["state"], 1) > rank[worst]:
                worst = "degraded"
        if rank.get(self._health.snapshot()["state"], 1) > rank[worst]:
            worst = "degraded"
        return self._health.payload(
            live=not closed,
            state_override="closed" if closed else worst,
            models=per, **extra)

    def stats(self) -> Dict[str, float]:
        """Flat fleet-level metrics summary (``fleet.*``)."""
        return self.metrics.subset("fleet.")

    def varz(self) -> Dict[str, Any]:
        """The ``/varz``-shaped fleet snapshot: per-model versions,
        rollout state, queue/bucket/executable state, and latency; the
        admission ledger; per-tenant counts; fleet counters and the full
        metrics snapshot.  JSON-serializable throughout —
        ``json.dumps(fleet.varz())`` IS the monitoring endpoint body
        (contract-tested, like ``Server.varz``)."""
        from sparkdl_tpu.obs.export import metrics_snapshot

        with self._lock:
            models = dict(self._models)
            closed = self._closed
            per_model = {k: dict(v) for k, v in self._per_model.items()}
            per_tenant = {k: dict(v) for k, v in self._per_tenant.items()}
        model_section: Dict[str, Any] = {}
        for name, state in sorted(models.items()):
            srv = state.server
            ro = state.rollout

            def dist_ms(m: Metrics, metric: str) -> Dict[str, float]:
                out: Dict[str, float] = {}
                for q, key in ((50, "p50_ms"), (99, "p99_ms")):
                    v = m.percentile(metric, q, kind="timing")
                    if v is not None:
                        out[key] = round(v * 1e3, 3)
                return out

            model_section[name] = {
                "version": state.version,
                "versions": self.registry.versions(name),
                "featurize": state.entry.featurize,
                "model": state.entry.model_desc,
                "queue_depth": srv.queue_depth(),
                "queue_pressure": round(srv.queue_pressure(), 4),
                "buckets": srv.bucket_sizes,
                "executables": srv.executable_state(),
                "rollout": ro.status() if ro is not None else None,
                "last_swap": state.last_swap_report,
                "counters": per_model.get(name, {}),
                "latency_ms": dist_ms(srv.metrics,
                                      "serving.request_latency"),
            }
            if isinstance(srv, HeadFanoutServer):
                model_section[name]["headfanout"] = {
                    "tenants": srv.tenants(),
                    "bank": srv.head_state(),
                    "feature_namespace": list(srv.feature_namespace),
                }
        snap = metrics_snapshot(self.metrics)
        return {
            "fleet": {
                "closed": closed,
                "models": model_section,
                "registry": self.registry.as_dict(),
                "cache": (self._cache.info() if self._cache is not None
                          else None),
            },
            "health": self.health(),
            "admission": self.admission.snapshot(),
            "tenants": per_tenant,
            "cost": (self._cost.snapshot() if self._cost is not None
                     else None),
            "counters": {k: v for k, v in snap["counters"].items()
                         if k.startswith("fleet.")},
            "metrics": snap,
        }

    @property
    def cost(self):
        """The fleet-shared :class:`~sparkdl_tpu.obs.cost.CostLedger`
        (None when cost attribution is off)."""
        return self._cost

    # -- lifecycle ---------------------------------------------------------
    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def close(self, drain: bool = True) -> None:
        """Stop the whole fleet: every model's server (and any live
        canary) closes with the given drain semantics.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            models = dict(self._models)
        for name, state in sorted(models.items()):
            ro = state.rollout
            if ro is not None and ro.active:
                ro.canary_server.close(drain=drain)
            state.server.close(drain=drain)
        if self._cache is not None:
            # the whole fleet prefix dies with the fleet: every
            # per-version namespace under it is unreachable now, and
            # leaving the entries would charge a shared/process-default
            # cache's byte budget forever (the Server-anon reclaim
            # rule, applied fleet-wide)
            self._cache.invalidate(self._cache_prefix)
            self._version_meta.clear()
        logger.info("fleet: closed (%d models)", len(models))

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(drain=exc_type is None)
