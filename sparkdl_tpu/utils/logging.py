"""Structured logging for the framework.

The reference only used ad-hoc ``logging`` warnings; SURVEY.md §5 flags
observability as a gap to fill — this gives every subsystem a namespaced
logger with one consistent format.

Trace correlation: when span tracing is active (``SPARKDL_TRACE``,
:mod:`sparkdl_tpu.obs.trace`), every record emitted from inside a span
carries that span's trace id (`` trace=t0000af``) so log lines from the
admission thread, dispatch workers, and pipeline stages join up with
the trace artifacts.  With tracing off the hook is one global read per
record and the format is unchanged.
"""

from __future__ import annotations

import logging
import os

# %(name)s is the full dotted logger name (already sparkdl_tpu-prefixed);
# %(trace)s is "" or " trace=<id>" (injected by _TraceContextFilter).
_FORMAT = "%(asctime)s %(levelname)s %(name)s%(trace)s: %(message)s"
_configured = False


class _TraceContextFilter(logging.Filter):
    """Stamps each record with the calling thread's current trace id
    (empty when tracing is disabled or no span is open).  Imports the
    tracer lazily so logging never drags ``obs`` in at import time."""

    def filter(self, record: logging.LogRecord) -> bool:
        tid = None
        try:
            from sparkdl_tpu.obs.trace import current_trace_id

            tid = current_trace_id()
        except Exception:  # graftlint: allow=SDL003 reason=logging must never raise
            pass
        record.trace = f" trace={tid}" if tid else ""
        return True


def _configure_root():
    global _configured
    if _configured:
        return
    level = os.environ.get("SPARKDL_TPU_LOG_LEVEL", "INFO").upper()
    if level not in ("CRITICAL", "FATAL", "ERROR", "WARNING", "WARN", "INFO",
                     "DEBUG", "NOTSET"):
        logging.getLogger("sparkdl_tpu").warning(
            "Invalid SPARKDL_TPU_LOG_LEVEL=%r; using INFO", level)
        level = "INFO"
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(_FORMAT))
    handler.addFilter(_TraceContextFilter())
    root = logging.getLogger("sparkdl_tpu")
    root.addHandler(handler)
    root.setLevel(level)
    root.propagate = False
    _configured = True


def get_logger(name: str) -> logging.Logger:
    _configure_root()
    # Callers pass __name__, which already starts with the package prefix.
    if name.startswith("sparkdl_tpu"):
        name = name[len("sparkdl_tpu"):].lstrip(".")
    root = logging.getLogger("sparkdl_tpu")
    return root.getChild(name) if name else root
