"""Structured logging for the framework.

The reference only used ad-hoc ``logging`` warnings; SURVEY.md §5 flags
observability as a gap to fill — this gives every subsystem a namespaced
logger with one consistent format.
"""

from __future__ import annotations

import logging
import os

# %(name)s is the full dotted logger name (already sparkdl_tpu-prefixed).
_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"
_configured = False


def _configure_root():
    global _configured
    if _configured:
        return
    level = os.environ.get("SPARKDL_TPU_LOG_LEVEL", "INFO").upper()
    if level not in ("CRITICAL", "FATAL", "ERROR", "WARNING", "WARN", "INFO",
                     "DEBUG", "NOTSET"):
        logging.getLogger("sparkdl_tpu").warning(
            "Invalid SPARKDL_TPU_LOG_LEVEL=%r; using INFO", level)
        level = "INFO"
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(_FORMAT))
    root = logging.getLogger("sparkdl_tpu")
    root.addHandler(handler)
    root.setLevel(level)
    root.propagate = False
    _configured = True


def get_logger(name: str) -> logging.Logger:
    _configure_root()
    # Callers pass __name__, which already starts with the package prefix.
    if name.startswith("sparkdl_tpu"):
        name = name[len("sparkdl_tpu"):].lstrip(".")
    root = logging.getLogger("sparkdl_tpu")
    return root.getChild(name) if name else root
