"""Step timing + throughput metrics.

SURVEY.md §5: the reference had no metrics at all (Spark UI only); the TPU
build makes images/sec/chip a first-class counter since it is the baseline
metric.  Timers bracket device work with ``jax.block_until_ready`` so async
dispatch doesn't fake speedups.

The serving layer (sparkdl_tpu.serving) adds concurrent writers (admission
thread + dispatch workers), so every mutation takes a process-local lock,
and adds latency-distribution consumers, so timing/observation series
expose percentiles (``percentile``) and ``summary`` carries p50/p99.

Series are BOUNDED: each timing/histogram list keeps at most
``max_samples`` recent samples (the oldest half is dropped on overflow),
so a long-running server records per-request latency forever without
growing without limit — percentiles/means then describe the recent
window, while counters stay cumulative.
"""

from __future__ import annotations

import contextlib
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from sparkdl_tpu.analysis.lockcheck import named_lock


@dataclass
class Metrics:
    """A tiny metrics registry: named counters + gauges + timing lists +
    unitless observation histograms (e.g. batch fill ratios, queue depths).
    """

    counters: Dict[str, float] = field(default_factory=dict)
    gauges: Dict[str, float] = field(default_factory=dict)
    timings_s: Dict[str, List[float]] = field(default_factory=dict)
    histograms: Dict[str, List[float]] = field(default_factory=dict)
    # Per-series sample bound: on overflow the OLDEST half is dropped, so
    # a server recording per-request latency indefinitely holds O(cap)
    # floats per series, and percentiles describe the recent window.
    max_samples: int = 16384
    # named_lock: a plain threading.Lock unless SPARKDL_LOCKCHECK=1, in
    # which case acquisitions feed the analysis.lockcheck order graph
    _lock: threading.Lock = field(
        default_factory=lambda: named_lock("utils.metrics"),
        init=False, repr=False, compare=False)

    def incr(self, name: str, value: float = 1.0):
        # float() on every recorder: numpy scalars (an np.float32 batch
        # statistic, an np.int64 row count) must never enter the
        # registry — json.dumps(Server.varz()) IS the monitoring
        # endpoint body, and a leaked numpy scalar breaks it
        value = float(value)
        with self._lock:
            self.counters[name] = self.counters.get(name, 0.0) + value

    def gauge(self, name: str, value: float):
        value = float(value)
        with self._lock:
            self.gauges[name] = value

    def _append_bounded(self, series: List[float], value: float):
        series.append(value)
        if self.max_samples and len(series) > self.max_samples:
            del series[:len(series) // 2]

    def record_time(self, name: str, seconds: float):
        seconds = float(seconds)
        with self._lock:
            self._append_bounded(self.timings_s.setdefault(name, []),
                                 seconds)

    def observe(self, name: str, value: float):
        """Append one sample to the unitless histogram ``name`` (for
        non-time distributions: batch fill ratio, queue depth, ...)."""
        with self._lock:
            self._append_bounded(self.histograms.setdefault(name, []),
                                 float(value))

    @staticmethod
    def _percentile(values: List[float], q: float) -> float:
        """Nearest-rank percentile (q in [0, 100]) of a non-empty list."""
        vs = sorted(values)
        k = max(0, min(len(vs) - 1, math.ceil(q / 100.0 * len(vs)) - 1))
        return vs[k]

    def percentile(self, name: str, q: float,
                   kind: Optional[str] = None) -> Optional[float]:
        """Percentile of a timing or histogram series; None when the
        series is absent/empty.

        Name-collision contract (a name living in BOTH families):
        lookup is EXPLICIT and deterministic — ``kind="timing"`` /
        ``kind="histogram"`` selects a family outright; with
        ``kind=None`` (default) a name PRESENT in ``timings_s`` always
        resolves to the timing series, even when that series is
        currently empty (historically an empty timing list fell through
        to a same-named histogram via ``or``-short-circuit, so the
        answer flipped family with buffer occupancy)."""
        with self._lock:
            if kind == "timing":
                series = self.timings_s.get(name)
            elif kind == "histogram":
                series = self.histograms.get(name)
            elif kind is not None:
                raise ValueError(f"kind must be 'timing', 'histogram', "
                                 f"or None, got {kind!r}")
            elif name in self.timings_s:  # timings win, even when empty
                series = self.timings_s[name]
            else:
                series = self.histograms.get(name)
            series = list(series) if series else None
        if not series:
            return None
        return self._percentile(series, q)

    def snapshot_raw(self) -> Dict[str, Dict]:
        """Consistent copies of every family under one lock hold —
        the raw shape the exporters (``obs.export``) aggregate from:
        ``{"counters", "gauges", "timings_s", "histograms"}`` with
        series copied so the caller can iterate without racing
        writers."""
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "timings_s": {k: list(v) for k, v in self.timings_s.items()},
                "histograms": {k: list(v)
                               for k, v in self.histograms.items()},
            }

    def subset(self, prefix: str) -> Dict[str, float]:
        """``summary()`` filtered to keys starting with ``prefix`` — the
        shape consumers embed elsewhere (``bench.py`` per-config JSON
        lines carry ``pipeline.*`` stage stalls; ``Server.stats`` carries
        ``serving.*``)."""
        return {k: v for k, v in self.summary().items()
                if k.startswith(prefix)}

    def summary(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self.counters)
            out.update(self.gauges)
            timings = {k: list(v) for k, v in self.timings_s.items()}
            hists = {k: list(v) for k, v in self.histograms.items()}
        for k, v in timings.items():
            if v:
                out[f"{k}.mean_s"] = sum(v) / len(v)
                out[f"{k}.total_s"] = sum(v)
                out[f"{k}.count"] = len(v)
                out[f"{k}.p50_s"] = self._percentile(v, 50)
                out[f"{k}.p99_s"] = self._percentile(v, 99)
        for k, v in hists.items():
            if v:
                out[f"{k}.mean"] = sum(v) / len(v)
                out[f"{k}.count"] = len(v)
                out[f"{k}.p50"] = self._percentile(v, 50)
                out[f"{k}.p99"] = self._percentile(v, 99)
        return out

    @contextlib.contextmanager
    def profile(self, trace_dir: str, block_on=None):
        """``jax.profiler.trace`` context around a pipeline section
        (SURVEY.md §5 tracing).  Writes an XPlane trace under ``trace_dir``
        viewable in TensorBoard/XProf; ``block_on`` forces device
        completion inside the trace window so async dispatch doesn't hide
        the compute."""
        import jax

        t0 = time.perf_counter()
        with jax.profiler.trace(trace_dir):
            try:
                yield self
            finally:
                if block_on is not None:
                    jax.block_until_ready(block_on)
        self.record_time("profile", time.perf_counter() - t0)


class StepTimer:
    """Wall-clock timer that forces device completion before stopping."""

    def __init__(self, metrics: Optional[Metrics] = None, name: str = "step"):
        self.metrics = metrics
        self.name = name
        self.elapsed_s = 0.0

    @contextlib.contextmanager
    def time(self, block_on=None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if block_on is not None:
                import jax
                jax.block_until_ready(block_on)
            self.elapsed_s = time.perf_counter() - t0
            if self.metrics is not None:
                self.metrics.record_time(self.name, self.elapsed_s)


def throughput_counter(num_items: int, seconds: float, num_devices: int = 1) -> Dict[str, float]:
    """items/sec and items/sec/chip — the baseline metric shape."""
    ips = num_items / seconds if seconds > 0 else float("inf")
    return {
        "items_per_sec": ips,
        "items_per_sec_per_chip": ips / max(1, num_devices),
        "seconds": seconds,
        "num_items": float(num_items),
    }
