"""Step timing + throughput metrics.

SURVEY.md §5: the reference had no metrics at all (Spark UI only); the TPU
build makes images/sec/chip a first-class counter since it is the baseline
metric.  Timers bracket device work with ``jax.block_until_ready`` so async
dispatch doesn't fake speedups.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Metrics:
    """A tiny metrics registry: named counters + gauges + timing lists."""

    counters: Dict[str, float] = field(default_factory=dict)
    gauges: Dict[str, float] = field(default_factory=dict)
    timings_s: Dict[str, List[float]] = field(default_factory=dict)

    def incr(self, name: str, value: float = 1.0):
        self.counters[name] = self.counters.get(name, 0.0) + value

    def gauge(self, name: str, value: float):
        self.gauges[name] = value

    def record_time(self, name: str, seconds: float):
        self.timings_s.setdefault(name, []).append(seconds)

    def summary(self) -> Dict[str, float]:
        out = dict(self.counters)
        out.update(self.gauges)
        for k, v in self.timings_s.items():
            if v:
                out[f"{k}.mean_s"] = sum(v) / len(v)
                out[f"{k}.total_s"] = sum(v)
                out[f"{k}.count"] = len(v)
        return out

    @contextlib.contextmanager
    def profile(self, trace_dir: str, block_on=None):
        """``jax.profiler.trace`` context around a pipeline section
        (SURVEY.md §5 tracing).  Writes an XPlane trace under ``trace_dir``
        viewable in TensorBoard/XProf; ``block_on`` forces device
        completion inside the trace window so async dispatch doesn't hide
        the compute."""
        import jax

        t0 = time.perf_counter()
        with jax.profiler.trace(trace_dir):
            try:
                yield self
            finally:
                if block_on is not None:
                    jax.block_until_ready(block_on)
        self.record_time("profile", time.perf_counter() - t0)


class StepTimer:
    """Wall-clock timer that forces device completion before stopping."""

    def __init__(self, metrics: Optional[Metrics] = None, name: str = "step"):
        self.metrics = metrics
        self.name = name
        self.elapsed_s = 0.0

    @contextlib.contextmanager
    def time(self, block_on=None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if block_on is not None:
                import jax
                jax.block_until_ready(block_on)
            self.elapsed_s = time.perf_counter() - t0
            if self.metrics is not None:
                self.metrics.record_time(self.name, self.elapsed_s)


def throughput_counter(num_items: int, seconds: float, num_devices: int = 1) -> Dict[str, float]:
    """items/sec and items/sec/chip — the baseline metric shape."""
    ips = num_items / seconds if seconds > 0 else float("inf")
    return {
        "items_per_sec": ips,
        "items_per_sec_per_chip": ips / max(1, num_devices),
        "seconds": seconds,
        "num_items": float(num_items),
    }
