"""Crash-safe JSONL appending + torn-trailing-line recovery.

The write contract (ISSUE 4): a SIGKILL at ANY instant must leave a
valid JSONL file containing every record written so far — atexit hooks
never run under SIGKILL, so the only mechanism that survives one is
flushing each record as it happens.  Each record is a single
``os.write`` of ``line + "\\n"`` (a kill between records can never tear
a line) followed by an ``fsync`` (the kernel has acked it to disk
before the writer moves on).

Failure policy: ``OSError`` (read-only checkout, full disk) DISABLES the
writer instead of failing the run — the artifact is a rider on the real
work (bench numbers, dryrun stages), never a reason to lose it.  Check
:attr:`disabled` (or ``write_line``'s return) when the record is
load-bearing, as the streaming commit journal does.

The read contract (ISSUE 8): :func:`read_jsonl` is the ONE tolerant
reader for files written under this contract — a power loss or torn
flush can leave at most one partial record at the TAIL, so a final line
that fails to parse (or trailing bytes with no newline) is recoverable
damage, while an unparsable line anywhere EARLIER is real corruption
and raises.  :func:`recover_jsonl` additionally truncates the torn tail
in place so the file can be re-opened for append — the restart half of
the journal's torn-tail story.  Both ``bench.py``'s artifact and
``sparkdl_tpu.streaming.journal`` ride this one implementation
(contract-tested from both callers in tests/test_stream_ingest.py).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple


class CrashSafeJsonlWriter:
    """Append-only fsync'd line writer; see module docstring."""

    def __init__(self, path: str):
        self.path = path
        self._fd: Optional[int] = None
        self.disabled = False

    def _open(self, truncate: bool) -> None:
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        flags = os.O_WRONLY | os.O_CREAT | os.O_APPEND
        if truncate:
            flags |= os.O_TRUNC
        self._fd = os.open(self.path, flags, 0o644)

    def reset(self) -> None:
        """Truncate and start fresh (one run owns one artifact);
        re-enables a writer a previous error disabled."""
        self.close()
        self.disabled = False
        try:
            self._open(truncate=True)
        except OSError:
            self.disabled = True

    def write_line(self, line: str) -> bool:
        """Append one already-serialized JSON line; True iff it reached
        the disk (False once disabled)."""
        if self.disabled:
            return False
        pos = None
        try:
            if self._fd is None:
                self._open(truncate=False)
            pos = os.lseek(self._fd, 0, os.SEEK_END)
            data = (line + "\n").encode()
            while data:  # a short write (disk filling) must not be
                n = os.write(self._fd, data)  # silently reported as done
                data = data[n:]
            os.fsync(self._fd)
            return True
        except OSError:
            # roll back a torn partial record before disabling — the
            # whole point of the writer is that every line on disk
            # parses, including the last one
            if pos is not None:
                try:
                    os.ftruncate(self._fd, pos)
                except OSError:
                    pass
            self.disabled = True
            return False

    def close(self) -> None:
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None


class JsonlCorruptionError(ValueError):
    """A record that is NOT the trailing line failed to parse — damage
    the crash model cannot explain (a tear only ever eats the tail), so
    the caller must not silently drop committed history."""


def read_jsonl(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Read a crash-safe JSONL file, tolerating a torn tail.

    Returns ``(records, valid_bytes)`` where ``valid_bytes`` is the byte
    offset of the end of the last GOOD record — everything after it (a
    partial trailing record from a crash mid-write, or a final
    newline-terminated line that does not parse) is the torn tail the
    caller may discard.  A missing file reads as ``([], 0)``.  An
    unparsable line that is not the last raises
    :class:`JsonlCorruptionError`.
    """
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return [], 0
    records: List[Dict[str, Any]] = []
    valid = 0
    pos = 0
    n = len(data)
    while pos < n:
        nl = data.find(b"\n", pos)
        if nl < 0:
            break  # trailing bytes with no newline: torn tail
        line = data[pos:nl].strip()
        if line:
            try:
                records.append(json.loads(line))
            except ValueError:
                if nl + 1 >= n:
                    break  # unparsable FINAL line: torn tail
                raise JsonlCorruptionError(
                    f"{path}: unparsable record at byte {pos} is not the "
                    f"trailing line — corruption, not a torn tail") from None
        pos = nl + 1
        valid = pos
    return records, valid


def recover_jsonl(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """:func:`read_jsonl` + in-place truncation of the torn tail.

    Returns ``(records, discarded_bytes)``.  After this call the file
    ends exactly at the last good record, so re-opening it for append
    (``CrashSafeJsonlWriter``) cannot interleave new records with torn
    bytes.  The truncation is fsync'd — a crash right after recovery
    must not resurrect the tail.
    """
    records, valid = read_jsonl(path)
    discarded = 0
    try:
        size = os.path.getsize(path)
    except OSError:
        return records, 0
    if size > valid:
        discarded = size - valid
        fd = os.open(path, os.O_WRONLY)
        try:
            os.ftruncate(fd, valid)
            os.fsync(fd)
        finally:
            os.close(fd)
    return records, discarded
