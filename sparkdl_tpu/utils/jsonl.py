"""Crash-safe JSONL appender for driver artifacts.

The contract (ISSUE 4): a SIGKILL at ANY instant must leave a valid
JSONL file containing every record written so far — atexit hooks never
run under SIGKILL, so the only mechanism that survives one is flushing
each record as it happens.  Each record is a single ``os.write`` of
``line + "\\n"`` (a kill between records can never tear a line) followed
by an ``fsync`` (the kernel has acked it to disk before the writer moves
on).

Failure policy: ``OSError`` (read-only checkout, full disk) DISABLES the
writer instead of failing the run — the artifact is a rider on the real
work (bench numbers, dryrun stages), never a reason to lose it.  Check
:attr:`disabled` when the artifact is load-bearing.
"""

from __future__ import annotations

import os
from typing import Optional


class CrashSafeJsonlWriter:
    """Append-only fsync'd line writer; see module docstring."""

    def __init__(self, path: str):
        self.path = path
        self._fd: Optional[int] = None
        self.disabled = False

    def _open(self, truncate: bool) -> None:
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        flags = os.O_WRONLY | os.O_CREAT | os.O_APPEND
        if truncate:
            flags |= os.O_TRUNC
        self._fd = os.open(self.path, flags, 0o644)

    def reset(self) -> None:
        """Truncate and start fresh (one run owns one artifact);
        re-enables a writer a previous error disabled."""
        self.close()
        self.disabled = False
        try:
            self._open(truncate=True)
        except OSError:
            self.disabled = True

    def write_line(self, line: str) -> bool:
        """Append one already-serialized JSON line; True iff it reached
        the disk (False once disabled)."""
        if self.disabled:
            return False
        pos = None
        try:
            if self._fd is None:
                self._open(truncate=False)
            pos = os.lseek(self._fd, 0, os.SEEK_END)
            data = (line + "\n").encode()
            while data:  # a short write (disk filling) must not be
                n = os.write(self._fd, data)  # silently reported as done
                data = data[n:]
            os.fsync(self._fd)
            return True
        except OSError:
            # roll back a torn partial record before disabling — the
            # whole point of the writer is that every line on disk
            # parses, including the last one
            if pos is not None:
                try:
                    os.ftruncate(self._fd, pos)
                except OSError:
                    pass
            self.disabled = True
            return False

    def close(self) -> None:
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None
