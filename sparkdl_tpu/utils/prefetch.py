"""Background-thread prefetch for host pipelines.

The feed-the-chip path (SURVEY.md §7 hard part #2) is host decode ->
device_put -> compute.  ``prefetch_iter`` runs the producer (decode) on a
background thread with a bounded queue so host prep of chunk k+1 overlaps
device compute of chunk k — the single-process analog of the reference's
executor-side per-partition pipelining.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator

_SENTINEL = object()


def prefetch_iter(iterable: Iterable[Any], depth: int = 2) -> Iterator[Any]:
    """Iterate ``iterable`` on a daemon thread, ``depth`` items ahead.

    Exceptions in the producer re-raise at the consumer's next pull.  The
    bounded queue caps host memory at O(depth) produced items.
    """
    if depth < 1:
        yield from iterable
        return
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()
    error: list = []

    def put(item) -> bool:
        # Bounded put that gives up when the consumer abandoned the
        # iterator (e.g. map_batches raised mid-stream) — otherwise the
        # producer would block on the full queue forever, leaking the
        # thread and `depth` decoded chunks per failed transform.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            for item in iterable:
                if not put(item):
                    return
        except BaseException as e:  # graftlint: allow=SDL003 reason=re-raised on the consumer side at next pull
            error.append(e)
        finally:
            put(_SENTINEL)

    t = threading.Thread(target=produce, daemon=True,
                         name="sparkdl-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                if error:
                    raise error[0]
                return
            yield item
    finally:
        stop.set()
