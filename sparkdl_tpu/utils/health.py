"""Shared ready/degraded health state machine (ISSUE 4 / 8 / 9).

``Server.health()`` introduced the contract — ``ready`` <-> ``degraded``
driven by failure/success outcomes, a ``last_error`` that survives
recovery for post-mortems, and a bounded ``transitions`` history so a
``degraded -> ready`` recovery is observable after a point-in-time poll
would have raced past it.  The streaming runner mirrors the same
contract for source stalls, and the fleet aggregates it across models,
so the state machine lives here once and every surface delegates to it.

ISSUE 9 adds the payload side of the contract: every ``health()`` in
the stack (``Server``, ``Fleet``, ``StreamScorer``) now BUILDS its
snapshot through :func:`health_payload` / :meth:`HealthTracker.payload`
— one schema (``live``/``state``/``last_error``/``transitions`` plus
caller extras) that the flight recorder and ``tools/blackbox.py`` parse
as a single contract (contract-tested from all three callers).  Each
actual transition also emits a ``health.degraded``/``health.ready``
flight event (outside the tracker lock), and a ready->degraded flip is
the flight recorder's durable-dump trigger — degradation is exactly
when the next instants stop being trustworthy.

Timestamps are ``time.monotonic`` (never wall clock) — they exist to
ORDER transitions and measure gaps, which wall-clock adjustments would
corrupt (graftlint SDL006's rationale).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Optional

from sparkdl_tpu.analysis.lockcheck import named_lock
from sparkdl_tpu.obs.flight import emit as flight_emit

#: The shared state vocabulary every health() surface speaks.
HEALTH_STATES = ("ready", "degraded", "closed")


def health_payload(*, live: bool, state: str,
                   last_error: Optional[Dict[str, Any]] = None,
                   transitions: Optional[list] = None,
                   **extra: Any) -> Dict[str, Any]:
    """THE ``health()`` schema: ``{"live", "state", "last_error",
    "transitions"}`` plus caller-specific extras (``breaker`` for the
    server, ``watermark``/``lag_s`` for the stream, ``models`` for the
    fleet, ``slo`` when an engine is attached).  Extras may never
    shadow a core key, and ``state`` must come from
    :data:`HEALTH_STATES` — the single contract ``blackbox`` parses."""
    if state not in HEALTH_STATES:
        raise ValueError(f"health state must be one of {HEALTH_STATES}, "
                         f"got {state!r}")
    payload: Dict[str, Any] = {
        "live": bool(live),
        "state": state,
        "last_error": last_error,
        "transitions": list(transitions or []),
    }
    for k, v in extra.items():
        if k in payload:
            raise ValueError(f"health extra field {k!r} collides with a "
                             f"core contract key")
        payload[k] = v
    return payload


class HealthTracker:
    """The ready/degraded half of a ``health()`` snapshot.

    Owners layer their own overrides on top (``closed``, breaker-open,
    watermark lag) exactly as ``Server.health()`` always has — this
    class only owns the failure/success-driven core state.  ``name``
    labels the tracker in flight events (defaults to ``lock_name``,
    which every owner already picks uniquely).
    """

    def __init__(self, lock_name: str, maxlen: int = 64,
                 name: Optional[str] = None):
        self._lock = named_lock(lock_name)
        self.name = name if name is not None else lock_name
        self._state = "ready"
        self._transitions: deque = deque(
            [{"state": "ready", "t_monotonic": round(time.monotonic(), 3)}],
            maxlen=maxlen)
        self._last_error: Optional[Dict[str, Any]] = None

    def note_failure(self, exc: BaseException) -> None:
        """Record one failed attempt: state -> degraded (idempotent —
        repeated failures extend the episode, not the history).  An
        actual transition emits ``health.degraded`` into the flight
        recorder AFTER the lock is released (the recorder may fsync a
        durable dump on this exact event)."""
        transitioned = False
        with self._lock:
            self._last_error = {
                "type": type(exc).__name__,
                "error": str(exc)[:300],
                "t_monotonic": round(time.monotonic(), 3),
            }
            if self._state != "degraded":
                self._state = "degraded"
                self._transitions.append(
                    {"state": "degraded",
                     "t_monotonic": round(time.monotonic(), 3)})
                transitioned = True
        if transitioned:
            flight_emit("health.degraded", tracker=self.name,
                        error=type(exc).__name__)

    def note_success(self) -> None:
        """Record recovery: state -> ready (no-op while already ready,
        so steady-state success never grows the transition history)."""
        transitioned = False
        with self._lock:
            if self._state != "ready":
                self._state = "ready"
                self._transitions.append(
                    {"state": "ready",
                     "t_monotonic": round(time.monotonic(), 3)})
                transitioned = True
        if transitioned:
            flight_emit("health.ready", tracker=self.name)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable ``{"state", "last_error", "transitions"}``
        (copies — callers may mutate freely)."""
        with self._lock:
            return {
                "state": self._state,
                "last_error": (dict(self._last_error)
                               if self._last_error else None),
                "transitions": list(self._transitions),
            }

    def payload(self, *, live: bool,
                state_override: Optional[str] = None,
                **extra: Any) -> Dict[str, Any]:
        """The tracker's state rendered through :func:`health_payload`.
        ``state_override`` replaces the tracker's own state (the
        owner's breaker-open/lag/closed layering); extras ride through
        verbatim."""
        snap = self.snapshot()
        return health_payload(
            live=live,
            state=state_override if state_override is not None
            else snap["state"],
            last_error=snap["last_error"],
            transitions=snap["transitions"],
            **extra)
