"""Shared ready/degraded health state machine (ISSUE 4 / ISSUE 8).

``Server.health()`` introduced the contract — ``ready`` <-> ``degraded``
driven by failure/success outcomes, a ``last_error`` that survives
recovery for post-mortems, and a bounded ``transitions`` history so a
``degraded -> ready`` recovery is observable after a point-in-time poll
would have raced past it.  The streaming runner mirrors the same
contract for source stalls, so the state machine lives here once and
both surfaces delegate to it.

Timestamps are ``time.monotonic`` (never wall clock) — they exist to
ORDER transitions and measure gaps, which wall-clock adjustments would
corrupt (graftlint SDL006's rationale).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Optional

from sparkdl_tpu.analysis.lockcheck import named_lock


class HealthTracker:
    """The ready/degraded half of a ``health()`` snapshot.

    Owners layer their own overrides on top (``closed``, breaker-open,
    watermark lag) exactly as ``Server.health()`` always has — this
    class only owns the failure/success-driven core state.
    """

    def __init__(self, lock_name: str, maxlen: int = 64):
        self._lock = named_lock(lock_name)
        self._state = "ready"
        self._transitions: deque = deque(
            [{"state": "ready", "t_monotonic": round(time.monotonic(), 3)}],
            maxlen=maxlen)
        self._last_error: Optional[Dict[str, Any]] = None

    def note_failure(self, exc: BaseException) -> None:
        """Record one failed attempt: state -> degraded (idempotent —
        repeated failures extend the episode, not the history)."""
        with self._lock:
            self._last_error = {
                "type": type(exc).__name__,
                "error": str(exc)[:300],
                "t_monotonic": round(time.monotonic(), 3),
            }
            if self._state != "degraded":
                self._state = "degraded"
                self._transitions.append(
                    {"state": "degraded",
                     "t_monotonic": round(time.monotonic(), 3)})

    def note_success(self) -> None:
        """Record recovery: state -> ready (no-op while already ready,
        so steady-state success never grows the transition history)."""
        with self._lock:
            if self._state != "ready":
                self._state = "ready"
                self._transitions.append(
                    {"state": "ready",
                     "t_monotonic": round(time.monotonic(), 3)})

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable ``{"state", "last_error", "transitions"}``
        (copies — callers may mutate freely)."""
        with self._lock:
            return {
                "state": self._state,
                "last_error": (dict(self._last_error)
                               if self._last_error else None),
                "transitions": list(self._transitions),
            }
