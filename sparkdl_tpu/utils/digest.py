"""Content digests — THE one sha256-over-dtype/shape/bytes helper.

Three layers had independently grown the same digest (ISSUE 11):
``streaming.source.content_chunk_id`` (chunk identity — the
exactly-once dedupe key), ``streaming.runner``'s artifact digest (the
torn/foreign-file check ``assemble_outputs`` verifies), and now the
serving result cache's input/output keys.  One implementation here
means "same bytes" can never mean three subtly different things:
every digest covers dtype, shape, AND bytes, so two arrays that merely
reinterpret each other's buffers (f32 vs u8 views, [2, 6] vs [3, 4])
never collide.

Import-light on purpose (numpy + hashlib only; jax is imported lazily
and only for pytree payloads): ``streaming.source`` and the journal
pull this in on cold start, where a jax import would re-initialize the
backend.
"""

from __future__ import annotations

import hashlib
from typing import Any

import numpy as np


def array_digest(arr: Any) -> str:
    """Full sha256 hexdigest over one array's dtype/shape/bytes.

    The core ``content_chunk_id`` has used since ISSUE 8 (truncated to
    16 hex chars there) and ``assemble_outputs`` verifies artifacts
    against (full width).  Stable across processes and crashes: two
    reads of the same payload always agree; two payloads differing in
    dtype, shape, or any byte never do."""
    a = np.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(str(a.dtype).encode())
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.hexdigest()


def content_chunk_id(offset: int, payload: Any) -> str:
    """Stable content-addressed chunk id: zero-padded offset (so ids
    sort in stream order) + sha256 over dtype/shape/bytes.  Two reads of
    the same chunk — across processes, before and after a crash — always
    agree; two different payloads at the same offset never do.

    (Moved here from ``streaming.source`` by ISSUE 11 so the serving
    cache shares the digest core; the id string is bit-for-bit what the
    source has produced since ISSUE 8 — journals written before the
    move replay cleanly.)"""
    return f"{offset:08d}-{array_digest(payload)[:16]}"


def content_digest(payload: Any) -> str:
    """Digest of an arbitrary payload: a single array digests via
    :func:`array_digest` (identical string — the serving cache and the
    streaming layer agree on single-array payloads by construction); a
    pytree of arrays digests each leaf plus the tree structure, so two
    pytrees collide only when every leaf AND the structure match."""
    if isinstance(payload, np.ndarray) or np.isscalar(payload):
        return array_digest(payload)
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(payload)
    h = hashlib.sha256()
    h.update(str(treedef).encode())
    for leaf in leaves:
        h.update(array_digest(leaf).encode())
    return h.hexdigest()
