"""Utilities: logging, metrics, profiling."""

from sparkdl_tpu.utils.logging import get_logger
from sparkdl_tpu.utils.metrics import Metrics, StepTimer, throughput_counter

__all__ = ["get_logger", "Metrics", "StepTimer", "throughput_counter"]
