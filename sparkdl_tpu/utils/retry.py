"""Retry orchestration for fits.

SURVEY.md §5 "failure detection / elastic": the reference delegated failure
recovery to Spark task retry (idempotent per-paramMap tasks, straggler
re-execution).  The TPU analog is retry-at-the-orchestration-layer composed
with the framework's epoch-granular checkpointing: a fit configured with
``fitParams={"checkpoint_dir": ...}`` resumes at the last saved epoch, so a
retried fit repeats only the epoch that failed — the same
unit-of-reexecution economics as a retried Spark task.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Sequence, Tuple, Type

from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


# Deterministic failures: retrying re-trains to the identical error.
# FloatingPointError is the SPARKDL_DEBUG_NANS fail-fast — retrying it
# would re-diverge max_retries times, defeating the flag; ValueError /
# TypeError are param/shape validation.
NON_RETRYABLE: Tuple[Type[BaseException], ...] = (
    FloatingPointError, ValueError, TypeError)


def with_retries(fn: Callable[[], Any], *, max_retries: int = 2,
                 retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                 non_retryable: Tuple[Type[BaseException], ...]
                 = NON_RETRYABLE,
                 backoff_seconds: float = 0.0,
                 on_retry: Optional[Callable[[int, BaseException], None]]
                 = None) -> Any:
    """Run ``fn()`` with up to ``max_retries`` re-executions.

    ``KeyboardInterrupt``/``SystemExit`` always propagate, as does
    anything in ``non_retryable`` (deterministic failures — see
    NON_RETRYABLE; pass ``non_retryable=()`` to retry everything).
    ``on_retry`` (attempt_index, exception) runs before each re-execution
    — the hook for external health checks or device re-initialization.
    """
    attempts = max(0, int(max_retries)) + 1
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            return fn()
        except non_retryable:
            raise
        except retry_on as e:
            last = e
            if attempt == attempts - 1:
                break
            logger.warning("attempt %d/%d failed (%s: %s); retrying",
                           attempt + 1, attempts, type(e).__name__, e)
            if on_retry is not None:
                on_retry(attempt, e)
            if backoff_seconds:
                time.sleep(backoff_seconds * (2 ** attempt))
    assert last is not None
    raise last


def fit_with_retries(estimator, dataset, params=None, *,
                     max_retries: int = 2,
                     non_retryable: Tuple[Type[BaseException], ...]
                     = NON_RETRYABLE,
                     backoff_seconds: float = 0.0,
                     on_retry: Optional[Callable] = None):
    """``estimator.fit(dataset, params)`` with retry orchestration.

    Pair with ``fitParams={"checkpoint_dir": ...}`` so each retry RESUMES
    from the newest epoch checkpoint instead of restarting: transient
    failures (preemption, host OOM, flaky storage) then cost one epoch of
    recompute.  Without a checkpoint_dir each retry restarts the fit from
    scratch (still correct — fits are idempotent like the reference's
    Spark tasks — just more expensive).
    """
    return with_retries(lambda: estimator.fit(dataset, params),
                        max_retries=max_retries,
                        non_retryable=non_retryable,
                        backoff_seconds=backoff_seconds,
                        on_retry=on_retry)
