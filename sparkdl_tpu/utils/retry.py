"""Retry orchestration for fits.

SURVEY.md §5 "failure detection / elastic": the reference delegated failure
recovery to Spark task retry (idempotent per-paramMap tasks, straggler
re-execution).  The TPU analog is retry-at-the-orchestration-layer composed
with the framework's epoch-granular checkpointing: a fit configured with
``fitParams={"checkpoint_dir": ...}`` resumes at the last saved epoch, so a
retried fit repeats only the epoch that failed — the same
unit-of-reexecution economics as a retried Spark task.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Optional, Sequence, Tuple, Type

from sparkdl_tpu.obs.flight import emit as flight_emit
from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


# Deterministic failures: retrying re-trains to the identical error.
# FloatingPointError is the SPARKDL_DEBUG_NANS fail-fast — retrying it
# would re-diverge max_retries times, defeating the flag; ValueError /
# TypeError are param/shape validation.
NON_RETRYABLE: Tuple[Type[BaseException], ...] = (
    FloatingPointError, ValueError, TypeError)


def backoff_delay(attempt: int, backoff_seconds: float,
                  max_backoff_seconds: Optional[float] = None,
                  jitter: float = 0.0,
                  rng: Optional[random.Random] = None) -> float:
    """The sleep before re-execution ``attempt`` (0-based): exponential
    ``backoff_seconds * 2**attempt``, de-synchronized by ``jitter``
    (each delay is scaled by a uniform draw from ``[1 - jitter, 1]`` so
    a fleet of retriers never thunders in lockstep), then HARD-capped at
    ``max_backoff_seconds`` — the cap applies after jitter, so the bound
    holds no matter the draw (pinned by the unit test)."""
    delay = backoff_seconds * (2 ** attempt)
    if jitter:
        j = min(1.0, max(0.0, float(jitter)))
        delay *= 1.0 - j * (rng or random).random()
    if max_backoff_seconds is not None:
        delay = min(delay, max_backoff_seconds)
    return max(0.0, delay)


def with_retries(fn: Callable[[], Any], *, max_retries: int = 2,
                 retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                 non_retryable: Tuple[Type[BaseException], ...]
                 = NON_RETRYABLE,
                 backoff_seconds: float = 0.0,
                 max_backoff_seconds: Optional[float] = None,
                 jitter: float = 0.0,
                 on_retry: Optional[Callable[[int, BaseException], None]]
                 = None) -> Any:
    """Run ``fn()`` with up to ``max_retries`` re-executions.

    ``KeyboardInterrupt``/``SystemExit`` always propagate, as does
    anything in ``non_retryable`` (deterministic failures — see
    NON_RETRYABLE; pass ``non_retryable=()`` to retry everything).
    ``on_retry`` (attempt_index, exception) runs before each re-execution
    — the hook for external health checks or device re-initialization.

    Backoff is exponential in ``backoff_seconds``, optionally jittered
    (``jitter`` in [0, 1]: each delay scaled by a uniform draw from
    ``[1 - jitter, 1]``) and BOUNDED by ``max_backoff_seconds`` — an
    unbounded ``backoff * 2**attempt`` turns a large retry budget into
    minutes of dead air; the cap keeps worst-case added latency
    ``<= max_retries * max_backoff_seconds`` (see :func:`backoff_delay`).
    """
    attempts = max(0, int(max_retries)) + 1
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            return fn()
        except non_retryable:
            raise
        except retry_on as e:
            last = e
            if attempt == attempts - 1:
                break
            logger.warning("attempt %d/%d failed (%s: %s); retrying",
                           attempt + 1, attempts, type(e).__name__, e)
            flight_emit("retry.attempt", attempt=attempt + 1,
                        of=attempts, error=type(e).__name__)
            if on_retry is not None:
                on_retry(attempt, e)
            if backoff_seconds:
                time.sleep(backoff_delay(attempt, backoff_seconds,
                                         max_backoff_seconds, jitter))
    assert last is not None
    raise last


def fit_with_retries(estimator, dataset, params=None, *,
                     max_retries: int = 2,
                     non_retryable: Tuple[Type[BaseException], ...]
                     = NON_RETRYABLE,
                     backoff_seconds: float = 0.0,
                     max_backoff_seconds: Optional[float] = None,
                     jitter: float = 0.0,
                     on_retry: Optional[Callable] = None):
    """``estimator.fit(dataset, params)`` with retry orchestration.

    Pair with ``fitParams={"checkpoint_dir": ...}`` so each retry RESUMES
    from the newest epoch checkpoint instead of restarting: transient
    failures (preemption, host OOM, flaky storage) then cost one epoch of
    recompute.  Without a checkpoint_dir each retry restarts the fit from
    scratch (still correct — fits are idempotent like the reference's
    Spark tasks — just more expensive).
    """
    return with_retries(lambda: estimator.fit(dataset, params),
                        max_retries=max_retries,
                        non_retryable=non_retryable,
                        backoff_seconds=backoff_seconds,
                        max_backoff_seconds=max_backoff_seconds,
                        jitter=jitter,
                        on_retry=on_retry)
