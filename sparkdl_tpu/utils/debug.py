"""Numerical-safety instrumentation (opt-in).

SURVEY.md §5 "race detection / sanitizers": the reference had none in-repo
(its closest analog was ``IsolatedSession`` preventing global-graph
pollution); JAX's functional model removes that bug class, so the analog
worth shipping is NUMERICAL sanitizing — the silent failure mode of
accelerator training:

* ``enable_nan_checks()`` — turns on ``jax_debug_nans``: any NaN produced
  inside a jitted program re-runs the offending op eagerly and raises at
  the op that made it (XLA's equivalent of a sanitizer stack trace).
* ``warn_or_raise_nonfinite_loss(step_losses, epoch)`` — what the train
  loops call at each EPOCH boundary (per-step host syncs would stall the
  dispatch pipeline): raises naming the first diverged step when checks
  are enabled, warns otherwise.  For op-level localization within the
  step, enable_nan_checks().
* ``check_finite(tree)`` — host-side assert over any pytree (params,
  gradients, features) for ad-hoc use.
* ``checks_enabled()`` — gated by ``enable_checks()`` or the
  ``SPARKDL_DEBUG_NANS=1`` environment variable (set it before launching;
  no code change needed).

Donation safety: the train steps donate params/opt_state buffers
(``donate_argnums``); with checks enabled the loop also verifies donated
inputs are not re-read after the step — jax already errors on access to a
donated buffer, so the check here is simply that the error surfaces
instead of being swallowed (nothing to do beyond not catching it).
"""

from __future__ import annotations

import os
from typing import Any

from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_ENABLED: bool = False
_NAN_DEBUG_SET_BY_US: bool = False


def checks_enabled() -> bool:
    return _ENABLED or os.environ.get("SPARKDL_DEBUG_NANS", "") not in (
        "", "0", "false", "False")


def enable_checks(nan_debug: bool = True) -> None:
    """Turn on numerical checks for this process.

    ``nan_debug=True`` additionally flips ``jax_debug_nans`` — precise
    NaN localization at ~2x step cost; leave False to keep only the cheap
    per-step finite-loss assertion."""
    global _ENABLED
    _ENABLED = True
    if nan_debug:
        enable_nan_checks()


def disable_checks() -> None:
    """Turn checks off; resets ``jax_debug_nans`` only if THIS module set
    it (a user's own jax.config setting is never clobbered)."""
    global _ENABLED, _NAN_DEBUG_SET_BY_US
    _ENABLED = False
    if _NAN_DEBUG_SET_BY_US:
        import jax

        jax.config.update("jax_debug_nans", False)
        _NAN_DEBUG_SET_BY_US = False


def enable_nan_checks() -> None:
    global _NAN_DEBUG_SET_BY_US
    import jax

    if not jax.config.jax_debug_nans:
        # only claim ownership if WE flipped it: a user's own pre-existing
        # setting must survive a later disable_checks()
        jax.config.update("jax_debug_nans", True)
        _NAN_DEBUG_SET_BY_US = True
    logger.info("jax_debug_nans enabled: NaNs raise at the producing op")


def warn_or_raise_nonfinite_loss(step_losses, epoch: int) -> None:
    """Epoch-boundary divergence check for the train loops.

    ``step_losses``: the epoch's per-step losses as host floats.  Raises
    (checks enabled) naming the first non-finite step, or warns."""
    import numpy as np

    arr = np.asarray(step_losses, dtype=np.float64)
    if arr.size == 0 or np.isfinite(arr).all():
        return
    first_bad = int(np.nonzero(~np.isfinite(arr))[0][0])
    msg = (f"non-finite loss at epoch {epoch + 1} (first at step "
           f"{first_bad + 1}/{arr.size})")
    if checks_enabled():
        raise FloatingPointError(
            msg + "; utils.debug.enable_nan_checks() localizes the "
                  "producing op")
    logger.warning("%s — set SPARKDL_DEBUG_NANS=1 to fail fast", msg)


def check_finite(tree: Any, what: str = "value") -> None:
    """Raise FloatingPointError if any leaf holds a non-finite value."""
    import numpy as np

    import jax

    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            bad.append("/".join(str(k) for k in path) or "<root>")
    if bad:
        raise FloatingPointError(
            f"non-finite {what}: {bad[:5]}{'...' if len(bad) > 5 else ''} "
            f"(enable_nan_checks() localizes the producing op)")
