"""Thread-safe bounded FIFO cache for compiled-program registries.

One implementation for the train-step and engine jit caches: get is
lock-free (GIL-atomic dict read — a stale miss only costs a recompile),
put/clear lock so concurrent workers (fitMultiple's mesh-slice fan-out)
cannot race the eviction loop.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from sparkdl_tpu.analysis.lockcheck import named_lock


class BoundedCache:
    def __init__(self, cap: int):
        self.cap = int(cap)
        self._data: Dict[Any, Any] = {}
        self._lock = named_lock("utils.cache.bounded")

    def get(self, key) -> Optional[Any]:
        return self._data.get(key)

    def put(self, key, value) -> None:
        with self._lock:
            if key in self._data:
                # racing double-compile of the same key: overwrite in
                # place, never evict an unrelated live entry for it
                self._data[key] = value
                return
            while len(self._data) >= self.cap:
                self._data.pop(next(iter(self._data)), None)
            self._data[key] = value

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key) -> bool:
        return key in self._data


class ByteBoundedLRU:
    """Thread-safe LRU bounded by total payload BYTES (not entry count).

    Backs the estimator's per-URI decode cache (ADVICE r3: unbounded
    growth across datasets sharing a loader): entries report their size
    via ``sizeof``; inserts evict least-recently-used entries until the
    total fits ``cap_bytes``.  An entry larger than the whole cap is
    served but never stored."""

    def __init__(self, cap_bytes: int, sizeof=None):
        import sys

        self.cap_bytes = int(cap_bytes)
        # nbytes for array payloads; getsizeof otherwise, so the cap is
        # never silently unenforced for non-array values.
        self._sizeof = sizeof or (
            lambda v: getattr(v, "nbytes", None) or sys.getsizeof(v))
        self._data: Dict[Any, Any] = {}
        self._bytes = 0
        self._lock = named_lock("utils.cache.lru")

    def get(self, key, default=None):
        with self._lock:
            if key not in self._data:
                return default
            val = self._data.pop(key)
            self._data[key] = val  # move to most-recent position
            return val

    def put(self, key, value) -> None:
        size = self._sizeof(value)
        with self._lock:
            if key in self._data:
                self._bytes -= self._sizeof(self._data.pop(key))
            if size > self.cap_bytes:
                return
            while self._data and self._bytes + size > self.cap_bytes:
                oldest = next(iter(self._data))  # insertion order = LRU order
                self._bytes -= self._sizeof(self._data.pop(oldest))
            self._data[key] = value
            self._bytes += size

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self._bytes = 0

    @property
    def total_bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key) -> bool:
        return key in self._data
