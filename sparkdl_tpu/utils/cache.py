"""Thread-safe bounded FIFO cache for compiled-program registries.

One implementation for the train-step and engine jit caches: get is
lock-free (GIL-atomic dict read — a stale miss only costs a recompile),
put/clear lock so concurrent workers (fitMultiple's mesh-slice fan-out)
cannot race the eviction loop.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional


class BoundedCache:
    def __init__(self, cap: int):
        self.cap = int(cap)
        self._data: Dict[Any, Any] = {}
        self._lock = threading.Lock()

    def get(self, key) -> Optional[Any]:
        return self._data.get(key)

    def put(self, key, value) -> None:
        with self._lock:
            if key in self._data:
                # racing double-compile of the same key: overwrite in
                # place, never evict an unrelated live entry for it
                self._data[key] = value
                return
            while len(self._data) >= self.cap:
                self._data.pop(next(iter(self._data)), None)
            self._data[key] = value

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key) -> bool:
        return key in self._data
