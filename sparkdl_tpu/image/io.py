"""Host-side image decode / resize / file ingestion.

Replaces ``imageIO._decodeImage`` / ``readImagesWithCustomFn`` / ``filesToDF``
/ ``createResizeImageUDF`` and the Scala ``ImageUtils.resizeImage``.  Decode
runs on the host (PIL) because the TPU has no decode engine; the output of
this layer is either image-struct rows (for the DataFrame API) or dense
numpy batches (for the device pipeline).
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Callable, Iterable, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from sparkdl_tpu.image.schema import (
    imageArrayToStruct,
    imageSchema,
    imageStructToArray,
    imageTypeByMode,
)


def PIL_decode(raw_bytes: bytes) -> Optional[np.ndarray]:
    """Decode compressed image bytes to a [H,W,3] uint8 **BGR** array.

    Counterpart of ``imageIO.PIL_decode``/``_decodeImage``: undecodable input
    yields ``None`` (the reference drops/nulls such rows rather than failing
    the job).
    """
    import io as _io

    from PIL import Image

    try:
        img = Image.open(_io.BytesIO(raw_bytes))
        img = img.convert("RGB")
        rgb = np.asarray(img, dtype=np.uint8)
    # graftlint: allow=SDL003 reason=PIL raises a zoo of types for bad bytes; None rides the ok-mask drop-to-null contract
    except Exception:
        return None
    return np.ascontiguousarray(rgb[:, :, ::-1])  # RGB -> BGR (OpenCV order)


def decodeImage(raw_bytes: bytes, origin: str = "") -> Optional[dict]:
    """Decode bytes into an image struct dict, or None on failure."""
    arr = PIL_decode(raw_bytes)
    if arr is None:
        return None
    return imageArrayToStruct(arr, origin=origin)


def resizeImage(array: np.ndarray, height: int, width: int) -> np.ndarray:
    """Bilinear resize of a [H,W,C] uint8/float32 array on the host.

    Counterpart of the Scala ``ImageUtils.resizeImage`` (java.awt bilinear) and
    the TF resize the Python path used — parity is tolerance-based, matching
    the reference's own tests (they assert closeness, not bit-equality, across
    their two resize backends).
    """
    from PIL import Image

    if array.shape[0] == height and array.shape[1] == width:
        return array
    dtype = array.dtype
    if dtype == np.uint8:
        img = Image.fromarray(array if array.shape[2] != 1 else array[:, :, 0])
        out = np.asarray(img.resize((width, height), Image.BILINEAR), dtype=np.uint8)
        if out.ndim == 2:
            out = out[:, :, None]
        return out
    # float path: resize channel-planes via PIL 'F' mode
    planes = [
        np.asarray(
            Image.fromarray(array[:, :, c].astype(np.float32), mode="F")
            .resize((width, height), Image.BILINEAR))
        for c in range(array.shape[2])
    ]
    return np.stack(planes, axis=2).astype(dtype)


def createResizeImageUDF(size: Sequence[int]) -> Callable[[dict], dict]:
    """Return a row-level function image-struct -> resized image-struct.

    Counterpart of ``imageIO.createResizeImageUDF``; with our DataFrame layer
    it is applied via ``DataFrame.withColumn(map_struct=...)`` and, when a real
    pyspark is present, can be wrapped with ``pyspark.sql.functions.udf``.
    """
    if len(size) != 2:
        raise ValueError(f"New image size should have format [height, width], got {size}")
    height, width = int(size[0]), int(size[1])

    def _resize(row: Optional[dict]) -> Optional[dict]:
        if row is None:
            return None
        arr = imageStructToArray(row)
        out = resizeImage(arr, height, width)
        return imageArrayToStruct(out, origin=row.get("origin", ""))

    return _resize


def structToModelInput(struct: dict, height: int, width: int) -> np.ndarray:
    """Image struct -> [h,w,3] uint8 **RGB** array resized for a model.

    Handles channel normalization the way the reference's converter subgraph
    did (``graph/pieces.py — buildSpImageConverter`` BGR->RGB swap):
    grayscale replicates to 3 channels, BGRA drops alpha, BGR flips to RGB.
    """
    arr = imageStructToArray(struct)
    if arr.dtype != np.uint8:
        arr = np.clip(arr, 0, 255).astype(np.uint8)
    c = arr.shape[2]
    if c == 1:
        arr = np.repeat(arr, 3, axis=2)
    elif c == 4:
        arr = arr[:, :, :3]          # BGRA -> BGR
    arr = resizeImage(arr, height, width)
    return arr[:, :, ::-1]           # BGR -> RGB


def _native_io_preferred() -> bool:
    """Use the native core whenever it built: measured on a 1-vCPU host
    (tools/native_thread_scaling.py, PERF.md) it beats serial PIL even
    single-threaded (232 vs 192 img/s at 500x375 JPEG -> 299x299), and it
    scales with real threads (no GIL) on multi-core hosts."""
    import sparkdl_tpu.native as native

    return native.native_available()


def decodeResizeBatch(blobs: Sequence[bytes], height: int, width: int
                      ) -> "tuple[np.ndarray, np.ndarray]":
    """Fused decode+resize of encoded images into a [N,h,w,3] uint8 **RGB**
    batch + ok-mask — the fast path from raw files straight to model input
    (skips the full-size intermediate the struct path materializes).

    Uses the native threaded core (libjpeg DCT prescale + libpng) when
    available and useful; PIL otherwise.  Undecodable rows: ok=False,
    zeroed pixels (drop-to-null upstream).

    Fault site ``io.decode`` (per row, :mod:`sparkdl_tpu.faults`): an
    injected decode error mid-stream must ride the SAME drop-to-null
    contract as a genuinely corrupt blob — the row's ok flag goes False
    and the stream continues.  A plan with ``io.decode`` rules routes
    around the native core AND the decode thread pool, so the per-row
    site is reached in deterministic row order (``at=``/``every=``
    schedules count calls; pool scheduling would make the dropped row
    arbitrary).
    """
    from sparkdl_tpu import faults as _faults

    io_faults = _faults.has_rules("io.decode")
    if not io_faults and _native_io_preferred():
        import sparkdl_tpu.native as native

        result = native.decode_resize_batch(blobs, height, width)
        if result is not None:
            return result
    out = np.zeros((len(blobs), height, width, 3), dtype=np.uint8)
    ok = np.zeros(len(blobs), dtype=bool)

    def one(i_blob):
        i, blob = i_blob
        try:
            _faults.inject("io.decode", row=i)
        except _faults.InjectedFault:
            return  # simulated corrupt row: ok stays False (drop-to-null)
        arr = PIL_decode(blob)  # BGR or None
        if arr is None:
            return
        if arr.shape[2] == 1:
            arr = np.repeat(arr, 3, axis=2)
        out[i] = resizeImage(arr, height, width)[:, :, ::-1]
        ok[i] = True

    if len(blobs) >= 4 and not io_faults:
        list(_io_executor().map(one, enumerate(blobs)))
    else:
        for pair in enumerate(blobs):
            one(pair)
    return out, ok


def filesToModelBatch(paths: Sequence[str], height: int, width: int
                      ) -> "tuple[np.ndarray, np.ndarray]":
    """Read+decode+resize files into a model-ready uint8 RGB batch."""
    blobs = []
    for p in paths:
        try:
            with open(p, "rb") as fh:
                blobs.append(fh.read())
        except OSError:
            blobs.append(b"")
    return decodeResizeBatch(blobs, height, width)


_IO_EXECUTOR = None


def _io_executor():
    """Shared host-prep thread pool — reused across batches (spawning a pool
    per device batch would put thread startup on the feed-the-chip path)."""
    global _IO_EXECUTOR
    if _IO_EXECUTOR is None:
        from concurrent.futures import ThreadPoolExecutor

        _IO_EXECUTOR = ThreadPoolExecutor(
            min(16, (os.cpu_count() or 4)), thread_name_prefix="sparkdl-io")
    return _IO_EXECUTOR


def structsToBatch(structs: Sequence[dict], height: int, width: int,
                   num_threads: Optional[int] = None) -> np.ndarray:
    """Decode+resize a sequence of image structs into one [N,h,w,3] uint8
    RGB batch.  Threaded: PIL releases the GIL during resize, and host-side
    prep is the throughput-critical path feeding the chip (SURVEY.md §7
    hard part #2)."""
    if len(structs) == 0:
        return np.zeros((0, height, width, 3), dtype=np.uint8)
    if _native_io_preferred() and len(structs) >= 4:
        import sparkdl_tpu.native as native

        def to_rgb(s):
            arr = imageStructToArray(s)
            if arr.dtype != np.uint8:
                arr = np.clip(arr, 0, 255).astype(np.uint8)
            c = arr.shape[2]
            if c == 1:
                arr = np.repeat(arr, 3, axis=2)
            elif c == 4:
                arr = arr[:, :, :3]
            return np.ascontiguousarray(arr[:, :, ::-1])  # BGR -> RGB

        batch = native.resize_batch_rgb(
            [to_rgb(s) for s in structs], height, width)
        if batch is not None:
            return batch
    if (num_threads is not None and num_threads <= 1) or len(structs) < 4:
        arrs = [structToModelInput(s, height, width) for s in structs]
    else:
        arrs = list(_io_executor().map(
            lambda s: structToModelInput(s, height, width), structs))
    return np.stack(arrs, axis=0)


def arrowStructsToBatch(column, height: int, width: int,
                        channel_order: str = "rgb", compact: bool = False
                        ) -> "tuple[np.ndarray, np.ndarray]":
    """Image-struct Arrow column -> ([N,h,w,3] uint8 batch, valid mask)
    WITHOUT materializing per-row Python dicts.

    This is the zero-copy replacement for ``to_pylist()`` +
    :func:`structsToBatch` on the UDF/scoring hot path: child arrays are
    read as numpy views over Arrow buffers, and each row's pixel block is
    sliced straight out of the binary child's value buffer.  When every
    valid row is already ``height x width`` uint8 BGR (the common case for a
    resized column), packing is one ~memcpy per row.  Chunked columns are
    packed chunk by chunk (never ``combine_chunks``, whose int32 binary
    offsets overflow past 2 GB of image bytes).

    ``channel_order``: "rgb" (default) swaps BGR struct bytes to RGB on the
    host; "bgr" returns the struct's native byte order untouched — the fast
    feed for pipelines that fold the channel swap into the device program
    (as the reference's converter subgraph did: ``graph/pieces.py``
    buildSpImageConverter swapped BGR->RGB *inside* the graph).  Host cost
    measured at 299x299: ~0.01 ms/img for "bgr", ~0.25 ms/img for "rgb"
    (the swap is the only non-memcpy work).

    ``compact``: when True the batch holds ONLY the ok rows (in row order) —
    row ``k`` of the batch is the ``k``-th True of the mask — so callers
    feeding an engine skip both the null-row zero fill and a second
    valid-rows copy.  When False (default) the batch is row-aligned with
    the column and failed rows are zeroed, matching the reference's
    scoring-path null contract.
    """
    if channel_order not in ("rgb", "bgr"):
        raise ValueError(f"channel_order must be 'rgb' or 'bgr', "
                         f"got {channel_order!r}")
    if isinstance(column, pa.ChunkedArray):
        chunks = column.chunks
        if len(chunks) == 1:
            column = chunks[0]
        else:
            parts = [arrowStructsToBatch(c, height, width,
                                         channel_order=channel_order,
                                         compact=compact)
                     for c in chunks if len(c)]
            if not parts:
                return (np.zeros((0, height, width, 3), dtype=np.uint8),
                        np.zeros(0, dtype=bool))
            return (np.concatenate([p[0] for p in parts], axis=0),
                    np.concatenate([p[1] for p in parts], axis=0))
    n = len(column)
    ok = np.zeros(n, dtype=bool)
    if n == 0:
        return np.zeros((0, height, width, 3), dtype=np.uint8), ok
    valid = np.asarray(column.is_valid())
    idx = np.nonzero(valid)[0]
    nrows = len(idx) if compact else n
    if len(idx) == 0:
        return np.zeros((nrows, height, width, 3), dtype=np.uint8), ok
    # Child arrays: pyarrow's .field() applies the parent struct's
    # offset/length, so sliced columns are handled.
    heights = np.asarray(column.field("height"))
    widths = np.asarray(column.field("width"))
    channels = np.asarray(column.field("nChannels"))
    modes = np.asarray(column.field("mode"))
    data = column.field("data")
    # Binary child buffers: [validity, int32 offsets, values].  The child
    # carries its own offset when the parent was sliced.
    bufs = data.buffers()
    offsets = np.frombuffer(bufs[1], dtype=np.int32)[
        data.offset:data.offset + n + 1]
    values = np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] is not None \
        else np.zeros(0, dtype=np.uint8)

    # slot[k]: output row for source row idx[k]
    slots = np.arange(len(idx)) if compact else idx
    uniform = (
        np.all(heights[idx] == height) and np.all(widths[idx] == width)
        and np.all(channels[idx] == 3) and np.all(modes[idx] == 16)  # CV_8UC3
        and np.all((offsets[idx + 1] - offsets[idx]) == height * width * 3))
    if uniform:
        hw3 = height * width * 3
        # compact output is fully written -> skip the zero fill
        alloc = np.empty if compact else np.zeros
        if channel_order == "bgr":
            out = alloc((nrows, height, width, 3), dtype=np.uint8)
            for s, i in zip(slots, idx):  # pure memcpy per row
                out[s] = values[offsets[i]:offsets[i] + hw3].reshape(
                    height, width, 3)
        else:
            # memcpy rows, then one batch-level channel shuffle (3 strided
            # assigns beat a negative-stride copy ~3x on this host)
            # non-compact alloc is zeros, so null rows stay zeroed through
            # the shuffle; compact output has no null slots to zero
            tmp = alloc((nrows, height, width, 3), dtype=np.uint8)
            for s, i in zip(slots, idx):
                tmp[s] = values[offsets[i]:offsets[i] + hw3].reshape(
                    height, width, 3)
            out = np.empty_like(tmp)
            out[..., 0] = tmp[..., 2]
            out[..., 1] = tmp[..., 1]
            out[..., 2] = tmp[..., 0]
        ok[idx] = True
        return out, ok

    # General path: per-row buffer views (still no dict round trip), then
    # the normal channel normalization + resize, threaded for large rows.
    out = np.zeros((nrows, height, width, 3), dtype=np.uint8)

    def one(si):
        s, i = si
        t = imageTypeByMode(int(modes[i]))
        h, w, c = int(heights[i]), int(widths[i]), int(channels[i])
        row = values[offsets[i]:offsets[i + 1]]
        arr = row.view(t.dtype) if t.dtype != "uint8" else row
        if arr.size != h * w * c:
            return
        arr = arr.reshape(h, w, c)
        if arr.dtype != np.uint8:
            arr = np.clip(arr, 0, 255).astype(np.uint8)
        if c == 1:
            arr = np.repeat(arr, 3, axis=2)
        elif c == 4:
            arr = arr[:, :, :3]
        resized = resizeImage(np.ascontiguousarray(arr), height, width)
        out[s] = resized if channel_order == "bgr" else resized[:, :, ::-1]
        ok[i] = True

    pairs = list(zip(slots, idx))
    if len(pairs) >= 4:
        list(_io_executor().map(one, pairs))
    else:
        for p in pairs:
            one(p)
    if compact and not ok[idx].all():
        # a valid struct failed decode (size mismatch): drop its slot so
        # batch rows stay aligned with the True positions of the mask
        out = out[ok[idx]]
    return out, ok


def _list_files(path: str, recursive: bool = False) -> List[str]:
    """Expand a path/glob/directory into a sorted file list (deterministic
    ordering replaces Spark's nondeterministic partition enumeration)."""
    if os.path.isdir(path):
        pattern = os.path.join(path, "**" if recursive else "*")
        files = [f for f in _glob.glob(pattern, recursive=recursive)
                 if os.path.isfile(f)]
    else:
        files = [f for f in _glob.glob(path, recursive=recursive)
                 if os.path.isfile(f)]
    return sorted(files)


def iterFileBatches(path: str, batch_size: int = 64,
                    recursive: bool = False) -> Iterable[pa.RecordBatch]:
    """LAZILY read files under ``path`` into ``{filePath, fileData}`` record
    batches of ``batch_size`` rows — bytes for one batch at a time, never
    the whole directory (the streaming analog of the reference's
    ``sc.binaryFiles`` partition iterator).  Compose with any transformer's
    ``transformStream``."""
    files = _list_files(path, recursive=recursive)
    batch_size = max(1, int(batch_size))
    for off in range(0, len(files), batch_size):
        chunk = files[off:off + batch_size]
        data = []
        for f in chunk:
            with open(f, "rb") as fh:
                data.append(fh.read())
        yield pa.record_batch({
            "filePath": pa.array(chunk, type=pa.string()),
            "fileData": pa.array(data, type=pa.binary()),
        })


def iterImageBatches(path: str, batch_size: int = 64, recursive: bool = False,
                     decode_f: Callable[[bytes], Optional[np.ndarray]] = None
                     ) -> Iterable[pa.RecordBatch]:
    """LAZILY decode images under ``path`` into image-struct record batches
    (null structs for undecodable files).  Peak host memory is one batch of
    decoded images, not the dataset."""
    decode = decode_f if decode_f is not None else PIL_decode
    for rb in iterFileBatches(path, batch_size=batch_size,
                              recursive=recursive):
        files = rb.column(0).to_pylist()
        blobs = rb.column(1).to_pylist()
        structs = []
        for f, blob in zip(files, blobs):
            arr = decode(blob)
            if arr is None:
                structs.append(None)
            elif isinstance(arr, dict):
                structs.append(arr)
            else:
                structs.append(
                    imageArrayToStruct(np.asarray(arr), origin=f))
        yield pa.record_batch({"image": pa.array(structs, type=imageSchema)})


def filesToDF(path: str, numPartitions: Optional[int] = None,
              recursive: bool = False):
    """Read raw files into a DataFrame ``{filePath: str, fileData: binary}``.

    Counterpart of ``imageIO.filesToDF`` (which wraps ``sc.binaryFiles``).
    ``numPartitions`` controls batch chunking of the resulting frame.  For
    datasets that don't fit in host RAM, use :func:`iterFileBatches` +
    ``transformStream`` instead of materializing a frame.
    """
    from sparkdl_tpu.frame import DataFrame

    table = pa.Table.from_batches(
        list(iterFileBatches(path, batch_size=1 << 30, recursive=recursive)),
        schema=pa.schema([pa.field("filePath", pa.string()),
                          pa.field("fileData", pa.binary())]))
    df = DataFrame(table)
    if numPartitions:
        df = df.repartition(numPartitions)
    return df


def readImagesWithCustomFn(path: str, decode_f: Callable[[bytes], Optional[np.ndarray]],
                           numPartitions: Optional[int] = None,
                           recursive: bool = False):
    """Read images under ``path`` using a custom decoder into an image-struct
    DataFrame.  Counterpart of ``imageIO.readImagesWithCustomFn``; rows whose
    decode fails become null image structs (kept, so origins stay auditable).
    For datasets that don't fit in host RAM, use :func:`iterImageBatches` +
    ``transformStream`` instead of materializing a frame."""
    from sparkdl_tpu.frame import DataFrame

    schema = pa.schema([pa.field("image", imageSchema)])
    table = pa.Table.from_batches(
        list(iterImageBatches(path, batch_size=256, recursive=recursive,
                              decode_f=decode_f)),
        schema=schema)
    df = DataFrame(table)
    if numPartitions:
        df = df.repartition(numPartitions)
    return df


def readImages(path: str, numPartitions: Optional[int] = None,
               recursive: bool = False):
    """Read images with the default PIL decoder (BGR uint8)."""
    return readImagesWithCustomFn(path, PIL_decode, numPartitions, recursive)
