"""OpenCV-convention image struct schema.

Replaces the image representation of ``python/sparkdl/image/imageIO.py``
(``imageSchema``, ``imageArrayToStruct``, ``imageStructToArray`` and the
OpenCV mode tables ``CV_8UC1/3/4`` + float variants).  An image is a struct

    {origin: str, height: i32, width: i32, nChannels: i32, mode: i32,
     data: binary}

with ``data`` holding row-major bytes in **BGR** channel order for 3/4-channel
uint8 images (OpenCV convention, same as Spark 2.3's ImageSchema which the
reference's schema was upstreamed into).  Arrow struct arrays use exactly these
field names so frames interop with Spark's image source format.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np
import pyarrow as pa


@dataclass(frozen=True)
class ImageType:
    """One OpenCV storage mode: name, numeric mode code, channels, dtype."""
    name: str
    ord: int
    nChannels: int
    dtype: str

    @property
    def itemsize(self) -> int:
        return np.dtype(self.dtype).itemsize


# OpenCV type table — codes follow OpenCV's CV_<depth>C<channels> encoding
# (mode = depth + (channels-1)*8), matching the reference's table and Spark's
# ImageSchema.ocvTypes.
_SUPPORTED_TYPES = [
    ImageType("CV_8UC1", 0, 1, "uint8"),
    ImageType("CV_8UC3", 16, 3, "uint8"),
    ImageType("CV_8UC4", 24, 4, "uint8"),
    ImageType("CV_32FC1", 5, 1, "float32"),
    ImageType("CV_32FC3", 21, 3, "float32"),
    ImageType("CV_32FC4", 29, 4, "float32"),
]

ocvTypes: Dict[str, int] = {t.name: t.ord for t in _SUPPORTED_TYPES}
_BY_MODE: Dict[int, ImageType] = {t.ord: t for t in _SUPPORTED_TYPES}
_BY_NAME: Dict[str, ImageType] = {t.name: t for t in _SUPPORTED_TYPES}


def imageTypeByMode(mode: int) -> ImageType:
    try:
        return _BY_MODE[int(mode)]
    except KeyError:
        raise ValueError(f"Unsupported OpenCV image mode {mode!r}; "
                         f"supported: {sorted(_BY_MODE)}")


def imageTypeByName(name: str) -> ImageType:
    try:
        return _BY_NAME[name]
    except KeyError:
        raise ValueError(f"Unsupported OpenCV image type {name!r}; "
                         f"supported: {sorted(_BY_NAME)}")


# Arrow schema for the image struct column (field order mirrors Spark's
# ImageSchema.columnSchema).
imageSchema: pa.StructType = pa.struct([
    pa.field("origin", pa.string()),
    pa.field("height", pa.int32()),
    pa.field("width", pa.int32()),
    pa.field("nChannels", pa.int32()),
    pa.field("mode", pa.int32()),
    pa.field("data", pa.binary()),
])


class ImageSchema:
    """Namespace mirroring the reference's schema helpers."""

    columnSchema = imageSchema
    ocvTypes = ocvTypes
    imageFields = ["origin", "height", "width", "nChannels", "mode", "data"]
    undefinedImageType = "Undefined"

    imageTypeByMode = staticmethod(imageTypeByMode)
    imageTypeByName = staticmethod(imageTypeByName)


def _infer_image_type(array: np.ndarray) -> ImageType:
    if array.ndim != 3:
        raise ValueError(
            f"Expected an image array of rank 3 [H,W,C], got shape {array.shape}")
    n = array.shape[2]
    if array.dtype == np.uint8:
        name = {1: "CV_8UC1", 3: "CV_8UC3", 4: "CV_8UC4"}.get(n)
    elif array.dtype == np.float32:
        name = {1: "CV_32FC1", 3: "CV_32FC3", 4: "CV_32FC4"}.get(n)
    else:
        raise ValueError(
            f"Unsupported image dtype {array.dtype}; use uint8 or float32")
    if name is None:
        raise ValueError(f"Unsupported channel count {n}")
    return imageTypeByName(name)


def imageArrayToStruct(array: np.ndarray, origin: str = "") -> dict:
    """Pack a [H,W,C] numpy array (BGR channel order for color) into the image
    struct dict.  Counterpart of ``imageIO.imageArrayToStruct``."""
    array = np.ascontiguousarray(array)
    if array.ndim == 2:
        array = array[:, :, None]
    t = _infer_image_type(array)
    h, w, c = array.shape
    return {
        "origin": origin,
        "height": int(h),
        "width": int(w),
        "nChannels": int(c),
        "mode": t.ord,
        "data": array.tobytes(),
    }


def imageStructToArray(struct: dict) -> np.ndarray:
    """Unpack an image struct dict into a [H,W,C] numpy array (BGR order for
    color images).  Counterpart of ``imageIO.imageStructToArray``."""
    t = imageTypeByMode(struct["mode"])
    h, w, c = int(struct["height"]), int(struct["width"]), int(struct["nChannels"])
    if c != t.nChannels:
        raise ValueError(
            f"nChannels {c} inconsistent with mode {t.name} ({t.nChannels})")
    data = struct["data"]
    if isinstance(data, memoryview):
        data = bytes(data)
    arr = np.frombuffer(data, dtype=t.dtype)
    expected = h * w * c
    if arr.size != expected:
        raise ValueError(
            f"Image data has {arr.size} elements; expected {expected} "
            f"for shape ({h},{w},{c})")
    return arr.reshape(h, w, c)


def structsToArrow(structs, column: str = "image") -> pa.Table:
    """Build a single-column Arrow table of image structs."""
    arr = pa.array(structs, type=imageSchema)
    return pa.table({column: arr})
