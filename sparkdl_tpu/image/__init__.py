"""Image schema + I/O (host side).

TPU chips have no image-decode unit, so decode/resize stay on the host and
feed the device pipeline — this package replaces the reference's
``python/sparkdl/image/imageIO.py`` (and the Scala ``ImageUtils``) with a
numpy/pyarrow/PIL implementation of the same OpenCV-convention image struct.
"""

from sparkdl_tpu.image.schema import (
    ImageSchema,
    imageSchema,
    ocvTypes,
    imageTypeByMode,
    imageTypeByName,
    imageArrayToStruct,
    imageStructToArray,
)
from sparkdl_tpu.image.io import (
    decodeImage,
    decodeResizeBatch,
    resizeImage,
    readImages,
    readImagesWithCustomFn,
    filesToDF,
    filesToModelBatch,
    createResizeImageUDF,
    PIL_decode,
    structsToBatch,
    arrowStructsToBatch,
    iterFileBatches,
    iterImageBatches,
)

__all__ = [
    "ImageSchema",
    "imageSchema",
    "ocvTypes",
    "imageTypeByMode",
    "imageTypeByName",
    "imageArrayToStruct",
    "imageStructToArray",
    "decodeImage",
    "decodeResizeBatch",
    "resizeImage",
    "readImages",
    "readImagesWithCustomFn",
    "filesToDF",
    "filesToModelBatch",
    "createResizeImageUDF",
    "PIL_decode",
    "structsToBatch",
    "arrowStructsToBatch",
    "iterFileBatches",
    "iterImageBatches",
]
