"""Checkpoint save/restore (orbax).

SURVEY.md §5: the reference could only LOAD model formats
(``TFInputGraph.fromCheckpoint``/``fromSavedModel``, Keras HDF5) — trained
estimator weights returned as in-memory bytes with no mid-training
checkpointing; failure recovery was Spark task retry.  Here checkpointing is
first-class: orbax-backed save AND restore of variable pytrees, plus an
epoch-granular train checkpointer the estimator uses for resumable fits
(the TPU analog of task re-execution: restart the fit, resume at the last
saved epoch).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _checkpointer():
    """A StandardCheckpointer safe for our single-writer protocol.

    Orbax's default save/finalize is a COLLECTIVE across all processes; the
    framework gates checkpoint writes to process 0 (see
    :class:`TrainCheckpointer`), so under multi-controller jax the
    checkpointer must be process-local — ``active_processes={self}`` drops
    the cross-process barriers that would otherwise deadlock a gated save.
    State passed in is host numpy (gathered from replicated device arrays),
    so no cross-process array shards are ever needed.
    """
    import jax
    import orbax.checkpoint as ocp

    if jax.process_count() == 1:
        return ocp.StandardCheckpointer()
    pid = jax.process_index()
    return ocp.StandardCheckpointer(
        multiprocessing_options=ocp.options.MultiprocessingOptions(
            primary_host=pid, active_processes={pid},
            barrier_sync_key_prefix=f"sparkdl-p{pid}"))


def save_pytree(path: str, tree: Any, *, force: bool = True) -> str:
    """Save a variables pytree to ``path`` (an orbax directory).

    The checkpointer is context-managed per call: orbax finalizes (renames
    the tmp dir into place) on close, so a long-lived unclosed checkpointer
    can leave ``*.orbax-checkpoint-tmp`` dirs behind.
    """
    path = os.path.abspath(path)
    with _checkpointer() as ckptr:
        ckptr.save(path, tree, force=force)
    return path


def restore_pytree(path: str, template: Optional[Any] = None) -> Any:
    """Restore a pytree; ``template`` (matching structure, e.g. abstract
    shapes) guides dtype/sharding restoration when given."""
    path = os.path.abspath(path)
    with _checkpointer() as ckptr:
        if template is not None:
            import jax

            abstract = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
                if hasattr(a, "shape") else a, template)
            return ckptr.restore(path, abstract)
        return ckptr.restore(path)


class TrainCheckpointer:
    """Epoch-granular save/resume for fits.

    Layout: ``<dir>/epoch_<k>`` orbax checkpoints holding
    ``{"params": ..., "epoch": k}``.  ``latest()`` finds the newest epoch so
    an interrupted fit restarts where it stopped.
    """

    def __init__(self, directory: str, every_epochs: int = 1):
        self.directory = os.path.abspath(directory)
        self.every_epochs = max(1, int(every_epochs))
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, epoch: int) -> str:
        return os.path.join(self.directory, f"epoch_{epoch:06d}")

    def due(self, epoch: int) -> bool:
        """Whether the cadence saves at ``epoch`` — check this BEFORE
        materializing device state to host so skipped epochs pay nothing."""
        return epoch % self.every_epochs == 0

    @staticmethod
    def is_writer() -> bool:
        """Single-writer rule for multi-controller runs: params/opt_state
        are replicated, so only process 0 writes — concurrent orbax tmp-dir
        renames from several hosts race on shared storage and can corrupt
        the checkpoint.  Non-writers skip the device->host gather too."""
        import jax

        return jax.process_index() == 0

    def maybe_save(self, epoch: int, state: Any) -> Optional[str]:
        """Save ``state`` (any pytree — e.g. {"params":..., "opt_state":...})
        if the epoch hits the cadence; returns the path if saved.  In a
        multi-controller run only process 0 writes (see :meth:`is_writer`)."""
        if not self.due(epoch) or not self.is_writer():
            return None
        path = self._path(epoch)
        save_pytree(path, {"state": state, "epoch": epoch})
        logger.info("checkpointed epoch %d -> %s", epoch, path)
        return path

    def latest(self) -> Optional[Tuple[int, str]]:
        if not os.path.isdir(self.directory):
            return None
        epochs = []
        for name in os.listdir(self.directory):
            if name.startswith("epoch_"):
                try:
                    epochs.append(int(name.split("_", 1)[1]))
                except ValueError:
                    continue
        if not epochs:
            return None
        e = max(epochs)
        return e, self._path(e)

    def restore_latest(self, template: Optional[Any] = None
                       ) -> Optional[Tuple[int, Any]]:
        import jax

        if jax.process_count() > 1:
            # Multi-controller resume must be CONSISTENT: only process 0
            # writes (is_writer), so process 0's view of the directory is
            # authoritative.  Barrier first (no host reads a checkpoint
            # process 0 is still finalizing), then broadcast process 0's
            # latest epoch — a host whose local view disagrees (e.g.
            # checkpoint_dir on host-local disk) would otherwise resume at
            # a different epoch and deadlock the collectives; that
            # misconfiguration fails loudly here instead.
            import numpy as _np
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("sparkdl:ckpt:restore")
            found = self.latest()
            local_epoch = found[0] if found is not None else -1
            epoch0 = int(multihost_utils.broadcast_one_to_all(
                _np.asarray(local_epoch, _np.int64)))
            if epoch0 < 0:
                return None
            path = self._path(epoch0)
            if not os.path.isdir(path):
                raise FileNotFoundError(
                    f"process {jax.process_index()} cannot see checkpoint "
                    f"{path} (process 0's latest). checkpoint_dir must be "
                    f"on shared storage visible to every host")
            epoch = epoch0
        else:
            found = self.latest()
            if found is None:
                return None
            epoch, path = found
        tree = restore_pytree(
            path, {"state": template, "epoch": 0} if template is not None
            else None)
        logger.info("resuming from %s (epoch %d)", path, epoch)
        return epoch, tree["state"]
