"""Checkpoint save/restore (orbax).

SURVEY.md §5: the reference could only LOAD model formats
(``TFInputGraph.fromCheckpoint``/``fromSavedModel``, Keras HDF5) — trained
estimator weights returned as in-memory bytes with no mid-training
checkpointing; failure recovery was Spark task retry.  Here checkpointing is
first-class: orbax-backed save AND restore of variable pytrees, plus an
epoch-granular train checkpointer the estimator uses for resumable fits
(the TPU analog of task re-execution: restart the fit, resume at the last
saved epoch).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

from sparkdl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def save_pytree(path: str, tree: Any, *, force: bool = True) -> str:
    """Save a variables pytree to ``path`` (an orbax directory).

    The checkpointer is context-managed per call: orbax finalizes (renames
    the tmp dir into place) on close, so a long-lived unclosed checkpointer
    can leave ``*.orbax-checkpoint-tmp`` dirs behind.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree, force=force)
    return path


def restore_pytree(path: str, template: Optional[Any] = None) -> Any:
    """Restore a pytree; ``template`` (matching structure, e.g. abstract
    shapes) guides dtype/sharding restoration when given."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        if template is not None:
            import jax

            abstract = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
                if hasattr(a, "shape") else a, template)
            return ckptr.restore(path, abstract)
        return ckptr.restore(path)


class TrainCheckpointer:
    """Epoch-granular save/resume for fits.

    Layout: ``<dir>/epoch_<k>`` orbax checkpoints holding
    ``{"params": ..., "epoch": k}``.  ``latest()`` finds the newest epoch so
    an interrupted fit restarts where it stopped.
    """

    def __init__(self, directory: str, every_epochs: int = 1):
        self.directory = os.path.abspath(directory)
        self.every_epochs = max(1, int(every_epochs))
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, epoch: int) -> str:
        return os.path.join(self.directory, f"epoch_{epoch:06d}")

    def due(self, epoch: int) -> bool:
        """Whether the cadence saves at ``epoch`` — check this BEFORE
        materializing device state to host so skipped epochs pay nothing."""
        return epoch % self.every_epochs == 0

    def maybe_save(self, epoch: int, state: Any) -> Optional[str]:
        """Save ``state`` (any pytree — e.g. {"params":..., "opt_state":...})
        if the epoch hits the cadence; returns the path if saved."""
        if not self.due(epoch):
            return None
        path = self._path(epoch)
        save_pytree(path, {"state": state, "epoch": epoch})
        logger.info("checkpointed epoch %d -> %s", epoch, path)
        return path

    def latest(self) -> Optional[Tuple[int, str]]:
        if not os.path.isdir(self.directory):
            return None
        epochs = []
        for name in os.listdir(self.directory):
            if name.startswith("epoch_"):
                try:
                    epochs.append(int(name.split("_", 1)[1]))
                except ValueError:
                    continue
        if not epochs:
            return None
        e = max(epochs)
        return e, self._path(e)

    def restore_latest(self, template: Optional[Any] = None
                       ) -> Optional[Tuple[int, Any]]:
        found = self.latest()
        if found is None:
            return None
        epoch, path = found
        tree = restore_pytree(
            path, {"state": template, "epoch": 0} if template is not None
            else None)
        logger.info("resuming from %s (epoch %d)", path, epoch)
        return epoch, tree["state"]
