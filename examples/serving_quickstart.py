"""Online inference with sparkdl_tpu.serving, end to end.

The offline stack scores whole DataFrames; this example shows the ONLINE
path the serving subsystem adds: single requests admitted into a bounded
queue, assembled into dynamic micro-batches, dispatched through the same
InferenceEngine the transformers use, and demultiplexed back to
per-request futures — with deadlines, backpressure, and metrics.

Walkthrough:
  1. a raw ``fn(variables, batch)`` served with ``Server`` (threaded
     submitters, futures, p50/p99 from the metrics registry);
  2. asyncio integration (``predict_async``);
  3. ``serving.from_transformer``: a configured ``ModelTransformer``
     lifted into a server, with the server's rows checked bit-identical
     against the offline ``transform`` of the same inputs;
  4. the shared-queue UDF: ``register_serving_udf`` scores a DataFrame
     column THROUGH the running server.

Run:  python examples/serving_quickstart.py      (CPU, ~30 seconds)
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import threading

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from sparkdl_tpu import serving  # noqa: E402
from sparkdl_tpu.frame import DataFrame  # noqa: E402
from sparkdl_tpu.graph.function import ModelFunction  # noqa: E402
from sparkdl_tpu.transformers.tensor import ModelTransformer  # noqa: E402
from sparkdl_tpu.udf.registry import (register_serving_udf,  # noqa: E402
                                      udf_registry)

DIM, CLASSES = 32, 8


def make_model():
    rng = np.random.default_rng(7)
    variables = {"w": rng.normal(0, 0.2, (DIM, CLASSES)).astype(np.float32)}

    def fn(v, x):
        import jax.numpy as jnp

        logits = jnp.asarray(x, jnp.float32) @ v["w"]
        return jnp.exp(logits) / jnp.sum(jnp.exp(logits), axis=-1,
                                         keepdims=True)

    return fn, variables


def main():
    fn, variables = make_model()
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(96, DIM)).astype(np.float32)

    # -- 1. raw fn behind a server: concurrent submitters ----------------
    with serving.Server(fn, variables, max_batch_size=16, max_wait_ms=3,
                        max_queue=256) as srv:
        srv.warmup(xs[0])
        results = [None] * len(xs)

        def client(lo, hi):
            futs = [(i, srv.submit(xs[i])) for i in range(lo, hi)]
            for i, f in futs:
                results[i] = np.asarray(f.result())

        threads = [threading.Thread(target=client, args=(lo, lo + 24))
                   for lo in range(0, 96, 24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = srv.stats()
        print(f"served {int(stats['serving.completed'])} requests in "
              f"{int(stats['serving.batches'])} micro-batches, p99 "
              f"{1e3 * stats['serving.request_latency.p99_s']:.1f} ms")

        # -- 2. asyncio handler form -------------------------------------
        async def handler():
            return await asyncio.gather(
                *[srv.predict_async(xs[i]) for i in range(4)])

        async_rows = asyncio.run(handler())
        assert len(async_rows) == 4

    # -- 3. transformer -> server, parity with the offline path ----------
    mf = ModelFunction(fn=fn, variables=variables)
    stage = ModelTransformer(inputCol="features", outputCol="probs",
                             modelFunction=mf, batchSize=16)
    df = DataFrame({"features": [row for row in xs]})
    offline = stage.transform(df).column_to_numpy("probs")
    # one bucket pinned to the stage's batch size: bit-identity is a
    # per-padded-shape contract (different bucket widths agree only to
    # XLA-refusion tolerance)
    with serving.from_transformer(stage, max_wait_ms=3,
                                  bucket_sizes=[16]) as srv:
        online = np.stack([np.asarray(srv.predict(x)) for x in xs])
        assert np.array_equal(online.astype(np.float32), offline), \
            "online rows must be bit-identical to transform()"

        # -- 4. DataFrame column scored THROUGH the running server -------
        register_serving_udf("probs_via_server", srv)
        scored = udf_registry.apply("probs_via_server", df, "features",
                                    "probs")
        udf_rows = scored.column_to_numpy("probs")
        assert np.allclose(udf_rows, offline, rtol=1e-6, atol=1e-7)

    print(json.dumps({"serving_quickstart": "ok",
                      "requests": int(stats["serving.completed"])}))


if __name__ == "__main__":
    main()
