"""Transfer-learning top-1 on a flowers-style dataset (BASELINE.md config #1).

The reference's README recipe — DeepImageFeaturizer(InceptionV3) + logistic
regression on tf_flowers — reproduced end-to-end.  Given a dataset laid out
as ``<root>/<class_name>/*.jpg`` (the tf_flowers archive layout), this
script featurizes every image on the TPU, fits the classifier head, and
prints one JSON line with held-out top-1 accuracy.

Usage:
    python examples/flowers_top1.py /data/flower_photos \
        [--model InceptionV3] [--train-ratio 0.8] [--batch-size 128] \
        [--max-per-class N] [--seed 0]

Real pretrained weights: set ``SPARKDL_WEIGHTS_DIR`` to a directory holding
``inception_v3.weights.h5`` (or ``.h5``/``.keras`` full models) — the
air-gapped weight contract (sparkdl_tpu/models/__init__.py).  Without it the
script falls back to the Keras download cache, and failing that to random
init (reported in the output; random-weight top-1 is only a smoke signal).

Output:
    {"top1": 0.93, "n_train": 2936, "n_test": 734, "classes": 5,
     "model": "InceptionV3", "weights_source": "...", "seconds": ...}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


def gather_files(root: str, max_per_class: int | None):
    classes = sorted(
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d)) and not d.startswith("."))
    if not classes:
        raise SystemExit(f"No class subdirectories under {root}")
    files, labels = [], []
    for ci, cls in enumerate(classes):
        cdir = os.path.join(root, cls)
        names = sorted(
            f for f in os.listdir(cdir)
            if f.lower().endswith((".jpg", ".jpeg", ".png")))
        if max_per_class:
            names = names[:max_per_class]
        for f in names:
            files.append(os.path.join(cdir, f))
            labels.append(ci)
    return files, np.asarray(labels), classes


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("root", help="dataset root: <root>/<class>/*.jpg")
    ap.add_argument("--model", default="InceptionV3")
    ap.add_argument("--train-ratio", type=float, default=0.8)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--max-per-class", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    from sparkdl_tpu.estimators import LogisticRegression
    from sparkdl_tpu.frame import DataFrame
    from sparkdl_tpu.image.io import filesToModelBatch
    from sparkdl_tpu.models import get_model_spec
    from sparkdl_tpu.parallel.engine import InferenceEngine
    from sparkdl_tpu.utils.prefetch import prefetch_iter

    # perf_counter, not time.time(): "seconds" is an elapsed-time
    # measurement and wall clock can step under NTP slew (SDL006)
    t0 = time.perf_counter()
    files, labels, classes = gather_files(args.root, args.max_per_class)
    spec = get_model_spec(args.model)
    h, w = spec.input_size

    wdir = os.environ.get("SPARKDL_WEIGHTS_DIR")
    weights_source = (f"SPARKDL_WEIGHTS_DIR={wdir}" if wdir
                      else "keras-cache (random fallback if absent)")

    # Featurize everything: streaming decode -> jit featurize on the mesh.
    from sparkdl_tpu.models import load_model

    import jax.numpy as jnp

    module, variables = load_model(args.model)
    pre = spec.preprocess

    def fn(v, x):
        xf = pre(x).astype(jnp.bfloat16)
        return module.apply(v, xf, train=False, features=True
                            ).astype(jnp.float32)

    eng = InferenceEngine(fn, variables, device_batch_size=args.batch_size,
                          compute_dtype=jnp.bfloat16)

    def chunks():
        for off in range(0, len(files), eng.device_batch_size):
            batch, ok = filesToModelBatch(
                files[off:off + eng.device_batch_size], h, w)
            if not ok.all():
                bad = [files[off + i] for i in np.nonzero(~ok)[0]]
                print(f"warning: {len(bad)} undecodable files (first: "
                      f"{bad[0]})", file=sys.stderr)
            yield batch

    feats = np.concatenate(
        list(eng.map_batches(prefetch_iter(chunks(), depth=2))), axis=0)

    # Split and fit the head (the reference used Spark ML LogisticRegression
    # on the driver; ours trains data-parallel on the mesh).
    rng = np.random.default_rng(args.seed)
    order = rng.permutation(len(files))
    cut = int(len(files) * args.train_ratio)
    tr, te = order[:cut], order[cut:]
    train_df = DataFrame({"features": [feats[i].tolist() for i in tr],
                          "label": labels[tr].tolist()})
    test_df = DataFrame({"features": [feats[i].tolist() for i in te],
                         "label": labels[te].tolist()})
    lr = LogisticRegression(featuresCol="features", labelCol="label",
                            maxIter=100, learningRate=0.05, batchSize=256,
                            seed=args.seed)
    model = lr.fit(train_df)
    rows = model.transform(test_df).collect()
    y = np.asarray([r["label"] for r in rows])
    p = np.asarray([r["prediction"] for r in rows])
    print(json.dumps({
        "top1": round(float((y == p).mean()), 4),
        "n_train": int(len(tr)), "n_test": int(len(te)),
        "classes": len(classes), "model": args.model,
        "weights_source": weights_source,
        "seconds": round(time.perf_counter() - t0, 1),
    }))


if __name__ == "__main__":
    main()
