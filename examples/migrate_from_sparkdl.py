"""Migration tour: spark-deep-learning -> sparkdl_tpu, API by API.

Every section pairs the reference's call (commented, as it appears in the
sparkdl README/docs) with this framework's equivalent, and RUNS the
equivalent on synthetic images so the whole file doubles as an executable
smoke of the migration surface.  Differences that matter are called out
inline; everything else is name-for-name.

Run:  python examples/migrate_from_sparkdl.py   (CPU or TPU; ~a minute
      on CPU — zoo models run at tiny batch sizes here)
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_images(root: str, n: int = 6) -> None:
    from PIL import Image

    rng = np.random.default_rng(0)
    for i in range(n):
        Image.fromarray((rng.random((64, 80, 3)) * 255).astype(np.uint8),
                        "RGB").save(os.path.join(root, f"img_{i}.jpg"))
    with open(os.path.join(root, "broken.jpg"), "wb") as f:
        f.write(b"not an image")  # undecodable rows stay null, as upstream


def main() -> None:
    d = tempfile.mkdtemp(prefix="sparkdl_migration_")
    make_images(d)

    # ------------------------------------------------------------------
    # 1. Reading images
    # reference:
    #   from sparkdl.image import imageIO
    #   df = imageIO.readImagesWithCustomFn(path, decode_f)
    #   df = ImageSchema.readImages(path)        # Spark 2.3 image source
    from sparkdl_tpu.image import readImages

    df = readImages(d)
    rows = df.collect()
    n_null = sum(1 for r in rows if r["image"] is None)
    print(f"readImages: {len(rows)} rows, {n_null} null (bad file)")
    # The image struct is the SAME OpenCV-convention schema
    # {origin, height, width, nChannels, mode, data} with BGR bytes.

    # ------------------------------------------------------------------
    # 1b. Row-level image manipulation (resize UDF)
    # reference:
    #   from sparkdl.image.imageIO import createResizeImageUDF
    #   df = df.withColumn("resized", createResizeImageUDF([32, 32])(df.image))
    # Here the same row fn rides DataFrame.map_rows; image structs are
    # read zero-copy from the Arrow buffers (binary `data` arrives as a
    # memoryview) and untouched struct columns are re-emitted without a
    # Python round trip (PERF.md "Zero-copy map_rows").
    from sparkdl_tpu.image import createResizeImageUDF

    resize = createResizeImageUDF([32, 32])
    resized = df.map_rows(
        lambda r: {"image": r["image"], "resized": resize(r["image"])})
    r0 = next(r for r in resized.collect() if r["resized"] is not None)
    print(f"createResizeImageUDF via map_rows: "
          f"{r0['resized']['height']}x{r0['resized']['width']}")
    assert r0["resized"]["height"] == 32

    # ------------------------------------------------------------------
    # 2. Featurization for transfer learning
    # reference:
    #   from sparkdl import DeepImageFeaturizer
    #   featurizer = DeepImageFeaturizer(inputCol="image",
    #                                    outputCol="features",
    #                                    modelName="InceptionV3")
    #   features_df = featurizer.transform(df)
    from sparkdl_tpu.transformers import DeepImageFeaturizer

    featurizer = DeepImageFeaturizer(inputCol="image", outputCol="features",
                                     modelName="InceptionV3", batchSize=4)
    features_df = featurizer.transform(df)
    feat = next(r["features"] for r in features_df.collect()
                if r["features"] is not None)
    print(f"DeepImageFeaturizer: {len(feat)}-d features")

    # ------------------------------------------------------------------
    # 3. Prediction with topK decode
    # reference:
    #   from sparkdl import DeepImagePredictor
    #   predictor = DeepImagePredictor(inputCol="image",
    #                                  outputCol="predicted_labels",
    #                                  modelName="InceptionV3",
    #                                  decodePredictions=True, topK=5)
    from sparkdl_tpu.transformers import DeepImagePredictor

    predictor = DeepImagePredictor(inputCol="image",
                                   outputCol="predicted_labels",
                                   modelName="InceptionV3",
                                   decodePredictions=True, topK=5,
                                   batchSize=4)
    preds = next(r["predicted_labels"] for r in
                 predictor.transform(df).collect()
                 if r["predicted_labels"] is not None)
    print(f"DeepImagePredictor topK: {len(preds)} (class, desc, prob) rows")

    # ------------------------------------------------------------------
    # 4. Applying your own model to the image column
    # reference:
    #   from sparkdl import TFImageTransformer
    #   transformer = TFImageTransformer(inputCol="image", outputCol="out",
    #                                    graph=graph, inputTensor=...,
    #                                    outputTensor=..., outputMode="vector")
    # Here the model is a jax-traceable fn wrapped in a ModelFunction (the
    # GraphDef/session pair's replacement); TF 1.x GraphDefs still load via
    # graph.input.TFInputGraph (section 7).
    from sparkdl_tpu.graph.function import ModelFunction
    from sparkdl_tpu.transformers import TFImageTransformer

    mf = ModelFunction(
        fn=lambda v, x: x.astype("float32") * v["scale"],
        variables={"scale": np.float32(1 / 255.0)})
    transformer = TFImageTransformer(inputCol="image", outputCol="out",
                                     modelFunction=mf, inputSize=[32, 32],
                                     outputMode="vector", batchSize=4)
    out = next(r["out"] for r in transformer.transform(df).collect()
               if r["out"] is not None)
    print(f"TFImageTransformer: vector of {len(out)}")

    # ------------------------------------------------------------------
    # 5. Keras models on 1-D float rows / image files
    # reference:
    #   from sparkdl import KerasTransformer, KerasImageFileTransformer
    #   KerasTransformer(inputCol=..., outputCol=..., modelFile="m.h5")
    from sparkdl_tpu.frame import DataFrame
    from sparkdl_tpu.transformers import KerasTransformer

    import keras
    from keras import layers

    model = keras.Sequential([layers.Input((8,)), layers.Dense(3)])
    mpath = os.path.join(d, "mlp.keras")
    model.save(mpath)
    vdf = DataFrame({"features": [list(map(float, row)) for row in
                                  np.eye(8, dtype=np.float32)[:4]]})
    kt = KerasTransformer(inputCol="features", outputCol="preds",
                          modelFile=mpath, batchSize=4)
    print(f"KerasTransformer: {len(kt.transform(vdf).collect())} rows")

    # ------------------------------------------------------------------
    # 6. SQL-style UDF registration
    # reference:
    #   from sparkdl.udf.keras_image_model import registerKerasImageUDF
    #   registerKerasImageUDF("my_udf", model)
    #   ...then SELECT my_udf(image) FROM ...
    from sparkdl_tpu.udf import registerKerasImageUDF, udf_registry

    img_model = keras.Sequential([layers.Input((16, 16, 3)),
                                  layers.Flatten(), layers.Dense(2)])
    registerKerasImageUDF("my_udf", img_model)
    scored = udf_registry.apply("my_udf", df, "image", "scores")
    n_scored = sum(1 for r in scored.collect() if r["scores"] is not None)
    print(f"registerKerasImageUDF: scored {n_scored} rows")
    # (with pyspark installed: udf_registry.to_pandas_udf("my_udf"))

    # ------------------------------------------------------------------
    # 7. Legacy TF-1.x graph import
    # reference:
    #   from sparkdl import TFInputGraph
    #   TFInputGraph.fromGraph / fromGraphDef / fromSavedModel(WithSignature)
    #   / fromCheckpoint(WithSignature)
    from sparkdl_tpu.graph.input import TFInputGraph  # noqa: F401

    print("TFInputGraph: all six constructors available "
          "(see tests/test_tf_input.py)")

    # ------------------------------------------------------------------
    # 8. Transfer-learning estimator + tuning
    # reference:
    #   from sparkdl import KerasImageFileEstimator
    #   est = KerasImageFileEstimator(inputCol="uri", outputCol="preds",
    #       labelCol="label", imageLoader=load_fn, modelFile="m.h5",
    #       kerasOptimizer="adam", kerasLoss="categorical_crossentropy",
    #       kerasFitParams={"epochs": 5})
    #   CrossValidator(estimator=est, estimatorParamMaps=grid, ...).fit(df)
    from sparkdl_tpu.estimators import ImageFileEstimator

    def loader(uri):
        from PIL import Image

        img = Image.open(uri).convert("RGB").resize((16, 16))
        return np.asarray(img, np.float32) / 255.0

    import jax.numpy as jnp

    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=ModelFunction(
            fn=lambda v, x: jnp.asarray(x).reshape(x.shape[0], -1) @ v["w"],
            variables={"w": np.zeros((16 * 16 * 3, 2), np.float32)}),
        imageLoader=loader, optimizer="sgd", loss="mse",
        fitParams={"epochs": 1, "steps_per_execution": 2}, batchSize=4)
    uris = [os.path.join(d, f"img_{i}.jpg") for i in range(6)]
    labels = [[1.0, 0.0] if i % 2 == 0 else [0.0, 1.0] for i in range(6)]
    tdf = DataFrame({"uri": uris, "label": labels})
    fitted = est.fit(tdf)
    print(f"ImageFileEstimator: fit done, losses={len(fitted.trainLosses)} "
          f"epoch(s)")
    # ParamGridBuilder / CrossValidator / TrainValidationSplit live in
    # sparkdl_tpu.estimators.tuning with the pyspark.ml API shape.

    print(json.dumps({"migration_smoke": "ok"}))


if __name__ == "__main__":
    main()
