"""Multi-host data-parallel training, end to end, on one machine.

The reference had NO gradient distribution (each Keras fit ran on one
executor; SURVEY.md §2 parallelism table) — this framework adds it as
the north-star capability: `fit_data_parallel` shards the batch over a
`jax.sharding.Mesh` data axis and XLA inserts the psum gradient
all-reduce the sharding implies.  The SAME code runs

  * single-process over all local devices (a TPU slice's ICI), and
  * MULTI-CONTROLLER: one process per host (`jax.distributed`), each
    holding only its local shard — the deployment shape of a TPU pod,
    where the data axis spans hosts/slices (DCN) and collectives ride
    the fastest link the topology offers.

This example demonstrates the multi-controller path on one machine by
launching TWO worker processes with 2 virtual CPU devices each
(dp=4 across 2 processes) and comparing the fitted weights against an
in-process single-controller oracle — the topology-envelope recipe
PERF.md documents for a real pod bring-up.

Run:  python examples/distributed_fit.py      (CPU, ~1 minute)
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

_WORKER = """
import json, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
pid, nproc, port, out_path = (int(sys.argv[1]), int(sys.argv[2]),
                              int(sys.argv[3]), sys.argv[4])
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=nproc, process_id=pid)
sys.path.insert(0, "__ROOT__")
import optax
from sparkdl_tpu.parallel.train import fit_data_parallel

# Each process holds ONLY its local rows (per-host sharded input) —
# fit_data_parallel assembles the global batch via
# make_array_from_process_local_data and agrees on steps-per-epoch
# across controllers.
rng = np.random.default_rng(7)
w_true = rng.normal(size=(4, 1)).astype(np.float32)
x_all = rng.normal(size=(32, 4)).astype(np.float32)
y_all = x_all @ w_true
lo, hi = (0, 16) if pid == 0 else (16, 32)

def predict(p, xb):
    import jax.numpy as jnp
    return jnp.asarray(xb) @ p["w"]

params = {"w": np.zeros((4, 1), np.float32)}
fitted, losses = fit_data_parallel(
    predict, params, x_all[lo:hi], y_all[lo:hi],
    optimizer=optax.sgd(0.05), loss="mse", batch_size=8, epochs=10,
    seed=3, shuffle=False)
if pid == 0:
    json.dump({"w": np.asarray(fitted["w"]).tolist(),
               "losses": [float(v) for v in losses]}, open(out_path, "w"))
"""


def main() -> None:
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]
    srv.close()  # free it for the jax.distributed coordinator
    out = os.path.join(tempfile.mkdtemp(prefix="sparkdl_dist_"), "w0.json")
    workers = []
    for pid in range(2):
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["JAX_PLATFORMS"] = "cpu"
        workers.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER.replace("__ROOT__", ROOT),
             str(pid), "2", str(port), out],
            env=env, stderr=subprocess.PIPE, text=True))
    for w in workers:
        rc = w.wait(timeout=300)
        if rc != 0:
            raise RuntimeError(
                f"worker failed (rc={rc}): {w.stderr.read()[-1500:]}")
    dist = json.load(open(out))

    # Single-controller oracle: same data, same schedule, one process.
    import jax

    jax.config.update("jax_platforms", "cpu")
    import optax

    from sparkdl_tpu.parallel import get_mesh
    from sparkdl_tpu.parallel.train import fit_data_parallel

    rng = np.random.default_rng(7)
    w_true = rng.normal(size=(4, 1)).astype(np.float32)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = x @ w_true

    def predict(p, xb):
        import jax.numpy as jnp

        return jnp.asarray(xb) @ p["w"]

    fitted, _ = fit_data_parallel(
        predict, {"w": np.zeros((4, 1), np.float32)}, x, y,
        optimizer=optax.sgd(0.05), loss="mse", batch_size=8, epochs=10,
        seed=3, shuffle=False, mesh=get_mesh(num_devices=1))
    # identical schedule/math; reduction ORDER differs (4-way psum vs one
    # device), so f32 drift accumulates over the 40 steps — tolerance
    # covers rounding, not behavior
    np.testing.assert_allclose(np.asarray(dist["w"]),
                               np.asarray(fitted["w"]),
                               rtol=5e-3, atol=1e-3)
    assert dist["losses"][-1] < 1e-3, dist["losses"][-1]
    print(json.dumps({
        "distributed_fit": "ok",
        "processes": 2, "devices_per_process": 2, "dp": 4,
        "final_loss": round(dist["losses"][-1], 6),
        "matches_single_controller_oracle": True}))


if __name__ == "__main__":
    main()
