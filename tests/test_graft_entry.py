"""Driver-contract tests for __graft_entry__.py.

The round-1 failure mode (MULTICHIP_r01.json ok=false) was dryrun_multichip
assuming n real devices exist.  These tests pin both paths: in-process when
enough devices are present (conftest provisions 8 virtual CPU devices) and
the subprocess fallback when more devices are requested than exist.
"""

import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__


def test_dryrun_in_process_with_enough_devices():
    # conftest gives this process 8 virtual CPU devices -> in-process path.
    __graft_entry__.dryrun_multichip(8)


def test_dryrun_subprocess_fallback_when_devices_insufficient():
    # 16 > 8 present -> must self-provision a virtual 16-device CPU platform
    # in a subprocess (the driver's bench env has ONE real chip).
    __graft_entry__.dryrun_multichip(16)


def test_dryrun_gates_on_subprocess_probe_and_pins_before_parent_probe(
        monkeypatch):
    """The MULTICHIP r05 hang mode: ``len(jax.devices())`` on an UNPINNED
    parent initializes whatever backend the environment chose, which
    blocks forever inside native code on a dead TPU relay.  The decision
    must be gated by the short-timeout subprocess probe first, and any
    parent-side device count (the committed-backend re-check) must come
    strictly AFTER the CPU pin."""
    import jax

    calls = []
    orig_update, orig_devices = jax.config.update, jax.devices
    monkeypatch.setattr(
        jax.config, "update",
        lambda k, v: (calls.append(("update", k, v)), orig_update(k, v))[1])
    monkeypatch.setattr(
        jax, "devices",
        lambda *a, **kw: (calls.append(("devices",)),
                          orig_devices(*a, **kw))[1])
    probed = []
    orig_probe = __graft_entry__._probe_local_device_count
    monkeypatch.setattr(
        __graft_entry__, "_probe_local_device_count",
        lambda *a, **kw: (probed.append(1), orig_probe(*a, **kw))[1])
    # the probe decision is what's under test, not the step itself
    monkeypatch.setattr(__graft_entry__, "_dryrun_impl", lambda n: None)
    __graft_entry__.dryrun_multichip(8)  # conftest env: probe child sees 8
    assert probed == [1]                 # subprocess probe gated the path
    pin = ("update", "jax_platforms", "cpu")
    assert pin in calls and ("devices",) in calls
    assert calls.index(pin) < calls.index(("devices",))


def test_dryrun_survives_hanging_backend_probe(monkeypatch):
    """Simulate the dead-relay hang: the probe child blocks forever (as a
    backend init on a dead relay does).  dryrun_multichip must kill it at
    the probe timeout and complete via the virtual-subprocess path —
    never touching the parent's jax backend — instead of hanging until
    the driver's rc=124 kill."""
    import time

    import jax

    monkeypatch.setattr(__graft_entry__, "_DEVICE_COUNT_PROBE",
                        "import time\ntime.sleep(600)\n")
    monkeypatch.setattr(__graft_entry__, "_PROBE_TIMEOUT_S", 2)
    monkeypatch.setattr(
        jax, "devices",
        lambda *a, **kw: (_ for _ in ()).throw(AssertionError(
            "parent touched jax.devices() on the dead-relay path")))
    ran = []
    monkeypatch.setattr(__graft_entry__, "_dryrun_in_virtual_subprocess",
                        lambda n: ran.append(n))
    t0 = time.monotonic()
    __graft_entry__.dryrun_multichip(8)
    assert ran == [8]                      # fell back, completed ok
    assert time.monotonic() - t0 < 30      # bounded by the probe timeout


def test_dryrun_falls_back_when_parent_backend_disagrees_with_probe(
        monkeypatch):
    """A caller whose jax backend is ALREADY committed (CPU pin no-ops)
    may expose fewer devices than the probe child saw — the re-check must
    route to the virtual subprocess instead of failing mesh creation."""
    import jax

    monkeypatch.setattr(__graft_entry__, "_probe_local_device_count",
                        lambda *a, **kw: 8)
    monkeypatch.setattr(jax, "devices",
                        lambda *a, **kw: [object()])  # parent sees 1
    ran = {"sub": [], "impl": []}
    monkeypatch.setattr(__graft_entry__, "_dryrun_in_virtual_subprocess",
                        lambda n: ran["sub"].append(n))
    monkeypatch.setattr(__graft_entry__, "_dryrun_impl",
                        lambda n: ran["impl"].append(n))
    __graft_entry__.dryrun_multichip(8)
    assert ran == {"sub": [8], "impl": []}


def test_entry_compiles_single_chip():
    import jax

    fn, (variables, batch) = __graft_entry__.entry()
    out = jax.jit(fn)(variables, batch)
    assert out.shape[0] == batch.shape[0]
    assert out.ndim == 2
