"""Driver-contract tests for __graft_entry__.py.

The round-1 failure mode (MULTICHIP_r01.json ok=false) was dryrun_multichip
assuming n real devices exist.  These tests pin both paths: in-process when
enough devices are present (conftest provisions 8 virtual CPU devices) and
the subprocess fallback when more devices are requested than exist.
"""

import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__


def test_dryrun_in_process_with_enough_devices():
    # conftest gives this process 8 virtual CPU devices -> in-process path.
    __graft_entry__.dryrun_multichip(8)


def test_dryrun_subprocess_fallback_when_devices_insufficient():
    # 16 > 8 present -> must self-provision a virtual 16-device CPU platform
    # in a subprocess (the driver's bench env has ONE real chip).
    __graft_entry__.dryrun_multichip(16)


def test_dryrun_pins_cpu_platform_before_device_probe(monkeypatch):
    """The MULTICHIP hang mode: probing ``len(jax.devices())`` with no
    platform pinned initializes the default backend, which blocks forever
    on a dead TPU relay.  The probe must be preceded by the same
    ``jax.config.update('jax_platforms', 'cpu')`` pin the subprocess and
    conftest use."""
    import jax

    calls = []
    orig_update, orig_devices = jax.config.update, jax.devices
    monkeypatch.setattr(
        jax.config, "update",
        lambda k, v: (calls.append(("update", k, v)), orig_update(k, v))[1])
    monkeypatch.setattr(
        jax, "devices",
        lambda *a, **kw: (calls.append(("devices",)),
                          orig_devices(*a, **kw))[1])
    # the probe decision is what's under test, not the step itself
    monkeypatch.setattr(__graft_entry__, "_dryrun_impl", lambda n: None)
    __graft_entry__.dryrun_multichip(8)
    pin = ("update", "jax_platforms", "cpu")
    assert pin in calls
    assert ("devices",) in calls
    assert calls.index(pin) < calls.index(("devices",))


def test_entry_compiles_single_chip():
    import jax

    fn, (variables, batch) = __graft_entry__.entry()
    out = jax.jit(fn)(variables, batch)
    assert out.shape[0] == batch.shape[0]
    assert out.ndim == 2
