"""Config-#1 accuracy harness (BASELINE.md config #1, VERDICT r2 missing #2).

The north star's second clause is transfer-accuracy parity: the reference's
README flowers recipe is Pipeline(DeepImageFeaturizer -> LogisticRegression).
This test runs that exact pipeline shape end-to-end on fixture images:
features must be learnable (accuracy above chance) and the whole fitted
PipelineModel must survive a persistence round-trip.

Weights: offline pretrained weights are used when ``SPARKDL_WEIGHTS_DIR``
provides them (air-gapped contract, models/__init__.py); otherwise the
architecture-faithful random init still yields deterministic per-image
features, so separability-above-chance remains a valid end-to-end check.
The real-top-1 measurement against actual flowers data is
``examples/flowers_top1.py`` (same pipeline, real weights + real dataset).
"""

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pytest

from sparkdl_tpu.estimators import LogisticRegression
from sparkdl_tpu.frame import DataFrame
from sparkdl_tpu.image.io import readImages
from sparkdl_tpu.transformers import DeepImageFeaturizer, Pipeline
from sparkdl_tpu.transformers.base import PipelineModel


@pytest.fixture(scope="module")
def labeled_image_df(fixture_images):
    """3 unique fixture images x 8 reps with image-identity-derived labels
    (img0 -> 0, img1 -> 1, img2 -> 0): any featurizer that preserves image
    identity makes this separable; chance accuracy is ~0.5."""
    base = readImages(fixture_images["dir"])
    good = base.table.filter(
        pc.invert(pc.is_null(base.table.column("image"))))
    reps = pa.concat_tables([good] * 8).combine_chunks()
    structs = reps.column("image").to_pylist()
    labels = []
    for s in structs:
        idx = next(i for i, p in enumerate(sorted(fixture_images["paths"]))
                   if s["origin"].endswith(p.rsplit("/", 1)[-1]))
        labels.append(idx % 2)
    table = reps.append_column("label", pa.array(labels, type=pa.int64()))
    return DataFrame(table)


def test_featurizer_lr_pipeline_above_chance(labeled_image_df, tmp_path):
    pipe = Pipeline(stages=[
        DeepImageFeaturizer(inputCol="image", outputCol="features",
                            modelName="InceptionV3", batchSize=8),
        LogisticRegression(featuresCol="features", labelCol="label",
                           maxIter=60, learningRate=0.05, batchSize=24),
    ])
    model = pipe.fit(labeled_image_df)
    out = model.transform(labeled_image_df)
    rows = out.collect()
    y = np.asarray([r["label"] for r in rows])
    p = np.asarray([r["prediction"] for r in rows])
    acc = float((y == p).mean())
    assert acc > 0.75, f"pipeline accuracy {acc} not above chance (0.5)"

    # persistence round-trip of the WHOLE PipelineModel
    path = str(tmp_path / "flowers_pipeline")
    model.save(path)
    loaded = PipelineModel.load(path)
    rows2 = loaded.transform(labeled_image_df).collect()
    p2 = np.asarray([r["prediction"] for r in rows2])
    np.testing.assert_array_equal(p, p2)
    probs = np.asarray([r["probability"] for r in rows])
    probs2 = np.asarray([r["probability"] for r in rows2])
    np.testing.assert_allclose(probs, probs2, rtol=1e-5, atol=1e-6)
