"""DataFrame layer tests."""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.frame import DataFrame


def _df():
    return DataFrame({"a": [1, 2, 3, 4, 5], "b": ["x", "y", "z", "w", "v"]})


def test_construction_paths():
    import pandas as pd
    assert DataFrame.from_pandas(pd.DataFrame({"a": [1]})).count() == 1
    assert DataFrame([{"a": 1}, {"a": 2}]).count() == 2
    assert DataFrame(pa.table({"a": [1]})).columns == ["a"]
    with pytest.raises(TypeError):
        DataFrame(42)


def test_select_drop_rename():
    df = _df()
    assert df.select("a").columns == ["a"]
    assert df.drop("a").columns == ["b"]
    assert df.withColumnRenamed("a", "c").columns == ["c", "b"]


def test_with_column_and_replace():
    df = _df().withColumn("c", np.arange(5))
    assert df.columns == ["a", "b", "c"]
    df2 = df.withColumn("c", [9, 9, 9, 9, 9])
    assert df2.collect()[0]["c"] == 9
    # rank-2 numpy becomes a list column
    df3 = df.withColumn("v", np.ones((5, 3), dtype=np.float32))
    mat = df3.column_to_numpy("v")
    assert mat.shape == (5, 3)


def test_filter_limit_union():
    df = _df()
    assert df.filter(np.array([True, False, True, False, True])).count() == 3
    assert df.limit(2).count() == 2
    assert df.union(df).count() == 10


def test_repartition_and_batches():
    df = _df().repartition(3)
    assert df.num_partitions == 3
    sizes = [b.num_rows for b in df.iter_batches()]
    assert sum(sizes) == 5 and len(sizes) == 3
    resliced = [b.num_rows for b in df.iter_batches(batch_size=2)]
    assert sum(resliced) == 5 and max(resliced) <= 2


def test_rows_and_map_rows():
    df = _df()
    rows = df.collect()
    assert rows[0].a == 1 and rows[0]["b"] == "x"
    out = df.map_rows(lambda r: {"double": r.a * 2})
    assert [r.double for r in out.collect()] == [2, 4, 6, 8, 10]


def test_map_rows_batchwise():
    """map_rows processes record-batch-wise (VERDICT r2 weak #8): peak
    Python-object residency is O(batch_size) — the map function must be
    invoked interleaved with batch iteration, not after materializing the
    whole table, and the output must preserve values and order."""
    import pyarrow as pa

    n = 10
    tbl = pa.table({"a": list(range(n))})
    df = DataFrame(tbl)
    seen = []
    out = df.map_rows(lambda r: seen.append(r["a"]) or {"b": r["a"] * 2},
                      batch_size=3)
    assert [r["b"] for r in out.collect()] == [v * 2 for v in range(n)]
    assert seen == list(range(n))
    # empty frame round-trips
    empty = DataFrame(pa.table({"a": pa.array([], type=pa.int64())}))
    assert empty.map_rows(lambda r: {"b": 1}).count() == 0
    # schema pinned by first batch even if later values are null-ish
    mixed = DataFrame(pa.table({"a": [1.5, 2.5, 3.5, 4.5]}))
    out2 = mixed.map_rows(lambda r: {"b": float(r["a"])}, batch_size=2)
    assert out2.table.column("b").type == pa.float64()


def test_map_rows_schema_promotion():
    """Schema quirks the old whole-table inference handled must survive the
    batch-wise rewrite: empty leading batches don't pin an empty schema,
    and a null-typed first batch promotes when later rows are concrete."""
    import pyarrow as pa

    empty = pa.table({"a": pa.array([], type=pa.int64())})
    full = pa.table({"a": [1, 2, 3]})
    df = DataFrame(pa.concat_tables([empty, full]))
    out = df.map_rows(lambda r: {"b": r["a"] * 10}, batch_size=2)
    assert [r["b"] for r in out.collect()] == [10, 20, 30]

    df2 = DataFrame(pa.table({"a": [1, 2, 3, 4]}))
    out2 = df2.map_rows(
        lambda r: {"b": None if r["a"] < 3 else float(r["a"])}, batch_size=2)
    assert [r["b"] for r in out2.collect()] == [None, None, 3.0, 4.0]
    assert out2.table.column("b").type == pa.float64()


def test_map_rows_int_to_float_widening():
    """ADVICE r3 (medium): an int64-inferred first batch must NOT silently
    truncate a later float batch (from_pylist(schema=...) coerces 3.5 -> 3
    without raising).  Per-batch inference + unify must yield float64."""
    import pyarrow as pa

    df = DataFrame(pa.table({"a": [1, 2, 3, 4]}))
    out = df.map_rows(
        lambda r: {"b": r["a"] if r["a"] < 3 else r["a"] + 0.5}, batch_size=2)
    assert out.table.column("b").type == pa.float64()
    assert [r["b"] for r in out.collect()] == [1.0, 2.0, 3.5, 4.5]


def test_map_rows_missing_key_null_fills():
    """A batch whose rows omit a key some other batch produced null-fills
    that column (pinned-schema behavior preserved across the unify path)."""
    import pyarrow as pa

    df = DataFrame(pa.table({"a": [1, 2, 3, 4]}))
    out = df.map_rows(
        lambda r: {"b": r["a"]} if r["a"] < 3 else {"b": r["a"], "c": "x"},
        batch_size=2)
    assert [r["c"] for r in out.collect()] == [None, None, "x", "x"]


def _image_frame(n=8, h=16, w=12, null_at=3):
    import pyarrow as pa

    from sparkdl_tpu.image.schema import imageArrayToStruct, imageSchema

    rng = np.random.default_rng(0)
    structs = [imageArrayToStruct(
        (rng.random((h, w, 3)) * 255).astype(np.uint8), origin=f"r{i}")
        for i in range(n)]
    if null_at is not None:
        structs[null_at] = None
    return DataFrame(pa.table({"image": pa.array(structs, type=imageSchema),
                               "k": list(range(n))}))


def test_map_rows_struct_zero_copy_passthrough():
    """VERDICT r4 #6: struct columns ride Arrow-buffer views through
    map_rows.  A struct returned untouched is re-emitted as the ORIGINAL
    Arrow column (no Python->Arrow round trip) and null rows survive."""
    df = _image_frame()
    seen_types = []
    out = df.map_rows(lambda r: seen_types.append(type(
        r["image"] and r["image"]["data"])) or
        {"image": r["image"], "k2": r["k"] * 2}, batch_size=3)
    # fn saw zero-copy views: binary child is a memoryview, not bytes
    assert memoryview in seen_types
    assert out.count() == 8
    assert out.table.column("image").null_count == 1
    assert out.table.column("image").combine_chunks().equals(
        df.table.column("image").combine_chunks())
    assert [r["k2"] for r in out.collect()] == [i * 2 for i in range(8)]


def test_map_rows_materialize_restores_bytes():
    """materialize=True opts out of the zero-copy struct views: fns get
    plain to_pylist dicts whose binary children are real ``bytes`` (for
    .decode()/dict-key/pickle-sensitive row fns — advisor round-5), at
    the old materialization cost; outputs match the view path."""
    df = _image_frame()
    seen_types = []
    out = df.map_rows(lambda r: seen_types.append(type(
        r["image"] and r["image"]["data"])) or
        {"image": r["image"], "k2": r["k"] * 2}, batch_size=3,
        materialize=True)
    assert seen_types and memoryview not in seen_types
    assert bytes in seen_types
    # same ROWS as the zero-copy path (materialize re-infers the struct
    # schema from plain dicts, so compare values, not arrow types)
    ref = df.map_rows(lambda r: {"image": r["image"], "k2": r["k"] * 2},
                      batch_size=3)
    assert (out.table.column("image").to_pylist()
            == ref.table.column("image").to_pylist())
    assert [r["k2"] for r in out.collect()] == [i * 2 for i in range(8)]


def test_map_rows_struct_modified_and_nulled():
    """Modified structs materialize normally (resize UDF path) and a fn
    nulling a live row defeats the passthrough, not the null contract."""
    from sparkdl_tpu.image.io import createResizeImageUDF

    df = _image_frame()
    resize = createResizeImageUDF([4, 4])
    out = df.map_rows(lambda r: {"image": resize(r["image"])}, batch_size=3)
    rows = out.table.column("image").to_pylist()
    assert rows[3] is None
    assert rows[0]["height"] == 4 and rows[0]["width"] == 4
    assert len(rows[0]["data"]) == 4 * 4 * 3

    out2 = df.map_rows(
        lambda r: {"image": None if r["k"] in (0, 3) else r["image"]},
        batch_size=4)
    assert out2.table.column("image").null_count == 2
    kept = out2.table.column("image").to_pylist()[1]
    assert kept == df.table.column("image").to_pylist()[1]


def test_map_rows_struct_inplace_mutation_preserved():
    """A fn that mutates the struct view IN PLACE and returns it must see
    its mutation in the output (the old to_pylist behavior) — dirty views
    defeat the zero-copy passthrough."""
    df = _image_frame(n=4, null_at=None)

    def mutate(r):
        img = r["image"]
        img["origin"] = "MUTATED"
        return {"image": img}

    out = df.map_rows(mutate, batch_size=2)
    assert [r["origin"] for r in
            out.table.column("image").to_pylist()] == ["MUTATED"] * 4


def test_map_rows_struct_view_survives_arrow_rebuild():
    """A view forwarded under a different batch alignment (shifted rows)
    must materialize correctly — identity passthrough only fires for
    row-aligned returns."""
    df = _image_frame(n=4, null_at=None)
    cache = []
    out = df.map_rows(lambda r: cache.append(r["image"]) or
                      {"image": cache[0]}, batch_size=4)
    rows = out.table.column("image").to_pylist()
    assert all(r["origin"] == "r0" for r in rows)


def test_map_rows_fuzz_against_old_path_semantics():
    """Seeded fuzz over one image-bearing schema: random data, null
    positions, chunkings, and a mix of passthrough/modify/rename fns —
    the zero-copy rewrite must reproduce the old to_pylist+from_pylist
    path's values row for row, bit-exactly."""
    from sparkdl_tpu.image.schema import imageArrayToStruct, imageSchema

    rng = np.random.default_rng(1234)

    def old_path(table, fn, batch_size):
        out = []
        for rb in table.to_batches(max_chunksize=batch_size):
            out.extend(fn(dict(r)) for r in rb.to_pylist())
        return out

    def norm(v):
        if isinstance(v, dict):
            return {k: norm(x) for k, x in v.items()}
        if isinstance(v, memoryview):
            return bytes(v)
        return v  # floats compare EXACTLY: both paths must be bit-identical

    for trial in range(8):
        n = int(rng.integers(3, 12))
        null_at = int(rng.integers(0, n)) if trial % 2 else None
        structs = [imageArrayToStruct(
            (rng.random((4, 5, 3)) * 255).astype(np.uint8),
            origin=f"t{trial}r{i}") for i in range(n)]
        if null_at is not None:
            structs[null_at] = None
        tbl = pa.table({
            "image": pa.array(structs, type=imageSchema),
            "k": [int(v) for v in rng.integers(0, 100, n)],
            "s": [f"s{v}" for v in rng.integers(0, 9, n)],
            "f": [float(v) for v in rng.random(n)],
        })
        fns = [
            lambda r: {"image": r["image"], "k2": r["k"] * 2},     # pass
            lambda r: {"img2": r["image"], "s": r["s"]},           # rename
            lambda r: {"image": (dict(r["image"], origin="X")      # modify
                                 if r["image"] is not None else None),
                       "f": r["f"] + 0.5},
        ]
        fn = fns[trial % 3]
        bs = int(rng.integers(2, n + 2))
        got = [ {k: norm(v) for k, v in r.items()}
                for r in DataFrame(tbl).map_rows(fn, batch_size=bs)
                .table.to_pylist()]
        want = [{k: norm(v) for k, v in fn_out.items()}
                for fn_out in old_path(tbl, fn, bs)]
        assert got == want, (trial, bs, got[:2], want[:2])


def test_map_blocks_columnar():
    """Block-wise map (TensorFrames map_blocks parity): fn sees record
    batches, never per-row Python objects, and may change the layout."""
    import pyarrow as pa
    import pyarrow.compute as pc

    df = DataFrame(pa.table({"a": list(range(10)),
                             "b": [float(v) for v in range(10)]}))
    seen_sizes = []

    def double(rb):
        seen_sizes.append(rb.num_rows)
        return pa.record_batch({
            "a2": pc.multiply(rb.column(0), 2),
            "b": rb.column(1),
        })

    out = df.map_blocks(double, batch_size=4)
    assert out.columns == ["a2", "b"]
    assert [r["a2"] for r in out.collect()] == [2 * v for v in range(10)]
    assert seen_sizes == [4, 4, 2]
    with pytest.raises(TypeError, match="RecordBatch"):
        df.map_blocks(lambda rb: rb.to_pylist())


def test_map_blocks_schema_promotion_matches_map_rows():
    """map_blocks shares map_rows' promotion contract: an int-inferred
    first batch must not raise against (or silently truncate) a later
    float batch, and a column only some batches emit null-fills."""
    import pyarrow as pa

    df = DataFrame(pa.table({"a": [1, 2, 3, 4]}))

    def block_widen(rb):
        return pa.record_batch({"b": [v + 0.5 if v >= 3 else v
                                      for v in rb.column(0).to_pylist()]})

    out = df.map_blocks(block_widen, batch_size=2)
    assert out.table.column("b").type == pa.float64()
    assert [r["b"] for r in out.collect()] == [1.0, 2.0, 3.5, 4.5]

    def block_missing(rb):
        vals = rb.column(0).to_pylist()
        cols = {"b": vals}
        if max(vals) >= 3:
            cols["c"] = ["x"] * len(vals)
        return pa.record_batch(cols)

    out2 = df.map_blocks(block_missing, batch_size=2)
    assert [r["c"] for r in out2.collect()] == [None, None, "x", "x"]


def test_map_blocks_fuzz_against_map_rows_oracle():
    """Seeded fuzz: map_blocks must reproduce map_rows bit-exactly when
    the block fn is the vectorized twin of the row fn — same random data,
    null positions, chunkings, and the promotion edge cases (int->float
    widening, null->concrete, per-batch missing columns) the map_rows
    fuzz pinned (map_rows itself is fuzz-pinned against the old
    to_pylist path)."""
    import pyarrow as pa

    rng = np.random.default_rng(4321)

    def pairs():
        # (row_fn, block_fn) twins — block fns go through the same
        # Python value path so equality is bit-exact, not approximate
        def row_widen(r):
            return {"b": r["a"] + 0.5 if r["a"] >= 50 else r["a"],
                    "s": r["s"]}

        def blk_widen(rb):
            a = rb.column(rb.schema.names.index("a")).to_pylist()
            s = rb.column(rb.schema.names.index("s")).to_pylist()
            return pa.record_batch(
                {"b": [v + 0.5 if v >= 50 else v for v in a], "s": s})

        def row_null(r):
            return {"b": None if r["a"] % 3 == 0 else r["f"] * 2.0}

        def blk_null(rb):
            a = rb.column(rb.schema.names.index("a")).to_pylist()
            f = rb.column(rb.schema.names.index("f")).to_pylist()
            return pa.record_batch(
                {"b": [None if x % 3 == 0 else y * 2.0
                       for x, y in zip(a, f)]})

        def row_rename(r):
            return {"a2": r["a"] * 2, "f": r["f"]}

        def blk_rename(rb):
            a = rb.column(rb.schema.names.index("a")).to_pylist()
            f = rb.column(rb.schema.names.index("f")).to_pylist()
            return pa.record_batch({"a2": [v * 2 for v in a], "f": f})

        return [(row_widen, blk_widen), (row_null, blk_null),
                (row_rename, blk_rename)]

    for trial in range(9):
        n = int(rng.integers(3, 14))
        tbl = pa.table({
            "a": [int(v) for v in rng.integers(0, 100, n)],
            "s": [f"s{v}" for v in rng.integers(0, 9, n)],
            "f": [float(v) for v in rng.random(n)],
        })
        row_fn, blk_fn = pairs()[trial % 3]
        bs = int(rng.integers(2, n + 2))
        df = DataFrame(tbl).repartition(int(rng.integers(1, 4)))
        got = df.map_blocks(blk_fn, batch_size=bs).table
        want = df.map_rows(row_fn, batch_size=bs).table
        assert got.schema == want.schema, (trial, bs)
        assert got.to_pylist() == want.to_pylist(), (trial, bs)


def test_with_column_rank3_nested_fixed_size_lists():
    """rank>=3 numpy nests fixed_size_list per trailing dim, leaf dtype
    preserved (pa.array alone refuses >1-D elements)."""
    import pyarrow as pa

    df = DataFrame({"k": [1, 2, 3]})
    v = np.arange(3 * 2 * 4, dtype=np.float32).reshape(3, 2, 4)
    out = df.withColumn("t", v)
    t = out.table.column("t").type
    assert pa.types.is_fixed_size_list(t) and t.list_size == 2
    assert (pa.types.is_fixed_size_list(t.value_type)
            and t.value_type.list_size == 4)
    assert t.value_type.value_type == pa.float32()
    assert out.table.column("t").to_pylist() == v.tolist()


def test_with_column_rank_gt1_fuzz_against_row_oracle():
    """Seeded fuzz over rank-2..4 numpy columns and int/float dtypes:
    withColumn's buffer/nested path must reproduce the per-row Python
    oracle (``values.tolist()``) bit-exactly — float32 -> Python float
    widening is exact, so == is the right comparison — and rank-2
    columns round-trip through column_to_numpy with dtype intact."""
    rng = np.random.default_rng(99)
    dtypes = [np.float32, np.float64, np.int32, np.int64]
    for trial in range(10):
        ndim = int(rng.integers(2, 5))
        shape = tuple(int(v) for v in rng.integers(1, 5, ndim))
        dt = dtypes[trial % len(dtypes)]
        if np.issubdtype(dt, np.floating):
            vals = rng.normal(size=shape).astype(dt)
        else:
            vals = rng.integers(-1000, 1000, size=shape).astype(dt)
        df = DataFrame({"k": list(range(shape[0]))})
        out = df.withColumn("v", vals)
        got = out.table.column("v").to_pylist()
        assert got == vals.tolist(), (trial, shape, dt)
        if ndim == 2:
            back = out.column_to_numpy("v")
            np.testing.assert_array_equal(back, vals)
            assert back.dtype == dt


def test_column_to_numpy_buffer_path_parity(rng):
    """Uniform list<float> columns read straight from the values buffer:
    identical result to the old to_pylist row path, across chunked,
    sliced, fixed-size-list, and int-typed columns; ragged still raises
    like np.stack would (via the row path)."""
    import pyarrow as pa

    from sparkdl_tpu.frame import DataFrame

    x = rng.normal(size=(50, 7)).astype(np.float32)
    rows = [list(map(float, r)) for r in x]
    # chunked: two batches
    tbl = pa.table({"v": pa.chunked_array([
        pa.array(rows[:20], type=pa.list_(pa.float32())),
        pa.array(rows[20:], type=pa.list_(pa.float32()))])})
    got = DataFrame(tbl).column_to_numpy("v")
    np.testing.assert_array_equal(got, x)
    assert got.dtype == np.float32
    # sliced
    sliced = DataFrame(tbl.slice(5, 11)).column_to_numpy("v")
    np.testing.assert_array_equal(sliced, x[5:16])
    # fixed-size list
    fsl = pa.table({"v": pa.array(rows, type=pa.list_(pa.float32(), 7))})
    np.testing.assert_array_equal(DataFrame(fsl).column_to_numpy("v"), x)
    # int lists
    xi = (x * 10).astype(np.int64)
    ti = pa.table({"v": pa.array([list(map(int, r)) for r in xi],
                                 type=pa.list_(pa.int64()))})
    np.testing.assert_array_equal(DataFrame(ti).column_to_numpy("v"), xi)
    # ragged -> error (same contract as before)
    ragged = pa.table({"v": pa.array([[1.0, 2.0], [3.0]],
                                     type=pa.list_(pa.float32()))})
    with pytest.raises(Exception):
        DataFrame(ragged).column_to_numpy("v")


def test_column_to_numpy_returns_writable(rng):
    """The buffer path must hand out a writable array that does NOT alias
    the Arrow table (the old row path's contract)."""
    import pyarrow as pa

    from sparkdl_tpu.frame import DataFrame

    x = rng.normal(size=(6, 4)).astype(np.float32)
    df = DataFrame(pa.table({"v": pa.array([list(map(float, r)) for r in x],
                                           type=pa.list_(pa.float32()))}))
    got = df.column_to_numpy("v")
    assert got.flags.writeable
    got /= 2.0  # must not raise, must not write through
    again = df.column_to_numpy("v")
    np.testing.assert_array_equal(again, x)


def test_column_to_numpy_inner_nulls_stay_loud():
    """A null ELEMENT inside an int list must raise (old row-path
    contract), never silently become INT64_MIN via the buffer path."""
    import pyarrow as pa

    from sparkdl_tpu.frame import DataFrame

    df = DataFrame(pa.table({"v": pa.array([[1, None], [3, 4]],
                                           type=pa.list_(pa.int64()))}))
    with pytest.raises(TypeError):
        df.column_to_numpy("v")
