"""DataFrame layer tests."""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.frame import DataFrame


def _df():
    return DataFrame({"a": [1, 2, 3, 4, 5], "b": ["x", "y", "z", "w", "v"]})


def test_construction_paths():
    import pandas as pd
    assert DataFrame.from_pandas(pd.DataFrame({"a": [1]})).count() == 1
    assert DataFrame([{"a": 1}, {"a": 2}]).count() == 2
    assert DataFrame(pa.table({"a": [1]})).columns == ["a"]
    with pytest.raises(TypeError):
        DataFrame(42)


def test_select_drop_rename():
    df = _df()
    assert df.select("a").columns == ["a"]
    assert df.drop("a").columns == ["b"]
    assert df.withColumnRenamed("a", "c").columns == ["c", "b"]


def test_with_column_and_replace():
    df = _df().withColumn("c", np.arange(5))
    assert df.columns == ["a", "b", "c"]
    df2 = df.withColumn("c", [9, 9, 9, 9, 9])
    assert df2.collect()[0]["c"] == 9
    # rank-2 numpy becomes a list column
    df3 = df.withColumn("v", np.ones((5, 3), dtype=np.float32))
    mat = df3.column_to_numpy("v")
    assert mat.shape == (5, 3)


def test_filter_limit_union():
    df = _df()
    assert df.filter(np.array([True, False, True, False, True])).count() == 3
    assert df.limit(2).count() == 2
    assert df.union(df).count() == 10


def test_repartition_and_batches():
    df = _df().repartition(3)
    assert df.num_partitions == 3
    sizes = [b.num_rows for b in df.iter_batches()]
    assert sum(sizes) == 5 and len(sizes) == 3
    resliced = [b.num_rows for b in df.iter_batches(batch_size=2)]
    assert sum(resliced) == 5 and max(resliced) <= 2


def test_rows_and_map_rows():
    df = _df()
    rows = df.collect()
    assert rows[0].a == 1 and rows[0]["b"] == "x"
    out = df.map_rows(lambda r: {"double": r.a * 2})
    assert [r.double for r in out.collect()] == [2, 4, 6, 8, 10]
