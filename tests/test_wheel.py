"""Wheel artifact proof (VERDICT r3 #4 / SURVEY C17).

``pip install -e .`` (what the dev loop uses) never exercises package-data,
so these tests build the real wheel, install it into a clean target, and
smoke-import from there — proving the artifact users get actually ships
the native source and the offline data dir and that the PIL fallback
engages without a build step.
"""

import os
import subprocess
import sys
import zipfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def wheel_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("wheel")
    proc = subprocess.run(
        [sys.executable, "-m", "pip", "wheel", REPO, "--no-deps",
         "--no-build-isolation", "-w", str(d)],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    wheels = [f for f in os.listdir(d) if f.endswith(".whl")]
    assert len(wheels) == 1, wheels
    return str(d / wheels[0])


def test_wheel_ships_package_data(wheel_path):
    """The wheel must contain the lazy-build native source and the offline
    model-data dir — the two package-data claims of pyproject.toml."""
    names = zipfile.ZipFile(wheel_path).namelist()
    assert "sparkdl_tpu/native/sparkdl_native.cpp" in names
    assert "sparkdl_tpu/models/data/README.md" in names
    # and no test/bench stowaways
    assert not any(n.startswith(("tests/", "examples/")) for n in names)
    assert "bench.py" not in names


def test_wheel_installs_and_imports(wheel_path, tmp_path):
    """Install the wheel into a clean --target dir and import from THERE
    (repo not on the path): package imports, native source is present in
    the installed tree, and the image layer works via the PIL fallback."""
    target = tmp_path / "site"
    proc = subprocess.run(
        [sys.executable, "-m", "pip", "install", "--no-deps",
         "--target", str(target), wheel_path],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]

    smoke = r"""
import os, sys
import sparkdl_tpu
root = os.path.dirname(os.path.abspath(sparkdl_tpu.__file__))
assert root.startswith(sys.argv[1]), (root, sys.argv[1])
assert os.path.isfile(os.path.join(root, "native", "sparkdl_native.cpp"))
assert os.path.isfile(os.path.join(root, "models", "data", "README.md"))

# image layer end-to-end on the PIL path (no toolchain required)
import io
import numpy as np
from PIL import Image
from sparkdl_tpu.image import PIL_decode, imageArrayToStruct
from sparkdl_tpu.image.io import decodeResizeBatch
buf = io.BytesIO()
Image.fromarray(np.full((10, 12, 3), 55, np.uint8), "RGB").save(
    buf, format="JPEG")
batch, ok = decodeResizeBatch([buf.getvalue(), b"junk"], 8, 8)
assert batch.shape == (2, 8, 8, 3) and list(ok) == [True, False]

# native layer degrades gracefully (callable either way)
import sparkdl_tpu.native as native
assert native.native_available() in (True, False)

# the serving subsystem ships and imports without initializing jax
from sparkdl_tpu.serving import Server, from_transformer  # noqa: F401
print("WHEEL-SMOKE-OK")
"""
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH",)}
    env["PYTHONPATH"] = str(target)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", smoke, str(target)],
        capture_output=True, text=True, timeout=300,
        cwd=str(tmp_path), env=env)
    assert proc.returncode == 0, (proc.stdout[-1000:], proc.stderr[-2000:])
    assert "WHEEL-SMOKE-OK" in proc.stdout
