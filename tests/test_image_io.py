"""Image schema & I/O tests — round-trip array<->struct, decode of real
fixture images, malformed input handling (reference C2 test strategy)."""

import numpy as np
import pytest

from sparkdl_tpu.image import (
    PIL_decode,
    createResizeImageUDF,
    filesToDF,
    imageArrayToStruct,
    imageStructToArray,
    imageTypeByMode,
    imageTypeByName,
    ocvTypes,
    readImages,
    resizeImage,
)


def test_ocv_mode_table():
    assert ocvTypes["CV_8UC3"] == 16
    assert imageTypeByName("CV_8UC3").dtype == "uint8"
    assert imageTypeByMode(21).name == "CV_32FC3"
    with pytest.raises(ValueError):
        imageTypeByMode(99)


@pytest.mark.parametrize("dtype,channels", [("uint8", 1), ("uint8", 3),
                                            ("uint8", 4), ("float32", 3)])
def test_array_struct_roundtrip(rng, dtype, channels):
    if dtype == "uint8":
        arr = (rng.random((7, 5, channels)) * 255).astype(np.uint8)
    else:
        arr = rng.random((7, 5, channels)).astype(np.float32)
    s = imageArrayToStruct(arr, origin="mem://x")
    assert s["height"] == 7 and s["width"] == 5 and s["nChannels"] == channels
    back = imageStructToArray(s)
    np.testing.assert_array_equal(arr, back)


def test_struct_validation():
    arr = np.zeros((4, 4, 3), dtype=np.uint8)
    s = imageArrayToStruct(arr)
    s["nChannels"] = 4
    with pytest.raises(ValueError):
        imageStructToArray(s)


def test_decode_real_jpeg_is_bgr(fixture_images):
    with open(fixture_images["paths"][0], "rb") as f:
        raw = f.read()
    bgr = PIL_decode(raw)
    assert bgr is not None and bgr.ndim == 3 and bgr.shape[2] == 3
    from PIL import Image
    rgb = np.asarray(Image.open(fixture_images["paths"][0]).convert("RGB"))
    np.testing.assert_array_equal(bgr[:, :, ::-1], rgb)


def test_decode_failure_returns_none(fixture_images):
    with open(fixture_images["bad"], "rb") as f:
        assert PIL_decode(f.read()) is None


def test_read_images_dataframe(fixture_images):
    df = readImages(fixture_images["dir"])
    assert df.count() == 4  # 3 good + 1 bad (null row kept)
    rows = df.collect()
    nulls = [r for r in rows if r["image"] is None]
    assert len(nulls) == 1
    good = [r for r in rows if r["image"] is not None]
    for r in good:
        arr = imageStructToArray(r["image"])
        assert arr.dtype == np.uint8 and arr.shape[2] == 3


def test_files_to_df_and_partitions(fixture_images):
    df = filesToDF(fixture_images["dir"], numPartitions=2)
    assert df.count() == 4
    assert set(df.columns) == {"filePath", "fileData"}
    assert df.num_partitions == 2


def test_resize_bilinear_parity_with_pil(rng):
    arr = (rng.random((20, 30, 3)) * 255).astype(np.uint8)
    out = resizeImage(arr, 10, 15)
    assert out.shape == (10, 15, 3)
    from PIL import Image
    ref = np.asarray(Image.fromarray(arr).resize((15, 10), Image.BILINEAR))
    np.testing.assert_array_equal(out, ref)
    # float path stays close to the uint8 path (tolerance-based, like the
    # reference's cross-backend resize tests)
    outf = resizeImage(arr.astype(np.float32), 10, 15)
    assert outf.dtype == np.float32
    assert np.abs(outf - ref.astype(np.float32)).max() <= 1.0


def test_resize_udf_on_struct(rng):
    arr = (rng.random((8, 8, 3)) * 255).astype(np.uint8)
    udf = createResizeImageUDF([4, 6])
    out = udf(imageArrayToStruct(arr, origin="o"))
    assert out["height"] == 4 and out["width"] == 6
    assert udf(None) is None
    with pytest.raises(ValueError):
        createResizeImageUDF([1, 2, 3])


# ---------------------------------------------------------------------------
# arrowStructsToBatch: the zero-copy UDF hot path (VERDICT r3 #5)

def _struct_column(arrays, origins=None, nulls=()):
    """Build an image-struct arrow column from [H,W,C] BGR arrays, with
    ``None`` at the positions listed in ``nulls``."""
    import pyarrow as pa
    from sparkdl_tpu.image import imageSchema
    structs = []
    j = 0
    n = len(arrays) + len(nulls)
    for i in range(n):
        if i in nulls:
            structs.append(None)
        else:
            structs.append(imageArrayToStruct(
                arrays[j], origin="" if origins is None else origins[j]))
            j += 1
    return pa.array(structs, type=imageSchema)


def test_arrow_structs_uniform_parity(rng):
    """Fast path (all rows target-size uint8 BGR) matches structsToBatch."""
    from sparkdl_tpu.image import arrowStructsToBatch, structsToBatch
    arrays = [(rng.random((16, 16, 3)) * 255).astype(np.uint8)
              for _ in range(6)]
    col = _struct_column(arrays)
    batch, ok = arrowStructsToBatch(col, 16, 16)
    assert ok.all() and batch.shape == (6, 16, 16, 3)
    ref = structsToBatch(col.to_pylist(), 16, 16)
    np.testing.assert_array_equal(batch, ref)


def test_arrow_structs_nulls_and_slice(rng):
    """Null rows -> ok=False + zeros; sliced columns read correct buffers."""
    from sparkdl_tpu.image import arrowStructsToBatch
    arrays = [np.full((8, 8, 3), 10 * (i + 1), np.uint8) for i in range(4)]
    col = _struct_column(arrays, nulls=(2,))  # [10, 20, None, 30, 40]
    batch, ok = arrowStructsToBatch(col, 8, 8)
    assert list(ok) == [True, True, False, True, True]
    assert (batch[2] == 0).all()
    assert (batch[3] == 30).all()  # array index shifts past the null
    # slice: drop the first two rows — offsets must follow the slice
    sliced = col.slice(2, 3)
    b2, ok2 = arrowStructsToBatch(sliced, 8, 8)
    assert list(ok2) == [False, True, True]
    assert (b2[1] == 30).all() and (b2[2] == 40).all()


def test_arrow_structs_resize_and_modes(rng):
    """Mixed sizes / grayscale / float32 rows take the general path and
    match the per-dict converter bit-for-bit."""
    import pyarrow as pa
    from sparkdl_tpu.image import arrowStructsToBatch, imageSchema
    from sparkdl_tpu.image.io import structToModelInput
    arrays = [
        (rng.random((20, 30, 3)) * 255).astype(np.uint8),   # resize needed
        (rng.random((12, 12, 1)) * 255).astype(np.uint8),   # grayscale
        (rng.random((12, 12, 3)) * 255).astype(np.float32),  # CV_32FC3
        (rng.random((12, 12, 4)) * 255).astype(np.uint8),   # BGRA
    ]
    structs = [imageArrayToStruct(a) for a in arrays]
    col = pa.array(structs, type=imageSchema)
    batch, ok = arrowStructsToBatch(col, 12, 12)
    assert ok.all()
    for i, s in enumerate(structs):
        np.testing.assert_array_equal(batch[i], structToModelInput(s, 12, 12))


def test_arrow_structs_chunked_and_empty(rng):
    import pyarrow as pa
    from sparkdl_tpu.image import arrowStructsToBatch, imageSchema
    arrays = [np.full((4, 4, 3), i + 1, np.uint8) for i in range(4)]
    c1 = _struct_column(arrays[:2])
    c2 = _struct_column(arrays[2:])
    chunked = pa.chunked_array([c1, c2])
    batch, ok = arrowStructsToBatch(chunked, 4, 4)
    assert ok.all() and (batch[3] == 4).all()
    empty = pa.array([], type=imageSchema)
    b0, ok0 = arrowStructsToBatch(empty, 4, 4)
    assert b0.shape == (0, 4, 4, 3) and ok0.shape == (0,)
    allnull = pa.array([None, None], type=imageSchema)
    bn, okn = arrowStructsToBatch(allnull, 4, 4)
    assert not okn.any() and (bn == 0).all()


def test_arrow_structs_channel_order(rng):
    """channel_order='bgr' returns struct bytes untouched (the UDF hot-path
    feed; the device program does the swap); 'rgb' is its flip."""
    from sparkdl_tpu.image import arrowStructsToBatch
    arrays = [(rng.random((10, 10, 3)) * 255).astype(np.uint8)
              for _ in range(3)]
    col = _struct_column(arrays)
    bgr, ok = arrowStructsToBatch(col, 10, 10, channel_order="bgr")
    rgb, _ = arrowStructsToBatch(col, 10, 10)
    assert ok.all()
    np.testing.assert_array_equal(bgr, np.stack(arrays))
    np.testing.assert_array_equal(rgb, bgr[..., ::-1])
    # general (resize) path honors the order too
    big = [(rng.random((20, 20, 3)) * 255).astype(np.uint8)]
    colb = _struct_column(big)
    b, _ = arrowStructsToBatch(colb, 10, 10, channel_order="bgr")
    r, _ = arrowStructsToBatch(colb, 10, 10)
    np.testing.assert_array_equal(r, b[..., ::-1])
    with pytest.raises(ValueError):
        arrowStructsToBatch(col, 10, 10, channel_order="hsv")


def test_arrow_structs_packing_cost(rng):
    """Host packing cost per 299x299 image stays under 0.5 ms (VERDICT r3
    #5 target) on the UDF hot path (BGR passthrough: pure memcpy — the
    channel swap rides the fused device program)."""
    import time
    from sparkdl_tpu.image import arrowStructsToBatch
    n = 32
    arrays = [(rng.random((299, 299, 3)) * 255).astype(np.uint8)
              for _ in range(n)]
    col = _struct_column(arrays)
    arrowStructsToBatch(col, 299, 299, channel_order="bgr")  # warm
    best = float("inf")
    best_ref = float("inf")
    stacked = np.stack(arrays)
    for _ in range(5):  # best-of-5: 1-vCPU CI hosts are noisy
        t0 = time.perf_counter()
        batch, ok = arrowStructsToBatch(col, 299, 299, channel_order="bgr")
        best = min(best, (time.perf_counter() - t0) * 1000 / n)
        t0 = time.perf_counter()
        stacked.copy()  # same bytes, pure memcpy: the contention baseline
        best_ref = min(best_ref, (time.perf_counter() - t0) * 1000 / n)
    assert ok.all()
    # absolute target (VERDICT r3 #5) on a quiet host, OR within 25x of a
    # raw memcpy of the same bytes when the host is contended — both sides
    # inflate together under noisy-neighbor load, so the relative bound
    # keeps the assertion meaningful without flaking
    assert best < max(0.5, 25 * best_ref), \
        f"packing {best:.3f} ms/img vs memcpy {best_ref:.3f} ms/img"


def test_arrow_structs_compact(rng):
    """compact=True emits only ok rows, in row order, on every path —
    uniform, resize, chunked — and never zero-fills null slots."""
    import pyarrow as pa
    from sparkdl_tpu.image import arrowStructsToBatch, imageSchema
    arrays = [np.full((8, 8, 3), 10 * (i + 1), np.uint8) for i in range(4)]
    col = _struct_column(arrays, nulls=(1, 3))  # [10, None, 20, None, 30, 40]
    b, ok = arrowStructsToBatch(col, 8, 8, compact=True)
    assert b.shape[0] == 4 and list(ok) == [True, False, True, False,
                                            True, True]
    assert [int(b[k, 0, 0, 2]) for k in range(4)] == [10, 20, 30, 40]
    # resize (general) path
    big = [np.full((16, 16, 3), 7, np.uint8), np.full((16, 16, 3), 9,
                                                      np.uint8)]
    colb = _struct_column(big, nulls=(1,))
    bb, okb = arrowStructsToBatch(colb, 8, 8, compact=True)
    assert bb.shape[0] == 2 and list(okb) == [True, False, True]
    assert (bb[0] == 7).all() and (bb[1] == 9).all()
    # multi-chunk: packed per chunk (no combine_chunks), concatenated
    chunked = pa.chunked_array([_struct_column(arrays[:2], nulls=(1,)),
                                _struct_column(arrays[2:])])
    bc, okc = arrowStructsToBatch(chunked, 8, 8, compact=True)
    assert bc.shape[0] == 4 and okc.sum() == 4
    assert [int(bc[k, 0, 0, 2]) for k in range(4)] == [10, 20, 30, 40]


def test_arrow_structs_multi_chunk_never_combines():
    """Chunked columns must be packed chunk by chunk: combine_chunks on a
    binary child overflows int32 offsets past 2 GB of image bytes
    (ArrowInvalid on pyarrow 25).  pa.ChunkedArray is an immutable C type
    (cannot be spied on), so pin the invariant at the source level for the
    two functions on the image hot path."""
    import inspect

    import sparkdl_tpu.udf.registry as registry_mod
    from sparkdl_tpu.image.io import arrowStructsToBatch
    assert ".combine_chunks(" not in inspect.getsource(arrowStructsToBatch)
    assert ".combine_chunks(" not in inspect.getsource(registry_mod)
