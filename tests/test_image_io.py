"""Image schema & I/O tests — round-trip array<->struct, decode of real
fixture images, malformed input handling (reference C2 test strategy)."""

import numpy as np
import pytest

from sparkdl_tpu.image import (
    PIL_decode,
    createResizeImageUDF,
    filesToDF,
    imageArrayToStruct,
    imageStructToArray,
    imageTypeByMode,
    imageTypeByName,
    ocvTypes,
    readImages,
    resizeImage,
)


def test_ocv_mode_table():
    assert ocvTypes["CV_8UC3"] == 16
    assert imageTypeByName("CV_8UC3").dtype == "uint8"
    assert imageTypeByMode(21).name == "CV_32FC3"
    with pytest.raises(ValueError):
        imageTypeByMode(99)


@pytest.mark.parametrize("dtype,channels", [("uint8", 1), ("uint8", 3),
                                            ("uint8", 4), ("float32", 3)])
def test_array_struct_roundtrip(rng, dtype, channels):
    if dtype == "uint8":
        arr = (rng.random((7, 5, channels)) * 255).astype(np.uint8)
    else:
        arr = rng.random((7, 5, channels)).astype(np.float32)
    s = imageArrayToStruct(arr, origin="mem://x")
    assert s["height"] == 7 and s["width"] == 5 and s["nChannels"] == channels
    back = imageStructToArray(s)
    np.testing.assert_array_equal(arr, back)


def test_struct_validation():
    arr = np.zeros((4, 4, 3), dtype=np.uint8)
    s = imageArrayToStruct(arr)
    s["nChannels"] = 4
    with pytest.raises(ValueError):
        imageStructToArray(s)


def test_decode_real_jpeg_is_bgr(fixture_images):
    with open(fixture_images["paths"][0], "rb") as f:
        raw = f.read()
    bgr = PIL_decode(raw)
    assert bgr is not None and bgr.ndim == 3 and bgr.shape[2] == 3
    from PIL import Image
    rgb = np.asarray(Image.open(fixture_images["paths"][0]).convert("RGB"))
    np.testing.assert_array_equal(bgr[:, :, ::-1], rgb)


def test_decode_failure_returns_none(fixture_images):
    with open(fixture_images["bad"], "rb") as f:
        assert PIL_decode(f.read()) is None


def test_read_images_dataframe(fixture_images):
    df = readImages(fixture_images["dir"])
    assert df.count() == 4  # 3 good + 1 bad (null row kept)
    rows = df.collect()
    nulls = [r for r in rows if r["image"] is None]
    assert len(nulls) == 1
    good = [r for r in rows if r["image"] is not None]
    for r in good:
        arr = imageStructToArray(r["image"])
        assert arr.dtype == np.uint8 and arr.shape[2] == 3


def test_files_to_df_and_partitions(fixture_images):
    df = filesToDF(fixture_images["dir"], numPartitions=2)
    assert df.count() == 4
    assert set(df.columns) == {"filePath", "fileData"}
    assert df.num_partitions == 2


def test_resize_bilinear_parity_with_pil(rng):
    arr = (rng.random((20, 30, 3)) * 255).astype(np.uint8)
    out = resizeImage(arr, 10, 15)
    assert out.shape == (10, 15, 3)
    from PIL import Image
    ref = np.asarray(Image.fromarray(arr).resize((15, 10), Image.BILINEAR))
    np.testing.assert_array_equal(out, ref)
    # float path stays close to the uint8 path (tolerance-based, like the
    # reference's cross-backend resize tests)
    outf = resizeImage(arr.astype(np.float32), 10, 15)
    assert outf.dtype == np.float32
    assert np.abs(outf - ref.astype(np.float32)).max() <= 1.0


def test_resize_udf_on_struct(rng):
    arr = (rng.random((8, 8, 3)) * 255).astype(np.uint8)
    udf = createResizeImageUDF([4, 6])
    out = udf(imageArrayToStruct(arr, origin="o"))
    assert out["height"] == 4 and out["width"] == 6
    assert udf(None) is None
    with pytest.raises(ValueError):
        createResizeImageUDF([1, 2, 3])
