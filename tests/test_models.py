"""Model-zoo parity tests.

The reference's core correctness oracle is tolerance-based parity against a
local Keras/TF run (``python/tests/transformers/named_image_test.py``,
``python/tests/graph/test_pieces.py``).  Same here: each flax zoo model,
loaded with weights imported from its keras.applications twin, must produce
the same logits as Keras (CPU, float32) within tolerance.

BN statistics are randomized before import so the running mean/var import
path is actually binding (fresh Keras BN stats are identity and would hide
bugs).
"""

import numpy as np
import pytest

from sparkdl_tpu.models import (SUPPORTED_MODELS, get_model_spec,
                                import_keras_weights)


def _keras():
    import keras
    return keras


def _build_keras(spec):
    keras = _keras()
    builder = getattr(keras.applications, spec.keras_app)
    # classifier_activation=None: compare logits, which is a binding test
    # even with O(1)-magnitude random weights (softmax of tiny logits would
    # compare near-uniform vectors and hide errors).
    return builder(weights=None, classifier_activation=None)


def _randomize_bn(model, rng):
    """Give BatchNorm (and EfficientNet's input Normalization) layers
    non-trivial statistics so the import is exercised, not defaults."""
    for layer in model.layers:
        tname = type(layer).__name__
        if tname == "Normalization":
            w = layer.get_weights()
            if w:  # [mean, variance, (count)]
                layer.set_weights(
                    [rng.normal(0.0, 0.1, size=w[0].shape).astype("float32"),
                     rng.uniform(0.5, 1.5, size=w[1].shape).astype("float32")]
                    + list(w[2:]))
            continue
        if tname != "BatchNormalization":
            continue
        new = []
        for w in layer.weights:
            shape = w.shape
            n = w.name if hasattr(w, "name") else ""
            if "moving_variance" in n or "variance" in n:
                new.append(rng.uniform(0.5, 1.5, size=shape).astype("float32"))
            elif "moving_mean" in n or "mean" in n:
                new.append(rng.normal(0.0, 0.1, size=shape).astype("float32"))
            elif "gamma" in n:
                new.append(rng.uniform(0.8, 1.2, size=shape).astype("float32"))
            else:  # beta
                new.append(rng.normal(0.0, 0.1, size=shape).astype("float32"))
        layer.set_weights(new)


# Tier-1 time budget (ISSUE 11 satellite; extended by ISSUE 13): a
# model family's shape and keras-parity contracts are identical block
# structure at different depths, so the DEEPEST twins — the heaviest
# calls in the whole tier-1 suite — carry the `slow` mark while the
# cheapest member keeps the family inside the tier-1 gate, and
# run-tests.sh's full pass (no `-m` filter) still runs the deep twins
# on every gate.  ResNet101/152 (~111s, ISSUE 11): ResNet50 stays
# tier-1.  VGG19 (~72s, ISSUE 13 — the next-heaviest offender by the
# --durations profile): VGG16 stays tier-1 and differs from VGG19 only
# by three repeated conv3 blocks.
_DEEP_TWINS = ("ResNet101", "ResNet152", "VGG19")


def _budgeted(models):
    return [pytest.param(n, marks=pytest.mark.slow)
            if n in _DEEP_TWINS else n for n in models]


@pytest.mark.parametrize("name", _budgeted(SUPPORTED_MODELS))
def test_logit_parity_vs_keras(name):
    spec = get_model_spec(name)
    keras_model = _build_keras(spec)
    rng = np.random.default_rng(42)
    _randomize_bn(keras_model, rng)

    h, w = spec.input_size
    x = rng.normal(0.0, 1.0, size=(2, h, w, 3)).astype("float32")
    ref = np.asarray(keras_model.predict(x, verbose=0))

    module = spec.build()
    # Shape-only template: the import must fill every leaf (load_model path).
    variables = import_keras_weights(
        name, keras_model, spec.abstract_variables())
    import jax
    apply = jax.jit(lambda v, x: module.apply(v, x, train=False, logits=True))
    got = np.asarray(apply(variables, x))

    assert got.shape == ref.shape == (2, 1000)
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=2e-3)


@pytest.mark.parametrize("name", _budgeted(SUPPORTED_MODELS))
def test_feature_cut_shape(name):
    spec = get_model_spec(name)
    module = spec.build()
    variables = spec.init_variables()
    h, w = spec.input_size
    x = np.zeros((1, h, w, 3), dtype="float32")
    import jax
    feats = jax.jit(
        lambda v, x: module.apply(v, x, train=False, features=True)
    )(variables, x)
    assert feats.shape == (1, spec.feature_size)


def test_preprocess_parity_vs_keras():
    """Our jax preprocess fns match keras.applications.imagenet_utils for
    every mode on uint8-range input."""
    keras = _keras()
    rng = np.random.default_rng(7)
    x = rng.uniform(0, 255, size=(2, 8, 8, 3)).astype("float32")
    for mode in ("tf", "caffe", "torch"):
        ref = keras.applications.imagenet_utils.preprocess_input(
            x.copy(), mode=mode)
        from sparkdl_tpu.models.preprocess import get_preprocess_fn
        got = np.asarray(get_preprocess_fn(mode)(x))
        np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_unknown_model_rejected():
    with pytest.raises(ValueError, match="Unknown model"):
        get_model_spec("NoSuchNet")


def test_efficientnet_imports_across_repeated_builds():
    """keras auto-suffixes the input Normalization layer name per session
    build ("normalization", "normalization_1", ...); the second import in
    one process must fall back to creation-order matching instead of
    failing by-name (caught live by the round-3 verify drive) — and it
    must import the right VALUES, not just shapes."""
    rng = np.random.default_rng(5)
    spec = get_model_spec("EfficientNetB0")
    for _ in range(2):
        keras_model = _build_keras(spec)
        _randomize_bn(keras_model, rng)
        variables = import_keras_weights(
            "EfficientNetB0", keras_model, spec.abstract_variables())
    norm_layer = next(l for l in keras_model.layers
                      if type(l).__name__ == "Normalization")
    got = variables["batch_stats"]["normalization"]
    np.testing.assert_allclose(
        np.asarray(got["mean"]),
        np.asarray(norm_layer.get_weights()[0]).reshape(-1))
    np.testing.assert_allclose(
        np.asarray(got["var"]),
        np.asarray(norm_layer.get_weights()[1]).reshape(-1))


def test_efficientnet_imagenet_rescaling_fixup():
    """EfficientNetB0(weights="imagenet") inserts a WEIGHTLESS extra
    Rescaling(1/sqrt(std)) after Normalization (upstream tf#49930); the
    import fixup must capture it as post_scale — and leave the default 1
    for weights=None builds (which lack the layer)."""
    from sparkdl_tpu.models.efficientnet import efficientnet_import_fixup

    spec = get_model_spec("EfficientNetB0")

    # weights=None build: single Rescaling, post_scale stays 1
    keras_model = _build_keras(spec)
    variables = import_keras_weights(
        "EfficientNetB0", keras_model, spec.abstract_variables())
    variables = efficientnet_import_fixup(keras_model, variables)
    np.testing.assert_allclose(
        np.asarray(variables["batch_stats"]["normalization"]["post_scale"]),
        np.ones(3))

    # simulate the imagenet build's layer list: a second Rescaling carrying
    # the per-channel correction
    class _FakeRescaling:
        pass

    _FakeRescaling.__name__ = "Rescaling"
    scale = [1.0 / np.sqrt(v) for v in (0.229 ** 2, 0.224 ** 2, 0.225 ** 2)]
    r1, r2 = _FakeRescaling(), _FakeRescaling()
    r1.scale, r2.scale = 1.0 / 255.0, scale

    class _FakeModel:
        layers = [r1, r2]

    variables = efficientnet_import_fixup(_FakeModel(), variables)
    np.testing.assert_allclose(
        np.asarray(variables["batch_stats"]["normalization"]["post_scale"]),
        np.asarray(scale, np.float32), rtol=1e-6)


def test_efficientnet_drop_connect():
    """ADVICE r3: stochastic depth is available for fine-tuning (keras
    recipe parity) behind a rate knob: default 0 is identity (no rng
    needed), rate>0 in train mode drops residual branches per sample,
    and inference is unaffected by the knob."""
    import jax
    import jax.numpy as jnp

    from sparkdl_tpu.models.efficientnet import EfficientNetB0

    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.random((2, 64, 64, 3)) * 255, jnp.float32)

    base = EfficientNetB0(num_classes=5)
    variables = base.init(jax.random.PRNGKey(0), x, train=False)
    out0 = base.apply(variables, x, train=False, features=True)

    sd = EfficientNetB0(num_classes=5, drop_connect_rate=0.9)
    # inference: knob is inert, bit-identical features
    out_inf = sd.apply(variables, x, train=False, features=True)
    np.testing.assert_array_equal(np.asarray(out0), np.asarray(out_inf))
    # train mode with rate>0 needs a dropout rng and perturbs the output
    outs = []
    for seed in (1, 2):
        o, _ = sd.apply(variables, x, train=True, features=True,
                        mutable=["batch_stats"],
                        rngs={"dropout": jax.random.PRNGKey(seed)})
        outs.append(np.asarray(o))
    assert not np.allclose(outs[0], outs[1])
    # rate=0 in train mode stays rng-free (the estimator fine-tune path)
    base.apply(variables, x, train=True, features=True,
               mutable=["batch_stats"])


def test_space_to_depth_conv_parity():
    """SpaceToDepthConv == nn.Conv (VALID, stride==block) bit-for-bit at
    f32 tolerance, across even/odd extents and kernel/stride combos —
    including InceptionV3's stem shape class (odd 2k+1 extent, 3x3/s2)."""
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    from sparkdl_tpu.models.layers import SpaceToDepthConv

    rng = np.random.default_rng(3)
    cases = [
        ((1, 19, 19, 3), (3, 3), (2, 2), 8),   # odd extent (299-like)
        ((2, 20, 24, 3), (3, 3), (2, 2), 8),   # even extent
        ((1, 21, 21, 3), (7, 7), (2, 2), 4),   # kernel > stride*2
        ((1, 16, 16, 4), (4, 4), (4, 4), 8),   # stride 4, kernel == stride
        ((1, 13, 17, 2), (3, 5), (2, 2), 3),   # anisotropic kernel
        # kernel % stride == 0 AND extent % stride != 0: the blocked conv
        # emits one extra padded-tap row/col that must be sliced off
        ((1, 9, 9, 3), (2, 2), (2, 2), 4),
        ((1, 18, 18, 3), (4, 4), (4, 4), 4),
    ]
    for shape, ks, st, feats in cases:
        x = jnp.asarray(rng.normal(size=shape).astype(np.float32))
        ref_mod = nn.Conv(feats, ks, strides=st, padding="VALID",
                          use_bias=False)
        v = ref_mod.init(jax.random.PRNGKey(0), x)
        ref = ref_mod.apply(v, x)
        s2d_mod = SpaceToDepthConv(feats, ks, st)
        got = s2d_mod.apply(v, x)  # SAME variables, by construction
        assert got.shape == ref.shape, (shape, ks, st)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_inception_s2d_stem_model_parity():
    """InceptionV3(s2d_stem=True) is the same function as the default
    model on the same variables (VERDICT r3 #3: the lever must be real,
    gated, and parity-tested), and the registry env knob builds it."""
    import jax

    from sparkdl_tpu.models.inception import InceptionV3

    base = InceptionV3()
    s2d = InceptionV3(s2d_stem=True)
    rng = np.random.default_rng(11)
    x = rng.uniform(0, 255, size=(1, 299, 299, 3)).astype(np.float32)
    x = (x / 127.5) - 1.0
    variables = jax.jit(
        lambda r, xx: base.init(r, xx, train=False))(
        jax.random.PRNGKey(0), x)
    f_base = jax.jit(lambda v, xx: base.apply(v, xx, train=False,
                                              features=True))
    f_s2d = jax.jit(lambda v, xx: s2d.apply(v, xx, train=False,
                                            features=True))
    a = np.asarray(f_base(variables, x))
    b = np.asarray(f_s2d(variables, x))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_inception_s2d_env_gate(monkeypatch):
    from sparkdl_tpu.models import get_model_spec

    spec = get_model_spec("InceptionV3")
    monkeypatch.delenv("SPARKDL_S2D_STEM", raising=False)
    assert spec.build().s2d_stem is False
    monkeypatch.setenv("SPARKDL_S2D_STEM", "1")
    assert spec.build().s2d_stem is True


def test_inception_fused_heads_parity():
    """InceptionV3 fused branch heads (one wide 1x1 conv per mixed block
    instead of 2-3 narrow ones, BN folded into the kernel) is the same
    function as the per-branch model on the same variables, with an
    identical variable tree (VERDICT r4 #2 structural lever)."""
    import jax

    from sparkdl_tpu.models.inception import InceptionV3

    base = InceptionV3(fused_heads=False)
    fh = InceptionV3(fused_heads=True)
    rng = np.random.default_rng(3)
    x = ((rng.uniform(0, 255, size=(1, 299, 299, 3)) / 127.5) - 1.0
         ).astype(np.float32)
    v0 = jax.jit(lambda r, xx: base.init(r, xx, train=False))(
        jax.random.PRNGKey(0), x)
    v1 = jax.eval_shape(lambda: fh.init(jax.random.PRNGKey(0), x,
                                        train=False))
    assert (jax.tree_util.tree_structure(v0)
            == jax.tree_util.tree_structure(v1))
    a = np.asarray(jax.jit(lambda v, xx: base.apply(
        v, xx, train=False, features=True))(v0, x))
    b = np.asarray(jax.jit(lambda v, xx: fh.apply(
        v, xx, train=False, features=True))(v0, x))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_resnet_fused_shortcut_parity(monkeypatch):
    """ResNet50's fused shortcut+reduce conv (downsample blocks) is the
    same function as the per-conv model on the same variables, with an
    identical variable tree; the registry env knob gates and keys it."""
    import jax

    from sparkdl_tpu.models import get_model_spec, model_variant_key
    from sparkdl_tpu.models.resnet import ResNet50

    base = ResNet50(num_classes=4, fused_shortcut=False)
    fused = ResNet50(num_classes=4, fused_shortcut=True)
    rng = np.random.default_rng(5)
    x = rng.normal(0, 1, size=(2, 96, 96, 3)).astype(np.float32)
    v0 = jax.jit(lambda r, xx: base.init(r, xx, train=False))(
        jax.random.PRNGKey(0), x)
    v1 = jax.eval_shape(lambda: fused.init(jax.random.PRNGKey(0), x,
                                           train=False))
    assert (jax.tree_util.tree_structure(v0)
            == jax.tree_util.tree_structure(v1))
    a = np.asarray(jax.jit(lambda v, xx: base.apply(
        v, xx, train=False, features=True))(v0, x))
    b = np.asarray(jax.jit(lambda v, xx: fused.apply(
        v, xx, train=False, features=True))(v0, x))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)
    # train mode takes the plain branch and updates batch_stats
    out, mut = fused.apply(v0, x, train=True, features=True,
                           mutable=["batch_stats"])
    assert "batch_stats" in mut

    spec = get_model_spec("ResNet50")
    monkeypatch.delenv("SPARKDL_RN_FUSED_SHORTCUT", raising=False)
    assert spec.build().fused_shortcut is False   # off until measured
    assert model_variant_key("ResNet50") == ""
    monkeypatch.setenv("SPARKDL_RN_FUSED_SHORTCUT", "1")
    assert spec.build().fused_shortcut is True
    assert model_variant_key("ResNet50") == "fsc"


def test_inception_fused_heads_env_gate(monkeypatch):
    from sparkdl_tpu.models import get_model_spec, model_variant_key

    spec = get_model_spec("InceptionV3")
    monkeypatch.delenv("SPARKDL_FUSED_HEADS", raising=False)
    assert spec.build().fused_heads is None       # auto: on at inference
    assert model_variant_key("InceptionV3") == ""
    monkeypatch.setenv("SPARKDL_FUSED_HEADS", "0")
    assert spec.build().fused_heads is False
    assert model_variant_key("InceptionV3") == "nofh"
    monkeypatch.setenv("SPARKDL_S2D_STEM", "1")
    assert model_variant_key("InceptionV3") == "s2d+nofh"
