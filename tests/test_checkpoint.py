"""Checkpoint/resume tests (orbax-backed).

The capability the reference lacked (SURVEY.md §5: loading only, no saving,
no mid-training checkpointing): pytree save/restore roundtrip, epoch-
cadenced training checkpoints, and a resumed fit reaching the same result
as an uninterrupted one.
"""

import numpy as np
import pytest

from sparkdl_tpu.checkpoint import (TrainCheckpointer, restore_pytree,
                                    save_pytree)
from sparkdl_tpu.parallel.train import fit_data_parallel


def test_pytree_roundtrip(tmp_path, rng):
    tree = {
        "w": rng.normal(size=(4, 3)).astype(np.float32),
        "nested": {"b": np.arange(5, dtype=np.int32)},
    }
    path = save_pytree(str(tmp_path / "ckpt"), tree)
    back = restore_pytree(path)
    np.testing.assert_array_equal(back["w"], tree["w"])
    np.testing.assert_array_equal(back["nested"]["b"], tree["nested"]["b"])
    # template-guided restore preserves dtypes
    back2 = restore_pytree(path, template=tree)
    assert back2["w"].dtype == np.float32


def test_train_checkpointer_cadence_and_latest(tmp_path):
    ck = TrainCheckpointer(str(tmp_path / "fits"), every_epochs=2)
    assert ck.latest() is None
    assert ck.maybe_save(1, {"a": np.ones(2)}) is None  # off-cadence
    assert ck.maybe_save(2, {"a": np.ones(2) * 2}) is not None
    assert ck.maybe_save(4, {"a": np.ones(2) * 4}) is not None
    epoch, path = ck.latest()
    assert epoch == 4 and path.endswith("epoch_000004")
    epoch, state = ck.restore_latest()
    assert epoch == 4
    np.testing.assert_array_equal(state["a"], np.ones(2) * 4)


def test_fit_resume_matches_uninterrupted(tmp_path, rng):
    import jax.numpy as jnp
    import optax

    w_true = rng.normal(size=(4, 1)).astype(np.float32)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = x @ w_true

    def predict(p, xb):
        return jnp.asarray(xb) @ p["w"]

    def run(ckpt_dir, epochs):
        params = {"w": np.zeros((4, 1), np.float32)}
        return fit_data_parallel(
            predict, params, x, y, optimizer=optax.sgd(0.05), loss="mse",
            batch_size=8, epochs=epochs, seed=3,
            checkpoint_dir=ckpt_dir, checkpoint_every_epochs=1)

    # uninterrupted 6-epoch fit
    full, losses_full = run(str(tmp_path / "full"), 6)
    # interrupted at 3 epochs, then "restarted" asking for 6 -> resumes at 4
    part_dir = str(tmp_path / "part")
    run(part_dir, 3)
    resumed, losses_resumed = run(part_dir, 6)
    assert len(losses_resumed) == 3  # only epochs 4..6 ran after resume
    np.testing.assert_allclose(resumed["w"], full["w"], rtol=1e-5, atol=1e-6)


def test_maybe_save_gated_to_writer_process(tmp_path, monkeypatch):
    """Multi-controller: only process 0 writes checkpoints — concurrent
    orbax tmp-dir renames from several hosts race on shared storage
    (ADVICE round 2)."""
    import jax

    from sparkdl_tpu.checkpoint import TrainCheckpointer

    ck = TrainCheckpointer(str(tmp_path / "ck"))
    state = {"w": np.zeros(2, np.float32)}
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    assert not ck.is_writer()
    assert ck.maybe_save(1, state) is None
    assert ck.latest() is None
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    assert ck.is_writer()
    assert ck.maybe_save(1, state) is not None
    assert ck.latest()[0] == 1
