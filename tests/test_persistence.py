"""Stage persistence round-trips (VERDICT round 1, Missing #6): the Spark
ML writable/readable contract — fit -> save -> load -> identical transform
output."""

import os

import numpy as np
import pytest

from sparkdl_tpu.estimators import (ImageFileEstimator,
                                    KerasImageFileEstimator,
                                    LogisticRegression)
from sparkdl_tpu.estimators.classification import LogisticRegressionModel
from sparkdl_tpu.frame import DataFrame
from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.transformers import (DeepImageFeaturizer, PipelineModel,
                                      TFImageTransformer)
from sparkdl_tpu.transformers.image_file import ImageFileTransformer


# module-level (picklable) model fn + loader

def _linear_fn(v, x):
    import jax.numpy as jnp

    return jnp.asarray(x).reshape(x.shape[0], -1) @ v["w"]


def _loader8(uri):
    from PIL import Image

    img = Image.open(uri).convert("RGB").resize((8, 8))
    return np.asarray(img, dtype=np.float32) / 255.0


def test_zoo_transformer_roundtrip(tmp_path):
    ft = DeepImageFeaturizer(inputCol="image", outputCol="features",
                             modelName="ResNet50", batchSize=16)
    p = str(tmp_path / "featurizer")
    ft.save(p)
    loaded = DeepImageFeaturizer.load(p)
    assert loaded.getModelName() == "ResNet50"
    assert loaded.getBatchSize() == 16
    assert loaded.getInputCol() == "image"
    # overwrite contract
    with pytest.raises(FileExistsError):
        ft.save(p)
    ft.save(p, overwrite=True)


def test_logistic_regression_model_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(60, 4)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    df = DataFrame({"features": [list(map(float, r)) for r in x],
                    "label": y})
    model = LogisticRegression(maxIter=20, learningRate=0.2).fit(df)
    p = str(tmp_path / "lr")
    model.save(p)
    loaded = LogisticRegressionModel.load(p)
    a = model.transform(df).collect()
    b = loaded.transform(df).collect()
    for ra, rb in zip(a, b):
        assert ra["prediction"] == rb["prediction"]
        np.testing.assert_allclose(ra["probability"], rb["probability"],
                                   rtol=1e-6)


def test_image_file_model_roundtrip(tmp_path, fixture_images):
    paths = fixture_images["paths"] * 4
    labels = [[1.0, 0.0] if i % 2 == 0 else [0.0, 1.0]
              for i in range(len(paths))]
    df = DataFrame({"uri": paths, "label": labels})
    rng = np.random.default_rng(1)
    mf = ModelFunction(fn=_linear_fn, variables={
        "w": rng.normal(0, 0.01, (8 * 8 * 3, 2)).astype(np.float32)})
    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=mf, imageLoader=_loader8, optimizer="sgd",
        loss="mse", fitParams={"epochs": 2}, batchSize=8)
    model = est.fit(df)
    p = str(tmp_path / "model")
    model.save(p)
    from sparkdl_tpu.estimators.image_file_estimator import ImageFileModel

    loaded = ImageFileModel.load(p)
    assert loaded.trainLosses == pytest.approx(model.trainLosses)
    a = model.transform(df).collect()
    b = loaded.transform(df).collect()
    for ra, rb in zip(a, b):
        np.testing.assert_allclose(ra["preds"], rb["preds"], rtol=1e-6)


def test_keras_image_file_model_roundtrip(tmp_path, fixture_images):
    import keras
    from keras import layers

    km = keras.Sequential([
        layers.Input((8, 8, 3)),
        layers.Conv2D(2, 3, padding="same"),
        layers.GlobalAveragePooling2D(),
        layers.Dense(2, activation="softmax"),
    ])
    kpath = str(tmp_path / "tiny.keras")
    km.save(kpath)
    paths = fixture_images["paths"] * 4
    labels = [[1.0, 0.0] if i % 2 == 0 else [0.0, 1.0]
              for i in range(len(paths))]
    df = DataFrame({"uri": paths, "label": labels})
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFile=kpath, imageLoader=_loader8, kerasOptimizer="sgd",
        kerasLoss="categorical_crossentropy",
        kerasFitParams={"epochs": 1}, batchSize=8)
    model = est.fit(df)
    p = str(tmp_path / "fitted")
    model.save(p)  # must NOT try to pickle keras closures
    from sparkdl_tpu.estimators.image_file_estimator import ImageFileModel

    loaded = ImageFileModel.load(p)
    a = model.transform(df).collect()
    b = loaded.transform(df).collect()
    for ra, rb in zip(a, b):
        np.testing.assert_allclose(ra["preds"], rb["preds"], rtol=1e-5,
                                   atol=1e-6)


def test_pipeline_model_roundtrip(tmp_path, fixture_images):
    from sparkdl_tpu.image.io import readImages

    df = readImages(fixture_images["dir"])
    mf = ModelFunction(fn=_linear_fn, variables={
        "w": np.full((16 * 16 * 3, 4), 0.01, np.float32)})
    t = TFImageTransformer(inputCol="image", outputCol="feats",
                           modelFunction=mf, inputSize=[16, 16],
                           outputMode="vector", batchSize=8)
    pm = PipelineModel([t])
    p = str(tmp_path / "pipe")
    pm.save(p)
    loaded = PipelineModel.load(p)
    a = pm.transform(df).collect()
    b = loaded.transform(df).collect()
    for ra, rb in zip(a, b):
        if ra["feats"] is None:
            assert rb["feats"] is None
        else:
            np.testing.assert_allclose(ra["feats"], rb["feats"], rtol=1e-6)


def test_lambda_model_fn_fails_loudly(tmp_path):
    mf = ModelFunction(fn=lambda v, x: x, variables={})
    t = ImageFileTransformer(inputCol="uri", outputCol="out",
                             modelFunction=mf, imageLoader=_loader8)
    with pytest.raises(ValueError, match="non-picklable"):
        t.save(str(tmp_path / "bad"))


def test_load_type_check(tmp_path):
    ft = DeepImageFeaturizer(inputCol="image", outputCol="f",
                             modelName="VGG16")
    p = str(tmp_path / "ft")
    ft.save(p)
    with pytest.raises(TypeError, match="not a"):
        LogisticRegressionModel.load(p)


def _train_fn_stub(v, x):
    return _linear_fn(v, x), {}


def test_train_fn_roundtrips(tmp_path):
    """A picklable train_fn survives save/load so the restored model can
    still re-fit with trainBatchStats=True (ADVICE round 2)."""
    from sparkdl_tpu.estimators.image_file_estimator import ImageFileModel

    rng = np.random.default_rng(0)
    mf = ModelFunction(
        fn=_linear_fn, train_fn=_train_fn_stub,
        variables={"w": rng.normal(0, 0.01, (8 * 8 * 3, 2)).astype(np.float32)})
    model = ImageFileModel(modelFunction=mf)
    model._set(inputCol="uri", outputCol="preds", imageLoader=_loader8,
               batchSize=8)
    p = str(tmp_path / "with_train_fn")
    model.save(p)
    loaded = ImageFileModel.load(p)
    lmf = loaded.getModelFunction()
    assert lmf.train_fn is not None
    x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    pred, stats = lmf.train_fn(lmf.variables, x)
    np.testing.assert_allclose(np.asarray(pred),
                               np.asarray(_linear_fn(mf.variables, x)))


def test_closure_train_fn_dropped_not_fatal(tmp_path):
    """An unpicklable train_fn (e.g. from_flax closures) must not fail a
    save that used to succeed: it is dropped with a warning and the loaded
    model has train_fn=None."""
    from sparkdl_tpu.estimators.image_file_estimator import ImageFileModel

    rng = np.random.default_rng(0)
    mf = ModelFunction(
        fn=_linear_fn, train_fn=lambda v, x: (_linear_fn(v, x), {}),
        variables={"w": rng.normal(0, 0.01, (8 * 8 * 3, 2)).astype(np.float32)})
    model = ImageFileModel(modelFunction=mf)
    model._set(inputCol="uri", outputCol="preds", batchSize=8)
    p = str(tmp_path / "closure_train_fn")
    model.save(p)
    loaded = ImageFileModel.load(p)
    assert loaded.getModelFunction().train_fn is None
