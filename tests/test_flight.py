"""Flight recorder + SLO burn-rate engine tests (ISSUE 9).

Contracts pinned here:
  * the ``SPARKDL_BLACKBOX`` gate and the near-zero DISABLED path
    (module-global read, no recorder allocated);
  * the bounded event ring: catalog-validated names, monotonic ``seq``,
    oldest-first eviction, wall + monotonic stamps, trace-id capture;
  * durability: incremental fsync'd dumps (each event on disk exactly
    once across triggers), the explicit-path full export, the
    ready->degraded synchronous dump, the SIGTERM dump, and the SIGKILL
    crash test — a child dies mid-incident and the recovered dump is
    valid JSONL (shared ``recover_jsonl``) holding the pre-kill
    breaker/health events;
  * the SLO engine: declarative objective validation, availability
    burn-rate math that flips breach at the EXACT synthetic crossing,
    the two-window guard (long window ignores blips, short window ends
    the episode), latency/lag kinds, HealthTracker degradation with
    ``SLOViolation`` in ``last_error``, and the ``slos=`` wiring in
    ``Server``/``StreamScorer`` ``health()``;
  * the unified ``health()`` payload schema (``utils.health.
    health_payload``) spoken by all three surfaces — Server, Fleet,
    StreamScorer — as one contract;
  * graftlint SDL008: ``flight_emit``/``flight.emit`` literals must
    exist in the ``EVENT_HELP`` catalog (static half of
    ``validate_event``), with the ast-read registry matching runtime;
  * ``tools/blackbox.py``: timeline document schema, exit codes, and
    THE acceptance chaos — breaker trip mid-rollout + stream stall
    reconstructed as the full causal chain, trace-id-correlated with
    the span JSONL, deterministic across two seeded runs.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu import faults, obs
from sparkdl_tpu.faults import FaultPlan
from sparkdl_tpu.obs import flight
from sparkdl_tpu.obs.flight import FlightRecorder
from sparkdl_tpu.obs.slo import SLO, SLOEngine, SLOViolation, slo_snapshot
from sparkdl_tpu.utils.health import (HEALTH_STATES, HealthTracker,
                                      health_payload)
from sparkdl_tpu.utils.jsonl import read_jsonl, recover_jsonl
from sparkdl_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _restore_flight():
    """Every test leaves the process recorder (and tracer) the way the
    environment configures them (disabled in the test env)."""
    yield
    r = flight.get_recorder()
    if r is not None:
        r.close()
    flight.configure_from_env()
    obs.configure_from_env()


def _fn(variables, x):
    import jax.numpy as jnp

    return jnp.tanh(x @ variables["w"])


# -- catalog + ring --------------------------------------------------------

def test_event_catalog_shape_and_validate():
    assert flight.EVENTS == tuple(flight.EVENT_HELP)
    for name, help_s in flight.EVENT_HELP.items():
        assert name == name.lower() and "." in name, name
        assert isinstance(help_s, str) and help_s
        assert flight.validate_event(name) == name
    with pytest.raises(ValueError, match="unknown flight event"):
        flight.validate_event("breaker.opne")


def test_disabled_by_default_and_off_path():
    """SPARKDL_BLACKBOX unset: no recorder exists and emit is a no-op
    returning None — the near-zero path the overhead guard times."""
    flight.configure_from_env()
    assert flight.get_recorder() is None
    assert flight.emit("breaker.open", error="X") is None


def test_ring_bounded_seq_monotonic_and_snapshot_copies():
    rec = flight.configure(enabled=True, capacity=4)
    for i in range(6):
        rec.record("retry.attempt", {"attempt": i})
    assert len(rec) == 4
    snap = rec.snapshot()
    # oldest evicted first: attempts 2..5 survive, seq strictly rises
    assert [e["attrs"]["attempt"] for e in snap] == [2, 3, 4, 5]
    assert [e["seq"] for e in snap] == sorted(e["seq"] for e in snap)
    for e in snap:
        assert e["pid"] == os.getpid()
        assert e["t_wall"] > 0 and e["t_mono"] > 0
        assert e["trace_id"] is None  # tracing off in the test env
    snap[0]["event"] = "mutated"  # copies: the ring is not aliased
    assert rec.snapshot()[0]["event"] == "retry.attempt"
    with pytest.raises(ValueError, match="unknown flight event"):
        rec.record("not.registered")
    # non-scalar attrs are stringified at emit time (always serializable)
    ev = rec.record("fault.fired", {"error": RuntimeError("boom")})
    json.dumps(ev)
    assert "boom" in ev["attrs"]["error"]


def test_blackbox_env_grammar(monkeypatch):
    for raw, want in [("", (False, None)), ("0", (False, None)),
                      ("off", (False, None)), ("1", (True, None)),
                      ("true", (True, None)),
                      ("/tmp/bb", (True, "/tmp/bb"))]:
        monkeypatch.setenv("SPARKDL_BLACKBOX", raw)
        assert flight.blackbox_from_env() == want


def test_emit_captures_active_trace_id(tmp_path):
    flight.configure(enabled=True)
    obs.configure(enabled=True)
    tracer = obs.get_tracer()
    span = tracer.start_span("serving.request")
    with tracer.use(span):
        ev = flight.emit("serving.shed", reason="queue_full")
    span.finish()
    assert ev["trace_id"] == span.trace_id


# -- durability ------------------------------------------------------------

def test_incremental_dump_each_event_once_and_explicit_export(tmp_path):
    rec = FlightRecorder(out_dir=str(tmp_path))
    rec.record("breaker.open", {"consecutive": 3})
    rec.record("serving.shed", {"reason": "queue_full"})
    p = rec.dump()
    assert p and os.path.basename(p) == f"flight_{os.getpid()}.jsonl"
    rec.record("breaker.close")
    assert rec.dump() == p
    events = flight.load_flight(p)
    assert [e["event"] for e in events] == [
        "breaker.open", "serving.shed", "breaker.close"]  # no dupes
    # explicit path: full-snapshot export (truncating one-off copy)
    exp = str(tmp_path / "export.jsonl")
    assert rec.dump(exp) == exp
    assert [e["event"] for e in flight.load_flight(exp)] == [
        "breaker.open", "serving.shed", "breaker.close"]
    rec.close()


def test_degraded_transition_triggers_durable_dump(tmp_path):
    """ready->degraded is the synchronous dump trigger: the moment the
    next instants stop being trustworthy, the past is already on disk."""
    flight.configure(enabled=True, out_dir=str(tmp_path))
    t = HealthTracker("serving.health")
    t.note_failure(RuntimeError("device dead"))
    files = glob.glob(str(tmp_path / "flight_*.jsonl"))
    assert len(files) == 1  # no explicit dump() call was made
    events = flight.load_flight(files[0])
    assert events[-1]["event"] == "health.degraded"
    assert events[-1]["attrs"]["tracker"] == "serving.health"
    t.note_success()  # ready: recorded in the ring, not a dump trigger
    names = [e["event"] for e in flight.get_recorder().snapshot()]
    assert names == ["health.degraded", "health.ready"]


def test_sigkill_mid_incident_dump_recovers(tmp_path):
    """ISSUE 9 satellite: a child SIGKILLs itself mid-incident (torn
    write in flight) and the recovered dump is valid JSONL — the shared
    ``recover_jsonl`` path — containing the pre-kill breaker/health
    events."""
    child = r"""
import os, signal
from sparkdl_tpu.obs import flight
from sparkdl_tpu.utils.health import HealthTracker

flight.emit("breaker.open", consecutive=2, error="InjectedDeadDeviceError")
t = HealthTracker("serving.health")
t.note_failure(RuntimeError("device dead mid-incident"))  # durable dump
# tear the tail exactly as a crash mid-append would, then die for real
with open(flight.get_recorder().dump(), "ab") as fh:
    fh.write(b'{"seq": 999, "event": "health.re')
    fh.flush(); os.fsync(fh.fileno())
os.kill(os.getpid(), signal.SIGKILL)
"""
    env = dict(os.environ, SPARKDL_BLACKBOX=str(tmp_path))
    proc = subprocess.run([sys.executable, "-c", child], env=env,
                          cwd=REPO, capture_output=True, timeout=60)
    assert proc.returncode == -signal.SIGKILL, proc.stderr.decode()
    files = glob.glob(str(tmp_path / "flight_*.jsonl"))
    assert len(files) == 1
    records, discarded = recover_jsonl(files[0])
    assert discarded > 0  # the torn tail was really there and truncated
    assert [r["event"] for r in records] == ["breaker.open",
                                             "health.degraded"]
    assert records[1]["attrs"]["tracker"] == "serving.health"
    clean, _ = read_jsonl(files[0])  # post-recovery file parses whole
    assert clean == records


def test_sigterm_dumps_before_termination(tmp_path):
    """SIGTERM: dump, then die of the signal (default disposition
    re-raised) — no degraded transition needed for durability."""
    child = r"""
import os, signal
from sparkdl_tpu.obs import flight

flight.emit("breaker.open", error="X")
flight.emit("serving.shed", reason="queue_full")
os.kill(os.getpid(), signal.SIGTERM)
"""
    env = dict(os.environ, SPARKDL_BLACKBOX=str(tmp_path))
    proc = subprocess.run([sys.executable, "-c", child], env=env,
                          cwd=REPO, capture_output=True, timeout=60)
    assert proc.returncode == -signal.SIGTERM, proc.stderr.decode()
    files = glob.glob(str(tmp_path / "flight_*.jsonl"))
    assert len(files) == 1
    assert [e["event"] for e in flight.load_flight(files[0])] == [
        "breaker.open", "serving.shed"]


# -- SLO engine ------------------------------------------------------------

def test_slo_declaration_validation():
    with pytest.raises(ValueError, match="kind"):
        SLO("x", "throughput")
    with pytest.raises(ValueError, match="good="):
        SLO("x", "availability", objective=0.99)
    with pytest.raises(ValueError, match="objective"):
        SLO("x", "availability", good="g", total="t", objective=1.5)
    with pytest.raises(ValueError, match="threshold_ms"):
        SLO("x", "latency", series="s")
    with pytest.raises(ValueError, match="gauge="):
        SLO("x", "lag", threshold_s=30.0)
    with pytest.raises(TypeError, match="SLO instances"):
        SLOEngine(Metrics(), [{"name": "x"}])
    slo = SLO("avail", "availability", good="g", total="t",
              objective=0.999)
    assert slo.burn_threshold == 14.4  # the fast-burn page default
    assert slo.as_dict()["objective"] == 0.999


def test_availability_burn_flips_at_exact_crossing():
    """THE chip-free SLO determinism guard: with synthetic clocks and
    counters, the breach flips exactly when the windowed burn rate
    reaches ``burn_threshold`` — 1.9 holds, 2.0 flips — and degrades
    the attached HealthTracker naming the objective."""
    m = Metrics()
    health = HealthTracker("slo.test.health", name="slo-owner")
    eng = SLOEngine(
        m, [SLO("avail", "availability", good="ok", total="all",
                objective=0.9, burn_threshold=2.0)],
        health=health, short_window_s=5.0, long_window_s=300.0)
    flight.configure(enabled=True)
    assert eng.evaluate(now=0.0)["state"] == "ok"  # baseline, no traffic
    m.incr("all", 100.0)
    m.incr("ok", 81.0)   # bad 19% -> burn 1.9: UNDER threshold 2.0
    out = eng.evaluate(now=10.0)
    assert out["state"] == "ok"
    assert out["objectives"][0]["burn_short"] == pytest.approx(1.9)
    assert health.snapshot()["state"] == "ready"
    m.incr("all", 100.0)
    m.incr("ok", 79.0)   # cumulative bad 40/200 = 20% -> long burn 2.0
    out = eng.evaluate(now=20.0)
    # the LONG window (baseline: the t=0 zero sample) crosses at
    # EXACTLY threshold (>= is a breach); the short window (baseline:
    # the t=10 sample) burns 21/100 -> 2.1 — both at/over: breach
    assert out["state"] == "breach"
    assert out["objectives"][0]["burn_long"] == pytest.approx(2.0)
    assert out["objectives"][0]["burn_short"] == pytest.approx(2.1)
    assert out["objectives"][0]["burn"] == pytest.approx(2.1)
    snap = health.snapshot()
    assert snap["state"] == "degraded"
    assert snap["last_error"]["type"] == "SLOViolation"
    assert "avail" in snap["last_error"]["error"]
    # recovery: the SHORT window (5 s -> baseline = the t=20 sample)
    # sees clean traffic only and ends the episode
    m.incr("all", 100.0)
    m.incr("ok", 100.0)
    out = eng.evaluate(now=26.0)
    assert out["state"] == "ok"
    assert health.snapshot()["state"] == "ready"
    names = [e["event"] for e in flight.get_recorder().snapshot()
             if e["event"].startswith("slo.")]
    assert names == ["slo.breach", "slo.recovered"]


def test_two_window_guard_long_window_ignores_blips():
    """A short error blip burns the SHORT window hot while the LONG
    window stays under threshold -> no breach (the classic guard)."""
    m = Metrics()
    eng = SLOEngine(
        m, [SLO("avail", "availability", good="ok", total="all",
                objective=0.9, burn_threshold=2.0)],
        short_window_s=5.0, long_window_s=300.0)
    eng.evaluate(now=0.0)         # zero baseline
    m.incr("all", 1000.0)
    m.incr("ok", 1000.0)          # long history of clean traffic
    eng.evaluate(now=100.0)
    m.incr("all", 10.0)
    m.incr("ok", 5.0)             # blip: 50% bad over the short window
    out = eng.evaluate(now=304.0)
    st = out["objectives"][0]
    assert st["burn_short"] == pytest.approx(5.0)       # blazing
    assert st["burn_long"] == pytest.approx(5.0 / 1010 / 0.1, rel=1e-2)
    assert out["state"] == "ok"  # the long window refused the page


def test_slo_recovery_never_clears_unrelated_degradation():
    """An SLO recovery calls note_success only while the tracker's
    last_error is still the SLO's own violation — a dispatch failure
    that degraded the tracker AFTER the breach keeps its 'no success
    since' episode until a real success."""
    m = Metrics()
    health = HealthTracker("slo.test.health", name="t")
    eng = SLOEngine(
        m, [SLO("avail", "availability", good="ok", total="all",
                objective=0.9, burn_threshold=1.0)],
        health=health, short_window_s=5.0, long_window_s=5.0)
    eng.evaluate(now=0.0)
    m.incr("all", 10.0)            # 100% bad -> breach
    eng.evaluate(now=10.0)
    assert health.snapshot()["last_error"]["type"] == "SLOViolation"
    # an unrelated failure lands while the SLO is still breaching
    health.note_failure(RuntimeError("device died"))
    m.incr("all", 100.0)
    m.incr("ok", 100.0)            # clean traffic -> objective recovers
    out = eng.evaluate(now=20.0)
    assert out["state"] == "ok"
    snap = health.snapshot()
    assert snap["state"] == "degraded"          # episode survives
    assert snap["last_error"]["type"] == "RuntimeError"
    health.note_success()                       # only a REAL success ends it
    assert health.snapshot()["state"] == "ready"


def test_latency_and_lag_burn_kinds():
    m = Metrics()
    for v in [0.05] * 9 + [0.199]:
        m.record_time("serving.request_latency", v)
    eng = SLOEngine(m, [SLO("p99", "latency",
                            series="serving.request_latency",
                            threshold_ms=200.0)])
    st = eng.evaluate(now=1.0)["objectives"][0]
    assert st["state"] == "ok" and st["burn"] < 1.0
    m.record_time("serving.request_latency", 0.400)  # p99 over budget
    st = eng.evaluate(now=2.0)["objectives"][0]
    assert st["state"] == "breach" and st["burn"] >= 1.0

    m2 = Metrics()
    eng2 = SLOEngine(m2, [SLO("lag", "lag", gauge="stream.lag_seconds",
                              threshold_s=30.0)])
    st = eng2.evaluate(now=1.0)["objectives"][0]
    assert st["state"] == "ok" and st["burn"] is None  # no gauge yet
    m2.gauge("stream.lag_seconds", 29.9)
    assert eng2.evaluate(now=2.0)["objectives"][0]["state"] == "ok"
    m2.gauge("stream.lag_seconds", 30.0)  # the exact crossing again
    st = eng2.evaluate(now=3.0)["objectives"][0]
    assert st["state"] == "breach" and st["burn"] == pytest.approx(1.0)


def test_default_objectives_and_bench_slo_snapshot():
    m = Metrics()
    assert slo_snapshot(m) is None  # nothing recorded -> no verdict
    m.incr("serving.requests", 10.0)
    m.incr("serving.completed", 10.0)
    m.record_time("serving.request_latency", 0.01)
    snap = slo_snapshot(m)
    assert snap["state"] == "ok"
    assert {o["name"] for o in snap["objectives"]} == {
        "serving-availability", "serving-p99-latency"}
    json.dumps(snap)  # the bench rider must always serialize
    m.incr("serving.requests", 10.0)   # 10 new requests, none complete
    assert slo_snapshot(m)["state"] == "breach"
    m2 = Metrics()
    m2.incr("fleet.requests", 5.0)
    m2.incr("fleet.completed", 5.0)
    m2.incr("stream.chunks", 3.0)
    m2.incr("stream.commits", 3.0)
    m2.gauge("stream.lag_seconds", 0.5)
    names = {o["name"] for o in slo_snapshot(m2)["objectives"]}
    assert names == {"fleet-availability", "stream-commit-availability",
                     "stream-watermark-lag"}


def test_server_health_slo_wiring(tmp_path):
    """``Server(slos=[...])``: every health() poll takes one burn-rate
    sample; a breach degrades the server's own tracker and the
    evaluation rides ``health()["slo"]``."""
    from sparkdl_tpu.serving import Server

    rng = np.random.default_rng(5)
    w = {"w": rng.normal(size=(12, 5)).astype(np.float32)}
    x = rng.normal(size=(12,)).astype(np.float32)
    with Server(_fn, w, max_batch_size=8, max_wait_ms=1,
                bucket_sizes=[8],
                slos=[SLO("p99", "latency",
                          series="serving.request_latency",
                          threshold_ms=1e-6)]) as srv:
        h = srv.health()
        assert h["slo"]["state"] == "ok"  # no traffic yet: no verdict
        np.asarray(srv.predict(x))        # any real latency breaches
        h = srv.health()
        assert h["slo"]["state"] == "breach"
        assert h["state"] == "degraded"
        assert h["last_error"]["type"] == "SLOViolation"
        json.dumps(srv.varz())


def test_stream_health_slo_wiring(tmp_path):
    from sparkdl_tpu import streaming
    from sparkdl_tpu.parallel.engine import InferenceEngine

    rng = np.random.default_rng(6)
    eng = InferenceEngine(_fn, {"w": rng.normal(size=(8, 4)).astype(
        np.float32)}, device_batch_size=8)
    sc = streaming.StreamScorer(
        eng, streaming.MemorySource([], finished=True),
        journal_path=str(tmp_path / "j.jsonl"),
        out_dir=str(tmp_path / "out"),
        slos=[SLO("lag", "lag", gauge="stream.lag_seconds",
                  threshold_s=30.0)])
    assert sc.health()["slo"]["objectives"][0]["state"] == "ok"
    sc.metrics.gauge("stream.lag_seconds", 31.0)
    h = sc.health()
    assert h["slo"]["state"] == "breach"
    assert h["state"] == "degraded"
    assert h["last_error"]["type"] == "SLOViolation"


# -- unified health contract (satellite) -----------------------------------

def test_health_payload_schema_guards():
    p = health_payload(live=True, state="ready", breaker={})
    assert list(p)[:4] == ["live", "state", "last_error", "transitions"]
    with pytest.raises(ValueError, match="health state"):
        health_payload(live=True, state="sideways")


def test_health_contract_shared_by_all_three_surfaces(tmp_path):
    """The one schema ``blackbox`` parses: Server, Fleet, and
    StreamScorer all build health() through ``HealthTracker.payload``
    — same core keys, same state vocabulary, JSON-serializable."""
    from sparkdl_tpu import streaming
    from sparkdl_tpu.parallel.engine import InferenceEngine
    from sparkdl_tpu.serving import Fleet, Server

    rng = np.random.default_rng(7)
    w = {"w": rng.normal(size=(12, 5)).astype(np.float32)}
    payloads = {}
    with Server(_fn, w, max_batch_size=8, max_wait_ms=1,
                bucket_sizes=[8]) as srv:
        payloads["server"] = srv.health()
    with Fleet(max_batch_size=8, max_wait_ms=1, bucket_sizes=[8]) as fl:
        fl.add_model("m", _fn, w)
        payloads["fleet"] = fl.health()
    eng = InferenceEngine(_fn, w, device_batch_size=8)
    sc = streaming.StreamScorer(
        eng, streaming.MemorySource([], finished=True),
        journal_path=str(tmp_path / "j.jsonl"),
        out_dir=str(tmp_path / "out"))
    payloads["stream"] = sc.health()
    for surface, h in payloads.items():
        assert list(h)[:4] == ["live", "state", "last_error",
                               "transitions"], surface
        assert h["state"] in HEALTH_STATES, surface
        assert isinstance(h["live"], bool), surface
        assert isinstance(h["transitions"], list) and h["transitions"]
        for tr in h["transitions"]:
            assert set(tr) == {"state", "t_monotonic"}, surface
        json.dumps(h)
    # the surface extras still ride along, outside the core contract
    assert "breaker" in payloads["server"]
    assert "models" in payloads["fleet"]
    assert {"watermark", "lag_s", "source_exhausted"} <= set(
        payloads["stream"])


# -- graftlint SDL008 ------------------------------------------------------

def test_sdl008_unknown_event_flagged_known_clean():
    from sparkdl_tpu.analysis import lint_source

    events = {"breaker.open", "serving.shed"}
    bad = 'flight_emit("breaker.opne", error="x")\n'
    found = lint_source(bad, events=events)
    assert [f.code for f in found] == ["SDL008"]
    assert "breaker.opne" in found[0].message
    ok = ('flight_emit("breaker.open")\n'
          'flight.emit("serving.shed", reason="full")\n')
    assert lint_source(ok, events=events) == []
    # dynamic names are the runtime half's job (validate_event)
    assert lint_source("flight_emit(name)\n", events=events) == []
    # an unrelated emit() spelling is never claimed
    assert lint_source('emit("config", "m", 1.0, "u")\n',
                       events=events) == []


def test_sdl008_missing_catalog_and_pragma():
    from sparkdl_tpu.analysis import lint_source

    found = lint_source('flight_emit("breaker.open")\n', events=None)
    assert [f.code for f in found] == ["SDL008"]
    assert "no catalog" in found[0].message
    suppressed = ('flight_emit("not.yet.registered")  '
                  '# graftlint: allow=SDL008 reason=staged rollout\n')
    assert lint_source(suppressed, events={"breaker.open"}) == []


def test_sdl008_registry_loader_matches_runtime():
    """The ast-read catalog (what the linter checks against) and the
    runtime EVENTS tuple (what validate_event enforces) can never
    drift — same file, both halves pinned equal here."""
    from sparkdl_tpu.analysis import (load_event_registry,
                                      load_event_registry_file)

    path = os.path.join(REPO, "sparkdl_tpu", "obs", "flight.py")
    assert load_event_registry_file(path) == set(flight.EVENTS)
    assert load_event_registry([os.path.join(REPO, "sparkdl_tpu")]) == \
        set(flight.EVENTS)


def test_graftlint_cli_events_file(tmp_path):
    bad = tmp_path / "mod.py"
    bad.write_text('flight_emit("breaker.opne")\n')
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graftlint.py"),
         str(bad), "--events-file",
         os.path.join(REPO, "sparkdl_tpu", "obs", "flight.py")],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "SDL008" in proc.stdout


# -- blackbox --------------------------------------------------------------

def _write_dump(path, events):
    rec = FlightRecorder()
    for name, attrs in events:
        rec.record(name, attrs)
    rec.dump(path)


def test_blackbox_document_and_exit_codes(tmp_path):
    from tools.blackbox import build_timeline, main

    clean = str(tmp_path / "clean.jsonl")
    _write_dump(clean, [
        ("fault.fired", {"site": "engine.dispatch"}),
        ("health.degraded", {"tracker": "serving.health"}),
        ("health.ready", {"tracker": "serving.health"}),
    ])
    doc = build_timeline(clean)
    assert doc["chain"] == ["fault.fired", "health.degraded",
                            "health.ready"]
    assert doc["health"] == {"serving.health": "ready"}
    assert doc["verdict"]["clean"] is True
    assert doc["events"][0]["rel_s"] == 0.0
    json.dumps(doc)
    assert main([clean]) == 0

    unresolved = str(tmp_path / "unresolved.jsonl")
    _write_dump(unresolved, [
        ("breaker.open", {"error": "X"}),
        ("health.degraded", {"tracker": "serving.health"}),
    ])
    assert main([unresolved]) == 1  # a tracker never recovered

    # --json CLI on a directory of dumps + a bench artifact fold
    bench_lines = tmp_path / "bench_lines.jsonl"
    bench_lines.write_text(json.dumps(
        {"config": "serving", "metric": "m", "faults": "none",
         "slo": {"state": "ok", "objectives": []}}) + "\n")
    bb_dir = tmp_path / "dumps"
    bb_dir.mkdir()
    _write_dump(str(bb_dir / "flight_1.jsonl"), [
        ("health.degraded", {"tracker": "t"}),
        ("health.ready", {"tracker": "t"})])
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "blackbox.py"),
         str(bb_dir), "--bench", str(bench_lines), "--json"],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["bench"] == [{"config": "serving", "metric": "m",
                             "faults": "none", "slo": "ok"}]
    assert proc.returncode == 0


def test_blackbox_corrupt_input_exit_2(tmp_path):
    from tools.blackbox import main

    bad = tmp_path / "corrupt.jsonl"
    bad.write_text('{"seq": 1, "event": "breaker.open"}\n'
                   'not json at all\n'
                   '{"seq": 2, "event": "breaker.close"}\n')
    assert main([str(bad)]) == 2  # mid-file damage is not a torn tail


# -- THE acceptance chaos --------------------------------------------------

def _is_subsequence(needle, haystack):
    it = iter(haystack)
    return all(any(h == n for h in it) for n in needle)


_CAUSAL = ("rollout.start", "fault.fired", "retry.attempt",
           "breaker.open", "fleet.shed", "stream.stall",
           "stream.stall_recovered", "breaker.half_open",
           "breaker.close", "rollout.promote")


def _run_incident(base_dir):
    """One seeded incident: breaker trip mid-rollout + stream stall,
    everything recovered; returns the blackbox timeline document."""
    from sparkdl_tpu import streaming
    from sparkdl_tpu.parallel.engine import InferenceEngine
    from sparkdl_tpu.serving import Fleet
    from sparkdl_tpu.serving.errors import ServiceUnavailableError
    from tools.blackbox import build_timeline

    bb_dir = os.path.join(base_dir, "blackbox")
    tr_dir = os.path.join(base_dir, "trace")
    flight.configure(enabled=True, out_dir=bb_dir)
    obs.configure(enabled=True, out_dir=tr_dir)
    rng = np.random.default_rng(17)
    w1 = {"w": rng.normal(size=(12, 5)).astype(np.float32)}
    w2 = {"w": rng.normal(size=(12, 5)).astype(np.float32)}
    x = rng.normal(size=(12,)).astype(np.float32)
    plan = FaultPlan.parse(
        "seed=9;engine.dispatch:error:exc=dead,every=1,times=2")
    with Fleet(max_batch_size=8, max_wait_ms=1, bucket_sizes=[8],
               dispatch_retries=1, breaker_threshold=2,
               breaker_cooldown_s=0.5) as fleet:
        fleet.add_model("m", _fn, w1, warm_example=x)
        fleet.add_version("m", w2)
        fleet.start_rollout("m", canary_fraction=0.5, warm_example=x)
        with faults.active(plan):
            # 1: the injected dead device eats the dispatch AND its one
            # retry -> breaker opens at threshold 2, request fails
            fut1 = fleet.submit("m", x)
            assert fut1.exception(timeout=30) is not None
            # 2: next two submissions alternate servers — the one routed
            # to the broken leg is shed at admission (breaker open)
            shed = 0
            for _ in range(2):
                try:
                    fleet.submit("m", x).result(timeout=30)
                except ServiceUnavailableError:
                    shed += 1
            assert shed == 1
            # 3: mid-incident the stream source goes silent past its
            # watchdog deadline, then recovers
            eng = InferenceEngine(
                _fn, w1, device_batch_size=8,
                metrics=Metrics())  # keep stream metrics out of serving
            src = streaming.MemorySource()
            sc = streaming.StreamScorer(
                eng, src, journal_path=os.path.join(base_dir, "j.jsonl"),
                out_dir=os.path.join(base_dir, "out"),
                stall_deadline_s=0.05, poll_backoff_s=0.005,
                pipeline=False)
            worker = threading.Thread(target=sc.run, daemon=True)
            worker.start()
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                names = [e["event"]
                         for e in flight.get_recorder().snapshot()]
                if "stream.stall" in names:
                    break
                time.sleep(0.005)
            assert "stream.stall" in names, names
            src.feed(rng.normal(size=(8, 12)).astype(np.float32))
            src.finish()
            worker.join(timeout=30)
            assert not worker.is_alive()
            # 4: cool-down elapses; the trial dispatch closes the
            # breaker and serving recovers
            time.sleep(0.7)
            for _ in range(2):
                fleet.submit("m", x).result(timeout=30)
            # 5: the rollout this all happened inside completes
            fleet.promote("m")
        assert fleet.health()["state"] == "ready"
    obs.get_tracer().flush()
    flight.get_recorder().dump()
    return build_timeline(bb_dir, spans_path=tr_dir,
                          journal_path=os.path.join(base_dir, "j.jsonl"))


@pytest.mark.filterwarnings("ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_chaos_blackbox_reconstructs_causal_chain(tmp_path):
    """ISSUE 9 acceptance: under injected faults (breaker trip
    mid-rollout + stream stall), ``tools/blackbox.py`` reconstructs
    from the durable dump a timeline containing the full causal chain
    — fault fired -> retries exhausted -> breaker open -> shed ->
    degraded -> half-open -> ready — in order, trace-id-correlated
    with the span JSONL, deterministic across two seeded runs."""
    doc1 = _run_incident(str(tmp_path / "run1"))
    doc2 = _run_incident(str(tmp_path / "run2"))
    for doc in (doc1, doc2):
        chain = doc["chain"]
        assert _is_subsequence(
            ["fault.fired", "retry.attempt", "breaker.open",
             "fleet.shed", "health.degraded", "breaker.half_open",
             "health.ready"], chain), chain
        # the trip really happened MID-rollout
        assert chain.index("rollout.start") < chain.index("fault.fired")
        assert (chain.index("breaker.close")
                < chain.index("rollout.promote"))
        # the stream's own stall/recovery episode is on the timeline
        assert _is_subsequence(
            ["stream.stall", "health.degraded", "stream.stall_recovered",
             "health.ready", "stream.commit"], chain), chain
        assert doc["counts"]["fault.fired"] == 2  # every=1,times=2 — exact
        assert doc["counts"]["retry.attempt"] == 1
        assert doc["counts"]["fleet.shed"] == 1
        # every degradation recovered, the journal has no replay debt
        assert doc["health"] == {"serving.health": "ready",
                                 "stream.health": "ready"}
        assert doc["verdict"]["clean"] is True, doc["verdict"]
        assert doc["journal"]["uncommitted"] == []
        # trace-id correlation with the span JSONL: the breaker/fault
        # events carry the dispatching request's trace id, and those
        # ids resolve to recorded span trees
        assert doc["correlated_events"] >= 1
        correlated = [e for e in doc["events"]
                      if e["trace_known"]
                      and e["event"] in ("fault.fired", "breaker.open",
                                         "retry.attempt")]
        assert correlated, "causal events lost their trace ids"
        tid = correlated[0]["trace_id"]
        assert doc["traces"][tid]["count"] >= 1
    # determinism: the causal event sequence is identical run to run
    causal1 = [(e["event"], (e.get("attrs") or {}).get("reason"))
               for e in doc1["events"] if e["event"] in _CAUSAL]
    causal2 = [(e["event"], (e.get("attrs") or {}).get("reason"))
               for e in doc2["events"] if e["event"] in _CAUSAL]
    assert causal1 == causal2


# -- ISSUE 18: the cost sentinel on the blackbox timeline ------------------

def test_cost_regression_rides_blackbox_timeline(tmp_path):
    """The sentinel's ``cost.regression``/``cost.recovered`` events and
    the health transitions they cause fold into the blackbox causal
    chain like any other incident — and once recovered, the timeline's
    verdict reads clean."""
    from sparkdl_tpu.obs.cost import CostLedger
    from tools.blackbox import build_timeline

    bb_dir = str(tmp_path / "blackbox")
    flight.configure(enabled=True, out_dir=bb_dir)
    tracker = HealthTracker("serving.cost.health")
    ledger = CostLedger(window=4, min_batches=4, regress_factor=2.0,
                        recover_factor=1.5, health=tracker,
                        lockfile_path="/nonexistent/lock.json")

    def batch(device_s):
        ledger.record_batch(model="m", bucket=8,
                            tenant_rows={"a": 8}, device_s=device_s)

    for _ in range(6):      # pin the baseline
        batch(0.001)
    for _ in range(4):      # sustained 12x slowdown -> open + degrade
        batch(0.012)
    for _ in range(4):      # recovery -> close + ready
        batch(0.001)
    flight.get_recorder().dump()

    doc = build_timeline(bb_dir)
    assert _is_subsequence(
        ["cost.regression", "health.degraded", "cost.recovered",
         "health.ready"], doc["chain"]), doc["chain"]
    assert doc["counts"]["cost.regression"] == 1
    assert doc["counts"]["cost.recovered"] == 1
    assert doc["health"] == {"serving.cost.health": "ready"}
    assert doc["verdict"]["clean"] is True, doc["verdict"]
    ev = next(e for e in doc["events"]
              if e["event"] == "cost.regression")
    assert ev["attrs"]["program"] == "m/b8"
    assert ev["attrs"]["factor"] >= 2.0
    assert ev["attrs"]["reason"] == "baseline"
    json.dumps(doc)
