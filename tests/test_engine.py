"""Inference-engine tests on the 8-device virtual CPU mesh.

The reference simulates multi-executor behavior with multiple local
partitions (SURVEY.md §4); the TPU analog is a virtual 8-device CPU mesh
(see conftest).  These tests assert the engine's fixed-shape padding, the
sharded execution path, and the streaming window produce exactly the same
numbers as a plain unsharded call.
"""

import numpy as np
import pytest

from sparkdl_tpu.parallel import InferenceEngine, get_mesh
from sparkdl_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def _fn(variables, x):
    # toy "model": affine + nonlinearity, batch on axis 0
    import jax.numpy as jnp

    return jnp.tanh(x @ variables["w"] + variables["b"])


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(3)
    variables = {
        "w": rng.normal(size=(12, 5)).astype(np.float32),
        "b": rng.normal(size=(5,)).astype(np.float32),
    }
    x = rng.normal(size=(45, 12)).astype(np.float32)
    ref = np.tanh(x @ variables["w"] + variables["b"])
    return variables, x, ref


def test_mesh_spans_all_devices():
    import jax

    mesh = get_mesh()
    assert mesh.size == len(jax.devices()) == 8
    assert mesh.shape[DATA_AXIS] == 8 and mesh.shape[MODEL_AXIS] == 1


def test_mesh_subset_and_validation():
    mesh = get_mesh(num_devices=4)
    assert mesh.size == 4
    with pytest.raises(ValueError, match="only"):
        get_mesh(num_devices=99)
    with pytest.raises(ValueError, match="does not divide"):
        get_mesh(num_devices=4, model_parallel=3)


def test_engine_matches_unsharded(setup):
    variables, x, ref = setup
    eng = InferenceEngine(_fn, variables, device_batch_size=16)
    out = eng(x)
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_engine_rounds_batch_to_data_axis(setup):
    variables, x, ref = setup
    eng = InferenceEngine(_fn, variables, device_batch_size=10)
    # 8-way data axis: 10 -> 16
    assert eng.device_batch_size == 16
    np.testing.assert_allclose(eng(x), ref, rtol=1e-5, atol=1e-6)


def test_engine_ragged_tail_is_trimmed(setup):
    variables, x, ref = setup
    # 45 rows, batch 32 -> chunks of 32 and 13 (padded to 32, trimmed)
    eng = InferenceEngine(_fn, variables, device_batch_size=32)
    out = eng(x)
    assert out.shape[0] == 45
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_engine_streaming_window(setup):
    variables, x, ref = setup
    eng = InferenceEngine(_fn, variables, device_batch_size=8)
    batches = [x[:20], x[20:23], x[23:]]
    outs = list(eng.map_batches(batches, window=2))
    got = np.concatenate(outs, axis=0)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_engine_batches_per_dispatch_matches_plain(setup):
    """Grouped dispatch (k host batches per compiled program via lax.map
    — the inference analog of steps_per_execution) returns EXACTLY the
    plain engine's outputs: same rows, same order, ragged tail groups
    and ragged final batches included."""
    variables, x, ref = setup
    plain = InferenceEngine(_fn, variables, device_batch_size=16)
    grouped = InferenceEngine(_fn, variables, device_batch_size=16,
                              batches_per_dispatch=3)
    # 45 rows / 16 = 3 pieces -> one full group of 3 (third piece ragged)
    # (allclose, not equal: the grouped program's op order differs at the
    # last ulp, same as any XLA re-fusion)
    np.testing.assert_allclose(grouped(x), plain(x), rtol=1e-5, atol=1e-6)
    # streaming, multiple chunks, tail group of 2 of 3: 5 pieces total
    chunks = [x[:20], x[20:41], x[41:]]
    got = list(grouped.map_batches(iter(chunks)))
    want = list(plain.map_batches(iter(chunks)))
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g.shape == w.shape
        np.testing.assert_allclose(g, w, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.concatenate(got), ref, rtol=1e-5,
                               atol=1e-6)


def test_engine_batches_per_dispatch_tail_uses_plain_program(setup,
                                                             monkeypatch):
    """A ragged tail group must run its pieces through the plain
    per-batch program — not pad the group with whole zero batches that
    would execute the full model for nothing."""
    variables, x, _ = setup
    eng = InferenceEngine(_fn, variables, device_batch_size=16,
                          batches_per_dispatch=3)
    calls = {"group": 0, "plain": 0}
    orig_group, orig_plain = eng._dispatch_group, eng.run_padded
    monkeypatch.setattr(eng, "_dispatch_group", lambda s: (
        calls.__setitem__("group", calls["group"] + 1), orig_group(s))[1])
    monkeypatch.setattr(eng, "run_padded", lambda b: (
        calls.__setitem__("plain", calls["plain"] + 1), orig_plain(b))[1])
    # serial path pinned: the call-count choreography under test is the
    # single-threaded one (test_pipeline covers the threaded analog)
    out = eng(np.concatenate([x, x[:19]]), pipeline=False)  # 4 pieces: 3+1
    assert out.shape[0] == 64
    assert calls == {"group": 1, "plain": 1}


def test_engine_grouped_dispatch_scales_inflight_window(setup, monkeypatch):
    """With batches_per_dispatch=k the in-flight unit is a k-batch GROUP,
    so the effective window must scale to max(1, window // k) groups —
    otherwise grouping silently multiplies peak device residency ~k-fold
    (advisor round-5).  window=2, k=3 -> at most 1+1 groups outstanding."""
    variables, _, _ = setup
    rng = np.random.default_rng(9)
    x = rng.normal(size=(144, 12)).astype(np.float32)  # 9 pieces, 3 groups
    ref = np.tanh(x @ variables["w"] + variables["b"])
    eng = InferenceEngine(_fn, variables, device_batch_size=16,
                          batches_per_dispatch=3)
    events = []
    orig_group, orig_trim = eng._dispatch_group, eng._trim
    monkeypatch.setattr(eng, "_dispatch_group", lambda s: (
        events.append("dispatch"), orig_group(s))[1])
    monkeypatch.setattr(eng, "_trim", lambda o, n: (
        events.append("trim"), orig_trim(o, n))[1])
    # serial path pinned: dispatch/trim interleaving on ONE thread is the
    # invariant under test (the pipelined runner bounds residency with
    # queue capacities instead — test_pipeline)
    outs = list(eng.map_batches([x], window=2, pipeline=False))
    np.testing.assert_allclose(np.concatenate(outs), ref, rtol=1e-5,
                               atol=1e-6)
    # every 3rd trim completes one group's gather
    outstanding = peak = trims = 0
    for e in events:
        if e == "dispatch":
            outstanding += 1
            peak = max(peak, outstanding)
        else:
            trims += 1
            if trims % 3 == 0:
                outstanding -= 1
    assert peak <= 2, events  # max(1, 2 // 3) + the batch being dispatched


def test_engine_batches_per_dispatch_pytree(setup):
    """Grouped dispatch with pytree outputs and integer leaves (argmax
    ids) — per-leaf group indexing and host-dtype rules must hold."""
    import jax.numpy as jnp

    variables, x, ref = setup

    def fn(v, xb):
        y = jnp.tanh(xb @ v["w"] + v["b"])
        return {"y": y, "ids": jnp.argmax(y, axis=-1)}

    plain = InferenceEngine(fn, variables, device_batch_size=8,
                            output_host_dtype=np.float32)
    grouped = InferenceEngine(fn, variables, device_batch_size=8,
                              batches_per_dispatch=2,
                              output_host_dtype=np.float32)
    a, b = plain(x), grouped(x)
    np.testing.assert_allclose(a["y"], b["y"], rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(a["ids"], b["ids"])
    assert b["ids"].dtype.kind in "iu"  # never floated


def test_engine_multicontroller_mesh_policy(setup, monkeypatch):
    """Scoring is per-controller: under multi-controller jax the DEFAULT
    mesh covers local devices only (the zoo transformers pass no mesh,
    so they keep working on pods), while an EXPLICIT mesh spanning other
    processes is refused loudly at construction (device_put of
    process-local numpy onto a global sharding fails confusingly at
    runtime otherwise)."""
    import jax

    from sparkdl_tpu.parallel import mesh as mesh_lib

    variables, x, ref = setup
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    # default mesh: local devices, scoring still works end to end
    eng = InferenceEngine(_fn, variables, device_batch_size=8)
    assert all(d.process_index == jax.process_index()
               for d in eng.mesh.devices.flat)
    np.testing.assert_allclose(eng(x), ref, rtol=1e-5, atol=1e-6)
    # explicit cross-process mesh: refused
    remote = mesh_lib.get_mesh()
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    with pytest.raises(NotImplementedError, match="single-controller"):
        InferenceEngine(_fn, variables, device_batch_size=8, mesh=remote)


def test_engine_empty_input_rejected(setup):
    variables, x, _ = setup
    eng = InferenceEngine(_fn, variables, device_batch_size=8)
    with pytest.raises(ValueError, match="Empty"):
        eng(x[:0])


def test_engine_compute_dtype_bf16(setup):
    import jax.numpy as jnp

    variables, x, ref = setup
    eng = InferenceEngine(_fn, variables, device_batch_size=16,
                          compute_dtype=jnp.bfloat16)
    out = np.asarray(eng(x), dtype=np.float32)
    # bf16 has ~3 decimal digits; loose tolerance
    np.testing.assert_allclose(out, ref, rtol=0.05, atol=0.05)


def test_engine_pytree_output(setup):
    variables, x, ref = setup

    def fn2(v, x):
        import jax.numpy as jnp

        y = jnp.tanh(x @ v["w"] + v["b"])
        return {"y": y, "norm": jnp.sum(y * y, axis=-1)}

    eng = InferenceEngine(fn2, variables, device_batch_size=16)
    out = eng(x)
    np.testing.assert_allclose(out["y"], ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(out["norm"], (ref * ref).sum(-1),
                               rtol=1e-4, atol=1e-5)


def test_engine_output_is_actually_sharded(setup):
    """The compiled call must shard the batch over the data axis (this is
    the chips-get-rows contract, not just a numerical one)."""
    import jax

    variables, x, _ = setup
    eng = InferenceEngine(_fn, variables, device_batch_size=16)
    dev_out = eng.run_padded(np.zeros((16, 12), np.float32))
    shards = dev_out.addressable_shards
    assert len(shards) == 8
    assert all(s.data.shape == (2, 5) for s in shards)


def test_engine_call_bounds_inflight_window(setup, monkeypatch):
    """__call__ must gather chunk k-window before dispatching chunk k+1 —
    device residency stays O(window), not O(n_chunks) (ADVICE round 1)."""
    variables, x, ref = setup
    eng = InferenceEngine(_fn, variables, device_batch_size=8)
    events = []
    orig_run, orig_trim = eng.run_padded, eng._trim

    def spy_run(batch):
        events.append("dispatch")
        return orig_run(batch)

    monkeypatch.setattr(eng, "run_padded", spy_run)
    monkeypatch.setattr(eng, "_trim",
                        lambda out, n: (events.append("gather"),
                                        orig_trim(out, n))[1])
    # serial path pinned: single-thread event ordering is the invariant
    # under test (pipelined residency bounds live in test_pipeline)
    out = eng(x, window=2, pipeline=False)  # 45 rows / 8 = 6 chunks
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    # With 6 chunks and window=2, the first gather must happen before the
    # last dispatch (not all dispatches first, as in round 1).
    first_gather = events.index("gather")
    last_dispatch = len(events) - 1 - events[::-1].index("dispatch")
    assert first_gather < last_dispatch, events
    # and never more than window+1 dispatches outstanding
    outstanding = peak = 0
    for e in events:
        outstanding += 1 if e == "dispatch" else -1
        peak = max(peak, outstanding)
    assert peak <= 3, events


def test_output_host_dtype_casts_after_fetch():
    """output_host_dtype fetches the compute dtype and casts on the host:
    results are bit-identical to a device-side upcast (bf16->f32 widening
    is exact) while the gathered buffer is the narrow dtype."""
    import jax.numpy as jnp

    from sparkdl_tpu.parallel.engine import InferenceEngine, clear_engine_jit_cache

    clear_engine_jit_cache()
    w = np.linspace(-1, 1, 12).reshape(3, 4).astype(np.float32)

    def fn_raw(v, x):  # bf16 out
        return (jnp.asarray(x, jnp.bfloat16) @ v["w"].astype(jnp.bfloat16))

    def fn_up(v, x):   # device-side upcast of the same computation
        return fn_raw(v, x).astype(jnp.float32)

    x = np.random.default_rng(0).normal(size=(10, 3)).astype(np.float32)
    host_cast = InferenceEngine(fn_raw, {"w": w}, device_batch_size=8,
                                output_host_dtype=np.float32)(x)
    assert host_cast.dtype == np.float32
    # without the option, outputs come back in the compute dtype; the host
    # cast must be exactly the f32 widening of those bf16 values
    raw = InferenceEngine(fn_raw, {"w": w}, device_batch_size=8)(x)
    assert raw.dtype != np.float32
    np.testing.assert_array_equal(host_cast, raw.astype(np.float32))
    # and within bf16 tolerance of the device-side-upcast program (XLA may
    # fuse the upcast and skip the intermediate bf16 rounding, so exact
    # equality with THAT program is not guaranteed)
    dev_cast = InferenceEngine(fn_up, {"w": w}, device_batch_size=8)(x)
    np.testing.assert_allclose(host_cast, dev_cast, rtol=2e-2, atol=2e-2)


def test_output_host_dtype_preserves_integer_leaves():
    """Integer outputs (e.g. argmax class ids) must pass through the
    host cast untouched."""
    import jax.numpy as jnp

    from sparkdl_tpu.parallel.engine import InferenceEngine

    def fn(v, x):
        logits = jnp.asarray(x, jnp.bfloat16) @ v["w"].astype(jnp.bfloat16)
        return {"scores": logits, "ids": jnp.argmax(logits, axis=-1)}

    w = np.eye(3, dtype=np.float32)
    x = np.random.default_rng(1).normal(size=(5, 3)).astype(np.float32)
    out = InferenceEngine(fn, {"w": w}, device_batch_size=8,
                          output_host_dtype=np.float32)(x)
    assert out["scores"].dtype == np.float32
    assert np.issubdtype(out["ids"].dtype, np.integer)
