"""Estimator/tuning tests on the 8-device CPU mesh.

Mirrors the reference's estimator tests (``python/tests/estimators/
test_keras_estimators.py``): tiny model + handful of images, 1-epoch fits,
param-validation failure cases, fit(df, paramMaps) returning one model per
map, CrossValidator smoke integration — plus data-parallel correctness
checks the reference couldn't have (gradient psum over the mesh).
"""

import numpy as np
import pytest

from sparkdl_tpu.estimators import (BinaryClassificationEvaluator,
                                    CrossValidator, ImageFileEstimator,
                                    KerasImageFileEstimator,
                                    LogisticRegression,
                                    MulticlassClassificationEvaluator,
                                    ParamGridBuilder, TrainValidationSplit)
from sparkdl_tpu.frame import DataFrame
from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.parallel import get_mesh
from sparkdl_tpu.parallel.train import fit_data_parallel


# ---------------------------------------------------------------------------
# train step


def test_fit_data_parallel_converges_and_matches_single_device(rng):
    import jax.numpy as jnp
    import optax

    w_true = rng.normal(size=(5, 1)).astype(np.float32)
    x = rng.normal(size=(64, 5)).astype(np.float32)
    y = x @ w_true

    def predict(p, xb):
        return jnp.asarray(xb) @ p["w"]

    def run(mesh):
        params = {"w": np.zeros((5, 1), np.float32)}
        return fit_data_parallel(
            predict, params, x, y, optimizer=optax.sgd(0.1), loss="mse",
            batch_size=16, epochs=30, seed=7, mesh=mesh)

    fitted8, losses8 = run(get_mesh())            # 8-way data parallel
    fitted1, losses1 = run(get_mesh(num_devices=1))
    assert losses8[-1] < 1e-3                     # converged
    # same batches + same init: the psum-sharded run must match 1-device
    np.testing.assert_allclose(fitted8["w"], fitted1["w"], rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(fitted8["w"], w_true, rtol=0.05, atol=0.01)


def test_fit_data_parallel_loss_names():
    from sparkdl_tpu.parallel.train import resolve_loss

    for name in ("categorical_crossentropy", "sparse_categorical_crossentropy",
                 "binary_crossentropy", "mse", "mae"):
        assert callable(resolve_loss(name))
    with pytest.raises(ValueError, match="Unknown loss"):
        resolve_loss("nope")


# ---------------------------------------------------------------------------
# LogisticRegression head


@pytest.fixture(scope="module")
def blobs():
    rng = np.random.default_rng(11)
    n = 120
    centers = np.asarray([[2.0, 0.0], [-2.0, 1.0], [0.0, -2.5]], np.float32)
    y = np.arange(n) % 3
    x = centers[y] + rng.normal(0, 0.4, size=(n, 2)).astype(np.float32)
    df = DataFrame({"features": [list(map(float, r)) for r in x],
                    "label": y.astype(np.int64)})
    return df, x, y


def test_logistic_regression_fits_blobs(blobs):
    df, x, y = blobs
    lr = LogisticRegression(maxIter=60, learningRate=0.1, batchSize=64)
    model = lr.fit(df)
    out = model.transform(df)
    acc = MulticlassClassificationEvaluator().evaluate(out)
    assert acc > 0.95
    rows = out.collect()
    assert len(rows[0]["probability"]) == 3
    assert abs(sum(rows[0]["probability"]) - 1.0) < 1e-4


# ---------------------------------------------------------------------------
# ImageFileEstimator


def _tiny_trainable_mf(h=8, w=8, classes=2, seed=0):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    variables = {"w": rng.normal(0, 0.01, (h * w * 3, classes)).astype(np.float32)}

    def fn(v, x):
        logits = x.reshape(x.shape[0], -1) @ v["w"]
        return jnp.asarray(jnp.exp(logits) /
                           jnp.sum(jnp.exp(logits), axis=-1, keepdims=True))

    return ModelFunction(fn=fn, variables=variables)


def _loader(uri):
    from PIL import Image

    img = Image.open(uri).convert("RGB").resize((8, 8))
    return np.asarray(img, dtype=np.float32) / 255.0


@pytest.fixture()
def uri_label_df(fixture_images):
    paths = fixture_images["paths"] * 4  # 12 rows
    labels = [[1.0, 0.0] if i % 2 == 0 else [0.0, 1.0]
              for i in range(len(paths))]
    return DataFrame({"uri": paths, "label": labels})


def test_image_file_estimator_fit_and_transform(uri_label_df):
    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=_tiny_trainable_mf(),
        imageLoader=_loader, optimizer="adam",
        loss="categorical_crossentropy",
        fitParams={"epochs": 3}, batchSize=8)
    model = est.fit(uri_label_df)
    assert len(model.trainLosses) == 3
    assert model.trainLosses[-1] <= model.trainLosses[0] + 1e-3
    out = model.transform(uri_label_df)
    rows = out.collect()
    assert all(len(r["preds"]) == 2 for r in rows)


def test_image_file_estimator_fit_multiple_shares_data(uri_label_df):
    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=_tiny_trainable_mf(),
        imageLoader=_loader, loss="categorical_crossentropy",
        fitParams={"epochs": 1}, batchSize=8)
    maps = [{est.fitParams: {"epochs": 1}}, {est.fitParams: {"epochs": 2}}]
    models = est.fit(uri_label_df, maps)
    assert len(models) == 2
    assert len(models[0].trainLosses) == 1
    assert len(models[1].trainLosses) == 2


def test_image_file_estimator_param_validation(uri_label_df):
    est = ImageFileEstimator(inputCol="uri", labelCol="label")
    with pytest.raises(ValueError, match="requires params"):
        est.fit(uri_label_df)


def test_keras_image_file_estimator(tmp_path, uri_label_df):
    import keras
    from keras import layers

    model = keras.Sequential([
        layers.Input((8, 8, 3)),
        layers.Conv2D(2, 3, padding="same", activation="relu"),
        layers.GlobalAveragePooling2D(),
        layers.Dense(2, activation="softmax"),
    ])
    path = str(tmp_path / "tiny.keras")
    model.save(path)
    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFile=path, imageLoader=_loader,
        kerasOptimizer="adam", kerasLoss="categorical_crossentropy",
        kerasFitParams={"epochs": 2}, batchSize=8)
    fitted = est.fit(uri_label_df)
    assert len(fitted.trainLosses) == 2
    rows = fitted.transform(uri_label_df).collect()
    assert all(abs(sum(r["preds"]) - 1.0) < 1e-3 for r in rows)

    with pytest.raises(ValueError, match="modelFile"):
        KerasImageFileEstimator(inputCol="uri", labelCol="label",
                                imageLoader=_loader).fit(uri_label_df)


# ---------------------------------------------------------------------------
# tuning


def test_param_grid_builder():
    lr = LogisticRegression()
    grid = (ParamGridBuilder()
            .addGrid(lr.regParam, [0.0, 0.1])
            .addGrid(lr.maxIter, [5, 10, 15])
            .baseOn((lr.learningRate, 0.2))
            .build())
    assert len(grid) == 6
    assert all(m[lr.learningRate] == 0.2 for m in grid)
    assert {m[lr.regParam] for m in grid} == {0.0, 0.1}
    with pytest.raises(TypeError, match="expects a Param"):
        ParamGridBuilder().addGrid("regParam", [0.1])


def test_cross_validator_selects_and_refits(blobs):
    df, _, _ = blobs
    lr = LogisticRegression(batchSize=64, learningRate=0.1)
    grid = (ParamGridBuilder()
            .addGrid(lr.maxIter, [1, 40])
            .build())
    cv = CrossValidator(estimator=lr, estimatorParamMaps=grid,
                        evaluator=MulticlassClassificationEvaluator(),
                        numFolds=3, seed=1)
    cv_model = cv.fit(df)
    assert len(cv_model.avgMetrics) == 2
    # 40 epochs must beat 1 epoch on separable blobs
    assert cv_model.avgMetrics[1] > cv_model.avgMetrics[0]
    out = cv_model.transform(df)
    assert MulticlassClassificationEvaluator().evaluate(out) > 0.9


def test_train_validation_split(blobs):
    df, _, _ = blobs
    lr = LogisticRegression(batchSize=64, learningRate=0.1)
    grid = ParamGridBuilder().addGrid(lr.maxIter, [1, 40]).build()
    tvs = TrainValidationSplit(estimator=lr, estimatorParamMaps=grid,
                               evaluator=MulticlassClassificationEvaluator(),
                               trainRatio=0.75, seed=2)
    m = tvs.fit(df)
    assert len(m.avgMetrics) == 2


# ---------------------------------------------------------------------------
# evaluators


def test_multiclass_evaluator_metrics():
    df = DataFrame({"label": [0, 0, 1, 1, 2, 2],
                    "prediction": [0, 1, 1, 1, 2, 0]})
    ev = MulticlassClassificationEvaluator()
    assert abs(ev.evaluate(df) - 4 / 6) < 1e-9
    f1 = MulticlassClassificationEvaluator(metricName="f1").evaluate(df)
    assert 0.0 < f1 < 1.0
    with pytest.raises(ValueError, match="Unknown metricName"):
        MulticlassClassificationEvaluator(metricName="nope").evaluate(df)


def test_binary_auc():
    # perfect ranking -> AUC 1; reversed -> 0
    df = DataFrame({"label": [0, 0, 1, 1],
                    "probability": [[0.9, 0.1], [0.8, 0.2],
                                    [0.3, 0.7], [0.1, 0.9]]})
    ev = BinaryClassificationEvaluator()
    assert ev.evaluate(df) == 1.0
    df2 = DataFrame({"label": [1, 1, 0, 0],
                     "probability": [[0.9, 0.1], [0.8, 0.2],
                                     [0.3, 0.7], [0.1, 0.9]]})
    assert ev.evaluate(df2) == 0.0


def test_epoch_batches_modular_wrap_tiny_dataset():
    """Dataset smaller than half the batch must still yield full-size
    batches via modular wrap-around (ADVICE round 1)."""
    from sparkdl_tpu.parallel.train import _epoch_batches

    x = np.arange(3, dtype=np.float32)[:, None]
    y = np.arange(3, dtype=np.float32)
    batches = list(_epoch_batches(x, y, batch_size=8, epoch=0,
                                  shuffle=True, seed=0))
    assert len(batches) == 1
    bx, by = batches[0]
    assert bx.shape == (8, 1) and by.shape == (8,)
    # every original sample still present
    assert set(np.unique(bx[:, 0])) == {0.0, 1.0, 2.0}


# ---------------------------------------------------------------------------
# trainBatchStats (BN semantics)


def _bn_model_function(seed=0):
    """Tiny flax conv+BN model wrapped as a ModelFunction (with train_fn)."""
    import jax
    from flax import linen as nn

    from sparkdl_tpu.graph.function import ModelFunction

    class BNNet(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = nn.Conv(4, (3, 3), name="conv")(x)
            x = nn.BatchNorm(use_running_average=not train, name="bn")(x)
            x = x.mean(axis=(1, 2))
            return nn.softmax(nn.Dense(2, name="head")(x))

    module = BNNet()
    variables = jax.jit(
        lambda r, xb: module.init(r, xb, train=False)
    )(jax.random.PRNGKey(seed), np.zeros((1, 8, 8, 3), np.float32))
    variables = jax.tree_util.tree_map(np.asarray, variables)
    return ModelFunction.from_flax(
        module, dict(variables), method_kwargs={"train": False})


def test_train_batch_stats_updates_stats(uri_label_df):
    mf = _bn_model_function()
    before = np.asarray(mf.variables["batch_stats"]["bn"]["mean"]).copy()
    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=mf, imageLoader=_loader, optimizer="sgd",
        loss="categorical_crossentropy", fitParams={"epochs": 2},
        batchSize=8, trainBatchStats=True)
    model = est.fit(uri_label_df)
    after = np.asarray(
        model.getModelFunction().variables["batch_stats"]["bn"]["mean"])
    assert not np.allclose(before, after), "BN stats did not update"
    rows = model.transform(uri_label_df).collect()
    assert all(abs(sum(r["preds"]) - 1.0) < 1e-3 for r in rows)


def test_default_keeps_batch_stats_frozen(uri_label_df):
    mf = _bn_model_function()
    before = np.asarray(mf.variables["batch_stats"]["bn"]["mean"]).copy()
    before_params = np.asarray(
        mf.variables["params"]["head"]["kernel"]).copy()
    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=mf, imageLoader=_loader, optimizer="sgd",
        loss="categorical_crossentropy", fitParams={"epochs": 2},
        batchSize=8)  # trainBatchStats defaults False
    model = est.fit(uri_label_df)
    fitted = model.getModelFunction().variables
    np.testing.assert_array_equal(
        before, np.asarray(fitted["batch_stats"]["bn"]["mean"]))
    assert not np.allclose(
        before_params, np.asarray(fitted["params"]["head"]["kernel"]))


def test_train_batch_stats_requires_train_fn(uri_label_df):
    from sparkdl_tpu.graph.function import ModelFunction

    mf = ModelFunction(fn=lambda v, x: x.reshape(x.shape[0], -1)[:, :2],
                       variables={"w": np.zeros((1,), np.float32)})
    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=mf, imageLoader=_loader,
        loss="mse", trainBatchStats=True)
    with pytest.raises(ValueError, match="trainBatchStats"):
        est.fit(uri_label_df)


def test_epoch_batches_pinned_step_count():
    """Multi-controller fits pin num_steps so unequal per-host shards run
    the SAME number of collective steps (ADVICE round 2 deadlock fix):
    short hosts wrap modularly, long hosts truncate."""
    from sparkdl_tpu.parallel.train import _epoch_batches

    x = np.arange(10, dtype=np.float32)[:, None]
    y = np.arange(10, dtype=np.float32)
    # more steps than local data covers -> wraps
    batches = list(_epoch_batches(x, y, batch_size=4, epoch=0, shuffle=False,
                                  seed=0, num_steps=5))
    assert len(batches) == 5
    assert all(bx.shape == (4, 1) for bx, _ in batches)
    # fewer steps than local data covers -> truncates
    batches = list(_epoch_batches(x, y, batch_size=4, epoch=0, shuffle=False,
                                  seed=0, num_steps=1))
    assert len(batches) == 1


def test_transform_param_override_not_stale(uri_label_df):
    """Params.copy() shallow-copies __dict__, so the fitted model's cached
    transformer must be keyed by its params — a transform-time outputCol
    override or a later setter must not reuse the stale one (ADVICE r2)."""
    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=_tiny_trainable_mf(), imageLoader=_loader,
        loss="categorical_crossentropy", fitParams={"epochs": 1},
        batchSize=8)
    model = est.fit(uri_label_df)
    out1 = model.transform(uri_label_df)
    assert "preds" in out1.columns
    out2 = model.transform(uri_label_df,
                           {model.getParam("outputCol"): "other"})
    assert "other" in out2.columns
    model._set(outputCol="third")
    out3 = model.transform(uri_label_df)
    assert "third" in out3.columns


def test_fit_decodes_each_image_once_across_folds_and_maps(fixture_images):
    """VERDICT r2 weak #3: the fit path (k fold-subsets x m maps + the
    final full refit — the CrossValidator decode pattern) must pay ONE
    decode per unique URI, not one full decode pass per fold: the
    estimator's per-URI cache is shared across fold/map copies.  (Transform
    -side evaluation decodes are a separate, streaming path.)"""
    paths = fixture_images["paths"] * 4
    labels = [i % 2 for i in range(len(paths))]
    df = DataFrame({"uri": paths, "label": labels})
    calls = []

    def counting_loader(uri):
        calls.append(uri)
        return _loader(uri)

    est = ImageFileEstimator(
        inputCol="uri", outputCol="prediction", labelCol="label",
        modelFunction=_tiny_trainable_mf(),
        imageLoader=counting_loader, optimizer="sgd",
        loss="sparse_categorical_crossentropy",
        fitParams={"epochs": 1}, batchSize=8)
    maps = [{est.batchSize: 8}, {est.batchSize: 12}]
    # the CrossValidator fit pattern: per-fold subsets through fitMultiple,
    # then a full-data refit
    fold1 = DataFrame(df.table.take(list(range(0, 12, 2))))
    fold2 = DataFrame(df.table.take(list(range(1, 12, 2))))
    list(est.fitMultiple(fold1, maps))
    list(est.fitMultiple(fold2, maps))
    est.fit(df)
    assert set(calls) == set(fixture_images["paths"])
    assert len(calls) == len(set(calls)), (
        f"each unique image must decode once across folds/maps/refit; "
        f"loader saw {len(calls)} calls for {len(set(calls))} unique files")
    # and the cache is droppable
    est.clearDecodeCache()
    est.fit(df)
    assert len(calls) > len(set(fixture_images["paths"]))


def test_decode_cache_is_byte_bounded(fixture_images, monkeypatch):
    """ADVICE r3: the per-URI decode cache must be BOUNDED — an estimator
    reused across datasets (same loader) must not hold every decoded
    image for its lifetime.  With a cap of ~2 images, residency stays at
    the cap while results stay correct, and older entries re-decode."""
    paths = fixture_images["paths"]
    labels = [i % 2 for i in range(len(paths))]
    df = DataFrame({"uri": paths, "label": labels})
    one_img = np.asarray(_loader(paths[0]), dtype=np.float32)
    cap_mb = (2 * one_img.nbytes + 1) / 1e6
    monkeypatch.setenv("SPARKDL_DECODE_CACHE_MB", f"{cap_mb:.6f}")
    calls = []

    def counting_loader(uri):
        calls.append(uri)
        return _loader(uri)

    est = ImageFileEstimator(
        inputCol="uri", outputCol="prediction", labelCol="label",
        modelFunction=_tiny_trainable_mf(),
        imageLoader=counting_loader, optimizer="sgd",
        loss="sparse_categorical_crossentropy",
        fitParams={"epochs": 1}, batchSize=8)
    est.fit(df)
    lru = est.__dict__["_decode_cache"][1]
    assert len(lru) <= 2
    assert lru.total_bytes <= lru.cap_bytes
    # second fit over the same data re-decodes the evicted entries but
    # still completes (bounded beats unbounded; correctness unchanged)
    est.fit(df)
    assert len(calls) > len(paths)
    assert len(lru) <= 2


def test_logistic_regression_standardization_tiny_scale(blobs):
    """Spark-parity standardization: features scaled down 1e4 must still
    train at the default learning rate (the deep-featurizer output regime);
    the scaler folds back into plain linear weights."""
    _, x, y = blobs
    tiny = x * 1e-4
    df = DataFrame({"features": [list(map(float, r)) for r in tiny],
                    "label": [int(v) for v in y]})
    model = LogisticRegression(maxIter=30).fit(df)
    rows = model.transform(df).collect()
    acc = np.mean([r["prediction"] == r["label"] for r in rows])
    assert acc > 0.9
    # folded model is a pure linear head: same result from raw weights
    logits = np.asarray(tiny, np.float32) @ model.weights["w"] + \
        model.weights["b"]
    np.testing.assert_array_equal(logits.argmax(1),
                                  [r["prediction"] for r in rows])
    # without standardization the same setup cannot move off chance
    m2 = LogisticRegression(maxIter=30, standardization=False).fit(df)
    rows2 = m2.transform(df).collect()
    acc2 = np.mean([r["prediction"] == r["label"] for r in rows2])
    assert acc2 < acc


def test_train_batch_stats_global_batch_equivalence(rng):
    """VERDICT r2 weak #6: the docstring claims updated BatchNorm stats
    match a single-device run over the same global batch (SPMD psum gives
    the stats reductions global semantics).  Prove it: identical data and
    batches, 8-device mesh vs 1-device mesh, fitted batch_stats AND params
    must agree."""
    import jax
    import optax
    from flax import linen as nn

    from sparkdl_tpu.parallel.train import fit_data_parallel

    class BNNet(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = nn.Dense(8, name="d1")(x)
            x = nn.BatchNorm(use_running_average=not train, name="bn")(x)
            return nn.Dense(2, name="head")(x)

    module = BNNet()
    x = rng.normal(size=(64, 5)).astype(np.float32)
    y = rng.normal(size=(64, 2)).astype(np.float32)
    variables = jax.tree_util.tree_map(np.asarray, module.init(
        jax.random.PRNGKey(0), x[:1], train=False))

    def train_fn(v, xb):
        pred, mutated = module.apply(v, xb, train=True,
                                     mutable=["batch_stats"])
        return pred, mutated["batch_stats"]

    def run(mesh):
        fitted, _ = fit_data_parallel(
            None, dict(variables["params"]), x, y,
            optimizer=optax.sgd(0.05), loss="mse", batch_size=16,
            epochs=4, shuffle=False, mesh=mesh,
            train_fn=train_fn, stats=dict(variables["batch_stats"]))
        return fitted

    f8 = run(get_mesh())
    f1 = run(get_mesh(num_devices=1))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        f8["batch_stats"], f1["batch_stats"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        f8["params"], f1["params"])


def test_fit_multiple_parallel_mesh_slices_match_sequential(uri_label_df):
    """parallelism>1 fans maps out over independent mesh slices (the TPU
    analog of the reference's one-Spark-task-per-map); results must be
    IDENTICAL to the sequential whole-mesh fits: a fit is deterministic
    given (data order, seed), and the gradient psum is batch-size-exact
    regardless of how many devices share it."""
    def build(par):
        return ImageFileEstimator(
            inputCol="uri", outputCol="preds", labelCol="label",
            modelFunction=_tiny_trainable_mf(),
            imageLoader=_loader, optimizer="sgd",
            loss="categorical_crossentropy",
            fitParams={"epochs": 2, "shuffle": False}, batchSize=8,
            parallelism=par)

    est_seq = build(1)
    maps = [{est_seq.fitParams: {"epochs": 1, "shuffle": False}},
            {est_seq.fitParams: {"epochs": 2, "shuffle": False}},
            {est_seq.fitParams: {"epochs": 3, "shuffle": False}},
            {est_seq.fitParams: {"epochs": 4, "shuffle": False}}]
    seq = est_seq.fit(uri_label_df, maps)
    est_par = build(4)
    par = est_par.fit(uri_label_df, [dict(m) for m in [
        {est_par.fitParams: {"epochs": 1, "shuffle": False}},
        {est_par.fitParams: {"epochs": 2, "shuffle": False}},
        {est_par.fitParams: {"epochs": 3, "shuffle": False}},
        {est_par.fitParams: {"epochs": 4, "shuffle": False}}]])
    assert len(par) == 4
    for m_seq, m_par in zip(seq, par):
        assert m_seq.trainLosses == pytest.approx(m_par.trainLosses,
                                                  rel=1e-4)
        np.testing.assert_allclose(
            np.asarray(m_seq.getModelFunction().variables["w"]),
            np.asarray(m_par.getModelFunction().variables["w"]),
            rtol=1e-4, atol=1e-6)


def test_fit_multiple_disambiguates_checkpoint_dirs(tmp_path, uri_label_df):
    """Param maps sharing one fitParams checkpoint_dir must not resume
    from each other's checkpoints: fitMultiple gives each map its own
    subdirectory."""
    ck = str(tmp_path / "ck")
    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=_tiny_trainable_mf(),
        imageLoader=_loader, optimizer="sgd",
        loss="categorical_crossentropy",
        fitParams={"epochs": 1, "checkpoint_dir": ck}, batchSize=8)
    maps = [{est.fitParams: {"epochs": 1, "checkpoint_dir": ck}},
            {est.fitParams: {"epochs": 2, "checkpoint_dir": ck}}]
    models = est.fit(uri_label_df, maps)
    # without per-map dirs, map 1 would resume at map 0's epoch-1
    # checkpoint and train only 1 epoch
    assert len(models[0].trainLosses) == 1
    assert len(models[1].trainLosses) == 2
    import os

    assert sorted(d for d in os.listdir(ck)) == ["map_000", "map_001"]
    assert os.path.isdir(os.path.join(ck, "map_001", "epoch_000002"))


def test_spe_checkpoint_resume_matches_k1_and_uninterrupted(tmp_path,
                                                            uri_label_df):
    """steps_per_execution x checkpoint-resume (VERDICT r4 #5): a fit
    interrupted after epoch 1 and resumed with spe=k must checkpoint at
    the same epoch cadence and reach the same weights as the k=1 resume
    path and as an uninterrupted run — grouped-step bookkeeping must not
    shift the checkpoint cadence or the resumed batch schedule."""
    import os

    def fit(epochs, spe, ck=None):
        fp = {"epochs": epochs, "shuffle": False,
              "steps_per_execution": spe}
        if ck:
            fp.update(checkpoint_dir=ck, checkpoint_every_epochs=1)
        est = ImageFileEstimator(
            inputCol="uri", outputCol="preds", labelCol="label",
            modelFunction=_tiny_trainable_mf(),
            imageLoader=_loader, optimizer="sgd",
            loss="categorical_crossentropy",
            fitParams=fp, batchSize=4)  # 12 rows / 4 = 3 steps: ragged
        return est.fit(uri_label_df)    # spe=2 group per epoch

    full = fit(3, 2)                    # uninterrupted spe=2 run
    ck2 = str(tmp_path / "spe2")        # interrupted spe=2: epoch 1,
    fit(1, 2, ck2)                      # then "restart" asking for 3
    assert os.path.isdir(os.path.join(ck2, "epoch_000001"))
    resumed2 = fit(3, 2, ck2)
    assert len(resumed2.trainLosses) == 2   # only epochs 2..3 ran
    assert os.path.isdir(os.path.join(ck2, "epoch_000003"))
    ck1 = str(tmp_path / "spe1")        # the k=1 resume path
    fit(1, 1, ck1)
    resumed1 = fit(3, 1, ck1)
    w_full = np.asarray(full.getModelFunction().variables["w"])
    w2 = np.asarray(resumed2.getModelFunction().variables["w"])
    w1 = np.asarray(resumed1.getModelFunction().variables["w"])
    np.testing.assert_allclose(w2, w1, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(w2, w_full, rtol=1e-5, atol=1e-7)
    assert resumed2.trainLosses == pytest.approx(resumed1.trainLosses,
                                                 rel=1e-5)


def test_tensor_parallel_head_matches_replicated(rng):
    """The mesh's ``model`` axis carries real tensor parallelism: a train
    step with the head kernel sharded over a (data=4, model=2) mesh must
    produce the same fit as the fully-replicated step — XLA inserts the
    activation/gradient collectives the layout implies, without changing
    the math."""
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from sparkdl_tpu.parallel.train import make_train_step

    dim, classes, n = 6, 4, 32
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (np.arange(n) % classes).astype(np.int32)
    params0 = {
        "body": rng.normal(0, 0.1, (dim, dim)).astype(np.float32),
        "head": {"kernel": rng.normal(0, 0.1, (dim, classes)
                                      ).astype(np.float32),
                 "bias": np.zeros((classes,), np.float32)},
    }

    def predict(p, xb):
        h = jnp.tanh(jnp.asarray(xb) @ p["body"])
        return h @ p["head"]["kernel"] + p["head"]["bias"]

    def ce(logits, yb):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb.astype(jnp.int32))

    def run(mesh, specs):
        opt = optax.sgd(0.1)
        step = make_train_step(predict, ce, opt, mesh=mesh, cache=False,
                               param_specs=specs, params_template=params0)
        params = {k: (dict(v) if isinstance(v, dict) else v.copy())
                  for k, v in params0.items()}
        opt_state = opt.init(params)
        params, opt_state = step.put_state(params, opt_state)
        import jax

        for off in range(0, n, 8):
            bx, by = step.put_batch(x[off:off + 8], y[off:off + 8])
            params, opt_state, lval = step(params, opt_state, bx, by)
        return jax.tree_util.tree_map(np.asarray, params), float(lval)

    def tp_rule(path, leaf):
        if path.endswith("head/kernel"):
            return P(None, "model")
        if path.endswith("head/bias"):
            return P("model")
        return P()

    mesh_tp = get_mesh(model_parallel=2)     # (data=4, model=2)
    mesh_rep = get_mesh()                    # (data=8, model=1)
    p_tp, l_tp = run(mesh_tp, tp_rule)
    p_rep, l_rep = run(mesh_rep, None)
    assert np.isfinite(l_tp) and np.isfinite(l_rep)
    np.testing.assert_allclose(l_tp, l_rep, rtol=1e-4)
    import jax

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        p_tp, p_rep)


def test_tensor_parallel_opt_state_single_compile(rng):
    """ADVICE r3: with a momentum optimizer, the TP step must pin mu/nu
    shardings to the param layouts so every step reuses ONE executable —
    leaving opt_state layout to the partitioner caused a second compile
    at step 2 with donation of mismatched buffers."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from sparkdl_tpu.parallel.train import make_train_step

    dim, classes, n = 6, 4, 32
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (np.arange(n) % classes).astype(np.int32)
    params0 = {
        "body": rng.normal(0, 0.1, (dim, dim)).astype(np.float32),
        "head": {"kernel": rng.normal(0, 0.1, (dim, classes)
                                      ).astype(np.float32),
                 "bias": np.zeros((classes,), np.float32)},
    }

    def predict(p, xb):
        h = jnp.tanh(jnp.asarray(xb) @ p["body"])
        return h @ p["head"]["kernel"] + p["head"]["bias"]

    def ce(logits, yb):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb.astype(jnp.int32))

    def tp_rule(path, leaf):
        if path.endswith("head/kernel"):
            return P(None, "model")
        if path.endswith("head/bias"):
            return P("model")
        return P()

    opt = optax.adam(1e-2)
    step = make_train_step(predict, ce, opt, mesh=get_mesh(model_parallel=2),
                           cache=False, param_specs=tp_rule,
                           params_template=params0)
    params, opt_state = step.put_state(params0, opt.init(params0))
    for off in range(0, n, 8):
        bx, by = step.put_batch(x[off:off + 8], y[off:off + 8])
        params, opt_state, lval = step(params, opt_state, bx, by)
    assert np.isfinite(float(lval))
    assert step.step_fn._cache_size() == 1
    # mu/nu follow the param layout; the step count stays replicated
    mu_kernel = opt_state[0].mu["head"]["kernel"]
    assert mu_kernel.sharding.spec == P(None, "model")


def test_cross_validator_parallelism_matches_sequential(fixture_images):
    """CrossValidator(parallelism=k) forwards the fan-out to the
    estimator (pyspark.ml.tuning contract); metrics and the selected
    model must match the sequential run exactly."""
    from sparkdl_tpu.estimators.evaluation import \
        MulticlassClassificationEvaluator

    paths = fixture_images["paths"] * 4
    labels = [i % 2 for i in range(len(paths))]
    df = DataFrame({"uri": paths, "label": labels})

    def build_cv(par):
        est = ImageFileEstimator(
            inputCol="uri", outputCol="prediction", labelCol="label",
            modelFunction=_tiny_trainable_mf(),
            imageLoader=_loader, optimizer="sgd",
            loss="sparse_categorical_crossentropy",
            fitParams={"epochs": 1, "shuffle": False}, batchSize=8)
        maps = [{est.batchSize: 8}, {est.batchSize: 12}]
        ev = MulticlassClassificationEvaluator(labelCol="label",
                                               predictionCol="prediction")
        return CrossValidator(estimator=est, estimatorParamMaps=maps,
                              evaluator=ev, numFolds=2, parallelism=par)

    m_seq = build_cv(1).fit(df)
    m_par = build_cv(2).fit(df)
    np.testing.assert_allclose(m_seq.avgMetrics, m_par.avgMetrics,
                               rtol=1e-6)
    a = m_seq.bestModel.transform(df).collect()
    b = m_par.bestModel.transform(df).collect()
    for ra, rb in zip(a, b):
        np.testing.assert_allclose(ra["prediction"], rb["prediction"])


def test_fit_multiple_parallel_with_train_batch_stats(uri_label_df):
    """VERDICT r3 #7: parallelism>1 composed with trainBatchStats=True —
    concurrent threads driving the stats-mutating train step on different
    sub-meshes must produce the SAME params AND batch_stats as the
    sequential whole-mesh fits (global-batch BN stats are psum-exact
    regardless of slice width)."""
    def build(par):
        return ImageFileEstimator(
            inputCol="uri", outputCol="preds", labelCol="label",
            modelFunction=_bn_model_function(seed=0),
            imageLoader=_loader, optimizer="sgd",
            loss="categorical_crossentropy",
            fitParams={"epochs": 1, "shuffle": False}, batchSize=8,
            trainBatchStats=True, parallelism=par)

    def maps_for(est):
        return [{est.fitParams: {"epochs": 1, "shuffle": False}},
                {est.fitParams: {"epochs": 3, "shuffle": False}}]

    est_seq = build(1)
    seq = est_seq.fit(uri_label_df, maps_for(est_seq))
    est_par = build(2)
    par = est_par.fit(uri_label_df, maps_for(est_par))
    assert len(par) == 2
    for m_seq, m_par in zip(seq, par):
        assert m_seq.trainLosses == pytest.approx(m_par.trainLosses,
                                                  rel=1e-4)
        vs, vp = (m.getModelFunction().variables for m in (m_seq, m_par))
        np.testing.assert_allclose(
            np.asarray(vs["batch_stats"]["bn"]["mean"]),
            np.asarray(vp["batch_stats"]["bn"]["mean"]),
            rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(vs["params"]["head"]["kernel"]),
            np.asarray(vp["params"]["head"]["kernel"]),
            rtol=1e-4, atol=1e-6)


def test_fit_multiple_parallel_checkpoint_dirs(tmp_path, uri_label_df):
    """VERDICT r3 #7: parallelism>1 composed with a shared checkpoint_dir —
    concurrent maps must write disjoint per-map subdirectories (no
    cross-map corruption) and still match the sequential fit."""
    import os

    ck_par = str(tmp_path / "ck_par")
    ck_seq = str(tmp_path / "ck_seq")

    def build(par, ck):
        return ImageFileEstimator(
            inputCol="uri", outputCol="preds", labelCol="label",
            modelFunction=_tiny_trainable_mf(),
            imageLoader=_loader, optimizer="sgd",
            loss="categorical_crossentropy",
            fitParams={"epochs": 1, "checkpoint_dir": ck,
                       "shuffle": False}, batchSize=8, parallelism=par)

    def maps_for(est, ck):
        return [{est.fitParams: {"epochs": 1, "checkpoint_dir": ck,
                                 "shuffle": False}},
                {est.fitParams: {"epochs": 2, "checkpoint_dir": ck,
                                 "shuffle": False}}]

    est_par = build(2, ck_par)
    par = est_par.fit(uri_label_df, maps_for(est_par, ck_par))
    est_seq = build(1, ck_seq)
    seq = est_seq.fit(uri_label_df, maps_for(est_seq, ck_seq))
    # per-map dirs exist with each map's own epoch count
    assert sorted(os.listdir(ck_par)) == ["map_000", "map_001"]
    assert os.path.isdir(os.path.join(ck_par, "map_000", "epoch_000001"))
    assert os.path.isdir(os.path.join(ck_par, "map_001", "epoch_000002"))
    for m_seq, m_par in zip(seq, par):
        assert len(m_seq.trainLosses) == len(m_par.trainLosses)
        np.testing.assert_allclose(
            np.asarray(m_seq.getModelFunction().variables["w"]),
            np.asarray(m_par.getModelFunction().variables["w"]),
            rtol=1e-4, atol=1e-6)


def test_steps_per_execution_matches_single_step(uri_label_df):
    """steps_per_execution packs k steps into one dispatch (lax.scan) —
    the loss series and fitted weights must be IDENTICAL to the one-step
    loop, including the ragged tail group."""
    def fit(spe):
        est = ImageFileEstimator(
            inputCol="uri", outputCol="preds", labelCol="label",
            modelFunction=_tiny_trainable_mf(),
            imageLoader=_loader, optimizer="sgd",
            loss="categorical_crossentropy",
            fitParams={"epochs": 3, "shuffle": False,
                       "steps_per_execution": spe}, batchSize=8)
        return est.fit(uri_label_df)

    base = fit(1)
    for spe in (2, 3):  # 16 rows / batch 8 = 2 steps/epoch: even + ragged
        packed = fit(spe)
        assert base.trainLosses == pytest.approx(packed.trainLosses,
                                                 rel=1e-5)
        np.testing.assert_allclose(
            np.asarray(base.getModelFunction().variables["w"]),
            np.asarray(packed.getModelFunction().variables["w"]),
            rtol=1e-5, atol=1e-7)


def test_steps_per_execution_with_batch_stats(uri_label_df):
    """spe composes with trainBatchStats: the scanned step updates BN
    statistics identically to the one-step loop."""
    def fit(spe):
        est = ImageFileEstimator(
            inputCol="uri", outputCol="preds", labelCol="label",
            modelFunction=_bn_model_function(seed=0),
            imageLoader=_loader, optimizer="sgd",
            loss="categorical_crossentropy",
            fitParams={"epochs": 2, "shuffle": False,
                       "steps_per_execution": spe},
            batchSize=8, trainBatchStats=True)
        return est.fit(uri_label_df)

    base, packed = fit(1), fit(4)
    assert base.trainLosses == pytest.approx(packed.trainLosses, rel=1e-5)
    vb = base.getModelFunction().variables
    vp = packed.getModelFunction().variables
    np.testing.assert_allclose(
        np.asarray(vb["batch_stats"]["bn"]["mean"]),
        np.asarray(vp["batch_stats"]["bn"]["mean"]), rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(
        np.asarray(vb["params"]["head"]["kernel"]),
        np.asarray(vp["params"]["head"]["kernel"]), rtol=1e-5, atol=1e-7)
