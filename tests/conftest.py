"""Test harness.

Mirrors the reference's test strategy (SURVEY.md §4): everything runs
single-machine, with multi-chip behavior simulated — here via an 8-device
virtual CPU platform (``xla_force_host_platform_device_count``), the TPU
analog of the reference's `local[*]` SparkSession with multiple partitions.
"""

import os

# Must be set before the CPU backend initializes (XLA_FLAGS is read from the
# environment at client-creation time).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# Keras (used only as a parity oracle / legacy-import reader) on CPU TF.
os.environ.setdefault("KERAS_BACKEND", "tensorflow")
os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

# The environment may pre-import jax with an accelerator platform pinned
# (e.g. the axon TPU plugin registers via sitecustomize and freezes
# JAX_PLATFORMS at import).  jax.config.update overrides that reliably;
# plain env vars do not.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(seed=0)


@pytest.fixture(scope="session")
def fixture_images(tmp_path_factory, rng):
    """A handful of tiny real JPEG files — the reference tests use small
    image fixtures under python/tests/resources/images/; we synthesize ours
    (no bundled binaries) but they are real encoded JPEGs on disk."""
    from PIL import Image

    d = tmp_path_factory.mktemp("images")
    paths = []
    for i, size in enumerate([(32, 48), (64, 64), (50, 40)]):
        arr = (rng.random((size[1], size[0], 3)) * 255).astype("uint8")
        p = d / f"img_{i}.jpg"
        Image.fromarray(arr).save(p, quality=95)
        paths.append(str(p))
    # one non-image file to exercise decode-failure handling
    bad = d / "not_an_image.jpg"
    bad.write_bytes(b"this is not a jpeg")
    return {"dir": str(d), "paths": paths, "bad": str(bad)}
