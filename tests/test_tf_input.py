"""TFInputGraph six-constructor tests.

Mirrors the reference's ``python/tests/graph/test_input.py``: one tiny
serialized model exercised through ALL SIX construction paths, each checked
for numeric parity against a direct TF session run (the reference's own
oracle), executed here through the GraphDef->jax importer.
"""

import os

import numpy as np
import pytest

from sparkdl_tpu.graph.input import TFInputGraph
from sparkdl_tpu.graph.tf_import import graphdef_to_jax
from sparkdl_tpu.graph.utils import op_name, tensor_name


def _tf():
    os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
    import tensorflow as tf

    return tf


@pytest.fixture(scope="module")
def tiny_tf_model(tmp_path_factory):
    """Build a TF1-style MLP; save checkpoint (with signature) + SavedModel
    (with signature); also return frozen GraphDef + reference outputs."""
    tf = _tf()
    v1 = tf.compat.v1
    base = tmp_path_factory.mktemp("tfmodel")
    ckpt_dir = str(base / "ckpt")
    sm_dir = str(base / "saved_model")
    os.makedirs(ckpt_dir, exist_ok=True)

    rng = np.random.default_rng(3)
    x_in = rng.normal(size=(6, 4)).astype(np.float32)

    graph = v1.Graph()
    with graph.as_default():
        x = v1.placeholder(tf.float32, [None, 4], name="x")
        w1 = v1.get_variable("w1", initializer=rng.normal(
            size=(4, 8)).astype(np.float32))
        b1 = v1.get_variable("b1", initializer=np.zeros(8, np.float32))
        h = tf.nn.relu(tf.matmul(x, w1) + b1, name="hidden")
        w2 = v1.get_variable("w2", initializer=rng.normal(
            size=(8, 3)).astype(np.float32))
        out = tf.nn.softmax(tf.matmul(h, w2), name="out")
        with v1.Session(graph=graph) as sess:
            sess.run(v1.global_variables_initializer())
            ref = sess.run(out, {x: x_in})

            sig = v1.saved_model.signature_def_utils.predict_signature_def(
                inputs={"features": x}, outputs={"scores": out})

            # checkpoint + signature-carrying meta
            saver = v1.train.Saver()
            path = saver.save(sess, os.path.join(ckpt_dir, "model"))
            meta = saver.export_meta_graph()
            meta.signature_def["my_sig"].CopyFrom(sig)
            with open(path + ".meta", "wb") as f:
                f.write(meta.SerializeToString())

            # SavedModel with signature
            builder = v1.saved_model.Builder(sm_dir)
            builder.add_meta_graph_and_variables(
                sess, ["serve"], signature_def_map={"serving_default": sig})
            builder.save()

            # frozen graphdef
            frozen = v1.graph_util.convert_variables_to_constants(
                sess, graph.as_graph_def(add_shapes=True), ["out"])
    return {
        "graph": graph, "ckpt_dir": ckpt_dir, "sm_dir": sm_dir,
        "frozen": frozen, "x": x_in, "ref": ref,
    }


def _check(tig: TFInputGraph, m, input_key=None):
    mf = tig.model_function()
    x = m["x"]
    arg = {input_key: x} if input_key else x
    got = mf(arg)
    if isinstance(got, dict):
        got = got[mf.output_names[0]]
    np.testing.assert_allclose(np.asarray(got), m["ref"],
                               rtol=1e-5, atol=1e-6)


def test_from_graph(tiny_tf_model):
    m = tiny_tf_model
    tf = _tf()
    v1 = tf.compat.v1
    # fresh session over the original graph (variables re-initialized from
    # the checkpoint to keep the same weights)
    with m["graph"].as_default():
        with v1.Session(graph=m["graph"]) as sess:
            v1.train.Saver().restore(
                sess, tf.train.latest_checkpoint(m["ckpt_dir"]))
            tig = TFInputGraph.fromGraph(m["graph"], sess, ["x"], ["out"])
    _check(tig, m)


def test_from_graphdef(tiny_tf_model):
    m = tiny_tf_model
    tig = TFInputGraph.fromGraphDef(m["frozen"], ["x"], ["out"])
    _check(tig, m)


def test_from_checkpoint(tiny_tf_model):
    m = tiny_tf_model
    tig = TFInputGraph.fromCheckpoint(m["ckpt_dir"], ["x"], ["out"])
    _check(tig, m)


def test_from_checkpoint_with_signature(tiny_tf_model):
    m = tiny_tf_model
    tig = TFInputGraph.fromCheckpointWithSignature(m["ckpt_dir"], "my_sig")
    assert tig.input_names == ["features"]
    assert tig.output_names == ["scores"]
    _check(tig, m, input_key="features")


def test_from_saved_model(tiny_tf_model):
    m = tiny_tf_model
    tig = TFInputGraph.fromSavedModel(m["sm_dir"], "serve", ["x"], ["out"])
    _check(tig, m)


def test_from_saved_model_with_signature(tiny_tf_model):
    m = tiny_tf_model
    tig = TFInputGraph.fromSavedModelWithSignature(
        m["sm_dir"], "serve", "serving_default")
    assert tig.input_names == ["features"]
    _check(tig, m, input_key="features")


def test_missing_signature_fails(tiny_tf_model):
    m = tiny_tf_model
    with pytest.raises(ValueError, match="not found"):
        TFInputGraph.fromSavedModelWithSignature(m["sm_dir"], "serve", "nope")


def test_importer_rejects_unsupported_ops(tiny_tf_model):
    tf = _tf()
    v1 = tf.compat.v1
    g = v1.Graph()
    with g.as_default():
        x = v1.placeholder(tf.float32, [None, 2, 2], name="x")
        # Cumsum is (deliberately) not in the supported op set
        y = tf.cumsum(x, axis=1, name="y")
        gd = g.as_graph_def()
    with pytest.raises(NotImplementedError, match="Cumsum"):
        graphdef_to_jax(gd, ["x"], ["y"])


def test_importer_jit_and_conv(tiny_tf_model):
    """Conv/pool/BN-style graph through the importer, jitted, vs TF."""
    import jax

    tf = _tf()
    v1 = tf.compat.v1
    rng = np.random.default_rng(4)
    x_in = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    g = v1.Graph()
    with g.as_default():
        x = v1.placeholder(tf.float32, [None, 8, 8, 3], name="x")
        k = tf.constant(rng.normal(size=(3, 3, 3, 4)).astype(np.float32))
        y = tf.nn.conv2d(x, k, strides=[1, 2, 2, 1], padding="SAME")
        y = tf.nn.relu(y)
        y = tf.nn.max_pool2d(y, 2, 2, padding="VALID")
        y = tf.reduce_mean(y, axis=[1, 2], name="feat")
        with v1.Session(graph=g) as sess:
            ref = sess.run(y, {x: x_in})
        gd = g.as_graph_def()
    mf = graphdef_to_jax(gd, ["x"], ["feat"])
    got = np.asarray(jax.jit(mf.fn)(mf.variables, x_in))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_name_utils():
    assert op_name("a/b:0") == "a/b"
    assert tensor_name("a/b") == "a/b:0"
    assert tensor_name("a/b:1") == "a/b:1"
    with pytest.raises(ValueError):
        tensor_name("a:b:c")


def test_importer_deep_chain_no_recursion_error():
    """A few-hundred-node sequential chain (typical for real zoo graphs)
    must evaluate iteratively, not by recursive descent (ADVICE round 1)."""
    tf = _tf()
    v1 = tf.compat.v1
    depth = 600
    graph = v1.Graph()
    with graph.as_default():
        x = v1.placeholder(tf.float32, shape=[None, 3], name="x")
        h = x
        for i in range(depth):
            h = tf.add(h, 1.0 / depth, name=f"add_{i}")
        out = tf.identity(h, name="out")
    mf = graphdef_to_jax(graph.as_graph_def(), ["x"], ["out"])
    xv = np.zeros((2, 3), dtype=np.float32)
    got = np.asarray(mf.fn(mf.variables, xv))
    np.testing.assert_allclose(got, np.ones((2, 3)), rtol=1e-4)
