"""Exactly-once streaming ingestion (ISSUE 8, ROADMAP item 5).

Tier-1 (CPU-only).  Pins the crash-safe continuous-scoring contracts:

* the shared JSONL torn-tail recovery (`utils.jsonl.read_jsonl` /
  `recover_jsonl`) contract-tested from BOTH callers — the bench
  artifact's writer and the streaming journal;
* source semantics: ordered content-addressed chunks, stable ids across
  seek/replay, directory-watch ordering + end marker;
* journal edge cases: cold start, torn-tail truncation on restart,
  duplicate-commit idempotence, resume offset around holes;
* StreamScorer: exactly-once vs the batch `map_batches` oracle
  (pipelined and serving-sink paths), duplicate suppression by id,
  crash-between-output-and-commit resume, `stream.resume` injection,
  source-stall watchdog -> degraded -> recovered health;
* the headline chaos test: a REAL SIGKILL between output write and
  journal commit mid-stream, restart, outputs exactly-once (no gap, no
  duplicate) and bit-identical to the batch oracle, lag recovered.

Budget note: tier-1 runs ~720-780s against an 870s driver timeout —
every in-process test here is sub-second except the two subprocess
runs of the SIGKILL headline (~10s total).
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu import faults, streaming
from sparkdl_tpu.faults import FaultPlan
from sparkdl_tpu.parallel.engine import InferenceEngine
from sparkdl_tpu.streaming import (DirectorySource, Journal, MemorySource,
                                   StreamScorer, assemble_outputs,
                                   content_chunk_id, finish_directory_stream,
                                   write_directory_chunk)
from sparkdl_tpu.utils.jsonl import (CrashSafeJsonlWriter,
                                     JsonlCorruptionError, read_jsonl,
                                     recover_jsonl)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolated_plan():
    """Never leak a fault plan between tests (or out of the suite)."""
    from sparkdl_tpu.faults import plan as plan_mod

    prev = plan_mod._PLAN
    yield
    plan_mod._PLAN = prev


def _fn(variables, x):
    import jax.numpy as jnp

    return jnp.tanh(x @ variables["w"])


@pytest.fixture(scope="module")
def engine():
    rng = np.random.default_rng(7)
    variables = {"w": rng.normal(size=(6, 4)).astype(np.float32)}
    return InferenceEngine(_fn, variables, device_batch_size=8)


@pytest.fixture(scope="module")
def payloads():
    rng = np.random.default_rng(11)
    return [rng.normal(size=(8, 6)).astype(np.float32) for _ in range(6)]


@pytest.fixture(scope="module")
def oracle(engine, payloads):
    """The batch half of the exactly-once acceptance check: one
    map_batches pass over the same chunks."""
    return np.concatenate(
        [np.asarray(o) for o in engine.map_batches(payloads,
                                                   pipeline=False)], axis=0)


def _scorer(engine, src, base, **kw):
    kw.setdefault("pipeline", False)
    return StreamScorer(engine, src,
                        journal_path=os.path.join(base, "journal.jsonl"),
                        out_dir=os.path.join(base, "out"), **kw)


def _assemble(base):
    return assemble_outputs(os.path.join(base, "journal.jsonl"),
                            os.path.join(base, "out"))


# -- shared JSONL: one implementation, both callers ------------------------

def test_read_jsonl_tolerates_torn_tail_and_recover_truncates(tmp_path):
    p = str(tmp_path / "a.jsonl")
    w = CrashSafeJsonlWriter(p)
    for i in range(3):
        assert w.write_line(json.dumps({"i": i}))
    w.close()
    good_size = os.path.getsize(p)
    with open(p, "ab") as f:
        f.write(b'{"i": 3, "torn')  # crash mid-append: no newline
    recs, valid = read_jsonl(p)
    assert [r["i"] for r in recs] == [0, 1, 2]
    assert valid == good_size
    recs2, discarded = recover_jsonl(p)
    assert [r["i"] for r in recs2] == [0, 1, 2] and discarded > 0
    assert os.path.getsize(p) == good_size  # tail gone, fsync'd
    # a terminated-but-unparsable FINAL line is also recoverable tail
    with open(p, "ab") as f:
        f.write(b'{"i": 3, "torn"\n')
    recs3, _ = read_jsonl(p)
    assert [r["i"] for r in recs3] == [0, 1, 2]


def test_read_jsonl_mid_file_corruption_raises(tmp_path):
    p = str(tmp_path / "a.jsonl")
    with open(p, "wb") as f:
        f.write(b'{"i": 0}\nnot json at all\n{"i": 2}\n')
    with pytest.raises(JsonlCorruptionError):
        read_jsonl(p)


def test_jsonl_contract_shared_by_bench_artifact_and_journal(tmp_path):
    """Both callers of the one implementation: a bench-style artifact
    and a streaming journal, each torn, each recovered by the same
    functions (the ISSUE 8 factoring satellite)."""
    # bench.py caller: its artifact is a CrashSafeJsonlWriter product
    import bench

    assert isinstance(bench._ARTIFACT, CrashSafeJsonlWriter)
    art = str(tmp_path / "bench_lines.jsonl")
    w = CrashSafeJsonlWriter(art)
    w.write_line(json.dumps({"config": "pipeline", "value": 1.5}))
    w.close()
    with open(art, "ab") as f:
        f.write(b'{"config": "serving", "val')  # SIGKILL mid-line
    recs, _ = recover_jsonl(art)
    assert [r["config"] for r in recs] == ["pipeline"]
    # journal caller: same torn-tail shape, recovered at Journal() open
    jp = str(tmp_path / "journal.jsonl")
    j = Journal(jp)
    j.begin("c0", 0)
    j.commit("c0", 0)
    j.close()
    with open(jp, "ab") as f:
        f.write(b'{"rec": "intent", "chunk_id": "c1"')
    j2 = Journal(jp)
    assert j2.recovered_torn_bytes > 0
    assert j2.is_committed("c0") and not j2.seen("c1")
    j2.close()


# -- sources ---------------------------------------------------------------

def test_memory_source_ordered_ids_stable_across_seek():
    rng = np.random.default_rng(0)
    src = MemorySource([rng.normal(size=(4, 3)) for _ in range(3)],
                       finished=True)
    first = [src.poll() for _ in range(3)]
    assert [c.offset for c in first] == [0, 1, 2]
    assert src.poll() is None and src.exhausted()
    src.seek(1)
    again = src.poll()
    assert again.chunk_id == first[1].chunk_id  # content-addressed, stable
    assert np.array_equal(again.payload, first[1].payload)
    ids = {c.chunk_id for c in first}
    assert len(ids) == 3  # distinct content/offset -> distinct ids


def test_directory_source_order_end_marker_seek(tmp_path):
    d = str(tmp_path / "in")
    rng = np.random.default_rng(1)
    chunks = [rng.normal(size=(4, 3)).astype(np.float32) for _ in range(3)]
    write_directory_chunk(d, 0, chunks[0])
    src = DirectorySource(d)
    c0 = src.poll()
    assert c0.offset == 0 and np.array_equal(c0.payload, chunks[0])
    assert src.poll() is None and not src.exhausted()  # nothing yet, live
    write_directory_chunk(d, 1, chunks[1])
    write_directory_chunk(d, 2, chunks[2])
    finish_directory_stream(d)
    got = [src.poll() for _ in range(2)]
    assert [c.offset for c in got] == [1, 2]
    assert src.exhausted()
    src.seek(1)  # replay: same bytes, same id
    replay = src.poll()
    assert replay.chunk_id == got[0].chunk_id
    assert replay.chunk_id == content_chunk_id(1, chunks[1])


# -- journal edge cases ----------------------------------------------------

def test_journal_cold_start_empty(tmp_path):
    j = Journal(str(tmp_path / "j.jsonl"))
    assert j.resume_offset() == 0
    assert j.committed_count() == 0 and j.uncommitted() == []
    assert j.recovered_torn_bytes == 0
    j.close()


def test_journal_torn_tail_truncated_on_restart(tmp_path):
    p = str(tmp_path / "j.jsonl")
    j = Journal(p)
    j.begin("c0", 0)
    j.record_output("c0", 0, "out-c0.npy", "d0")
    j.commit("c0", 0)
    j.begin("c1", 1)
    j.close()
    size = os.path.getsize(p)
    with open(p, "ab") as f:
        f.write(b'{"rec": "output", "chunk_id": "c1", "off')  # torn
    j2 = Journal(p)
    assert j2.recovered_torn_bytes > 0
    assert os.path.getsize(p) == size
    assert j2.is_committed("c0")
    assert j2.uncommitted() == [{"chunk_id": "c1", "offset": 1,
                                 "has_output": False}]
    assert j2.resume_offset() == 1
    # and the recovered journal appends cleanly right where it left off
    j2.record_output("c1", 1, "out-c1.npy", "d1")
    j2.commit("c1", 1)
    j2.close()
    recs, valid = read_jsonl(p)
    assert recs[-1]["rec"] == "commit" and valid == os.path.getsize(p)


def test_journal_duplicate_commit_idempotent(tmp_path):
    p = str(tmp_path / "j.jsonl")
    j = Journal(p)
    j.begin("c0", 0)
    assert j.commit("c0", 0) is True
    assert j.commit("c0", 0) is False  # idempotent: no second record
    j.close()
    recs, _ = read_jsonl(p)
    assert sum(r["rec"] == "commit" for r in recs) == 1
    j2 = Journal(p)  # and the reopened index agrees
    assert j2.commit("c0", 0) is False
    assert j2.committed_count() == 1
    j2.close()


def test_journal_resume_offset_skips_only_contiguous_prefix(tmp_path):
    j = Journal(str(tmp_path / "j.jsonl"))
    for cid, off in (("c0", 0), ("c2", 2)):  # hole at offset 1
        j.begin(cid, off)
        j.commit(cid, off)
    assert j.resume_offset() == 1  # seek to the hole...
    assert j.is_committed("c2")    # ...and suppress c2 by id on replay
    assert j.committed_offsets() == [0, 2]
    j.close()


# -- StreamScorer ----------------------------------------------------------

def test_exactly_once_basic_pipelined(engine, payloads, oracle, tmp_path):
    base = str(tmp_path)
    src = MemorySource(payloads, finished=True)
    sc = _scorer(engine, src, base, pipeline=True)
    summary = sc.run()
    assert summary["chunks_scored"] == len(payloads)
    assert summary["duplicates_suppressed"] == 0
    got = _assemble(base)
    assert np.array_equal(got, oracle)  # bit-identical, exactly-once
    m = sc.metrics
    assert m.counters["stream.chunks"] == len(payloads)
    assert m.counters["stream.commits"] == len(payloads)
    assert m.gauges["stream.watermark"] == len(payloads)
    h = sc.health()
    assert h["state"] == "ready" and h["watermark"] == len(payloads)
    sc.close()
    assert sc.health()["state"] == "closed" and not sc.health()["live"]


def test_duplicate_delivery_suppressed_by_id(engine, payloads, oracle,
                                             tmp_path):
    """A chunk the journal already committed (here: offset 1, committed
    out of band so the contiguous prefix stops at 0) is re-delivered by
    the seeked source and must be suppressed by id, not re-scored."""
    base = str(tmp_path)
    jp = os.path.join(base, "journal.jsonl")
    cid1 = content_chunk_id(1, payloads[1])
    j = Journal(jp)
    j.begin(cid1, 1)
    out1 = np.asarray(list(engine.map_batches([payloads[1]],
                                              pipeline=False))[0])
    from sparkdl_tpu.streaming.runner import (_array_digest,
                                              _write_artifact_atomic)

    os.makedirs(os.path.join(base, "out"), exist_ok=True)
    _write_artifact_atomic(
        os.path.join(base, "out", f"out-{cid1}.npy"), out1)
    j.record_output(cid1, 1, f"out-{cid1}.npy", _array_digest(out1))
    j.commit(cid1, 1)
    j.close()
    src = MemorySource(payloads, finished=True)
    sc = _scorer(engine, src, base)
    summary = sc.run()
    assert summary["resume_offset"] == 0
    assert summary["duplicates_suppressed"] == 1
    assert summary["chunks_scored"] == len(payloads) - 1
    assert sc.metrics.counters["stream.duplicates_suppressed"] == 1
    assert np.array_equal(_assemble(base), oracle)
    sc.close()


def test_crash_between_output_and_commit_then_resume(engine, payloads,
                                                     oracle, tmp_path):
    """The injected form of the headline: stream.commit kills run 1
    after the output artifact is durable but before the commit record;
    run 2 replays the uncommitted suffix to exactly-once output."""
    base = str(tmp_path)
    src = MemorySource(payloads, finished=True)
    sc = _scorer(engine, src, base)
    with faults.active(FaultPlan.parse(
            "stream.commit:error:exc=fatal,at=3")) as plan:
        with pytest.raises(faults.InjectedFatalError):
            sc.run()
        assert plan.fired("stream.commit") == 1
    # the crash left offsets 0,1 committed and offset 2's artifact
    # on disk without a commit — the exactly-once window
    j = Journal(os.path.join(base, "journal.jsonl"))
    assert j.resume_offset() == 2
    assert any(r["offset"] == 2 and r["has_output"]
               for r in j.uncommitted())
    j.close()
    src2 = MemorySource(payloads, finished=True)
    sc2 = _scorer(engine, src2, base)
    summary = sc2.run()
    assert summary["resume_offset"] == 2
    assert summary["redeliveries"] >= 1
    assert sc2.metrics.counters["stream.redeliveries"] >= 1
    got = _assemble(base)
    assert np.array_equal(got, oracle)
    # no duplicate commits, no artifact duplicates
    recs, _ = read_jsonl(os.path.join(base, "journal.jsonl"))
    commits = [r["chunk_id"] for r in recs if r["rec"] == "commit"]
    assert len(commits) == len(set(commits)) == len(payloads)
    arts = [f for f in os.listdir(os.path.join(base, "out"))
            if f.endswith(".npy")]
    assert len(arts) == len(payloads)
    sc2.close()


def test_replay_survives_stream_resume_injection(engine, payloads, oracle,
                                                 tmp_path):
    """stream.resume fires AT replay time: a restart that dies again
    while redelivering still converges on the next clean restart."""
    base = str(tmp_path)
    src = MemorySource(payloads, finished=True)
    sc = _scorer(engine, src, base)
    with faults.active(FaultPlan.parse("stream.commit:error:exc=fatal,at=2")):
        with pytest.raises(faults.InjectedFatalError):
            sc.run()
    with faults.active(FaultPlan.parse(
            "stream.resume:error:exc=fatal,at=1")) as plan:
        sc2 = _scorer(engine, MemorySource(payloads, finished=True), base)
        with pytest.raises(faults.InjectedFatalError):
            sc2.run()
        assert plan.fired("stream.resume") == 1
    sc3 = _scorer(engine, MemorySource(payloads, finished=True), base)
    summary = sc3.run()
    assert summary["redeliveries"] >= 1
    assert np.array_equal(_assemble(base), oracle)
    sc3.close()


def test_source_transient_fault_absorbed_by_repoll(engine, payloads, oracle,
                                                   tmp_path):
    base = str(tmp_path)
    src = MemorySource(payloads, finished=True)
    sc = _scorer(engine, src, base)
    with faults.active(FaultPlan.parse(
            "seed=5;stream.source:error:exc=transient,at=2")) as plan:
        summary = sc.run()
        assert plan.fired("stream.source") == 1
    assert summary["chunks_scored"] == len(payloads)
    assert sc.metrics.counters["stream.source_errors"] == 1
    assert np.array_equal(_assemble(base), oracle)
    # the transient left a health trace, then recovery won
    states = [t["state"] for t in sc.health()["transitions"]]
    assert "degraded" in states and sc.health()["state"] == "ready"
    sc.close()


def test_stall_watchdog_degraded_then_recovered(engine, payloads, tmp_path):
    """Source silent past the deadline -> degraded (with last_error and
    a transitions entry), seeded-backoff re-poll keeps the runner alive,
    late chunks recover it to ready — no wedged threads."""
    base = str(tmp_path)
    src = MemorySource([payloads[0]])  # live stream: not finished yet
    sc = _scorer(engine, src, base, stall_deadline_s=0.05)
    mid_state = {}

    def feeder():
        time.sleep(0.35)
        mid_state.update(sc.health())
        src.feed(payloads[1])
        src.finish()

    t = threading.Thread(target=feeder)
    t.start()
    summary = sc.run()
    t.join()
    assert summary["chunks_scored"] == 2
    assert mid_state["state"] == "degraded"
    assert mid_state["lag_s"] > 0.05
    assert mid_state["last_error"]["type"] == "StreamStallError"
    h = sc.health()
    assert h["state"] == "ready" and h["watermark"] == 2
    states = [x["state"] for x in h["transitions"]]
    assert states[-2:] == ["degraded", "ready"]
    assert sc.metrics.counters["stream.stalls"] >= 1
    assert sc.metrics.counters["stream.stall_recoveries"] >= 1
    left = [th.name for th in threading.enumerate()
            if th.name.startswith(("sparkdl-pipeline", "sparkdl-serving"))]
    assert not left, left
    sc.close()


def test_health_mirrors_server_contract(engine, payloads, tmp_path):
    """StreamScorer.health() carries every core key Server.health()
    does (live/state/last_error/transitions) with the same state
    vocabulary, plus the stream's watermark/lag surface."""
    base = str(tmp_path)
    sc = _scorer(engine, MemorySource(payloads[:1], finished=True), base)
    h = sc.health()
    for key in ("live", "state", "last_error", "transitions"):
        assert key in h
    assert h["state"] in ("ready", "degraded", "closed")
    assert h["transitions"][0]["state"] == "ready"
    assert {"watermark", "lag_s", "source_exhausted"} <= set(h)
    json.dumps(h)  # JSON-serializable like Server.health()
    sc.close()
    assert sc.health()["state"] == "closed"


def test_serving_sink_rides_online_queue(engine, payloads, tmp_path):
    from sparkdl_tpu.serving import Server

    base = str(tmp_path)
    variables = {"w": engine.variables["w"]}
    with Server(_fn, variables, max_batch_size=8, max_wait_ms=1.0) as srv:
        src = MemorySource(payloads[:2], finished=True)
        sc = StreamScorer(srv, src,
                          journal_path=os.path.join(base, "j.jsonl"),
                          out_dir=os.path.join(base, "out"))
        summary = sc.run()
        assert summary["chunks_scored"] == 2
        got = assemble_outputs(os.path.join(base, "j.jsonl"),
                               os.path.join(base, "out"))
        assert got.shape == (16, 4)
        ref = np.concatenate(
            [np.asarray(o) for o in engine.map_batches(payloads[:2],
                                                       pipeline=False)])
        np.testing.assert_allclose(got, ref, rtol=0, atol=1e-6)
        sc.close()


def test_stream_journal_cli_summary(engine, payloads, tmp_path, capsys):
    from tools.stream_journal import main, summarize

    base = str(tmp_path)
    src = MemorySource(payloads[:2], finished=True)
    sc = _scorer(engine, src, base)
    sc.run()
    sc.close()
    jp = os.path.join(base, "journal.jsonl")
    s = summarize(jp)
    assert s["committed"] == 2 and s["uncommitted"] == []
    assert s["resume_offset"] == 2
    assert main([jp]) == 0  # clean journal
    capsys.readouterr()
    j = Journal(jp)
    j.begin("cX", 2)
    j.close()
    assert main([jp, "--json"]) == 1  # pending replay
    out = json.loads(capsys.readouterr().out)
    assert out["uncommitted"][0]["chunk_id"] == "cX"


# -- headline chaos: SIGKILL between output write and commit ---------------

_CHILD = r"""
import json, os, signal, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from sparkdl_tpu import faults, streaming
from sparkdl_tpu.parallel.engine import InferenceEngine

base = sys.argv[1]

def _fn(variables, x):
    import jax.numpy as jnp
    return jnp.tanh(x @ variables["w"])

rng = np.random.default_rng(7)
variables = {"w": rng.normal(size=(6, 4)).astype(np.float32)}
eng = InferenceEngine(_fn, variables, device_batch_size=8)
src = streaming.DirectorySource(os.path.join(base, "in"))
sc = streaming.StreamScorer(
    eng, src, journal_path=os.path.join(base, "journal.jsonl"),
    out_dir=os.path.join(base, "out"), pipeline=False,
    stall_deadline_s=2.0)
try:
    summary = sc.run()
except faults.InjectedFatalError:
    # a REAL SIGKILL at the exact crash point the fault marks: no
    # finally blocks, no atexit, no flush — only what fsync already
    # made durable survives
    os.kill(os.getpid(), signal.SIGKILL)
print(json.dumps({"summary": summary, "health": sc.health()}))
"""


def test_sigkill_between_output_and_commit_exactly_once(engine, payloads,
                                                        oracle, tmp_path):
    """ISSUE 8 acceptance: sustained stream, SIGKILL the scoring
    process in the window between output-artifact write and journal
    commit, restart from the journal — final outputs are exactly-once
    (no gap, no duplicate), bit-identical to the batch oracle, and the
    lag/watermark metrics recover."""
    base = str(tmp_path)
    indir = os.path.join(base, "in")
    for i, p in enumerate(payloads):
        write_directory_chunk(indir, i, p)
    finish_directory_stream(indir)
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "SPARKDL_TRACE": "0",
                "SPARKDL_FAULTS": "stream.commit:error:exc=fatal,at=4"})
    r1 = subprocess.run([sys.executable, "-c", _CHILD, base], cwd=REPO,
                        env=env, capture_output=True, text=True,
                        timeout=180)
    assert r1.returncode == -9, (r1.returncode, r1.stderr[-2000:])
    # the kill landed in the window: offsets 0-2 committed, offset 3's
    # artifact durable but uncommitted
    j = Journal(os.path.join(base, "journal.jsonl"))
    assert j.resume_offset() == 3
    pending = j.uncommitted()
    assert any(r["offset"] == 3 and r["has_output"] for r in pending)
    j.close()
    env2 = dict(os.environ)
    env2.update({"JAX_PLATFORMS": "cpu", "SPARKDL_TRACE": "0"})
    env2.pop("SPARKDL_FAULTS", None)
    r2 = subprocess.run([sys.executable, "-c", _CHILD, base], cwd=REPO,
                        env=env2, capture_output=True, text=True,
                        timeout=180)
    assert r2.returncode == 0, r2.stderr[-2000:]
    rec = json.loads(r2.stdout.strip().splitlines()[-1])
    assert rec["summary"]["resume_offset"] == 3
    assert rec["summary"]["redeliveries"] >= 1
    assert rec["summary"]["committed_total"] == len(payloads)
    # lag recovered: the restarted run ends ready with a full watermark
    assert rec["health"]["state"] == "ready"
    assert rec["health"]["watermark"] == len(payloads)
    assert rec["health"]["lag_s"] == 0.0  # exhausted: lag cleared
    # exactly-once and bit-correct vs the batch oracle over the same
    # chunks (same seeded weights in the child, CPU-deterministic)
    got = _assemble(base)
    assert np.array_equal(got, oracle)
    recs, _ = read_jsonl(os.path.join(base, "journal.jsonl"))
    commits = [r["chunk_id"] for r in recs if r["rec"] == "commit"]
    assert len(commits) == len(set(commits)) == len(payloads)
    arts = [f for f in os.listdir(os.path.join(base, "out"))
            if f.endswith(".npy")]
    assert len(arts) == len(payloads)
