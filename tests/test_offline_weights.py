"""Offline pretrained-weight bundle + air-gapped class index (VERDICT
round 1, Missing #3): the zoo must load real weights from a local file with
no network, and topK decode must use a locally provided class index.
"""

import json
import os

import numpy as np
import pytest

from sparkdl_tpu.models import get_model_spec, load_model
from sparkdl_tpu.models import imagenet as imagenet_lib


@pytest.fixture(autouse=True)
def _reset_class_index():
    imagenet_lib.reset_class_index_cache()
    yield
    imagenet_lib.reset_class_index_cache()


def test_explicit_weights_path_must_exist():
    spec = get_model_spec("ResNet50")
    with pytest.raises(FileNotFoundError, match="does not exist"):
        spec.resolve_weights("/no/such/file.h5")


def test_weights_dir_resolution(tmp_path, monkeypatch):
    spec = get_model_spec("ResNet50")
    # no dir set -> passthrough
    monkeypatch.delenv("SPARKDL_WEIGHTS_DIR", raising=False)
    assert spec.resolve_weights("imagenet") == "imagenet"
    # dir set but empty -> passthrough
    monkeypatch.setenv("SPARKDL_WEIGHTS_DIR", str(tmp_path))
    assert spec.resolve_weights("imagenet") == "imagenet"
    # candidate file present -> picked up
    cand = tmp_path / "ResNet50.weights.h5"
    cand.write_bytes(b"")
    assert spec.resolve_weights("imagenet") == str(cand)
    assert spec.resolve_weights(None) is None


def test_load_model_from_local_weights_matches_keras_twin(tmp_path,
                                                          monkeypatch):
    """End-to-end: keras twin (random init, randomized BN) saves weights;
    load_model with SPARKDL_WEIGHTS_DIR set must produce the twin's exact
    predictions — proving the local file was loaded, not a fresh init."""
    import jax

    name = "ResNet50"
    spec = get_model_spec(name)
    twin = spec.keras_model(weights=None)
    # make BN stats non-trivial so a fresh random init can't accidentally agree
    rng = np.random.default_rng(3)
    for layer in twin.layers:
        if type(layer).__name__ == "BatchNormalization":
            ws = layer.get_weights()
            layer.set_weights([
                w + rng.normal(0, 0.05, size=w.shape).astype("float32")
                for w in ws])
    wpath = str(tmp_path / f"{name}.weights.h5")
    twin.save_weights(wpath)
    monkeypatch.setenv("SPARKDL_WEIGHTS_DIR", str(tmp_path))

    module, variables = load_model(name)  # default "imagenet" -> local file
    h, w = spec.input_size
    x = rng.normal(0, 1, size=(2, h, w, 3)).astype("float32")
    ref = np.asarray(twin.predict(x, verbose=0))
    got = np.asarray(jax.jit(
        lambda v, xb: module.apply(v, xb, train=False))(variables, x))
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=2e-3)


def test_class_index_from_env_file(tmp_path, monkeypatch):
    index = {str(i): [f"n{i:08d}", f"thing_{i}"] for i in range(10)}
    path = tmp_path / "imagenet_class_index.json"
    path.write_text(json.dumps(index))
    monkeypatch.setenv("SPARKDL_CLASS_INDEX", str(path))
    imagenet_lib.reset_class_index_cache()

    probs = np.zeros((1, 10), np.float32)
    probs[0, 3] = 0.9
    probs[0, 7] = 0.1
    decoded = imagenet_lib.decode_predictions(probs, top=2)
    assert decoded[0][0] == ("n00000003", "thing_3", pytest.approx(0.9))
    assert decoded[0][1][1] == "thing_7"


def test_class_index_from_weights_dir(tmp_path, monkeypatch):
    index = {"0": ["n0", "zero"], "1": ["n1", "one"]}
    (tmp_path / "imagenet_class_index.json").write_text(json.dumps(index))
    monkeypatch.delenv("SPARKDL_CLASS_INDEX", raising=False)
    monkeypatch.setenv("SPARKDL_WEIGHTS_DIR", str(tmp_path))
    imagenet_lib.reset_class_index_cache()
    decoded = imagenet_lib.decode_predictions(
        np.asarray([[0.2, 0.8]], np.float32), top=1)
    assert decoded[0][0][:2] == ("n1", "one")


def test_class_index_degrades_to_synthetic(monkeypatch, tmp_path):
    monkeypatch.delenv("SPARKDL_CLASS_INDEX", raising=False)
    monkeypatch.setenv("SPARKDL_WEIGHTS_DIR", str(tmp_path))  # empty dir
    monkeypatch.setenv("HOME", str(tmp_path))  # hide any keras cache
    imagenet_lib.reset_class_index_cache()
    decoded = imagenet_lib.decode_predictions(
        np.asarray([[0.2, 0.8]], np.float32), top=1)
    assert decoded[0][0][0] == "class_1"
