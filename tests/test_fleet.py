"""Fleet-serving tests (tier-1, CPU-only, 8-device virtual mesh).

Pins ISSUE 7's contracts for ``sparkdl_tpu.serving.fleet``:

* registry: monotonically numbered versions over ONE pinned fn per
  entry (the no-recompile precondition), weights-only re-registration;
* multi-model front door: results bitwise-match each model's own
  ``InferenceEngine`` oracle; futures carry model/version/tenant tags;
* zero-downtime hot-swap: canary → promote with ZERO failed in-flight
  requests and a per-bucket no-recompile report (shared jit object,
  executable cache unchanged) — plus the PROGRAMS.lock.json tie-in: the
  fleet's enumerable program set IS the committed zoo × bucket set, and
  v1/v2 builds produce the identical executable cache key/fingerprint;
* rollback with requests still in flight on the canary version;
* canary fractions 0.0 / 1.0 and the deterministic fraction counter;
* admission: zero-quota tenants, token-bucket rate + burst, in-flight
  caps, shed-lowest-priority-first under queue pressure;
* varz JSON contract for BOTH Server and Fleet (numpy scalars must not
  break ``json.dumps``);
* the headline chaos test: version rollout under sustained mixed-tenant
  load with injected ``fleet.swap``/``fleet.canary``/``fleet.admit``
  faults — zero failed in-flight requests, bit-correct outputs vs the
  per-version single-model oracles, quotas enforced exactly.
"""

import json
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu import faults
from sparkdl_tpu.faults import FaultPlan
from sparkdl_tpu.parallel.engine import InferenceEngine
from sparkdl_tpu.serving import (Fleet, QueueFullError, QuotaExceededError,
                                 ServerClosedError, ServiceUnavailableError,
                                 TenantQuota)
from sparkdl_tpu.serving.fleet import (PRIORITY_HIGH, PRIORITY_LOW,
                                       ModelRegistry)


@pytest.fixture(autouse=True)
def _isolated_plan():
    """Never leak a fault plan between tests."""
    from sparkdl_tpu.faults import plan as plan_mod

    prev = plan_mod._PLAN
    yield
    plan_mod._PLAN = prev


def _fn(variables, x):
    import jax.numpy as jnp

    return jnp.tanh(x @ variables["w"])


def _fn2(variables, x):
    import jax.numpy as jnp

    return jnp.sin(x @ variables["w"] + variables["b"])


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(17)
    w1 = {"w": rng.normal(size=(6, 4)).astype(np.float32)}
    w2 = {"w": rng.normal(size=(6, 4)).astype(np.float32)}
    wb = {"w": rng.normal(size=(6, 3)).astype(np.float32),
          "b": rng.normal(size=(3,)).astype(np.float32)}
    x = rng.normal(size=(48, 6)).astype(np.float32)
    return w1, w2, wb, x


def _oracle(fn, variables, x):
    eng = InferenceEngine(fn, variables, device_batch_size=8)
    return np.concatenate(
        [np.asarray(o) for o in eng.map_batches([x], pipeline=False)])


def _no_serving_threads(timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        left = [t.name for t in threading.enumerate()
                if t.name.startswith("sparkdl-serving")]
        if not left:
            return
        time.sleep(0.02)
    raise AssertionError(f"wedged serving threads: {left}")


# -- registry ---------------------------------------------------------------

def test_registry_versions_monotonic_and_fn_pinned(setup):
    w1, w2, _, _ = setup
    reg = ModelRegistry()
    v1 = reg.register("clf", _fn, w1)
    v2 = reg.register("clf", variables=w2)
    v3 = reg.register("clf")  # defaults to the entry's resolved weights
    assert [v1.version, v2.version, v3.version] == [1, 2, 3]
    assert reg.versions("clf") == [1, 2, 3]
    assert reg.get("clf").version == 3          # latest
    assert reg.get("clf", 2).variables is w2
    assert v3.variables is w1                   # entry default
    # ONE fn object per entry — the no-recompile precondition
    entry = reg.entry("clf")
    assert entry.fn is _fn
    with pytest.raises(ValueError, match="WEIGHTS only"):
        reg.register("clf", _fn2)
    with pytest.raises(ValueError, match="first register"):
        reg.register("brand-new")
    with pytest.raises(KeyError, match="no version 9"):
        reg.get("clf", 9)
    with pytest.raises(KeyError, match="unknown model entry"):
        reg.entry("nope")


# -- multi-model front door -------------------------------------------------

def test_multi_model_results_match_engine_oracles(setup):
    w1, _, wb, x = setup
    ref_a = _oracle(_fn, w1, x[:8])
    ref_b = _oracle(_fn2, wb, x[:8])
    with Fleet(max_batch_size=8, max_wait_ms=2, bucket_sizes=[8]) as fleet:
        fleet.add_model("a", _fn, w1)
        fleet.add_model("b", _fn2, wb)
        futs_a = [fleet.submit("a", x[i], tenant="t1") for i in range(8)]
        futs_b = [fleet.submit("b", x[i], tenant="t2") for i in range(8)]
        got_a = np.stack([np.asarray(f.result(timeout=60)) for f in futs_a])
        got_b = np.stack([np.asarray(f.result(timeout=60)) for f in futs_b])
        assert all(f.fleet_model == "a" and f.fleet_version == 1
                   and f.fleet_tenant == "t1" and not f.fleet_canary
                   for f in futs_a)
        with pytest.raises(KeyError, match="not deployed"):
            fleet.submit("nope", x[0])
        with pytest.raises(ValueError, match="already deployed"):
            fleet.add_model("a", _fn, w1)
    np.testing.assert_array_equal(got_a, ref_a)
    np.testing.assert_array_equal(got_b, ref_b)
    _no_serving_threads()


# -- hot swap ---------------------------------------------------------------

def test_hot_swap_zero_downtime_and_no_recompile(setup):
    w1, w2, _, x = setup
    ref_v1 = _oracle(_fn, w1, x)
    ref_v2 = _oracle(_fn, w2, x)
    with Fleet(max_batch_size=8, max_wait_ms=2, bucket_sizes=[8]) as fleet:
        fleet.add_model("m", _fn, w1, warm_example=x[0])
        for i in range(4):  # stable traffic compiles/warms v1
            np.testing.assert_array_equal(
                np.asarray(fleet.predict("m", x[i])), ref_v1[i])
        fleet.add_version("m", w2, label="retrained")
        ro = fleet.start_rollout("m", canary_fraction=0.5,
                                 warm_example=x[0])
        futs = [fleet.submit("m", x[i]) for i in range(8)]
        rows = [np.asarray(f.result(timeout=60)) for f in futs]
        # deterministic fraction: every 2nd request rode the canary
        assert [f.fleet_canary for f in futs] == [False, True] * 4
        for f, row, i in zip(futs, rows, range(8)):
            np.testing.assert_array_equal(
                row, ref_v2[i] if f.fleet_version == 2 else ref_v1[i])
        report = fleet.promote("m")
        assert report["phase"] == "promoted"
        assert report["no_recompile"] is True
        assert all(b["shared_jit"] for b in report["buckets"].values())
        assert fleet.deployed_version("m") == 2
        assert fleet.swap_report("m") == report
        # post-swap traffic serves v2, bit-correct
        f = fleet.submit("m", x[9])
        np.testing.assert_array_equal(np.asarray(f.result(timeout=60)),
                                      ref_v2[9])
        assert f.fleet_version == 2 and not f.fleet_canary
        with pytest.raises(RuntimeError, match="no rollout"):
            fleet.promote("m")
        assert ro.phase == "promoted"
    _no_serving_threads()


def test_canary_fraction_zero_and_one(setup):
    w1, w2, _, x = setup
    with Fleet(max_batch_size=8, max_wait_ms=2, bucket_sizes=[8]) as fleet:
        fleet.add_model("m", _fn, w1)
        fleet.add_version("m", w2)
        with pytest.raises(ValueError, match="fraction"):
            fleet.start_rollout("m", canary_fraction=1.5)
        ro = fleet.start_rollout("m", canary_fraction=0.0)
        futs = [fleet.submit("m", x[i]) for i in range(6)]
        for f in futs:
            f.result(timeout=60)
        assert all(not f.fleet_canary for f in futs)
        assert ro.status()["canary_requests"] == 0
        ro.set_fraction(1.0)  # dark-launch: everything rides the canary
        futs = [fleet.submit("m", x[i]) for i in range(6)]
        for f in futs:
            f.result(timeout=60)
        assert all(f.fleet_canary and f.fleet_version == 2 for f in futs)
        fleet.rollback("m")
        assert fleet.deployed_version("m") == 1
        # a second rollout of the SAME registered version still works
        ro2 = fleet.start_rollout("m", canary_fraction=1.0)
        assert ro2.canary_version == 2
        fleet.promote("m")
        assert fleet.deployed_version("m") == 2
    _no_serving_threads()


def test_rollback_completes_inflight_on_canary_version(setup):
    w1, w2, _, x = setup
    ref_v1 = _oracle(_fn, w1, x)
    ref_v2 = _oracle(_fn, w2, x)
    # wait window much longer than the test: in-flight requests are still
    # QUEUED on the canary when rollback fires — the drain must serve
    # them on the version that admitted them (v2), not fail them
    with Fleet(max_batch_size=8, max_wait_ms=2_000,
               bucket_sizes=[8]) as fleet:
        fleet.add_model("m", _fn, w1)
        fleet.add_version("m", w2)
        fleet.start_rollout("m", canary_fraction=1.0, warm_example=x[0])
        inflight = [fleet.submit("m", x[i]) for i in range(4)]
        assert all(f.fleet_version == 2 for f in inflight)
        report = fleet.rollback("m")  # drains the canary server
        assert report["phase"] == "rolled_back"
        for i, f in enumerate(inflight):
            np.testing.assert_array_equal(
                np.asarray(f.result(timeout=60)), ref_v2[i])
        # stable never stopped serving; new traffic is v1 again (settled
        # by the context-exit drain — the 2s wait window never flushes)
        f = fleet.submit("m", x[5])
        assert f.fleet_version == 1
        with pytest.raises(ValueError, match="already serving"):
            fleet.start_rollout("m", version=1)
    np.testing.assert_array_equal(np.asarray(f.result(timeout=60)),
                                  ref_v1[5])
    _no_serving_threads()


def test_swap_report_allows_first_compile_of_new_bucket():
    """The shared jit's executable counter is GLOBAL: a bucket compiled
    for the first time mid-rollout may grow it by one without failing
    the no-recompile proof; growth beyond the new buckets means a
    same-shape re-jit and must fail it."""
    from sparkdl_tpu.serving.fleet.rollout import Rollout

    class _Srv:
        def __init__(self, state):
            self._state = state

        def executable_state(self):
            return {b: dict(v) for b, v in self._state.items()}

    jid = 0xBEEF
    before = {8: {"jit_id": jid, "executables": 1}}
    now = {8: {"jit_id": jid, "executables": 2},
           16: {"jit_id": jid, "executables": 2}}
    ro = Rollout("m", 1, _Srv(before), 2, _Srv(now), 0.5,
                 exec_before=before)
    rep = ro.report()
    assert rep["no_recompile"] is True  # growth == one new bucket
    assert rep["buckets"][8]["shared_jit"] is True
    now[8]["executables"] = now[16]["executables"] = 3
    assert ro.report()["no_recompile"] is False  # same-shape re-jit
    now[8]["executables"] = now[16]["executables"] = 2
    now[8]["jit_id"] = jid + 1  # forked jit object: never shared
    assert ro.report()["no_recompile"] is False


# -- admission --------------------------------------------------------------

def test_admission_refund_returns_token_and_slot():
    """The swap-window re-route must not charge a tenant twice:
    refund() frees the slot, returns the rate token, and backs out the
    admitted count."""
    from sparkdl_tpu.serving.fleet import AdmissionController

    ac = AdmissionController(
        quotas={"t": TenantQuota(rate_per_s=1e-6, burst=1,
                                 max_inflight=4)})
    ac.admit("t")
    with pytest.raises(QuotaExceededError):  # bucket empty, no refill
        ac.admit("t")
    ac.refund("t")
    ac.admit("t")  # the refunded token admits the retry
    snap = ac.snapshot()["tenants"]["t"]
    assert snap["admitted"] == 1  # the refunded admit was backed out
    assert snap["inflight"] == 1
    assert snap["shed"] == 1


def test_cap_rejection_costs_no_token_and_zero_quota_burst():
    """A capped-out rejection must not also burn rate quota, and
    rate_per_s=0.0 stays deny-by-config even with an explicit burst."""
    from sparkdl_tpu.serving.fleet import AdmissionController

    ac = AdmissionController(
        quotas={"t": TenantQuota(rate_per_s=1e-6, burst=2,
                                 max_inflight=1)})
    ac.admit("t")  # one token spent, slot 1/1
    with pytest.raises(QuotaExceededError, match="in-flight cap"):
        ac.admit("t")
    ac.release("t")
    ac.admit("t")  # the cap rejection burned no token: one remained
    assert TenantQuota(rate_per_s=0.0, burst=100).effective_burst() == 0.0


def test_add_model_failure_leaves_no_thread_and_name_reusable(setup):
    """A failed deploy (warmup blows up) must leave nothing behind: no
    live dispatcher thread and no catalog entry poisoning the name."""
    w1, _, _, x = setup
    with Fleet(max_batch_size=8, max_wait_ms=2, bucket_sizes=[8]) as fleet:
        with pytest.raises(Exception):
            fleet.add_model("m", _fn, w1,
                            warm_example=np.zeros((3, 3), np.float32))
        _no_serving_threads()
        assert "m" not in fleet.registry
        fleet.add_model("m", _fn, w1, warm_example=x[0])  # name reusable
        np.asarray(fleet.predict("m", x[0]))
    _no_serving_threads()

def test_zero_quota_tenant_always_shed(setup):
    w1, _, _, x = setup
    with Fleet(max_batch_size=8, max_wait_ms=2, bucket_sizes=[8],
               quotas={"banned": TenantQuota(rate_per_s=0.0)}) as fleet:
        fleet.add_model("m", _fn, w1)
        for _ in range(3):
            with pytest.raises(QuotaExceededError, match="zero quota") as ei:
                fleet.submit("m", x[0], tenant="banned")
            assert ei.value.retry_after_s > 0
            assert ei.value.tenant == "banned"
        # other tenants are untouched
        np.asarray(fleet.predict("m", x[0], tenant="ok"))
        snap = fleet.admission.snapshot()
        assert snap["tenants"]["banned"]["shed"] == 3
        assert snap["tenants"]["banned"]["admitted"] == 0


def test_rate_quota_token_bucket(setup):
    w1, _, _, x = setup
    with Fleet(max_batch_size=8, max_wait_ms=2, bucket_sizes=[8],
               quotas={"m1": TenantQuota(rate_per_s=200.0, burst=2)}
               ) as fleet:
        fleet.add_model("m", _fn, w1)
        a = fleet.submit("m", x[0], tenant="m1")
        b = fleet.submit("m", x[1], tenant="m1")
        with pytest.raises(QuotaExceededError, match="rate quota") as ei:
            fleet.submit("m", x[2], tenant="m1")
        assert 0 < ei.value.retry_after_s <= 60.0
        a.result(timeout=60), b.result(timeout=60)
        time.sleep(0.1)  # 200/s refills a token in 5ms
        c = fleet.submit("m", x[3], tenant="m1")
        np.asarray(c.result(timeout=60))


def test_inflight_cap_released_on_settle(setup):
    w1, _, _, x = setup
    fleet = Fleet(max_batch_size=64, max_wait_ms=10_000, bucket_sizes=[64],
                  quotas={"cap": TenantQuota(max_inflight=2)})
    try:
        fleet.add_model("m", _fn, w1)
        futs = [fleet.submit("m", x[i], tenant="cap") for i in range(2)]
        with pytest.raises(QuotaExceededError, match="in-flight cap"):
            fleet.submit("m", x[2], tenant="cap")
        assert fleet.admission.inflight("cap") == 2
        fleet.close(drain=True)  # settles the queued requests
        for f in futs:
            np.asarray(f.result(timeout=60))
        assert fleet.admission.inflight("cap") == 0
    finally:
        fleet.close()
    _no_serving_threads()


def test_priority_shed_lowest_first_under_queue_pressure(setup):
    w1, _, _, x = setup
    # nothing flushes (batch never fills, wait is 10s): the queue IS the
    # pressure signal.  max_queue=10 -> low sheds at depth >= 5 (0.5),
    # normal at >= 8 (0.8), high boards until the server itself is full.
    fleet = Fleet(max_batch_size=64, max_wait_ms=10_000, bucket_sizes=[64],
                  max_queue=10,
                  quotas={"gold": TenantQuota(priority=PRIORITY_HIGH),
                          "scraper": TenantQuota(priority=PRIORITY_LOW)})
    try:
        fleet.add_model("m", _fn, w1)
        futs = [fleet.submit("m", x[i], tenant="gold") for i in range(5)]
        # depth 5/10: the low-priority tenant is shed FIRST...
        with pytest.raises(ServiceUnavailableError, match="queue pressure"):
            fleet.submit("m", x[0], tenant="scraper")
        # ...while normal-priority tenants still board (0.5 <= p < 0.8)
        futs += [fleet.submit("m", x[5 + i], tenant="norm")
                 for i in range(3)]
        with pytest.raises(ServiceUnavailableError, match="queue pressure"):
            fleet.submit("m", x[0], tenant="norm")  # depth 8/10
        # high priority boards to the brim, then hits the server's own
        # backpressure (QueueFullError with retry_after) — the fleet
        # gate never outranks the queue bound
        futs += [fleet.submit("m", x[8 + i], tenant="gold")
                 for i in range(2)]
        with pytest.raises(QueueFullError) as ei:
            fleet.submit("m", x[0], tenant="gold")
        assert not isinstance(ei.value, QuotaExceededError)
        assert ei.value.retry_after_s > 0
        fleet.close(drain=True)  # everyone admitted gets served
        for f in futs:
            np.asarray(f.result(timeout=60))
    finally:
        fleet.close()
    _no_serving_threads()


# -- varz JSON contract -----------------------------------------------------

def test_fleet_and_server_varz_json_with_numpy_scalars(setup):
    w1, w2, _, x = setup
    with Fleet(max_batch_size=8, max_wait_ms=2, bucket_sizes=[8]) as fleet:
        fleet.add_model("m", _fn, w1)
        np.asarray(fleet.predict("m", x[0], tenant="t"))
        fleet.add_version("m", w2)
        fleet.start_rollout("m", canary_fraction=1.0)
        np.asarray(fleet.predict("m", x[1]))
        fleet.promote("m")
        # numpy scalars must be coerced at the recorder, not trusted to
        # stay out: the docstring promises json.dumps(varz()) IS the
        # monitoring endpoint body
        fleet.metrics.incr("fleet.numpy_counter", np.float32(1.5))
        fleet.metrics.gauge("fleet.numpy_gauge", np.int64(3))
        fleet.metrics.record_time("fleet.numpy_time", np.float64(0.01))
        fleet.metrics.observe("fleet.numpy_obs", np.float32(0.25))
        v = fleet.varz()
        body = json.loads(json.dumps(v))
    assert body["fleet"]["models"]["m"]["version"] == 2
    assert body["fleet"]["models"]["m"]["last_swap"]["no_recompile"] is True
    assert body["fleet"]["registry"]["m"]["versions"] == [1, 2]
    assert body["tenants"]["t"]["completed"] == 1
    assert body["admission"]["tenants"]["t"]["admitted"] == 1
    assert body["counters"]["fleet.swaps"] == 1
    assert body["health"]["state"] == "ready"
    assert body["metrics"]["counters"]["fleet.numpy_counter"] == 1.5


def test_server_varz_json_with_numpy_scalars(setup):
    from sparkdl_tpu.serving import Server

    w1, _, _, x = setup
    with Server(_fn, w1, max_batch_size=8, max_wait_ms=2,
                bucket_sizes=[8]) as srv:
        np.asarray(srv.predict(x[0]))
        srv.metrics.incr("serving.numpy_counter", np.float32(2.5))
        srv.metrics.gauge("serving.numpy_gauge", np.int64(7))
        srv.metrics.record_time("serving.numpy_time", np.float64(0.02))
        body = json.loads(json.dumps(srv.varz()))
    assert body["counters"]["serving.numpy_counter"] == 2.5
    assert body["metrics"]["gauges"]["serving.numpy_gauge"] == 7.0


# -- program audit tie-in ---------------------------------------------------

def test_fleet_sites_registered():
    from sparkdl_tpu.faults.sites import SITES, validate_site

    for site in ("fleet.admit", "fleet.canary", "fleet.swap"):
        assert validate_site(site) == site
        assert site in SITES


def test_fleet_program_set_is_the_committed_zoo_set():
    """The fleet enumeration hook adds NO programs: its set is exactly
    the zoo × bucket plan already in PROGRAMS.lock.json, and building
    the SAME spec twice (a v1 and a v2 of a fleet entry, worst case:
    fresh fn objects) yields the identical executable cache key and
    StableHLO fingerprint — the committed-lockfile form of the
    no-recompile hot-swap guarantee."""
    from sparkdl_tpu.analysis.program import (DEFAULT_LOCKFILE,
                                              audit_program,
                                              fleet_dispatch_specs,
                                              read_lockfile)
    from sparkdl_tpu.analysis.program.inventory import zoo_dispatch_specs

    fleet_specs = fleet_dispatch_specs(models=["MobileNetV2"],
                                       max_batch_size=8)
    zoo_specs = zoo_dispatch_specs(models=["MobileNetV2"], max_batch_size=8)
    assert [s.name for s in fleet_specs] == [s.name for s in zoo_specs]
    committed = read_lockfile(DEFAULT_LOCKFILE)["programs"]
    assert {s.name for s in fleet_specs} <= set(committed)
    spec_v1 = fleet_specs[0]  # featurize b8 — cheapest zoo lowering
    spec_v2 = fleet_dispatch_specs(models=["MobileNetV2"],
                                   max_batch_size=8)[0]
    rec1 = audit_program(spec_v1)["record"]
    rec2 = audit_program(spec_v2)["record"]
    base = committed[spec_v1.name]
    assert (rec1["in_avals"]["key"] == rec2["in_avals"]["key"]
            == base["in_avals"]["key"])
    assert (rec1["fingerprint"] == rec2["fingerprint"]
            == base["fingerprint"])


# -- the headline chaos test ------------------------------------------------

def test_chaos_rollout_under_mixed_tenant_load(setup):
    """ISSUE 7 acceptance: roll a model version under sustained
    mixed-tenant load with injected swap-time faults.  Zero failed
    in-flight requests (every admitted future resolves), bit-correct
    outputs vs the per-version single-model oracles, quotas enforced
    exactly, and the first promote attempt dying on the injected
    ``fleet.swap`` fault leaves both versions serving (retry wins)."""
    w1, w2, _, x = setup
    ref = {1: _oracle(_fn, w1, x), 2: _oracle(_fn, w2, x)}
    plan = FaultPlan.parse(
        "seed=11;"
        "fleet.swap:error:exc=transient,at=1,times=1;"
        "fleet.canary:sleep:ms=1,every=7;"
        "fleet.admit:error:exc=queue_full,at=40,times=1,retry_after=0.02")

    settled = []          # (future, row_index) for every ADMITTED request
    sheds = {"quota": 0, "storm": 0}
    shed_lock = threading.Lock()

    with faults.active(plan):
        with Fleet(max_batch_size=8, max_wait_ms=2, bucket_sizes=[8],
                   quotas={"metered": TenantQuota(rate_per_s=1e-4,
                                                  burst=5)}) as fleet:
            fleet.add_model("m", _fn, w1, warm_example=x[0])
            fleet.add_version("m", w2)

            def client(tenant, n_requests):
                for k in range(n_requests):
                    i = k % len(x)
                    try:
                        fut = fleet.submit("m", x[i], tenant=tenant)
                    except QuotaExceededError:
                        with shed_lock:
                            sheds["quota"] += 1
                    except QueueFullError as e:  # the injected storm
                        assert e.retry_after_s > 0
                        with shed_lock:
                            sheds["storm"] += 1
                    else:
                        with shed_lock:
                            settled.append((fut, i))
                    time.sleep(0.002)

            threads = [threading.Thread(target=client, args=(t, 30))
                       for t in ("gold", "silver", "metered")]
            for t in threads:
                t.start()
            time.sleep(0.03)  # load is flowing; start the rollout
            fleet.start_rollout("m", canary_fraction=0.5,
                                warm_example=x[0])
            time.sleep(0.03)
            # the injected fleet.swap fault kills the FIRST promote
            # attempt with state unchanged — both versions keep serving
            with pytest.raises(faults.InjectedTransientError):
                fleet.promote("m")
            assert fleet.deployed_version("m") == 1
            time.sleep(0.02)
            report = fleet.promote("m")  # retry wins mid-load
            assert report["no_recompile"] is True
            for t in threads:
                t.join()
            # zero failed in-flight requests: every admitted future
            # resolves, and every row is bit-correct for the version
            # that served it
            assert settled, "no requests were admitted"
            for fut, i in settled:
                row = np.asarray(fut.result(timeout=60))
                np.testing.assert_array_equal(row, ref[fut.fleet_version][i])
            versions = {fut.fleet_version for fut, _ in settled}
            assert versions == {1, 2}  # load really spanned the swap
            # quotas enforced exactly: burst 5, negligible refill -> the
            # metered tenant lands exactly 5 of its 30 submissions
            # (minus the one storm reject if it drew it)
            snap = fleet.admission.snapshot()
            assert snap["tenants"]["metered"]["admitted"] <= 5
            assert (snap["tenants"]["metered"]["admitted"]
                    + snap["tenants"]["metered"]["shed"]
                    + (1 if sheds["storm"] else 0) >= 30)
            assert sheds["quota"] >= 24
            assert sheds["storm"] == 1  # the injected admission storm
            assert fleet.deployed_version("m") == 2
            h = fleet.health()
            assert h["state"] == "ready"
            json.dumps(fleet.varz())
    stats = plan.stats()
    assert stats["fleet.swap"]["fired"] == 1       # killed promote #1 only
    assert stats["fleet.admit"]["fired"] == 1      # the storm
    assert stats["fleet.canary"]["fired"] >= 1     # routing stalls ran
    _no_serving_threads()
