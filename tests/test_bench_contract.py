"""bench.py output-contract tests (hardware-free).

The driver parses bench stdout line by line and keeps the FINAL line as
the tracked metric, so the JSON-line contract — self-describing
denominators, the two-sided baseline fields, and the explicit
dead-relay diagnostics — is product surface and gets pinned here; the
actual throughput numbers need the chip and are the driver's job.
"""

import json
import subprocess

import pytest

import bench


@pytest.fixture()
def captured(monkeypatch, tmp_path):
    from sparkdl_tpu.utils.jsonl import CrashSafeJsonlWriter

    lines = []
    monkeypatch.setattr(bench, "_print_line",
                        lambda s: lines.append(json.loads(s)))
    monkeypatch.setattr(bench, "_LINES", {})
    # in-process main() calls reset() on the crash-safe artifact rider:
    # point it at a scratch path so contract tests never truncate the
    # repo's real artifacts/bench_lines.jsonl forensics record
    monkeypatch.setattr(bench, "_ARTIFACT",
                        CrashSafeJsonlWriter(str(tmp_path / "lines.jsonl")))
    return lines


def test_emit_two_sided_baseline_fields(captured):
    """FLOP-scaled lines carry BOTH vs_baseline (per-model denominator)
    and vs_sourced_anchor (value / the single sourced 875) so the
    denominator-method sensitivity is visible in the JSON itself
    (VERDICT r4 #4)."""
    bench.emit("2-Xception", "m", 3184.0, "images/sec/chip",
               baseline_model="Xception")
    rec = captured[-1]
    assert rec["vs_baseline"] == pytest.approx(3184 / 573, rel=0.01)
    assert rec["vs_sourced_anchor"] == pytest.approx(3184 / 875, rel=0.01)
    # the sourced anchor itself carries only vs_baseline (same number)
    bench.emit("1", "m", 6500.0, "images/sec/chip",
               baseline_model="InceptionV3")
    rec = captured[-1]
    assert rec["vs_baseline"] == pytest.approx(6500 / 875, rel=0.01)
    assert "vs_sourced_anchor" not in rec


def test_denominators_cover_reference_zoo():
    """Every reference SUPPORTED_MODELS member has a defensible
    denominator; beyond-reference models report null."""
    for name in ("InceptionV3", "ResNet50", "VGG16", "VGG19", "Xception"):
        ips, basis = bench.v100_baseline(name)
        assert ips and basis, name
    for name in ("MobileNetV2", "EfficientNetB0", "ResNet101", "ResNet152"):
        assert bench.v100_baseline(name) == (None, None), name


def test_dead_relay_emits_skip_lines(captured, monkeypatch):
    """A dead relay must produce explicit diagnostic lines, not a silent
    hang inside uninterruptible native transfer calls."""
    def dead_probe(timeout_s=240):
        raise subprocess.TimeoutExpired(cmd="probe", timeout=timeout_s)

    monkeypatch.setattr(bench, "measure_relay_profile", dead_probe)
    monkeypatch.setenv("SPARKDL_BENCH_CONFIGS", "1,3")
    monkeypatch.setattr(bench, "RELAY", {})
    bench.main()
    assert captured[0]["config"] == "relay"
    assert "unreachable" in captured[0]["error"]
    assert [r["config"] for r in captured[1:]] == ["1", "3"]
    assert all("skipped" in r["error"] for r in captured[1:])


def test_retry_nontimeout_failure_does_not_skip_configs(captured,
                                                        monkeypatch):
    """A transient first-probe timeout followed by a fast non-timeout
    retry failure means the device answered: diagnostics only, configs
    still run (the first-attempt 'profile failure must not block the
    bench' policy)."""
    calls = {"n": 0}

    def probe(timeout_s=240):
        calls["n"] += 1
        if calls["n"] == 1:
            raise subprocess.TimeoutExpired(cmd="p", timeout=timeout_s)
        raise RuntimeError("fast rc=1 failure")

    monkeypatch.setattr(bench, "measure_relay_profile", probe)
    monkeypatch.setattr(bench, "RELAY", {})
    ran = []
    monkeypatch.setitem(bench.BENCHES, "1", lambda: ran.append("1"))
    monkeypatch.setenv("SPARKDL_BENCH_CONFIGS", "1")
    bench.main()
    assert ran == ["1"]                       # attempted, not skipped
    assert "RuntimeError" in captured[0]["error"]
    assert not any("skipped" in (r.get("error") or "") for r in captured)


def test_emit_extra_fields_merge_without_touching_core_keys(captured):
    """The serving line carries p50/p99 next to the core contract keys;
    ``extra`` must merge, never shadow, the core fields."""
    bench.emit("serving", "m", 1234.5, "images/sec",
               extra={"p50_ms": 4.2, "p99_ms": 9.9, "num_requests": 64})
    rec = captured[-1]
    assert rec["value"] == 1234.5 and rec["unit"] == "images/sec"
    assert rec["p50_ms"] == 4.2 and rec["p99_ms"] == 9.9
    assert rec["vs_baseline"] is None and rec["baseline"] is None
    # a colliding key is a loud error, never a silent overwrite
    with pytest.raises(ValueError, match="collides"):
        bench.emit("serving", "m", 1.0, "images/sec",
                   extra={"value": 2.0})


def test_serving_config_runs_on_cpu_fallback_when_relay_dead(captured,
                                                             monkeypatch):
    """Dead relay: every device config is skipped, but 'serving' still
    runs end-to-end pinned to host CPU and its JSON line parses under the
    contract with the latency fields present — the serving config can
    never silently emit malformed JSON."""
    def dead_probe(timeout_s=240):
        raise subprocess.TimeoutExpired(cmd="probe", timeout=timeout_s)

    monkeypatch.setattr(bench, "measure_relay_profile", dead_probe)
    monkeypatch.setattr(bench, "RELAY", {})
    monkeypatch.setenv("SPARKDL_BENCH_CONFIGS", "1,serving")
    monkeypatch.setenv("SPARKDL_BENCH_SERVING_REQUESTS", "32")
    bench.main()
    by_config = {}
    for r in captured:
        by_config.setdefault(r["config"], r)
    assert "unreachable" in by_config["relay"]["error"]
    assert "skipped" in by_config["1"]["error"]
    rec = by_config["serving"]
    assert "error" not in rec, rec
    assert rec["unit"] == "images/sec" and rec["value"] > 0
    assert rec["p50_ms"] > 0 and rec["p99_ms"] >= rec["p50_ms"]
    assert rec["num_requests"] == 32
    assert "cpu-fallback" in rec["env_bound"]
    # contract keys stay intact on the serving line
    for key in ("config", "metric", "value", "unit", "vs_baseline",
                "baseline", "env_bound"):
        assert key in rec


def test_midsession_relay_recovery_salvages_later_configs(captured,
                                                          monkeypatch,
                                                          tmp_path):
    """A dead start-of-run probe must not blank the whole run: the relay
    is RE-PROBED before each device config, so a mid-session recovery
    runs everything that remains (and refreshes the last-good cache)."""
    calls = {"n": 0}

    def probe(timeout_s=240):
        calls["n"] += 1
        if calls["n"] <= 2:  # start-of-run probe + its long retry
            raise subprocess.TimeoutExpired(cmd="p", timeout=timeout_s)
        return {"dispatch_ms": 100.0, "h2d_MBps": 50.0, "d2h_MBps": 5.0}

    monkeypatch.setattr(bench, "measure_relay_profile", probe)
    monkeypatch.setattr(bench, "RELAY", {})
    monkeypatch.setattr(bench, "RELAY_CACHE_PATH",
                        str(tmp_path / "lg.json"))
    ran = []
    monkeypatch.setitem(bench.BENCHES, "1", lambda: ran.append("1"))
    monkeypatch.setitem(bench.BENCHES, "3", lambda: ran.append("3"))
    monkeypatch.setenv("SPARKDL_BENCH_CONFIGS", "1,3")
    bench.main()
    assert ran == ["1", "3"]  # both salvaged by the pre-config re-probe
    relay_lines = [r for r in captured if r["config"] == "relay"]
    assert any(r.get("recovered") for r in relay_lines)
    assert not any("skipped" in (r.get("error") or "") for r in captured)
    cached = json.loads((tmp_path / "lg.json").read_text())
    assert cached["dispatch_ms"] == 100.0 and cached["ts"]


def test_dead_relay_error_records_carry_last_good_profile(captured,
                                                          monkeypatch,
                                                          tmp_path):
    """When every probe fails, the relay line AND each skip line carry
    the last SUCCESSFUL probe's numbers with their staleness timestamp —
    a dead-relay BENCH_r*.json stays interpretable on its own."""
    cache = tmp_path / "lg.json"
    cache.write_text(json.dumps({
        "dispatch_ms": 108.5, "h2d_MBps": 34.0, "d2h_MBps": 4.1,
        "ts": "2026-07-30T00:00:00+0000"}))
    monkeypatch.setattr(bench, "RELAY_CACHE_PATH", str(cache))

    def dead(timeout_s=240):
        raise subprocess.TimeoutExpired(cmd="p", timeout=timeout_s)

    monkeypatch.setattr(bench, "measure_relay_profile", dead)
    monkeypatch.setattr(bench, "RELAY", {})
    monkeypatch.setenv("SPARKDL_BENCH_CONFIGS", "1,3")
    bench.main()
    by_config = {}
    for r in captured:
        by_config.setdefault(r["config"], r)
    for cfg in ("relay", "1", "3"):
        lg = by_config[cfg]["last_good_relay"]
        assert lg["dispatch_ms"] == 108.5
        assert lg["ts"] == "2026-07-30T00:00:00+0000"  # staleness visible


def test_successful_probe_writes_last_good_cache(captured, monkeypatch,
                                                 tmp_path):
    cache = tmp_path / "lg.json"
    monkeypatch.setattr(bench, "RELAY_CACHE_PATH", str(cache))
    monkeypatch.setattr(
        bench, "measure_relay_profile",
        lambda timeout_s=240: {"dispatch_ms": 1.0, "h2d_MBps": 2.0,
                               "d2h_MBps": 3.0})
    monkeypatch.setattr(bench, "RELAY", {})
    monkeypatch.setenv("SPARKDL_BENCH_CONFIGS", "none-such")
    bench.main()
    rec = json.loads(cache.read_text())
    assert rec["dispatch_ms"] == 1.0 and rec["ts"]


def test_dead_relay_runs_chipless_first_and_bounds_reprobes(captured,
                                                            monkeypatch):
    """Fully dead relay, full default config list: the chip-independent
    configs run FIRST (guaranteed lines before any re-probe wait) and
    the mid-run re-probe budget caps the added wait — after MAX_REPROBES
    consecutive failures the remaining device configs skip instantly."""
    probes = {"n": 0}

    def dead(timeout_s=240):
        probes["n"] += 1
        raise subprocess.TimeoutExpired(cmd="p", timeout=timeout_s)

    monkeypatch.setattr(bench, "measure_relay_profile", dead)
    monkeypatch.setattr(bench, "RELAY", {})
    order = []
    monkeypatch.setitem(bench.BENCHES, "serving",
                        lambda: order.append("serving"))
    monkeypatch.setitem(bench.BENCHES, "pipeline",
                        lambda: order.append("pipeline"))
    monkeypatch.setenv("SPARKDL_BENCH_CONFIGS",
                       "1,1e2e,2,3,4,5,serving,pipeline")
    bench.main()
    assert order == ["serving", "pipeline"]  # chipless salvaged up front
    skips = [r for r in captured if "skipped" in (r.get("error") or "")]
    assert len(skips) == 6                   # every device config skipped
    assert probes["n"] == 2 + bench.MAX_REPROBES  # start pair + budget
    assert sum("budget" in r["error"] for r in skips) == 6 - bench.MAX_REPROBES


def test_pipeline_config_is_chipless_and_runs_when_relay_dead(captured,
                                                              monkeypatch):
    """Like 'serving', the synthetic-device 'pipeline' config measures a
    chip-independent layer and must run (not skip) on a dead relay."""
    def dead(timeout_s=240):
        raise subprocess.TimeoutExpired(cmd="p", timeout=timeout_s)

    monkeypatch.setattr(bench, "measure_relay_profile", dead)
    monkeypatch.setattr(bench, "RELAY", {})
    ran = []
    monkeypatch.setitem(bench.BENCHES, "pipeline",
                        lambda: ran.append("pipeline"))
    monkeypatch.setenv("SPARKDL_BENCH_CONFIGS", "1,pipeline")
    bench.main()
    assert ran == ["pipeline"]
    by_config = {}
    for r in captured:
        by_config.setdefault(r["config"], r)
    assert "skipped" in by_config["1"]["error"]
    assert "pipeline" not in by_config or "error" not in by_config.get(
        "pipeline", {})


@pytest.mark.slow
def test_pipeline_bench_line_contract(captured):
    """The real synthetic-device child emits a line with the overlap
    speedup and the per-stage stall ledger under the core contract keys
    (slow: spawns a python child that imports jax + runs ~2.5s of
    sleep-clocked batches)."""
    bench.bench_pipeline()
    rec = captured[-1]
    assert rec["config"] == "pipeline"
    assert rec["unit"] == "x vs serial path"
    assert rec["value"] >= 1.5
    assert rec["pipelined_s"] < rec["serial_s"]
    assert rec["pipeline_stages"]["pipeline.dispatches"] == rec["n_batches"]
    for key in ("config", "metric", "value", "unit", "vs_baseline",
                "baseline", "env_bound"):
        assert key in rec


def test_relay_tag_formats_measured_profile(monkeypatch):
    monkeypatch.setattr(bench, "RELAY", {})
    assert "unmeasured" in bench._relay_tag()
    bench.RELAY.update({"dispatch_ms": 108.5, "h2d_MBps": 34.0,
                        "d2h_MBps": 4.1})
    tag = bench._relay_tag()
    assert "108.5" in tag and "34.0" in tag and "4.1" in tag


def test_pad_overhead_rider_on_every_line(captured):
    """Every per-config line carries the ``pad_overhead`` rider (ISSUE
    11, the prep step for ROADMAP item 2's ragged batching): the GC004
    analytic bounds from the committed PROGRAMS.lock.json, plus the
    measured pad-row fraction whenever the line's metrics snapshot
    recorded the engine's rows/pad_rows ledger."""
    bench.emit("2-Xception", "m", 3184.0, "images/sec/chip",
               baseline_model="Xception")
    rec = captured[-1]
    lock = rec["pad_overhead"]["lockfile"]
    assert "MobileNetV2" in lock and "InceptionV3" in lock
    for model, b in lock.items():
        assert b["buckets"] == sorted(b["buckets"])
        # the analytic worst cases sit inside graftcheck's GC004
        # budgets (interior 55% / floor 95%) — the committed bucket
        # plan cannot quietly drift past what the auditor allows
        assert 0.0 <= b["interior_worst_frac"] <= 0.55
        assert 0.0 <= b["floor_frac"] <= 0.95
    # a line whose snapshot carries the engine ledger gets the
    # measured half stamped next to the analytic one
    snap = {"counters": {"engine.rows": 30.0, "engine.pad_rows": 10.0},
            "gauges": {}, "timings_s": {},
            "histograms": {"serving.batch_fill_ratio":
                           {"count": 4, "mean": 0.75,
                            "p50": 0.75, "p99": 1.0}}}
    bench.emit("serving", "m", 100.0, "images/sec",
               extra={"metrics_snapshot": snap})
    measured = captured[-1]["pad_overhead"]["measured"]
    assert measured["pad_row_frac"] == pytest.approx(0.25)
    assert measured["serving_pad_frac"] == pytest.approx(0.25)


def test_cache_config_is_chipless_and_line_contract(captured, monkeypatch):
    """The ``cache`` config is chipless by design (synthetic sleep
    device) and its line is self-auditing: measured hit rate pinned
    next to the analytic floor, dispatch counts for both passes, and
    the bit-identical verdict (small replay via the env knobs to keep
    this tier-1-cheap)."""
    assert "cache" in bench._CHIPLESS_CONFIGS
    monkeypatch.setenv("SPARKDL_BENCH_CACHE_REQUESTS", "24")
    monkeypatch.setenv("SPARKDL_BENCH_CACHE_UNIVERSE", "6")
    monkeypatch.setenv("SPARKDL_BENCH_CACHE_DISPATCH_MS", "5.0")
    bench.bench_cache()
    rec = captured[-1]
    assert rec["config"] == "cache"
    assert rec["unit"] == "x vs uncached serving path"
    assert rec["value"] >= 1.5
    assert rec["bit_identical"] is True
    assert rec["hit_rate"] >= rec["analytic_hit_rate"]
    assert rec["uncached_dispatches"] == rec["n_requests"] == 24
    assert rec["cached_dispatches"] < rec["uncached_dispatches"]
    assert rec["faults"] == "none"
    for key in ("config", "metric", "value", "unit", "vs_baseline",
                "baseline", "env_bound", "pad_overhead"):
        assert key in rec
