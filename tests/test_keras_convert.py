"""Keras->jax converter parity tests.

Mirrors the reference's graph-layer oracle (``python/tests/graph/
test_builder.py``/``test_pieces.py``: run the composed graph, compare
allclose vs. direct Keras execution) — here the converted jax fn must match
``model.predict`` on random weights/inputs.
"""

import numpy as np
import pytest

from sparkdl_tpu.graph.function import ModelFunction


def _keras():
    import keras
    return keras


@pytest.fixture(scope="module")
def branchy_cnn():
    """Functional model exercising conv/bn/pool/branch/merge/dense layers."""
    keras = _keras()
    from keras import layers

    rng = np.random.default_rng(5)
    inp = layers.Input((16, 16, 3))
    x = layers.ZeroPadding2D(((1, 1), (1, 1)))(inp)
    x = layers.Conv2D(8, 3, strides=2, padding="valid", name="c1")(x)
    x = layers.BatchNormalization(name="bn1")(x)
    x = layers.ReLU()(x)
    a = layers.SeparableConv2D(8, 3, padding="same", name="sep")(x)
    b = layers.DepthwiseConv2D(3, padding="same", name="dw")(x)
    x = layers.Add()([a, b])
    y = layers.AveragePooling2D(2, padding="same")(x)
    z = layers.MaxPooling2D(2, padding="same")(x)
    x = layers.Concatenate()([y, z])
    x = layers.Conv2D(4, 1, activation="relu", name="c2")(x)
    x = layers.GlobalAveragePooling2D()(x)
    x = layers.Dropout(0.5)(x)
    out = layers.Dense(3, activation="softmax", name="d")(x)
    model = _keras().Model(inp, out)
    # randomize BN stats so inference-mode stats are exercised
    bn = model.get_layer("bn1")
    bn.set_weights([
        rng.uniform(0.8, 1.2, w.shape).astype("float32") if "gamma" in w.name
        else rng.normal(0, 0.1, w.shape).astype("float32") if w.name in ("beta", "moving_mean")
        else rng.uniform(0.5, 1.5, w.shape).astype("float32")
        for w in bn.weights
    ])
    return model


def test_branchy_cnn_parity(branchy_cnn):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 16, 16, 3)).astype(np.float32)
    ref = branchy_cnn.predict(x, verbose=0)
    mf = ModelFunction.from_keras(branchy_cnn)
    got = np.asarray(mf(x))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_converted_fn_is_jittable(branchy_cnn):
    import jax

    mf = ModelFunction.from_keras(branchy_cnn)
    x = np.zeros((2, 16, 16, 3), np.float32)
    got = jax.jit(mf.fn)(mf.variables, x)
    assert np.asarray(got).shape == (2, 3)


def test_mlp_file_roundtrip(tmp_path):
    """Save .keras + .h5, reload via path, parity vs predict — the
    reference's modelFile contract (KerasTransformer)."""
    keras = _keras()
    from keras import layers

    model = keras.Sequential([
        layers.Input((12,)),
        layers.Dense(8, activation="tanh"),
        layers.Dense(4, activation="softmax"),
    ])
    rng = np.random.default_rng(1)
    x = rng.normal(size=(5, 12)).astype(np.float32)
    ref = model.predict(x, verbose=0)
    for ext in ("keras", "h5"):
        path = str(tmp_path / f"m.{ext}")
        model.save(path)
        mf = ModelFunction.from_keras(path)
        np.testing.assert_allclose(np.asarray(mf(x)), ref,
                                   rtol=1e-5, atol=1e-6)


def test_multi_input_output():
    keras = _keras()
    from keras import layers

    a = layers.Input((4,), name="a")
    b = layers.Input((4,), name="b")
    h = layers.Add()([a, b])
    o1 = layers.Dense(2, name="o1")(h)
    o2 = layers.Subtract()([a, b])
    model = keras.Model([a, b], [o1, o2])
    rng = np.random.default_rng(2)
    xa = rng.normal(size=(3, 4)).astype(np.float32)
    xb = rng.normal(size=(3, 4)).astype(np.float32)
    ref1, ref2 = model.predict([xa, xb], verbose=0)
    mf = ModelFunction.from_keras(model)
    assert len(mf.input_names) == 2 and len(mf.output_names) == 2
    out = mf({mf.input_names[0]: xa, mf.input_names[1]: xb})
    np.testing.assert_allclose(out[mf.output_names[0]], ref1, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(out[mf.output_names[1]], ref2, rtol=1e-5, atol=1e-6)


def test_unsupported_layer_fails_loudly():
    keras = _keras()
    from keras import layers

    model = keras.Sequential([
        layers.Input((4, 3)),
        layers.LSTM(2),
    ])
    # must fail at conversion time, not at first call/trace
    with pytest.raises(NotImplementedError, match="LSTM"):
        ModelFunction.from_keras(model)


def test_compose():
    pre = ModelFunction.from_callable(lambda x: x / 2.0)
    mf = ModelFunction(fn=lambda v, x: x @ v["w"],
                       variables={"w": np.eye(3, dtype=np.float32) * 4})
    comp = pre.compose(mf)
    x = np.ones((2, 3), np.float32)
    np.testing.assert_allclose(np.asarray(comp(x)), x * 2)
