"""Continuous ragged batching (ISSUE 13): bucket-boundary flush cuts,
late-arrival top-off, the pad-row-reduction benchmark, and the edges —
deadline shed inside a partially-formed ragged batch, exactly-full vs
one-over top-off, cross-tenant coalescing under per-tenant admission
charges, and the SPARKDL_CACHE hit-probe ordering staying ahead of the
(ragged) admission path.  Everything is CPU-deterministic: flush math
is driven synchronously at the batcher layer, and the one timed server
test holds the dispatch worker open with an injected ``batch.topoff``
sleep so the top-off window is wide, not raced.
"""

import time

import numpy as np
import pytest

from sparkdl_tpu import faults
from sparkdl_tpu.serving.batcher import (DynamicBatcher, Request,
                                         ragged_arrival_benchmark,
                                         ragged_enabled_from_env)
from sparkdl_tpu.serving.errors import (DeadlineExceededError,
                                        QueueFullError)
from sparkdl_tpu.serving.server import Server


def _fn(v, x):
    import jax.numpy as jnp

    return jnp.tanh(x * v["s"] + 0.25)


VARS = {"s": np.float32(2.0)}


def _rows(n, dim=6, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.normal(size=(dim,)).astype(np.float32) for _ in range(n)]


# -- batcher-level flush cuts ----------------------------------------------

def test_ragged_flush_cuts_at_bucket_boundaries():
    b = DynamicBatcher(max_batch_size=32, max_wait_ms=1.0,
                       bucket_plan=[8, 16, 32])
    for r in _rows(20):
        b.submit(Request(r))
    first = b.next_batch()
    second = b.next_batch()
    # 20 waiting -> a zero-pad cut of 16, then the true residual of 4
    assert [len(first), len(second)] == [16, 4]


def test_ragged_flush_caps_at_max_batch_size():
    # mesh-rounded buckets can exceed the configured batch; the flush
    # cut must still honor the baseline's max_batch_size contract
    b = DynamicBatcher(max_batch_size=4, max_wait_ms=1.0,
                       bucket_plan=[8])
    for r in _rows(6):
        b.submit(Request(r))
    assert len(b.next_batch()) == 4
    assert len(b.next_batch()) == 2


def test_ragged_residual_below_smallest_bucket_flushes_whole():
    b = DynamicBatcher(max_batch_size=32, max_wait_ms=1.0,
                       bucket_plan=[8, 16, 32])
    for r in _rows(5):
        b.submit(Request(r))
    assert len(b.next_batch()) == 5  # sub-bucket: pad is the true floor


def test_urgent_deadline_beyond_cut_rides_this_flush():
    b = DynamicBatcher(max_batch_size=32, max_wait_ms=10_000.0,
                       bucket_plan=[8, 16, 32])
    reqs = [Request(r) for r in _rows(20)]
    # index 18 would be left behind by the plain 16-cut; its deadline
    # is already inside the guard window, so the cut must grow
    reqs[18].deadline = time.monotonic() + 5e-3
    for r in reqs:
        b.submit(r)
    batch = b.next_batch()
    assert len(batch) == 20  # min(depth, smallest bucket covering #18)
    assert reqs[18] in batch


# -- top-off ---------------------------------------------------------------

def test_top_off_exactly_full_vs_one_over():
    b = DynamicBatcher(max_batch_size=8, max_wait_ms=1.0,
                       bucket_plan=[8])
    for r in _rows(9):
        b.submit(Request(r))
    batch = b.next_batch()
    assert len(batch) == 8           # exactly one full bucket
    late = b.top_off(0, like=batch[0].payload)
    assert late == []                # exactly-full: nothing to pull
    residual = b.next_batch()
    assert len(residual) == 1        # the one-over remainder
    for r in _rows(3, seed=7):
        b.submit(Request(r))
    pulled = b.top_off(7, like=residual[0].payload)
    assert len(pulled) == 3          # tops the residual toward its bucket


def test_top_off_stops_at_stack_incompatible_payload():
    b = DynamicBatcher(max_batch_size=8, max_wait_ms=1.0,
                       bucket_plan=[8])
    base = Request(np.zeros((6,), np.float32))
    b.submit(Request(np.zeros((6,), np.float32)))
    poison = Request(np.zeros((7,), np.float32))  # different shape
    b.submit(poison)
    b.submit(Request(np.zeros((6,), np.float32)))  # behind the poison
    pulled = b.top_off(8, like=base.payload)
    # FIFO preserved: the pull stops AT the poison — it neither rides a
    # batch it cannot stack into nor is skipped over (no reordering)
    assert len(pulled) == 1
    assert b.depth() == 2
    assert not poison.future.done()


def test_deadline_shed_inside_partially_formed_ragged_batch():
    b = DynamicBatcher(max_batch_size=8, max_wait_ms=1.0,
                       bucket_plan=[8])
    live1 = Request(np.zeros((6,), np.float32))
    expired = Request(np.zeros((6,), np.float32),
                      deadline=time.monotonic() - 1e-3)
    live2 = Request(np.zeros((6,), np.float32))
    for r in (live1, expired, live2):
        b.submit(r)
    pulled = b.top_off(8, like=live1.payload)
    # the expired request is shed by the pull exactly like a flush
    # would shed it: it never pads a dispatch, its future fails now
    assert pulled == [live1, live2]
    with pytest.raises(DeadlineExceededError):
        expired.future.result(timeout=1)
    assert b.metrics.counters["serving.shed_deadline"] == 1


def test_server_top_off_fills_forming_batch(tmp_path):
    """The continuous half end-to-end: a sub-bucket flush forms, the
    injected ``batch.topoff`` sleep holds the worker BEFORE its pull,
    late arrivals land, and the pull absorbs them — one full-bucket
    dispatch, fill 1.0, zero pad rows for the late arrivals."""
    rows = _rows(8)
    plan = faults.FaultPlan.parse(
        "seed=13;batch.topoff:sleep:ms=250,times=1")
    # max_wait is LONG (the late arrivals must stay queued instead of
    # age-flushing into their own batch while the worker sleeps); the
    # early requests carry a deadline so the deadline guard flushes
    # them promptly into the forming batch
    with Server(_fn, VARS, max_batch_size=8, max_wait_ms=2_000,
                bucket_sizes=[8], max_inflight_batches=1,
                cache=False) as srv:
        srv.warmup(rows[0])
        with faults.active(plan):
            early = [srv.submit(r, timeout_ms=60) for r in rows[:3]]
            time.sleep(0.1)  # flush fired; worker asleep in top-off
            late = [srv.submit(r) for r in rows[3:]]
            outs = [np.asarray(f.result(timeout=60))
                    for f in early + late]
        s = srv.metrics.summary()
    assert s["serving.batches"] == 1          # ONE dispatch for all 8
    assert s["serving.topoff_rows"] == 5
    eng_rows = s["engine.rows"] - 8           # minus the warmup batch
    assert eng_rows == 8
    # warmup padded nothing and neither did the topped-off batch
    assert s.get("engine.pad_rows", 0) == 0
    expect = [np.tanh(r * 2.0 + 0.25) for r in rows]
    for got, want in zip(outs, expect):
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_injected_topoff_error_degrades_to_baseline_padding():
    rows = _rows(3)
    plan = faults.FaultPlan.parse("seed=13;batch.topoff:error:times=1")
    with Server(_fn, VARS, max_batch_size=8, max_wait_ms=10,
                bucket_sizes=[8], cache=False) as srv:
        with faults.active(plan):
            outs = [np.asarray(srv.submit(r).result(timeout=60))
                    for r in rows]
        s = srv.metrics.summary()
    # the pull aborted but the base batch still dispatched (padded)
    assert s["serving.topoff_aborted"] >= 1
    assert s["serving.completed"] == 3
    for got, r in zip(outs, rows):
        np.testing.assert_allclose(got, np.tanh(r * 2.0 + 0.25),
                                   rtol=1e-6, atol=1e-6)


def test_mixed_shape_base_batch_never_pulls_healthy_arrivals():
    """Review regression: a flush can legitimately pop MIXED payload
    shapes into one (doomed) batch; top-off must then pull nothing —
    a healthy late arrival must not die with a batch it could never
    stack into (the baseline would have served it in its own batch)."""
    good = np.zeros((6,), np.float32)
    poison = np.zeros((7,), np.float32)
    plan = faults.FaultPlan.parse(
        "seed=13;batch.topoff:sleep:ms=200,times=1")
    with Server(_fn, VARS, max_batch_size=8, max_wait_ms=2_000,
                bucket_sizes=[8], max_inflight_batches=1,
                cache=False) as srv:
        with faults.active(plan):
            # the deadline guard flushes these TWO mixed shapes together
            doomed = [srv.submit(good, timeout_ms=60),
                      srv.submit(poison, timeout_ms=60)]
            time.sleep(0.1)  # mixed batch formed; worker held in top-off
            healthy = srv.submit(good)
            for f in doomed:
                with pytest.raises(Exception):
                    f.result(timeout=30)
            out = np.asarray(healthy.result(timeout=30))
    np.testing.assert_allclose(out, np.tanh(good * 2.0 + 0.25),
                               rtol=1e-6, atol=1e-6)


# -- knobs / wiring --------------------------------------------------------

def test_sparkdl_ragged_env_knob(monkeypatch):
    monkeypatch.delenv("SPARKDL_RAGGED", raising=False)
    assert ragged_enabled_from_env() is True
    for off in ("0", "false", "off", "no"):
        monkeypatch.setenv("SPARKDL_RAGGED", off)
        assert ragged_enabled_from_env() is False
    monkeypatch.setenv("SPARKDL_RAGGED", "1")
    assert ragged_enabled_from_env() is True


def test_server_ragged_wiring():
    with Server(_fn, VARS, max_batch_size=8, bucket_sizes=[8],
                cache=False) as on:
        assert on._batcher.bucket_plan == on.bucket_sizes
        assert on.varz()["server"]["ragged"] is True
    with Server(_fn, VARS, max_batch_size=8, bucket_sizes=[8],
                ragged=False, cache=False) as off:
        assert off._batcher.bucket_plan is None
        assert off.varz()["server"]["ragged"] is False


def test_donation_probe_declares_consumable_donation_only():
    """The serving auto-donation (ISSUE 13 satellite): a square float
    fn's batch aliases its output — the engine must declare the
    donation (GC001's consumed criterion, audited in the lockfile's
    serving/generic program); a non-aliasable output shape must leave
    donation OFF (no declared-then-dropped noise)."""
    import jax

    rng = np.random.default_rng(3)
    square = {"w": rng.normal(size=(8, 8)).astype(np.float32)}
    narrow = {"w": rng.normal(size=(8, 2)).astype(np.float32)}

    def mat(v, x):
        import jax.numpy as jnp

        return jnp.tanh(x @ v["w"])

    def aliasing(srv, variables):
        row = rng.normal(size=(8,)).astype(np.float32)
        srv.warmup(row)
        eng = srv._engine_for(srv.bucket_sizes[0])
        av = {"w": jax.ShapeDtypeStruct(variables["w"].shape, np.float32)}
        batch = jax.ShapeDtypeStruct((eng.device_batch_size, 8),
                                     np.float32)
        return eng._compiled.lower(av, batch).as_text().count(
            "tf.aliasing_output")

    with Server(mat, square, max_batch_size=8, bucket_sizes=[8],
                cache=False) as srv:
        assert aliasing(srv, square) == 1   # donated AND consumed
    with Server(mat, narrow, max_batch_size=8, bucket_sizes=[8],
                cache=False) as srv:
        assert aliasing(srv, narrow) == 0   # probe kept donation off


# -- cross-tenant coalescing (fleet path) ----------------------------------

def test_cross_tenant_coalescing_respects_admission_charges():
    """Sub-bucket remainders from DIFFERENT tenants coalesce into one
    ragged dispatch (they share the version's server queue), while the
    admission layer still charges each tenant individually — and a
    zero-quota tenant is shed, never coalesced."""
    from sparkdl_tpu.serving.fleet import Fleet, TenantQuota
    from sparkdl_tpu.serving.errors import QuotaExceededError

    rows = _rows(8)
    with Fleet(quotas={"a": TenantQuota(rate_per_s=100.0, burst=8),
                       "b": TenantQuota(rate_per_s=100.0, burst=8),
                       "nobody": TenantQuota(rate_per_s=0.0)},
               max_batch_size=8, max_wait_ms=40, bucket_sizes=[8],
               cache=False) as fleet:
        fleet.add_model("m", _fn, VARS, warm_example=rows[0])
        futs = [fleet.submit("m", rows[i], tenant="a") for i in range(5)]
        futs += [fleet.submit("m", rows[i], tenant="b")
                 for i in range(5, 8)]
        with pytest.raises(QuotaExceededError):
            fleet.submit("m", rows[0], tenant="nobody")
        outs = [np.asarray(f.result(timeout=60)) for f in futs]
        state = fleet._models["m"]
        s = state.server.metrics.summary()
        tenants = fleet.varz()["tenants"]
    # one coalesced full-bucket dispatch carried BOTH tenants' rows
    assert s["serving.batches"] == 1
    assert s.get("engine.pad_rows", 0) == 0
    assert tenants["a"]["completed"] == 5
    assert tenants["b"]["completed"] == 3
    # the zero-quota shed never reached a server queue (it raised at
    # the admission gate, before any coalescing could see it)
    assert "nobody" not in tenants
    for got, r in zip(outs, rows):
        np.testing.assert_allclose(got, np.tanh(r * 2.0 + 0.25),
                                   rtol=1e-6, atol=1e-6)


# -- cache probe ordering --------------------------------------------------

def test_cache_hit_probe_still_ahead_of_ragged_admission():
    """ISSUE 13 edge: the SPARKDL_CACHE hit probe runs BEFORE the
    admission-queue charge, ragged or not — a cached payload serves
    even while the queue is at capacity."""
    from sparkdl_tpu.serving.cache import InferenceCache, example_digest

    rows = _rows(3, seed=11)
    cache = InferenceCache()
    ns = ("t", "probe-order")
    hot = rows[0]
    want = np.tanh(hot * 2.0 + 0.25).astype(np.float32)
    cache.put(ns + (example_digest(hot),), want)
    srv = Server(_fn, VARS, max_batch_size=8, max_wait_ms=10_000,
                 max_queue=1, bucket_sizes=[8], cache=cache,
                 cache_namespace=ns)
    try:
        srv.submit(rows[1])              # occupies the 1-slot queue
        with pytest.raises(QueueFullError):
            srv.submit(rows[2])          # admission is genuinely full
        got = np.asarray(srv.submit(hot).result(timeout=5))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
        assert srv.metrics.counters["serving.cache_hits"] == 1
    finally:
        srv.close(drain=False)


# -- the headline benchmark ------------------------------------------------

def test_ragged_arrival_benchmark_headline():
    """The acceptance guard: a seeded mixed-size arrival replay over a
    sleep-wrapped Server measures a pad-row REDUCTION (the engine's
    rows/pad_rows ledger) vs the flush-on-full baseline, with
    bit-identical per-request outputs and a higher mean fill ratio."""
    res = ragged_arrival_benchmark(n_bursts=6, gap_ms=60.0,
                                   dispatch_ms=5.0)
    assert res["bit_identical"], res
    assert res["ragged"]["rows"] == res["flush"]["rows"] == \
        res["n_requests"]
    assert res["pad_rows_saved"] > 0, res
    assert res["ragged"]["pad_rows"] < res["flush"]["pad_rows"], res
    assert res["ragged"]["fill_mean"] > res["flush"]["fill_mean"], res
