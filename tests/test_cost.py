"""Cost ledger + perf-regression sentinel tests (ISSUE 18).

Tier-1, CPU-only, seconds-scale: the headline chip-free conservation
proof (per-tenant attributed device time sums to the engine's metered
total, bit-stable across two seeded replays, pad tax and cache hits
itemized), the sentinel end-to-end (injected slowdown flips
``cost.regression`` + a degraded ``health()``, recovery clears both,
``tools/costreport.py`` exits 1 while open), the 10k-tenant
cardinality storm staying bounded at top-K + ``__overflow__``, the
``cost.attr`` degrade-not-fail fault site, the varz/cache schema
contract across ``Server`` and ``HeadFanoutServer``, the
``SPARKDL_COST`` gate grammar, and the twin policy's cost-share cap.
"""

import json
import os
import sys

import numpy as np
import pytest

from sparkdl_tpu import faults
from sparkdl_tpu.faults.plan import FaultPlan
from sparkdl_tpu.obs import flight
from sparkdl_tpu.obs.cost import (DEFAULT_MAX_TENANTS, OVERFLOW_TENANT,
                                  PAD_TENANT, CostLedger, CostRegression,
                                  cost_from_env, cost_rider, resolve_cost)
from sparkdl_tpu.obs import cost as cost_module
from sparkdl_tpu.serving import InferenceCache, Server
from sparkdl_tpu.utils.health import HealthTracker

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))


def _fn(variables, x):
    import jax.numpy as jnp

    return jnp.tanh(x @ variables["w"] + variables["b"])


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(11)
    variables = {
        "w": rng.normal(size=(12, 5)).astype(np.float32),
        "b": rng.normal(size=(5,)).astype(np.float32),
    }
    x = rng.normal(size=(64, 12)).astype(np.float32)
    return variables, x


@pytest.fixture(autouse=True)
def _restore_obs():
    """Tests flip the flight recorder and the process-default ledger;
    hand both back exactly as the environment would configure them."""
    saved = cost_module._default
    yield
    cost_module._default = saved
    flight.configure_from_env()


def _fake_lockfile(tmp_path, model="m", rows=8, flops_per_row=100.0,
                   bytes_accessed=64.0, name="m/fused/b8"):
    doc = {
        "schema_version": 1,
        "programs": {
            name: {
                "kind": "dispatch", "model": model, "rows": rows,
                "fingerprint": "abc123", "flops_per_row": flops_per_row,
                "bytes_accessed": bytes_accessed,
            },
        },
    }
    p = tmp_path / "lock.json"
    p.write_text(json.dumps(doc))
    return str(p), name


# -- the headline conservation proof ---------------------------------------

def _seeded_replay(seed):
    """A deterministic mixed-tenant replay into a fresh ledger: 60
    batches over 12 tenants with pad, queue wait, and cache hits."""
    ledger = CostLedger(max_tenants=8, window=6,
                        lockfile_path="/nonexistent/lock.json")
    rng = np.random.default_rng(seed)
    total_device = 0.0
    for _ in range(60):
        k = int(rng.integers(1, 4))
        tenants = rng.choice(12, size=k, replace=False)
        tenant_rows = {f"t{int(t)}": int(rng.integers(1, 5))
                       for t in tenants}
        pad = int(rng.integers(0, 4))
        device_s = float(rng.uniform(1e-4, 5e-3))
        total_device += device_s
        ledger.record_batch(
            model="m", bucket=8, tenant_rows=tenant_rows,
            device_s=device_s,
            queue_s_by_tenant={t: float(rng.uniform(0, 1e-3))
                               for t in tenant_rows},
            pad_rows=pad, hbm_bytes=1024.0)
        if rng.uniform() < 0.3:
            ledger.record_hit(tenant=f"t{int(tenants[0])}", model="m",
                              kind=("hit" if rng.uniform() < 0.5
                                    else "coalesced"))
    return ledger, total_device


def test_conservation_seeded_replay_bit_stable():
    """ISSUE 18 acceptance: attributed device time (tenants + pad)
    equals the metered total within 1e-6 relative, the snapshot is
    IDENTICAL across two seeded runs, and the pad tax and cache hits
    appear as their own itemized lines."""
    faults.clear()  # the cost stage re-runs this file with
    # SPARKDL_FAULTS exported; conservation is only defined without
    # attribution chaos (the degrade path has its own test below)
    ledger_a, device_a = _seeded_replay(7)
    ledger_b, device_b = _seeded_replay(7)
    snap_a, snap_b = ledger_a.snapshot(), ledger_b.snapshot()
    assert device_a == device_b
    assert json.dumps(snap_a, sort_keys=True) == \
        json.dumps(snap_b, sort_keys=True)

    tot = snap_a["totals"]
    assert tot["device_s"] == pytest.approx(device_a, rel=1e-12)
    # conservation: tenant shares + pad residual == metered total
    assert abs(tot["attributed_device_s"] - tot["device_s"]) <= \
        1e-6 * tot["device_s"]
    # the pad tax is itemized on its own shared line, never a tenant
    assert snap_a["pad"]["device_s"] > 0.0
    assert snap_a["pad"]["rows"] == tot["pad_rows"] > 0
    assert PAD_TENANT not in snap_a["tenants"]
    # cache hits itemized at zero device cost
    assert tot["hits"] + tot["coalesced"] > 0
    hit_tenants = [t for t, v in snap_a["tenants"].items()
                   if v["hits"] + v["coalesced"] > 0]
    assert hit_tenants
    # per-tenant sums re-derive the totals
    assert sum(v["device_s"] for v in snap_a["tenants"].values()) + \
        snap_a["pad"]["device_s"] == pytest.approx(tot["device_s"],
                                                   rel=1e-9)
    assert sum(v["rows"] for v in snap_a["tenants"].values()) == \
        tot["rows"]


def test_server_e2e_conservation_vs_engine_counter(setup):
    """End to end through the real batcher + engine: the ledger's
    metered total equals the ``engine.device_time_s`` counter, and the
    attributed split (tenants + pad) conserves it within 1e-6."""
    faults.clear()  # conservation needs every batch attributed — see
    # test_conservation_seeded_replay_bit_stable
    variables, x = setup
    ledger = CostLedger(max_tenants=16)
    with Server(_fn, variables, max_batch_size=8, max_wait_ms=5,
                bucket_sizes=[8], max_queue=256, cache=False,
                cost=ledger, model_desc="m") as srv:
        futs = [srv.submit(x[i], tenant=f"t{i % 5}") for i in range(43)]
        for f in futs:
            np.asarray(f.result(timeout=60))
        metered = srv.metrics.counters["engine.device_time_s"]
        snap = ledger.snapshot()
    tot = snap["totals"]
    assert metered > 0.0
    assert tot["device_s"] == pytest.approx(metered, rel=1e-9)
    assert abs(tot["attributed_device_s"] - tot["device_s"]) <= \
        1e-6 * tot["device_s"]
    assert set(snap["tenants"]) == {f"t{i}" for i in range(5)}
    assert tot["rows"] == 43
    # 43 rows over bucket-8 batches -> at least one padded dispatch
    assert tot["pad_rows"] > 0 and snap["pad"]["device_s"] > 0.0
    assert tot["queue_s"] > 0.0
    # varz carries the section, JSON-clean
    with Server(_fn, variables, max_batch_size=8, max_wait_ms=5,
                bucket_sizes=[8], cache=False, cost=ledger) as srv2:
        doc = srv2.varz()
        json.dumps(doc)
        assert doc["cost"]["totals"]["rows"] == 43


# -- lockfile-analytic FLOPs / HBM ----------------------------------------

def test_lockfile_flops_and_hbm_attribution(tmp_path):
    """A covered (model, bucket) resolves its lockfile program name and
    charges rows x ``flops_per_row``; HBM byte-seconds scale with each
    attributed second; uncovered programs degrade to rows-only."""
    path, prog = _fake_lockfile(tmp_path, model="m", rows=8,
                                flops_per_row=100.0)
    ledger = CostLedger(lockfile_path=path)
    ledger.record_batch(model="m", bucket=8,
                        tenant_rows={"a": 3, "b": 1}, device_s=0.008,
                        pad_rows=4, hbm_bytes=1000.0)
    snap = ledger.snapshot()
    assert snap["tenants"]["a"]["flops"] == 300.0
    assert snap["tenants"]["b"]["flops"] == 100.0
    assert snap["pad"]["flops"] == 400.0
    # shares: 3/8 and 1/8 of 8ms; hbm_bytes_s = bytes * share
    assert snap["tenants"]["a"]["device_s"] == pytest.approx(0.003)
    assert snap["tenants"]["a"]["hbm_bytes_s"] == pytest.approx(3.0)
    assert prog in snap["programs"]
    # uncovered model: synthetic program name, rows-only
    ledger.record_batch(model="other", bucket=4,
                        tenant_rows={"a": 4}, device_s=0.001)
    snap = ledger.snapshot()
    assert "other/b4" in snap["programs"]
    assert snap["tenants"]["a"]["flops"] == 300.0  # unchanged


# -- bounded cardinality ---------------------------------------------------

def test_cardinality_bound_survives_10k_tenant_storm():
    """An adversarial 10k-distinct-tenant storm stays bounded at
    top-``max_tenants`` + ``__overflow__`` — and conservation still
    holds because folding merges lines instead of dropping them."""
    ledger = CostLedger(max_tenants=16,
                        lockfile_path="/nonexistent/lock.json")
    total = 0.0
    for i in range(10_000):
        d = 1e-5 * (1 + (i % 7))
        total += d
        ledger.record_batch(model="m", bucket=8,
                            tenant_rows={f"storm-{i}": 1},
                            device_s=d, pad_rows=7)
    # a few repeat big spenders must keep their own lines
    for i in range(4):
        total += 0.01
        ledger.record_batch(model="m", bucket=8,
                            tenant_rows={f"whale-{i}": 8},
                            device_s=0.01)
    snap = ledger.snapshot()
    assert snap["tracked_tenants"] <= 16
    assert snap["overflow"] is True
    assert len(snap["tenants"]) <= 17  # top-K + __overflow__
    assert OVERFLOW_TENANT in snap["tenants"]
    for i in range(4):
        assert f"whale-{i}" in snap["tenants"]
    tot = snap["totals"]
    assert tot["rows"] == 10_000 + 32
    assert tot["device_s"] == pytest.approx(total, rel=1e-9)
    assert abs(tot["attributed_device_s"] - tot["device_s"]) <= \
        1e-6 * tot["device_s"]
    # the export surfaces stay bounded too
    text = ledger.prometheus_text()
    assert text.count("\n") < 400
    json.dumps(snap)


# -- the regression sentinel ----------------------------------------------

def test_sentinel_regression_degrades_health_then_recovers(tmp_path):
    """The e2e sentinel story: a sustained slowdown past
    ``regress_factor`` opens a ``cost.regression`` flight event and
    degrades the bound ``health()`` with a ``CostRegression``; dropping
    back under ``recover_factor`` emits ``cost.recovered`` and clears
    the degradation; ``tools/costreport.py`` exits 1 exactly while the
    regression is open."""
    from costreport import main as costreport_main

    tracker = HealthTracker("test.cost.sentinel")
    ledger = CostLedger(window=4, min_batches=4, regress_factor=2.0,
                        recover_factor=1.5, health=tracker,
                        lockfile_path="/nonexistent/lock.json")
    rec = flight.configure(enabled=True)

    def batch(device_s):
        ledger.record_batch(model="m", bucket=8,
                            tenant_rows={"a": 8}, device_s=device_s)

    for _ in range(6):          # pin the baseline at 1ms / 8 rows
        batch(0.001)
    assert ledger.regressions() == {}
    assert tracker.snapshot()["state"] == "ready"

    for _ in range(4):          # 10x slowdown fills the window
        batch(0.010)
    open_now = ledger.regressions()
    assert set(open_now) == {"m/b8"}
    assert open_now["m/b8"]["factor"] >= 2.0
    assert open_now["m/b8"]["reason"] == "baseline"
    health = tracker.snapshot()
    assert health["state"] == "degraded"
    assert health["last_error"]["type"] == CostRegression.__name__

    # costreport: exit 1 while open, table render does not crash
    dump = tmp_path / "varz.json"
    dump.write_text(json.dumps({"cost": ledger.snapshot()}))
    assert costreport_main([str(dump)]) == 1
    assert costreport_main([str(dump), "--json", "--tenant", "a"]) == 1

    for _ in range(4):          # recovery: back to the pinned rate
        batch(0.001)
    assert ledger.regressions() == {}
    assert tracker.snapshot()["state"] == "ready"
    dump.write_text(json.dumps({"cost": ledger.snapshot()}))
    assert costreport_main([str(dump)]) == 0

    names = [e["event"] for e in rec.snapshot()]
    assert "cost.regression" in names
    assert "cost.recovered" in names
    assert names.index("cost.regression") < names.index("cost.recovered")
    # and the health transitions rode the same recorder
    assert "health.degraded" in names and "health.ready" in names


def test_sentinel_recovery_guard_preserves_foreign_degradation():
    """The SLOEngine recovery guard: the sentinel only clears a
    degradation IT caused — a foreign failure recorded after the
    regression opened survives the cost recovery."""
    tracker = HealthTracker("test.cost.guard")
    ledger = CostLedger(window=4, min_batches=4, regress_factor=2.0,
                        recover_factor=1.5, health=tracker,
                        lockfile_path="/nonexistent/lock.json")

    def batch(device_s):
        ledger.record_batch(model="m", bucket=8,
                            tenant_rows={"a": 8}, device_s=device_s)

    for _ in range(6):
        batch(0.001)
    for _ in range(4):
        batch(0.010)
    assert tracker.snapshot()["state"] == "degraded"
    tracker.note_failure(RuntimeError("unrelated outage"))
    for _ in range(4):
        batch(0.001)
    assert ledger.regressions() == {}
    # the foreign degradation must NOT have been cleared
    snap = tracker.snapshot()
    assert snap["state"] == "degraded"
    assert snap["last_error"]["type"] == "RuntimeError"


def test_sentinel_analytic_check_catches_slow_pinned_baseline(tmp_path):
    """A program whose baseline was pinned while ALREADY slow is still
    caught by the lockfile-analytic cross-check: measured device-time/
    row beyond ``analytic_slack`` x the calibrated expectation opens
    with reason ``analytic`` even at factor 1.0."""
    doc = {
        "schema_version": 1,
        "programs": {
            "fast/b8": {"kind": "dispatch", "model": "fast", "rows": 8,
                        "fingerprint": "f", "flops_per_row": 100.0,
                        "bytes_accessed": 1.0},
            "slow/b8": {"kind": "dispatch", "model": "slow", "rows": 8,
                        "fingerprint": "s", "flops_per_row": 100.0,
                        "bytes_accessed": 1.0},
        },
    }
    path = str(tmp_path / "lock.json")
    with open(path, "w") as f:
        json.dump(doc, f)
    ledger = CostLedger(window=4, min_batches=4, regress_factor=2.0,
                        analytic_slack=4.0, lockfile_path=path)
    # the fast program calibrates s_per_flop from its pinned window
    for _ in range(4):
        ledger.record_batch(model="fast", bucket=8,
                            tenant_rows={"a": 8}, device_s=0.0008)
    # same analytic FLOPs, but 100x slower from the very first batch:
    # its own baseline is flat (factor 1.0) yet the analytic check trips
    for _ in range(5):
        ledger.record_batch(model="slow", bucket=8,
                            tenant_rows={"a": 8}, device_s=0.08)
    open_now = ledger.regressions()
    assert "slow/b8" in open_now
    assert open_now["slow/b8"]["reason"] == "analytic"
    assert "fast/b8" not in open_now


def test_pin_baseline_explicit_and_from_window():
    ledger = CostLedger(window=4, min_batches=4,
                        lockfile_path="/nonexistent/lock.json")
    with pytest.raises(ValueError):
        ledger.pin_baseline("never-seen")
    pinned = ledger.pin_baseline("m/b8", s_per_row=1e-4)
    assert pinned == {"m/b8": 1e-4}
    for _ in range(3):
        ledger.record_batch(model="m", bucket=8,
                            tenant_rows={"a": 8}, device_s=8e-4)
    # pin-all re-derives from the rolling windows
    pinned = ledger.pin_baseline()
    assert pinned["m/b8"] == pytest.approx(1e-4)
    snap = ledger.snapshot()
    assert snap["programs"]["m/b8"]["baseline_s_per_row"] == \
        pytest.approx(1e-4)


# -- the cost.attr fault site (degrade, never fail) ------------------------

def test_cost_attr_fault_never_fails_a_request(setup):
    """An injected ``cost.attr`` failure degrades to the
    ``serving.cost_attr_errors`` counter + the ledger's own
    ``attr_errors`` — the request itself still settles with its
    result."""
    variables, x = setup
    ledger = CostLedger()
    plan = FaultPlan.parse("seed=9;cost.attr:error:at=1")
    with faults.active(plan):
        with Server(_fn, variables, max_batch_size=8, max_wait_ms=5,
                    bucket_sizes=[8], cache=False, cost=ledger,
                    model_desc="m") as srv:
            out = np.asarray(srv.submit(x[0], tenant="t0")
                             .result(timeout=60))
            assert out.shape == (5,)
            assert plan.fired("cost.attr") == 1
            assert srv.metrics.counters["serving.cost_attr_errors"] >= 1
    snap = ledger.snapshot()
    assert snap["totals"]["attr_errors"] >= 1
    # the poisoned batch was skipped, not half-charged
    assert snap["totals"]["batches"] == 0


def test_disabled_ledger_is_inert_even_under_fault():
    """``enabled=False`` short-circuits BEFORE the fault site — the
    disabled path is one attribute read, never an injection probe."""
    ledger = CostLedger(enabled=False)
    plan = FaultPlan.parse("seed=9;cost.attr:error:at=1")
    with faults.active(plan):
        ledger.record_batch(model="m", bucket=8,
                            tenant_rows={"a": 8}, device_s=1.0)
        ledger.record_hit(tenant="a", model="m")
    assert plan.fired("cost.attr") == 0
    snap = ledger.snapshot()
    assert snap["totals"]["batches"] == 0
    assert snap["totals"]["hits"] == 0


# -- cache / hit charging --------------------------------------------------

def test_record_hit_kinds_and_unknown_kind():
    ledger = CostLedger(lockfile_path="/nonexistent/lock.json")
    ledger.record_hit(tenant="a", model="m", kind="hit")
    ledger.record_hit(tenant="a", model="m", kind="coalesced")
    ledger.record_hit(tenant="b", model="m", kind="feature_hit")
    with pytest.raises(ValueError):
        ledger.record_hit(tenant="a", model="m", kind="warm")
    snap = ledger.snapshot()
    assert snap["tenants"]["a"]["hits"] == 1
    assert snap["tenants"]["a"]["coalesced"] == 1
    assert snap["tenants"]["b"]["feature_hits"] == 1
    # hits charge ZERO device seconds — that is the cache's point
    assert snap["totals"]["device_s"] == 0.0
    assert snap["tenants"]["a"]["device_s"] == 0.0


def test_server_cache_hit_charged_to_tenant(setup):
    """A result-cache absorption lands on the riding tenant's ledger
    line (zero device seconds) instead of vanishing from showback."""
    variables, x = setup
    ledger = CostLedger()
    cache = InferenceCache()
    with Server(_fn, variables, max_batch_size=8, max_wait_ms=5,
                bucket_sizes=[8], cache=cache, cost=ledger,
                model_desc="m") as srv:
        a = np.asarray(srv.submit(x[0], tenant="t0").result(timeout=60))
        b = np.asarray(srv.submit(x[0], tenant="t1").result(timeout=60))
        assert a.tobytes() == b.tobytes()
        assert cache.metrics.counters.get("cache.hits", 0) >= 1
    snap = ledger.snapshot()
    assert snap["tenants"]["t1"]["hits"] >= 1
    assert snap["tenants"]["t1"]["device_s"] == 0.0
    assert snap["tenants"]["t0"]["device_s"] > 0.0


# -- varz contract: Server and HeadFanoutServer agree ----------------------

def test_varz_cache_and_cost_schema_unified_across_server_types(setup):
    """Satellite 2: both server classes expose the SAME cache-counter
    key schema (``cache.feature_hits``/``cache.feature_requests``
    present even when zero) and a JSON-clean ``cost`` section."""
    from sparkdl_tpu.parallel.engine import head_fanout_backbone_fn
    from sparkdl_tpu.serving.server import HeadFanoutServer

    variables, x = setup
    ledger = CostLedger()
    with Server(_fn, variables, max_batch_size=8, max_wait_ms=5,
                bucket_sizes=[8], cache=InferenceCache(), cost=ledger,
                model_desc="m") as srv:
        np.asarray(srv.submit(x[0], tenant="t0").result(timeout=60))
        doc_plain = srv.varz()
    json.dumps(doc_plain)
    plain_keys = set(doc_plain["cache"]["counters"])
    assert {"cache.feature_hits", "cache.feature_requests"} <= plain_keys
    assert doc_plain["cost"]["totals"]["rows"] >= 1

    rng = np.random.default_rng(0)
    hf_vars = {"backbone": rng.normal(size=(12, 16)).astype(np.float32)}
    head = {"kernel": rng.normal(size=(16, 4)).astype(np.float32),
            "bias": rng.normal(size=(4,)).astype(np.float32)}
    hf_ledger = CostLedger()
    with HeadFanoutServer(head_fanout_backbone_fn, hf_vars,
                          model_desc="headfanout",
                          cache=InferenceCache(),
                          cost=hf_ledger, max_batch_size=8,
                          max_wait_ms=0.5) as hsrv:
        hsrv.add_head("t0", head)
        hsrv.submit(x[0][:12], "t0").result(timeout=60)
        hsrv.submit(x[0][:12], "t0").result(timeout=60)  # feature hit
        doc_hf = hsrv.varz()
    json.dumps(doc_hf)
    hf_keys = set(doc_hf["cache"]["counters"])
    assert {"cache.feature_hits", "cache.feature_requests"} <= hf_keys
    assert doc_hf["cache"]["counters"]["cache.feature_hits"] >= 1
    # the feature hit rode the warm entry onto t0's ledger line
    assert doc_hf["cost"]["tenants"]["t0"]["feature_hits"] >= 1
    # the two classes agree on the unified counter keys
    assert {"cache.feature_hits", "cache.feature_requests"} <= \
        (plain_keys & hf_keys)


# -- env gate + constructor resolution -------------------------------------

def test_sparkdl_cost_env_grammar(monkeypatch):
    monkeypatch.setenv("SPARKDL_COST", "")
    assert cost_from_env() is None
    monkeypatch.setenv("SPARKDL_COST", "off")
    assert cost_from_env() is None
    monkeypatch.setenv("SPARKDL_COST", "1")
    ledger = cost_from_env()
    assert isinstance(ledger, CostLedger)
    assert ledger.max_tenants == DEFAULT_MAX_TENANTS
    monkeypatch.setenv("SPARKDL_COST", "tenants=4,window=8,factor=3.5")
    ledger = cost_from_env()
    assert (ledger.max_tenants, ledger.window,
            ledger.regress_factor) == (4, 8, 3.5)
    for bad in ("bogus", "tenants=x", "volume=11"):
        monkeypatch.setenv("SPARKDL_COST", bad)
        with pytest.raises(ValueError):
            cost_from_env()


def test_resolve_cost_rules():
    ledger = CostLedger()
    assert resolve_cost(False) is None
    assert resolve_cost(ledger) is ledger
    with pytest.raises(TypeError):
        resolve_cost(42)
    cost_module.configure(ledger)
    assert resolve_cost(None) is ledger
    cost_module.configure(None)
    assert resolve_cost(None) is None


# -- export surfaces -------------------------------------------------------

def test_prometheus_text_deterministic_and_escaped():
    ledger = CostLedger(window=2, min_batches=2, regress_factor=2.0,
                        lockfile_path="/nonexistent/lock.json")
    ledger.record_batch(model='mo"del\\x', bucket=8,
                        tenant_rows={'te"nant\nz': 4}, device_s=0.004,
                        pad_rows=4)
    ledger.record_hit(tenant='te"nant\nz', model='mo"del\\x')
    assert ledger.prometheus_text() == ledger.prometheus_text()
    text = ledger.prometheus_text()
    assert r'te\"nant\nz' in text
    assert "\n" + "sparkdl_cost_device_seconds_total{" in text
    assert 'bucket="8"' in text
    # zero-valued fields are elided, the regression gauge absent
    assert "sparkdl_cost_regression_open{" not in text
    # force a regression open -> the gauge line appears
    ledger.pin_baseline('mo"del\\x/b8', s_per_row=1e-9)
    for _ in range(2):
        ledger.record_batch(model='mo"del\\x', bucket=8,
                            tenant_rows={"a": 8}, device_s=0.01)
    assert "sparkdl_cost_regression_open{" in ledger.prometheus_text()


def test_cost_rider_shape():
    assert cost_rider(None) is None
    ledger = CostLedger(lockfile_path="/nonexistent/lock.json")
    ledger.record_batch(model="m", bucket=8, tenant_rows={"a": 6},
                        device_s=0.006, pad_rows=2)
    ledger.record_hit(tenant="a", model="m")
    rider = cost_rider(ledger)
    assert rider["sentinel"] == "ok"
    assert rider["open_regressions"] == []
    assert rider["tenants"]["a"]["rows"] == 6
    assert rider["tenants"]["a"]["hits"] == 1
    assert rider["pad_device_s"] == pytest.approx(0.0015, rel=1e-6)
    json.dumps(rider)


def test_costreport_cli_edge_cases(tmp_path, capsys):
    from costreport import main as costreport_main

    # cost attribution off (varz "cost": null) -> informative exit 0
    off = tmp_path / "off.json"
    off.write_text(json.dumps({"cost": None}))
    assert costreport_main([str(off)]) == 0
    # corrupt input -> exit 2
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert costreport_main([str(bad)]) == 2
    assert costreport_main([str(tmp_path / "missing.json")]) == 2
    wrong = tmp_path / "wrong.json"
    wrong.write_text(json.dumps({"cost": {"nope": 1}}))
    assert costreport_main([str(wrong)]) == 2
    capsys.readouterr()


# -- twin policy: cost-aware grants ----------------------------------------

def test_quota_autoscaler_cost_share_cap():
    """A tenant holding more than ``cost_share_cap`` of the measured
    cost is denied its burn-driven scale-up (recorded as a
    ``quota_denied`` adjustment); under-cap tenants still scale."""
    from sparkdl_tpu.serving.fleet.admission import TenantQuota
    from sparkdl_tpu.twin.policy import QuotaAutoscaler, TickObservation

    def obs(cost_by_tenant):
        return TickObservation(
            tick=3, vt=3.0, arrivals=40, admitted=30, completed=28,
            shed_total=10, shed_by_reason={"quota": 10},
            shed_by_tenant={"whale": 6, "minnow": 4},
            slo_state="breach", burn_short=20.0, burn_long=2.0,
            cost_by_tenant=cost_by_tenant)

    base = TenantQuota(rate_per_s=0.2, burst=60)
    pol = QuotaAutoscaler(base, cost_share_cap=0.5)
    d = pol.decide(obs({"whale": 90.0, "minnow": 10.0}))
    by_lever = {}
    for adj in d.adjustments:
        by_lever.setdefault(adj["lever"], []).append(adj)
    denied = {a["tenant"] for a in by_lever.get("quota_denied", [])}
    assert denied == {"whale"}
    scaled = {a.get("tenant") for a in by_lever.get("quota", [])}
    assert "minnow" in scaled and "whale" not in scaled
    # without the cap (default None) both scale — the pre-cost law
    pol_uncapped = QuotaAutoscaler(base)
    d2 = pol_uncapped.decide(obs({"whale": 90.0, "minnow": 10.0}))
    assert not any(a["lever"] == "quota_denied" for a in d2.adjustments)


@pytest.mark.slow
def test_twin_day_cost_fairness_deterministic():
    """The twin reads the LIVE ledger each tick (deterministic cost
    units: lockfile FLOPs or rows, never wall seconds) — two identical
    virtual days agree byte-for-byte including the new
    ``cost_by_tenant`` stream field and the ``cost_fairness`` score."""
    from sparkdl_tpu.serving import TenantQuota
    from sparkdl_tpu.twin import QuotaAutoscaler, ScenarioConfig, run_day

    def run():
        cfg = ScenarioConfig(seed=5, ticks=12, tenants=16,
                             mean_arrivals_per_tick=60.0, flash_start=4,
                             flash_end=8, flash_tenants=4,
                             canary_tick=2, stream_every=5,
                             digest_universe=64)
        quota = TenantQuota(rate_per_s=0.15, burst=60)
        pol = QuotaAutoscaler(quota, cost_share_cap=0.5)
        return run_day(cfg, policy=pol, default_quota=quota)

    a, b = run(), run()
    assert a.event_digest == b.event_digest
    assert a.scores["cost_fairness"] == b.scores["cost_fairness"]
    assert 0.0 < a.scores["cost_fairness"] <= 1.0
    assert '"cost_by_tenant"' in a.event_lines[-1]
