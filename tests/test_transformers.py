"""Transformer-stage tests.

Mirrors the reference's transformer test strategy (SURVEY.md §4): DataFrame
path vs. in-process numpy path equality; null-row handling; Pipeline
chaining; partition-count variation.  Zoo stages are tested with a tiny fake
module injected into the model cache (plumbing) — full-architecture numeric
parity is covered by test_models.py.
"""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.frame import DataFrame
from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.image.io import readImages
from sparkdl_tpu.models import get_model_spec
from sparkdl_tpu.transformers import (DeepImageFeaturizer, DeepImagePredictor,
                                      ModelTransformer, Pipeline,
                                      TFImageTransformer, TFTransformer,
                                      Transformer)
from sparkdl_tpu.transformers import named_image as ni


class _TinyZooModule:
    """Stands in for a flax zoo module: deterministic function of the input
    so plumbing (decode, resize, null alignment, batching) is checkable."""

    def __init__(self, feature_size=2048, classes=1000):
        self.feature_size = feature_size
        self.classes = classes

    def apply(self, variables, x, train=False, features=False):
        import jax.numpy as jnp

        m = jnp.mean(x, axis=(1, 2, 3), keepdims=False)  # [B]
        dim = self.feature_size if features else self.classes
        idx = jnp.arange(dim, dtype=jnp.float32)
        return m[:, None] * 0.01 + idx[None, :] * 1e-4


@pytest.fixture()
def fake_resnet(monkeypatch):
    spec = get_model_spec("ResNet50")
    module = _TinyZooModule(feature_size=spec.feature_size)
    monkeypatch.setitem(ni._MODEL_CACHE, ("ResNet50", ""), (module, {}))
    # engines cache per (name, featurize, batch) — clear so the fake is used
    ni._ENGINE_CACHE.clear()
    yield spec
    ni._ENGINE_CACHE.clear()


@pytest.fixture()
def image_df(fixture_images):
    # 3 decodable images + 1 null row (bad jpeg)
    return readImages(fixture_images["dir"])


def test_featurizer_plumbing(fake_resnet, image_df):
    ft = DeepImageFeaturizer(inputCol="image", outputCol="features",
                             modelName="resnet50", batchSize=8)
    out = ft.transform(image_df)
    rows = out.collect()
    assert len(rows) == 4
    nulls = [r for r in rows if r["features"] is None]
    vals = [r for r in rows if r["features"] is not None]
    assert len(nulls) == 1 and len(vals) == 3  # bad jpeg stays null
    assert all(len(r["features"]) == fake_resnet.feature_size for r in vals)
    # deterministic across runs
    out2 = ft.transform(image_df)
    v1 = [r["features"] for r in out.collect() if r["features"]]
    v2 = [r["features"] for r in out2.collect() if r["features"]]
    np.testing.assert_allclose(v1, v2)


def test_predictor_raw_and_decoded(fake_resnet, image_df):
    pred = DeepImagePredictor(inputCol="image", outputCol="probs",
                              modelName="ResNet50", batchSize=8)
    rows = pred.transform(image_df).collect()
    vals = [r for r in rows if r["probs"] is not None]
    assert all(len(r["probs"]) == 1000 for r in vals)

    topk = DeepImagePredictor(inputCol="image", outputCol="preds",
                              modelName="ResNet50", decodePredictions=True,
                              topK=3, batchSize=8)
    rows = topk.transform(image_df).collect()
    vals = [r for r in rows if r["preds"] is not None]
    assert len(vals) == 3
    for r in vals:
        assert len(r["preds"]) == 3
        probs = [p["probability"] for p in r["preds"]]
        assert probs == sorted(probs, reverse=True)
        assert all(isinstance(p["class"], str) for p in r["preds"])


def test_named_transformer_rejects_unknown_model():
    with pytest.raises(TypeError, match="not in the supported list"):
        DeepImageFeaturizer(inputCol="image", outputCol="f",
                            modelName="NoSuchNet")


def test_tf_image_transformer_vector_and_image(image_df):
    mf = ModelFunction(fn=lambda v, x: x.astype("float32") * v["scale"],
                       variables={"scale": np.float32(0.5)})
    t = TFImageTransformer(inputCol="image", outputCol="out",
                           modelFunction=mf, inputSize=[24, 20],
                           outputMode="vector", batchSize=8)
    rows = t.transform(image_df).collect()
    vals = [r for r in rows if r["out"] is not None]
    assert len(vals) == 3
    assert all(len(r["out"]) == 24 * 20 * 3 for r in vals)

    t_img = TFImageTransformer(inputCol="image", outputCol="img_out",
                               modelFunction=mf, inputSize=[24, 20],
                               outputMode="image", batchSize=8)
    rows = t_img.transform(image_df).collect()
    vals = [r for r in rows if r["img_out"] is not None]
    assert all(r["img_out"]["height"] == 24 and r["img_out"]["width"] == 20
               and r["img_out"]["mode"] == 21  # CV_32FC3
               for r in vals)


def test_model_transformer_matches_numpy(rng):
    import jax.numpy as jnp

    w = rng.normal(size=(6, 3)).astype(np.float32)
    x = rng.normal(size=(11, 6)).astype(np.float32)
    df = DataFrame({"feats": [list(map(float, r)) for r in x]})
    mf = ModelFunction(fn=lambda v, t: jnp.tanh(t @ v["w"]),
                       variables={"w": w})
    mt = ModelTransformer(inputCol="feats", outputCol="out",
                          modelFunction=mf, batchSize=4)
    got = np.asarray([r["out"] for r in mt.transform(df).collect()])
    np.testing.assert_allclose(got, np.tanh(x @ w), rtol=1e-5, atol=1e-6)


def test_tf_transformer_mapping(rng):
    xa = rng.normal(size=(9, 4)).astype(np.float32)
    xb = rng.normal(size=(9, 4)).astype(np.float32)
    df = DataFrame({"colA": [list(map(float, r)) for r in xa],
                    "colB": [list(map(float, r)) for r in xb]})
    mf = ModelFunction(
        fn=lambda v, d: {"sum": d["a"] + d["b"], "diff": d["a"] - d["b"]},
        variables={}, input_names=("a", "b"), output_names=("sum", "diff"))
    t = TFTransformer(modelFunction=mf,
                      inputMapping={"colA": "a", "colB": "b"},
                      outputMapping={"sum": "s", "diff": "d"},
                      batchSize=4)
    out = t.transform(df)
    s = np.asarray([r["s"] for r in out.collect()])
    d = np.asarray([r["d"] for r in out.collect()])
    np.testing.assert_allclose(s, xa + xb, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(d, xa - xb, rtol=1e-5, atol=1e-6)

    with pytest.raises(ValueError, match="unknown model inputs"):
        TFTransformer(modelFunction=mf, inputMapping={"colA": "nope"},
                      outputMapping={"sum": "s"}).transform(df)


def test_pipeline_chains_stages(fake_resnet, image_df):
    class _Renamer(Transformer):
        def _transform(self, ds):
            return ds.withColumnRenamed("features", "fvec")

    pipe = Pipeline(stages=[
        DeepImageFeaturizer(inputCol="image", outputCol="features",
                            modelName="ResNet50", batchSize=8),
        _Renamer(),
    ])
    model = pipe.fit(image_df)
    out = model.transform(image_df)
    assert "fvec" in out.columns and "features" not in out.columns


def test_keras_transformer_end_to_end(tmp_path, rng):
    """modelFile contract: save a tiny Keras MLP, transform a frame of 1-D
    float arrays, parity vs. local keras predict (reference's
    keras_tensor_test pattern)."""
    import keras
    from keras import layers

    from sparkdl_tpu.transformers import KerasTransformer

    model = keras.Sequential([
        layers.Input((10,)),
        layers.Dense(6, activation="relu"),
        layers.Dense(3, activation="softmax"),
    ])
    path = str(tmp_path / "mlp.keras")
    model.save(path)
    x = rng.normal(size=(7, 10)).astype(np.float32)
    ref = model.predict(x, verbose=0)
    df = DataFrame({"in": [list(map(float, r)) for r in x]})
    kt = KerasTransformer(inputCol="in", outputCol="out", modelFile=path,
                          batchSize=4)
    got = np.asarray([r["out"] for r in kt.transform(df).collect()])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_keras_image_file_transformer(tmp_path, fixture_images):
    import keras
    from keras import layers

    from sparkdl_tpu.transformers import KerasImageFileTransformer

    model = keras.Sequential([
        layers.Input((8, 8, 3)),
        layers.Conv2D(2, 3, padding="same", activation="relu"),
        layers.GlobalAveragePooling2D(),
    ])
    path = str(tmp_path / "cnn.keras")
    model.save(path)

    def loader(uri):
        from PIL import Image

        img = Image.open(uri).convert("RGB").resize((8, 8))
        return np.asarray(img, dtype=np.float32) / 255.0

    df = DataFrame({"uri": fixture_images["paths"]})
    t = KerasImageFileTransformer(inputCol="uri", outputCol="out",
                                  modelFile=path, imageLoader=loader,
                                  batchSize=4)
    rows = t.transform(df).collect()
    batch = np.stack([loader(u) for u in fixture_images["paths"]])
    ref = model.predict(batch, verbose=0)
    got = np.asarray([r["out"] for r in rows])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_image_file_transformer(fixture_images):
    from sparkdl_tpu.transformers import ImageFileTransformer

    def loader(uri):
        from PIL import Image

        img = Image.open(uri).convert("RGB").resize((8, 8))
        return np.asarray(img, dtype=np.float32) / 255.0

    paths = fixture_images["paths"] + [fixture_images["bad"]]
    df = DataFrame({"uri": paths})
    mf = ModelFunction(fn=lambda v, x: x.reshape(x.shape[0], -1) @ v["w"],
                       variables={"w": np.ones((8 * 8 * 3, 2), np.float32)})
    t = ImageFileTransformer(inputCol="uri", outputCol="out",
                             modelFunction=mf, imageLoader=loader, batchSize=4)
    rows = t.transform(df).collect()
    assert len(rows) == 4
    assert rows[-1]["out"] is None  # bad jpeg -> loader fails -> null
    assert all(len(r["out"]) == 2 for r in rows[:-1])


def test_tf_image_transformer_4channel_keeps_alpha_last(image_df):
    """RGBA model output must become BGRA in the struct (alpha stays the
    LAST channel — CV_32FC4 convention), not ABGR (ADVICE round 1)."""
    from sparkdl_tpu.image.schema import imageStructToArray

    def add_alpha(v, x):
        import jax.numpy as jnp

        rgb = x.astype("float32")
        alpha = jnp.full_like(rgb[..., :1], 99.0)
        return jnp.concatenate([rgb, alpha], axis=-1)

    mf = ModelFunction(fn=add_alpha, variables={})
    t = TFImageTransformer(inputCol="image", outputCol="out",
                           modelFunction=mf, inputSize=[16, 16],
                           outputMode="image", batchSize=8)
    rows = t.transform(image_df).collect()
    vals = [r for r in rows if r["out"] is not None]
    assert len(vals) == 3
    for r in vals:
        arr = imageStructToArray(r["out"])  # BGRA float32
        assert arr.shape[-1] == 4
        # alpha must be the last channel, everywhere 99
        np.testing.assert_allclose(arr[..., 3], 99.0)
        assert not np.allclose(arr[..., 0], 99.0)  # not ABGR


def test_image_mode_packs_outputs_incrementally(fixture_images, monkeypatch):
    """VERDICT r2 weak #5: outputMode="image" must emit structs per engine
    chunk, not concatenate the whole output first: (a) structurally, the
    concatenate-everything path (_run_streaming) is never entered; (b)
    behaviorally, packing of early chunks happens while later chunks are
    still being decoded — O(chunk) residency."""
    import time

    import pyarrow as pa
    import pyarrow.compute as pc

    from sparkdl_tpu.frame import DataFrame

    events = []
    real_s2b = ni.arrowStructsToBatch
    real_a2s = ni.imageArrayToStruct

    def spy_decode(column, h, w, **kw):
        # slow the producer so interleaving is deterministic: the consumer
        # packs chunk 1 long before the serial decode of chunk 6 starts
        time.sleep(0.05)
        events.append("decode")
        return real_s2b(column, h, w, **kw)

    def spy_pack(arr, origin=""):
        events.append("pack")
        return real_a2s(arr, origin=origin)

    monkeypatch.setattr(ni, "arrowStructsToBatch", spy_decode)
    monkeypatch.setattr(ni, "imageArrayToStruct", spy_pack)

    def fail_run_streaming(*a, **kw):
        raise AssertionError(
            "image mode must stream per chunk, not concatenate via "
            "_run_streaming")

    monkeypatch.setattr(TFImageTransformer, "_run_streaming",
                        fail_run_streaming)

    # 48 decodable rows, batchSize 2 (rounds to 8 on the 8-dev mesh) -> 6
    # decode chunks; the engine window (2) + prefetch (2) hold at most ~4
    # chunks before the first output is yielded.
    base = readImages(fixture_images["dir"])
    good = base.table.filter(
        pc.invert(pc.is_null(base.table.column("image"))))
    reps = pa.concat_tables([good] * 16).combine_chunks()
    df = DataFrame(reps)
    mf = ModelFunction(fn=lambda v, x: x.astype("float32") * v["s"],
                       variables={"s": np.float32(1.0)})
    t = TFImageTransformer(inputCol="image", outputCol="out",
                           modelFunction=mf, inputSize=[16, 16],
                           outputMode="image", batchSize=2)
    rows = t.transform(df).collect()
    assert sum(1 for r in rows if r["out"] is not None) == 48
    decode_positions = [i for i, e in enumerate(events) if e == "decode"]
    pack_positions = [i for i, e in enumerate(events) if e == "pack"]
    assert len(decode_positions) == 6
    assert len(pack_positions) == 48
    assert pack_positions[0] < decode_positions[-1], (
        f"first pack must precede last decode (interleaved streaming); "
        f"events: {events[:40]}")


def test_zoo_engine_bf16_env_knob(fake_resnet, image_df, monkeypatch):
    """SPARKDL_ZOO_COMPUTE_DTYPE=bfloat16 keeps the featurizer contract
    (f32 feature vectors, same values within bf16 tolerance)."""
    df = image_df
    ft = DeepImageFeaturizer(inputCol="image", outputCol="features",
                             modelName="ResNet50", batchSize=8)
    base = [r["features"] for r in ft.transform(df).collect()]
    monkeypatch.setenv("SPARKDL_ZOO_COMPUTE_DTYPE", "bfloat16")
    bf16 = [r["features"] for r in ft.transform(df).collect()]
    assert len(base) == len(bf16)
    for a, b in zip(base, bf16):
        if a is None:
            assert b is None
            continue
        a, b = np.asarray(a), np.asarray(b)
        scale = max(1.0, float(np.abs(a).max()))
        assert np.abs(a - b).max() / scale < 0.05  # bf16 compute tolerance
    # the engine itself must hand back f32 (the output_host_dtype cast),
    # not raw bf16 — the one property the knob's plumbing guarantees
    eng = ni._zoo_engine("ResNet50", True, 8)
    out = eng(np.zeros((3, 8, 8, 3), np.uint8))
    assert out.dtype == np.float32
    # unknown dtype values are rejected, not silently f32
    monkeypatch.setenv("SPARKDL_ZOO_COMPUTE_DTYPE", "float16")
    with pytest.raises(ValueError, match="not supported"):
        ni._zoo_engine("ResNet50", True, 8)
