"""Serving-layer tests (tier-1, CPU-only, 8-device virtual mesh).

Pins the acceptance contract of sparkdl_tpu.serving: results bitwise
identical to direct ``InferenceEngine.map_batches`` regardless of request
arrival order/interleaving, deadline shedding BEFORE dispatch,
bounded-queue backpressure with retry-after, per-batch fault isolation
(raising AND stalling model fns, retry wiring through utils.retry),
graceful drain, the transformer/UDF adapters, and the metrics surface.
"""

import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.parallel.engine import InferenceEngine
from sparkdl_tpu.serving import (DeadlineExceededError, DispatchTimeoutError,
                                 QueueFullError, Server, ServerClosedError,
                                 from_transformer)


def _fn(variables, x):
    import jax.numpy as jnp

    return jnp.tanh(x @ variables["w"] + variables["b"])


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(11)
    variables = {
        "w": rng.normal(size=(12, 5)).astype(np.float32),
        "b": rng.normal(size=(5,)).astype(np.float32),
    }
    x = rng.normal(size=(45, 12)).astype(np.float32)
    return variables, x


# -- correctness -----------------------------------------------------------

def test_results_bitwise_match_engine_any_arrival_order(setup):
    """Every request's result must be byte-for-byte what direct
    ``InferenceEngine.map_batches`` produces for the same example — across
    shuffled submission order and concurrent submitter interleaving (the
    micro-batch composition a request lands in must not leak into its
    numbers)."""
    variables, x = setup
    eng = InferenceEngine(_fn, variables, device_batch_size=16)
    ref = np.concatenate(list(eng.map_batches([x])), axis=0)

    with Server(_fn, variables, max_batch_size=16, max_wait_ms=5,
                bucket_sizes=[16], max_queue=256) as srv:
        results = [None] * len(x)
        order = np.random.default_rng(3).permutation(len(x))

        def client(idxs):
            futs = [(int(i), srv.submit(x[int(i)])) for i in idxs]
            for i, f in futs:
                results[i] = np.asarray(f.result(timeout=60))

        threads = [threading.Thread(target=client, args=(order[lo::3],))
                   for lo in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    got = np.stack(results)
    np.testing.assert_array_equal(got, ref)


def test_pytree_requests_and_results(setup):
    """Pytree payloads stack per-leaf and demux per-row, preserving
    integer leaves (argmax ids never floated)."""
    variables, x = setup

    def fn(v, xb):
        import jax.numpy as jnp

        y = jnp.tanh(xb["a"] @ v["w"] + v["b"])
        return {"y": y, "ids": jnp.argmax(y, axis=-1)}

    plain = InferenceEngine(fn, variables, device_batch_size=8)
    ref = plain({"a": x})
    with Server(fn, variables, max_batch_size=8, max_wait_ms=5,
                bucket_sizes=[8]) as srv:
        futs = [srv.submit({"a": x[i]}) for i in range(len(x))]
        rows = [f.result(timeout=60) for f in futs]
    np.testing.assert_array_equal(np.stack([r["y"] for r in rows]),
                                  ref["y"])
    ids = np.stack([r["ids"] for r in rows])
    np.testing.assert_array_equal(ids, ref["ids"])
    assert ids.dtype.kind in "iu"


def test_bucket_padding_keeps_fill_ratio_honest(setup):
    """A light micro-batch dispatches through the SMALLEST covering
    bucket, and the fill-ratio histogram records n/bucket."""
    variables, x = setup
    with Server(_fn, variables, max_batch_size=16, max_wait_ms=5,
                bucket_sizes=[8, 16]) as srv:
        futs = [srv.submit(x[i]) for i in range(3)]
        for f in futs:
            f.result(timeout=60)
        # allow the worker to finish metric writes after settling futures
        deadline = time.monotonic() + 5
        while (not srv.metrics.histograms.get("serving.batch_fill_ratio")
               and time.monotonic() < deadline):
            time.sleep(0.01)
        fills = srv.metrics.histograms["serving.batch_fill_ratio"]
        # 3 requests -> bucket 8 (8-device mesh keeps it at 8): fill 3/8
        assert fills and abs(fills[0] - 3 / 8) < 1e-9
        assert list(srv._engines) == [8]


# -- deadlines / backpressure ---------------------------------------------

def test_expired_deadlines_shed_before_dispatch(setup):
    variables, x = setup
    with Server(_fn, variables, max_batch_size=4, max_wait_ms=30,
                bucket_sizes=[4]) as srv:
        doomed = [srv.submit(x[i], timeout_ms=0) for i in range(2)]
        live = [srv.submit(x[i]) for i in range(2)]  # 4th fills the batch
        for f in doomed:
            with pytest.raises(DeadlineExceededError):
                f.result(timeout=60)
        for f in live:
            np.asarray(f.result(timeout=60))
        s = srv.metrics.summary()
    assert s["serving.shed_deadline"] == 2
    assert s["serving.completed"] == 2
    # shed requests never reached the engine: dispatched batch held 2 rows
    assert s["serving.batches"] == 1


def test_timeout_tighter_than_wait_window_still_serves(setup):
    """A deadline SHORTER than max_wait_ms must flush early and serve
    under light load — not wait out the window and shed 100% of
    traffic."""
    variables, x = setup
    with Server(_fn, variables, max_batch_size=64, max_wait_ms=5_000,
                bucket_sizes=[64], default_timeout_ms=500) as srv:
        np.asarray(srv.predict(x[0]))  # would be shed at the 5s flush
        assert srv.metrics.counters.get("serving.shed_deadline", 0) == 0


def test_queue_full_rejects_with_retry_after(setup):
    variables, x = setup
    # Nothing flushes (batch never fills, wait is 10s), so the queue holds.
    srv = Server(_fn, variables, max_batch_size=64, max_wait_ms=10_000,
                 max_queue=4, bucket_sizes=[64])
    try:
        futs = [srv.submit(x[i]) for i in range(4)]
        with pytest.raises(QueueFullError) as ei:
            srv.submit(x[4])
        assert ei.value.retry_after_s > 0
        assert srv.metrics.counters["serving.rejected_queue_full"] == 1
        # graceful close drains the queued 4 as one final micro-batch
        srv.close(drain=True)
        eng = InferenceEngine(_fn, variables, device_batch_size=64)
        ref = np.concatenate(list(eng.map_batches([x[:4]])), axis=0)
        np.testing.assert_array_equal(
            np.stack([np.asarray(f.result(timeout=60)) for f in futs]), ref)
    finally:
        srv.close()


# -- fault isolation -------------------------------------------------------

def test_bad_batch_fails_only_its_own_futures(setup):
    """A model failure (here: a poison request shape the traced fn
    rejects) must fail ONLY the batch it rode in; the next batch serves
    normally."""
    variables, x = setup
    with Server(_fn, variables, max_batch_size=4, max_wait_ms=50,
                bucket_sizes=[4]) as srv:
        poison = np.zeros((13,), np.float32)  # fn expects 12 features
        bad = [srv.submit(poison) for _ in range(4)]  # full batch -> flush
        good = [srv.submit(x[i]) for i in range(4)]
        for f in bad:
            with pytest.raises(Exception):
                f.result(timeout=60)
        for f in good:
            np.asarray(f.result(timeout=60))
        assert srv.metrics.counters["serving.batch_failures"] == 1
        assert srv.metrics.counters["serving.completed"] == 4


def test_transient_failure_retried_through_utils_retry(setup, monkeypatch):
    """max_retries wires the batch dispatch through utils.retry: a
    transient (retryable) failure re-executes and the batch still
    succeeds; deterministic failures stay non-retryable."""
    variables, x = setup
    with Server(_fn, variables, max_batch_size=4, max_wait_ms=20,
                bucket_sizes=[4], max_retries=1) as srv:
        calls = {"n": 0}
        real_engine_for = srv._engine_for

        class Flaky:
            def __init__(self, eng):
                self._eng = eng
                self.device_batch_size = eng.device_batch_size

            def __call__(self, batch):
                calls["n"] += 1
                if calls["n"] == 1:
                    raise RuntimeError("transient device hiccup")
                return self._eng(batch)

        monkeypatch.setattr(srv, "_engine_for",
                            lambda b, ex=None: Flaky(real_engine_for(b)))
        futs = [srv.submit(x[i]) for i in range(4)]
        for f in futs:
            np.asarray(f.result(timeout=60))
        assert calls["n"] == 2  # first attempt + one retry
        assert srv.metrics.counters.get("serving.batch_failures", 0) == 0


def test_stalled_batch_times_out_and_later_batches_proceed(setup,
                                                           monkeypatch):
    """A model call that stalls past dispatch_timeout_ms fails its OWN
    batch with DispatchTimeoutError (the wedged worker is abandoned, its
    concurrency slot freed) and the next batch still serves."""
    variables, x = setup
    with Server(_fn, variables, max_batch_size=2, max_wait_ms=50,
                bucket_sizes=[2], dispatch_timeout_ms=300,
                max_inflight_batches=1) as srv:
        calls = {"n": 0}
        real_engine_for = srv._engine_for

        class Stalls:
            def __init__(self, eng):
                self._eng = eng
                self.device_batch_size = eng.device_batch_size

            def __call__(self, batch):
                if not np.asarray(batch).any():
                    # the server's untimed compile-warm probe (zeros):
                    # never stall it — the watchdog scopes model calls
                    return self._eng(batch)
                calls["n"] += 1
                if calls["n"] == 1:
                    time.sleep(2.0)  # well past the 300ms watchdog
                return self._eng(batch)

        monkeypatch.setattr(srv, "_engine_for",
                            lambda b, ex=None: Stalls(real_engine_for(b)))
        stuck = [srv.submit(x[i]) for i in range(2)]
        for f in stuck:
            with pytest.raises(DispatchTimeoutError):
                f.result(timeout=60)
        ok = [srv.submit(x[i]) for i in range(2)]
        for f in ok:
            np.asarray(f.result(timeout=60))
        assert srv.metrics.counters["serving.dispatch_timeouts"] == 1


# -- lifecycle -------------------------------------------------------------

def test_graceful_drain_serves_queue_then_rejects(setup):
    variables, x = setup
    srv = Server(_fn, variables, max_batch_size=64, max_wait_ms=10_000,
                 bucket_sizes=[64])
    futs = [srv.submit(x[i]) for i in range(5)]  # parked: batch never fills
    srv.close(drain=True)
    for f in futs:
        np.asarray(f.result(timeout=60))  # drained, not dropped
    with pytest.raises(ServerClosedError):
        srv.submit(x[0])


def test_abandoned_close_settles_undispatched_futures(setup, monkeypatch):
    """A wedged model call with NO watchdog configured: close() must not
    leave requests the dispatcher is holding (or still queued) pending
    forever — everything outside the wedged batch itself settles with
    ServerClosedError."""
    variables, x = setup
    srv = Server(_fn, variables, max_batch_size=2, max_wait_ms=20,
                 bucket_sizes=[2], max_inflight_batches=1)
    try:
        real_engine_for = srv._engine_for
        calls = {"n": 0}

        class Wedge:
            def __init__(self, eng):
                self._eng = eng
                self.device_batch_size = eng.device_batch_size

            def __call__(self, batch):
                calls["n"] += 1
                if calls["n"] == 1:
                    time.sleep(3.0)  # wedged well past close(timeout)
                return self._eng(batch)

        monkeypatch.setattr(srv, "_engine_for",
                            lambda b, ex=None: Wedge(real_engine_for(b)))
        wedged = [srv.submit(x[i]) for i in range(2)]   # dispatches, hangs
        time.sleep(0.2)  # let the wedged batch ENTER the model call —
        # submitted any earlier, the ragged top-off would legitimately
        # pull the next requests into the forming batch before dispatch
        parked = [srv.submit(x[i]) for i in range(2)]   # blocked behind it
        time.sleep(0.1)
        srv.close(drain=True, timeout_s=0.5)
        for f in parked:
            with pytest.raises(ServerClosedError):
                f.result(timeout=10)
        # the wedged batch itself settles once its model call returns
        for f in wedged:
            np.asarray(f.result(timeout=30))
    finally:
        srv.close()


def test_hard_close_fails_queued_futures(setup):
    variables, x = setup
    srv = Server(_fn, variables, max_batch_size=64, max_wait_ms=10_000,
                 bucket_sizes=[64])
    futs = [srv.submit(x[i]) for i in range(3)]
    srv.close(drain=False)
    for f in futs:
        with pytest.raises(ServerClosedError):
            f.result(timeout=60)


def test_predict_and_predict_async(setup):
    import asyncio

    variables, x = setup
    eng = InferenceEngine(_fn, variables, device_batch_size=8)
    ref = np.concatenate(list(eng.map_batches([x[:4]])), axis=0)
    with Server(_fn, variables, max_batch_size=8, max_wait_ms=5,
                bucket_sizes=[8]) as srv:
        np.testing.assert_array_equal(np.asarray(srv.predict(x[0])), ref[0])

        async def handler():
            rows = await asyncio.gather(
                *[srv.predict_async(x[i]) for i in range(4)])
            return np.stack([np.asarray(r) for r in rows])

        np.testing.assert_array_equal(asyncio.run(handler()), ref)


def test_warmup_compiles_every_bucket(setup):
    variables, x = setup
    with Server(_fn, variables, max_batch_size=16, max_wait_ms=5,
                bucket_sizes=[8, 16]) as srv:
        srv.warmup(x[0])
        assert sorted(srv._engines) == [8, 16]


# -- adapters --------------------------------------------------------------

def test_from_transformer_model_transformer_parity(setup):
    from sparkdl_tpu.frame import DataFrame
    from sparkdl_tpu.graph.function import ModelFunction
    from sparkdl_tpu.transformers.tensor import ModelTransformer

    variables, x = setup
    stage = ModelTransformer(
        inputCol="features", outputCol="out",
        modelFunction=ModelFunction(fn=_fn, variables=variables),
        batchSize=16)
    df = DataFrame({"features": [row for row in x]})
    offline = stage.transform(df).column_to_numpy("out")
    # bucket pinned to the stage's batch: bitwise identity is a per-shape
    # contract (an 8-wide padded matmul may differ from a 16-wide one in
    # the last ulp — same as any XLA re-fusion; see test_engine's allclose)
    with from_transformer(stage, max_wait_ms=5, bucket_sizes=[16]) as srv:
        assert srv.max_batch_size == 16  # stage batchSize seeds the server
        online = np.stack(
            [np.asarray(srv.predict(list(row))) for row in x])
    np.testing.assert_array_equal(online.astype(np.float32), offline)


def test_from_transformer_image_stage_accepts_structs_and_arrays(setup):
    from sparkdl_tpu.graph.function import ModelFunction
    from sparkdl_tpu.image.schema import imageArrayToStruct
    from sparkdl_tpu.transformers.named_image import TFImageTransformer

    rng = np.random.default_rng(5)

    def img_fn(v, x):
        import jax.numpy as jnp

        return jnp.mean(jnp.asarray(x, jnp.float32), axis=(1, 2))

    stage = TFImageTransformer(
        inputCol="image", outputCol="vec",
        modelFunction=ModelFunction(fn=img_fn, variables={}),
        inputSize=[8, 8], batchSize=8)
    rgb = (rng.random((8, 8, 3)) * 255).astype(np.uint8)
    with from_transformer(stage, max_wait_ms=5) as srv:
        via_array = np.asarray(srv.predict(rgb))
        # struct requests decode through the same converter the offline
        # path uses (structs store BGR byte order; the adapter flips back
        # to the RGB the model sees)
        struct = imageArrayToStruct(
            np.ascontiguousarray(rgb[:, :, ::-1]), origin="r0")
        via_struct = np.asarray(srv.predict(struct))
        # a mis-sized array resizes host-side instead of failing
        big = (rng.random((16, 16, 3)) * 255).astype(np.uint8)
        resized = np.asarray(srv.predict(big))
    np.testing.assert_array_equal(via_array, via_struct)
    assert resized.shape == via_array.shape
    np.testing.assert_allclose(via_array, rgb.mean(axis=(0, 1)), atol=0.5)


def test_from_transformer_rejects_unknown_stage():
    from sparkdl_tpu.transformers.base import Transformer

    with pytest.raises(TypeError, match="from_transformer"):
        from_transformer(Transformer())


def test_register_serving_udf_shares_queue(setup):
    from sparkdl_tpu.frame import DataFrame
    from sparkdl_tpu.udf.registry import UDFRegistry, register_serving_udf

    variables, x = setup
    reg = UDFRegistry()
    df = DataFrame({"features": [list(row) for row in x[:9]] + [None]})
    with Server(_fn, variables, max_batch_size=8, max_wait_ms=5,
                bucket_sizes=[8],
                host_preprocess=lambda v: np.asarray(v, np.float32)) as srv:
        register_serving_udf("srv_udf", srv, registry=reg)
        out = reg.apply("srv_udf", df, "features", "scored")
        rows = out.table.column("scored").to_pylist()
    eng = InferenceEngine(_fn, variables, device_batch_size=8)
    ref = np.concatenate(list(eng.map_batches([x[:9]])), axis=0)
    assert rows[-1] is None  # null row stays null
    np.testing.assert_allclose(np.asarray(rows[:9], np.float32), ref,
                               rtol=1e-6, atol=1e-7)


def test_client_cancel_never_kills_the_dispatcher(setup):
    """A client cancel() racing deadline shedding must not raise out of
    the dispatcher thread: the cancelled future is skipped and the server
    keeps serving."""
    variables, x = setup
    with Server(_fn, variables, max_batch_size=4, max_wait_ms=30,
                bucket_sizes=[4]) as srv:
        doomed = srv.submit(x[0], timeout_ms=0)
        assert doomed.cancel()  # pending future: cancel wins the race
        live = srv.submit(x[1])
        np.asarray(live.result(timeout=60))
        # dispatcher survived the InvalidStateError window: still serving
        np.asarray(srv.predict(x[2]))


def test_named_model_honors_zoo_compute_dtype(monkeypatch):
    """Server('<zoo name>') must follow the zoo transformers'
    SPARKDL_ZOO_COMPUTE_DTYPE contract (bf16 compute + f32 host cast
    under the bench configuration) so from_transformer keeps its
    same-rows-as-transform() promise."""
    import jax.numpy as jnp

    import sparkdl_tpu.models as models
    import sparkdl_tpu.transformers.named_image as named_image
    from sparkdl_tpu.serving import server as server_mod

    class _Spec:
        preprocess = staticmethod(lambda x: x)

    class _Mod:
        def apply(self, v, x, train=False, features=False):
            return x

    monkeypatch.setattr(models, "get_model_spec", lambda n: _Spec())
    # _resolve_model now builds the fn through named_image.zoo_model_fn
    # (the shared constructor), which resolves the spec via named_image's
    # own import binding
    monkeypatch.setattr(named_image, "get_model_spec", lambda n: _Spec())
    monkeypatch.setattr(named_image, "_cached_model", lambda n: (_Mod(), {}))
    monkeypatch.setenv("SPARKDL_ZOO_COMPUTE_DTYPE", "bfloat16")
    _, _, ov = server_mod._resolve_model("FakeZoo", None, True)
    assert ov["compute_dtype"] == jnp.bfloat16
    assert ov["output_host_dtype"] == np.float32
    monkeypatch.setenv("SPARKDL_ZOO_COMPUTE_DTYPE", "float32")
    _, _, ov = server_mod._resolve_model("FakeZoo", None, True)
    # zoo overrides always pin donation OFF (the recorded GC001
    # exemption: a uint8 batch can never alias the float features) and
    # carry the family's default partition rules (ISSUE 14 — an
    # all-replicated no-op until the mesh grows a model axis)
    from sparkdl_tpu.parallel import mesh as mesh_lib

    assert ov == {"donate_batch": False,
                  "partition_rules": mesh_lib.default_partition_rules}
    monkeypatch.setenv("SPARKDL_ZOO_COMPUTE_DTYPE", "bogus")
    with pytest.raises(ValueError, match="not supported"):
        server_mod._resolve_model("FakeZoo", None, True)


def test_result_rows_do_not_pin_batch_output(setup):
    """Each future's result must be its own O(row) array, not a view
    pinning the whole [bucket, ...] batch output."""
    variables, x = setup
    with Server(_fn, variables, max_batch_size=8, max_wait_ms=5,
                bucket_sizes=[8]) as srv:
        row = np.asarray(srv.predict(x[0]))
    assert row.base is None  # owns its memory


# -- construction errors ---------------------------------------------------

def test_register_serving_udf_overrides_online_deadline(setup):
    """Bulk offline rows must NOT inherit the server's online
    default_timeout_ms: queue-tail rows would be shed and one
    DeadlineExceededError would fail the whole column apply."""
    from sparkdl_tpu.frame import DataFrame
    from sparkdl_tpu.udf.registry import UDFRegistry, register_serving_udf

    variables, x = setup
    reg = UDFRegistry()
    df = DataFrame({"features": [list(row) for row in x]})
    # tiny batches + an aggressive online deadline: 45 queued rows take
    # many dispatch cycles, far beyond 1ms in-queue for the tail
    with Server(_fn, variables, max_batch_size=8, max_wait_ms=5,
                bucket_sizes=[8], default_timeout_ms=1) as srv:
        register_serving_udf("bulk", srv, registry=reg)
        out = reg.apply("bulk", df, "features", "scored")
        rows = out.table.column("scored").to_pylist()
    assert all(r is not None for r in rows)
    assert srv.metrics.counters.get("serving.shed_deadline", 0) == 0


def test_server_rejects_bad_buckets(setup):
    variables, _ = setup
    with pytest.raises(ValueError, match="cover"):
        Server(_fn, variables, max_batch_size=16, bucket_sizes=[4, 8])
    with pytest.raises(ValueError, match="positive"):
        Server(_fn, variables, bucket_sizes=[0])


def test_server_rejects_unknown_model_form():
    with pytest.raises(TypeError, match="Cannot serve"):
        Server(12345)
