"""Numerical sanitizing + retry orchestration (SURVEY.md §5 analogs)."""

import numpy as np
import pytest

from sparkdl_tpu.utils import debug, retry


def test_check_finite_flags_bad_leaves():
    debug.check_finite({"a": np.ones(3), "b": {"c": np.zeros(2)}})  # ok
    with pytest.raises(FloatingPointError, match="non-finite"):
        debug.check_finite({"a": np.asarray([1.0, np.nan])})
    with pytest.raises(FloatingPointError, match="non-finite"):
        debug.check_finite({"w": np.asarray([np.inf], np.float32)})
    # integer leaves can't be non-finite; must not crash
    debug.check_finite({"i": np.asarray([1, 2, 3])})


def test_checks_enabled_env_and_api(monkeypatch):
    monkeypatch.delenv("SPARKDL_DEBUG_NANS", raising=False)
    debug.disable_checks()
    assert not debug.checks_enabled()
    monkeypatch.setenv("SPARKDL_DEBUG_NANS", "1")
    assert debug.checks_enabled()
    monkeypatch.delenv("SPARKDL_DEBUG_NANS")
    debug.enable_checks(nan_debug=False)
    assert debug.checks_enabled()
    debug.disable_checks()


def test_nonfinite_loss_fails_fast_when_enabled(rng):
    import jax.numpy as jnp
    import optax

    from sparkdl_tpu.parallel.train import fit_data_parallel

    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = rng.normal(size=(16, 1)).astype(np.float32)

    def predict(p, xb):
        # divides by ~0 after the first update -> NaN loss
        return jnp.asarray(xb) @ p["w"] / jnp.sum(p["w"]) * jnp.nan

    params = {"w": np.ones((4, 1), np.float32)}
    debug.enable_checks(nan_debug=False)
    try:
        with pytest.raises(FloatingPointError, match="non-finite loss"):
            fit_data_parallel(predict, params, x, y,
                              optimizer=optax.sgd(0.1), loss="mse",
                              batch_size=8, epochs=2)
    finally:
        debug.disable_checks()


def test_with_retries_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("transient")
        return "ok"

    seen = []
    out = retry.with_retries(flaky, max_retries=3,
                             on_retry=lambda i, e: seen.append((i, str(e))))
    assert out == "ok"
    assert len(calls) == 3
    assert seen == [(0, "transient"), (1, "transient")]


def test_with_retries_exhausts_and_raises():
    calls = []

    def always():
        calls.append(1)
        raise RuntimeError("persistent")

    with pytest.raises(RuntimeError, match="persistent"):
        retry.with_retries(always, max_retries=1)
    assert len(calls) == 2  # initial + 1 retry


def test_with_retries_deterministic_failures_not_retried():
    """FloatingPointError (the SPARKDL_DEBUG_NANS fail-fast) and
    validation errors must surface immediately — re-training a diverged
    fit max_retries times defeats the debug flag."""
    calls = []

    def diverged():
        calls.append(1)
        raise FloatingPointError("non-finite loss")

    with pytest.raises(FloatingPointError):
        retry.with_retries(diverged, max_retries=3)
    assert len(calls) == 1
    calls.clear()

    def bad_params():
        calls.append(1)
        raise ValueError("requires params")

    with pytest.raises(ValueError):
        retry.with_retries(bad_params, max_retries=3)
    assert len(calls) == 1


def test_fit_with_retries_restarts_on_load_failure(fixture_images):
    """A transient failure during data loading (before any epoch trains)
    is retried from scratch — fits are idempotent like the reference's
    Spark tasks."""
    from sparkdl_tpu.estimators import ImageFileEstimator
    from sparkdl_tpu.frame import DataFrame
    from sparkdl_tpu.graph.function import ModelFunction

    import jax.numpy as jnp

    paths = fixture_images["paths"] * 4
    labels = [[1.0, 0.0] if i % 2 == 0 else [0.0, 1.0]
              for i in range(len(paths))]
    df = DataFrame({"uri": paths, "label": labels})
    fails = {"left": 1}

    def loader(uri):
        from PIL import Image

        if fails["left"] > 0 and uri.endswith("img_2.jpg"):
            fails["left"] -= 1
            raise OSError("simulated flaky storage")
        img = Image.open(uri).convert("RGB").resize((8, 8))
        return np.asarray(img, dtype=np.float32) / 255.0

    rng2 = np.random.default_rng(0)
    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=ModelFunction(
            fn=lambda v, x: jnp.asarray(x).reshape(x.shape[0], -1) @ v["w"],
            variables={"w": rng2.normal(0, 0.01, (192, 2)
                                        ).astype(np.float32)}),
        imageLoader=loader, optimizer="sgd", loss="mse",
        fitParams={"epochs": 3}, batchSize=8)
    model = retry.fit_with_retries(est, df, max_retries=2)
    assert fails["left"] == 0  # the failure DID happen
    assert len(model.trainLosses) == 3


def test_fit_with_retries_resumes_mid_training_from_checkpoint(tmp_path,
                                                               rng):
    """A fit that dies MID-TRAINING (after epoch 2 of 4) is retried and
    RESUMES at the last epoch checkpoint: the retry trains only the
    remaining epochs and the final params match an uninterrupted run."""
    import jax.numpy as jnp
    import optax

    from sparkdl_tpu.parallel.train import fit_data_parallel
    from sparkdl_tpu.utils.metrics import Metrics

    w_true = rng.normal(size=(4, 1)).astype(np.float32)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = x @ w_true

    def predict(p, xb):
        return jnp.asarray(xb) @ p["w"]

    class CrashAfterEpochs(Metrics):
        """Simulated preemption: dies at the end of epoch N, AFTER the
        checkpoint cadence has had its chance to save."""

        def __init__(self, crash_after):
            super().__init__()
            self.crash_after = crash_after
            self.epochs_seen = 0

        def record_time(self, name, value):
            super().record_time(name, value)
            if name == "epoch_loss":
                self.epochs_seen += 1
                if (self.crash_after is not None
                        and self.epochs_seen >= self.crash_after):
                    raise RuntimeError("simulated preemption")

    # NOTE record_time fires before maybe_save in the loop, so a crash
    # "after epoch 2" leaves checkpoints for epochs 1..1 — the retry
    # resumes at epoch 2 and trains epochs 2..4.
    opt = optax.sgd(0.05)
    ck = str(tmp_path / "ck")
    attempts = []

    class _Est:
        """Minimal .fit object for fit_with_retries: first attempt
        crashes after 2 recorded epochs, the retry runs clean."""

        def fit(self, dataset, params=None):
            crash = 2 if not attempts else None
            attempts.append(crash)
            fitted, losses = fit_data_parallel(
                predict, {"w": np.zeros((4, 1), np.float32)}, x, y,
                optimizer=opt, loss="mse", batch_size=8, epochs=4,
                seed=3, checkpoint_dir=ck,
                metrics=CrashAfterEpochs(crash))
            return fitted, losses

    fitted, losses = retry.fit_with_retries(_Est(), None, max_retries=1)
    assert attempts == [2, None]      # crashed once, then retried
    assert len(losses) == 3           # resumed at epoch 2: epochs 2..4 only
    # and the resumed result matches an uninterrupted 4-epoch fit
    full, _ = fit_data_parallel(
        predict, {"w": np.zeros((4, 1), np.float32)}, x, y,
        optimizer=opt, loss="mse", batch_size=8, epochs=4, seed=3)
    np.testing.assert_allclose(np.asarray(fitted["w"]),
                               np.asarray(full["w"]), rtol=1e-5, atol=1e-6)
