"""Numerical sanitizing + retry orchestration (SURVEY.md §5 analogs)."""

import numpy as np
import pytest

from sparkdl_tpu.utils import debug, retry


def test_check_finite_flags_bad_leaves():
    debug.check_finite({"a": np.ones(3), "b": {"c": np.zeros(2)}})  # ok
    with pytest.raises(FloatingPointError, match="non-finite"):
        debug.check_finite({"a": np.asarray([1.0, np.nan])})
    with pytest.raises(FloatingPointError, match="non-finite"):
        debug.check_finite({"w": np.asarray([np.inf], np.float32)})
    # integer leaves can't be non-finite; must not crash
    debug.check_finite({"i": np.asarray([1, 2, 3])})


def test_checks_enabled_env_and_api(monkeypatch):
    monkeypatch.delenv("SPARKDL_DEBUG_NANS", raising=False)
    debug.disable_checks()
    assert not debug.checks_enabled()
    monkeypatch.setenv("SPARKDL_DEBUG_NANS", "1")
    assert debug.checks_enabled()
    monkeypatch.delenv("SPARKDL_DEBUG_NANS")
    debug.enable_checks(nan_debug=False)
    assert debug.checks_enabled()
    debug.disable_checks()


def test_nonfinite_loss_fails_fast_when_enabled(rng):
    import jax.numpy as jnp
    import optax

    from sparkdl_tpu.parallel.train import fit_data_parallel

    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = rng.normal(size=(16, 1)).astype(np.float32)

    def predict(p, xb):
        # divides by ~0 after the first update -> NaN loss
        return jnp.asarray(xb) @ p["w"] / jnp.sum(p["w"]) * jnp.nan

    params = {"w": np.ones((4, 1), np.float32)}
    debug.enable_checks(nan_debug=False)
    try:
        with pytest.raises(FloatingPointError, match="non-finite loss"):
            fit_data_parallel(predict, params, x, y,
                              optimizer=optax.sgd(0.1), loss="mse",
                              batch_size=8, epochs=2)
    finally:
        debug.disable_checks()


def test_with_retries_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("transient")
        return "ok"

    seen = []
    out = retry.with_retries(flaky, max_retries=3,
                             on_retry=lambda i, e: seen.append((i, str(e))))
    assert out == "ok"
    assert len(calls) == 3
    assert seen == [(0, "transient"), (1, "transient")]


def test_with_retries_exhausts_and_raises():
    calls = []

    def always():
        calls.append(1)
        raise RuntimeError("persistent")

    with pytest.raises(RuntimeError, match="persistent"):
        retry.with_retries(always, max_retries=1)
    assert len(calls) == 2  # initial + 1 retry


def test_with_retries_deterministic_failures_not_retried():
    """FloatingPointError (the SPARKDL_DEBUG_NANS fail-fast) and
    validation errors must surface immediately — re-training a diverged
    fit max_retries times defeats the debug flag."""
    calls = []

    def diverged():
        calls.append(1)
        raise FloatingPointError("non-finite loss")

    with pytest.raises(FloatingPointError):
        retry.with_retries(diverged, max_retries=3)
    assert len(calls) == 1
    calls.clear()

    def bad_params():
        calls.append(1)
        raise ValueError("requires params")

    with pytest.raises(ValueError):
        retry.with_retries(bad_params, max_retries=3)
    assert len(calls) == 1


def test_fit_with_retries_resumes_from_checkpoint(tmp_path, fixture_images):
    """A fit that dies mid-run is retried and RESUMES at the last epoch
    checkpoint: the completed run's total trained epochs equal the
    requested count, with the pre-crash epochs not re-trained."""
    from sparkdl_tpu.estimators import ImageFileEstimator
    from sparkdl_tpu.frame import DataFrame
    from sparkdl_tpu.graph.function import ModelFunction

    import jax.numpy as jnp

    paths = fixture_images["paths"] * 4
    labels = [[1.0, 0.0] if i % 2 == 0 else [0.0, 1.0]
              for i in range(len(paths))]
    df = DataFrame({"uri": paths, "label": labels})
    fails = {"left": 1}

    def loader(uri):
        from PIL import Image

        if fails["left"] > 0 and uri.endswith("img_2.jpg"):
            fails["left"] -= 1
            raise OSError("simulated flaky storage")
        img = Image.open(uri).convert("RGB").resize((8, 8))
        return np.asarray(img, dtype=np.float32) / 255.0

    rng2 = np.random.default_rng(0)
    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=ModelFunction(
            fn=lambda v, x: jnp.asarray(x).reshape(x.shape[0], -1) @ v["w"],
            variables={"w": rng2.normal(0, 0.01, (192, 2)
                                        ).astype(np.float32)}),
        imageLoader=loader, optimizer="sgd", loss="mse",
        fitParams={"epochs": 3,
                   "checkpoint_dir": str(tmp_path / "ck")}, batchSize=8)
    model = retry.fit_with_retries(est, df, max_retries=2)
    assert fails["left"] == 0  # the failure DID happen
    assert len(model.trainLosses) == 3
