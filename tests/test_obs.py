"""Observability tests (sparkdl_tpu.obs — ISSUE 3).

Contracts pinned here:
  * the ``SPARKDL_TRACE`` gate and the near-zero DISABLED path (shared
    null-span singleton, empty ring, ``block_until_ready`` pass-through
    that never blocks);
  * END-TO-END NESTING (the acceptance criterion): a CPU-backend run —
    one serving request wave and one ``map_batches`` call — produces a
    valid Chrome-trace JSON whose spans nest serving → batcher →
    engine → pipeline-stage with the child-window-within-parent-window
    invariant;
  * the >= 1.5x overlap contract still holds WITH tracing ON;
  * exporters: Chrome JSON round-trip, span JSONL + ``load_spans`` on
    both artifact forms, Prometheus text exposition, metrics snapshot
    stable schema;
  * ``Metrics``: deterministic timing-vs-histogram percentile lookup
    (the name-collision satellite) and no lost counts / bounded series
    under concurrent writers (admission + dispatch + stage threads);
  * slow-request exemplars (top-K span trees) and ``Server.varz``;
  * ``tools/trace_summary.py`` folds both artifact forms;
  * ``bench.py`` per-config lines carry a FRESH metrics snapshot and a
    trace artifact path.
"""

import json
import logging
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from sparkdl_tpu import obs
from sparkdl_tpu.obs.trace import NULL_SPAN
from sparkdl_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _restore_tracer():
    """Every test leaves the process tracer the way the environment
    configures it (disabled in the test env)."""
    yield
    obs.configure_from_env()


def _fn(variables, x):
    import jax.numpy as jnp

    return jnp.tanh(x @ variables["w"])


@pytest.fixture(scope="module")
def model():
    rng = np.random.default_rng(7)
    return {"w": rng.normal(size=(12, 5)).astype(np.float32)}, \
        rng.normal(size=(50, 12)).astype(np.float32)


def _assert_child_within_parent(spans):
    """THE nesting invariant: every recorded child's [start, end] window
    sits inside its parent's (1 us epsilon for rounding)."""
    by_id = {s["span_id"]: s for s in spans}
    checked = 0
    for s in spans:
        p = by_id.get(s["parent_id"])
        if p is None:
            continue
        assert p["ts_us"] - 1 <= s["ts_us"], (s, p)
        assert (s["ts_us"] + s["dur_us"]
                <= p["ts_us"] + p["dur_us"] + 1), (s, p)
        checked += 1
    return checked


def _chains(spans, leaf_name):
    """Name paths leaf -> root for every span named ``leaf_name``."""
    by_id = {s["span_id"]: s for s in spans}
    out = []
    for s in spans:
        if s["name"] != leaf_name:
            continue
        path, cur = [], s
        while cur is not None:
            path.append(cur["name"])
            cur = by_id.get(cur["parent_id"])
        out.append(tuple(path))
    return out


# -- gate + disabled path --------------------------------------------------

def test_trace_env_gate(monkeypatch):
    from sparkdl_tpu.obs.trace import tracing_from_env

    for off in ("", "0", "false", "OFF", "no"):
        monkeypatch.setenv("SPARKDL_TRACE", off)
        assert tracing_from_env() == (False, None)
    for on in ("1", "true", "ON", "yes"):
        monkeypatch.setenv("SPARKDL_TRACE", on)
        assert tracing_from_env() == (True, None)
    monkeypatch.setenv("SPARKDL_TRACE", "/tmp/some/dir")
    assert tracing_from_env() == (True, "/tmp/some/dir")
    monkeypatch.delenv("SPARKDL_TRACE", raising=False)
    assert tracing_from_env() == (False, None)


def test_disabled_path_is_null_and_recordless():
    tracer = obs.configure(enabled=False)
    sp = tracer.span("anything", rows=3)
    assert sp is NULL_SPAN                      # one shared no-op object
    assert tracer.start_span("x") is NULL_SPAN
    with sp as inner:
        assert inner is NULL_SPAN
        inner.annotate(k=1)
        marker = object()
        # never blocks, never touches jax — returns the value untouched
        assert inner.block_until_ready(marker) is marker
    sp.finish()
    assert len(tracer) == 0 and tracer.snapshot() == []
    assert obs.current_trace_id() is None


def test_disabled_span_calls_are_cheap():
    """~50k disabled instrumentation hits in well under a second — an
    ultra-generous 20 us/call budget that still catches accidental
    O(ring) or locking work sneaking onto the disabled path."""
    import time

    tracer = obs.configure(enabled=False)
    t0 = time.perf_counter()
    for _ in range(50_000):
        with tracer.span("hot"):
            pass
    assert time.perf_counter() - t0 < 1.0


# -- span mechanics --------------------------------------------------------

def test_span_nesting_ids_ring_and_clear():
    tracer = obs.configure(enabled=True)
    with tracer.span("outer", a=1) as outer:
        assert tracer.current() is outer
        assert obs.current_trace_id() == outer.trace_id
        with tracer.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    assert tracer.current() is None
    spans = tracer.snapshot()
    assert [s["name"] for s in spans] == ["inner", "outer"]  # finish order
    assert spans[1]["attrs"] == {"a": 1}
    assert spans[0]["parent_id"] == spans[1]["span_id"]
    assert _assert_child_within_parent(spans) == 1
    tracer.clear()
    assert tracer.snapshot() == []


def test_ring_is_bounded():
    tracer = obs.configure(enabled=True, capacity=8)
    for i in range(30):
        with tracer.span("s", i=i):
            pass
    spans = tracer.snapshot()
    assert len(spans) == 8
    assert [s["attrs"]["i"] for s in spans] == list(range(22, 30))


def test_cross_thread_start_span_and_use():
    """start_span + use: the cross-thread continuation pattern serving
    uses (request opened on the caller thread, children created on a
    worker)."""
    tracer = obs.configure(enabled=True)
    root = tracer.start_span("root")
    seen = {}

    def worker():
        with tracer.use(root):
            with tracer.span("child") as c:
                seen["trace"] = c.trace_id
                seen["parent"] = c.parent_id

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    root.finish()
    root.finish("error")  # idempotent: second finish is a no-op
    assert seen["trace"] == root.trace_id
    assert seen["parent"] == root.span_id
    spans = tracer.snapshot()
    assert [s["name"] for s in spans] == ["child", "root"]
    assert spans[1]["status"] == "ok"


def test_error_exit_marks_status():
    tracer = obs.configure(enabled=True)
    with pytest.raises(RuntimeError):
        with tracer.span("boom"):
            raise RuntimeError("x")
    assert tracer.snapshot()[0]["status"] == "error"


def test_snapshot_while_recording_never_raises():
    """Readers (exemplar capture, /varz scrapes) snapshot the ring while
    worker threads record spans: a bare deque iteration would raise
    'deque mutated during iteration' — the ring lock must prevent it."""
    tracer = obs.configure(enabled=True, capacity=256)
    stop = threading.Event()
    errors = []

    def writer():
        try:
            while not stop.is_set():
                with tracer.span("w"):
                    pass
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        try:
            for _ in range(200):
                tracer.snapshot()
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    writers = [threading.Thread(target=writer) for _ in range(3)]
    readers = [threading.Thread(target=reader) for _ in range(2)]
    for t in writers + readers:
        t.start()
    for t in readers:
        t.join()
    stop.set()
    for t in writers:
        t.join()
    assert not errors, errors
    assert len(tracer.snapshot()) == 256  # ring stayed bounded


# -- engine / pipeline nesting ---------------------------------------------

def test_map_batches_trace_nests_pipeline_stages(model):
    """map_batches(pipeline=True): engine.dispatch nests under the
    pipeline.dispatch stage span, stages nest under pipeline.run, one
    dispatch/gather span per piece, and the gather spans carry the
    block_until_ready-bracketed device split."""
    from sparkdl_tpu.parallel.engine import InferenceEngine

    variables, x = model
    eng = InferenceEngine(_fn, variables, device_batch_size=8)
    obs.configure(enabled=True)
    list(eng.map_batches([x], pipeline=True))
    spans = obs.get_tracer().snapshot()
    names = [s["name"] for s in spans]
    n_pieces = 7  # ceil(50/8)
    assert names.count("pipeline.dispatch") == n_pieces
    assert names.count("pipeline.gather") == n_pieces
    assert names.count("pipeline.run") == 1
    assert names.count("engine.dispatch") == n_pieces
    assert _chains(spans, "engine.dispatch") == \
        [("engine.dispatch", "pipeline.dispatch", "pipeline.run")] * n_pieces
    assert _chains(spans, "pipeline.gather") == \
        [("pipeline.gather", "pipeline.run")] * n_pieces
    assert _assert_child_within_parent(spans) >= 3 * n_pieces
    gathers = [s for s in spans if s["name"] == "pipeline.gather"]
    assert all("device_us" in s for s in gathers)


def test_pipeline_outputs_identical_with_tracing_on(model):
    """Tracing must be an observer: pipelined outputs with tracing ON
    are byte-identical to the untraced run."""
    from sparkdl_tpu.parallel.engine import InferenceEngine

    variables, x = model
    eng = InferenceEngine(_fn, variables, device_batch_size=8)
    obs.configure(enabled=False)
    ref = list(eng.map_batches([x], pipeline=True))
    obs.configure(enabled=True)
    traced = list(eng.map_batches([x], pipeline=True))
    assert len(ref) == len(traced)
    for a, b in zip(ref, traced):
        np.testing.assert_array_equal(a, b)


def test_overlap_contract_holds_with_tracing_on():
    """The tier-1 >= 1.5x synthetic-slow-device contract must survive
    tracing ON (the run-tests.sh guard asserts the same plus the
    disabled-path factor)."""
    from sparkdl_tpu.parallel.pipeline import synthetic_overlap_benchmark

    obs.configure(enabled=True)
    result = synthetic_overlap_benchmark()
    assert result["speedup"] >= 1.5, result
    spans = obs.get_tracer().snapshot()
    assert any(s["name"] == "pipeline.run" for s in spans)


# -- THE acceptance test: end-to-end nesting + valid Chrome trace ----------

def test_end_to_end_trace_nesting_and_chrome_json(model, tmp_path):
    """CPU-backend end-to-end run (a serving request wave AND a
    map_batches call) -> valid Chrome-trace JSON whose spans nest
    serving.request -> serving.microbatch -> engine.call ->
    engine.dispatch and pipeline.run -> pipeline.<stage>, with
    non-overlapping child/parent window invariants throughout."""
    from sparkdl_tpu.parallel.engine import InferenceEngine
    from sparkdl_tpu.serving import Server

    variables, x = model
    obs.configure(enabled=True)

    # online: one wave of single-example requests
    with Server(_fn, dict(variables), max_batch_size=8,
                max_wait_ms=2.0) as srv:
        futs = [srv.submit(row) for row in x[:20]]
        for f in futs:
            f.result()
    # offline: one pipelined map_batches call
    eng = InferenceEngine(_fn, variables, device_batch_size=8)
    list(eng.map_batches([x], pipeline=True))

    tracer = obs.get_tracer()
    spans = tracer.snapshot()
    names = {s["name"] for s in spans}
    assert {"serving.request", "serving.microbatch", "engine.call",
            "engine.dispatch", "pipeline.run", "pipeline.dispatch",
            "pipeline.gather"} <= names
    # every request span is a trace ROOT; every microbatch adopts the
    # first live member's trace
    reqs = [s for s in spans if s["name"] == "serving.request"]
    assert len(reqs) == 20 and all(s["parent_id"] is None for s in reqs)
    req_traces = {s["trace_id"] for s in reqs}
    batches = [s for s in spans if s["name"] == "serving.microbatch"]
    assert batches and all(s["trace_id"] in req_traces for s in batches)
    assert all(s["attrs"]["batch_size"] >= 1 for s in batches)
    # the serving chain, leaf to root
    serving_chains = [c for c in _chains(spans, "engine.dispatch")
                      if "serving.microbatch" in c]
    assert serving_chains and all(
        c == ("engine.dispatch", "engine.call", "serving.microbatch",
              "serving.request") for c in serving_chains)
    assert _assert_child_within_parent(spans) >= len(serving_chains)

    # valid Chrome trace JSON: round-trips through disk, every complete
    # event has the required fields, and span lineage rides args
    path = tmp_path / "trace.json"
    obs.write_chrome_trace(str(path), spans)
    doc = json.loads(path.read_text())
    events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert len(events) == len(spans)
    for e in events:
        assert e["name"] and "ts" in e and "dur" in e and e["dur"] >= 0
        assert "trace_id" in e["args"] and "span_id" in e["args"]
    # and the artifact reloads as spans (the trace_summary input path)
    assert len(obs.load_spans(str(path))) == len(spans)


def test_shed_request_span_records_shed_status():
    from sparkdl_tpu.serving.batcher import DynamicBatcher, Request
    from sparkdl_tpu.serving.errors import DeadlineExceededError

    tracer = obs.configure(enabled=True)
    b = DynamicBatcher(max_batch_size=4, max_wait_ms=1.0)
    r = Request(np.zeros(3), deadline=-1.0)  # already expired
    r.span = tracer.start_span("serving.request")
    b.submit(r)
    batch = b.next_batch()
    assert batch == []
    with pytest.raises(DeadlineExceededError):
        r.future.result(timeout=1)
    spans = tracer.snapshot()
    assert [s["status"] for s in spans
            if s["name"] == "serving.request"] == ["shed"]


# -- exemplars + varz ------------------------------------------------------

def test_exemplar_reservoir_keeps_top_k():
    from sparkdl_tpu.obs.exemplar import ExemplarReservoir

    tracer = obs.configure(enabled=True)
    res = ExemplarReservoir(k=2)
    # admission is against the CURRENT floor: 0.02 evicts 0.01 when it
    # arrives; only the final 0.04 (floor already 0.05) is rejected
    for i, dur in enumerate([0.01, 0.05, 0.02, 0.30, 0.04]):
        with tracer.span("serving.request") as sp:
            tid = sp.trace_id
        assert res.offer(dur, tid, tracer) == (dur != 0.04)
    snap = res.snapshot()
    assert [e["duration_ms"] for e in snap] == [300.0, 50.0]
    assert all(e["spans"] for e in snap)  # full span tree captured
    # inert while tracing is disabled
    res2 = ExemplarReservoir(k=2)
    assert not res2.offer(9.9, "t1", obs.configure(enabled=False))
    assert res2.snapshot() == []


def test_server_varz_structured_form(model):
    from sparkdl_tpu.serving import Server

    variables, x = model
    obs.configure(enabled=True)
    with Server(_fn, dict(variables), max_batch_size=8,
                max_wait_ms=2.0) as srv:
        for f in [srv.submit(row) for row in x[:16]]:
            f.result()
        v = srv.varz()
    json.dumps(v)  # the monitoring endpoint body must serialize
    assert v["server"]["max_batch_size"] == 8
    assert v["counters"]["serving.requests"] == 16
    assert v["counters"]["serving.completed"] == 16
    assert v["latency_ms"]["request"]["p99_ms"] >= \
        v["latency_ms"]["request"]["p50_ms"] > 0
    assert v["metrics"]["counters"]["serving.batches"] >= 1
    assert v["exemplars"], "tracing was on: slow-request exemplars expected"
    ex = v["exemplars"][0]
    assert ex["duration_ms"] > 0 and ex["trace_id"]
    assert any(s["name"] == "serving.request" for s in ex["spans"])
    # flat stats() keeps working alongside the structured form
    assert srv.stats()["serving.requests"] == 16


# -- metrics satellites ----------------------------------------------------

def test_percentile_name_collision_is_deterministic():
    m = Metrics()
    m.observe("x", 5.0)             # histogram "x"
    m.timings_s.setdefault("x", [])  # EMPTY timing series, same name
    # timings win even when empty (the or-short-circuit used to fall
    # through to the histogram, flipping family with buffer occupancy)
    assert m.percentile("x", 50) is None
    assert m.percentile("x", 50, kind="histogram") == 5.0
    m.record_time("x", 2.0)
    assert m.percentile("x", 50) == 2.0
    assert m.percentile("x", 50, kind="timing") == 2.0
    assert m.percentile("x", 50, kind="histogram") == 5.0
    assert m.percentile("absent", 99) is None
    with pytest.raises(ValueError, match="kind"):
        m.percentile("x", 50, kind="bogus")


def test_metrics_concurrent_writers_no_lost_counts():
    """Admission thread + dispatch workers + pipeline stages hammer ONE
    registry: counters must be exact (no lost increments) and every
    series must stay within the max_samples bound."""
    m = Metrics(max_samples=256)
    n_threads, n_iters = 8, 2000
    barrier = threading.Barrier(n_threads)
    errors = []

    def hammer(tid):
        try:
            barrier.wait()
            for i in range(n_iters):
                m.incr("shared.count")
                m.incr(f"worker.{tid}", 2.0)
                m.record_time("shared.latency", i * 1e-6)
                m.observe("shared.depth", float(i % 7))
                m.gauge("shared.gauge", float(i))
                if i % 100 == 0:
                    m.percentile("shared.latency", 99)  # reader in the mix
                    m.summary()
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert m.counters["shared.count"] == n_threads * n_iters
    for t in range(n_threads):
        assert m.counters[f"worker.{t}"] == 2.0 * n_iters
    raw = m.snapshot_raw()
    assert 0 < len(raw["timings_s"]["shared.latency"]) <= 256
    assert 0 < len(raw["histograms"]["shared.depth"]) <= 256
    json.dumps(obs.metrics_snapshot(m))  # snapshot stays serializable


# -- exporters -------------------------------------------------------------

def _seeded_metrics():
    m = Metrics()
    m.incr("serving.requests", 3)
    m.gauge("queue.depth", 2.0)
    for v in (0.010, 0.020, 0.030):
        m.record_time("request_latency", v)
    m.observe("fill-ratio", 0.5)
    return m


def test_metrics_snapshot_stable_schema():
    snap = obs.metrics_snapshot(_seeded_metrics())
    assert set(snap) == {"counters", "gauges", "timings_s", "histograms"}
    t = snap["timings_s"]["request_latency"]
    assert set(t) == {"count", "total_s", "mean_s", "p50_s", "p99_s"}
    assert t["count"] == 3 and t["p50_s"] == 0.02 and t["p99_s"] == 0.03
    h = snap["histograms"]["fill-ratio"]
    assert set(h) == {"count", "mean", "p50", "p99"}
    assert snap["counters"]["serving.requests"] == 3
    assert snap["gauges"]["queue.depth"] == 2.0


def test_prometheus_text_exposition():
    text = obs.prometheus_text(_seeded_metrics())
    assert "# TYPE sparkdl_serving_requests_total counter" in text
    assert "sparkdl_serving_requests_total 3" in text
    assert "# TYPE sparkdl_queue_depth gauge" in text
    assert "# TYPE sparkdl_request_latency_seconds summary" in text
    assert 'sparkdl_request_latency_seconds{quantile="0.99"} 0.03' in text
    assert "sparkdl_request_latency_seconds_count 3" in text
    assert "sparkdl_fill_ratio" in text  # '-' sanitized to '_'
    assert text.endswith("\n")


def test_metrics_jsonl_appends(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    m = _seeded_metrics()
    obs.write_metrics_jsonl(path, m, extra={"config": "a"})
    obs.write_metrics_jsonl(path, m, extra={"config": "b"})
    lines = [json.loads(line)
             for line in open(path).read().strip().splitlines()]
    assert [r["config"] for r in lines] == ["a", "b"]
    assert all(r["ts"] and r["counters"]["serving.requests"] == 3
               for r in lines)


def test_spans_jsonl_roundtrip(tmp_path):
    tracer = obs.configure(enabled=True)
    with tracer.span("a"):
        with tracer.span("b"):
            pass
    spans = tracer.snapshot()
    path = str(tmp_path / "spans.jsonl")
    obs.write_spans_jsonl(path, spans)
    assert obs.load_spans(path) == spans


def test_tracer_flush_writes_both_artifacts(tmp_path):
    tracer = obs.configure(enabled=True, out_dir=str(tmp_path / "td"))
    with tracer.span("a"):
        pass
    paths = tracer.flush()
    assert len(paths) == 2
    chrome = [p for p in paths if p.endswith(".json")][0]
    assert json.loads(open(chrome).read())["traceEvents"]
    jsonl = [p for p in paths if p.endswith(".jsonl")][0]
    assert obs.load_spans(jsonl)[0]["name"] == "a"
    # the DIRECTORY itself loads too — the trace_artifact shape bench
    # emits for subprocess configs folds without naming a file
    assert obs.load_spans(str(tmp_path / "td"))[0]["name"] == "a"
    # empty ring / no dir -> no files, no error
    tracer.clear()
    assert tracer.flush() == []


# -- trace-id-aware logs ---------------------------------------------------

def test_log_records_carry_current_trace_id():
    from sparkdl_tpu.utils.logging import _TraceContextFilter

    f = _TraceContextFilter()

    def record():
        return logging.LogRecord("sparkdl_tpu.x", logging.INFO, "f", 1,
                                 "msg", None, None)

    obs.configure(enabled=False)
    r = record()
    assert f.filter(r) and r.trace == ""
    tracer = obs.configure(enabled=True)
    with tracer.span("op") as sp:
        r = record()
        assert f.filter(r) and r.trace == f" trace={sp.trace_id}"
    r = record()
    assert f.filter(r) and r.trace == ""  # outside any span again


# -- trace_summary CLI -----------------------------------------------------

def test_trace_summary_cli_folds_both_forms(tmp_path):
    tracer = obs.configure(enabled=True)
    with tracer.span("pipeline.run"):
        for _ in range(3):
            with tracer.span("pipeline.prepare"):
                pass
    spans = tracer.snapshot()
    jsonl = str(tmp_path / "spans.jsonl")
    chrome = str(tmp_path / "trace.json")
    obs.write_spans_jsonl(jsonl, spans)
    obs.write_chrome_trace(chrome, spans)
    flushdir = str(tmp_path / "flushed")
    os.makedirs(flushdir)
    obs.write_spans_jsonl(os.path.join(flushdir, "spans_1.jsonl"), spans)
    tool = os.path.join(REPO, "tools", "trace_summary.py")
    for src, extra in ((jsonl, []),
                       (flushdir, []),  # directory-form trace_artifact
                       (chrome, ["--wall-span", "pipeline.run"])):
        out = subprocess.run(
            [sys.executable, tool, src, *extra],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "| stage |" in out.stdout
        assert "pipeline.prepare | 3 |" in out.stdout
        assert "wall:" in out.stdout


# -- bench integration -----------------------------------------------------

def test_bench_lines_carry_fresh_snapshot_and_trace_artifact(tmp_path,
                                                             monkeypatch):
    """Driver-record contract: each per-config line carries THAT
    config's metrics snapshot (fresh registry — no accumulation from
    earlier configs) and a trace artifact path that exists and loads."""
    import bench

    lines = []
    monkeypatch.setattr(bench, "_print_line",
                        lambda s: lines.append(json.loads(s)))
    monkeypatch.setattr(bench, "_LINES", {})
    monkeypatch.setattr(bench, "RELAY", {})
    monkeypatch.setattr(bench, "TRACE_DIR", str(tmp_path))
    monkeypatch.setattr(bench, "BENCH_TRACE", True)
    monkeypatch.setattr(
        bench, "measure_relay_profile",
        lambda timeout_s=240: {"dispatch_ms": 1.0, "h2d_MBps": 2.0,
                               "d2h_MBps": 3.0})
    monkeypatch.setattr(bench, "RELAY_CACHE_PATH",
                        str(tmp_path / "lg.json"))

    def fake_config(key):
        def run():
            m = bench._config_metrics()
            m.incr(f"{key}.work")
            with obs.get_tracer().span(f"{key}.stage"):
                pass
            bench.emit(key, "fake metric", 1.0, "units")
        return run

    monkeypatch.setitem(bench.BENCHES, "fakeA", fake_config("fakeA"))
    monkeypatch.setitem(bench.BENCHES, "fakeB", fake_config("fakeB"))
    monkeypatch.setenv("SPARKDL_BENCH_CONFIGS", "fakeA,fakeB")
    bench.main()

    by_config = {r["config"]: r for r in lines if "metric" in r}
    for key, other in (("fakeA", "fakeB"), ("fakeB", "fakeA")):
        rec = by_config[key]
        snap = rec["metrics_snapshot"]
        assert snap["counters"] == {f"{key}.work": 1.0}, \
            f"{other} leaked into {key}'s snapshot"
        path = rec["trace_artifact"]
        assert path.endswith(f"trace_{key}.json")
        assert os.path.exists(path)
        loaded = obs.load_spans(path)
        assert [s["name"] for s in loaded] == [f"{key}.stage"]
    # main() restored the env-configured tracer (disabled in tests)
    assert not obs.get_tracer().enabled
