"""Traffic-twin tests (ISSUE 16): virtual time, seeded days, closed-loop
control, HBM-aware placement.

Pins the subsystem's contracts:

* ``VirtualClock``: starts at scenario time zero, only moves on
  ``advance``, never backward;
* clock injection (satellite 1): an ``AdmissionController`` bucket
  refills ONLY when virtual time moves; a ``Server`` holds a parked
  request under a frozen clock and flushes after ``advance + wake``;
  an ``SLOEngine`` burn window is a virtual-time window;
* scenario: one seed -> byte-identical arrival arrays, flash-crowd
  uplift, retry feedback, the hard per-tick clip;
* the headline determinism bar: two full simulated days against a REAL
  fleet produce byte-identical event sequences, decisions, and scores;
* closed loop (a): the quota autoscaler beats the static baseline on
  SLO-minutes (and goodput) through a flash crowd + retry storm;
* closed loop (b): the placement planner respects the per-chip HBM
  budget — re-verified here through ``param_sharding_stats``, not the
  planner's own claim — shards only when replication cannot fit, and
  raises loudly on infeasible demands;
* chaos: ``twin.arrival`` error rules drop arrivals at the door and are
  scored; ``twin.tick`` sleep rules must not move an event byte;
* incident rendering: a simulated day's flight events fold through
  ``tools/blackbox.py`` into a clean timeline.

Tier-1 scenarios are deliberately tiny (a dozen ticks, ~1-5k virtual
requests, seconds of wall time); the canonical 288-tick day rides the
``slow`` marker and the run-tests.sh twin stage's speed guard.
"""

import json
import os

import numpy as np
import pytest

from sparkdl_tpu import faults
from sparkdl_tpu.faults import FaultPlan
from sparkdl_tpu.faults.sites import SITE_HELP, validate_site
from sparkdl_tpu.obs import flight
from sparkdl_tpu.obs.slo import SLO, SLOEngine
from sparkdl_tpu.parallel.mesh import param_sharding_stats
from sparkdl_tpu.serving import Server, TenantQuota
from sparkdl_tpu.serving.errors import QuotaExceededError
from sparkdl_tpu.serving.fleet.admission import AdmissionController
from sparkdl_tpu.twin import (MeshSlice, PlacementError, QuotaAutoscaler,
                              Scenario, ScenarioConfig, StaticPolicy,
                              TrafficTwin, VirtualClock, plan_placement,
                              run_day)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: tight quota (refill 45 tokens / 300 s tick) — makes the tiny
#: flash crowd shed hard, which is the whole policy story
TIGHT_QUOTA = TenantQuota(rate_per_s=0.15, burst=60)


def _small_cfg(**kw):
    base = dict(seed=5, ticks=12, tenants=16,
                mean_arrivals_per_tick=60.0, flash_start=4, flash_end=8,
                flash_tenants=4, canary_tick=2, stream_every=5,
                digest_universe=64)
    base.update(kw)
    return ScenarioConfig(**base)


@pytest.fixture(autouse=True)
def _restore_flight():
    yield
    r = flight.get_recorder()
    if r is not None:
        r.close()
    flight.configure_from_env()


def _fn(variables, x):
    import jax.numpy as jnp

    return jnp.tanh(x @ variables["w"])


# -- virtual clock ----------------------------------------------------------

def test_virtual_clock_contract():
    clock = VirtualClock()
    assert clock() == 0.0 and clock.now == 0.0
    assert clock.advance(2.5) == 2.5
    assert clock() == 2.5
    clock.advance(0.0)  # zero advance is legal (a no-op tick)
    with pytest.raises(ValueError, match="backward"):
        clock.advance(-0.1)
    assert clock.now == 2.5


# -- satellite 1: clock injection -------------------------------------------

def test_admission_bucket_refills_on_virtual_time_only():
    clock = VirtualClock()
    ctrl = AdmissionController(
        default_quota=TenantQuota(rate_per_s=1.0, burst=2), clock=clock)
    for _ in range(2):
        ctrl.admit("t")
        ctrl.release("t")
    # bucket empty and the clock frozen: NO amount of wall time refills
    with pytest.raises(QuotaExceededError):
        ctrl.admit("t")
    clock.advance(1.0)  # one virtual second = one token
    ctrl.admit("t")
    ctrl.release("t")
    with pytest.raises(QuotaExceededError):
        ctrl.admit("t")


def test_server_flush_waits_for_virtual_time(rng):
    clock = VirtualClock()
    w = {"w": rng.normal(size=(6, 6)).astype(np.float32)}
    x = rng.normal(size=(6,)).astype(np.float32)
    with Server(_fn, w, max_batch_size=8, max_wait_ms=5_000.0,
                bucket_sizes=[8], clock=clock) as srv:
        fut = srv.submit(x)
        # under a frozen clock the 5-virtual-second wait window never
        # elapses, no matter how much wall time passes
        with pytest.raises(Exception):
            fut.result(timeout=0.3)
        clock.advance(10.0)
        srv.wake()
        y = fut.result(timeout=30)
        assert np.asarray(y).shape == (6,)


def test_slo_engine_windows_ride_virtual_time():
    from sparkdl_tpu.utils.metrics import Metrics

    clock = VirtualClock()
    m = Metrics()
    eng = SLOEngine(
        m, [SLO("avail", "availability", good="g", total="t",
                objective=0.999)], clock=clock)
    m.incr("g", 100)
    m.incr("t", 100)
    eng.evaluate()
    clock.advance(300.0)
    m.incr("g", 50)
    m.incr("t", 100)  # 50% bad over the last virtual window
    snap = eng.evaluate()
    st = snap["objectives"][0]
    assert snap["state"] == "breach"
    assert st["burn_short"] > st["burn_threshold"]
    # recovery is also a virtual-time fact
    clock.advance(300.0)
    m.incr("g", 100)
    m.incr("t", 100)
    assert eng.evaluate()["state"] == "ok"


# -- scenario ---------------------------------------------------------------

def test_scenario_seeded_and_shaped():
    cfg = _small_cfg()
    a, b = Scenario(cfg), Scenario(cfg)
    total = 0
    for tick in range(cfg.ticks):
        arr_a = a.arrivals(tick)
        arr_b = b.arrivals(tick)
        for f in ("tenant", "model", "digest", "retry"):
            np.testing.assert_array_equal(getattr(arr_a, f),
                                          getattr(arr_b, f))
        assert len(arr_a) <= cfg.max_arrivals_per_tick
        assert arr_a.tenant.max(initial=0) < cfg.tenants
        assert arr_a.digest.max(initial=0) < cfg.digest_universe
        total += len(arr_a)
    assert total > 0
    np.testing.assert_array_equal(a.payloads, b.payloads)
    # flash ticks carry the crowd
    steady = len(a.arrivals(1))
    flash = len(a.arrivals(cfg.flash_start))
    assert flash > 2 * steady
    assert a.phase(cfg.flash_start) == "flash_crowd"
    # retry feedback adds re-presented traffic, flagged as such
    with_retries = a.arrivals(1, retry_counts={0: 40})
    assert with_retries.retry.sum() > 0
    assert len(with_retries) > steady


def test_scenario_clip_is_deterministic():
    cfg = _small_cfg(max_arrivals_per_tick=50,
                     mean_arrivals_per_tick=200.0)
    s = Scenario(cfg)
    arr = s.arrivals(1)
    assert len(arr) == 50 and arr.clipped > 0
    arr2 = Scenario(cfg).arrivals(1)
    np.testing.assert_array_equal(arr.tenant, arr2.tenant)
    assert arr.clipped == arr2.clipped


# -- the headline bar: byte-identical days ----------------------------------

def test_two_runs_byte_identical_events_decisions_scores():
    cfg = _small_cfg()
    r1 = run_day(cfg, policy=StaticPolicy(), default_quota=TIGHT_QUOTA)
    r2 = run_day(cfg, policy=StaticPolicy(), default_quota=TIGHT_QUOTA)
    assert r1.event_lines == r2.event_lines
    assert r1.event_digest == r2.event_digest
    assert r1.scores == r2.scores
    assert len(r1.event_lines) == cfg.ticks
    # the day did real work against the real fleet
    assert r1.scores["offered"] > 500
    assert r1.scores["stream_commits"] > 0
    assert r1.scores["cache_hit_rate"] > 0.1  # Zipf content hit the cache
    assert r1.scores["tenants_active"] == cfg.tenants
    # event lines are canonical JSON with the scored fields
    doc = json.loads(r1.event_lines[-1])
    for key in ("tick", "vt", "phase", "slo", "decision",
                "cache_hits_coalesced_total"):
        assert key in doc
    # virtual timestamps are scenario-relative and tick-spaced
    assert json.loads(r1.event_lines[0])["vt"] == cfg.tick_s
    assert doc["vt"] == cfg.ticks * cfg.tick_s


def test_adaptive_run_deterministic_with_decisions():
    cfg = _small_cfg()
    mk = lambda: QuotaAutoscaler(TIGHT_QUOTA)  # noqa: E731
    r1 = run_day(cfg, policy=mk(), default_quota=TIGHT_QUOTA)
    r2 = run_day(cfg, policy=mk(), default_quota=TIGHT_QUOTA)
    assert r1.event_lines == r2.event_lines
    assert r1.scores == r2.scores
    # the autoscaler actually decided things (quota raises + canary)
    levers = [a["lever"] for line in r1.event_lines
              for a in json.loads(line)["decision"]]
    assert "quota" in levers
    assert "canary" in levers


# -- closed loop (a): policy beats static -----------------------------------

def test_policy_beats_static_through_flash_crowd():
    cfg = _small_cfg(ticks=16)
    rs = run_day(cfg, policy=StaticPolicy(), default_quota=TIGHT_QUOTA)
    ra = run_day(cfg, policy=QuotaAutoscaler(TIGHT_QUOTA),
                 default_quota=TIGHT_QUOTA)
    # the flash crowd must actually burn the static baseline, or the
    # comparison is vacuous
    assert rs.scores["slo_minutes"] > 0
    assert rs.scores["shed"] > 0
    assert ra.scores["slo_minutes"] < rs.scores["slo_minutes"]
    assert ra.scores["goodput"] > rs.scores["goodput"]
    assert ra.scores["fairness"] >= rs.scores["fairness"]


# -- closed loop (b): placement ---------------------------------------------

def _entries(leaf_shapes):
    """name -> param dict; keys matter: the default partition rules
    only split leaves named ``kernel``/``embedding``."""
    rng = np.random.default_rng(0)
    return {name: {leaf: rng.normal(size=s).astype(np.float32)
                   for leaf, s in shapes.items()}
            for name, shapes in leaf_shapes.items()}


def test_placement_respects_hbm_budget_via_stats():
    entries = _entries({
        "big": {"kernel": (256, 256), "bias": (256,)},  # 256 KiB + bias
        "small": {"kernel": (32, 32)},
    })
    chip = 200 * 1024
    plan = plan_placement(entries, chip_hbm_bytes=chip,
                          total_chip_budget=16)
    usable = plan.usable_hbm_bytes
    assert usable == int(chip * 0.75)
    for p in plan.placements:
        # re-verify against param_sharding_stats on the SAME geometry,
        # not the planner's own bookkeeping
        mesh = MeshSlice(data=1, model=p.model_parallel)
        stats = param_sharding_stats(mesh, entries[p.model])
        assert p.stats["param_bytes_per_chip"] <= usable
        assert p.stats["param_bytes_total"] == stats["param_bytes_total"]
    by_name = {p.model: p for p in plan.placements}
    # 256 KiB replicated > 150 KiB usable -> the big model must shard
    assert by_name["big"].model_parallel > 1
    assert not by_name["big"].replicated
    assert by_name["big"].partition_digest != "replicated"
    # the small model replicates on one chip (the classic cheap layout)
    assert by_name["small"].model_parallel == 1
    assert by_name["small"].replicated
    assert plan.chips_used <= plan.total_chip_budget
    # plan digest is a deterministic content address
    plan2 = plan_placement(entries, chip_hbm_bytes=chip,
                           total_chip_budget=16)
    assert plan.digest() == plan2.digest()
    json.dumps(plan.as_dict())


def test_placement_infeasible_raises():
    # odd last dim: the divisibility rule can never split it
    entries = _entries({"huge": {"kernel": (512, 513)}})
    with pytest.raises(PlacementError, match="fits no allowed slice"):
        plan_placement(entries, chip_hbm_bytes=64 * 1024,
                       total_chip_budget=64)
    # feasible per model but over the chip budget
    many = _entries({f"m{i}": {"kernel": (256, 256)} for i in range(4)})
    with pytest.raises(PlacementError, match="budget"):
        plan_placement(many, chip_hbm_bytes=200 * 1024,
                       total_chip_budget=2, slice_chips=(2, 4))
    with pytest.raises(ValueError):
        plan_placement({}, chip_hbm_bytes=1, total_chip_budget=1)
    with pytest.raises(ValueError):
        plan_placement(entries, chip_hbm_bytes=1, total_chip_budget=1,
                       reserve_fraction=1.5)


def test_mesh_slice_matches_helper_surface():
    s = MeshSlice(data=2, model=4)
    assert s.chips == 8
    assert s.shape["model"] == 4 and s.axis_names == ("data", "model")
    with pytest.raises(ValueError):
        MeshSlice(data=0)


# -- satellite 2: registries ------------------------------------------------

def test_twin_sites_and_events_registered():
    assert validate_site("twin.tick") == "twin.tick"
    assert validate_site("twin.arrival") == "twin.arrival"
    for site in ("twin.tick", "twin.arrival"):
        assert SITE_HELP[site]
    for ev in ("twin.scenario", "policy.adjust", "placement.plan"):
        assert flight.validate_event(ev) == ev


# -- chaos ------------------------------------------------------------------

def test_twin_arrival_fault_drops_are_scored():
    cfg = _small_cfg(ticks=6, canary_tick=None, stream_every=0)
    plan = FaultPlan.parse("seed=1;twin.arrival:error:exc=transient,"
                           "every=25")
    with faults.active(plan):
        r = run_day(cfg, policy=StaticPolicy())
    assert r.scores["fault_drops"] > 0
    # stream off: every offered arrival was either admitted or shed,
    # and a dropped arrival counts as a shed (it feeds the retry storm)
    assert (r.scores["offered"]
            == r.scores["submitted"] + r.scores["shed"])
    assert r.scores["shed"] >= r.scores["fault_drops"]


def test_twin_tick_sleep_rule_does_not_move_an_event_byte():
    cfg = _small_cfg(ticks=6, canary_tick=None, stream_every=0)
    r_clean = run_day(cfg, policy=StaticPolicy(),
                      default_quota=TIGHT_QUOTA)
    plan = FaultPlan.parse("seed=7;twin.tick:sleep:ms=1,times=3")
    with faults.active(plan):
        r_chaos = run_day(cfg, policy=StaticPolicy(),
                          default_quota=TIGHT_QUOTA)
    assert r_clean.event_lines == r_chaos.event_lines
    assert r_clean.event_digest == r_chaos.event_digest


# -- blackbox ---------------------------------------------------------------

def test_blackbox_timeline_folds_twin_incident(tmp_path):
    from tools.blackbox import build_timeline

    flight.configure(enabled=True, out_dir=str(tmp_path))
    r = run_day(_small_cfg(ticks=10), policy=QuotaAutoscaler(TIGHT_QUOTA),
                default_quota=TIGHT_QUOTA)
    rec = flight.get_recorder()
    dump = str(tmp_path / "flight_twin.jsonl")
    rec.dump(dump)
    doc = build_timeline(dump)
    chain = doc["chain"]
    assert "twin.scenario" in chain
    assert "policy.adjust" in chain
    assert "placement.plan" in chain
    assert "slo.breach" in chain      # the flash crowd burned
    assert "slo.recovered" in chain   # ...and the policy recovered it
    assert doc["verdict"]["clean"] is True
    json.dumps(doc)
    assert r.scores["slo_minutes"] >= 0


# -- the canonical day ------------------------------------------------------

@pytest.mark.slow
def test_canonical_day_twice_byte_identical():
    from sparkdl_tpu.twin import DEFAULT_TENANT_QUOTA

    cfg = ScenarioConfig()
    mk = lambda: QuotaAutoscaler(DEFAULT_TENANT_QUOTA)  # noqa: E731
    r1 = run_day(cfg, policy=mk())
    r2 = run_day(cfg, policy=mk())
    assert r1.event_digest == r2.event_digest
    assert r1.scores == r2.scores
    assert r1.scores["offered"] >= 100_000
    assert r1.scores["tenants_active"] >= 50
