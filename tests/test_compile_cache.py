"""Compile-once guarantees across param maps and folds (VERDICT round 1,
Missing/Weak #3 — SURVEY.md §7 hard part #5), and — since ISSUE 13 —
compile-once guarantees across PROCESS RESTARTS: the persistent
compilation cache (``parallel.compile_cache``, ``SPARKDL_COMPILE_CACHE``)
keyed on the committed ``PROGRAMS.lock.json``.

A tuning grid must not pay one XLA compile per (map, fold): the TrainStep
cache keys on (predict fn, loss, optimizer, mesh) and jax.jit's own
executable cache de-duplicates equal batch shapes, so the whole grid
compiles once.  Same for inference: fitted models over one fn share the
compiled program.  And a fleet redeploy / serving cold-start over an
unchanged lockfile must not re-jit at all — the subprocess-restart test
below is the cross-process half of PR 7's hot-swap recompile-free proof.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from sparkdl_tpu.parallel import compile_cache

from sparkdl_tpu.estimators import (CrossValidator, ImageFileEstimator,
                                    MulticlassClassificationEvaluator)
from sparkdl_tpu.frame import DataFrame
from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.parallel import train as train_lib
from sparkdl_tpu.parallel.engine import InferenceEngine, clear_engine_jit_cache
from sparkdl_tpu.parallel.train import (clear_train_step_cache,
                                        fit_data_parallel, make_train_step)


@pytest.fixture(autouse=True)
def _fresh_caches():
    clear_train_step_cache()
    clear_engine_jit_cache()
    yield
    clear_train_step_cache()
    clear_engine_jit_cache()


def _counting_predict():
    traces = []

    def predict(p, xb):
        import jax.numpy as jnp

        traces.append(1)  # increments once per TRACE, not per step
        return jnp.asarray(xb).reshape(xb.shape[0], -1) @ p["w"]

    return predict, traces


def test_make_train_step_returns_same_object_for_same_key():
    import optax

    predict, _ = _counting_predict()
    opt = optax.sgd(0.1)
    s1 = make_train_step(predict, "mse", opt)
    s2 = make_train_step(predict, "mse", opt)
    assert s1 is s2
    # different loss -> different step
    s3 = make_train_step(predict, "mae", opt)
    assert s3 is not s1


def test_repeated_fits_trace_once():
    import optax

    predict, traces = _counting_predict()
    opt = optax.sgd(0.1)
    x = np.random.default_rng(0).normal(size=(32, 4)).astype(np.float32)
    y = (x @ np.ones((4, 1), np.float32))

    params = {"w": np.zeros((4, 1), np.float32)}
    fit_data_parallel(predict, params, x, y, optimizer=opt, loss="mse",
                      batch_size=8, epochs=2)
    first = len(traces)
    assert first >= 1
    # 3 more fits, same shapes/opt/loss: ZERO new traces
    for _ in range(3):
        fit_data_parallel(predict, params, x, y, optimizer=opt, loss="mse",
                          batch_size=8, epochs=1)
    assert len(traces) == first


def test_default_optimizer_is_stable_across_fits():
    predict, traces = _counting_predict()
    x = np.zeros((16, 4), np.float32)
    y = np.zeros((16, 1), np.float32)
    params = {"w": np.zeros((4, 1), np.float32)}
    fit_data_parallel(predict, params, x, y, loss="mse", batch_size=8,
                      epochs=1)
    first = len(traces)
    fit_data_parallel(predict, params, x, y, loss="mse", batch_size=8,
                      epochs=1)
    assert len(traces) == first  # optimizer=None resolved to one instance


def _loader(uri):
    from PIL import Image

    img = Image.open(uri).convert("RGB").resize((8, 8))
    return np.asarray(img, dtype=np.float32) / 255.0


def test_grid_times_folds_compiles_once(fixture_images):
    """4 param maps x 3 folds + the final refit: one trace total for the
    train step and one for inference."""
    import jax.numpy as jnp

    train_traces = []
    rng = np.random.default_rng(0)
    variables = {"w": rng.normal(0, 0.01, (8 * 8 * 3, 2)).astype(np.float32)}

    def fn(v, xb):
        train_traces.append(1)
        logits = xb.reshape(xb.shape[0], -1) @ v["w"]
        return jnp.exp(logits) / jnp.sum(jnp.exp(logits), axis=-1,
                                         keepdims=True)

    mf = ModelFunction(fn=fn, variables=variables)
    paths = fixture_images["paths"] * 8  # 24 rows
    labels = [i % 2 for i in range(len(paths))]
    df = DataFrame({
        "uri": paths,
        "label": [[1.0, 0.0] if l == 0 else [0.0, 1.0] for l in labels],
        "labelIdx": np.asarray(labels, np.int64),
    })

    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=mf, imageLoader=_loader, optimizer="sgd",
        loss="categorical_crossentropy", fitParams={"epochs": 1},
        batchSize=8)
    maps = [{est.fitParams: {"epochs": e, "seed": s}}
            for e in (1, 2) for s in (0, 1)]  # 4 maps
    ev = MulticlassClassificationEvaluator(predictionCol="preds",
                                           labelCol="labelIdx")
    cv = CrossValidator(estimator=est, estimatorParamMaps=maps,
                        evaluator=ev, numFolds=3)
    model = cv.fit(df)
    assert len(model.avgMetrics) == 4
    # fn traces: once for the train step (inside value_and_grad) and once
    # for the inference engine — NOT once per (map, fold).
    assert len(train_traces) <= 3, (
        f"expected <=3 traces for 4 maps x 3 folds, got {len(train_traces)}")


# ---------------------------------------------------------------------------
# persistent compilation cache (ISSUE 13): compile-once across restarts
# ---------------------------------------------------------------------------

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: a restarted serving process: build a Server over a tiny fn, warm one
#: bucket, serve a fixed replay, and report the persistent-cache state,
#: hit/miss counters, and an output digest on stdout.
_CHILD = """
import hashlib, json, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from sparkdl_tpu.serving.server import Server
from sparkdl_tpu.parallel import compile_cache

def fn(v, x):
    import jax.numpy as jnp
    return jnp.tanh(x * v["s"] + 0.25)

rng = np.random.default_rng(7)
rows = [rng.normal(size=(6,)).astype(np.float32) for _ in range(6)]
with Server(fn, {{"s": np.float32(3.0)}}, max_batch_size=8,
            max_wait_ms=2, bucket_sizes=[8], cache=False) as srv:
    srv.warmup(rows[0])
    outs = [np.asarray(srv.predict(r)) for r in rows]
digest = hashlib.sha256(b"".join(o.tobytes() for o in outs)).hexdigest()
print(json.dumps({{"state": compile_cache.state(),
                   "stats": compile_cache.stats(),
                   "digest": digest}}))
"""


def _run_restart(cache_dir):
    env = dict(os.environ)
    env["SPARKDL_COMPILE_CACHE"] = str(cache_dir)
    env.pop("SPARKDL_FAULTS", None)
    r = subprocess.run(
        [sys.executable, "-c", _CHILD.format(repo=_REPO)],
        capture_output=True, text=True, timeout=240, env=env)
    assert r.returncode == 0, r.stderr
    return json.loads(r.stdout.strip().splitlines()[-1])


@pytest.fixture
def _fresh_compile_cache_state():
    yield
    compile_cache._reset_for_tests()


def test_restart_serves_lockfile_pinned_programs_with_zero_fresh_compiles(
        tmp_path):
    """THE cross-process proof: process A compiles and populates the
    on-disk cache; a restarted process B serving the same programs
    performs ZERO fresh compiles (every compile request is a persistent
    hit) with bit-identical outputs; tampering the manifest's committed
    fingerprint then forces a clean purge + recompile — classified
    drift, no stale executable served, outputs still bit-identical."""
    cache_dir = tmp_path / "cc"
    a = _run_restart(cache_dir)
    assert a["state"]["dir"] == str(cache_dir)
    assert a["state"]["reused"] is False
    assert a["stats"]["misses"] > 0          # populated the cache
    assert a["stats"]["hits"] == 0

    b = _run_restart(cache_dir)
    assert b["state"]["reused"] is True      # manifest matched the lockfile
    assert b["state"]["invalidated"] is False
    assert b["stats"]["misses"] == 0, b      # zero fresh compiles
    assert b["stats"]["hits"] > 0
    assert b["digest"] == a["digest"]        # bit-identical serving

    manifest = cache_dir / compile_cache.MANIFEST_NAME
    doc = json.loads(manifest.read_text())
    name = sorted(doc["programs"])[0]
    doc["programs"][name]["fingerprint"] = "0" * 64
    manifest.write_text(json.dumps(doc))
    c = _run_restart(cache_dir)
    assert c["state"]["invalidated"] is True
    assert c["state"]["drift_rules"] == ["GC000"]  # fingerprint-only drift
    assert c["state"]["purged_entries"] > 0
    assert c["stats"]["hits"] == 0           # nothing stale was served
    assert c["stats"]["misses"] > 0          # clean recompile
    assert c["digest"] == a["digest"]


def test_compile_cache_env_grammar(monkeypatch):
    monkeypatch.delenv("SPARKDL_COMPILE_CACHE", raising=False)
    assert compile_cache.dir_from_env() is None
    for off in ("0", "false", "off", "no"):
        monkeypatch.setenv("SPARKDL_COMPILE_CACHE", off)
        assert compile_cache.dir_from_env() is None
    monkeypatch.setenv("SPARKDL_COMPILE_CACHE", "1")
    assert compile_cache.dir_from_env() == compile_cache.DEFAULT_DIR
    monkeypatch.setenv("SPARKDL_COMPILE_CACHE", "/somewhere/else")
    assert compile_cache.dir_from_env() == "/somewhere/else"


def test_compile_cache_disabled_by_default(monkeypatch,
                                           _fresh_compile_cache_state):
    monkeypatch.delenv("SPARKDL_COMPILE_CACHE", raising=False)
    compile_cache._reset_for_tests()
    assert compile_cache.ensure_from_env() is None
    assert compile_cache.state() is None
    assert compile_cache.enabled() is False


def test_compile_cache_drift_classified_to_gc_rule(
        tmp_path, _fresh_compile_cache_state):
    """A manifest whose stored program records drifted in a TRACKED
    field classifies back to the rule whose invariant moved (GC002
    here: a dtype-mix change), not just generic fingerprint drift."""
    st = compile_cache.configure(str(tmp_path / "cc"))
    assert st is not None and st["invalidated"] is False
    manifest = tmp_path / "cc" / compile_cache.MANIFEST_NAME
    doc = json.loads(manifest.read_text())
    name = sorted(doc["programs"])[0]
    doc["programs"][name]["dtype_counts"] = {"conv_f32": 999}
    manifest.write_text(json.dumps(doc))
    st2 = compile_cache.configure(str(tmp_path / "cc"))
    assert st2["invalidated"] is True
    assert st2["drift_rules"] == ["GC002"]


def test_compile_cache_injected_fault_degrades_to_fresh_compiles(
        tmp_path, _fresh_compile_cache_state):
    """The ``compile.cache`` chaos contract: a corrupt cache dir (an
    injected configure-time error) disables the cache — serving
    continues on fresh compiles, nothing raises."""
    from sparkdl_tpu import faults

    with faults.active(faults.FaultPlan.parse(
            "seed=9;compile.cache:error:times=1")):
        assert compile_cache.configure(str(tmp_path / "cc")) is None
    assert compile_cache.state() is None
    # the same dir configures fine once the fault is gone
    assert compile_cache.configure(str(tmp_path / "cc")) is not None


def test_engines_share_compiled_program_across_weight_sets():
    def fn(v, x):
        import jax.numpy as jnp

        return jnp.tanh(x @ v["w"])

    rng = np.random.default_rng(1)
    v1 = {"w": rng.normal(size=(4, 3)).astype(np.float32)}
    v2 = {"w": rng.normal(size=(4, 3)).astype(np.float32)}
    e1 = InferenceEngine(fn, v1, device_batch_size=8)
    e2 = InferenceEngine(fn, v2, device_batch_size=8)
    assert e1._compiled is e2._compiled  # one program, two weight sets
    x = rng.normal(size=(8, 4)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(e1(x)), np.tanh(x @ v1["w"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(e2(x)), np.tanh(x @ v2["w"]),
                               rtol=1e-5, atol=1e-6)
