"""Compile-once guarantees across param maps and folds (VERDICT round 1,
Missing/Weak #3 — SURVEY.md §7 hard part #5).

A tuning grid must not pay one XLA compile per (map, fold): the TrainStep
cache keys on (predict fn, loss, optimizer, mesh) and jax.jit's own
executable cache de-duplicates equal batch shapes, so the whole grid
compiles once.  Same for inference: fitted models over one fn share the
compiled program.
"""

import numpy as np
import pytest

from sparkdl_tpu.estimators import (CrossValidator, ImageFileEstimator,
                                    MulticlassClassificationEvaluator)
from sparkdl_tpu.frame import DataFrame
from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.parallel import train as train_lib
from sparkdl_tpu.parallel.engine import InferenceEngine, clear_engine_jit_cache
from sparkdl_tpu.parallel.train import (clear_train_step_cache,
                                        fit_data_parallel, make_train_step)


@pytest.fixture(autouse=True)
def _fresh_caches():
    clear_train_step_cache()
    clear_engine_jit_cache()
    yield
    clear_train_step_cache()
    clear_engine_jit_cache()


def _counting_predict():
    traces = []

    def predict(p, xb):
        import jax.numpy as jnp

        traces.append(1)  # increments once per TRACE, not per step
        return jnp.asarray(xb).reshape(xb.shape[0], -1) @ p["w"]

    return predict, traces


def test_make_train_step_returns_same_object_for_same_key():
    import optax

    predict, _ = _counting_predict()
    opt = optax.sgd(0.1)
    s1 = make_train_step(predict, "mse", opt)
    s2 = make_train_step(predict, "mse", opt)
    assert s1 is s2
    # different loss -> different step
    s3 = make_train_step(predict, "mae", opt)
    assert s3 is not s1


def test_repeated_fits_trace_once():
    import optax

    predict, traces = _counting_predict()
    opt = optax.sgd(0.1)
    x = np.random.default_rng(0).normal(size=(32, 4)).astype(np.float32)
    y = (x @ np.ones((4, 1), np.float32))

    params = {"w": np.zeros((4, 1), np.float32)}
    fit_data_parallel(predict, params, x, y, optimizer=opt, loss="mse",
                      batch_size=8, epochs=2)
    first = len(traces)
    assert first >= 1
    # 3 more fits, same shapes/opt/loss: ZERO new traces
    for _ in range(3):
        fit_data_parallel(predict, params, x, y, optimizer=opt, loss="mse",
                          batch_size=8, epochs=1)
    assert len(traces) == first


def test_default_optimizer_is_stable_across_fits():
    predict, traces = _counting_predict()
    x = np.zeros((16, 4), np.float32)
    y = np.zeros((16, 1), np.float32)
    params = {"w": np.zeros((4, 1), np.float32)}
    fit_data_parallel(predict, params, x, y, loss="mse", batch_size=8,
                      epochs=1)
    first = len(traces)
    fit_data_parallel(predict, params, x, y, loss="mse", batch_size=8,
                      epochs=1)
    assert len(traces) == first  # optimizer=None resolved to one instance


def _loader(uri):
    from PIL import Image

    img = Image.open(uri).convert("RGB").resize((8, 8))
    return np.asarray(img, dtype=np.float32) / 255.0


def test_grid_times_folds_compiles_once(fixture_images):
    """4 param maps x 3 folds + the final refit: one trace total for the
    train step and one for inference."""
    import jax.numpy as jnp

    train_traces = []
    rng = np.random.default_rng(0)
    variables = {"w": rng.normal(0, 0.01, (8 * 8 * 3, 2)).astype(np.float32)}

    def fn(v, xb):
        train_traces.append(1)
        logits = xb.reshape(xb.shape[0], -1) @ v["w"]
        return jnp.exp(logits) / jnp.sum(jnp.exp(logits), axis=-1,
                                         keepdims=True)

    mf = ModelFunction(fn=fn, variables=variables)
    paths = fixture_images["paths"] * 8  # 24 rows
    labels = [i % 2 for i in range(len(paths))]
    df = DataFrame({
        "uri": paths,
        "label": [[1.0, 0.0] if l == 0 else [0.0, 1.0] for l in labels],
        "labelIdx": np.asarray(labels, np.int64),
    })

    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=mf, imageLoader=_loader, optimizer="sgd",
        loss="categorical_crossentropy", fitParams={"epochs": 1},
        batchSize=8)
    maps = [{est.fitParams: {"epochs": e, "seed": s}}
            for e in (1, 2) for s in (0, 1)]  # 4 maps
    ev = MulticlassClassificationEvaluator(predictionCol="preds",
                                           labelCol="labelIdx")
    cv = CrossValidator(estimator=est, estimatorParamMaps=maps,
                        evaluator=ev, numFolds=3)
    model = cv.fit(df)
    assert len(model.avgMetrics) == 4
    # fn traces: once for the train step (inside value_and_grad) and once
    # for the inference engine — NOT once per (map, fold).
    assert len(train_traces) <= 3, (
        f"expected <=3 traces for 4 maps x 3 folds, got {len(train_traces)}")


def test_engines_share_compiled_program_across_weight_sets():
    def fn(v, x):
        import jax.numpy as jnp

        return jnp.tanh(x @ v["w"])

    rng = np.random.default_rng(1)
    v1 = {"w": rng.normal(size=(4, 3)).astype(np.float32)}
    v2 = {"w": rng.normal(size=(4, 3)).astype(np.float32)}
    e1 = InferenceEngine(fn, v1, device_batch_size=8)
    e2 = InferenceEngine(fn, v2, device_batch_size=8)
    assert e1._compiled is e2._compiled  # one program, two weight sets
    x = rng.normal(size=(8, 4)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(e1(x)), np.tanh(x @ v1["w"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(e2(x)), np.tanh(x @ v2["w"]),
                               rtol=1e-5, atol=1e-6)
