"""Mesh-sharded inference core (ISSUE 14): tensor-parallel weight
sharding via partition rules, on the 8-virtual-device CPU topology.

The contract under test, end to end:

* ``mesh.match_partition_rules`` — regex over ``/``-joined param paths
  to ``PartitionSpec``s (scalars replicated, no-match is a loud error);
* the default rule set splits dense/conv kernels on the ``model`` axis
  iff the axis is >1 and the dim divides (the divisibility fallback),
  collapsing to the classic replicate-everything layout otherwise —
  byte-identical programs on every model-axis-1 mesh;
* sharded outputs are BIT-IDENTICAL to the replicated oracle on the
  same mesh (the split rides output dims, no cross-shard reductions);
* graftcheck GC005 proves the HBM claim chip-free: a synthetic
  wide-dense model whose 64 MB kernel busts the 32 MB replicated-param
  budget on a model-axis mesh audits CLEAN once sharded by the default
  rules, and the sharded programs are pinned in PROGRAMS.lock.json with
  drift classified back to GC005;
* ragged batching cuts stay multiples of the mesh data-axis size;
* the persistent compile-cache manifest carries the mesh/partition
  policy, so a restarted process under a different policy purges.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from sparkdl_tpu.parallel import mesh as mesh_lib
from sparkdl_tpu.parallel.engine import (InferenceEngine,
                                         clear_engine_jit_cache)


@pytest.fixture(autouse=True)
def _fresh_jit_cache():
    clear_engine_jit_cache()
    yield
    clear_engine_jit_cache()


def _wide_fn(v, x):
    return jnp.tanh(x @ v["dense"]["kernel"] + v["dense"]["bias"])


def _variables(rng, d=16):
    return {"dense": {
        "kernel": rng.normal(size=(d, d)).astype(np.float32),
        "bias": rng.normal(size=(d,)).astype(np.float32),
    }}


# ---------------------------------------------------------------------------
# rule matching
# ---------------------------------------------------------------------------

def test_match_partition_rules_regex_scalar_and_order():
    params = {"dense": {"kernel": np.zeros((8, 8), np.float32),
                        "bias": np.zeros((8,), np.float32)},
              "scale": np.float32(2.0),
              "one_elem": np.zeros((1,), np.float32)}
    specs = mesh_lib.match_partition_rules(
        [(r"(^|/)kernel$", P(None, "model")),
         (r".*", P())], params)
    assert tuple(specs["dense"]["kernel"]) == (None, "model")
    assert tuple(specs["dense"]["bias"]) == ()
    # scalars and one-element leaves are never partitioned, even if a
    # rule would match them
    assert tuple(specs["scale"]) == ()
    assert tuple(specs["one_elem"]) == ()
    # FIRST matching rule wins
    ordered = mesh_lib.match_partition_rules(
        [(r"dense/kernel", P("model")), (r"kernel", P(None, "model")),
         (r".*", P())], params)
    assert tuple(ordered["dense"]["kernel"]) == ("model",)


def test_match_partition_rules_no_match_raises():
    with pytest.raises(ValueError, match="Partition rule not found.*bias"):
        mesh_lib.match_partition_rules(
            [(r"kernel$", P(None, "model"))],
            {"kernel": np.zeros((4, 4), np.float32),
             "bias": np.zeros((4,), np.float32)})


def test_default_rules_divisibility_fallback():
    mesh = mesh_lib.get_mesh(model_parallel=8)
    params = {"a": {"kernel": np.zeros((4, 16), np.float32)},
              "b": {"kernel": np.zeros((4, 12), np.float32)},  # 12 % 8
              "c": {"bias": np.zeros((16,), np.float32)}}
    _, specs = mesh_lib.resolve_param_shardings(params, mesh)
    assert tuple(specs["a"]["kernel"]) == (None, mesh_lib.MODEL_AXIS)
    assert tuple(specs["b"]["kernel"]) == ()   # indivisible -> replicated
    assert tuple(specs["c"]["bias"]) == ()


def test_resolve_collapses_replicated_on_model_axis_1():
    """Model-axis-1 meshes must keep the pre-ISSUE-14 layout exactly:
    the resolved policy is all-replicated, the digest is the canonical
    "replicated", and an engine built with the default rules shares the
    SAME compiled jit object (same cache key) as one built without."""
    rng = np.random.default_rng(0)
    v = _variables(rng)
    _, specs = mesh_lib.resolve_param_shardings(v, mesh_lib.get_mesh())
    assert mesh_lib.specs_all_replicated(specs)
    assert mesh_lib.partition_digest(specs) == "replicated"
    e_plain = InferenceEngine(_wide_fn, v, device_batch_size=8)
    e_rules = InferenceEngine(_wide_fn, v, device_batch_size=8,
                              partition_rules=mesh_lib.
                              default_partition_rules)
    assert e_rules.param_shardings is None
    assert e_rules._compiled is e_plain._compiled


# ---------------------------------------------------------------------------
# engine parity: sharded == replicated, bit for bit
# ---------------------------------------------------------------------------

def test_engine_sharded_vs_replicated_bit_identical_tp8():
    rng = np.random.default_rng(1)
    v = _variables(rng)
    x = rng.normal(size=(40, 16)).astype(np.float32)
    mesh = mesh_lib.get_mesh(model_parallel=8)
    e_rep = InferenceEngine(_wide_fn, v, mesh=mesh, device_batch_size=16)
    e_tp = InferenceEngine(_wide_fn, v, mesh=mesh, device_batch_size=16,
                           partition_rules=mesh_lib.
                           default_partition_rules)
    # the kernel really is split: each chip holds a (16, 2) column slice
    kernel = e_tp.variables["dense"]["kernel"]
    assert tuple(kernel.sharding.spec) == (None, mesh_lib.MODEL_AXIS)
    assert kernel.addressable_shards[0].data.shape == (16, 2)
    # distinct compiled programs (the policy is part of the cache key)…
    assert e_tp._compiled is not e_rep._compiled
    assert e_tp.sharding_digest != e_rep.sharding_digest
    # …but bit-identical outputs: the split rides the kernel's OUTPUT
    # dim, so no cross-shard reduction enters the math
    assert np.array_equal(np.asarray(e_tp(x)), np.asarray(e_rep(x)))
    info = e_tp.sharding_info()
    assert info["sharded"] and info["sharded_leaves"] == 1
    assert info["mesh_shape"] == {"data": 1, "model": 8}
    total, per_chip = (info["param_bytes_total"],
                       info["param_bytes_per_chip"])
    # kernel bytes / 8 + replicated bias
    assert per_chip == total - (16 * 16 * 4) + (16 * 16 * 4) // 8
    json.dumps(info)  # varz-embeddable


def test_engine_explicit_param_shardings_and_grouped_dispatch():
    rng = np.random.default_rng(2)
    v = _variables(rng)
    x = rng.normal(size=(64, 16)).astype(np.float32)
    mesh = mesh_lib.get_mesh(model_parallel=4)  # dp2 x tp4
    ref = np.asarray(InferenceEngine(_wide_fn, v, mesh=mesh,
                                     device_batch_size=16)(x))
    e_exp = InferenceEngine(
        _wide_fn, v, mesh=mesh, device_batch_size=16,
        param_shardings={"dense": {"kernel": P(None, "model"),
                                   "bias": P()}})
    assert np.array_equal(np.asarray(e_exp(x)), ref)
    # the grouped (lax.map) program shards the same way
    e_grp = InferenceEngine(_wide_fn, v, mesh=mesh, device_batch_size=16,
                            partition_rules=mesh_lib.
                            default_partition_rules,
                            batches_per_dispatch=2)
    got = np.concatenate(
        list(e_grp.map_batches([x], pipeline=False)), axis=0)
    assert np.array_equal(got, ref)


def test_server_sharded_parity_dp2tp4():
    """The serving path end to end on a mixed dp2 x tp4 mesh: sharded
    vs replicated servers on the SAME mesh serve bit-identical rows,
    and varz reports the layout."""
    from sparkdl_tpu.serving.server import Server

    rng = np.random.default_rng(3)
    v = _variables(rng, d=8)
    rows = [rng.normal(size=(8,)).astype(np.float32) for _ in range(12)]
    mesh = mesh_lib.get_mesh(model_parallel=4)

    def run(rules):
        with Server(_wide_fn, v, mesh=mesh, max_batch_size=8,
                    max_wait_ms=2, bucket_sizes=[4, 8], cache=False,
                    partition_rules=rules) as srv:
            srv.warmup(rows[0])
            outs = [np.asarray(srv.predict(r)) for r in rows]
            return outs, srv.varz()["sharding"]

    tp_outs, tp_info = run(mesh_lib.default_partition_rules)
    rep_outs, rep_info = run(None)
    assert all(np.array_equal(a, b) for a, b in zip(tp_outs, rep_outs))
    assert tp_info["sharded"] and not rep_info["sharded"]
    assert tp_info["mesh_shape"] == {"data": 2, "model": 4}
    assert (tp_info["param_bytes_per_chip"]
            < rep_info["param_bytes_per_chip"])


def test_fleet_exposes_partition_rules_knob():
    from sparkdl_tpu.serving.fleet import Fleet

    rng = np.random.default_rng(4)
    v = _variables(rng, d=8)
    mesh = mesh_lib.get_mesh(model_parallel=8)
    fleet = Fleet(cache=False)
    try:
        fleet.add_model("wide", _wide_fn, v, mesh=mesh,
                        max_batch_size=8, bucket_sizes=[8], max_wait_ms=2,
                        partition_rules=mesh_lib.default_partition_rules,
                        warm_example=np.zeros((8,), np.float32))
        out = np.asarray(fleet.submit("wide", rng.normal(size=(8,)).astype(
            np.float32), tenant="t").result(timeout=30))
        assert out.shape == (8,)
        info = fleet._state("wide").server.sharding_info()
        assert info["sharded"] and info["mesh_shape"]["model"] == 8
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# the chip-free HBM proof (graftcheck GC005 + lockfile)
# ---------------------------------------------------------------------------

def _wide_dense_spec(sharded: bool, model_parallel: int = 8):
    from sparkdl_tpu.analysis.program.audit import ProgramSpec
    from sparkdl_tpu.parallel.engine import build_dispatch_jit

    mesh = mesh_lib.get_mesh(model_parallel=model_parallel)
    d = 4096  # 64 MB f32 kernel: busts the 32 MB replicated budget

    def build():
        variables = {"dense": {
            "kernel": jax.ShapeDtypeStruct((d, d), np.float32),
            "bias": jax.ShapeDtypeStruct((d,), np.float32)}}
        shardings = None
        if sharded:
            shardings, _ = mesh_lib.resolve_param_shardings(variables,
                                                            mesh)
        jitted = build_dispatch_jit(_wide_fn, mesh, donate_batch=True,
                                    param_shardings=shardings)
        batch = jax.ShapeDtypeStruct((32, d), np.float32)
        return jitted, (variables, batch)

    axes = {str(n): int(mesh.shape[n]) for n in mesh.axis_names}
    if sharded:
        kw = dict(shardings=("params", "batch"),
                  param_partition=(("dense/bias", []),
                                   ("dense/kernel", [None, "model"])))
    else:
        kw = dict(shardings=("replicated", "batch"))
    return ProgramSpec(name="synth/wide_dense", kind="dispatch",
                       build=build, donate=(1,), batch_rows=32,
                       mesh_axes=axes, group="synth/wide_dense", **kw)


def test_gc005_budget_buster_goes_clean_when_sharded():
    """THE acceptance gate: replicated, the wide-dense model's 64 MB
    kernel fires GC005 on a model-axis mesh; under the default
    partition rules the SAME program audits clean — per-chip kernel
    bytes are bytes/8, below budget — with the donation still consumed
    and sharding annotations present."""
    from sparkdl_tpu.analysis.program.audit import audit_program

    busted = audit_program(_wide_dense_spec(sharded=False))
    assert any(f.code == "GC005" and "replicated" in f.message
               for f in busted["findings"])
    clean = audit_program(_wide_dense_spec(sharded=True))
    assert clean["findings"] == []
    summary = clean["record"]["sharding_summary"]
    assert summary["largest_replicated_leaf_bytes"] == 4096 * 4  # bias
    shards = summary["param_shards"]
    assert shards["sharded_leaves"] == 1
    assert shards["sharded_bytes_per_chip"] == 4096 * 4096 * 4 // 8
    assert shards["indivisible"] == []
    assert summary["annotated"] > 0
    # donation consumed under the sharded layout too (GC001's criterion)
    assert clean["record"]["donation"]["aliased"] >= 1


def test_budget_buster_serves_bit_identical_to_single_device_oracle():
    """The acceptance criterion, runtime half: the EXACT wide-dense
    model the lockfile pins (``inventory.wide_dense_fn`` at the
    committed 128 x 131072 shape — its 64 MB kernel busts the GC005
    per-chip budget) runs tensor-parallel on the 8-virtual-device
    model-axis mesh with outputs BIT-IDENTICAL to a single-device
    replicated oracle: the split rides output columns, so no output
    element's accumulation order changes."""
    from sparkdl_tpu.analysis.program.inventory import (WIDE_DENSE_IN,
                                                        WIDE_DENSE_OUT,
                                                        wide_dense_fn)

    rng = np.random.default_rng(8)
    v = {"dense": {"kernel": rng.normal(
        scale=0.05, size=(WIDE_DENSE_IN, WIDE_DENSE_OUT)).astype(
            np.float32),
        "bias": rng.normal(size=(WIDE_DENSE_OUT,)).astype(np.float32)}}
    x = rng.normal(size=(32, WIDE_DENSE_IN)).astype(np.float32)
    oracle = InferenceEngine(wide_dense_fn, v,
                             mesh=mesh_lib.get_mesh(num_devices=1),
                             device_batch_size=32)
    tp = InferenceEngine(wide_dense_fn, v,
                         mesh=mesh_lib.get_mesh(model_parallel=8),
                         device_batch_size=32,
                         partition_rules=mesh_lib.
                         default_partition_rules)
    # per-chip HBM really dropped below the 32 MB budget
    from sparkdl_tpu.analysis.program.audit import (
        REPLICATED_PARAM_BUDGET_BYTES)

    info = tp.sharding_info()
    assert info["param_bytes_total"] > REPLICATED_PARAM_BUDGET_BYTES
    assert info["param_bytes_per_chip"] < REPLICATED_PARAM_BUDGET_BYTES
    assert np.array_equal(np.asarray(tp(x)), np.asarray(oracle(x)))


def test_gc005_indivisible_declared_split_fires():
    from sparkdl_tpu.analysis.program.audit import audit_program

    spec = _wide_dense_spec(sharded=True)
    # declare a split the leaf cannot honor: bias (4096,) "split" on a
    # dim it does not have
    spec.param_partition = (("dense/bias", [None, "model"]),
                            ("dense/kernel", [None, "model"]))
    out = audit_program(spec)
    assert any(f.code == "GC005" and "not divisible" in f.message
               for f in out["findings"])


def test_sharded_programs_pinned_in_lockfile():
    """The committed PROGRAMS.lock.json carries the tensor-parallel
    wide-dense programs with fingerprints matching a fresh abstract
    lowering — the mesh-sharded core regenerated the lockfile exactly
    once and the sharded variants are now part of the audited
    surface."""
    from sparkdl_tpu.analysis.program.audit import audit_program
    from sparkdl_tpu.analysis.program.inventory import (
        sharded_dispatch_specs)
    from sparkdl_tpu.analysis.program.lockfile import (DEFAULT_LOCKFILE,
                                                       read_lockfile)

    committed = read_lockfile(DEFAULT_LOCKFILE)["programs"]
    specs = sharded_dispatch_specs()
    assert {s.name for s in specs} == {
        "serving/wide_dense/f32/b32/dp1tp8",
        "serving/wide_dense/f32/b32/dp2tp4"}
    for spec in specs:
        out = audit_program(spec)
        assert out["findings"] == []
        base = committed[spec.name]
        assert out["record"]["fingerprint"] == base["fingerprint"]
        fresh_summary = json.loads(  # JSON-normalize tuples vs lists
            json.dumps(out["record"]["sharding_summary"]))
        assert fresh_summary == base["sharding_summary"]


def test_lockfile_sharding_drift_classified_gc005():
    from sparkdl_tpu.analysis.program.lockfile import (DEFAULT_LOCKFILE,
                                                       diff_records,
                                                       read_lockfile)

    committed = read_lockfile(DEFAULT_LOCKFILE)
    name = "serving/wide_dense/f32/b32/dp1tp8"
    rec = dict(committed["programs"][name], name=name)
    summary = json.loads(json.dumps(rec["sharding_summary"]))
    summary["param_shards"]["sharded_leaves"] = 0  # layout "un-sharded"
    rec["sharding_summary"] = summary
    findings = diff_records(committed, [rec], subset=True)
    assert [f.code for f in findings] == ["GC005"]
    assert "sharding" in findings[0].message


# ---------------------------------------------------------------------------
# ragged batching x mesh alignment (dp=4)
# ---------------------------------------------------------------------------

def test_batcher_rounds_raw_bucket_plan_to_mesh_multiple():
    from sparkdl_tpu.serving.batcher import DynamicBatcher

    b = DynamicBatcher(max_batch_size=30, bucket_plan=[6, 12, 30],
                       align=4)
    assert b.bucket_plan == [8, 12, 32]  # effective_device_batch rounding
    assert all(x % 4 == 0 for x in b.bucket_plan)
    # align=1 keeps raw plans untouched
    assert DynamicBatcher(max_batch_size=30,
                          bucket_plan=[6, 12, 30]).bucket_plan == [6, 12, 30]


def test_ragged_cuts_stay_mesh_aligned_dp4():
    """Regression gate for the ragged/mesh interplay: on a dp=4 mesh
    every ragged CUT lands on a mesh-rounded bucket boundary, so a
    20-deep queue dispatches as 12 + 8 with ZERO pad rows, and a
    5-deep residual pads to the 8 bucket — all device batches
    multiples of the data-axis size."""
    from sparkdl_tpu.serving.server import Server

    rng = np.random.default_rng(5)
    v = _variables(rng, d=8)
    mesh = mesh_lib.get_mesh(num_devices=4)  # dp4 x tp1
    rows = [rng.normal(size=(8,)).astype(np.float32) for _ in range(25)]
    from sparkdl_tpu.utils.metrics import Metrics
    metrics = Metrics()
    with Server(_wide_fn, v, mesh=mesh, max_batch_size=24,
                max_wait_ms=25, bucket_sizes=[6, 12, 24], ragged=True,
                cache=False, max_inflight_batches=1,
                metrics=metrics) as srv:
        assert srv.bucket_sizes == [8, 12, 24]  # mesh-rounded
        assert srv._batcher.bucket_plan == [8, 12, 24]
        assert srv._batcher.align == 4
        srv.warmup(rows[0])
        warm = dict(metrics.snapshot_raw()["counters"])
        futs = [srv.submit(r) for r in rows[:20]]
        outs = [np.asarray(f.result(timeout=30)) for f in futs]
        counters = metrics.snapshot_raw()["counters"]
        # 20 queued -> cut 12 + cut 8: zero pad rows for the burst
        assert counters.get("engine.pad_rows", 0) == warm.get(
            "engine.pad_rows", 0)
        # a 5-deep residual pads to the smallest (8) bucket
        futs = [srv.submit(r) for r in rows[20:]]
        outs += [np.asarray(f.result(timeout=30)) for f in futs]
        counters = metrics.snapshot_raw()["counters"]
        assert (counters.get("engine.pad_rows", 0)
                - warm.get("engine.pad_rows", 0)) == 3
    ref = np.tanh(np.stack(rows) @ v["dense"]["kernel"]
                  + v["dense"]["bias"]).astype(np.float32)
    assert all(np.allclose(o, r, rtol=1e-6, atol=1e-6)
               for o, r in zip(outs, ref))


# ---------------------------------------------------------------------------
# review-hardening regressions
# ---------------------------------------------------------------------------

def test_engine_indivisible_explicit_spec_falls_back_to_replicated():
    """An explicit param_shardings spec that does not divide its leaf
    gets the SAME per-leaf replicate fallback the rules path promises
    (resolve_param_shardings' contract) instead of crashing
    device_put/jit."""
    rng = np.random.default_rng(9)
    v = {"dense": {"kernel": rng.normal(size=(16, 12)).astype(np.float32),
                   "bias": rng.normal(size=(12,)).astype(np.float32)}}

    def fn(vv, x):
        return jnp.tanh(x @ vv["dense"]["kernel"])

    mesh = mesh_lib.get_mesh(model_parallel=8)  # 12 % 8 != 0
    eng = InferenceEngine(fn, v, mesh=mesh, device_batch_size=8,
                          param_shardings={"dense": {
                              "kernel": P(None, "model"), "bias": P()}})
    assert eng.param_shardings is None  # both leaves fell back -> collapse
    x = rng.normal(size=(8, 16)).astype(np.float32)
    ref = np.tanh(x @ v["dense"]["kernel"])
    np.testing.assert_allclose(np.asarray(eng(x)), ref, rtol=1e-5,
                               atol=1e-6)
    # a spec pytree that does NOT mirror the params structure raises
    # instead of silently pairing specs with the wrong leaves
    with pytest.raises(ValueError, match="mirror the params"):
        InferenceEngine(fn, v, mesh=mesh, device_batch_size=8,
                        param_shardings=[P(None, "model"), P()])


def test_none_only_specs_collapse_like_empty():
    """``P(None, None)`` names no axis: it must collapse exactly like
    ``P()`` — same digest ("replicated"), same compiled program — or a
    spelling difference would fork a second compile of a byte-identical
    program and purge the compile cache across restarts."""
    assert mesh_lib.spec_is_replicated(P(None, None))
    assert mesh_lib.specs_all_replicated({"a": P(None, None), "b": P()})
    assert mesh_lib.partition_digest(
        {"a": P(None, None), "b": P()}) == "replicated"
    rng = np.random.default_rng(10)
    v = _variables(rng)
    mesh = mesh_lib.get_mesh(model_parallel=8)
    e_spelled = InferenceEngine(
        _wide_fn, v, mesh=mesh, device_batch_size=8,
        param_shardings={"dense": {"kernel": P(None, None),
                                   "bias": P()}})
    e_plain = InferenceEngine(_wide_fn, v, mesh=mesh,
                              device_batch_size=8)
    assert e_spelled.sharding_digest == "replicated"
    assert e_spelled._compiled is e_plain._compiled


def test_fleet_zoo_overrides_survive_explicit_dtype():
    """A caller pinning compute_dtype must not silently drop the
    entry's NON-dtype overrides (partition_rules, the donate_batch
    GC001 exemption) — only the dtype contract yields to the caller."""
    from types import SimpleNamespace

    from sparkdl_tpu.serving.fleet import Fleet

    rng = np.random.default_rng(11)
    v = _variables(rng, d=8)
    mesh = mesh_lib.get_mesh(model_parallel=8)
    entry = SimpleNamespace(
        fn=_wide_fn,
        engine_overrides={"donate_batch": False,
                          "partition_rules":
                          mesh_lib.default_partition_rules,
                          "compute_dtype": jnp.bfloat16,
                          "output_host_dtype": np.float32})
    mv = SimpleNamespace(version=1, variables=v)
    # donate_batch=True as a FLEET-WIDE default: the entry's recorded
    # exemption (False) must still win — entry overrides beat fleet
    # defaults, explicit per-entry server_kwargs beat both
    fleet = Fleet(cache=False, donate_batch=True)
    try:
        srv = fleet._build_server(
            entry, mv, {"compute_dtype": None, "mesh": mesh,
                        "max_batch_size": 8, "bucket_sizes": [8]})
        try:
            # caller's dtype choice won; the sharding + donation
            # overrides still applied
            assert srv._compute_dtype is None
            assert (srv._partition_rules
                    is mesh_lib.default_partition_rules)
            assert srv._donate_batch is False
        finally:
            srv.close(drain=False)
    finally:
        fleet.close()


def test_gc005_unknown_axis_in_declaration_fires():
    from sparkdl_tpu.analysis.program.audit import audit_program

    spec = _wide_dense_spec(sharded=True)
    spec.param_partition = (("dense/bias", []),
                            ("dense/kernel", [None, "modle"]))  # typo
    out = audit_program(spec)
    assert any(f.code == "GC005" and "unknown mesh axis" in f.message
               for f in out["findings"])


# ---------------------------------------------------------------------------
# compile-cache manifest carries the sharding policy
# ---------------------------------------------------------------------------

def test_compile_cache_policy_flip_purges_classified_gc005(tmp_path):
    from sparkdl_tpu.parallel import compile_cache

    d = str(tmp_path / "cc")
    rng = np.random.default_rng(6)
    v = _variables(rng)
    mesh = mesh_lib.get_mesh(model_parallel=8)
    e_rep = InferenceEngine(_wide_fn, v, mesh=mesh, device_batch_size=8)
    e_tp = InferenceEngine(_wide_fn, v, mesh=mesh, device_batch_size=8,
                           partition_rules=mesh_lib.
                           default_partition_rules)
    assert e_rep.compile_policy() != e_tp.compile_policy()
    assert e_rep.compile_policy().endswith("params=replicated")
    try:
        st = compile_cache.configure(d, policy=e_rep.compile_policy())
        assert st["invalidated"] is False
        assert st["sharding_policy"] == e_rep.compile_policy()
        manifest = json.loads(
            (tmp_path / "cc" / compile_cache.MANIFEST_NAME).read_text())
        assert manifest["sharding_policies"] == [e_rep.compile_policy()]
        # same policy on "restart": reused, nothing purged
        st = compile_cache.configure(d, policy=e_rep.compile_policy())
        assert st["reused"] is True and st["invalidated"] is False
        # a policy the deployment never used purges, classified GC005
        st = compile_cache.configure(d, policy=e_tp.compile_policy())
        assert st["invalidated"] is True
        assert st["drift_rules"] == ["GC005"]
    finally:
        compile_cache._reset_for_tests()


def test_compile_cache_policy_set_is_order_independent(tmp_path):
    """A deployment whose engines use SEVERAL policies (a fleet mixing
    sharded and replicated entries) must reuse across restarts no
    matter which engine constructs first: every engine's policy joins
    the manifest's set (note_policy), and a restart whose first policy
    is already IN the set reuses; only a policy the deployment never
    used purges."""
    from sparkdl_tpu.parallel import compile_cache

    d = str(tmp_path / "cc")
    a, b, c = ("mesh=1x8|params=aaa", "mesh=8x1|params=replicated",
               "mesh=2x4|params=ccc")
    try:
        st = compile_cache.configure(d, policy=a)
        assert st["invalidated"] is False
        compile_cache.note_policy(b)  # the second engine's layout
        manifest = json.loads(
            (tmp_path / "cc" / compile_cache.MANIFEST_NAME).read_text())
        assert manifest["sharding_policies"] == sorted([a, b])
        # restart constructing the OTHER engine first: reused
        st = compile_cache.configure(d, policy=b)
        assert st["reused"] is True and st["invalidated"] is False
        # a test/CLI configure with no policy is a wildcard: no purge
        st = compile_cache.configure(d)
        assert st["reused"] is True
        # a layout the deployment never used still purges (GC005)
        st = compile_cache.configure(d, policy=c)
        assert st["invalidated"] is True
        assert st["drift_rules"] == ["GC005"]
        assert st["sharding_policies"] == [c]  # fresh set after purge
    finally:
        compile_cache._reset_for_tests()


# ---------------------------------------------------------------------------
# bench HBM rider
# ---------------------------------------------------------------------------

def test_bench_sharding_rider_stamps_mesh_and_bytes():
    import bench

    bench._SHARD_LOCK_CACHE.clear()
    try:
        snapshot = {"gauges": {
            "engine.mesh_data_axis": 1.0, "engine.mesh_model_axis": 8.0,
            "engine.replicated_param_bytes": 800.0,
            "engine.param_bytes_per_chip": 100.0}}
        rider = bench._sharding_rider(snapshot)
        m = rider["measured"]
        assert m["mesh_shape"] == {"data": 1, "model": 8}
        assert m["replicated_param_bytes_per_chip"] == 800
        assert m["sharded_param_bytes_per_chip"] == 100
        assert m["sharded_vs_replicated_ratio"] == 0.125
        lock = rider["lockfile"]
        # the lockfile half: every zoo model's replicated HBM cost and
        # the committed tensor-parallel programs' per-chip ratio
        assert len(lock["zoo"]) >= 9
        tp8 = lock["sharded_programs"][
            "serving/wide_dense/f32/b32/dp1tp8"]
        assert tp8["sharded_vs_replicated_ratio"] < 0.2
        assert (tp8["sharded_param_bytes_per_chip"]
                < tp8["replicated_param_bytes_per_chip"])
        # no gauges -> lockfile half only, never a crash
        assert bench._sharding_rider(None)["measured"] is None
        json.dumps(rider)
    finally:
        bench._SHARD_LOCK_CACHE.clear()


def test_live_engine_gauges_feed_the_rider():
    import bench

    from sparkdl_tpu.obs.export import metrics_snapshot
    from sparkdl_tpu.utils.metrics import Metrics

    rng = np.random.default_rng(7)
    v = _variables(rng)
    metrics = Metrics()
    mesh = mesh_lib.get_mesh(model_parallel=8)
    InferenceEngine(_wide_fn, v, mesh=mesh, device_batch_size=8,
                    partition_rules=mesh_lib.default_partition_rules,
                    metrics=metrics)
    rider = bench._sharding_rider(metrics_snapshot(metrics))
    m = rider["measured"]
    assert m["mesh_shape"] == {"data": 1, "model": 8}
    assert m["sharded_vs_replicated_ratio"] < 1.0
