"""Native host-IO core tests.

Parity is tolerance-based vs PIL (the reference tolerated cross-backend
resize differences between java.awt and TF bilinear the same way); failure
handling must preserve the drop-to-null contract; the PIL fallback path must
produce identical-shape results when the native core is unavailable.
"""

import io as _io

import numpy as np
import pytest

import sparkdl_tpu.native as native
from sparkdl_tpu.image.io import decodeResizeBatch, filesToModelBatch


def _jpeg(arr, quality=92):
    from PIL import Image

    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, "JPEG", quality=quality)
    return buf.getvalue()


def _png(arr):
    from PIL import Image

    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, "PNG")
    return buf.getvalue()


@pytest.fixture(scope="module")
def blobs(rng=None):
    rng = np.random.default_rng(9)
    imgs = [(rng.random((h, w, 3)) * 255).astype(np.uint8)
            for h, w in [(80, 100), (64, 64), (120, 90)]]
    return imgs, [_jpeg(imgs[0]), _jpeg(imgs[1]), _png(imgs[2]), b"garbage"]


needs_native = pytest.mark.skipif(not native.native_available(),
                                  reason="native core unavailable")


@needs_native
def test_native_decode_resize_parity(blobs):
    from PIL import Image

    imgs, encoded = blobs
    out, ok = native.decode_resize_batch(encoded, 48, 56)
    assert out.shape == (4, 48, 56, 3) and out.dtype == np.uint8
    assert ok.tolist() == [True, True, True, False]
    assert not out[3].any()  # failed row zeroed
    for i, blob in enumerate(encoded[:3]):
        ref = np.asarray(Image.open(_io.BytesIO(blob)).convert("RGB")
                         .resize((56, 48), Image.BILINEAR))
        diff = np.abs(out[i].astype(int) - ref.astype(int))
        assert diff.mean() < 8.0, f"img {i} mean diff {diff.mean()}"


@needs_native
def test_native_resize_batch(blobs):
    imgs, _ = blobs
    out = native.resize_batch_rgb(imgs, 32, 32)
    assert out.shape == (3, 32, 32, 3)
    # identity resize is exact
    same = native.resize_batch_rgb([imgs[1]], 64, 64)
    np.testing.assert_array_equal(same[0], imgs[1])
    with pytest.raises(ValueError, match="uint8"):
        native.resize_batch_rgb([np.zeros((4, 4), np.uint8)], 8, 8)


def test_decode_resize_batch_api(blobs):
    """Public fused API works regardless of which backend serves it."""
    _, encoded = blobs
    out, ok = decodeResizeBatch(encoded, 40, 40)
    assert out.shape == (4, 40, 40, 3)
    assert ok.tolist() == [True, True, True, False]


def test_decode_resize_batch_pil_fallback(blobs, monkeypatch):
    """Force the PIL path and compare against the default path's shape and
    mask behavior."""
    _, encoded = blobs
    monkeypatch.setattr(
        "sparkdl_tpu.image.io._native_io_preferred", lambda: False)
    out, ok = decodeResizeBatch(encoded, 40, 40)
    assert out.shape == (4, 40, 40, 3)
    assert ok.tolist() == [True, True, True, False]


def test_files_to_model_batch(fixture_images):
    paths = fixture_images["paths"] + [fixture_images["bad"], "/nope.jpg"]
    out, ok = filesToModelBatch(paths, 32, 32)
    assert out.shape == (len(paths), 32, 32, 3)
    assert ok.tolist() == [True] * 3 + [False, False]
