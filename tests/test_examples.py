"""Executable-docs guard: the migration example must keep running as the
APIs evolve (it is the reference-user's entry document)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_migration_example_runs(tmp_path):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": REPO,
        "TF_CPP_MIN_LOG_LEVEL": "2",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples",
                                      "migrate_from_sparkdl.py")],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert '{"migration_smoke": "ok"}' in proc.stdout
