"""Executable-docs guard: the migration example must keep running as the
APIs evolve (it is the reference-user's entry document)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(script: str, marker: str, cwd, extra_env=None,
                 pop_env=()):
    """Shared runner: one place owns the subprocess contract (cwd
    isolation, timeout, stderr truncation, marker assert)."""
    env = dict(os.environ)
    env.update({"PYTHONPATH": REPO, "TF_CPP_MIN_LOG_LEVEL": "2"})
    env.update(extra_env or {})
    for k in pop_env:
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script)],
        env=env, cwd=str(cwd), capture_output=True, text=True,
        timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert marker in proc.stdout


@pytest.mark.slow
def test_migration_example_runs(tmp_path):
    _run_example(
        "migrate_from_sparkdl.py", '{"migration_smoke": "ok"}', tmp_path,
        extra_env={"JAX_PLATFORMS": "cpu",
                   "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})


@pytest.mark.slow
def test_serving_quickstart_example_runs(tmp_path):
    """The serving subsystem's executable documentation (threads,
    asyncio, transformer parity, shared-queue UDF) — keep it green."""
    _run_example(
        "serving_quickstart.py", '"serving_quickstart": "ok"', tmp_path,
        extra_env={"JAX_PLATFORMS": "cpu",
                   "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})


@pytest.mark.slow
def test_distributed_fit_example_runs(tmp_path):
    """The multi-controller training example (2 processes x 2 virtual
    devices, dp=4, vs a single-controller oracle) is the topology
    envelope's executable documentation — keep it green."""
    _run_example("distributed_fit.py", '"distributed_fit": "ok"',
                 tmp_path,
                 pop_env=("XLA_FLAGS",))  # example provisions devices
