"""Multi-host scaffolding tests (VERDICT round 1, Missing #4).

Real multi-process jax cannot run inside one pytest process; these tests
pin the deterministic sharding math, the single-process degenerate paths
(which production code now routes through), and that the estimator fit is
unchanged under processes=1.
"""

import numpy as np
import pytest

from sparkdl_tpu.parallel import distributed as dist
from sparkdl_tpu.parallel import get_mesh
from sparkdl_tpu.parallel.mesh import batch_sharding


def test_shard_files_deterministic_and_balanced():
    paths = [f"/data/img_{i:04d}.jpg" for i in range(103)]
    shuffled = list(reversed(paths))  # every host may list in any order
    shards = [dist.shard_files(shuffled, index=i, count=4) for i in range(4)]
    # disjoint, complete, balanced within 1
    merged = sorted(p for s in shards for p in s)
    assert merged == sorted(paths)
    sizes = [len(s) for s in shards]
    assert max(sizes) - min(sizes) <= 1
    # deterministic regardless of input order
    assert shards[2] == dist.shard_files(paths, index=2, count=4)


def test_shard_files_validation():
    with pytest.raises(ValueError, match="count"):
        dist.shard_files(["a"], index=0, count=0)
    with pytest.raises(ValueError, match="out of range"):
        dist.shard_files(["a"], index=3, count=2)


def test_shard_files_defaults_to_process_info():
    # single process: index 0 of 1 -> identity (sorted)
    assert dist.shard_files(["b", "a"]) == ["a", "b"]


def test_local_batch_size():
    assert dist.local_batch_size(64, count=4) == 16
    assert dist.local_batch_size(64) == 64  # pc=1
    with pytest.raises(ValueError, match="not divisible"):
        dist.local_batch_size(10, count=4)


def test_initialize_noop_single_process():
    assert dist.initialize() is False
    assert dist.initialize(num_processes=1) is False


def test_put_sharded_single_process_matches_device_put():
    import jax

    mesh = get_mesh()
    sharding = batch_sharding(mesh)
    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    arr = dist.put_sharded(sharding, x)
    assert arr.sharding.is_equivalent_to(sharding, ndim=2)
    np.testing.assert_array_equal(np.asarray(arr), x)


def test_fit_goes_through_put_sharded(monkeypatch):
    """The estimator's batch-put path must route through the distributed
    helper so multi-controller assembly is the SAME code path."""
    import jax.numpy as jnp
    import optax

    from sparkdl_tpu.parallel import train as train_lib
    from sparkdl_tpu.parallel.train import fit_data_parallel

    calls = []
    orig = dist.put_sharded

    def spy(sharding, data):
        calls.append(np.asarray(data).shape)
        return orig(sharding, data)

    monkeypatch.setattr(dist, "put_sharded", spy)

    def predict(p, xb):
        return jnp.asarray(xb) @ p["w"]

    x = np.random.default_rng(0).normal(size=(32, 3)).astype(np.float32)
    y = (x @ np.ones((3, 1), np.float32))
    params = {"w": np.zeros((3, 1), np.float32)}
    fitted, losses = fit_data_parallel(
        predict, params, x, y, optimizer=optax.sgd(0.1), loss="mse",
        batch_size=16, epochs=2)
    assert calls, "put_batch did not route through distributed.put_sharded"
    assert losses[-1] < losses[0]


def _run_two_process_workers(tmp_path, ckpt=None, mode="arrays"):
    """Spawn two REAL jax.distributed worker processes and return their
    parsed result dicts (with the 2-process / 2x2-device topology
    asserted).  Shared by the arrays- and stream-mode integration tests."""
    import json
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:  # free port for the coordinator
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "_multihost_worker.py")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        # repo ONLY: an inherited sitecustomize path (e.g. a TPU plugin's)
        # pre-initializes jax at interpreter start, which would silently
        # defeat jax.distributed.initialize in the worker.
        "PYTHONPATH": repo,
        "TF_CPP_MIN_LOG_LEVEL": "2",
    })
    outs = [str(tmp_path / f"out_{i}.json") for i in range(2)]
    procs = []
    try:
        # spawn INSIDE the try: a failed second Popen must still kill the
        # first worker (otherwise it hangs forever in the coordinator
        # handshake as an orphan)
        for i in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, worker, str(i), "2", str(port), outs[i],
                 ckpt or "-", mode],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        for p in procs:
            stdout, _ = p.communicate(timeout=300)
            assert p.returncode == 0, stdout.decode(errors="replace")[-4000:]
    finally:
        for p in procs:
            p.kill()

    results = []
    for path in outs:
        with open(path) as f:
            results.append(json.load(f))
    # the topology actually formed: 2 processes x 2 local devices
    assert all(r["process_count"] == 2 for r in results)
    assert all(r["device_count"] == 4 for r in results)
    assert all(r["local_device_count"] == 2 for r in results)
    return results


@pytest.mark.slow
def test_two_process_fit_unequal_shards(tmp_path):
    """REAL 2-process jax.distributed integration (VERDICT r2 Missing #3):
    two subprocesses on the CPU backend, 2 virtual devices each, UNEQUAL
    local shards (10 vs 6 rows).  Exercises put_sharded's
    make_array_from_process_local_data branch, the global steps-per-epoch
    allgather (the old local-count derivation deadlocked here), and
    process-0-gated checkpoint writes."""
    import os

    ckpt = str(tmp_path / "ckpt")
    results = _run_two_process_workers(tmp_path, ckpt=ckpt)
    # same number of collective steps -> both completed 3 epochs
    assert all(len(r["losses"]) == 3 for r in results)
    # params are replicated: every host must hold the identical fit
    np.testing.assert_allclose(results[0]["w"], results[1]["w"],
                               rtol=1e-6, atol=1e-7)
    assert all(np.isfinite(r["losses"]).all() for r in results)
    # single-writer checkpointing: epochs saved exactly once (by process 0)
    saved = sorted(d for d in os.listdir(ckpt) if d.startswith("epoch_"))
    assert saved == ["epoch_000001", "epoch_000002", "epoch_000003"]


@pytest.mark.slow
def test_two_process_streaming_fit(tmp_path):
    """REAL 2-process streaming fit: re-iterable chunk sources with
    unequal per-host rows and a PINNED steps_per_epoch (the
    multi-controller streaming contract) — both hosts complete the same
    number of collective steps and hold identical fitted params."""
    results = _run_two_process_workers(tmp_path, mode="stream")
    assert all(len(r["losses"]) == 3 for r in results)
    np.testing.assert_allclose(results[0]["w"], results[1]["w"],
                               rtol=1e-6, atol=1e-7)
    assert all(np.isfinite(r["losses"]).all() for r in results)


@pytest.mark.slow
def test_two_process_tensor_parallel_fit(tmp_path):
    """REAL 2-process dp2 x tp2 fit (VERDICT r3 #9): the model axis spans
    devices while the data axis spans PROCESSES, so every step's
    activation/gradient collectives cross the process boundary.  Both
    hosts must hold identical gathered params, and the fit must match a
    single-process dp2 x tp2 oracle on the same data/batch order."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from sparkdl_tpu.parallel.train import make_train_step
    from tests._multihost_worker import tp_fit_reference

    results = _run_two_process_workers(tmp_path, mode="tp")
    assert all(r["mesh_shape"] == {"data": 2, "model": 2} for r in results)
    assert all(len(r["losses"]) == 3 for r in results)
    np.testing.assert_allclose(results[0]["head_kernel"],
                               results[1]["head_kernel"],
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(results[0]["body"], results[1]["body"],
                               rtol=1e-6, atol=1e-7)

    # single-process oracle: same dp2 x tp2 topology on 4 local devices
    x, y, params0, epochs = tp_fit_reference()
    mesh = get_mesh(num_devices=4, model_parallel=2)

    def predict(p, xb):
        h = jnp.tanh(jnp.asarray(xb) @ p["body"])
        return h @ p["head"]["kernel"] + p["head"]["bias"]

    def ce(logits, yb):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb.astype(jnp.int32))

    def tp_rule(path, leaf):
        if path.endswith("head/kernel"):
            return P(None, "model")
        if path.endswith("head/bias"):
            return P("model")
        return P()

    opt = optax.sgd(0.1)
    step = make_train_step(predict, ce, opt, mesh=mesh, cache=False,
                           param_specs=tp_rule, params_template=params0)
    params, opt_state = step.put_state(params0, opt.init(params0))
    for _ in range(epochs):
        for off in range(0, len(x), 8):
            bx, by = step.put_batch(x[off:off + 8], y[off:off + 8])
            params, opt_state, lval = step(params, opt_state, bx, by)
    gather = jax.jit(lambda p: p, out_shardings=step.replicated)
    oracle = jax.tree_util.tree_map(np.asarray, gather(params))
    np.testing.assert_allclose(
        np.asarray(results[0]["head_kernel"]),
        oracle["head"]["kernel"].ravel(), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(results[0]["body"]),
        oracle["body"].ravel(), rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_two_process_fit_steps_per_execution(tmp_path):
    """steps_per_execution under multi-controller: the stacked-batch
    global assembly (put_batch_stack -> make_array_from_process_local_data
    with a leading k dim) must produce the same fit as the one-step
    2-process run."""
    base = _run_two_process_workers(tmp_path, mode="arrays")
    (tmp_path / "spe").mkdir()
    packed = _run_two_process_workers(tmp_path / "spe", mode="arrays_spe")
    assert all(len(r["losses"]) == 3 for r in packed)
    np.testing.assert_allclose(packed[0]["w"], packed[1]["w"],
                               rtol=1e-6, atol=1e-7)
    # parity with the one-step 2-process fit
    np.testing.assert_allclose(base[0]["losses"], packed[0]["losses"],
                               rtol=1e-5)
    np.testing.assert_allclose(base[0]["w"], packed[0]["w"],
                               rtol=1e-5, atol=1e-7)
