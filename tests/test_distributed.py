"""Multi-host scaffolding tests (VERDICT round 1, Missing #4).

Real multi-process jax cannot run inside one pytest process; these tests
pin the deterministic sharding math, the single-process degenerate paths
(which production code now routes through), and that the estimator fit is
unchanged under processes=1.
"""

import numpy as np
import pytest

from sparkdl_tpu.parallel import distributed as dist
from sparkdl_tpu.parallel import get_mesh
from sparkdl_tpu.parallel.mesh import batch_sharding


def test_shard_files_deterministic_and_balanced():
    paths = [f"/data/img_{i:04d}.jpg" for i in range(103)]
    shuffled = list(reversed(paths))  # every host may list in any order
    shards = [dist.shard_files(shuffled, index=i, count=4) for i in range(4)]
    # disjoint, complete, balanced within 1
    merged = sorted(p for s in shards for p in s)
    assert merged == sorted(paths)
    sizes = [len(s) for s in shards]
    assert max(sizes) - min(sizes) <= 1
    # deterministic regardless of input order
    assert shards[2] == dist.shard_files(paths, index=2, count=4)


def test_shard_files_validation():
    with pytest.raises(ValueError, match="count"):
        dist.shard_files(["a"], index=0, count=0)
    with pytest.raises(ValueError, match="out of range"):
        dist.shard_files(["a"], index=3, count=2)


def test_shard_files_defaults_to_process_info():
    # single process: index 0 of 1 -> identity (sorted)
    assert dist.shard_files(["b", "a"]) == ["a", "b"]


def test_local_batch_size():
    assert dist.local_batch_size(64, count=4) == 16
    assert dist.local_batch_size(64) == 64  # pc=1
    with pytest.raises(ValueError, match="not divisible"):
        dist.local_batch_size(10, count=4)


def test_initialize_noop_single_process():
    assert dist.initialize() is False
    assert dist.initialize(num_processes=1) is False


def test_put_sharded_single_process_matches_device_put():
    import jax

    mesh = get_mesh()
    sharding = batch_sharding(mesh)
    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    arr = dist.put_sharded(sharding, x)
    assert arr.sharding.is_equivalent_to(sharding, ndim=2)
    np.testing.assert_array_equal(np.asarray(arr), x)


def test_fit_goes_through_put_sharded(monkeypatch):
    """The estimator's batch-put path must route through the distributed
    helper so multi-controller assembly is the SAME code path."""
    import jax.numpy as jnp
    import optax

    from sparkdl_tpu.parallel import train as train_lib
    from sparkdl_tpu.parallel.train import fit_data_parallel

    calls = []
    orig = dist.put_sharded

    def spy(sharding, data):
        calls.append(np.asarray(data).shape)
        return orig(sharding, data)

    monkeypatch.setattr(dist, "put_sharded", spy)

    def predict(p, xb):
        return jnp.asarray(xb) @ p["w"]

    x = np.random.default_rng(0).normal(size=(32, 3)).astype(np.float32)
    y = (x @ np.ones((3, 1), np.float32))
    params = {"w": np.zeros((3, 1), np.float32)}
    fitted, losses = fit_data_parallel(
        predict, params, x, y, optimizer=optax.sgd(0.1), loss="mse",
        batch_size=16, epochs=2)
    assert calls, "put_batch did not route through distributed.put_sharded"
    assert losses[-1] < losses[0]
