"""jax.profiler observability (VERDICT round 1, Missing #7 / SURVEY.md §5
tracing bullet)."""

import glob
import os

import numpy as np

from sparkdl_tpu.utils.metrics import Metrics, StepTimer, throughput_counter


def test_metrics_profile_writes_trace(tmp_path):
    import jax
    import jax.numpy as jnp

    m = Metrics()
    d = str(tmp_path / "trace")
    x = np.ones((8, 8), np.float32)
    with m.profile(d, block_on=None):
        out = jax.jit(lambda a: jnp.tanh(a @ a))(x)
        jax.block_until_ready(out)
    # a non-empty trace dir with at least one xplane file
    files = [p for p in glob.glob(os.path.join(d, "**", "*"), recursive=True)
             if os.path.isfile(p)]
    assert files, "profiler trace dir is empty"
    assert any("xplane" in os.path.basename(p) for p in files), files
    assert m.timings_s["profile"]


def test_transformer_logs_throughput(fixture_images):
    # The sparkdl_tpu logger sets propagate=False, so pytest's caplog (which
    # captures via the root logger) never sees its records; attach a handler
    # directly to the framework logger instead.
    import logging

    from sparkdl_tpu.graph.function import ModelFunction
    from sparkdl_tpu.image.io import readImages
    from sparkdl_tpu.transformers import TFImageTransformer

    df = readImages(fixture_images["dir"])
    mf = ModelFunction(fn=lambda v, x: x.astype("float32").mean(axis=(1, 2)),
                       variables={})
    t = TFImageTransformer(inputCol="image", outputCol="o",
                           modelFunction=mf, inputSize=[8, 8], batchSize=8)

    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = logging.getLogger("sparkdl_tpu")
    handler = _Capture(level=logging.INFO)
    old_level = logger.level
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)
    try:
        t.transform(df)
    finally:
        logger.removeHandler(handler)
        logger.setLevel(old_level)
    assert any("img/s/chip" in msg for msg in records), records


def test_metrics_summary_and_timer():
    m = Metrics()
    m.incr("items", 5)
    m.gauge("depth", 2.0)
    timer = StepTimer(m, name="step")
    with timer.time():
        pass
    s = m.summary()
    assert s["items"] == 5 and s["depth"] == 2.0
    assert s["step.count"] == 1
    tc = throughput_counter(100, 2.0, num_devices=4)
    assert tc["items_per_sec"] == 50.0
    assert tc["items_per_sec_per_chip"] == 12.5


def test_metrics_percentiles_and_histograms():
    """The serving layer's latency surface: nearest-rank percentiles over
    timing AND unitless histogram series, with p50/p99 in summary."""
    m = Metrics()
    for v in range(1, 101):  # 0.01s .. 1.00s
        m.record_time("lat", v / 100.0)
    assert m.percentile("lat", 50) == 0.50
    assert m.percentile("lat", 99) == 0.99
    assert m.percentile("lat", 100) == 1.00
    assert m.percentile("absent", 50) is None
    m.observe("fill", 0.25)
    m.observe("fill", 0.75)
    s = m.summary()
    assert s["lat.p50_s"] == 0.50 and s["lat.p99_s"] == 0.99
    assert s["fill.mean"] == 0.5 and s["fill.count"] == 2
    assert s["fill.p50"] == 0.25 and s["fill.p99"] == 0.75


def test_metrics_series_are_bounded():
    """Per-request serving series must not grow without limit: on
    overflow the oldest half drops, recent samples survive."""
    m = Metrics(max_samples=8)
    for v in range(20):
        m.record_time("lat", float(v))
        m.observe("h", float(v))
    assert len(m.timings_s["lat"]) <= 8
    assert len(m.histograms["h"]) <= 8
    assert m.timings_s["lat"][-1] == 19.0  # newest retained
    assert m.percentile("lat", 100) == 19.0
