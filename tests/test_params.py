"""Param system tests — the config-system contract (reference C16)."""

import pytest

from sparkdl_tpu.param import (
    HasInputCol,
    HasOutputCol,
    Param,
    Params,
    SparkDLTypeConverters,
    TypeConverters,
    keyword_only,
)


class _Stage(HasInputCol, HasOutputCol):
    threshold = Param("undefined", "threshold", "a float knob",
                      typeConverter=TypeConverters.toFloat)

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, threshold=None):
        super().__init__()
        self._setDefault(threshold=0.5)
        self._set(**self._input_kwargs)


def test_keyword_only_rejects_positional():
    with pytest.raises(TypeError):
        _Stage("x")


def test_defaults_and_overrides():
    s = _Stage(inputCol="in")
    assert s.getInputCol() == "in"
    assert s.getOrDefault("threshold") == 0.5
    s.set("threshold", 0.9)
    assert s.getOrDefault(s.threshold) == 0.9
    assert s.isSet("threshold") and s.hasDefault("threshold")


def test_string_addressability_for_grids():
    s = _Stage(inputCol="in")
    p = s.getParam("threshold")
    m = s.extractParamMap({p: 0.25})
    assert m[p] == 0.25
    with pytest.raises(ValueError):
        s.getParam("nope")


def test_type_converters_validate():
    s = _Stage(inputCol="in")
    with pytest.raises(TypeError):
        s.set("threshold", "not a float")
    with pytest.raises(TypeError):
        s.set("inputCol", 42)
    assert TypeConverters.toInt(3.0) == 3
    with pytest.raises(TypeError):
        TypeConverters.toInt(3.5)


def test_instances_do_not_alias():
    a = _Stage(inputCol="a")
    b = _Stage(inputCol="b")
    a.set("threshold", 0.1)
    assert b.getOrDefault("threshold") == 0.5
    assert a.uid != b.uid


def test_copy_with_extra():
    a = _Stage(inputCol="a")
    c = a.copy({a.getParam("threshold"): 0.7})
    assert c.getOrDefault("threshold") == 0.7
    assert a.getOrDefault("threshold") == 0.5


def test_supported_name_converter():
    conv = SparkDLTypeConverters.supportedNameConverter(["InceptionV3", "ResNet50"])
    assert conv("inceptionv3") == "InceptionV3"
    with pytest.raises(TypeError):
        conv("AlexNet")
    with pytest.raises(TypeError):
        conv(7)


def test_optimizer_and_loss_converters():
    import optax
    # Name strings construct ready-to-use optimizers with default LRs.
    opt = SparkDLTypeConverters.toOptimizer("adam")
    assert isinstance(opt, optax.GradientTransformation)
    got = SparkDLTypeConverters.toOptimizer(optax.sgd(0.1))
    assert isinstance(got, optax.GradientTransformation)
    # Zero-arg factories pass through for fit-time construction.
    factory = SparkDLTypeConverters.toOptimizer(lambda: optax.adam(2e-3))
    assert callable(factory)
    with pytest.raises(TypeError):
        SparkDLTypeConverters.toOptimizer("nonsense")
    assert SparkDLTypeConverters.toLoss("mean_squared_error") == "mse"
    with pytest.raises(TypeError):
        SparkDLTypeConverters.toLoss("nonsense")


def test_explain_params():
    s = _Stage(inputCol="in")
    text = s.explainParams()
    assert "threshold" in text and "inputCol" in text
