"""Shared-backbone head fan-out tier (ISSUE 17).

Tier-1, CPU-only, seconds-scale: the headline seeded-Zipf 64-tenant
replay (backbone dispatches == distinct content digests, warm-path
latency under the full-model baseline, every row bit-identical to an
INDEPENDENT per-tenant full-model oracle), head hot-swap under load
with the three-witness no-backbone-recompile proof, feature-cache
survival across head churn vs rotation on backbone weight change,
stacked-bank eviction, the indivisible/oversized fallback modes, the
``head.dispatch``/``head.swap`` fault sites, the flight events on the
blackbox timeline, the lockfile-pinned program pair, and the fleet's
``add_fanout_model``/``add_head``/``swap_head`` surface.
"""

import threading
import time

import numpy as np
import pytest

from sparkdl_tpu import faults
from sparkdl_tpu.parallel.engine import (HeadBank, dense_head_row,
                                         head_fanout_backbone_fn,
                                         head_fanout_oracle_fn)
from sparkdl_tpu.serving import InferenceCache
from sparkdl_tpu.serving.cache import (feature_namespace,
                                       head_fanout_benchmark,
                                       lockfile_model_fingerprint)
from sparkdl_tpu.serving.server import HeadFanoutServer

D_IN, D_FEAT, CLASSES = 12, 16, 4


def _variables(seed=0):
    rng = np.random.default_rng(seed)
    return {"backbone": rng.normal(size=(D_IN, D_FEAT)).astype(np.float32)}


def _head(seed):
    rng = np.random.default_rng(100 + seed)
    return {"kernel": rng.normal(size=(D_FEAT, CLASSES)).astype(np.float32),
            "bias": rng.normal(size=(CLASSES,)).astype(np.float32)}


def _payload(seed):
    return np.random.default_rng(200 + seed).normal(
        size=(D_IN,)).astype(np.float32)


def _server(cache=False, variables=None, **kw):
    kw.setdefault("max_batch_size", 8)
    kw.setdefault("max_wait_ms", 0.5)
    return HeadFanoutServer(
        head_fanout_backbone_fn,
        variables if variables is not None else _variables(),
        model_desc="headfanout", cache=cache, **kw)


_oracle_jit = None


def _oracle(variables, head, x):
    """The independent full-model oracle: ONE unbatched row through its
    own jit of ``head_fanout_oracle_fn`` — never the fan-out pipeline."""
    global _oracle_jit
    import jax
    import jax.numpy as jnp

    if _oracle_jit is None:
        _oracle_jit = jax.jit(head_fanout_oracle_fn)
    return np.asarray(_oracle_jit(
        {"backbone": variables["backbone"], **head}, jnp.asarray(x)))


def _wrap_slow(srv, sleep_s=0.0):
    """Count (and optionally slow) the BACKBONE's dispatches."""
    calls = [0]
    for b in srv.bucket_sizes:
        eng = srv.backbone._engine_for(b)
        real = eng.run_padded

        def slow(batch, _real=real):
            calls[0] += 1
            if sleep_s:
                time.sleep(sleep_s)
            return _real(batch)

        eng.run_padded = slow
    return calls


# -- the headline replay ----------------------------------------------------
def test_headline_zipf_64_tenant_replay():
    """ISSUE 17 acceptance: a seeded Zipf-content replay over 64
    tenants and a sleep-wrapped backbone — backbone dispatches equal
    distinct content digests (featurize ONCE), per-tenant outputs are
    bit-identical to independent full-model oracles, and the warm
    per-request latency sits well under the full-model baseline."""
    out = head_fanout_benchmark(n_requests=96, universe=12, tenants=64,
                                dispatch_ms=5.0, seed=0)
    assert out["bit_identical"] is True
    assert out["backbone_dispatches"] == out["distinct"]
    assert out["dispatch_ratio"] == 1.0
    assert out["baseline_dispatches"] == out["n_requests"]
    assert out["warm_p50_ms"] < out["baseline_p50_ms"]
    assert out["feature_hits"] > 0
    assert out["bank_mode"] == "stacked"
    assert out["bank_capacity"] == 64
    assert out["bank_param_bytes_per_chip"] > 0


def test_mixed_tenant_batch_one_head_pass_bit_identical():
    """K tenants' rows in one predict_batch cost ONE head pass, and
    every row matches its tenant's own oracle bitwise."""
    variables = _variables()
    with _server(variables=variables) as srv:
        heads = {f"t{i}": _head(i) for i in range(5)}
        for t, h in heads.items():
            srv.add_head(t, h)
        srv.warmup(_payload(0))
        xs = [_payload(i % 3) for i in range(7)]
        ts = [f"t{i % 5}" for i in range(7)]
        before = srv.metrics.snapshot_raw()["counters"].get(
            "headfanout.head_passes", 0)
        rows = srv.predict_batch(xs, ts)
        after = srv.metrics.snapshot_raw()["counters"].get(
            "headfanout.head_passes", 0)
        assert after - before == 1
        for x, t, y in zip(xs, ts, rows):
            ref = _oracle(variables, heads[t], x)
            assert np.asarray(y).tobytes() == ref.tobytes()


# -- head hot-swap ----------------------------------------------------------
def test_head_hot_swap_under_load_proof_and_bit_correctness():
    """Swap a tenant's head mid-load: zero failed futures, every output
    bitwise equal to the OLD or NEW oracle (never a torn head), the
    swapped tenant serves the new head afterwards, and the swap report
    carries all three no-backbone-recompile witnesses."""
    variables = _variables()
    old, new = _head(1), _head(99)
    with _server(variables=variables, cache=InferenceCache()) as srv:
        srv.add_head("a", old)
        srv.add_head("b", _head(2))
        srv.warmup(_payload(0))
        srv.warm_head(np.zeros(D_FEAT, np.float32))
        x = _payload(0)
        srv.predict(x, "a")  # warm the feature cache for this digest

        results, errors = [], []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    results.append(np.asarray(srv.predict(x, "a")))
                # graftlint: allow=SDL003 reason=collected and asserted empty below
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        report = srv.swap_head("a", new)
        time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join()

        assert not errors, errors
        assert len(results) > 0
        ref_old = _oracle(variables, old, x)
        ref_new = _oracle(variables, new, x)
        for y in results:
            assert (y.tobytes() == ref_old.tobytes()
                    or y.tobytes() == ref_new.tobytes())
        # post-swap requests serve the NEW head exactly
        got = np.asarray(srv.predict(x, "a"))
        assert got.tobytes() == ref_new.tobytes()
        # the three-witness proof
        assert report["no_backbone_recompile"] is True
        assert report["head_jit_shared"] is True
        assert report["fingerprint_pinned"] is True
        assert all(b["shared_jit"] for b in report["buckets"].values())


def test_feature_cache_survives_head_swap():
    """The feature-cut namespace is backbone identity: a head swap must
    keep warm feature entries serving (zero new backbone dispatches),
    with the post-swap output already on the NEW head."""
    variables = _variables()
    cache = InferenceCache()
    with _server(variables=variables, cache=cache) as srv:
        srv.add_head("a", _head(1))
        srv.warmup(_payload(0))
        calls = _wrap_slow(srv)
        x = _payload(5)
        srv.predict(x, "a")
        assert calls[0] == 1
        entries_before = len(cache)
        srv.swap_head("a", _head(7))
        assert len(cache) == entries_before  # nothing invalidated
        got = np.asarray(srv.predict(x, "a"))
        assert calls[0] == 1, "feature hit must skip the backbone"
        ref = _oracle(variables, _head(7), x)
        assert got.tobytes() == ref.tobytes()


def test_backbone_weight_change_rotates_feature_namespace():
    """Different backbone weights → different weight digest → a
    DIFFERENT feature namespace: the old entries are unreachable, so a
    stale featurization can never reach the new backbone's tenants."""
    cache = InferenceCache()
    with _server(variables=_variables(0), cache=cache) as srv1:
        srv1.add_head("a", _head(1))
        srv1.warmup(_payload(0))
        srv1.predict(_payload(5), "a")
        ns1 = srv1.feature_namespace
    # close() must NOT reclaim the namespace (backbone identity, not
    # server identity): a restarted server over the SAME backbone
    # serves the entries warm
    with _server(variables=_variables(0), cache=cache) as srv2:
        srv2.add_head("a", _head(1))
        srv2.warmup(_payload(0))
        calls = _wrap_slow(srv2)
        srv2.predict(_payload(5), "a")
        assert srv2.feature_namespace == ns1
        assert calls[0] == 0, "same backbone must inherit warm entries"
    with _server(variables=_variables(3), cache=cache) as srv3:
        srv3.add_head("a", _head(1))
        srv3.warmup(_payload(0))
        calls = _wrap_slow(srv3)
        assert srv3.feature_namespace != ns1
        srv3.predict(_payload(5), "a")
        assert calls[0] == 1, "new backbone weights must re-featurize"
    # and the schema itself: head churn appears NOWHERE in the key
    ns = feature_namespace("headfanout", "fp", "digest")
    assert ns == ("features", "headfanout", "fp", "digest")
    assert feature_namespace("headfanout", None, "d") == (
        "features", "headfanout", "unpinned", "d")


def test_stacked_bank_evicts_departed_tenant():
    """Eviction re-stacks the survivors; the departed tenant fails
    loudly (KeyError) instead of serving a stale row."""
    variables = _variables()
    with _server(variables=variables) as srv:
        heads = {f"t{i}": _head(i) for i in range(3)}
        for t, h in heads.items():
            srv.add_head(t, h)
        srv.warmup(_payload(0))
        report = srv.remove_head("t1")
        assert report["op"] == "remove"
        assert srv.tenants() == ["t0", "t2"]
        with pytest.raises(KeyError):
            srv.predict(_payload(0), "t1")
        for t in ("t0", "t2"):
            got = np.asarray(srv.predict(_payload(1), t))
            ref = _oracle(variables, heads[t], _payload(1))
            assert got.tobytes() == ref.tobytes()


# -- degraded modes ---------------------------------------------------------
def test_indivisible_head_falls_back_per_tenant_not_crash():
    """A head whose pytree cannot stack with the bank flips the bank to
    per-tenant fallback: every tenant (old shape and new) keeps serving
    bit-identically through the SAME fan-out jit as a bank of one."""
    bank = HeadBank()
    h0 = _head(0)
    bank.add_head("a", h0)
    jit_before = bank.jit_info()["jit_id"]
    odd = {"kernel": np.random.default_rng(9).normal(
        size=(D_FEAT, CLASSES + 3)).astype(np.float32),
        "bias": np.zeros(CLASSES + 3, np.float32)}
    bank.add_head("weird", odd)  # must degrade, not raise
    assert bank.mode == "fallback"
    assert bank.jit_info()["jit_id"] == jit_before
    assert "mismatch" in bank.stats()["fallback_reason"]
    feats = np.random.default_rng(3).normal(
        size=(D_FEAT,)).astype(np.float32)
    got_a = np.asarray(bank.dispatch(feats[None], ["a"]))[0]
    ref_a = np.asarray(dense_head_row(h0, feats))
    assert got_a.tobytes() == ref_a.tobytes()
    got_w = np.asarray(bank.dispatch(feats[None], ["weird"]))[0]
    ref_w = np.asarray(dense_head_row(odd, feats))
    assert got_w.shape == (CLASSES + 3,)
    assert got_w.tobytes() == ref_w.tobytes()


def test_oversized_bank_falls_back_within_budget():
    """A bank whose stacked bytes would bust ``hbm_budget_bytes``
    degrades to per-tenant dispatch instead of crashing, and the
    budget check uses the same ``param_sharding_stats`` ledger GC005
    audits."""
    one_head_bytes = (D_FEAT * CLASSES + CLASSES) * 4
    bank = HeadBank(hbm_budget_bytes=3 * one_head_bytes)
    bank.add_head("a", _head(1))
    bank.add_head("b", _head(2))
    assert bank.mode == "stacked"  # capacity 2 fits
    bank.add_head("c", _head(3))   # capacity 4 would bust the budget
    assert bank.mode == "fallback"
    assert "hbm_budget_bytes" in bank.stats()["fallback_reason"]
    feats = np.random.default_rng(4).normal(
        size=(2, D_FEAT)).astype(np.float32)
    out = bank.dispatch(feats, ["a", "c"])
    for i, t in enumerate(("a", "c")):
        ref = np.asarray(dense_head_row(_head({"a": 1, "c": 3}[t]),
                                        feats[i]))
        assert np.asarray(out[i]).tobytes() == ref.tobytes()


# -- fault sites + flight events (SDL008) -----------------------------------
def test_head_fault_sites_registered_and_abort_cleanly():
    from sparkdl_tpu.faults.sites import SITE_HELP, validate_site

    for site in ("head.dispatch", "head.swap"):
        assert site in SITE_HELP
        validate_site(site)
    plan = faults.FaultPlan.parse(
        "seed=8;head.dispatch:error:times=1;head.swap:error:times=1")
    assert plan.has_rules("head.dispatch") and plan.has_rules("head.swap")

    variables = _variables()
    old = _head(1)
    with _server(variables=variables) as srv:
        srv.add_head("a", old)
        srv.warmup(_payload(0))
        x = _payload(0)
        # head.swap fires BEFORE state changes: the bank is unchanged
        # and the OLD head keeps serving
        with faults.active(faults.FaultPlan.parse(
                "seed=8;head.swap:error:exc=fatal,times=1")):
            with pytest.raises(faults.InjectedFault):
                srv.swap_head("a", _head(9))
        got = np.asarray(srv.predict(x, "a"))
        assert got.tobytes() == _oracle(variables, old, x).tobytes()
        # head.dispatch fails that head pass only; the next one serves
        with faults.active(faults.FaultPlan.parse(
                "seed=8;head.dispatch:error:exc=fatal,times=1")):
            with pytest.raises(faults.InjectedFault):
                srv.predict_batch([x], ["a"])
        got = np.asarray(srv.predict(x, "a"))
        assert got.tobytes() == _oracle(variables, old, x).tobytes()


def test_head_events_cataloged_and_on_blackbox_timeline(tmp_path):
    from sparkdl_tpu.obs import flight
    from tools.blackbox import build_timeline

    for name in ("head.swap", "cache.feature_hit"):
        assert name in flight.EVENT_HELP
        flight.validate_event(name)
    rec = flight.configure(enabled=True, out_dir=str(tmp_path))
    try:
        with _server(cache=InferenceCache()) as srv:
            srv.add_head("a", _head(1))      # head.swap (op=add)
            srv.warmup(_payload(0))
            x = _payload(0)
            srv.predict(x, "a")              # cache.miss on features
            srv.predict(x, "a")              # cache.feature_hit
            srv.swap_head("a", _head(2))     # head.swap (op=swap)
        path = rec.dump()
    finally:
        flight.configure_from_env()
    doc = build_timeline(path)
    chain = doc["chain"]
    for name in ("head.swap", "cache.feature_hit"):
        assert name in chain, f"{name} missing from blackbox timeline"
    assert doc["counts"]["head.swap"] >= 2


# -- the lockfile pin -------------------------------------------------------
def test_lockfile_pins_headfanout_program_pair():
    """The backbone-cut and stacked-head programs are in the committed
    PROGRAMS.lock.json with byte-stable fingerprints, the backbone
    record resolves through ``lockfile_model_fingerprint`` (what the
    feature namespace and the swap proof key on), and the head record
    deliberately does NOT carry the model tag."""
    from sparkdl_tpu.analysis.program import (DEFAULT_LOCKFILE,
                                              audit_program,
                                              headfanout_dispatch_specs,
                                              read_lockfile)

    committed = read_lockfile(DEFAULT_LOCKFILE)["programs"]
    specs = headfanout_dispatch_specs()
    assert len(specs) == 2
    for spec in specs:
        assert spec.name in committed, spec.name
        rec = audit_program(spec)["record"]
        assert rec["fingerprint"] == committed[spec.name]["fingerprint"]
    backbone, heads = specs
    assert backbone.model == "headfanout" and heads.model is None
    fp = lockfile_model_fingerprint("headfanout")
    assert fp is not None
    # a fresh server over the canonical backbone pins that fingerprint
    with _server() as srv:
        assert srv.feature_namespace[2] == fp


# -- fleet surface ----------------------------------------------------------
def test_fleet_fanout_deploy_swap_and_guards():
    from sparkdl_tpu.serving.fleet import Fleet

    variables = _variables()
    with Fleet(max_batch_size=8, max_wait_ms=0.5) as fleet:
        fleet.add_fanout_model("multi", head_fanout_backbone_fn, variables,
                               model_desc="headfanout")
        r1 = fleet.add_head("multi", "a", _head(1))
        assert r1["head_version"] == 1
        srv = fleet._state("multi").server
        srv.warmup(_payload(0))
        srv.warm_head(np.zeros(D_FEAT, np.float32))
        x = _payload(0)
        got = np.asarray(fleet.predict("multi", x, tenant="a"))
        assert got.tobytes() == _oracle(variables, _head(1), x).tobytes()
        rep = fleet.swap_head("multi", "a", _head(5))
        assert rep["no_backbone_recompile"] is True
        assert rep["head_version"] == 2
        assert fleet.registry.head_versions("multi", "a") == [1, 2]
        got = np.asarray(fleet.predict("multi", x, tenant="a"))
        assert got.tobytes() == _oracle(variables, _head(5), x).tobytes()
        # backbone versioning is refused for fan-out entries
        fleet.add_version("multi", variables)
        with pytest.raises(RuntimeError, match="fan-out"):
            fleet.start_rollout("multi")
        # head ops are refused for plain entries
        fleet.add_model("plain", head_fanout_backbone_fn, variables)
        with pytest.raises(TypeError, match="not a head fan-out"):
            fleet.add_head("plain", "t", _head(1))
        # varz carries the fan-out section, JSON-clean
        import json

        v = fleet.varz()
        section = v["fleet"]["models"]["multi"]["headfanout"]
        assert section["tenants"] == ["a"]
        assert section["bank"]["mode"] == "stacked"
        json.dumps(v, default=str)
        assert v["fleet"]["registry"]["multi"]["heads"] == {"a": 2}
