"""Streaming (larger-than-RAM) estimator fit — VERDICT r2 missing #5 /
next-round #9: re-iterable epoch sources, O(chunk) host residency, parity
with the in-memory fit."""

import gc
import weakref

import numpy as np
import pytest

from sparkdl_tpu.parallel.train import (_stream_epoch_batches,
                                        fit_data_parallel,
                                        fit_data_parallel_stream)


def _chunks_of(x, y, sizes):
    off = 0
    for s in sizes:
        yield x[off:off + s], y[off:off + s]
        off += s


def test_stream_epoch_batches_shapes_and_tail_wrap():
    x = np.arange(22, dtype=np.float32)[:, None]
    y = np.arange(22, dtype=np.float32)
    batches = list(_stream_epoch_batches(
        _chunks_of(x, y, [5, 9, 3, 5]), batch_size=8))
    assert len(batches) == 3  # ceil(22/8)
    assert all(bx.shape == (8, 1) for bx, _ in batches)
    # rows preserved in order across chunk boundaries
    flat = np.concatenate([bx[:, 0] for bx, _ in batches])
    np.testing.assert_array_equal(flat[:22], np.arange(22))
    # tail wrapped with head-reservoir rows (full shape, no zeros)
    np.testing.assert_array_equal(batches[-1][0][6:, 0], [0.0, 1.0])


def test_stream_epoch_batches_pinned_steps():
    x = np.arange(16, dtype=np.float32)[:, None]
    y = np.arange(16, dtype=np.float32)
    # truncate
    got = list(_stream_epoch_batches(_chunks_of(x, y, [16]), 4, num_steps=2))
    assert len(got) == 2
    # extend: short stream wraps reservoir batches to reach the pin
    got = list(_stream_epoch_batches(_chunks_of(x, y, [16]), 8, num_steps=5))
    assert len(got) == 5
    assert all(bx.shape == (8, 1) for bx, _ in got)
    # stream smaller than one batch still yields a full batch
    got = list(_stream_epoch_batches(_chunks_of(x[:3], y[:3], [3]), 8))
    assert len(got) == 1 and got[0][0].shape == (8, 1)


def test_stream_fit_matches_in_memory(rng):
    import jax.numpy as jnp
    import optax

    w_true = rng.normal(size=(5, 1)).astype(np.float32)
    x = rng.normal(size=(32, 5)).astype(np.float32)
    y = x @ w_true

    def predict(p, xb):
        return jnp.asarray(xb) @ p["w"]

    opt = optax.sgd(0.1)
    params0 = {"w": np.zeros((5, 1), np.float32)}
    in_mem, losses_mem = fit_data_parallel(
        predict, dict(params0), x, y, optimizer=opt, loss="mse",
        batch_size=8, epochs=4, shuffle=False)

    def source():
        return _chunks_of(x, y, [8, 8, 8, 8])

    streamed, losses_stream = fit_data_parallel_stream(
        predict, dict(params0), source, optimizer=opt, loss="mse",
        batch_size=8, epochs=4)
    assert len(losses_stream) == 4
    np.testing.assert_allclose(losses_stream, losses_mem, rtol=1e-5)
    np.testing.assert_allclose(streamed["w"], in_mem["w"], rtol=1e-5,
                               atol=1e-6)


def test_stream_fit_releases_consumed_chunks(rng):
    """O(chunk) residency: by the time chunk i is yielded, chunk i-3 must
    already be garbage — the trainer may not accumulate the stream."""
    import jax.numpy as jnp
    import optax

    x = rng.normal(size=(80, 4)).astype(np.float32)
    y = (x @ rng.normal(size=(4, 1)).astype(np.float32))

    refs = []

    def source():
        refs.clear()

        def gen():
            for i in range(10):
                cx = x[i * 8:(i + 1) * 8].copy()
                cy = y[i * 8:(i + 1) * 8].copy()
                refs.append(weakref.ref(cx))
                if i >= 3:
                    gc.collect()
                    dead = [r() is None for r in refs[:i - 2]]
                    assert all(dead), (
                        f"chunk(s) {[j for j, d in enumerate(dead) if not d]}"
                        f" still alive when yielding chunk {i}")
                yield cx, cy

        return gen()

    def predict(p, xb):
        return jnp.asarray(xb) @ p["w"]

    fit_data_parallel_stream(
        predict, {"w": np.zeros((4, 1), np.float32)}, source,
        optimizer=optax.sgd(0.05), loss="mse", batch_size=8, epochs=2)


def test_estimator_fit_stream(fixture_images):
    """ImageFileEstimator.fit over a RecordBatch epoch source: epochs
    re-iterate the source; the fitted model matches the plumbing contract."""
    import pyarrow as pa

    from sparkdl_tpu.estimators import ImageFileEstimator
    from sparkdl_tpu.frame import DataFrame
    from sparkdl_tpu.graph.function import ModelFunction

    import jax.numpy as jnp

    paths = fixture_images["paths"] * 8  # 24 rows
    labels = [[1.0, 0.0] if i % 2 == 0 else [0.0, 1.0]
              for i in range(len(paths))]

    def loader(uri):
        from PIL import Image

        img = Image.open(uri).convert("RGB").resize((8, 8))
        return np.asarray(img, dtype=np.float32) / 255.0

    rng2 = np.random.default_rng(0)
    mf = ModelFunction(
        fn=lambda v, x: jnp.asarray(x).reshape(x.shape[0], -1) @ v["w"],
        variables={"w": rng2.normal(0, 0.01, (8 * 8 * 3, 2)
                                    ).astype(np.float32)})

    pulls = []

    def source():
        pulls.append(0)

        def gen():
            for off in range(0, len(paths), 6):
                yield pa.record_batch({
                    "uri": pa.array(paths[off:off + 6]),
                    "label": pa.array(labels[off:off + 6]),
                })

        return gen()

    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=mf, imageLoader=loader, optimizer="sgd",
        loss="mse", fitParams={"epochs": 3}, batchSize=8)
    model = est.fit(source)
    assert len(pulls) == 3  # one re-iteration per epoch
    assert len(model.trainLosses) == 3
    df = DataFrame({"uri": paths, "label": labels})
    rows = model.transform(df).collect()
    assert all(len(r["preds"]) == 2 for r in rows)


def test_stream_fit_with_train_batch_stats(fixture_images):
    """The streaming fit path supports trainBatchStats through the shared
    runner: BatchNorm statistics update during a stream-sourced fit."""
    import pyarrow as pa

    from sparkdl_tpu.estimators import ImageFileEstimator
    from tests.test_estimators import _bn_model_function, _loader

    mf = _bn_model_function()
    before = np.asarray(mf.variables["batch_stats"]["bn"]["mean"]).copy()
    paths = fixture_images["paths"] * 4
    labels = [[1.0, 0.0] if i % 2 == 0 else [0.0, 1.0]
              for i in range(len(paths))]

    def source():
        for off in range(0, len(paths), 6):
            yield pa.record_batch({
                "uri": pa.array(paths[off:off + 6]),
                "label": pa.array(labels[off:off + 6]),
            })

    est = ImageFileEstimator(
        inputCol="uri", outputCol="preds", labelCol="label",
        modelFunction=mf, imageLoader=_loader, optimizer="sgd",
        loss="categorical_crossentropy", trainBatchStats=True,
        fitParams={"epochs": 2}, batchSize=8)
    model = est.fit(lambda: source())
    after = np.asarray(
        model.getModelFunction().variables["batch_stats"]["bn"]["mean"])
    assert not np.allclose(before, after)


def test_stream_fit_steps_per_execution_parity():
    """steps_per_execution on the streaming loop: identical loss series
    and fitted params to the one-step stream fit (incl. the reservoir-
    wrapped ragged tail)."""
    import jax.numpy as jnp
    import optax

    from sparkdl_tpu.parallel.train import fit_data_parallel_stream

    rng = np.random.default_rng(5)
    x = rng.normal(size=(44, 6)).astype(np.float32)
    w_true = rng.normal(size=(6, 1)).astype(np.float32)
    y = x @ w_true

    def source():
        for off in range(0, len(x), 10):  # uneven chunks
            yield x[off:off + 10], y[off:off + 10]

    def predict(p, xb):
        return jnp.asarray(xb) @ p["w"]

    def fit(spe):
        return fit_data_parallel_stream(
            predict, {"w": np.zeros((6, 1), np.float32)}, source,
            optimizer=optax.sgd(0.05), loss="mse", batch_size=16,
            epochs=3, steps_per_execution=spe)

    (w1, l1), (w4, l4) = fit(1), fit(4)
    assert l1 == pytest.approx(l4, rel=1e-5)
    np.testing.assert_allclose(w1["w"], w4["w"], rtol=1e-5, atol=1e-7)


def test_stream_fit_spe_groups_do_not_pin_chunks():
    """Grouped steps must not retain chunk-sized view bases: while a group
    of spe batches is PENDING, every previously-yielded chunk must already
    be collectable (O(spe x batch) residency, not O(spe x chunk)).
    Checked with weakrefs from inside the batch generator — a version of
    _run_grouped_steps that holds raw views keeps each chunk's base alive
    through the pending group and fails here."""
    import gc
    import weakref

    from sparkdl_tpu.parallel.train import _run_grouped_steps

    class _SpyStep:
        def put_batch(self, bx, by):
            return bx, by

        def put_batch_stack(self, xs, ys):
            return xs, ys

        def multi(self, k):
            def run(params, opt_state, xs, ys):
                return params, opt_state, np.zeros(xs.shape[0], np.float32)

            return run

        def __call__(self, params, opt_state, bx, by):
            return params, opt_state, np.float32(0)

    chunk_refs = []

    def batches():
        for i in range(8):
            chunk = np.full((1000, 4), i, np.float32)  # one "big" chunk
            chunk_refs.append(weakref.ref(chunk))
            gc.collect()
            # every chunk except the immediately-previous one (the
            # consumer's loop variable legitimately holds that view until
            # its next assignment) must be dead, even though up to spe-1
            # batches sit in the pending group
            alive = [j for j, r in enumerate(chunk_refs[:-2])
                     if r() is not None]
            assert not alive, f"chunks {alive} pinned by the pending group"
            yield chunk[:8], np.zeros(8, np.float32)
            del chunk

    _run_grouped_steps(_SpyStep(), False, 4, batches(), {}, None, {})
    assert len(chunk_refs) == 8  # the stream actually ran
