"""Pipelined host/device execution tests (parallel.pipeline).

Contracts pinned here:
  * bit-identical outputs to the serial path on every scoring surface —
    engine (``map_batches``/``__call__``), transformer, UDF, and serving;
  * the synthetic slow-device benchmark proves the overlap: >= 1.5x
    throughput vs ``SPARKDL_PIPELINE=0`` with a simulated 100 ms dispatch
    latency on the CPU backend (the tier-1 contract run-tests.sh guards);
  * pipelined ``__call__`` streams into ONE preallocated output — a frame
    much larger than the in-flight window keeps peak host chunk residency
    bounded (no per-chunk accumulation list);
  * the ``SPARKDL_PIPELINE=0`` escape hatch, error propagation, and
    worker-thread cleanup on early consumer abandonment.
"""

import threading
import time
import weakref

import numpy as np
import pytest

from sparkdl_tpu.parallel import engine as engine_mod
from sparkdl_tpu.parallel.engine import InferenceEngine
from sparkdl_tpu.parallel.pipeline import (PipelinedRunner,
                                           pipeline_enabled_from_env,
                                           pipeline_stage_summary,
                                           synthetic_overlap_benchmark)
from sparkdl_tpu.utils.metrics import Metrics


def _fn(variables, x):
    import jax.numpy as jnp

    return jnp.tanh(x @ variables["w"] + variables["b"])


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(7)
    variables = {
        "w": rng.normal(size=(12, 5)).astype(np.float32),
        "b": rng.normal(size=(5,)).astype(np.float32),
    }
    x = rng.normal(size=(145, 12)).astype(np.float32)
    return variables, x


def _pipeline_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("sparkdl-pipeline")]


def _wait_threads_gone(timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not _pipeline_threads():
            return True
        time.sleep(0.02)
    return False


# -- env knob --------------------------------------------------------------

def test_pipeline_env_knob(monkeypatch):
    monkeypatch.delenv("SPARKDL_PIPELINE", raising=False)
    assert pipeline_enabled_from_env()
    for off in ("0", "false", "OFF", "no"):
        monkeypatch.setenv("SPARKDL_PIPELINE", off)
        assert not pipeline_enabled_from_env()
    monkeypatch.setenv("SPARKDL_PIPELINE", "1")
    assert pipeline_enabled_from_env()


def test_escape_hatch_never_builds_a_runner(setup, monkeypatch):
    """SPARKDL_PIPELINE=0 must route through the serial path without even
    constructing a PipelinedRunner."""
    variables, x = setup
    monkeypatch.setenv("SPARKDL_PIPELINE", "0")

    def boom(*a, **k):
        raise AssertionError("PipelinedRunner built despite the escape "
                             "hatch")

    monkeypatch.setattr(engine_mod, "PipelinedRunner", boom)
    eng = InferenceEngine(_fn, variables, device_batch_size=16)
    ref = np.tanh(x @ variables["w"] + variables["b"])
    np.testing.assert_allclose(eng(x), ref, rtol=1e-5, atol=1e-6)
    got = np.concatenate(list(eng.map_batches([x])), axis=0)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


# -- engine parity ---------------------------------------------------------

@pytest.mark.parametrize("bpd", [1, 3])
def test_map_batches_bit_identical_to_serial(setup, bpd):
    """Same programs, same pad/trim, same order — the pipelined stream is
    byte-for-byte the serial stream, ragged chunks and ragged tail groups
    included."""
    variables, x = setup
    eng = InferenceEngine(_fn, variables, device_batch_size=16,
                          batches_per_dispatch=bpd)
    chunks = [x[:60], x[60:63], x[63:]]
    serial = list(eng.map_batches(iter(chunks), pipeline=False))
    piped = list(eng.map_batches(iter(chunks), pipeline=True))
    assert len(serial) == len(piped)
    for a, b in zip(serial, piped):
        assert a.shape == b.shape and a.dtype == b.dtype
        np.testing.assert_array_equal(a, b)
    assert _wait_threads_gone()


def test_call_bit_identical_to_serial_pytree(setup):
    """Pipelined __call__ on pytree outputs with integer leaves: the
    preallocated-stream result equals the serial concatenation exactly,
    and integer leaves are never floated."""
    import jax.numpy as jnp

    variables, x = setup

    def fn(v, xb):
        y = jnp.tanh(xb @ v["w"] + v["b"])
        return {"y": y, "ids": jnp.argmax(y, axis=-1)}

    eng = InferenceEngine(fn, variables, device_batch_size=8,
                          output_host_dtype=np.float32)
    a = eng(x, pipeline=False)
    b = eng(x, pipeline=True)
    np.testing.assert_array_equal(a["y"], b["y"])
    np.testing.assert_array_equal(a["ids"], b["ids"])
    assert b["ids"].dtype.kind in "iu"
    assert b["y"].dtype == np.float32


def test_single_piece_call_skips_worker_threads(setup, monkeypatch):
    """Inputs that fit one device batch (the serving micro-batch shape)
    have nothing to overlap: the call must not pay the thread hop."""
    variables, x = setup

    def boom(*a, **k):
        raise AssertionError("runner built for a single-piece call")

    monkeypatch.setattr(engine_mod, "PipelinedRunner", boom)
    eng = InferenceEngine(_fn, variables, device_batch_size=16)
    out = eng(x[:10], pipeline=True)
    ref = np.tanh(x[:10] @ variables["w"] + variables["b"])
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_pipelined_grouped_tail_uses_plain_program(setup, monkeypatch):
    """The grouped-dispatch ragged tail must run through the plain
    per-batch program in the pipelined stages too — never padded with
    whole zero batches."""
    variables, x = setup
    eng = InferenceEngine(_fn, variables, device_batch_size=16,
                          batches_per_dispatch=3)
    calls = {"group": 0, "plain": 0}
    lock = threading.Lock()
    orig_group, orig_plain = eng._dispatch_group, eng.run_padded

    def spy_group(stacked):
        with lock:
            calls["group"] += 1
        return orig_group(stacked)

    def spy_plain(batch):
        with lock:
            calls["plain"] += 1
        return orig_plain(batch)

    monkeypatch.setattr(eng, "_dispatch_group", spy_group)
    monkeypatch.setattr(eng, "run_padded", spy_plain)
    out = eng(np.concatenate([x[:45], x[:19]]), pipeline=True)  # 4 pieces
    assert out.shape[0] == 64
    assert calls == {"group": 1, "plain": 1}  # one full group, 1-piece tail


# -- host-memory contract --------------------------------------------------

def test_large_frame_call_preallocates_and_bounds_residency(setup,
                                                            monkeypatch):
    """A frame MUCH larger than the in-flight window (48 chunks vs
    window 2) through pipelined __call__: the output is preallocated once
    and chunks are released as they are copied in — at no point does a
    per-chunk accumulation list hold the stream."""
    variables, _ = setup
    rng = np.random.default_rng(11)
    n_chunks = 48
    x = rng.normal(size=(8 * n_chunks, 12)).astype(np.float32)
    eng = InferenceEngine(_fn, variables, device_batch_size=8)
    ref = eng(x, pipeline=False)

    refs, peaks = [], []
    orig_trim = eng._trim

    def spy_trim(out, nn):
        res = orig_trim(out, nn)
        refs.append(weakref.ref(res))
        peaks.append(sum(1 for r in refs if r() is not None))
        return res

    monkeypatch.setattr(eng, "_trim", spy_trim)
    before = eng.metrics.counters.get("engine_call_prealloc", 0)
    out = eng(x, pipeline=True)
    np.testing.assert_array_equal(out, ref)
    assert eng.metrics.counters["engine_call_prealloc"] == before + 1
    assert len(refs) == n_chunks
    # gathered chunks die as soon as they are copied into the preallocated
    # output: simultaneous live chunks stay O(queue depths), never O(n)
    assert max(peaks) <= 8, max(peaks)


# -- failure / cleanup -----------------------------------------------------

def test_producer_error_propagates_to_consumer(setup):
    variables, x = setup
    eng = InferenceEngine(_fn, variables, device_batch_size=16)

    def bad():
        yield x[:16]
        raise RuntimeError("decode exploded")

    with pytest.raises(RuntimeError, match="decode exploded"):
        list(eng.map_batches(bad(), pipeline=True))
    assert _wait_threads_gone()


def test_consumer_abandonment_stops_worker_threads(setup):
    """Closing the output iterator early (a raising downstream consumer)
    must cancel all three stages — no producer left blocked on a full
    queue, no leaked thread."""
    variables, x = setup
    eng = InferenceEngine(_fn, variables, device_batch_size=8)
    it = eng.map_batches([x], pipeline=True)
    first = next(it)
    assert first.shape[0] == 8
    it.close()
    assert _wait_threads_gone()


# -- metrics + the overlap contract ----------------------------------------

def test_stage_metrics_recorded(setup):
    variables, x = setup
    m = Metrics()
    eng = InferenceEngine(_fn, variables, device_batch_size=8, metrics=m)
    list(eng.map_batches([x], pipeline=True))
    assert m.counters.get("pipeline.dispatches") == 19  # ceil(145/8)
    assert m.counters.get("pipeline.gathers") == 19
    assert "pipeline.prep_q_depth" in m.histograms
    assert "pipeline.inflight_q_depth" in m.histograms
    assert "pipeline.out_q_depth" in m.histograms
    summary = pipeline_stage_summary(m)
    assert summary["pipeline.dispatches"] == 19
    assert any(k.endswith("_depth.mean") for k in summary)


def test_synthetic_overlap_benchmark_speedup():
    """THE tier-1 overlap contract: with a simulated 100 ms blocking
    dispatch (the relayed-link regime) and 100 ms host prepare per batch,
    the pipelined path must be >= 1.5x the serial path on the CPU backend
    (ideal is 2x; the bound leaves headroom for thread scheduling noise).
    Deterministic: sleep-dominated, parity-checked inside."""
    result = synthetic_overlap_benchmark()  # 6 batches, 100 ms / 100 ms
    assert result["speedup"] >= 1.5, result
    assert result["stages"]["pipeline.dispatches"] == result["n_batches"]
    # the stall ledger tells the bottleneck story: with prep == dispatch
    # cost, gather mostly waits on the device — its in-stall dominates
    assert "pipeline.gather_in_stall_s" in result["stages"]


# -- surface parity (transformer / UDF / serving) --------------------------

def _image_frame(n=7, h=16, w=12, null_at=2):
    import pyarrow as pa

    from sparkdl_tpu.frame import DataFrame
    from sparkdl_tpu.image.schema import imageArrayToStruct, imageSchema

    rng = np.random.default_rng(5)
    structs = [imageArrayToStruct(
        (rng.random((h, w, 3)) * 255).astype(np.uint8), origin=f"r{i}")
        for i in range(n)]
    if null_at is not None:
        structs[null_at] = None
    return DataFrame(pa.table(
        {"image": pa.array(structs, type=imageSchema)}))


def test_transformer_surface_parity(monkeypatch):
    """TFImageTransformer.transform and transformStream emit bit-identical
    columns with the pipeline on and off."""
    from sparkdl_tpu.graph.function import ModelFunction
    from sparkdl_tpu.transformers.named_image import TFImageTransformer

    df = _image_frame()
    mf = ModelFunction(
        fn=lambda v, x: (x.astype("float32").reshape(x.shape[0], -1)
                         @ v["w"]),
        variables={"w": np.linspace(-1, 1, 16 * 12 * 3 * 4).reshape(
            16 * 12 * 3, 4).astype(np.float32)})

    def run():
        t = TFImageTransformer(inputCol="image", outputCol="out",
                               modelFunction=mf, inputSize=[16, 12],
                               batchSize=2)
        full = t.transform(df).table.column("out").to_pylist()
        streamed = []
        for rb in t.transformStream(df.table.to_batches(max_chunksize=3)):
            streamed.extend(rb.column(rb.schema.names.index("out"))
                            .to_pylist())
        return full, streamed

    monkeypatch.setenv("SPARKDL_PIPELINE", "0")
    full_serial, stream_serial = run()
    monkeypatch.setenv("SPARKDL_PIPELINE", "1")
    full_piped, stream_piped = run()
    assert full_piped == full_serial          # bit-exact floats
    assert stream_piped == stream_serial
    assert full_serial[2] is None             # null row contract intact


def test_udf_surface_parity(monkeypatch):
    """register_image_udf scoring emits bit-identical columns with the
    pipeline on and off."""
    from sparkdl_tpu.graph.function import ModelFunction
    from sparkdl_tpu.udf import UDFRegistry, register_image_udf

    df = _image_frame()
    mf = ModelFunction(
        fn=lambda v, x: x.reshape(x.shape[0], -1) @ v["w"],
        variables={"w": np.linspace(0, 1, 16 * 12 * 3 * 2).reshape(
            16 * 12 * 3, 2).astype(np.float32)})

    def run():
        reg = UDFRegistry()
        register_image_udf("p", mf, input_size=(16, 12), batch_size=2,
                           registry=reg)
        out = reg.apply("p", df, "image", "scores")
        return out.table.column("scores").to_pylist()

    monkeypatch.setenv("SPARKDL_PIPELINE", "0")
    serial = run()
    monkeypatch.setenv("SPARKDL_PIPELINE", "1")
    piped = run()
    assert piped == serial
    assert serial[2] is None


def test_serving_surface_parity(monkeypatch):
    """Served rows are bit-identical with the pipeline on and off (the
    serving micro-batch is a single device batch, so it rides the
    single-piece fast path either way — this pins that equivalence)."""
    from sparkdl_tpu.serving import Server

    rng = np.random.default_rng(3)
    w = rng.normal(size=(12, 4)).astype(np.float32)

    def fn(v, x):
        import jax.numpy as jnp

        return jnp.tanh(x @ v["w"])

    xs = rng.normal(size=(20, 12)).astype(np.float32)

    def run():
        with Server(fn, {"w": w}, max_batch_size=8, max_wait_ms=2.0) as srv:
            futs = [srv.submit(row) for row in xs]
            return [np.asarray(f.result()) for f in futs]

    monkeypatch.setenv("SPARKDL_PIPELINE", "0")
    serial = run()
    monkeypatch.setenv("SPARKDL_PIPELINE", "1")
    piped = run()
    for a, b in zip(serial, piped):
        np.testing.assert_array_equal(a, b)
