"""UDF-layer tests.

Mirrors the reference's ``python/tests/udf/keras_image_model_test.py``:
register -> apply over an image DataFrame -> parity vs local keras predict.
"""

import numpy as np
import pytest

from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.image.io import readImages
from sparkdl_tpu.udf import (UDFRegistry, register_image_udf,
                             registerKerasImageUDF, udf_registry)


@pytest.fixture()
def image_df(fixture_images):
    return readImages(fixture_images["dir"])


def test_register_image_udf_and_apply(image_df):
    reg = UDFRegistry()
    mf = ModelFunction(
        fn=lambda v, x: x.reshape(x.shape[0], -1) @ v["w"],
        variables={"w": np.ones((16 * 16 * 3, 2), np.float32)})
    register_image_udf("sum2", mf, input_size=(16, 16), registry=reg)
    out = reg.apply("sum2", image_df, "image", "scores")
    rows = out.collect()
    vals = [r for r in rows if r["scores"] is not None]
    nulls = [r for r in rows if r["scores"] is None]
    assert len(vals) == 3 and len(nulls) == 1  # bad jpeg stays null
    assert all(len(r["scores"]) == 2 for r in vals)


def test_register_keras_image_udf_parity(image_df, fixture_images):
    import keras
    from keras import layers

    from sparkdl_tpu.image.io import resizeImage
    from sparkdl_tpu.image.schema import imageStructToArray

    model = keras.Sequential([
        layers.Input((10, 12, 3)),
        layers.Conv2D(2, 3, padding="same", activation="relu"),
        layers.GlobalAveragePooling2D(),
    ])

    def preprocessor(x):
        return x / 255.0

    reg = UDFRegistry()
    registerKerasImageUDF("cnn_udf", model, preprocessor=preprocessor,
                          registry=reg)
    out = reg.apply("cnn_udf", image_df, "image", "feats")
    rows = [r for r in out.collect() if r["feats"] is not None]

    # oracle: host resize -> RGB -> /255 -> keras predict
    structs = [r["image"] for r in image_df.collect() if r["image"]]
    batch = np.stack([
        resizeImage(imageStructToArray(s), 10, 12)[:, :, ::-1]
        for s in structs]).astype(np.float32) / 255.0
    ref = model.predict(batch, verbose=0)
    got = np.asarray([r["feats"] for r in rows])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_registry_lookup_and_errors():
    reg = UDFRegistry()
    with pytest.raises(KeyError, match="No UDF"):
        reg.get("missing")
    reg.register("f", lambda rows: [len(rows)] )
    assert reg.names() == ["f"]


def test_pandas_udf_gated_on_pyspark():
    reg = UDFRegistry()
    reg.register("g", lambda rows: rows)
    with pytest.raises(ImportError, match="pyspark"):
        reg.to_pandas_udf("g")


def test_global_registry_roundtrip(image_df):
    mf = ModelFunction(
        fn=lambda v, x: x.astype("float32").mean(axis=(1, 2)),
        variables={})
    name = "mean_rgb_test"
    register_image_udf(name, mf, input_size=(8, 8))
    try:
        out = udf_registry.apply(name, image_df, "image", "m")
        vals = [r["m"] for r in out.collect() if r["m"] is not None]
        assert all(len(v) == 3 for v in vals)
    finally:
        udf_registry._udfs.pop(name, None)


def test_pandas_udf_contract_with_stub_pyspark(monkeypatch):
    """VERDICT r2 missing #4: positive-path coverage of to_pandas_udf via a
    stub pyspark module — the produced callable must round-trip a pandas
    Series and carry the declared return type through pandas_udf."""
    import sys
    import types

    import pandas as pd

    captured = {}

    def fake_pandas_udf(return_type):
        captured["return_type"] = return_type

        def deco(fn):
            def wrapper(series):
                out = fn(series)
                assert isinstance(out, pd.Series), (
                    "pandas_udf functions must return a pandas Series")
                return out
            wrapper._is_pandas_udf = True
            return wrapper

        return deco

    pyspark = types.ModuleType("pyspark")
    pyspark_sql = types.ModuleType("pyspark.sql")
    pyspark_fns = types.ModuleType("pyspark.sql.functions")
    pyspark_fns.pandas_udf = fake_pandas_udf
    pyspark.sql = pyspark_sql
    pyspark_sql.functions = pyspark_fns
    monkeypatch.setitem(sys.modules, "pyspark", pyspark)
    monkeypatch.setitem(sys.modules, "pyspark.sql", pyspark_sql)
    monkeypatch.setitem(sys.modules, "pyspark.sql.functions", pyspark_fns)

    reg = UDFRegistry()
    reg.register("double_up", lambda rows: [[2.0 * v for v in r]
                                            for r in rows])
    spark_udf = reg.to_pandas_udf("double_up")
    assert getattr(spark_udf, "_is_pandas_udf", False)
    assert captured["return_type"] == "array<float>"
    series = pd.Series([[1.0, 2.0], [3.0, 4.0]])
    out = spark_udf(series)
    assert isinstance(out, pd.Series)
    assert list(out) == [[2.0, 4.0], [6.0, 8.0]]


def test_arrow_hot_path_parity_with_list_path(image_df):
    """The zero-copy Arrow scoring path (apply over a DataFrame) and the
    legacy list-of-dicts path produce identical scores, and the Arrow
    column is handed to the UDF without to_pylist (VERDICT r3 #5)."""
    reg = UDFRegistry()
    mf = ModelFunction(
        fn=lambda v, x: x.reshape(x.shape[0], -1) @ v["w"],
        variables={"w": np.arange(16 * 16 * 3 * 2, dtype=np.float32
                                  ).reshape(16 * 16 * 3, 2) / 1e4})
    udf = register_image_udf("parity_udf", mf, input_size=(16, 16),
                             registry=reg)
    assert getattr(udf.fn, "accepts_arrow", False)
    col = image_df.table.column("image")
    arrow_out = udf(col)                      # arrow path
    list_out = udf.fn(col.to_pylist())        # legacy path
    assert len(arrow_out) == len(list_out)
    for a, b in zip(arrow_out, list_out):
        if a is None:
            assert b is None
        else:
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
