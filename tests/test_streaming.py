"""Streaming data-path tests (VERDICT round 1, Missing #2).

The production transform path must be partition-at-a-time like the
reference's executor hot loop: at no point may the whole dataset's decoded
pixels coexist in host memory, and ``transformStream`` must be lazy
end-to-end (batch k yields before batch k+1 is read from disk).
"""

import os

import numpy as np
import pyarrow as pa
import pytest
from PIL import Image

from sparkdl_tpu.frame import DataFrame
from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.image.io import (iterFileBatches, iterImageBatches,
                                  readImages)
from sparkdl_tpu.models import get_model_spec
from sparkdl_tpu.transformers import (DeepImageFeaturizer, PipelineModel,
                                      TFImageTransformer)
from sparkdl_tpu.transformers import named_image as ni
from sparkdl_tpu.utils.prefetch import prefetch_iter


@pytest.fixture()
def many_images(tmp_path):
    """40 tiny JPEGs — 10x the device batch used below — plus 2 bad files."""
    rng = np.random.default_rng(7)
    d = tmp_path / "imgs"
    d.mkdir()
    for i in range(40):
        arr = (rng.random((24, 24, 3)) * 255).astype("uint8")
        Image.fromarray(arr).save(d / f"img_{i:03d}.jpg", quality=92)
    (d / "bad_a.jpg").write_bytes(b"nope")
    (d / "bad_b.jpg").write_bytes(b"also nope")
    return str(d)


@pytest.fixture()
def fake_resnet(monkeypatch):
    class _Tiny:
        feature_size = 2048

        def apply(self, variables, x, train=False, features=False):
            import jax.numpy as jnp

            m = jnp.mean(x, axis=(1, 2, 3))
            dim = self.feature_size if features else 1000
            return m[:, None] * 0.01 + jnp.arange(
                dim, dtype=jnp.float32)[None, :] * 1e-4

    spec = get_model_spec("ResNet50")
    monkeypatch.setitem(ni._MODEL_CACHE, ("ResNet50", ""), (_Tiny(), {}))
    ni._ENGINE_CACHE.clear()
    yield spec
    ni._ENGINE_CACHE.clear()


def test_featurizer_never_materializes_full_decoded_batch(
        fake_resnet, many_images, monkeypatch):
    """Decode calls must each cover at most one device batch of rows even
    when the frame is 10x larger (the round-1 path decoded ALL rows into
    one [N,H,W,3] array)."""
    df = readImages(many_images)
    assert len(df) == 42

    sizes = []
    orig = ni.arrowStructsToBatch

    def spy(column, h, w, **kw):
        sizes.append(len(column))
        return orig(column, h, w, **kw)

    monkeypatch.setattr(ni, "arrowStructsToBatch", spy)
    ft = DeepImageFeaturizer(inputCol="image", outputCol="features",
                             modelName="ResNet50", batchSize=4)
    rows = ft.transform(df).collect()
    assert len(rows) == 42
    assert sum(1 for r in rows if r["features"] is None) == 2
    # 8-device mesh rounds batchSize=4 up to 8; decode granularity follows.
    assert sizes, "streaming decode was never exercised"
    assert max(sizes) <= 8, sizes
    # the arrow packer sees every row of each chunk (nulls masked inside)
    assert sum(sizes) == 42


def test_streaming_matches_materialized_path(fake_resnet, many_images):
    """Chunked streaming must produce exactly the numbers a single
    whole-table pass produces (row order and null alignment included)."""
    df = readImages(many_images)
    ft = DeepImageFeaturizer(inputCol="image", outputCol="features",
                             modelName="ResNet50", batchSize=16)
    out1 = [r["features"] for r in ft.transform(df).collect()]
    out2 = [r["features"] for r in
            ft.transform(df.repartition(7)).collect()]
    assert len(out1) == len(out2) == 42
    for a, b in zip(out1, out2):
        if a is None:
            assert b is None
        else:
            np.testing.assert_allclose(a, b, rtol=1e-5)


def test_iter_file_batches_is_lazy(many_images, monkeypatch):
    """Bytes must be read per batch, not all up front."""
    import builtins

    opened = []
    orig_open = builtins.open

    def spy_open(path, *a, **kw):
        if str(path).endswith(".jpg"):
            opened.append(str(path))
        return orig_open(path, *a, **kw)

    monkeypatch.setattr(builtins, "open", spy_open)
    it = iterFileBatches(many_images, batch_size=10)
    first = next(it)
    assert first.num_rows == 10
    assert len(opened) == 10  # only the first batch touched disk
    rest = list(it)
    assert sum(rb.num_rows for rb in rest) == 32
    assert len(opened) == 42


def test_transform_stream_is_lazy_end_to_end(fake_resnet, many_images):
    """Batch k's output must be yielded before batch k+1 is decoded."""
    events = []

    def source():
        for i, rb in enumerate(iterImageBatches(many_images, batch_size=8)):
            events.append(f"read:{i}")
            yield rb

    ft = DeepImageFeaturizer(inputCol="image", outputCol="features",
                             modelName="ResNet50", batchSize=8)
    stream = ft.transformStream(source())
    first = next(stream)
    events.append("first-output")
    assert first.num_rows == 8
    assert events.index("first-output") <= 2, events  # not all 6 reads first
    total = first.num_rows + sum(rb.num_rows for rb in stream)
    assert total == 42


def test_pipeline_transform_stream_chains_lazily(fake_resnet, many_images):
    mf = ModelFunction(fn=lambda v, x: x.astype("float32").mean(
        axis=(1, 2)), variables={})
    t1 = TFImageTransformer(inputCol="image", outputCol="mean_bgr",
                            modelFunction=mf, inputSize=[16, 16],
                            outputMode="vector", batchSize=8)
    ft = DeepImageFeaturizer(inputCol="image", outputCol="features",
                             modelName="ResNet50", batchSize=8)
    pm = PipelineModel([t1, ft])
    out_batches = list(pm.transformStream(
        iterImageBatches(many_images, batch_size=8)))
    table = pa.Table.from_batches(out_batches)
    assert table.num_rows == 42
    assert set(table.column_names) >= {"image", "mean_bgr", "features"}


def test_prefetch_iter_propagates_errors_and_order():
    def gen():
        yield 1
        yield 2
        raise RuntimeError("boom")

    it = prefetch_iter(gen(), depth=2)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(RuntimeError, match="boom"):
        next(it)
    assert list(prefetch_iter(iter(range(5)), depth=1)) == list(range(5))


def test_image_file_transformer_streams(many_images, monkeypatch):
    """URI-column path: files are loaded per chunk, not all at once."""
    from sparkdl_tpu.transformers.image_file import ImageFileTransformer

    paths = sorted(
        os.path.join(many_images, f) for f in os.listdir(many_images))
    df = DataFrame({"uri": paths})

    chunk_sizes = []

    def loader(uri):
        img = Image.open(uri).convert("RGB").resize((16, 16))
        return np.asarray(img, dtype=np.float32)

    mf = ModelFunction(fn=lambda v, x: x.mean(axis=(1, 2)), variables={})
    t = ImageFileTransformer(inputCol="uri", outputCol="out",
                             modelFunction=mf, imageLoader=loader,
                             batchSize=8)
    orig = t._loaded_chunks

    def spy(dataset, chunk_rows, valid_idx):
        for chunk in orig(dataset, chunk_rows, valid_idx):
            chunk_sizes.append(chunk.shape[0])
            yield chunk

    monkeypatch.setattr(t, "_loaded_chunks", spy)
    rows = t.transform(df).collect()
    assert len(rows) == 42
    assert sum(1 for r in rows if r["out"] is None) == 2  # bad files
    assert max(chunk_sizes) <= 8


def test_prefetch_iter_producer_stops_when_consumer_abandons():
    """Abandoning the consumer mid-stream must release the producer thread
    (it was previously stuck forever in q.put on the full queue)."""
    import threading
    import time

    produced = []

    def gen():
        for i in range(100):
            produced.append(i)
            yield i

    before = threading.active_count()
    it = prefetch_iter(gen(), depth=1)
    assert next(it) == 0
    it.close()  # consumer walks away
    deadline = time.monotonic() + 5.0
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before, "producer thread leaked"
    assert len(produced) < 100


def test_prefetch_iter_producer_stops_when_consumer_garbage_collected():
    """The close() above is the polite path; a consumer that simply
    DROPS the iterator (function return, exception unwound past it) must
    release the producer too — CPython finalizes the generator on GC,
    its ``finally`` sets the stop flag, and the producer's bounded-put
    loop observes it instead of spinning on the full queue forever."""
    import gc
    import threading
    import time

    from sparkdl_tpu.utils.prefetch import prefetch_iter

    def gen():
        for i in range(100):
            yield i

    it = prefetch_iter(gen(), depth=1)
    assert next(it) == 0
    del it          # consumer walks away without close()
    gc.collect()    # finalize the generator deterministically
    deadline = time.monotonic() + 5.0
    while (any(t.name == "sparkdl-prefetch" for t in threading.enumerate())
           and time.monotonic() < deadline):
        time.sleep(0.05)
    leaked = [t.name for t in threading.enumerate()
              if t.name == "sparkdl-prefetch"]
    assert not leaked, f"producer thread leaked after consumer GC: {leaked}"
