"""Worker script for the 2-process jax.distributed integration test.

Run as: python _multihost_worker.py <pid> <nproc> <port> <out.json>
            [ckpt_dir] [mode]

Each process gets an UNEQUAL local shard (10 vs 6 rows — the case that
used to deadlock when steps-per-epoch derived from the local count) and
runs a data-parallel fit through the production path: put_sharded's
make_array_from_process_local_data branch, the global steps-per-epoch
agreement, and (with ckpt_dir) process-0-gated checkpoint writes all
execute for real.  ``mode``: "arrays" (default, fit_data_parallel) or
"stream" (fit_data_parallel_stream over a re-iterable chunk source with
a pinned steps_per_epoch — the multi-controller streaming contract).
"""

import json
import sys

import numpy as np


def tp_fit_reference(epochs: int = 3):
    """Deterministic (data, params, batch order) for the dp x tp fit —
    shared by the workers and the in-test single-process oracle."""
    rng = np.random.default_rng(42)
    dim, classes, n = 6, 4, 32
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (np.arange(n) % classes).astype(np.int32)
    params0 = {
        "body": rng.normal(0, 0.1, (dim, dim)).astype(np.float32),
        "head": {"kernel": rng.normal(0, 0.1, (dim, classes)
                                      ).astype(np.float32),
                 "bias": np.zeros((classes,), np.float32)},
    }
    return x, y, params0, epochs


def _run_tensor_parallel(pid, nproc, out_path):
    """dp2 x tp2 over 2 processes x 2 devices (VERDICT r3 #9): the head
    kernel/bias shard on the ``model`` axis while the batch shards on
    ``data`` ACROSS processes — every step's activation/gradient
    collectives cross the process boundary for real."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from sparkdl_tpu.parallel import mesh as mesh_lib
    from sparkdl_tpu.parallel.train import make_train_step

    mesh = mesh_lib.get_mesh(model_parallel=2)  # (data=2, model=2) on 4 dev
    x, y, params0, epochs = tp_fit_reference()
    batch = 8
    local = batch // nproc

    def predict(p, xb):
        h = jnp.tanh(jnp.asarray(xb) @ p["body"])
        return h @ p["head"]["kernel"] + p["head"]["bias"]

    def ce(logits, yb):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb.astype(jnp.int32))

    def tp_rule(path, leaf):
        if path.endswith("head/kernel"):
            return P(None, "model")
        if path.endswith("head/bias"):
            return P("model")
        return P()

    opt = optax.sgd(0.1)
    step = make_train_step(predict, ce, opt, mesh=mesh, cache=False,
                           param_specs=tp_rule, params_template=params0)
    params, opt_state = step.put_state(params0, opt.init(params0))
    losses = []
    for _ in range(epochs):
        for off in range(0, len(x), batch):
            rows = slice(off + pid * local, off + (pid + 1) * local)
            bx, by = step.put_batch(x[rows], y[rows])
            params, opt_state, lval = step(params, opt_state, bx, by)
        losses.append(float(lval))
    # gather TP-sharded params to replicated so every host can read them
    gather = jax.jit(lambda p: p, out_shardings=step.replicated)
    full = jax.tree_util.tree_map(np.asarray, gather(params))
    with open(out_path, "w") as f:
        json.dump({
            "process_count": jax.process_count(),
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count(),
            "mesh_shape": {k: int(v) for k, v in mesh.shape.items()},
            "losses": losses,
            "head_kernel": full["head"]["kernel"].ravel().tolist(),
            "body": full["body"].ravel().tolist(),
        }, f)


def main():
    pid, nproc, port, out_path = (int(sys.argv[1]), int(sys.argv[2]),
                                  sys.argv[3], sys.argv[4])
    ckpt_dir = sys.argv[5] if len(sys.argv) > 5 and sys.argv[5] != "-" \
        else None
    mode = sys.argv[6] if len(sys.argv) > 6 else "arrays"

    import jax

    jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                               num_processes=nproc, process_id=pid)

    import jax.numpy as jnp
    import optax

    from sparkdl_tpu.parallel import mesh as mesh_lib
    from sparkdl_tpu.parallel.train import (fit_data_parallel,
                                            fit_data_parallel_stream)

    # Unequal shards across hosts (rows % nproc != 0 overall).
    n_local = 10 if pid == 0 else 6
    rng = np.random.default_rng(100 + pid)
    w_true = (np.arange(5, dtype=np.float32)[:, None] - 2.0) / 5.0
    x = rng.normal(size=(n_local, 5)).astype(np.float32)
    y = x @ w_true

    def predict(p, xb):
        return jnp.asarray(xb) @ p["w"]

    if mode == "tp":
        _run_tensor_parallel(pid, nproc, out_path)
        return
    if mode not in ("arrays", "arrays_spe", "stream"):
        raise ValueError(f"unknown worker mode {mode!r}")
    params = {"w": np.zeros((5, 1), np.float32)}
    if mode == "stream":
        def source():
            for off in range(0, n_local, 4):  # uneven chunking per host
                yield x[off:off + 4], y[off:off + 4]

        # steps_per_epoch from the GLOBAL row count (16) / global batch (8)
        fitted, losses = fit_data_parallel_stream(
            predict, params, source, optimizer=optax.sgd(0.05), loss="mse",
            batch_size=8, epochs=3, steps_per_epoch=2,
            mesh=mesh_lib.get_mesh(), checkpoint_dir=ckpt_dir)
    else:
        # arrays_spe: same fit with k steps per dispatch — exercises
        # put_batch_stack's multi-process global assembly; the parent
        # test asserts parity with the one-step "arrays" run
        spe = 2 if mode == "arrays_spe" else 1
        fitted, losses = fit_data_parallel(
            predict, params, x, y, optimizer=optax.sgd(0.05), loss="mse",
            batch_size=8, epochs=3, seed=0, mesh=mesh_lib.get_mesh(),
            checkpoint_dir=ckpt_dir, steps_per_execution=spe)

    with open(out_path, "w") as f:
        json.dump({
            "process_count": jax.process_count(),
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count(),
            "losses": [float(l) for l in losses],
            "w": np.asarray(fitted["w"]).ravel().tolist(),
        }, f)


if __name__ == "__main__":
    main()
