"""Worker script for the 2-process jax.distributed integration test.

Run as: python _multihost_worker.py <pid> <nproc> <port> <out.json>
            [ckpt_dir] [mode]

Each process gets an UNEQUAL local shard (10 vs 6 rows — the case that
used to deadlock when steps-per-epoch derived from the local count) and
runs a data-parallel fit through the production path: put_sharded's
make_array_from_process_local_data branch, the global steps-per-epoch
agreement, and (with ckpt_dir) process-0-gated checkpoint writes all
execute for real.  ``mode``: "arrays" (default, fit_data_parallel) or
"stream" (fit_data_parallel_stream over a re-iterable chunk source with
a pinned steps_per_epoch — the multi-controller streaming contract).
"""

import json
import sys

import numpy as np


def main():
    pid, nproc, port, out_path = (int(sys.argv[1]), int(sys.argv[2]),
                                  sys.argv[3], sys.argv[4])
    ckpt_dir = sys.argv[5] if len(sys.argv) > 5 and sys.argv[5] != "-" \
        else None
    mode = sys.argv[6] if len(sys.argv) > 6 else "arrays"

    import jax

    jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                               num_processes=nproc, process_id=pid)

    import jax.numpy as jnp
    import optax

    from sparkdl_tpu.parallel import mesh as mesh_lib
    from sparkdl_tpu.parallel.train import (fit_data_parallel,
                                            fit_data_parallel_stream)

    # Unequal shards across hosts (rows % nproc != 0 overall).
    n_local = 10 if pid == 0 else 6
    rng = np.random.default_rng(100 + pid)
    w_true = (np.arange(5, dtype=np.float32)[:, None] - 2.0) / 5.0
    x = rng.normal(size=(n_local, 5)).astype(np.float32)
    y = x @ w_true

    def predict(p, xb):
        return jnp.asarray(xb) @ p["w"]

    if mode not in ("arrays", "stream"):
        raise ValueError(f"unknown worker mode {mode!r}")
    params = {"w": np.zeros((5, 1), np.float32)}
    if mode == "stream":
        def source():
            for off in range(0, n_local, 4):  # uneven chunking per host
                yield x[off:off + 4], y[off:off + 4]

        # steps_per_epoch from the GLOBAL row count (16) / global batch (8)
        fitted, losses = fit_data_parallel_stream(
            predict, params, source, optimizer=optax.sgd(0.05), loss="mse",
            batch_size=8, epochs=3, steps_per_epoch=2,
            mesh=mesh_lib.get_mesh(), checkpoint_dir=ckpt_dir)
    else:
        fitted, losses = fit_data_parallel(
            predict, params, x, y, optimizer=optax.sgd(0.05), loss="mse",
            batch_size=8, epochs=3, seed=0, mesh=mesh_lib.get_mesh(),
            checkpoint_dir=ckpt_dir)

    with open(out_path, "w") as f:
        json.dump({
            "process_count": jax.process_count(),
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count(),
            "losses": [float(l) for l in losses],
            "w": np.asarray(fitted["w"]).ravel().tolist(),
        }, f)


if __name__ == "__main__":
    main()
