"""Worker script for the 2-process jax.distributed integration test.

Run as: python _multihost_worker.py <pid> <nproc> <port> <out.json> [ckpt_dir]

Each process gets an UNEQUAL local shard (10 vs 6 rows — the case that
used to deadlock when steps-per-epoch derived from the local count) and
runs a data-parallel fit through the production fit_data_parallel path:
put_sharded's make_array_from_process_local_data branch, the global
steps-per-epoch allgather, and (with ckpt_dir) process-0-gated checkpoint
writes all execute for real.
"""

import json
import sys

import numpy as np


def main():
    pid, nproc, port, out_path = (int(sys.argv[1]), int(sys.argv[2]),
                                  sys.argv[3], sys.argv[4])
    ckpt_dir = sys.argv[5] if len(sys.argv) > 5 else None

    import jax

    jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                               num_processes=nproc, process_id=pid)

    import jax.numpy as jnp
    import optax

    from sparkdl_tpu.parallel import mesh as mesh_lib
    from sparkdl_tpu.parallel.train import fit_data_parallel

    # Unequal shards across hosts (rows % nproc != 0 overall).
    n_local = 10 if pid == 0 else 6
    rng = np.random.default_rng(100 + pid)
    w_true = (np.arange(5, dtype=np.float32)[:, None] - 2.0) / 5.0
    x = rng.normal(size=(n_local, 5)).astype(np.float32)
    y = x @ w_true

    def predict(p, xb):
        return jnp.asarray(xb) @ p["w"]

    params = {"w": np.zeros((5, 1), np.float32)}
    fitted, losses = fit_data_parallel(
        predict, params, x, y, optimizer=optax.sgd(0.05), loss="mse",
        batch_size=8, epochs=3, seed=0, mesh=mesh_lib.get_mesh(),
        checkpoint_dir=ckpt_dir)

    with open(out_path, "w") as f:
        json.dump({
            "process_count": jax.process_count(),
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count(),
            "losses": [float(l) for l in losses],
            "w": np.asarray(fitted["w"]).ravel().tolist(),
        }, f)


if __name__ == "__main__":
    main()
