"""Chaos suite: deterministic fault injection across the scoring stack.

Tier-1 (CPU-only, 8-device virtual mesh).  Pins ISSUE 4's failure-domain
contracts with the :mod:`sparkdl_tpu.faults` harness:

* the spec grammar / plan semantics (seeded determinism, at/every/p/
  times schedules, sticky ``dead``);
* engine dispatch retry (jittered, capped) + circuit breaker
  (fail-fast ``CircuitOpenError``, half-open recovery);
* pipeline worker crashes -> structured ``PipelineStageError`` with the
  failing stage + piece, clean drain (no wedged threads/queues);
* serving: queue-full storms, breaker-open shed with ``retry_after``,
  ``health()`` ready/degraded/closed transitions, wedged-model drain;
* host I/O decode errors ride the drop-to-null contract; the device
  probe falls back fast on a hanging relay;
* the chaos e2e acceptance run and the kill-the-driver bench-artifact
  test (SIGKILL mid-run -> valid JSONL for every completed config).
"""

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu import faults
from sparkdl_tpu.faults import FaultPlan
from sparkdl_tpu.parallel.engine import CircuitOpenError, InferenceEngine
from sparkdl_tpu.parallel.pipeline import PipelineStageError
from sparkdl_tpu.serving import (QueueFullError, Server,
                                 ServiceUnavailableError)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolated_plan():
    """Never leak a plan between tests (or out of the suite)."""
    from sparkdl_tpu.faults import plan as plan_mod

    prev = plan_mod._PLAN
    yield
    plan_mod._PLAN = prev


def _fn(variables, x):
    import jax.numpy as jnp

    return jnp.tanh(x @ variables["w"])


@pytest.fixture(scope="module")
def model():
    rng = np.random.default_rng(4)
    variables = {"w": rng.normal(size=(6, 4)).astype(np.float32)}
    x = rng.normal(size=(24, 6)).astype(np.float32)
    return variables, x


def _no_stack_threads(prefixes=("sparkdl-pipeline", "sparkdl-serving"),
                      timeout_s=5.0):
    """Join-with-timeout assert: every stack worker thread exits."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        left = [t.name for t in threading.enumerate()
                if t.name.startswith(prefixes)]
        if not left:
            return
        time.sleep(0.02)
    raise AssertionError(f"wedged threads after {timeout_s}s: {left}")


# -- spec grammar / plan semantics -----------------------------------------

def test_spec_parse_roundtrip_and_rejects():
    spec = ("seed=7;engine.dispatch:error:exc=transient,at=2;"
            "serving.admit:error:exc=queue_full,times=3;"
            "pipeline.gather:sleep:every=2,ms=1")
    plan = FaultPlan.parse(spec)
    assert plan.seed == 7
    assert plan.sites() == {"engine.dispatch", "serving.admit",
                            "pipeline.gather"}
    assert FaultPlan.parse(plan.spec).spec == plan.spec  # canonical form
    for bad in ("nope.site:error", "engine.dispatch:boom",
                "engine.dispatch:error:zz=1", "seed=x",
                "engine.dispatch:error:exc=nonsense", "justasite",
                # queue_full is not an InjectedFault: outside serving.*
                # it would escape the site handlers instead of testing
                # them, so the grammar refuses it there
                "io.decode:error:exc=queue_full",
                "engine.dispatch:error:exc=queue_full"):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)
    # a seed embedded in a rule STRING means the same as in parse()
    p = FaultPlan(["seed=9;engine.dispatch:error:p=0.5"])
    assert p.seed == 9 and p.spec.startswith("seed=9;")


def test_plan_schedules_fire_deterministically():
    # at= fires on exactly the Nth site call; times= caps firings
    plan = FaultPlan.parse("engine.dispatch:error:at=2")
    faults.configure(plan)
    faults.inject("engine.dispatch")
    with pytest.raises(faults.InjectedTransientError) as ei:
        faults.inject("engine.dispatch")
    assert ei.value.site == "engine.dispatch"
    faults.inject("engine.dispatch")  # inert again
    assert plan.fired() == 1 and plan.stats()["engine.dispatch"][
        "calls"] == 3

    # p= draws ride the per-rule seeded RNG: identical replay per seed
    def firing_seq(p):
        out = []
        for _ in range(30):
            try:
                p.fire("engine.dispatch", {})
                out.append(0)
            except faults.InjectedFault:
                out.append(1)
        return out

    s1 = firing_seq(FaultPlan.parse("seed=3;engine.dispatch:error:p=0.4"))
    s2 = firing_seq(FaultPlan.parse("seed=3;engine.dispatch:error:p=0.4"))
    s3 = firing_seq(FaultPlan.parse("seed=4;engine.dispatch:error:p=0.4"))
    assert s1 == s2 and 0 < sum(s1) < 30
    assert s1 != s3  # a different seed is a different chaos run


def test_dead_rule_is_sticky():
    faults.configure(FaultPlan.parse("engine.dispatch:dead:at=2"))
    faults.inject("engine.dispatch")
    for _ in range(3):  # once fired, EVERY later call keeps failing
        with pytest.raises(faults.InjectedDeadDeviceError):
            faults.inject("engine.dispatch")
    faults.clear()
    faults.inject("engine.dispatch")  # cleared: site is healthy again


def test_disabled_inject_is_noop_and_env_gate(monkeypatch):
    faults.clear()
    assert faults.inject("engine.dispatch") is None
    assert faults.get_plan() is None and faults.current_spec() is None
    monkeypatch.setenv("SPARKDL_FAULTS", "seed=5;io.decode:error:at=1")
    plan = faults.configure_from_env()
    assert plan is not None and plan.seed == 5
    assert faults.current_spec() == plan.spec
    with pytest.raises(faults.InjectedTransientError):
        faults.inject("io.decode")
    faults.clear()


def test_active_context_restores_previous_plan():
    outer = faults.configure(FaultPlan.parse("io.decode:error:at=1"))
    with faults.active(FaultPlan.parse("engine.dispatch:error:at=1")) as p:
        with pytest.raises(faults.InjectedFault):
            faults.inject("engine.dispatch")
        assert p.fired() == 1
    assert faults.get_plan() is outer
    faults.clear()


# -- retry satellite: jitter + bounded backoff -----------------------------

def test_backoff_delay_jittered_and_hard_capped():
    from sparkdl_tpu.utils.retry import backoff_delay

    rng = random.Random(0)
    # the cap binds AFTER jitter: no draw may exceed max_backoff_seconds
    for attempt in range(16):
        d = backoff_delay(attempt, 0.1, max_backoff_seconds=0.75,
                          jitter=0.5, rng=rng)
        assert 0.0 <= d <= 0.75
    # unjittered growth is the documented exponential below the cap
    assert backoff_delay(3, 0.1) == pytest.approx(0.8)
    assert backoff_delay(10, 0.1, max_backoff_seconds=2.0) == 2.0
    # jitter only DE-synchronizes (scales into [1-j, 1]), never inflates
    draws = {backoff_delay(2, 0.1, jitter=0.5, rng=random.Random(i))
             for i in range(20)}
    assert len(draws) > 5
    assert all(0.4 * 0.5 <= d <= 0.4 for d in draws)


def test_with_retries_sleeps_are_bounded(monkeypatch):
    from sparkdl_tpu.utils import retry as retry_mod

    sleeps = []
    monkeypatch.setattr(retry_mod.time, "sleep", sleeps.append)
    with pytest.raises(RuntimeError):
        retry_mod.with_retries(
            lambda: (_ for _ in ()).throw(RuntimeError("flaky")),
            max_retries=6, backoff_seconds=0.5,
            max_backoff_seconds=1.25, jitter=0.3)
    assert len(sleeps) == 6
    assert all(0.0 <= s <= 1.25 for s in sleeps), sleeps
    # without the cap, attempt 5 would have slept 0.5 * 2**5 = 16s
    assert max(sleeps) <= 1.25


# -- engine: dispatch retry + circuit breaker ------------------------------

def test_engine_retry_absorbs_transient_dispatch_fault(model):
    variables, x = model
    eng = InferenceEngine(_fn, variables, device_batch_size=8,
                          dispatch_retries=2, dispatch_backoff_s=0.001)
    ref = [np.asarray(o) for o in eng.map_batches([x], pipeline=False)]
    with faults.active(FaultPlan.parse(
            "engine.dispatch:error:exc=transient,at=2")) as plan:
        out = [np.asarray(o) for o in eng.map_batches([x], pipeline=False)]
        assert plan.fired("engine.dispatch") == 1
    assert all(np.array_equal(a, b) for a, b in zip(ref, out))
    assert eng.metrics.counters["engine.dispatch_retries"] == 1
    assert eng.breaker_state()["state"] == "closed"


def test_engine_fatal_faults_are_not_retried(model):
    variables, x = model
    eng = InferenceEngine(_fn, variables, device_batch_size=8,
                          dispatch_retries=3, dispatch_backoff_s=0.001)
    with faults.active(FaultPlan.parse(
            "engine.dispatch:error:exc=fatal,at=1")):
        with pytest.raises(faults.InjectedFatalError):
            list(eng.map_batches([x], pipeline=False))
    # deterministic failure: no retry burned, breaker not charged
    assert "engine.dispatch_retries" not in eng.metrics.counters
    assert eng.breaker_state()["consecutive_failures"] == 0


def test_breaker_opens_fails_fast_and_recovers(model):
    variables, x = model
    xb = x[:8]  # single device batch: the serial fast path, so the
    # injected error type reaches the caller unwrapped
    eng = InferenceEngine(_fn, variables, device_batch_size=8,
                          breaker_threshold=2, breaker_cooldown_s=0.25)
    eng(xb)  # healthy warm call
    with faults.active(FaultPlan.parse("engine.dispatch:dead:at=1")):
        for _ in range(2):  # two consecutive device errors trip it
            with pytest.raises(faults.InjectedDeadDeviceError):
                eng(xb)
        st = eng.breaker_state()
        assert st["state"] == "open" and st["consecutive_failures"] == 2
        assert "InjectedDeadDeviceError" in st["last_error"]
        # open = FAIL FAST: no dispatch attempt, a clear error, instantly
        t0 = time.perf_counter()
        with pytest.raises(CircuitOpenError) as ei:
            eng(xb)
        assert time.perf_counter() - t0 < 0.1
        assert ei.value.retry_after_s > 0
    time.sleep(0.3)  # cool-down elapses -> half-open admits one trial
    assert eng.breaker_state()["state"] == "half_open"
    # a DETERMINISTIC error during the trial proves nothing about the
    # device: the trial slot must be handed back (not pinned forever)
    with faults.active(FaultPlan.parse("engine.dispatch:error:exc=fatal")):
        with pytest.raises(faults.InjectedFatalError):
            eng(xb)
    assert eng.breaker_state()["state"] == "half_open"  # still probeable
    out = eng(xb)  # plan inactive: the trial succeeds and closes it
    assert eng.breaker_state()["state"] == "closed"
    assert np.asarray(out).shape == (len(xb), 4)


def test_force_time_device_errors_trip_breaker(model):
    """jax dispatch is async: a dying device usually raises when the
    result is FORCED (D2H), not at enqueue.  The engine.gather site
    proves those failures charge the same breaker — without this, a
    dead device would never trip fail-fast on real hardware."""
    variables, x = model
    xb = x[:8]
    eng = InferenceEngine(_fn, variables, device_batch_size=8,
                          breaker_threshold=2, breaker_cooldown_s=30.0)
    eng(xb)
    with faults.active(FaultPlan.parse("engine.gather:dead:at=1")):
        for _ in range(2):
            with pytest.raises(faults.InjectedDeadDeviceError):
                eng(xb)
        assert eng.breaker_state()["state"] == "open"
        with pytest.raises(CircuitOpenError):  # next DISPATCH fails fast
            eng(xb)
    assert eng.metrics.counters["engine.gather_errors"] == 2


# -- pipeline: structured stage crashes + clean drain ----------------------

@pytest.mark.parametrize("stage,at", [("gather", 2), ("dispatch", 1)])
def test_pipeline_stage_crash_is_structured_and_drains(model, stage, at):
    variables, x = model
    eng = InferenceEngine(_fn, variables, device_batch_size=8)
    batches = [x[i:i + 8] for i in range(0, len(x), 8)]
    ref = [np.asarray(o) for o in eng.map_batches(list(batches),
                                                  pipeline=False)]
    with faults.active(FaultPlan.parse(
            f"pipeline.{stage}:error:exc=transient,at={at},times=1")):
        with pytest.raises(PipelineStageError) as ei:
            list(eng.map_batches(list(batches), pipeline=True))
        assert ei.value.stage == stage
        assert ei.value.piece == at - 1  # 0-based failing piece index
        assert isinstance(ei.value.__cause__,
                          faults.InjectedTransientError)
        _no_stack_threads()  # crash drained the graph: nothing wedged
        # rule exhausted (times=1): the retried run completes, and is
        # bit-identical to the serial path
        out = [np.asarray(o) for o in eng.map_batches(list(batches),
                                                      pipeline=True)]
    assert all(np.array_equal(a, b) for a, b in zip(ref, out))
    assert eng.metrics.counters[f"pipeline.{stage}_crashes"] == 1
    _no_stack_threads()


def test_pipeline_fatal_cause_stays_non_retryable(model):
    """A deterministic failure inside a stage must surface as the
    ValueError-lineage PipelineStageFatalError, so utils.retry wrappers
    around the pipelined path still fail fast instead of re-burning a
    retry budget on a caller bug."""
    from sparkdl_tpu.parallel.pipeline import PipelineStageFatalError
    from sparkdl_tpu.utils.retry import NON_RETRYABLE, with_retries

    variables, x = model
    eng = InferenceEngine(_fn, variables, device_batch_size=8)
    batches = [x[i:i + 8] for i in range(0, len(x), 8)]
    calls = {"n": 0}

    def run_once():
        calls["n"] += 1
        with faults.active(FaultPlan.parse(
                "pipeline.gather:error:exc=fatal,at=1")):
            return list(eng.map_batches(list(batches), pipeline=True))

    with pytest.raises(PipelineStageFatalError) as ei:
        with_retries(run_once, max_retries=3)
    assert isinstance(ei.value, PipelineStageError)  # still the one type
    assert isinstance(ei.value, NON_RETRYABLE)
    assert calls["n"] == 1  # deterministic: zero retries burned
    _no_stack_threads()


def test_circuit_open_passes_through_pipeline_unwrapped(model):
    """The breaker's typed fail-fast signal must survive the pipelined
    path: wrapping CircuitOpenError in a RuntimeError-lineage
    PipelineStageError would strip retry_after_s/last_error and turn
    fail-fast back into retryable noise for utils.retry callers."""
    variables, x = model
    eng = InferenceEngine(_fn, variables, device_batch_size=8,
                          breaker_threshold=1, breaker_cooldown_s=30.0)
    batches = [x[i:i + 8] for i in range(0, len(x), 8)]
    with faults.active(FaultPlan.parse("engine.dispatch:dead:at=1")):
        with pytest.raises(PipelineStageError):  # the outage itself
            list(eng.map_batches(list(batches), pipeline=True))
        assert eng.breaker_state()["state"] == "open"
        with pytest.raises(CircuitOpenError) as ei:  # NOT wrapped
            list(eng.map_batches(list(batches), pipeline=True))
        assert ei.value.retry_after_s > 0
    _no_stack_threads()


def test_pipeline_prepare_crash_names_the_input_side(model):
    variables, x = model
    eng = InferenceEngine(_fn, variables, device_batch_size=8)

    def bad_batches():
        yield x[:8]
        raise OSError("decoder disk vanished")

    with pytest.raises(PipelineStageError) as ei:
        list(eng.map_batches(bad_batches(), pipeline=True))
    assert ei.value.stage == "prepare"
    assert isinstance(ei.value.__cause__, OSError)
    assert "decoder disk vanished" in str(ei.value)  # match= compat
    _no_stack_threads()


# -- serving: storms, breaker shed, health, wedged drain -------------------

def test_breaker_open_sheds_at_submit_with_retry_after(model):
    variables, x = model
    with Server(_fn, variables, max_batch_size=8, max_wait_ms=2,
                bucket_sizes=[8], breaker_threshold=1,
                breaker_cooldown_s=30.0) as srv:
        srv.predict(x[0])  # healthy
        with faults.active(FaultPlan.parse("engine.dispatch:dead:at=1")):
            with pytest.raises(faults.InjectedDeadDeviceError):
                srv.predict(x[1])  # trips the 1-failure breaker
            with pytest.raises(ServiceUnavailableError) as ei:
                srv.submit(x[2])  # shed at SUBMIT: no queue, no timeout
            assert ei.value.retry_after_s > 0
            h = srv.health()
            assert h["state"] == "degraded" and h["live"]
            assert h["breaker"][8]["state"] == "open"
            assert h["last_error"]["type"] == "InjectedDeadDeviceError"
            assert srv.metrics.counters["serving.rejected_breaker_open"] \
                == 1
    assert srv.health()["state"] == "closed"


def test_circuit_open_is_exempt_from_serving_retry_budget(model):
    """A batch whose dispatch hits an OPEN breaker must fail fast even
    with a server retry budget configured — retrying CircuitOpenError
    with backoff would turn every shed batch into seconds of dead sleep
    against a device known to be failing."""
    variables, x = model
    with Server(_fn, variables, max_batch_size=4, max_wait_ms=2,
                bucket_sizes=[4], max_retries=3, retry_backoff_s=0.4,
                breaker_threshold=1, breaker_cooldown_s=30.0) as srv:
        srv.predict(x[0])  # compile + healthy
        with faults.active(FaultPlan.parse("engine.dispatch:dead:at=1")):
            t0 = time.monotonic()
            with pytest.raises((faults.InjectedDeadDeviceError,
                                CircuitOpenError)):
                # attempt 1 dies (opens the 1-failure breaker); attempt 2
                # gates on CircuitOpenError and must NOT burn attempts
                # 3/4 with 0.8s/1.6s backoffs
                srv.predict(x[1])
            assert time.monotonic() - t0 < 1.5
    assert srv.metrics.counters.get("serving.batch_failures", 0) == 1


def test_close_drain_returns_within_timeout_with_wedged_model(model):
    """Satellite: ``close(drain=True, timeout_s=...)`` under an injected
    stalled model — queued requests settle with errors and the call
    returns within (a small multiple of) the timeout, not the wedge."""
    variables, x = model
    srv = Server(_fn, variables, max_batch_size=2, max_wait_ms=10,
                 bucket_sizes=[2], max_inflight_batches=1)
    try:
        srv.predict(x[0])  # compile outside the wedge window
        with faults.active(FaultPlan.parse(
                "serving.model:sleep:ms=2500,times=1")):
            wedged = [srv.submit(x[i]) for i in range(2)]
            time.sleep(0.3)  # let the wedged batch start its model call
            parked = [srv.submit(x[i]) for i in range(2, 4)]
            t0 = time.monotonic()
            srv.close(drain=True, timeout_s=0.5)
            elapsed = time.monotonic() - t0
            assert elapsed < 2.0, (
                f"close() took {elapsed:.2f}s — it waited out the wedge "
                f"instead of honoring timeout_s")
            from sparkdl_tpu.serving import ServerClosedError

            for f in parked:  # queued behind the wedge: settled, errored
                with pytest.raises(ServerClosedError):
                    f.result(timeout=10)
            # the wedged batch itself settles once its model call returns
            for f in wedged:
                np.asarray(f.result(timeout=30))
    finally:
        srv.close()
    _no_stack_threads(("sparkdl-serving",))


# -- host I/O + probe sites ------------------------------------------------

def test_io_decode_fault_rides_drop_to_null(fixture_images):
    from sparkdl_tpu.image.io import decodeResizeBatch

    blobs = []
    for p in fixture_images["paths"][:3]:
        with open(p, "rb") as fh:
            blobs.append(fh.read())
    with faults.active(FaultPlan.parse("io.decode:error:exc=decode,at=2")):
        out, ok = decodeResizeBatch(blobs, 16, 16)
    assert list(ok) == [True, False, True]  # stream survived the fault
    assert not out[1].any() and out[0].any() and out[2].any()
    out2, ok2 = decodeResizeBatch(blobs, 16, 16)  # plan gone: all decode
    assert list(ok2) == [True, True, True]


def test_probe_device_fault_falls_back_fast():
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__
    finally:
        sys.path.remove(REPO)
    with faults.active(FaultPlan.parse("probe.device:error:every=1")):
        t0 = time.perf_counter()
        assert __graft_entry__._probe_local_device_count() is None
        assert time.perf_counter() - t0 < 1.0  # no child, no 120s wait


def test_bench_lines_stamp_faults_spec(monkeypatch):
    import bench

    faults.clear()  # the stage may run with SPARKDL_FAULTS exported
    lines = []
    monkeypatch.setattr(bench, "_print_line",
                        lambda s: lines.append(json.loads(s)))
    monkeypatch.setattr(bench, "_LINES", {})
    bench.emit("x", "m", 1.0, "u")
    assert lines[-1]["faults"] == "none"
    plan = FaultPlan.parse("seed=2;engine.dispatch:error:at=1")
    with faults.active(plan):
        bench.emit("x", "m", 1.0, "u")
    assert lines[-1]["faults"] == plan.spec  # chaos runs self-describe


# -- the acceptance chaos e2e ----------------------------------------------

def test_chaos_e2e_serving_plus_map_batches(model):
    """ISSUE 4 acceptance: one seeded plan injects one transient
    dispatch error, one pipeline gather-thread crash, and one queue-full
    storm into a CPU-backend serving + map_batches run.  All non-shed
    requests get correct outputs, health() transitions degraded->ready,
    and nothing is left wedged."""
    variables, x = model
    plan = FaultPlan.parse(
        "seed=7;"
        "engine.dispatch:error:exc=transient,at=4,times=1;"
        "pipeline.gather:error:exc=transient,at=2,times=1;"
        "serving.admit:error:exc=queue_full,at=9,times=1,retry_after=0.02")

    ref_eng = InferenceEngine(_fn, variables, device_batch_size=8)
    ref_rows = np.concatenate(
        [np.asarray(o) for o in ref_eng.map_batches([x], pipeline=False)])

    shed = []
    results = {}
    with faults.active(plan):
        # -- serving phase: sequential predicts make the dispatch order
        # (and thus the seeded plan's firing points) deterministic
        with Server(_fn, variables, max_batch_size=8, max_wait_ms=2,
                    bucket_sizes=[8], dispatch_retries=2,
                    breaker_threshold=8) as srv:
            srv.warmup(x[0])  # engine.dispatch call #1
            for i in range(16):
                try:
                    results[i] = np.asarray(srv.predict(x[i]))
                except QueueFullError as e:  # the injected storm
                    assert e.retry_after_s > 0
                    shed.append(i)
            h = srv.health()
        # exactly one storm reject; every other request served correctly
        assert shed == [8]
        for i, row in results.items():
            np.testing.assert_array_equal(row, ref_rows[i])
        # the transient dispatch error degraded health; the engine-level
        # retry absorbed it and the next served batch restored ready
        states = [t["state"] for t in h["transitions"]]
        assert "degraded" in states
        assert states[-1] == "ready" or h["state"] == "closed"
        assert states[states.index("degraded"):].count("ready") >= 1
        assert h["last_error"]["type"] == "InjectedTransientError"

        # -- map_batches phase, same plan: the gather-thread crash
        eng = InferenceEngine(_fn, variables, device_batch_size=8,
                              dispatch_retries=2)
        batches = [x[i:i + 8] for i in range(0, len(x), 8)]
        with pytest.raises(PipelineStageError) as ei:
            list(eng.map_batches(list(batches), pipeline=True))
        assert ei.value.stage == "gather"
        _no_stack_threads()  # crashed run drained cleanly
        out = [np.asarray(o) for o in eng.map_batches(list(batches),
                                                      pipeline=True)]
        np.testing.assert_array_equal(np.concatenate(out), ref_rows)

    # every planned fault actually fired exactly once
    stats = plan.stats()
    assert stats["engine.dispatch"]["fired"] == 1
    assert stats["pipeline.gather"]["fired"] == 1
    assert stats["serving.admit"]["fired"] == 1
    # join-with-timeout asserts: no thread or queue left wedged
    _no_stack_threads()


# -- kill the driver -------------------------------------------------------

def test_bench_artifact_survives_sigkill(tmp_path):
    """ISSUE 4 acceptance: SIGKILL bench.py mid-run; the incremental
    fsync'd JSONL artifact still holds a valid line for every completed
    config — an empty BENCH_*.json is no longer possible for any run
    that completed at least one config.  The relay is killed via the
    ``bench.relay_probe`` fault site, which also drives the real
    dead-relay path (chipless configs first)."""
    artifact = tmp_path / "bench_lines.jsonl"
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SPARKDL_BENCH_CONFIGS": "pipeline,serving",
        "SPARKDL_BENCH_ARTIFACT": str(artifact),
        "SPARKDL_BENCH_TRACE": "0",
        "SPARKDL_FAULTS": "bench.relay_probe:error:every=1",
        "SPARKDL_RELAY_CACHE": str(tmp_path / "relay.json"),
    })
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL, start_new_session=True)
    try:
        # wait for the first COMPLETED config line, then kill mid-run
        # (the serving config is underway or about to start)
        deadline = time.monotonic() + 240
        seen_pipeline = False
        while time.monotonic() < deadline and not seen_pipeline:
            if proc.poll() is not None:
                break  # finished early: artifact must still be complete
            if artifact.exists():
                seen_pipeline = any(
                    '"config": "pipeline"' in ln
                    for ln in artifact.read_text().splitlines())
            time.sleep(0.25)
        assert artifact.exists(), "no artifact written before kill"
        if proc.poll() is None:
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    lines = artifact.read_text().splitlines()
    assert lines, "artifact empty — the crash-safe contract failed"
    recs = [json.loads(ln) for ln in lines]  # every line is valid JSON
    # the injected dead relay left explicit diagnostics, not silence
    assert any(r.get("config") == "relay" and "error" in r for r in recs)
    # and the completed config's full record survived the SIGKILL
    pipeline = [r for r in recs if r.get("config") == "pipeline"]
    assert pipeline and "value" in pipeline[0]
    assert pipeline[0]["faults"].endswith("bench.relay_probe:error:every=1")
