"""graftlint: static-analysis rules, pragmas, registry, and the runtime
lock-order checker (ISSUE 5).

Everything here is stdlib-fast (in-memory fixture snippets, no jax
work): the whole file must stay in the low single-digit seconds —
tier-1 runs at ~85-90% of the driver's wall budget.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading

import pytest

from sparkdl_tpu.analysis import (RULE_HELP, lint_paths, lint_source,
                                  load_site_registry, lockcheck)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SITES_FIXTURE = {"engine.dispatch", "io.decode"}


def codes(src: str, sites=None) -> list:
    return [f.code for f in lint_source(
        src, sites=SITES_FIXTURE if sites is None else sites)]


# ---------------------------------------------------------------------------
# SDL001 — thread lifecycle
# ---------------------------------------------------------------------------

def test_sdl001_unjoined_thread_fires():
    src = (
        "import threading\n"
        "def f():\n"
        "    t = threading.Thread(target=print)\n"
        "    t.start()\n")
    assert codes(src) == ["SDL001"]


def test_sdl001_daemon_and_joined_pass():
    daemon = (
        "import threading\n"
        "def f():\n"
        "    t = threading.Thread(target=print, daemon=True)\n"
        "    t.start()\n")
    joined = (
        "import threading\n"
        "def f():\n"
        "    t = threading.Thread(target=print)\n"
        "    t.start()\n"
        "    try:\n"
        "        pass\n"
        "    finally:\n"
        "        t.join(timeout=2.0)\n")
    assert codes(daemon) == []
    assert codes(joined) == []


def test_sdl001_self_attr_joined_in_other_method_passes():
    src = (
        "import threading\n"
        "class S:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=print)\n"
        "        self._t.start()\n"
        "    def close(self):\n"
        "        self._t.join()\n")
    assert codes(src) == []


def test_sdl001_thread_pool_list_joined_in_loop_passes():
    src = (
        "import threading\n"
        "def f():\n"
        "    ts = [threading.Thread(target=print),\n"
        "          threading.Thread(target=print)]\n"
        "    for t in ts:\n"
        "        t.start()\n"
        "    for t in ts:\n"
        "        t.join()\n")
    assert codes(src) == []
    comp = (
        "import threading\n"
        "def f():\n"
        "    ts = [threading.Thread(target=print) for _ in range(3)]\n"
        "    for t in ts:\n"
        "        t.join()\n")
    assert codes(comp) == []
    unjoined_pool = (
        "import threading\n"
        "def f():\n"
        "    ts = [threading.Thread(target=print)]\n"
        "    for t in ts:\n"
        "        t.start()\n")
    assert codes(unjoined_pool) == ["SDL001"]


def test_sdl001_unbound_thread_and_timer_fire():
    assert codes("import threading\n"
                 "threading.Thread(target=print).start()\n"
                 ) == ["SDL001"]
    assert codes("import threading\n"
                 "def f(cb):\n"
                 "    threading.Timer(1.0, cb).start()\n"
                 ) == ["SDL001"]


# ---------------------------------------------------------------------------
# SDL002 — lockset discipline
# ---------------------------------------------------------------------------

_SDL002_BAD = (
    "import threading\n"
    "class C:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self.n = 0\n"
    "    def incr(self):\n"
    "        with self._lock:\n"
    "            self.n += 1\n"
    "    def reset(self):\n"
    "        self.n = 0\n")


def test_sdl002_unlocked_write_fires():
    found = lint_source(_SDL002_BAD, sites=SITES_FIXTURE)
    assert [f.code for f in found] == ["SDL002"]
    assert found[0].line == 10  # the reset() write, not the guarded one


def test_sdl002_all_writes_locked_pass_and_init_exempt():
    src = _SDL002_BAD.replace(
        "    def reset(self):\n        self.n = 0\n",
        "    def reset(self):\n"
        "        with self._lock:\n"
        "            self.n = 0\n")
    assert codes(src) == []


def test_sdl002_condition_counts_as_lock():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._cond = threading.Condition()\n"
        "        self.depth = 0\n"
        "    def a(self):\n"
        "        with self._cond:\n"
        "            self.depth += 1\n"
        "    def b(self):\n"
        "        self.depth -= 1\n")
    assert codes(src) == ["SDL002"]


def test_sdl002_pragma_suppresses():
    src = _SDL002_BAD.replace(
        "        self.n = 0\n    def incr",
        "        self.n = 0\n    def incr").replace(
        "    def reset(self):\n        self.n = 0\n",
        "    def reset(self):\n"
        "        # graftlint: allow=SDL002 reason=called before threads exist\n"
        "        self.n = 0\n")
    assert codes(src) == []


# ---------------------------------------------------------------------------
# SDL003 — broad except hygiene
# ---------------------------------------------------------------------------

def test_sdl003_swallowing_broad_except_fires():
    src = ("def f():\n"
           "    try:\n"
           "        g()\n"
           "    except Exception:\n"
           "        return None\n")
    assert codes(src) == ["SDL003"]
    bare = src.replace("except Exception:", "except:")
    assert codes(bare) == ["SDL003"]


def test_sdl003_reraise_log_and_pragma_pass():
    reraise = ("def f():\n"
               "    try:\n"
               "        g()\n"
               "    except Exception as e:\n"
               "        raise RuntimeError('x') from e\n")
    logs = ("def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception as e:\n"
            "        logger.warning('boom: %s', e)\n")
    pragma = ("def f():\n"
              "    try:\n"
              "        g()\n"
              "    except Exception:  "
              "# graftlint: allow=SDL003 reason=probe must not raise\n"
              "        return None\n")
    narrow = ("def f():\n"
              "    try:\n"
              "        g()\n"
              "    except ValueError:\n"
              "        return None\n")
    for src in (reraise, logs, pragma, narrow):
        assert codes(src) == []


def test_sdl000_pragma_without_reason_fires():
    src = ("def f():\n"
           "    try:\n"
           "        g()\n"
           "    except Exception:  # graftlint: allow=SDL003\n"
           "        return None\n")
    assert sorted(codes(src)) == ["SDL000", "SDL003"]


def test_pragma_on_line_above_suppresses():
    src = ("def f():\n"
           "    try:\n"
           "        g()\n"
           "    # graftlint: allow=SDL003 reason=deliberate swallow\n"
           "    except Exception:\n"
           "        return None\n")
    assert codes(src) == []


def test_pragma_inside_string_literal_is_inert():
    # pragma-shaped TEXT in a string must neither fire SDL000 nor
    # suppress a genuine finding on the next line
    bogus = 'MSG = "# graftlint: allow=SDL003"\n'
    assert codes(bogus) == []
    # the string literal sits on the line directly above the handler —
    # exactly where a real pragma would suppress it
    not_a_shield = ('def f():\n'
                    '    try:\n'
                    '        s = "# graftlint: allow=SDL003 reason=nope"\n'
                    '    except Exception:\n'
                    '        return None\n')
    assert codes(not_a_shield) == ["SDL003"]


# ---------------------------------------------------------------------------
# SDL004 — fault-site registry
# ---------------------------------------------------------------------------

def test_sdl004_typo_site_fires_and_known_site_passes():
    typo = ("from sparkdl_tpu.faults import inject\n"
            "def f():\n"
            "    inject('engine.dispach')\n")
    ok = typo.replace("engine.dispach", "engine.dispatch")
    found = lint_source(typo, sites=SITES_FIXTURE)
    assert [f.code for f in found] == ["SDL004"]
    assert "engine.dispach" in found[0].message
    assert codes(ok) == []


def test_sdl004_has_rules_checked_and_missing_registry_reported():
    src = ("from sparkdl_tpu import faults\n"
           "def f():\n"
           "    return faults.has_rules('io.decodee')\n")
    assert codes(src) == ["SDL004"]
    # no registry at all: site uses are reported as unverifiable
    assert [f.code for f in lint_source(src, sites=None)] == ["SDL004"]


def test_registry_file_matches_runtime_sites():
    from sparkdl_tpu.faults import SITE_HELP, SITES

    extracted = load_site_registry([os.path.join(REPO, "sparkdl_tpu")])
    assert extracted == set(SITES) == set(SITE_HELP)


def test_fault_plan_rejects_unknown_site_at_construction():
    from sparkdl_tpu.faults import FaultPlan, FaultRule, validate_site

    with pytest.raises(ValueError, match="unknown fault site"):
        FaultRule(site="engine.dispach", action="error")
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultPlan.parse("seed=1;engine.dispach:error:at=1")
    # even a rule mutated after construction fails at plan build
    r = FaultRule(site="engine.dispatch", action="error")
    r.site = "nope.nope"
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultPlan([r], seed=1)
    assert validate_site("engine.dispatch") == "engine.dispatch"


# ---------------------------------------------------------------------------
# SDL005 — naming schema + span pairing
# ---------------------------------------------------------------------------

def test_sdl005_bad_metric_name_fires():
    assert codes("def f(m):\n    m.incr('Serving Requests')\n"
                 ) == ["SDL005"]
    assert codes("def f(m):\n    m.record_time('servingLatency', 1.0)\n"
                 ) == ["SDL005"]


def test_sdl005_schema_names_pass():
    src = ("def f(m, t):\n"
           "    m.incr('serving.requests')\n"
           "    m.observe('pipeline.prep_q_depth', 3)\n"
           "    m.gauge('items', 1)\n"
           "    with t.span('engine.dispatch'):\n"
           "        pass\n")
    assert codes(src) == []


def test_sdl005_leaked_span_fires():
    dead_local = ("def f(tracer):\n"
                  "    sp = tracer.start_span('serving.request')\n"
                  "    return 1\n")
    bare = "def f(tracer):\n    tracer.span('engine.call')\n"
    assert codes(dead_local) == ["SDL005"]
    assert codes(bare) == ["SDL005"]


def test_sdl005_closed_or_handed_off_spans_pass():
    finished = ("def f(tracer):\n"
                "    sp = tracer.start_span('serving.request')\n"
                "    work()\n"
                "    sp.finish()\n")
    cross_thread = ("def f(tracer, req):\n"
                    "    req.span = tracer.start_span('serving.request')\n")
    conditional = ("def f(tracer):\n"
                   "    sp = (tracer.start_span('pipeline.run')\n"
                   "          if tracer.enabled else None)\n"
                   "    try:\n"
                   "        pass\n"
                   "    finally:\n"
                   "        if sp is not None:\n"
                   "            sp.finish()\n")
    for src in (finished, cross_thread, conditional):
        assert codes(src) == []


# ---------------------------------------------------------------------------
# SDL006 — monotonic timing
# ---------------------------------------------------------------------------

def test_sdl006_wall_clock_latency_fires():
    src = ("import time\n"
           "def f():\n"
           "    t0 = time.time()\n"
           "    g()\n"
           "    return time.time() - t0\n")
    assert codes(src) == ["SDL006"]  # one finding per subtraction
    direct = ("import time\n"
              "def g(t0):\n"
              "    return time.time() - t0\n")
    assert codes(direct) == ["SDL006"]


def test_sdl006_sees_time_module_aliases():
    aliased = ("import time as time_lib\n"
               "def f():\n"
               "    t0 = time_lib.time()\n"
               "    return time_lib.time() - t0\n")
    assert codes(aliased) == ["SDL006"]
    from_import = ("from time import time as now\n"
                   "def f(t0):\n"
                   "    return now() - t0\n")
    assert codes(from_import) == ["SDL006"]
    # monotonic through the alias stays legal
    mono = ("import time as time_lib\n"
            "def f():\n"
            "    t0 = time_lib.monotonic()\n"
            "    return time_lib.monotonic() - t0\n")
    assert codes(mono) == []


def test_sdl006_stamps_and_perf_counter_pass():
    stamp = ("import time\n"
             "def f(rec):\n"
             "    rec['ts'] = time.time()\n")
    perf = ("import time\n"
            "def f():\n"
            "    t0 = time.perf_counter()\n"
            "    return time.perf_counter() - t0\n")
    assert codes(stamp) == []
    assert codes(perf) == []


# ---------------------------------------------------------------------------
# SDL007 — explicit donation decision at every jit site (ISSUE 6)
# ---------------------------------------------------------------------------

def test_sdl007_bare_jit_fires():
    src = ("import jax\n"
           "def f(fn):\n"
           "    return jax.jit(fn)\n")
    assert codes(src) == ["SDL007"]
    from_import = ("from jax import jit\n"
                   "def f(fn):\n"
                   "    return jit(fn)\n")
    assert codes(from_import) == ["SDL007"]


def test_sdl007_partial_decorator_form_fires():
    src = ("import functools\n"
           "import jax\n"
           "@functools.partial(jax.jit, static_argnames=('h',))\n"
           "def f(x, h):\n"
           "    return x\n")
    assert codes(src) == ["SDL007"]


def test_sdl007_bare_decorator_form_fires():
    # no Call node exists for @jax.jit — the decorator list is checked
    src = ("import jax\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    return x\n")
    assert codes(src) == ["SDL007"]
    from_import = ("from jax import jit\n"
                   "@jit\n"
                   "def f(x):\n"
                   "    return x\n")
    assert codes(from_import) == ["SDL007"]


def test_sdl007_explicit_decision_passes():
    empty = ("import jax\n"
             "def f(fn):\n"
             "    return jax.jit(fn, donate_argnums=())\n")
    donated = ("import jax\n"
               "def f(fn):\n"
               "    return jax.jit(fn, donate_argnames=('x',))\n")
    partial = ("import functools\n"
               "import jax\n"
               "@functools.partial(jax.jit, donate_argnums=(0,))\n"
               "def f(x):\n"
               "    return x\n")
    assert codes(empty) == []
    assert codes(donated) == []
    assert codes(partial) == []


def test_sdl007_pragma_needs_reason():
    with_reason = ("import jax\n"
                   "def f(fn):\n"
                   "    # graftlint: allow=SDL007 reason=one-shot probe\n"
                   "    return jax.jit(fn)\n")
    assert codes(with_reason) == []
    bare = ("import jax\n"
            "def f(fn):\n"
            "    # graftlint: allow=SDL007\n"
            "    return jax.jit(fn)\n")
    # a reason-less pragma is itself a finding AND suppresses nothing
    assert codes(bare) == ["SDL000", "SDL007"]


def test_sdl007_ignores_non_jax_jit():
    src = ("import numba\n"
           "def f(fn):\n"
           "    return numba.jit(fn)\n")
    assert codes(src) == []


# ---------------------------------------------------------------------------
# the repo itself must lint clean (the acceptance gate, in-tree)
# ---------------------------------------------------------------------------

def test_repo_lints_clean():
    targets = [os.path.join(REPO, "sparkdl_tpu"),
               os.path.join(REPO, "tools"),
               os.path.join(REPO, "bench.py")]
    findings = lint_paths(targets)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f():\n"
                   "    try:\n"
                   "        g()\n"
                   "    except Exception:\n"
                   "        return None\n")
    cli = os.path.join(REPO, "tools", "graftlint.py")
    r = subprocess.run([sys.executable, cli, str(bad)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "SDL003" in r.stdout
    bad.write_text("X = 1\n")
    r = subprocess.run([sys.executable, cli, str(bad)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout
    r = subprocess.run([sys.executable, cli, "--list-rules"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0
    for code in RULE_HELP:
        assert code in r.stdout


def test_cli_json_output(tmp_path):
    """--json (ISSUE 6 satellite): stable machine-readable findings for
    CI — rule/path/line/message per finding, exit codes unchanged."""
    import json

    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n"
                   "def f(fn):\n"
                   "    return jax.jit(fn)\n")
    cli = os.path.join(REPO, "tools", "graftlint.py")
    r = subprocess.run([sys.executable, cli, "--json", str(bad)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    assert doc["files"] == 1 and doc["rules"] == len(RULE_HELP)
    [finding] = doc["findings"]
    assert finding["rule"] == "SDL007"
    assert finding["path"] == str(bad) and finding["line"] == 3
    assert "donate_argnums" in finding["message"]
    bad.write_text("X = 1\n")
    r = subprocess.run([sys.executable, cli, "--json", str(bad)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0
    assert json.loads(r.stdout)["findings"] == []


def test_cli_sites_file_option(tmp_path):
    # an explicit registry file works regardless of its name/location
    reg = tmp_path / "my_sites.py"
    reg.write_text('SITE_HELP = {"custom.site": "a site"}\n')
    src = tmp_path / "code.py"
    src.write_text("def f(x):\n    inject('custom.site')\n")
    cli = os.path.join(REPO, "tools", "graftlint.py")
    r = subprocess.run(
        [sys.executable, cli, "--sites-file", str(reg), str(src)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    src.write_text("def f(x):\n    inject('custom.typo')\n")
    r = subprocess.run(
        [sys.executable, cli, "--sites-file", str(reg), str(src)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 1 and "SDL004" in r.stdout


# ---------------------------------------------------------------------------
# runtime lock-order checker
# ---------------------------------------------------------------------------

@pytest.fixture
def checked_locks():
    lockcheck.enable()
    lockcheck.reset()
    try:
        yield
    finally:
        lockcheck.reset()
        lockcheck.disable()


def test_lockcheck_disabled_returns_plain_primitives():
    lockcheck.disable()
    try:
        lk = lockcheck.named_lock("t.plain")
        assert type(lk) is type(threading.Lock())
        cond = lockcheck.named_condition("t.plain_cond")
        assert isinstance(cond, threading.Condition)
    finally:
        lockcheck.reset()


def test_lockcheck_detects_inverted_order(checked_locks):
    a = lockcheck.named_lock("t.a")
    b = lockcheck.named_lock("t.b")
    with a:
        with b:
            pass
    with pytest.raises(lockcheck.LockOrderError) as ei:
        with b:
            with a:
                pass
    assert ei.value.cycle == ["t.a", "t.b"]
    assert "t.a" in str(ei.value) and "t.b" in str(ei.value)


def test_lockcheck_consistent_order_and_same_name_pass(checked_locks):
    a = lockcheck.named_lock("t.a")
    b = lockcheck.named_lock("t.b")
    for _ in range(3):  # repeated consistent nesting is fine
        with a:
            with b:
                pass
    # two INSTANCES of one lock class never self-edge
    b2 = lockcheck.named_lock("t.b")
    with b:
        with b2:
            pass
    assert lockcheck.order_graph() == {"t.a": ["t.b"]}


def test_lockcheck_three_way_cycle_detected(checked_locks):
    a = lockcheck.named_lock("t3.a")
    b = lockcheck.named_lock("t3.b")
    c = lockcheck.named_lock("t3.c")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(lockcheck.LockOrderError) as ei:
        with c:
            with a:
                pass
    assert ei.value.cycle == ["t3.a", "t3.b", "t3.c"]


def test_lockcheck_condition_wait_keeps_stack_consistent(checked_locks):
    cond = lockcheck.named_condition("t.cond")
    state = []

    def waiter():
        with cond:
            while not state:
                cond.wait(timeout=5.0)
            state.append("woke")

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    with cond:
        state.append("go")
        cond.notify_all()
    t.join(timeout=5.0)
    assert not t.is_alive() and state == ["go", "woke"]
