"""graftcheck: program-level rules GC001–GC005, the lockfile contract,
the CLI, and the repo-audits-clean acceptance gate (ISSUE 6).

Budget discipline: the per-rule fixtures are tiny matmul programs
(abstract lowering only — fractions of a second each); the one
real-model audit is MobileNetV2 at a single bucket, shared by the
acceptance gate and the CLI/--json test.  Everything stays well under
the tier-1 headroom (~720-780 s of the 870 s driver window).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkdl_tpu.analysis.program import (ProgramSpec, audit_inventory,
                                          audit_program, diff_records,
                                          pad_waste_audit, read_lockfile,
                                          retrace_audit, stack_programs,
                                          write_lockfile, zoo_gflop_per_img)
from sparkdl_tpu.parallel import mesh as mesh_lib
from sparkdl_tpu.parallel.engine import build_dispatch_jit

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOCKFILE = os.path.join(REPO, "PROGRAMS.lock.json")

D = 16  # feature dim of the synthetic programs


def _axes(mesh):
    return {str(n): int(s)
            for n, s in zip(mesh.axis_names, mesh.devices.shape)}


def _dispatch_spec(fn, *, rows=8, compute_dtype=None, donate_reason="n/a",
                   name="synth/dispatch", in_dtype=np.float32,
                   mesh=None, param_shape=(D, D)):
    """A small engine-style dispatch program over the test mesh."""
    mesh = mesh if mesh is not None else mesh_lib.get_mesh()

    def build():
        jitted = build_dispatch_jit(fn, mesh, donate_batch=False)
        v = {"w": jax.ShapeDtypeStruct(param_shape, np.float32)}
        x = jax.ShapeDtypeStruct((rows, param_shape[0]), in_dtype)
        return jitted, (v, x)

    return ProgramSpec(name=name, kind="dispatch", build=build,
                       compute_dtype=compute_dtype,
                       donate_reason=donate_reason, batch_rows=rows,
                       shardings=("replicated", "batch"),
                       mesh_axes=_axes(mesh), group=name)


def _train_spec(*, donate=(0,), out_dtype=None, name="synth/train",
                donate_reason=None):
    """A train-style program: params in, params out (donatable unless
    ``out_dtype`` breaks the alias)."""
    mesh = mesh_lib.get_mesh()
    repl = mesh_lib.replicated_sharding(mesh)
    bsh = mesh_lib.batch_sharding(mesh)

    def step(p, x):
        g = x.T @ x @ p["w"]
        new = {"w": p["w"] - 0.1 * g}
        if out_dtype is not None:
            new = {"w": new["w"].astype(out_dtype)}
        return new, jnp.mean(g)

    def build():
        jitted = jax.jit(step, in_shardings=(repl, bsh),
                         out_shardings=(repl, repl),
                         donate_argnums=donate)
        p = {"w": jax.ShapeDtypeStruct((D, D), np.float32)}
        x = jax.ShapeDtypeStruct((8, D), np.float32)
        return jitted, (p, x)

    return ProgramSpec(name=name, kind="train", build=build, donate=donate,
                       donate_reason=donate_reason, batch_rows=8,
                       shardings=("replicated", "batch"),
                       mesh_axes=_axes(mesh), group=name)


# ---------------------------------------------------------------------------
# GC001 — donation
# ---------------------------------------------------------------------------

def test_gc001_missing_donation_fires():
    spec = _train_spec(donate=())
    out = audit_program(spec)
    assert [f.code for f in out["findings"]] == ["GC001"]
    assert "donates nothing" in out["findings"][0].message


def test_gc001_established_alias_passes():
    out = audit_program(_train_spec(donate=(0,)))
    assert out["findings"] == []
    d = out["record"]["donation"]
    assert d["donated_leaves"] == 1 and d["aliased"] >= 1


def test_gc001_dropped_donation_fires():
    # params f32 in but bf16 out: XLA cannot alias, donation is silently
    # dropped — exactly the regression class GC001 exists for
    out = audit_program(_train_spec(donate=(0,), out_dtype=jnp.bfloat16))
    assert [f.code for f in out["findings"]] == ["GC001"]
    assert "silently dropped" in out["findings"][0].message


def test_gc001_reason_exempts():
    spec = _train_spec(donate=(), donate_reason="caller reuses params")
    assert audit_program(spec)["findings"] == []
    rec = audit_program(spec)["record"]
    assert rec["donation"]["reason"] == "caller reuses params"


# ---------------------------------------------------------------------------
# GC002 — dtype leaks
# ---------------------------------------------------------------------------

def _bf16_fn(leak: bool):
    def fn(v, x):
        xc = x.astype(jnp.float32 if leak else jnp.bfloat16)
        w = v["w"].astype(xc.dtype)
        return xc @ w

    return fn


def test_gc002_f32_dot_under_bf16_fires():
    out = audit_program(_dispatch_spec(_bf16_fn(leak=True),
                                       compute_dtype="bfloat16"))
    assert [f.code for f in out["findings"]] == ["GC002"]
    assert out["record"]["dtype_counts"].get("dot_f32", 0) >= 1


def test_gc002_bf16_clean_and_f32_config_exempt():
    clean = audit_program(_dispatch_spec(_bf16_fn(leak=False),
                                         compute_dtype="bfloat16"))
    assert clean["findings"] == []
    assert clean["record"]["dtype_counts"].get("dot_bf16", 0) >= 1
    # the same leaky program audited under a declared f32 config is fine
    f32 = audit_program(_dispatch_spec(_bf16_fn(leak=True),
                                       compute_dtype="float32"))
    assert f32["findings"] == []


def test_gc002_bf16_accumulate_f32_is_not_a_leak():
    # bf16 operands + preferred_element_type=f32 is the kernels'
    # deliberate precision contract (sepconv), not an upcast leak
    def fn(v, x):
        return jax.lax.dot_general(
            x.astype(jnp.bfloat16), v["w"].astype(jnp.bfloat16),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    out = audit_program(_dispatch_spec(fn, compute_dtype="bfloat16"))
    assert out["findings"] == []


# ---------------------------------------------------------------------------
# GC003 — retrace / cache keys
# ---------------------------------------------------------------------------

def test_gc003_weak_type_fires():
    def fn(v, x):
        return x * v["w"][0, 0]

    def build():
        # a plain jit (no shardings) traced with a python float: the
        # scalar enters the signature as a WEAK f32 aval
        jitted = jax.jit(fn, donate_argnums=())
        v = {"w": jax.ShapeDtypeStruct((D, D), np.float32)}
        return jitted, (v, 3.0)

    spec = ProgramSpec(name="synth/weak", kind="dispatch", build=build,
                       donate_reason="n/a", group="synth/weak")
    records, findings = audit_inventory([spec])
    assert any(f.code == "GC003" and "weak-typed" in f.message
               for f in findings)
    assert records[0]["in_avals"]["weak"] == 1


def test_gc003_duplicate_and_churn():
    a = audit_program(_dispatch_spec(_bf16_fn(False),
                                     name="synth/dup"))["record"]
    b = dict(a, name="synth/dup2")
    dup = retrace_audit([a, dict(a, name="synth/dup-copy")])
    assert any(f.code == "GC003" and "duplicate" in f.message for f in dup)
    # same shapes, different dtype signature in one group -> churn
    b["group"] = a["group"]
    b["in_avals"] = dict(a["in_avals"], key="different-dtype-key")
    churn = retrace_audit([a, b])
    assert any(f.code == "GC003" and "churn" in f.message for f in churn)
    assert retrace_audit([a]) == []


# ---------------------------------------------------------------------------
# GC004 — pad-waste budget
# ---------------------------------------------------------------------------

def _bucket_rec(model, bucket, gflop_per_row=1.0):
    return {"name": f"zoo/{model}/b{bucket}", "model": model,
            "bucket": bucket, "flops": gflop_per_row * 1e9 * bucket,
            "in_avals": {"n": 1, "weak": 0, "key": str(bucket),
                         "shape_key": str(bucket)}}


def test_gc004_quarter_half_full_passes():
    recs = [_bucket_rec("M", b) for b in (8, 16, 32)]
    assert pad_waste_audit(recs) == []


def test_gc004_wide_gap_and_single_bucket_fire():
    gap = pad_waste_audit([_bucket_rec("M", 8), _bucket_rec("M", 64)])
    assert any(f.code == "GC004" and "bucket gap" in f.message for f in gap)
    single = pad_waste_audit([_bucket_rec("M", 64)])
    assert any(f.code == "GC004" and "smallest bucket" in f.message
               for f in single)


def test_gc004_nonlinear_flops_fire():
    recs = [_bucket_rec("M", 8), _bucket_rec("M", 16, gflop_per_row=1.2)]
    out = pad_waste_audit(recs)
    assert any(f.code == "GC004" and "per-row FLOPs" in f.message
               for f in out)


# ---------------------------------------------------------------------------
# GC005 — sharding audit
# ---------------------------------------------------------------------------

def test_gc005_large_replicated_param_with_model_axis_fires():
    mesh = mesh_lib.get_mesh(model_parallel=2)

    def fn(v, x):
        return x @ v["w"]

    spec = _dispatch_spec(fn, mesh=mesh, rows=8,
                          param_shape=(4096, 4096))  # 64 MB leaf
    out = audit_program(spec)
    assert any(f.code == "GC005" and "replicated" in f.message
               for f in out["findings"])


def test_gc005_indivisible_batch_fires():
    # jax refuses the lowering itself (10 rows on a 4-way data axis);
    # the auditor reports it as a GC005 finding instead of crashing
    mesh = mesh_lib.get_mesh(model_parallel=2)  # data axis = 4
    spec = _dispatch_spec(_bf16_fn(False), mesh=mesh, rows=10)
    out = audit_program(spec)
    assert any(f.code == "GC005" and "failed to lower" in f.message
               for f in out["findings"])
    assert out["record"]["fingerprint"] is None


def test_gc005_replicated_on_data_only_mesh_passes():
    spec = _dispatch_spec(_bf16_fn(False), param_shape=(4096, 4096))
    assert [f.code for f in audit_program(spec)["findings"]] == []


# ---------------------------------------------------------------------------
# lockfile — round trip, tamper detection, drift classification
# ---------------------------------------------------------------------------

@pytest.fixture()
def small_records():
    specs = [_train_spec(donate=(0,)),
             _dispatch_spec(_bf16_fn(False), compute_dtype="bfloat16",
                            name="synth/disp")]
    records, findings = audit_inventory(specs)
    assert findings == []
    return records


def test_lockfile_round_trip_and_tamper(tmp_path, small_records):
    path = str(tmp_path / "lock.json")
    write_lockfile(small_records, path, meta={"jax_version": "x"})
    doc = read_lockfile(path)
    assert doc["meta"]["jax_version"] == "x"
    assert diff_records(doc, small_records) == []

    # tamper classes -> the GC rule that names them
    def tampered(mutate):
        d = json.loads(json.dumps(doc))
        mutate(d["programs"]["synth/train"])
        return d

    drift = diff_records(tampered(
        lambda p: p.update(fingerprint="0" * 64)), small_records)
    assert [f.code for f in drift] == ["GC000"]
    drift = diff_records(tampered(
        lambda p: p["donation"].update(declared=[])), small_records)
    assert [f.code for f in drift] == ["GC001"]
    drift = diff_records(tampered(
        lambda p: p["dtype_counts"].update(dot_f32=9)), small_records)
    assert [f.code for f in drift] == ["GC002"]
    drift = diff_records(tampered(
        lambda p: p["in_avals"].update(key="churned")), small_records)
    assert [f.code for f in drift] == ["GC003"]
    drift = diff_records(tampered(
        lambda p: p.update(flops=p["flops"] * 2)), small_records)
    assert [f.code for f in drift] == ["GC004"]


def test_lockfile_program_set_drift(tmp_path, small_records):
    path = str(tmp_path / "lock.json")
    write_lockfile(small_records[:1], path)
    doc = read_lockfile(path)
    # new program not in baseline
    drift = diff_records(doc, small_records)
    assert any(f.code == "GC003" and "not in the committed" in f.message
               for f in drift)
    # program left the stack (full audit) vs narrowed subset audit
    write_lockfile(small_records, path)
    doc = read_lockfile(path)
    drift = diff_records(doc, small_records[:1], subset=False)
    assert any("not enumerated" in f.message for f in drift)
    assert diff_records(doc, small_records[:1], subset=True) == []


def test_lockfile_schema_version_guard(tmp_path):
    path = str(tmp_path / "lock.json")
    with open(path, "w") as fh:
        json.dump({"schema_version": 99, "programs": {}}, fh)
    with pytest.raises(ValueError, match="unsupported lockfile schema"):
        read_lockfile(path)


# ---------------------------------------------------------------------------
# bench denominators ride the lockfile
# ---------------------------------------------------------------------------

def test_bench_constants_agree_with_lockfile():
    """The drift gate the ISSUE asks for: bench.py's pinned fallback
    GF/img constants and the committed lockfile's audited programs must
    agree — a program change that moves real FLOPs has to update BOTH
    (constants document the derivation, the lockfile is the live
    source)."""
    locked = zoo_gflop_per_img(LOCKFILE)
    assert locked, "committed PROGRAMS.lock.json missing zoo programs"
    import bench

    for model, pinned in bench._ZOO_GFLOP_FALLBACK.items():
        assert model in locked, model
        assert abs(locked[model] - pinned) / pinned < 0.02, (
            f"{model}: lockfile {locked[model]:.3f} GF/img vs bench "
            f"constant {pinned:.3f} — regenerate the baseline or fix "
            f"the constant")
        # and bench actually serves the lockfile value
        assert bench.ZOO_GFLOP_PER_IMG[model] == pytest.approx(
            locked[model])


# ---------------------------------------------------------------------------
# acceptance gate: the repo audits clean against its committed lockfile
# ---------------------------------------------------------------------------

def test_repo_subset_audits_clean_against_committed_lockfile():
    """MobileNetV2 x one bucket + train steps + kernels, audited fresh
    in-process and diffed (subset mode) against the committed
    PROGRAMS.lock.json: zero findings, zero drift.  The FULL zoo sweep
    runs in run-tests.sh's guarded graftcheck stage; this keeps the
    chip-free contract inside tier-1 at ~a tenth of the cost."""
    specs = stack_programs(max_batch_size=8, models=["MobileNetV2"])
    records, findings = audit_inventory(specs)
    assert findings == [], [f.render() for f in findings]
    committed = read_lockfile(LOCKFILE)
    drift = diff_records(committed, records, subset=True)
    assert drift == [], [f.render() for f in drift]


def test_deliberate_mutations_named_by_rule():
    """The acceptance criterion's two mutations, exercised at the audit
    layer: dropping donate_argnums fails GC001 BY NAME; forcing an f32
    upcast under bf16 fails GC002 BY NAME."""
    dropped = audit_program(_train_spec(donate=()))["findings"]
    assert [f.code for f in dropped] == ["GC001"]
    upcast = audit_program(_dispatch_spec(
        _bf16_fn(leak=True), compute_dtype="bfloat16"))["findings"]
    assert [f.code for f in upcast] == ["GC002"]


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_json_subset_clean(capsys):
    """graftcheck --json over the MobileNetV2 subset vs the committed
    lockfile: exit 0, stable machine-readable schema."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import graftcheck
    finally:
        sys.path.pop(0)
    rc = graftcheck.main(["--models", "MobileNetV2", "--max-batch", "8",
                          "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0, out
    assert out["findings"] == []
    names = set(out["programs"])
    assert "zoo/MobileNetV2/featurize/bfloat16/b8" in names
    assert all({"fingerprint", "flops", "findings"}
               <= set(v) for v in out["programs"].values())


def test_cli_missing_lockfile_exits_2(tmp_path):
    cli = os.path.join(REPO, "tools", "graftcheck.py")
    r = subprocess.run(
        [sys.executable, cli, "--lockfile", str(tmp_path / "nope.json"),
         "--models", "MobileNetV2", "--max-batch", "8", "--no-train",
         "--no-kernels"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 2, r.stdout + r.stderr
    assert "no lockfile" in r.stderr


def test_cli_list_rules():
    cli = os.path.join(REPO, "tools", "graftcheck.py")
    r = subprocess.run([sys.executable, cli, "--list-rules"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0
    for code in ("GC000", "GC001", "GC002", "GC003", "GC004", "GC005"):
        assert code in r.stdout
