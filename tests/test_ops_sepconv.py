"""Fused separable-conv kernel (ops/sepconv.py) parity and plumbing.

The pallas kernel itself runs here through the PALLAS INTERPRETER
(``force="interpret"``) so CI exercises the real roll/dot/mask kernel
logic on CPU; the compiled-TPU parity was additionally pinned bit-exact
against the same reference on hardware (PERF.md round 4).  Reference
behavior: keras SeparableConv2D + inference BatchNorm
(python/sparkdl/transformers/named_image.py Xception path).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkdl_tpu.ops.sepconv import (flat_width, fused_sepconv_flat,
                                     pad_to_flat, sepconv_reference,
                                     unflatten)

SHAPES = [
    (19, 19, 32, 40),   # middle-flow class (728->728 at full scale)
    (10, 10, 24, 48),   # exit-flow class (post_relu)
    (12, 9, 16, 16),    # non-square, w+2 already a sublane multiple
]


def _mats(rng, c, f):
    dwk = jnp.asarray(rng.normal(0, 0.2, (3, 3, c)), jnp.float32)
    pw = jnp.asarray(rng.normal(0, 0.05, (c, f)), jnp.float32)
    scale = jnp.asarray(rng.normal(1, 0.1, (f,)), jnp.float32)
    shift = jnp.asarray(rng.normal(0, 0.1, (f,)), jnp.float32)
    return dwk, pw, scale, shift


def test_flat_roundtrip(rng):
    x = jnp.asarray(rng.normal(size=(2, 7, 5, 3)), jnp.float32)
    xf = pad_to_flat(x, 7, 5)
    assert xf.shape == (2, 9 * flat_width(5), 3)
    np.testing.assert_array_equal(np.asarray(unflatten(xf, 7, 5)),
                                  np.asarray(x))
    # halo positions are zero
    grid = np.asarray(xf).reshape(2, 9, flat_width(5), 3)
    assert np.all(grid[:, 0] == 0) and np.all(grid[:, -1] == 0)
    assert np.all(grid[:, :, 0] == 0) and np.all(grid[:, :, 6:] == 0)


def test_reference_matches_direct_convs(rng):
    """The jax reference twin == explicit depthwise+pointwise+affine."""
    h, w, c, f = 9, 9, 8, 12
    x = jnp.asarray(rng.normal(size=(2, h, w, c)), jnp.float32)
    dwk, pw, scale, shift = _mats(rng, c, f)
    got = sepconv_reference(x, dwk, pw, scale, shift, pre_relu=True,
                            post_relu=True)
    xr = jax.nn.relu(x.astype(jnp.bfloat16))
    dw_out = jax.lax.conv_general_dilated(
        xr, dwk.reshape(3, 3, 1, c).astype(jnp.bfloat16), (1, 1), "SAME",
        feature_group_count=c, dimension_numbers=("NHWC", "HWIO", "NHWC"))
    pw_out = jnp.einsum("nhwc,cf->nhwf", dw_out.astype(jnp.float32),
                        pw.astype(jnp.float32))
    want = jax.nn.relu(pw_out * scale + shift)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=0.1, atol=0.05)


@pytest.mark.parametrize("h,w,c,f", SHAPES)
@pytest.mark.parametrize("pre_relu,post_relu", [(False, False),
                                                (True, False),
                                                (False, True)])
def test_kernel_parity_interpreted(rng, h, w, c, f, pre_relu, post_relu):
    """The REAL pallas kernel (interpreted) == jax reference, including
    the output-halo contract (zeros, next-layer consumable)."""
    x = jnp.asarray(rng.normal(size=(2, h, w, c)), jnp.float32)
    dwk, pw, scale, shift = _mats(rng, c, f)
    xf = pad_to_flat(x, h, w)
    got_f = fused_sepconv_flat(xf, dwk, pw, scale, shift, h, w,
                               pre_relu, post_relu, force="interpret")
    ref_f = fused_sepconv_flat(xf, dwk, pw, scale, shift, h, w,
                               pre_relu, post_relu, force=False)
    got = np.asarray(unflatten(got_f, h, w), np.float32)
    ref = np.asarray(unflatten(ref_f, h, w), np.float32)
    np.testing.assert_allclose(got, ref, rtol=0.08, atol=0.05)
    # halo contract: kernel output halo is ZERO (chainable)
    wp = flat_width(w)
    grid = np.asarray(got_f, np.float32).reshape(2, h + 2, wp, f)
    assert np.all(grid[:, 0] == 0) and np.all(grid[:, -1] == 0)
    assert np.all(grid[:, :, 0] == 0) and np.all(grid[:, :, w + 1:] == 0)


def test_kernel_chain_interpreted(rng):
    """Two chained kernels with NO repacking == two reference layers —
    the property the Xception middle flow relies on."""
    h, w, c = 13, 13, 16
    x = jnp.asarray(rng.normal(size=(2, h, w, c)), jnp.float32)
    dwk1, pw1, s1, t1 = _mats(rng, c, c)
    dwk2, pw2, s2, t2 = _mats(rng, c, c)
    xf = pad_to_flat(x, h, w)
    a = fused_sepconv_flat(xf, dwk1, pw1, s1, t1, h, w, True, False,
                           force="interpret")
    b = fused_sepconv_flat(a, dwk2, pw2, s2, t2, h, w, True, False,
                           force="interpret")
    got = np.asarray(unflatten(b, h, w), np.float32)
    r1 = sepconv_reference(x, dwk1, pw1, s1, t1, True)
    r2 = sepconv_reference(r1, dwk2, pw2, s2, t2, True)
    np.testing.assert_allclose(got, np.asarray(r2, np.float32),
                               rtol=0.1, atol=0.08)


TILED_SHAPES = [
    (13, 11, 16, 16, 5),   # rows 15 = 3 tiles of 5
    (19, 19, 32, 40, 7),   # rows 21 = 3 tiles of 7; c != f
    (12, 9, 16, 24, 7),    # rows round UP 14 -> 21 (bottom pad rows)
]


@pytest.mark.parametrize("h,w,c,f,th", TILED_SHAPES)
@pytest.mark.parametrize("pre_relu,post_relu", [(True, False),
                                                (False, True)])
def test_tiled_kernel_parity_interpreted(rng, h, w, c, f, th, pre_relu,
                                         post_relu):
    """The row-tiled kernel generation (VERDICT r4 #1: the 147^2/74^2
    entry-flow shapes whose whole image exceeds VMEM) == jax reference,
    including clamped edge-tile halos, rows rounded up to the tile, and
    the zeroed-halo output contract."""
    x = jnp.asarray(rng.normal(size=(2, h, w, c)), jnp.float32)
    dwk, pw, scale, shift = _mats(rng, c, f)
    xf = pad_to_flat(x, h, w, row_tile=th)
    rows = xf.shape[1] // flat_width(w)
    assert rows % th == 0 and rows >= h + 2
    got_f = fused_sepconv_flat(xf, dwk, pw, scale, shift, h, w,
                               pre_relu, post_relu, force="interpret",
                               row_tile=th)
    ref_f = fused_sepconv_flat(xf, dwk, pw, scale, shift, h, w,
                               pre_relu, post_relu, force=False)
    assert got_f.shape == ref_f.shape
    got = np.asarray(unflatten(got_f, h, w), np.float32)
    ref = np.asarray(unflatten(ref_f, h, w), np.float32)
    np.testing.assert_allclose(got, ref, rtol=0.08, atol=0.05)
    # halo/pad contract: everything outside the h x w interior is zero
    wp = flat_width(w)
    grid = np.asarray(got_f, np.float32).reshape(2, rows, wp, f)
    assert np.all(grid[:, 0] == 0) and np.all(grid[:, h + 1:] == 0)
    assert np.all(grid[:, :, 0] == 0) and np.all(grid[:, :, w + 1:] == 0)


def test_tiled_kernel_chain_interpreted(rng):
    """Chained tiled kernels with no repacking == two reference layers —
    the entry-flow blocks' sepconv1 -> sepconv2 pattern."""
    h, w, c, th = 13, 13, 16, 5
    x = jnp.asarray(rng.normal(size=(2, h, w, c)), jnp.float32)
    dwk1, pw1, s1, t1 = _mats(rng, c, c)
    dwk2, pw2, s2, t2 = _mats(rng, c, c)
    xf = pad_to_flat(x, h, w, row_tile=th)
    a = fused_sepconv_flat(xf, dwk1, pw1, s1, t1, h, w, False, False,
                           force="interpret", row_tile=th)
    b = fused_sepconv_flat(a, dwk2, pw2, s2, t2, h, w, True, False,
                           force="interpret", row_tile=th)
    got = np.asarray(unflatten(b, h, w), np.float32)
    r1 = sepconv_reference(x, dwk1, pw1, s1, t1, False)
    r2 = sepconv_reference(r1, dwk2, pw2, s2, t2, True)
    np.testing.assert_allclose(got, np.asarray(r2, np.float32),
                               rtol=0.1, atol=0.08)


@pytest.mark.parametrize("h,w,c,f", [(13, 11, 16, 24), (14, 14, 48, 32)])
def test_mbconv_kernel_parity_interpreted(rng, h, w, c, f):
    """The fused MobileNet inverted-residual tail kernel (depthwise ->
    +BN-shift -> relu6 -> 1x1 project -> +BN-shift, scales pre-folded)
    == the jax reference, incl. the zero-halo output contract."""
    from sparkdl_tpu.ops.sepconv import fused_mbconv_flat

    x = jnp.asarray(rng.normal(size=(2, h, w, c)), jnp.float32)
    dwk = jnp.asarray(rng.normal(0, 0.3, (3, 3, c)), jnp.float32)
    pw = jnp.asarray(rng.normal(0, 0.1, (c, f)), jnp.float32)
    mid = jnp.asarray(rng.normal(0, 0.5, (c,)), jnp.float32)
    sh = jnp.asarray(rng.normal(0, 0.2, (f,)), jnp.float32)
    xf = pad_to_flat(x, h, w)
    got_f = fused_mbconv_flat(xf, dwk, pw, mid, sh, h, w,
                              force="interpret")
    ref_f = fused_mbconv_flat(xf, dwk, pw, mid, sh, h, w, force=False)
    got = np.asarray(unflatten(got_f, h, w), np.float32)
    ref = np.asarray(unflatten(ref_f, h, w), np.float32)
    np.testing.assert_allclose(got, ref, rtol=0.08, atol=0.05)
    wp = flat_width(w)
    grid = np.asarray(got_f, np.float32).reshape(2, h + 2, wp, f)
    assert np.all(grid[:, 0] == 0) and np.all(grid[:, -1] == 0)
    assert np.all(grid[:, :, 0] == 0) and np.all(grid[:, :, w + 1:] == 0)


def test_mobilenet_fused_matches_unfused(rng, monkeypatch):
    """Model-level parity for MobileNetV2(fused_inference=True): the
    flat-stage chaining (masked expand matmul + fused tail + residuals in
    flat layout) matches the plain module from the same variables, with
    an identical variable tree; the registry env knob gates and keys
    the variant."""
    import jax

    from sparkdl_tpu.models import get_model_spec, model_variant_key
    from sparkdl_tpu.models.mobilenet import MobileNetV2

    x = jnp.asarray(rng.random((2, 96, 96, 3)) * 2 - 1, jnp.float32)
    m0 = MobileNetV2(num_classes=5, fused_inference=False)
    m1 = MobileNetV2(num_classes=5, fused_inference=True)
    v0 = m0.init(jax.random.PRNGKey(0), x, train=False)
    v1 = jax.eval_shape(lambda: m1.init(jax.random.PRNGKey(0), x,
                                        train=False))
    assert (jax.tree_util.tree_structure(v0)
            == jax.tree_util.tree_structure(v1))
    a = np.asarray(m0.apply(v0, x, train=False, features=True))
    b = np.asarray(m1.apply(v0, x, train=False, features=True))
    np.testing.assert_allclose(b, a, rtol=0.05, atol=0.02)
    # train mode takes the plain branch (BN needs batch stats)
    out, mut = m1.apply(v0, x, train=True, features=True,
                        mutable=["batch_stats"])
    assert "batch_stats" in mut

    spec = get_model_spec("MobileNetV2")
    monkeypatch.delenv("SPARKDL_MNV2_FUSED", raising=False)
    assert spec.build().fused_inference is False  # off until measured
    assert model_variant_key("MobileNetV2") == ""
    monkeypatch.setenv("SPARKDL_MNV2_FUSED", "1")
    assert spec.build().fused_inference is True
    assert model_variant_key("MobileNetV2") == "fused"


def test_xception_tiled_entry_wiring(rng, monkeypatch):
    """Model-level wiring of the row-tiled entry path: with
    ``tiled_entry=True`` the entry blocks route through
    ``pad_to_flat(row_tile=...)`` and still match the plain module graph
    from the same variables, and the registry env gate builds/keys the
    variant.  (Kernel math itself is parity-pinned in the tiled-kernel
    tests; on CPU this exercises the flat plumbing via the reference
    fallback, including the rounded-rows layout.)"""
    import jax

    from sparkdl_tpu.models import get_model_spec, model_variant_key
    from sparkdl_tpu.models.xception import Xception, _pick_row_tile

    # the 224x224 input makes block2 h=111 exceed the VMEM budget, so the
    # tiled path (rows rounded up to the tile) actually engages
    assert _pick_row_tile(111, 111, 128) is not None
    x = jnp.asarray(rng.random((1, 224, 224, 3)) * 2 - 1, jnp.float32)
    m0 = Xception(num_classes=3, fused_inference=False)
    m1 = Xception(num_classes=3, fused_inference=True, tiled_entry=True)
    v0 = m0.init(jax.random.PRNGKey(0), x, train=False)
    f0 = np.asarray(m0.apply(v0, x, train=False, features=True))
    f1 = np.asarray(m1.apply(v0, x, train=False, features=True))
    np.testing.assert_allclose(f1, f0, rtol=0.05, atol=0.02)

    spec = get_model_spec("Xception")
    monkeypatch.delenv("SPARKDL_XC_TILED", raising=False)
    assert spec.build().tiled_entry is False      # retired: off by default
    assert model_variant_key("Xception") == ""
    monkeypatch.setenv("SPARKDL_XC_TILED", "1")
    assert spec.build().tiled_entry is True
    assert model_variant_key("Xception") == "tiled"


def test_xception_fused_matches_unfused(rng):
    """Model-level parity: Xception(fused_inference=True) — the pallas
    routing, padded-flat chaining, BNAffine folding — matches the plain
    module graph, from the SAME variables, and both declare identical
    variable trees (weight import/persistence compatibility)."""
    from sparkdl_tpu.models.xception import Xception

    x = jnp.asarray(rng.random((2, 96, 96, 3)) * 2 - 1, jnp.float32)
    m0 = Xception(num_classes=5, fused_inference=False)
    m1 = Xception(num_classes=5, fused_inference=True)
    v0 = m0.init(jax.random.PRNGKey(0), x, train=False)
    v1 = m1.init(jax.random.PRNGKey(0), x, train=False)
    assert (jax.tree_util.tree_structure(v0)
            == jax.tree_util.tree_structure(v1))
    f0 = np.asarray(m0.apply(v0, x, train=False, features=True))
    f1 = np.asarray(m1.apply(v0, x, train=False, features=True))
    np.testing.assert_allclose(f1, f0, rtol=0.05, atol=0.02)
    # train-mode apply takes the unfused branch regardless of the flag
    # (BatchNorm needs batch statistics) and works from fused-init vars
    out, mut = m1.apply(v1, x, train=True, features=True,
                        mutable=["batch_stats"])
    assert out.shape == (2, 2048) and "batch_stats" in mut
