"""Content-addressed inference cache + single-flight coalescing
(ISSUE 11).

Tier-1, CPU-only, seconds-scale: the Zipfian replay benchmark (>= 1.5x
over the uncached path, hit rate pinned to the analytic floor, outputs
bit-identical to the uncached oracle), the coalescing contract (N
concurrent identical requests -> exactly ONE engine dispatch), the
hot-swap survival rule pinned against PROGRAMS.lock.json (which must
NOT regenerate), the eviction/invalidation edges, the injected
hit-corruption digest re-check, the streaming replay short-circuit, and
the shared ``utils.digest`` contract from both its callers.
"""

import json
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu import faults
from sparkdl_tpu.serving import InferenceCache, Server
from sparkdl_tpu.serving.cache import (cache_from_env, example_digest,
                                       lockfile_model_fingerprint,
                                       zipfian_cache_benchmark)
from sparkdl_tpu.utils.digest import (array_digest, content_chunk_id,
                                      content_digest)


def _fn(v, x):
    import jax.numpy as jnp

    return jnp.tanh(x @ v["w"])


def _variables(dim=8, out=4, seed=0):
    rng = np.random.default_rng(seed)
    return {"w": rng.normal(size=(dim, out)).astype(np.float32)}


def _server(cache, variables=None, **kw):
    kw.setdefault("max_batch_size", 8)
    kw.setdefault("max_wait_ms", 1.0)
    return Server(_fn, variables if variables is not None else _variables(),
                  cache=cache, **kw)


def _wrap_slow(srv, sleep_s=0.0):
    """Wrap every bucket engine's run_padded with a dispatch counter
    (and optional synthetic slowness); returns the counter cell."""
    calls = [0]
    for b in srv.bucket_sizes:
        eng = srv._engine_for(b)
        real = eng.run_padded

        def slow(batch, _real=real):
            calls[0] += 1
            if sleep_s:
                time.sleep(sleep_s)
            return _real(batch)

        eng.run_padded = slow
    return calls


# -- utils.digest: the one sha256 core, contract-tested from both callers --
def test_digest_shared_by_streaming_and_serving():
    from sparkdl_tpu.streaming import runner, source

    arr = np.arange(24, dtype=np.float32).reshape(4, 6)
    # the chunk id re-exported from streaming.source IS the utils.digest
    # helper, and the id string is the pre-move format: padded offset +
    # 16 hex chars of the full digest
    assert source.content_chunk_id is content_chunk_id
    cid = content_chunk_id(7, arr)
    assert cid == f"{7:08d}-{array_digest(arr)[:16]}"
    # the journal's artifact digest is the same core at full width
    assert runner._array_digest is array_digest
    # dtype/shape/bytes all discriminate
    assert array_digest(arr) != array_digest(arr.astype(np.float64))
    assert array_digest(arr) != array_digest(arr.reshape(6, 4))
    mutated = arr.copy()
    mutated[0, 0] += 1
    assert array_digest(arr) != array_digest(mutated)
    # serving's payload digest: a bare array digests identically to the
    # streaming spelling; a pytree folds leaves + structure
    assert content_digest(arr) == array_digest(arr)
    assert content_digest({"a": arr}) != content_digest({"b": arr})
    assert content_digest([arr, arr]) != content_digest([arr])
    assert example_digest(arr) == content_digest(arr)


# -- cache core ------------------------------------------------------------
def test_hit_returns_independent_copy():
    c = InferenceCache(max_entries=4, max_bytes=1 << 20)
    key = ("ns", "d1")
    val = np.arange(8, dtype=np.float32)
    c.put(key, val)
    got = c.get(key)
    assert np.array_equal(got, val)
    got[0] = 99.0  # a consumer scribbling on its row
    again = c.get(key)
    assert np.array_equal(again, val), "stored entry was aliased"


def test_bytes_cap_evicts_in_lru_order():
    row = np.zeros(256, dtype=np.float32)  # 1 KiB
    c = InferenceCache(max_entries=100, max_bytes=int(2.5 * row.nbytes))
    c.put(("a",), row)
    c.put(("b",), row + 1)
    c.put(("c",), row + 2)  # capacity 2 -> evicts a (oldest)
    assert c.get(("a",)) is None
    assert c.get(("b",)) is not None  # refreshes b to MRU
    c.put(("d",), row + 3)  # evicts c, NOT the refreshed b
    assert c.get(("c",)) is None
    assert c.get(("b",)) is not None
    counters = c.metrics.snapshot_raw()["counters"]
    assert counters["cache.evictions"] == 2.0
    assert c.total_bytes <= int(2.5 * row.nbytes)
    # an entry bigger than the whole budget is served but never stored
    big = np.zeros(4096, dtype=np.float32)
    c.put(("big",), big)
    assert c.get(("big",)) is None


def test_zero_capacity_disables_cleanly():
    for kw in ({"max_entries": 0}, {"max_bytes": 0}):
        c = InferenceCache(**kw)
        c.put(("k",), np.ones(4))
        assert len(c) == 0 and c.total_bytes == 0
        assert c.get(("k",)) is None
        # the serving path still works end to end over a disabled store
        with _server(c) as srv:
            x = np.ones(8, np.float32)
            y1 = srv.predict(x)
            y2 = srv.predict(x)
        assert np.array_equal(y1, y2)
        assert len(c) == 0


def test_namespace_isolation_between_servers():
    cache = InferenceCache()
    x = np.ones(8, np.float32)
    with _server(cache, _variables(seed=1)) as s1, \
            _server(cache, _variables(seed=2)) as s2:
        y1 = s1.predict(x)
        y2 = s2.predict(x)
        # same input bytes, different models: the auto-assigned
        # namespaces keep the entries apart
        assert not np.array_equal(y1, y2)
        assert s1.cache_namespace != s2.cache_namespace
        assert np.array_equal(s1.predict(x), y1)
        assert np.array_equal(s2.predict(x), y2)
    counters = cache.metrics.snapshot_raw()["counters"]
    assert counters["cache.hits"] == 2.0
    assert counters["cache.misses"] == 2.0


def test_close_reclaims_owned_anon_namespace():
    cache = InferenceCache()
    x = np.ones(8, np.float32)
    srv = _server(cache)
    srv.predict(x)
    assert len(cache) == 1
    srv.close()
    assert len(cache) == 0 and cache.total_bytes == 0, (
        "a closed server's anon namespace must not orphan bytes in the "
        "shared store")
    # explicit namespaces are NOT owned — their lifecycle belongs to
    # whoever assigned them (the fleet's swap/rollback paths)
    srv2 = _server(cache, cache_namespace=("shared", "ns"))
    srv2.predict(x)
    srv2.close()
    assert len(cache) == 1


def test_adopt_collision_keeps_byte_ledger_consistent():
    c = InferenceCache()
    row = np.zeros(64, np.float32)
    c.put(("old", "k1"), row)
    c.put(("old", "k2"), row)
    c.put(("new", "k1"), row + 1)  # a post-flip racer already settled k1
    before = c.total_bytes
    moved = c.adopt(("old",), ("new",))
    assert moved == 1  # k2 moved; the k1 collision kept the fresher entry
    assert len(c) == 2
    assert c.total_bytes == before - row.nbytes, (
        "adopt over an existing key must release the replaced bytes")
    assert np.array_equal(c.get(("new", "k1")), row + 1)
    assert np.array_equal(c.get(("new", "k2")), row)


# -- single flight ---------------------------------------------------------
def test_coalescing_n_concurrent_identical_one_dispatch():
    cache = InferenceCache()
    with _server(cache, max_wait_ms=5.0, max_queue=64) as srv:
        x = np.ones(8, np.float32)
        srv.warmup(x)
        calls = _wrap_slow(srv, sleep_s=0.4)
        futs = [srv.submit(x) for _ in range(6)]
        outs = [f.result(timeout=30) for f in futs]
    assert calls[0] == 1, (
        f"6 concurrent identical requests cost {calls[0]} dispatches; "
        f"single-flight coalescing must make that exactly 1")
    oracle = outs[0]
    assert all(np.array_equal(o, oracle) for o in outs)
    counters = cache.metrics.snapshot_raw()["counters"]
    assert counters["cache.misses"] == 1.0
    assert counters["cache.coalesced"] == 5.0
    # follower rows are copies, not views of one buffer
    outs[1][0] = 123.0
    assert not np.array_equal(outs[1], outs[2])


def test_leader_failure_settles_followers_and_caches_nothing():
    cache = InferenceCache()
    plan = faults.FaultPlan.parse(
        "cache.stampede:sleep:ms=300,times=1;"
        "serving.model:error:exc=fatal,times=1")
    with _server(cache, max_wait_ms=5.0) as srv:
        x = np.ones(8, np.float32)
        srv.warmup(x)
        with faults.active(plan):
            leader_fut = [None]

            def lead():
                # blocks ~300ms inside submit at cache.stampede, giving
                # the followers below a deterministic window to park
                leader_fut[0] = srv.submit(x)

            t = threading.Thread(target=lead)
            t.start()
            time.sleep(0.1)  # leader is inside its stampede window
            followers = [srv.submit(x) for _ in range(3)]
            t.join()
            with pytest.raises(faults.InjectedFatalError):
                leader_fut[0].result(timeout=30)
            for f in followers:
                with pytest.raises(faults.InjectedFatalError):
                    f.result(timeout=30)
        assert len(cache) == 0, "a failed dispatch must cache nothing"
        # the error was not sticky: the next request recomputes fine
        y = srv.predict(x)
    assert y.shape == (4,)
    counters = cache.metrics.snapshot_raw()["counters"]
    assert counters["cache.leader_failures"] == 1.0
    assert counters["cache.coalesced"] == 3.0


def test_leader_settles_before_caller_and_result_is_unaliased():
    cache = InferenceCache()
    with _server(cache) as srv:
        x = np.ones(8, np.float32)
        fut = srv.submit(x)
        y = fut.result(timeout=30)
        # the caller-facing future resolves only AFTER settle stored
        # its copy — so the caller can never race the insert...
        assert len(cache) == 1
        y[:] = -1.0  # ...and scribbling on the returned row is safe
        y2 = srv.predict(x)
    assert not np.array_equal(y, y2), "stored entry aliased the row " \
                                      "handed to the leader's caller"
    assert cache.metrics.snapshot_raw()["counters"]["cache.hits"] == 1.0


def test_follower_keeps_its_own_deadline():
    from sparkdl_tpu.serving import DeadlineExceededError

    cache = InferenceCache()
    with _server(cache, max_wait_ms=5.0) as srv:
        x = np.ones(8, np.float32)
        srv.warmup(x)
        _wrap_slow(srv, sleep_s=0.6)
        leader = srv.submit(x)  # no deadline of its own
        follower = srv.submit(x, timeout_ms=100)
        with pytest.raises(DeadlineExceededError):
            follower.result(timeout=30)
        # the leader (and the cache insert) are unaffected
        assert leader.result(timeout=30).shape == (4,)


def test_injected_hit_corruption_caught_by_digest_recheck():
    cache = InferenceCache()
    with _server(cache) as srv:
        x = np.ones(8, np.float32)
        y1 = srv.predict(x)  # populates
        calls = _wrap_slow(srv)
        with faults.active(faults.FaultPlan.parse(
                "cache.hit:error:times=1")):
            y2 = srv.predict(x)  # hit path corrupts -> re-dispatch
        # read BEFORE close(): the server reclaims its anon namespace
        # on close, which adds a second (unrelated) invalidation
        counters = cache.metrics.snapshot_raw()["counters"]
    assert np.array_equal(y1, y2), "corrupt entry leaked to a caller"
    assert calls[0] == 1, "corruption must demote the hit to a dispatch"
    assert counters["cache.corruptions"] == 1.0
    assert counters["cache.invalidations"] == 1.0


# -- the headline benchmark ------------------------------------------------
def test_zipfian_replay_speedup_hit_rate_and_oracle():
    res = zipfian_cache_benchmark(n_requests=48, universe=8,
                                  dispatch_ms=6.0, seed=0)
    assert res["bit_identical"], (
        "cached outputs diverged from the uncached oracle")
    assert res["hit_rate"] >= res["analytic_hit_rate"], res
    assert res["speedup"] >= 1.5, (
        f"cache speedup {res['speedup']}x under Zipfian replay below "
        f"the 1.5x contract")
    assert res["uncached_dispatches"] == res["n_requests"]
    assert res["cached_dispatches"] == res["distinct"]
    assert res["cache_entries"] == res["distinct"]


# -- env gate / config -----------------------------------------------------
def test_sparkdl_cache_grammar(monkeypatch):
    monkeypatch.delenv("SPARKDL_CACHE", raising=False)
    assert cache_from_env() is None
    for off in ("0", "off", "no", "false", ""):
        monkeypatch.setenv("SPARKDL_CACHE", off)
        assert cache_from_env() is None
    monkeypatch.setenv("SPARKDL_CACHE", "1")
    c = cache_from_env()
    assert isinstance(c, InferenceCache)
    monkeypatch.setenv("SPARKDL_CACHE", "entries=8,mb=2")
    c = cache_from_env()
    assert c.max_entries == 8 and c.max_bytes == 2 << 20
    monkeypatch.setenv("SPARKDL_CACHE", "bogus")
    with pytest.raises(ValueError):
        cache_from_env()
    monkeypatch.setenv("SPARKDL_CACHE", "entries=zap")
    with pytest.raises(ValueError):
        cache_from_env()


def test_server_uncached_by_default(monkeypatch):
    from sparkdl_tpu.serving import cache as cache_mod

    monkeypatch.delenv("SPARKDL_CACHE", raising=False)
    cache_mod.configure_from_env()
    try:
        with _server(cache=None) as srv:
            assert srv.cache is None
            x = np.ones(8, np.float32)
            np.testing.assert_array_equal(srv.predict(x), srv.predict(x))
            assert srv.varz()["cache"] is None
    finally:
        cache_mod.configure_from_env()


def test_varz_carries_cache_section_json_serializable():
    cache = InferenceCache()
    with _server(cache) as srv:
        x = np.ones(8, np.float32)
        srv.predict(x)
        srv.predict(x)
        v = srv.varz()
    json.dumps(v)  # the monitoring endpoint body must stay serializable
    assert v["cache"]["entries"] == 1
    assert v["cache"]["counters"]["cache.hits"] == 1.0
    assert v["counters"]["serving.cache_hits"] == 1.0


# -- hot-swap survival pinned against PROGRAMS.lock.json -------------------
def _swap_fleet(cache, fingerprints, w1, w2):
    from sparkdl_tpu.serving import Fleet

    fleet = Fleet(max_batch_size=8, max_wait_ms=1.0, cache=cache,
                  program_fingerprints=fingerprints)
    fleet.add_model("m", _fn, w1)
    fleet.add_version("m", w2)
    return fleet


def test_unchanged_fingerprint_promote_keeps_entries():
    import os

    lock_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PROGRAMS.lock.json")
    with open(lock_path, "rb") as fh:
        lock_before = fh.read()
    cache = InferenceCache()
    w = _variables()
    fleet = _swap_fleet(cache, {"m": "fp-stable"}, w, w)
    x = np.ones(8, np.float32)
    y1 = fleet.predict("m", x)
    fleet.start_rollout("m", canary_fraction=0.0)
    report = fleet.promote("m")
    assert report["cache"] == {"survived": True, "entries": 1,
                              "fingerprint_unchanged": True,
                              "weights_unchanged": True}
    calls = _wrap_slow(fleet._state("m").server)
    y2 = fleet.predict("m", x)  # the v1-warmed entry serves v2
    fleet.close()
    assert calls[0] == 0, "unchanged-fingerprint promote must stay warm"
    assert np.array_equal(y1, y2)
    assert cache.metrics.snapshot_raw()["counters"]["cache.hits"] == 1.0
    with open(lock_path, "rb") as fh:
        assert fh.read() == lock_before, "PROGRAMS.lock.json regenerated"


def test_changed_fingerprint_promote_invalidates():
    cache = InferenceCache()
    fps = {"m": "fp-v1"}
    w = _variables()
    fleet = _swap_fleet(cache, lambda name, entry: fps[name], w, w)
    x = np.ones(8, np.float32)
    y1 = fleet.predict("m", x)
    fps["m"] = "fp-v2"  # the committed program moved between deploys
    fleet.start_rollout("m", canary_fraction=0.0)
    report = fleet.promote("m")
    assert report["cache"]["survived"] is False
    assert report["cache"]["fingerprint_unchanged"] is False
    assert len(cache) == 0, "changed fingerprint must drop the entries"
    calls = _wrap_slow(fleet._state("m").server)
    y2 = fleet.predict("m", x)  # miss -> fresh dispatch
    fleet.close()
    assert calls[0] == 1
    assert np.array_equal(y1, y2)  # same weights, so same answer
    counters = cache.metrics.snapshot_raw()["counters"]
    assert counters["cache.invalidations"] >= 1.0


def test_new_weights_promote_invalidates_despite_fingerprint():
    cache = InferenceCache()
    w1, w2 = _variables(seed=1), _variables(seed=2)
    fleet = _swap_fleet(cache, {"m": "fp-stable"}, w1, w2)
    x = np.ones(8, np.float32)
    y1 = fleet.predict("m", x)
    fleet.start_rollout("m", canary_fraction=0.0)
    report = fleet.promote("m")
    assert report["cache"]["survived"] is False
    assert report["cache"]["fingerprint_unchanged"] is True
    assert report["cache"]["weights_unchanged"] is False
    y2 = fleet.predict("m", x)
    fleet.close()
    # v2 genuinely computes different outputs — serving the v1 entry
    # would have been a correctness bug, not a cache win
    assert not np.array_equal(y1, y2)


def test_rollback_drops_canary_namespace_keeps_stable():
    cache = InferenceCache()
    w = _variables()
    fleet = _swap_fleet(cache, {"m": "fp-stable"}, w, w)
    x = np.ones(8, np.float32)
    y1 = fleet.predict("m", x)  # warms v1
    ro = fleet.start_rollout("m", canary_fraction=1.0)
    y_canary = fleet.predict("m", x)  # warms the canary namespace
    assert len(cache) == 2
    report = fleet.rollback("m")
    assert report["cache"]["survived"] is False
    assert len(cache) == 1, "rollback must reclaim the canary entries"
    calls = _wrap_slow(fleet._state("m").server)
    y2 = fleet.predict("m", x)
    fleet.close()
    assert calls[0] == 0, "the stable entries must survive a rollback"
    assert np.array_equal(y1, y2) and np.array_equal(y1, y_canary)
    assert ro.active is False


def test_lockfile_model_fingerprint_resolves_from_committed_lock():
    fp1 = lockfile_model_fingerprint("MobileNetV2")
    fp2 = lockfile_model_fingerprint("MobileNetV2")
    assert fp1 is not None and fp1 == fp2, "must be deterministic"
    assert lockfile_model_fingerprint("InceptionV3") != fp1
    assert lockfile_model_fingerprint("NoSuchModel") is None
    assert lockfile_model_fingerprint(
        "MobileNetV2", path="/nonexistent/lock.json") is None


# -- streaming replay ------------------------------------------------------
def test_stream_replay_hits_cache_instead_of_redispatching(tmp_path):
    import os

    from sparkdl_tpu import streaming
    from sparkdl_tpu.parallel.engine import InferenceEngine

    rng = np.random.default_rng(3)
    v = {"w": rng.normal(size=(16, 8)).astype(np.float32)}
    eng = InferenceEngine(_fn, v, device_batch_size=32)
    payloads = [rng.normal(size=(32, 16)).astype(np.float32)
                for _ in range(6)]
    jp = str(tmp_path / "j.jsonl")
    od = str(tmp_path / "out")
    cache = InferenceCache()
    ns = ("stream", "t")
    sc1 = streaming.StreamScorer(
        eng, streaming.MemorySource(payloads, finished=True),
        journal_path=jp, out_dir=od, pipeline=False,
        cache=cache, cache_namespace=ns)
    with faults.active(faults.FaultPlan.parse(
            "stream.commit:error:exc=fatal,at=3")):
        with pytest.raises(faults.InjectedFatalError):
            sc1.run()  # dies between output write and commit
    sc1.close()
    calls = [0]
    real = eng.run_padded

    def counting(batch):
        calls[0] += 1
        return real(batch)

    eng.run_padded = counting
    sc2 = streaming.StreamScorer(
        eng, streaming.MemorySource(payloads, finished=True),
        journal_path=jp, out_dir=od, pipeline=False,
        cache=cache, cache_namespace=ns)
    s2 = sc2.run()
    sc2.close()
    eng.run_padded = real
    assert s2["cache_hits"] == 1, s2
    assert s2["redeliveries"] >= 1
    # the crashed chunk (offset 2) came from the cache: only the
    # genuinely unscored chunks 3..5 paid a dispatch on resume
    assert calls[0] == 3, calls
    got = streaming.assemble_outputs(jp, od)
    oracle = np.concatenate(
        [np.asarray(o) for o in eng.map_batches(payloads, pipeline=False)],
        axis=0)
    assert np.array_equal(got, oracle), "resume must stay bit-identical"
    assert os.path.isdir(od)


# -- observability ---------------------------------------------------------
def test_cache_events_cataloged_and_on_blackbox_timeline(tmp_path):
    from sparkdl_tpu.obs import flight
    from tools.blackbox import build_timeline

    for name in ("cache.hit", "cache.miss", "cache.coalesced",
                 "cache.evict", "cache.invalidate"):
        assert name in flight.EVENT_HELP
        flight.validate_event(name)
    rec = flight.configure(enabled=True, out_dir=str(tmp_path))
    try:
        cache = InferenceCache(max_entries=1, max_bytes=1 << 20)
        cache.put(("a",), np.ones(4))
        cache.get(("a",))        # cache.hit
        cache.put(("b",), np.ones(4))  # cache.evict (entries cap = 1)
        cache.invalidate(("b",))       # cache.invalidate
        with _server(cache) as srv:
            x = np.ones(8, np.float32)
            srv.predict(x)       # cache.miss
            srv.predict(x)       # cache.hit
        path = rec.dump()
    finally:
        flight.configure_from_env()
    doc = build_timeline(path)
    chain = doc["chain"]
    for name in ("cache.hit", "cache.miss", "cache.evict",
                 "cache.invalidate"):
        assert name in chain, f"{name} missing from blackbox timeline"
    assert doc["counts"]["cache.hit"] >= 2


def test_faults_sites_registered_for_cache():
    from sparkdl_tpu.faults.sites import SITE_HELP, validate_site

    for site in ("cache.hit", "cache.stampede"):
        assert site in SITE_HELP
        validate_site(site)
    # spec grammar accepts them end to end
    plan = faults.FaultPlan.parse(
        "seed=5;cache.hit:error:times=1;cache.stampede:sleep:ms=1")
    assert plan.has_rules("cache.hit") and plan.has_rules("cache.stampede")
